"""Static per-op cost model over the graph (ref: tensorflow/core/grappler/
costs/{cost_estimator.h,op_level_cost_estimator.cc,graph_memory.cc},
grappler/clusters/).

The reference predicts per-op execution cost and graph peak memory from a
GraphDef *before* running, to drive placement and scheduling decisions.
TPU-native equivalent: predict FLOPs, HBM bytes, and peak live bytes of a
(pruned) stf graph slice before XLA ever sees it — used by

- ``bench.py`` / ``client/timeline.py`` to print predicted-vs-measured,
- ``parallel.pipeline_train(n_microbatches="auto")`` /
  ``suggest_remat`` to pick microbatch count and remat granularity from
  the activation-memory estimate instead of trial-and-error OOMs.

Methodology: per-op rules (matmul/conv/reduction families) with an
elementwise default; ``bytes = inputs + outputs`` per op — deliberately
the same accounting as XLA's pre-fusion HLO cost analysis, which is the
machine-checkable comparator (tests assert within 2x on the five bench
configs). Fusion cuts real HBM traffic below this; the roofline numbers
in utils/perf.py measure that side. SymbolicGradient is costed as 2x its
forward slice (replay is CSE'd by XLA; backward ≈ 2x forward FLOPs — the
standard training heuristic), and its residual traffic as the slice's
activation outputs re-read once.

Peak live bytes: forward liveness sweep in topological order — a buffer
allocates at its producer and frees after its last consumer — plus
resident variable state; gradient residents (the forward slice's outputs,
alive until the backward consumes them) are what ``suggest_remat``
trades against recompute.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import graph as ops_mod
from . import lowering as lowering_mod

Tensor = ops_mod.Tensor
Operation = ops_mod.Operation

# Every step pays host dispatch (Python run() plumbing, executable
# lookup, device launch, result sync) regardless of program size —
# measured at ~100-300 µs on the bench rig's eager path. Predictions
# are floored here so predicted-vs-measured on tiny configs reads as
# dispatch-bound (ratio ≈ measured/floor) instead of a nonsense 100x.
HOST_DISPATCH_FLOOR_S = 1.5e-4


def _nelems(shape) -> Optional[int]:
    if shape is None or shape.rank is None:
        return None
    n = 1
    for d in shape.dims:
        if d.value is None:
            return None
        n *= d.value
    return n


def _tensor_bytes(t: Tensor) -> int:
    n = _nelems(t.shape)
    if n is None:
        return 0
    return n * t.dtype.base_dtype.size


def _out_elems(op: Operation) -> int:
    total = 0
    for t in op.outputs:
        n = _nelems(t.shape)
        total += n or 0
    return total


# ---------------------------------------------------------------------------
# per-op FLOP rules (ref: grappler/costs/op_level_cost_estimator.cc — the
# reference's PredictMatMul / PredictConv2D / elementwise default)
# ---------------------------------------------------------------------------

def _flops_matmul(op: Operation) -> float:
    a, b = op.inputs[0], op.inputs[1]
    if a.shape.rank is None or b.shape.rank is None:
        return 0.0
    ash = [d.value or 0 for d in a.shape.dims]
    bsh = [d.value or 0 for d in b.shape.dims]
    ta = bool(op.attrs.get("transpose_a", op.attrs.get("adj_x", False)))
    tb = bool(op.attrs.get("transpose_b", op.attrs.get("adj_y", False)))
    m = ash[-1 if ta else -2]
    k = ash[-2 if ta else -1]
    n = bsh[-2 if tb else -1]
    batch = 1
    for d in ash[:-2]:
        batch *= d
    return 2.0 * batch * m * k * n


def _flops_conv2d(op: Operation) -> float:
    # out_elems x (2 x kh x kw x cin) — same formula the reference uses
    x, w = op.inputs[0], op.inputs[1]
    out_n = _out_elems(op)
    if w.shape.rank is None or out_n == 0:
        return 0.0
    wsh = [d.value or 0 for d in w.shape.dims]
    if len(wsh) < 3:
        return 0.0
    kh, kw, cin = wsh[0], wsh[1], wsh[2]
    return 2.0 * out_n * kh * kw * cin


def _flops_conv_backward(op: Operation) -> float:
    # dgrad/wgrad are convs of the same arithmetic intensity
    return _flops_conv2d(op) if len(op.inputs) >= 2 else 0.0


_REDUCTION_OPS = {"Sum", "Mean", "Prod", "Max", "Min", "All", "Any",
                  "ArgMax", "ArgMin", "LogSumExp"}
_FREE_OPS = {"Identity", "Reshape", "StopGradient", "Placeholder", "Const",
             "VariableV2", "ReadVariable", "Shape", "Rank", "Size",
             "NoOp", "ExpandDims", "Squeeze", "ZerosLike", "Snapshot",
             "PreventGradient", "CheckNumerics",
             # a layout annotation, not compute: any resharding it
             # forces is priced by the sharding analyzer's edge
             # classification, never double-counted here
             "ShardingConstraint"}
# pure data movement: bytes count, flops don't
_ZERO_FLOP_OPS = {"Transpose", "CapturedInput", "FuncArg"}
_TRANSCENDENTAL_OPS = {"Exp", "Log", "Sigmoid", "Tanh", "Softmax",
                       "LogSoftmax", "Erf", "Erfc", "Pow", "Rsqrt",
                       "Sqrt", "Softplus", "Elu", "Selu", "Gelu",
                       "Expm1", "Log1p", "Sin", "Cos", "Tan", "Digamma",
                       "Lgamma"}


def _op_flops(op: Operation, grad_depth: int = 0,
              fn_depth: int = 0) -> float:
    t = op.type
    if t in ("MatMul", "BatchMatMul", "Einsum", "SparseMatMul"):
        return _flops_matmul(op) if t != "Einsum" else 2.0 * _out_elems(op)
    if t in ("Conv2D", "DepthwiseConv2dNative", "Conv3D"):
        return _flops_conv2d(op)
    if t in ("Conv2DBackpropInput", "Conv2DBackpropFilter"):
        return _flops_conv_backward(op)
    if t == "SymbolicGradient":
        return _symbolic_gradient_flops(op, grad_depth)
    if t == "SymbolicHessian":
        return 4.0 * _symbolic_gradient_flops(op, grad_depth)
    fc = _function_op_cost(op, grad_depth, fn_depth)
    if fc is not None:
        return fc[0]
    if t in _FREE_OPS or t in _ZERO_FLOP_OPS:
        return 0.0
    if t == "NumericSummary":
        # four fused elementwise reductions over the tapped tensor
        # (nonfinite count, max-abs, sum-of-squares, zero count) — NOT
        # free: the health plane's cost must show up in plan estimates
        # so the <3% overhead budget is a priced, checkable claim
        n = _nelems(op.inputs[0].shape) or 0
        return 4.0 * n
    if t == "HistogramBucketCounts":
        # searchsorted over the fixed reference grid (~log2(|edges|)
        # comparisons per element) plus the moment reductions
        n = _nelems(op.inputs[0].shape) or 0
        return 14.0 * n
    if t in _REDUCTION_OPS:
        # one flop per INPUT element reduced
        n = sum(_nelems(i.shape) or 0 for i in op.inputs[:1])
        return float(n)
    if t in ("FusedBatchNorm", "FusedBatchNormV2", "LayerNorm"):
        n = _nelems(op.inputs[0].shape) or 0
        return 5.0 * n  # two reduction passes + normalize + scale/shift
    if t in ("FusedAdamUpdate", "FusedMomentumUpdate"):
        # the fused optimizer tail (stf.kernels): elementwise over every
        # gradient element — m/v updates, alpha scaling, param subtract
        # (~12 flops/elem Adam, ~6 Momentum); same arithmetic the
        # per-variable assign chains carried, now priced on one op
        n = sum(_nelems(i.shape) or 0 for i in op.inputs)
        return (12.0 if t == "FusedAdamUpdate" else 6.0) * n
    if t == "DecodeAttention":
        # q·K + P·V over the gathered cache: 4 * B * Kq * H * max_len
        # * D (Kq = 1 for the classic single-query step, the query-
        # block width for verify/block-prefill plans; the output is
        # only (B[, Kq], H, D) — the default out-elems pricing would
        # miss the cache-length factor entirely)
        ks = op.inputs[1].shape
        qs = op.inputs[0].shape
        kq = 1
        if qs.rank == 4 and qs.dims[1].value:
            kq = int(qs.dims[1].value)
        if ks.rank == 4 and all(d.value for d in ks.dims):
            b, max_len, h, d = (int(x.value) for x in ks.dims)
            return 4.0 * b * kq * h * max_len * d
        return 2.0 * _out_elems(op)
    if t in ("KVCacheAlloc", "KVCacheAppend", "KVCacheGather",
             "KVCachePageCopy"):
        return 0.0  # pure data movement; bytes are priced in _op_bytes
    if t == "EmbeddingLookupFused":
        # row routing is data movement (the whole point vs the one-hot
        # contraction's B*vocab_shard*D matmul flops); the dedup
        # unique-sort is ~b log b, negligible against the row bytes
        return 0.0
    if t == "EmbeddingScatterAddGrad":
        # one accumulate per incoming cotangent element (segment_sum +
        # owning-shard scatter-add); NOT the default out-elems pricing,
        # which would charge the whole table per step
        return 2.0 * (_nelems(op.inputs[1].shape) or 0) \
            if len(op.inputs) > 1 else 0.0
    mult = 2.0 if t in _TRANSCENDENTAL_OPS else 1.0
    return mult * _out_elems(op)


def _symbolic_gradient_flops(op: Operation, grad_depth: int) -> float:
    """Backward slice ≈ 2x the forward slice it differentiates (wgrad +
    dgrad per matmul/conv; the forward replay is CSE'd by XLA against the
    original forward, so it is NOT recounted)."""
    if grad_depth > 2:  # grad-of-grad-of-grad: stop the recursion
        return 0.0
    n_ys = op.attrs.get("n_ys", 1)
    n_xs = op.attrs.get("n_xs", 1)
    ys = list(op.inputs[:n_ys])
    xs = list(op.inputs[n_ys:n_ys + n_xs])
    try:
        path_ops, _ = lowering_mod.ancestors_between(xs, ys)
    except Exception:
        return 0.0
    return 2.0 * sum(_op_flops(p, grad_depth + 1) for p in path_ops)


def _op_bytes(op: Operation) -> float:
    """inputs + outputs — the pre-fusion HLO accounting (each use of an
    operand is a read; fusion reduces the real number, measured
    separately by utils/perf)."""
    return float(sum(_tensor_bytes(t) for t in op.inputs)
                 + sum(_tensor_bytes(t) for t in op.outputs))


_NCHW_PENALTY_OPS = {"Conv2D", "DepthwiseConv2dNative", "MaxPool",
                     "AvgPool", "FusedBatchNorm", "BiasAdd"}


def _nchw_lowering_transpose_bytes(op: Operation) -> float:
    """The per-op lowering of an NCHW image op transposes its data input
    to NHWC and its primary output back (ops/nn_ops.py) — two
    read+write pairs the graph never shows as nodes. Charging them here
    makes the layout pass's win measurable: after the rewrite the
    conversions are explicit Transpose nodes (mostly cancelled), and
    converted NHWC ops pay nothing."""
    if op.type not in _NCHW_PENALTY_OPS \
            or op.attrs.get("data_format") != "NCHW":
        return 0.0
    b = 0.0
    if op.inputs:
        b += 2.0 * _tensor_bytes(op.inputs[0])
    if op.outputs:
        b += 2.0 * _tensor_bytes(op.outputs[0])
    return b


def _op_bytes_dispatch(op: Operation, fn_depth: int = 0) -> float:
    """Per-op bytes with the special cases routed: gradient slices,
    free ops, function ops (cost attributed into their bodies), and the
    hidden NCHW lowering transposes."""
    if op.type == "SymbolicGradient":
        return _symbolic_gradient_bytes(op)
    if op.type in ("FusedAdamUpdate", "FusedMomentumUpdate"):
        # inputs (grads + scalar hypers) move once, plus the
        # store-resident state the op reads AND writes in place:
        # m/v/param for Adam (6 streams over n), accumulator/param for
        # Momentum (4 streams) — traffic the per-variable assign chains
        # previously charged across their many ops
        n = sum(_nelems(i.shape) or 0 for i in op.inputs)
        streams = 6.0 if op.type == "FusedAdamUpdate" else 4.0
        return _op_bytes(op) + streams * n * 4.0
    if op.type == "KVCacheAppend":
        # in-place scatter of B rows at one position range: the touched
        # bytes are value read + write (the output tensor is the WHOLE
        # cache only nominally — XLA donates and updates in place; the
        # default inputs+outputs accounting would charge a full cache
        # write per append and dominate every decode-step attribution)
        return 2.0 * sum(_tensor_bytes(t) for t in op.inputs)
    if op.type == "KVCachePageCopy":
        # CoW: M whole rows read + written in place (same donation
        # argument as the append) — row bytes from the cache attrs,
        # never the nominal whole-cache output
        sh = op.attrs.get("shape") or []
        m = _nelems(op.inputs[0].shape) or 0
        row = 1
        for d in sh[1:]:
            row *= int(d)
        itemsize = op.outputs[0].dtype.base_dtype.size if op.outputs else 4
        return 2.0 * m * row * itemsize
    if op.type == "EmbeddingLookupFused":
        # the default inputs+outputs accounting would charge reading
        # the ENTIRE table per lookup; the fused route touches ids +
        # the gathered rows (read at the owner, written twice through
        # the send/receive buffers)
        ids_b = _tensor_bytes(op.inputs[1]) if len(op.inputs) > 1 else 0.0
        out_b = _tensor_bytes(op.outputs[0]) if op.outputs else 0.0
        return ids_b + 2.0 * out_b
    if op.type == "EmbeddingScatterAddGrad":
        # cotangents read twice (segment_sum + scatter) plus the dense
        # per-shard gradient buffer write (the output IS materialized —
        # unlike the lookup, the table-shaped write is real)
        grad_b = _tensor_bytes(op.inputs[1]) if len(op.inputs) > 1 else 0.0
        out_b = _tensor_bytes(op.outputs[0]) if op.outputs else 0.0
        return 2.0 * grad_b + out_b
    fc = _function_op_cost(op, 0, fn_depth)
    if fc is not None:
        return fc[1]
    if op.type in _FREE_OPS:
        return 0.0
    return _op_bytes(op) + _nchw_lowering_transpose_bytes(op)


# ---------------------------------------------------------------------------
# cost attribution into FuncGraph bodies (cond/while/scan/defun): the
# flat walk used to price a While at its output-elems — a conv chain
# executing 100 iterations inside the body was invisible. Bodies are
# priced by recursing over their pruned op lists; the function-op
# registry (framework/optimizer.py register_function_op) supplies where
# the bodies live, how often they run (mode/trip), and how branches
# combine.
# ---------------------------------------------------------------------------

def _function_body_cost(fg, grad_depth: int,
                        fn_depth: int) -> Tuple[float, float]:
    fed = set(fg.inputs) | {inner for _, inner in fg.captures}
    try:
        plan = lowering_mod.prune([t.op for t in fg.outputs], fed)
    except Exception:
        return 0.0, 0.0
    flops = 0.0
    byts = 0.0
    for p in plan:
        flops += _op_flops(p, grad_depth, fn_depth)
        byts += _op_bytes_dispatch(p, fn_depth)
    return flops, byts


# (flops, bytes) memo: pricing a body means pruning and walking it, and
# BOTH _op_flops and _op_bytes_dispatch route function ops here — without
# the memo every nesting level would be walked twice per query. Keyed by
# the op plus the body identities (optimize_graph_functions swaps body
# FuncGraphs in place, which must invalidate).
_function_cost_memo = None  # created lazily: WeakKeyDictionary


def _function_op_cost(op: Operation, grad_depth: int,
                      fn_depth: int = 0) -> Optional[Tuple[float, float]]:
    """(flops, bytes) for a function op, or None when ``op`` carries no
    registered FuncGraph bodies. Loops multiply by the static trip count
    when one is known (While max_iterations, scan/map leading dim);
    branches cost as the heavier side (one branch executes).
    ``fn_depth`` counts BODY nesting only — it must stay separate from
    ``grad_depth`` (the grad-of-grad cutoff) or a gradient inside a
    loop body would be priced at 0."""
    from . import optimizer as optimizer_mod

    spec = optimizer_mod.function_op_spec(op.type)
    if spec is None:
        return None
    if fn_depth > 4:  # deeply nested bodies: stop the recursion
        return 0.0, 0.0
    try:
        descs = spec.bodies(op.attrs, len(op.inputs))
    except (KeyError, TypeError):
        return None
    fgs = []
    for d in descs:
        fg = op.attrs.get(d["attr"])
        if fg is None or not hasattr(fg, "outputs"):
            return None
        fgs.append(fg)
    if not fgs:
        return None

    import weakref

    global _function_cost_memo
    if _function_cost_memo is None:
        _function_cost_memo = weakref.WeakKeyDictionary()
    memo_key = (grad_depth, fn_depth)
    per_op = _function_cost_memo.setdefault(op, {})
    hit = per_op.get(memo_key)
    if hit is not None:
        # validate the bodies are the SAME objects (weakrefs, so a
        # rewritten-and-freed FuncGraph whose id is recycled can never
        # alias): optimize_graph_functions swaps bodies in place and the
        # memo must never hand back the pre-rewrite cost
        refs, result = hit
        if len(refs) == len(fgs) and all(
                r() is fg for r, fg in zip(refs, fgs)):
            return result

    costs = [_function_body_cost(fg, grad_depth, fn_depth + 1)
             for fg in fgs]
    boundary = _op_bytes(op)  # the op's own operands/results move once
    if spec.mode == "branch":
        result = (max(c[0] for c in costs),
                  max(c[1] for c in costs) + boundary)
    else:
        flops = sum(c[0] for c in costs)
        byts = sum(c[1] for c in costs)
        trip = 1
        if spec.mode == "loop":
            t = spec.trip(op.attrs, op.inputs) if spec.trip else None
            # an unbounded While (t None) prices one iteration — a
            # documented lower bound; a KNOWN trip of 0 stays 0
            trip = int(t) if t is not None else 1
        result = (trip * flops, trip * byts + boundary)
    per_op[memo_key] = (tuple(weakref.ref(fg) for fg in fgs), result)
    return result


def _symbolic_gradient_bytes(op: Operation) -> float:
    """Backward traffic ≈ the forward slice's own traffic (each op's
    backward re-reads its operands/residuals and writes cotangents of the
    same sizes), plus this node's gradient outputs."""
    n_ys = op.attrs.get("n_ys", 1)
    n_xs = op.attrs.get("n_xs", 1)
    ys = list(op.inputs[:n_ys])
    xs = list(op.inputs[n_ys:n_ys + n_xs])
    try:
        path_ops, _ = lowering_mod.ancestors_between(xs, ys)
    except Exception:
        return 0.0
    fwd = sum(_op_bytes(p) for p in path_ops if p.type not in _FREE_OPS)
    outs = sum(_tensor_bytes(t) for t in op.outputs)
    return fwd + outs


# ---------------------------------------------------------------------------
# the estimator
# ---------------------------------------------------------------------------

@dataclass
class OpCost:
    name: str
    op_type: str
    flops: float
    bytes: float


@dataclass
class CostEstimate:
    """(ref: grappler/costs/cost_estimator.h ``struct Costs``)."""

    flops: float = 0.0
    bytes_accessed: float = 0.0
    peak_bytes: float = 0.0
    resident_bytes: float = 0.0     # variables (persistent_memory)
    per_op: List[OpCost] = field(default_factory=list)

    def seconds_on(self, peak_flops: float, peak_bw: float,
                   dispatch_floor_s: Optional[float] = None) -> float:
        """Roofline projection: max of compute time, HBM time, and the
        host-dispatch floor. A tiny program's roofline time (~µs) is
        unreachable — every step pays Python dispatch + device launch +
        result sync, so the prediction is floored at
        HOST_DISPATCH_FLOOR_S before being compared with measurements
        (VERDICT weak #4: tiny bench configs printed
        measured_over_predicted ≈ 108 against a 75 µs 'prediction').
        Pass ``dispatch_floor_s=0`` for the raw roofline number."""
        if dispatch_floor_s is None:
            dispatch_floor_s = HOST_DISPATCH_FLOOR_S
        return max(self.flops / max(peak_flops, 1.0),
                   self.bytes_accessed / max(peak_bw, 1.0),
                   float(dispatch_floor_s))

    def summary(self) -> Dict[str, float]:
        return {
            "predicted_tflops": round(self.flops / 1e12, 4),
            "predicted_gbytes": round(self.bytes_accessed / 1e9, 3),
            "predicted_peak_gb": round(self.peak_bytes / 1e9, 3),
        }


def estimate(fetches, feeds: Sequence[Tensor] = (),
             graph: Optional[ops_mod.Graph] = None,
             top_k: int = 0,
             shard_factor_fn=None) -> CostEstimate:
    """Predict FLOPs / bytes / peak live memory of running ``fetches``.

    ``fetches``: tensors/ops (same things you pass to Session.run).
    ``feeds``: placeholders that will be fed (pruning boundary).
    ``shard_factor_fn``: optional fn(tensor) -> int dividing that
    tensor's RESIDENT/LIVE bytes — the sharding analyzer passes the
    per-tensor mesh shard factor so ``peak_bytes``/``resident_bytes``
    become PER-SHARD HBM (flops/bytes_accessed stay global: the whole
    mesh still does the whole step's work).
    """
    tensors: List[Tensor] = []
    target_ops: List[Operation] = []
    items = fetches if isinstance(fetches, (list, tuple)) else [fetches]
    for f in items:
        if isinstance(f, Operation):
            target_ops.append(f)
        elif isinstance(f, Tensor):
            tensors.append(f)
            target_ops.append(f.op)
        elif hasattr(f, "_ref"):  # Variable
            target_ops.append(f._ref.op)
        else:
            raise TypeError(f"estimate: cannot cost {f!r}")
    fed = set(feeds)
    plan = lowering_mod.prune(target_ops, fed_tensors=fed)

    def _live_bytes(t):
        b = _tensor_bytes(t)
        if shard_factor_fn is not None and b:
            try:
                f = int(shard_factor_fn(t) or 1)
            except Exception:
                f = 1
            if f > 1:
                b = b / f
        return b

    est = CostEstimate()
    # resident state: every variable in the slice stays in HBM all step
    seen_vars = set()
    for op in plan:
        if op.type in ("VariableV2", "ReadVariable"):
            vn = op.attrs.get("var_name")
            if vn not in seen_vars:
                seen_vars.add(vn)
                est.resident_bytes += sum(_live_bytes(t)
                                          for t in op.outputs[:1])

    # liveness sweep for peak memory: feed buffers are live from step
    # start; a tensor is freed at its last use only if something actually
    # allocated it (fed or produced in-plan — a pruned producer's tensor
    # must not drive `live` below baseline)
    last_use: Dict[Tensor, int] = {}
    for idx, op in enumerate(plan):
        for t in op.inputs:
            last_use[t] = idx
    for t in tensors:  # fetched tensors live to the end
        last_use[t] = len(plan)
    allocated = set(fed)
    live = est.resident_bytes + sum(_live_bytes(t) for t in fed)
    peak = live
    frees: Dict[int, List[Tensor]] = {}
    for t, idx in last_use.items():
        frees.setdefault(idx, []).append(t)

    for idx, op in enumerate(plan):
        flops = _op_flops(op)
        byts = _op_bytes_dispatch(op)
        est.flops += flops
        est.bytes_accessed += byts
        if top_k:
            est.per_op.append(OpCost(op.name, op.type, flops, byts))
        # allocate outputs
        if op.type not in ("VariableV2", "ReadVariable"):
            for t in op.outputs:
                allocated.add(t)
            live += sum(_live_bytes(t) for t in op.outputs)
        if op.type == "SymbolicGradient":
            # residuals of the forward slice stay live through backward
            pass  # their producers' buffers are already counted live
        peak = max(peak, live)
        for t in frees.get(idx, ()):
            if t in allocated and t.op.type not in ("VariableV2",
                                                    "ReadVariable"):
                live -= _live_bytes(t)
    est.peak_bytes = peak
    if top_k:
        est.per_op.sort(key=lambda o: -(o.flops + o.bytes))
        est.per_op = est.per_op[:top_k]
    return est


def predicted_vs_measured(fetches, feeds: Sequence[Tensor] = (),
                          measured_seconds: Optional[float] = None,
                          est: Optional[CostEstimate] = None
                          ) -> Dict[str, float]:
    """Static cost-model prediction for ``fetches`` next to a measured
    step time (ref: grappler/costs/cost_estimator.h — the reference
    checks its cost model against real run stats the same way).

    Returns predicted FLOPs/bytes/peak-memory, the roofline-projected
    step seconds for the attached chip, and — when ``measured_seconds``
    is given — measured/predicted, where >>1 means the program is
    leaving roofline performance on the table (or the model missed
    traffic: compare bytes against utils.perf.cost_of on the compiled
    step to tell which). Pass a precomputed ``est`` to skip the graph
    walk (the prediction is a pure function of graph + fetches, so
    periodic reporters cache it)."""
    from ..utils import perf

    if est is None:
        est = estimate(fetches, feeds=feeds)
    peak_flops, peak_bw = perf.chip_spec()
    out = dict(est.summary())
    pred_s = est.seconds_on(peak_flops, peak_bw)
    out["predicted_sec_per_step"] = float(f"{pred_s:.4g}")
    if pred_s <= HOST_DISPATCH_FLOOR_S:
        # the roofline time is below the host-dispatch floor: the row is
        # dispatch-bound and measured/predicted compares against the
        # floor, not the (unreachable) roofline
        out["dispatch_floor_bound"] = True
    if measured_seconds:
        out["measured_sec_per_step"] = float(f"{measured_seconds:.4g}")
        out["measured_over_predicted"] = round(
            float(measured_seconds) / max(pred_s, 1e-12), 3)
        # model FLOPs utilization from the unrounded estimate (the
        # summary()'s tflops rounds small programs to 0)
        out["mfu"] = round(
            perf.mfu(est.flops, float(measured_seconds)), 6)
    return out


# ---------------------------------------------------------------------------
# planning helpers (the consumers grappler's cost model exists for)
# ---------------------------------------------------------------------------

def suggest_microbatches(per_stage_activation_bytes: float,
                         n_stages: int,
                         hbm_budget_bytes: float,
                         schedule: str = "1f1b") -> int:
    """Smallest power-of-two microbatch count whose in-flight activation
    footprint fits the budget. Under 1F1B, stage i holds at most
    ``min(n_microbatches, n_stages - i)`` activation stashes; GPipe holds
    all of them (ref: GPipe / PipeDream-1F1B papers; grappler's
    graph_memory.cc plays this role for the reference's schedulers)."""
    if per_stage_activation_bytes <= 0 or hbm_budget_bytes <= 0:
        return 1
    for m in (1, 2, 4, 8, 16, 32, 64, 128):
        stash = (n_stages if schedule == "1f1b"
                 else m)  # gpipe stashes every microbatch
        per_micro = per_stage_activation_bytes / m
        if per_micro * stash <= hbm_budget_bytes:
            return m
    return 256


def suggest_remat(forward_activation_bytes: float,
                  hbm_budget_bytes: float,
                  forward_flops: float = 0.0,
                  peak_flops: float = 1.0,
                  peak_bw: float = 1.0) -> bool:
    """Remat when the forward residuals alone would blow the budget, or
    when the step is bandwidth-bound enough that recomputing is cheaper
    than re-reading (arithmetic intensity below the chip's balance
    point). Returns True = recompute per block."""
    if forward_activation_bytes > 0.7 * hbm_budget_bytes:
        return True
    if forward_flops > 0 and peak_bw > 0:
        intensity = forward_flops / max(forward_activation_bytes, 1.0)
        balance = peak_flops / peak_bw
        # deeply bandwidth-bound: trade FLOPs for bytes
        return intensity < 0.25 * balance
    return False


def transformer_activation_bytes(batch, seq_len, hidden, n_layers,
                                 dtype_bytes=2):
    """Order-of-magnitude forward-residual footprint of a transformer
    encoder stack: per layer, the backward consumes roughly qkv (3BSH) +
    attention out (BSH) + mlp hidden (4BSH) + mlp out (BSH) + two
    norms/residual reads (~4BSH) ~= 13 BSH."""
    return 13.0 * batch * seq_len * hidden * n_layers * dtype_bytes


def transformer_forward_flops(batch, seq_len, hidden, n_layers,
                              d_ff=None):
    """Order-of-magnitude forward FLOPs of a transformer stack (for the
    remat intensity heuristic, not the MFU accounting): per layer,
    qkv/out projections (2*4H^2 per token), the mlp (2*2*H*d_ff), and
    the S-dependent attention matmuls (2*2*S*H)."""
    d_ff = d_ff if d_ff is not None else 4 * hidden
    per_token = 2.0 * (4 * hidden * hidden + 2 * hidden * d_ff
                       + 2 * seq_len * hidden)
    return batch * seq_len * n_layers * per_token


def resnet_activation_bytes(batch, image_size, dtype_bytes=2, depth=50):
    """Order-of-magnitude forward-residual footprint of a ResNet-v1.5:
    per stage, blocks save ~3 conv outputs + BN/relu reads (~5x the
    stage's B*H*W*C feature map per block)."""
    stages = [(image_size // 4, 256, 3), (image_size // 8, 512, 4),
              (image_size // 16, 1024, 6), (image_size // 32, 2048, 3)]
    if depth >= 101:
        stages[2] = (image_size // 16, 1024, 23)
    total = 0.0
    for hw, c, blocks in stages:
        total += 5.0 * blocks * batch * hw * hw * c
    return total * dtype_bytes


def mesh_shard_factor(axes):
    """Product of the active mesh's sizes along ``axes`` (1 when no mesh
    or the axis is absent) — divides a GLOBAL activation estimate down
    to per-chip before comparing against one chip's HBM."""
    from ..parallel import mesh as mesh_mod

    m = mesh_mod.current_mesh()
    if m is None:
        return 1
    n = 1
    for ax in axes:
        if ax and ax in m.axis_names:
            n *= m.axis_size(ax)
    return n


def resolve_recompute(recompute, forward_activation_bytes,
                      forward_flops=0.0, device=None):
    """Resolve a model's ``recompute`` flag: ``"auto"`` asks
    ``suggest_remat`` against the ATTACHED chip's HBM capacity and
    balance point (the grappler memory-optimizer role, decided from the
    static estimate instead of a post-hoc OOM); True/False pass
    through. ``forward_activation_bytes`` must be PER-CHIP (divide a
    global estimate by ``mesh_shard_factor`` over the sharded axes)."""
    if recompute != "auto":
        return bool(recompute)
    from ..utils import perf

    peak_flops, peak_bw = perf.chip_spec(device)
    hbm = perf.chip_hbm_bytes(device)
    # params + optimizer state + workspace share the budget; activations
    # may claim roughly half of HBM before remat becomes the default
    return suggest_remat(forward_activation_bytes, 0.5 * hbm,
                         forward_flops, peak_flops, peak_bw)
