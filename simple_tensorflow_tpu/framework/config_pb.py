"""Session configuration (ref: tensorflow/core/protos/config.proto
``ConfigProto`` and python/client usage ``tf.Session(config=...)``).

Thread-pool and GPU knobs from the reference are accepted for API
compatibility but are advisory here — XLA owns scheduling on TPU. The
TPU-meaningful additions are the L0 transfer guards: per-step host↔device
transfers are the classic silent TPU performance killer (feeding numpy
every step instead of staging via data.prefetch_to_device; fetching big
activations to host), so the Session can log or reject implicit
transfers above a threshold on the hot path.
"""

from __future__ import annotations


class GPUOptions:
    """(ref: config.proto ``GPUOptions``) — accepted, advisory on TPU."""

    def __init__(self, per_process_gpu_memory_fraction=0.0,
                 allow_growth=False, allocator_type="",
                 visible_device_list=""):
        self.per_process_gpu_memory_fraction = per_process_gpu_memory_fraction
        self.allow_growth = allow_growth
        self.allocator_type = allocator_type
        self.visible_device_list = visible_device_list


class GraphOptions:
    """(ref: config.proto ``GraphOptions``)."""

    def __init__(self, enable_recv_scheduling=False, build_cost_model=0,
                 infer_shapes=False, place_pruned_graph=False,
                 optimizer_options=None):
        self.enable_recv_scheduling = enable_recv_scheduling
        self.build_cost_model = build_cost_model
        self.infer_shapes = infer_shapes
        self.place_pruned_graph = place_pruned_graph
        self.optimizer_options = optimizer_options


class ConfigProto:
    """(ref: config.proto ``ConfigProto``).

    transfer_guard: "allow" (default) | "log" | "disallow" — applied by
    Session.run on the HOT path (after the step is compiled and warm) to
    host-numpy feeds and host fetches larger than
    ``transfer_guard_threshold_bytes``. "log" warns once per tensor;
    "disallow" raises InvalidArgumentError with staging guidance.

    graph_analysis: "off" (default) | "warn" | "strict" — stf.analysis
    graph verification. "strict" verifies the whole graph at Session
    construction (ERROR diagnostics raise InvalidArgumentError) and
    re-verifies every new run plan; "warn" logs instead of raising.
    Per-plan results are cached by plan signature (verification runs
    only on executable-cache misses).

    variable_hazard_mode: None (process default, see
    stf.analysis.set_hazard_mode / STF_HAZARD_MODE) | "off" | "warn" |
    "raise" | "auto_deps" — unordered same-variable read/write policy
    per run plan (RAW/WAR/WAW; docs/ANALYSIS.md).

    loop_fusion_steps: default multi-step window for
    ``Session.run_steps(n=None)`` and the transparent
    MonitoredSession/hook driving (docs/PERFORMANCE.md): N > 1 compiles
    N training steps into one device loop, amortizing host dispatch
    1/N. 1 (default) disables transparent fusion.

    compile_cache_dir: directory for the persistent XLA executable
    cache (``compiler.aot.enable_persistent_cache``); a second process
    compiling the same HLO hits the disk cache instead of paying the
    full compile again (the 13-24 s/process ``warmup_plus_compile_s``
    in bench.py). None (default) falls back to the ``STF_COMPILE_CACHE``
    environment variable; empty/unset leaves persistent caching off.
    PROCESS-GLOBAL: the underlying jax compilation-cache directory is
    process-wide state — the first Session that sets it points every
    later compile in the process (including Sessions constructed with
    compile_cache_dir=None) at that directory until it is explicitly
    changed; it is not reverted on Session.close().

    async_fetches: True makes steady-state ``Session.run`` return
    device-produced fetches as lazy ``stf.FetchFuture`` objects that
    ride JAX async dispatch — ``device_get`` happens only when the
    caller materializes (np.asarray/float/.result()), so step N+1's
    staging overlaps step N's device execution. Default False keeps
    the eager-numpy return contract.

    kernel_registry: None (process default: ``STF_PALLAS`` /
    ``stf.kernels.set_mode``) | "off" | "auto" | "force" — the Pallas
    kernel-routing mode for programs this Session lowers
    (docs/PERFORMANCE.md "kernel tier"). "off" restores the
    pre-registry lowerings exactly; "auto" routes per (op, shape,
    dtype, backend) through the cost-model gate + micro-autotune
    cache; "force" pins every eligible op to the Pallas kernel
    (interpret mode off-TPU — the tier-1 testing mode). Applies at
    TRACE time: executables already compiled by this Session keep the
    routing they were traced with. NOTE: the fused optimizer tail is a
    GRAPH-BUILD decision — a graph built while the process default was
    not "off" already contains the fused update op and flat slot
    layout; this session-scoped "off" only picks its composed lowering.
    To restore the per-variable assign tail (and its per-variable slot
    checkpoint layout) set STF_PALLAS=0 / stf.kernels.set_mode("off")
    BEFORE building the optimizer.

    auto_shard: False (default) | True — prescriptive sharding
    (stf.analysis.autoshard; docs/ANALYSIS.md "Auto-sharding"). When a
    >1-device mesh is active at plan time, the FIRST fed (step-shaped)
    plan runs the PartitionSpec search over its pruned op list and
    commits the winner BEFORE compile: variable shardings (already-
    committed state is re-placed immediately), feed shardings, and
    committing ShardingConstraint ops at the searched cut points.
    Explicit user-placed specs are kept as fixed seeds, never
    overridden; the search result is applied once per graph. The
    searched layout then feeds the PR 6 per-plan analyzer, so
    /statusz and RunMetadata predicted-collectives report the CHOSEN
    layout. device_memory_budget_bytes (below), when set, doubles as
    the search's per-shard peak-HBM feasibility budget.

    device_memory_budget_bytes: device-memory admission budget for this
    Session (stf.telemetry.memory; docs/OBSERVABILITY.md "Device
    memory"). When set, every plan is admission-checked at plan time
    (static cost-model peak vs the process HBM ledger's live set),
    every AOT bucket at compile time (XLA memory_analysis), and
    ModelServer.load / GenerativeEngine construction refuse servables
    that cannot fit — all with errors.ResourceExhaustedError naming
    the top owners by bytes plus a flight-recorder oom dump, BEFORE
    anything launches. None/0 (default) disables the check (and its
    plan-time cost estimate entirely).

    telemetry_port: start the process's stf.telemetry HTTP server
    (``/metrics`` Prometheus scrape, ``/healthz``, ``/statusz``,
    ``/tracez``, ``/flightz``, ``/trainz``; docs/OBSERVABILITY.md) when
    the Session is constructed. 0 binds an ephemeral port
    (``stf.telemetry.get_server().port``); None (default) starts
    nothing. PROCESS-GLOBAL like compile_cache_dir: the server outlives
    the Session (one process, one telemetry plane) — constructing a
    second Session with the same (or None) port is a no-op, a
    different fixed port raises.

    numerics: None (process default, see
    stf.debug.numerics.set_numerics_mode / STF_NUMERICS) | "off" |
    "metrics" | "raise" | "dump" — the training numerics-health plane
    (stf.debug.numerics; docs/DEBUG.md). Training-shaped plans are
    auto-instrumented with device-side NumericSummary taps (gradients,
    optimizer updates, loss, plus activations matched by
    ``numerics_taps``); the packed health tensor rides fused windows.
    "metrics" feeds /stf/train/* + /trainz; "raise" additionally raises
    InvalidArgumentError naming the first nonfinite tap and its
    creation site; "dump" additionally re-executes the failing plan in
    checked mode, localizes the first bad op, and writes a tfdbg-style
    dump directory (STF_NUMERICS_DUMP_ROOT or a tmp dir).

    numerics_taps: optional list of name-pattern regexes (the
    match_partition_rules idiom) selecting EXTRA tensors to tap by op
    name, on top of the automatic gradient/update/loss selection.
    """

    def __init__(self, device_count=None, intra_op_parallelism_threads=0,
                 inter_op_parallelism_threads=0, use_per_session_threads=False,
                 session_inter_op_thread_pool=None, placement_period=0,
                 device_filters=None, gpu_options=None,
                 allow_soft_placement=False, log_device_placement=False,
                 graph_options=None, operation_timeout_in_ms=0,
                 transfer_guard="allow",
                 transfer_guard_threshold_bytes=1 << 20,
                 graph_analysis="off", variable_hazard_mode=None,
                 loop_fusion_steps=1, async_fetches=False,
                 compile_cache_dir=None, telemetry_port=None,
                 kernel_registry=None, device_memory_budget_bytes=None,
                 auto_shard=False, numerics=None, numerics_taps=None):
        self.device_count = dict(device_count or {})
        self.intra_op_parallelism_threads = intra_op_parallelism_threads
        self.inter_op_parallelism_threads = inter_op_parallelism_threads
        self.use_per_session_threads = use_per_session_threads
        self.session_inter_op_thread_pool = session_inter_op_thread_pool
        self.placement_period = placement_period
        self.device_filters = list(device_filters or [])
        self.gpu_options = gpu_options or GPUOptions()
        self.allow_soft_placement = allow_soft_placement
        self.log_device_placement = log_device_placement
        self.graph_options = graph_options or GraphOptions()
        self.operation_timeout_in_ms = operation_timeout_in_ms
        if transfer_guard not in ("allow", "log", "disallow"):
            raise ValueError(
                f"transfer_guard must be allow|log|disallow, "
                f"got {transfer_guard!r}")
        self.transfer_guard = transfer_guard
        self.transfer_guard_threshold_bytes = transfer_guard_threshold_bytes
        if graph_analysis not in ("off", "warn", "strict"):
            raise ValueError(
                f"graph_analysis must be off|warn|strict, "
                f"got {graph_analysis!r}")
        self.graph_analysis = graph_analysis
        if variable_hazard_mode is not None and variable_hazard_mode \
                not in ("off", "warn", "raise", "auto_deps"):
            raise ValueError(
                "variable_hazard_mode must be None|off|warn|raise|"
                f"auto_deps, got {variable_hazard_mode!r}")
        self.variable_hazard_mode = variable_hazard_mode
        loop_fusion_steps = int(loop_fusion_steps)
        if loop_fusion_steps < 1:
            raise ValueError(
                f"loop_fusion_steps must be >= 1, got {loop_fusion_steps}")
        self.loop_fusion_steps = loop_fusion_steps
        self.async_fetches = bool(async_fetches)
        self.compile_cache_dir = compile_cache_dir
        if kernel_registry is not None and kernel_registry not in (
                "off", "auto", "force"):
            raise ValueError(
                f"kernel_registry must be None|off|auto|force, "
                f"got {kernel_registry!r}")
        self.kernel_registry = kernel_registry
        if device_memory_budget_bytes is not None:
            device_memory_budget_bytes = int(device_memory_budget_bytes)
            if device_memory_budget_bytes < 0:
                raise ValueError(
                    "device_memory_budget_bytes must be >= 0 or None, "
                    f"got {device_memory_budget_bytes}")
        self.device_memory_budget_bytes = device_memory_budget_bytes
        self.auto_shard = bool(auto_shard)
        if numerics is not None and numerics not in (
                "off", "metrics", "raise", "dump"):
            raise ValueError(
                f"numerics must be None|off|metrics|raise|dump, "
                f"got {numerics!r}")
        self.numerics = numerics
        self.numerics_taps = list(numerics_taps or [])
        if telemetry_port is not None:
            telemetry_port = int(telemetry_port)
            if telemetry_port < 0 or telemetry_port > 65535:
                raise ValueError(
                    f"telemetry_port must be 0..65535 or None, "
                    f"got {telemetry_port}")
        self.telemetry_port = telemetry_port
