"""Graph functions: @Defun (ref: tensorflow/python/framework/function.py).

The reference registers a FunctionDef and calls it through a Call kernel in
the dynamic executor. TPU-native, a defined function is a FuncGraph (the
same machinery as cond/while bodies): the call node lowers by tracing the
body inline into the enclosing XLA program — so XLA inlines, fuses, and
differentiates through it (jax.vjp); there is no call-frame overhead at
runtime. Bodies are traced per input-signature (shape specialization is
what the MXU wants) and cached.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import dtypes as dtypes_mod
from . import graph as ops_mod
from . import op_registry
from . import optimizer as optimizer_mod
from . import lowering as lowering_mod
from . import tensor_shape as shape_mod


def _lower_function_call(ctx, op, inputs):
    fg = op.attrs["func_graph"]
    n_args = op.attrs["n_args"]
    return lowering_mod.lower_func_graph(ctx, fg, inputs[:n_args],
                                         inputs[n_args:])


op_registry.register("GraphFunctionCall", lower=_lower_function_call,
                     n_outputs=None)

# PassManager anatomy: inputs = declared args + captures; the body
# inlines once per call, so no hoisting (LICM would only reorder work)
_CALL_BODIES = lambda a, n: [  # noqa: E731 — shared by both call ops
    dict(attr="func_graph", start=a["n_args"], count=n - a["n_args"],
         hoist=False, count_attr=None)]
optimizer_mod.register_function_op("GraphFunctionCall", mode="call",
                                   bodies=_CALL_BODIES)


def _trace_body(g, func, name, arg_specs):
    """Trace ``func`` into a FuncGraph of ``g`` for the given input specs
    (shared by @Defun and recompute_grad)."""
    fg = ops_mod.FuncGraph(name, outer_graph=g)
    with ops_mod._as_current(fg):
        args = [fg.add_input(dtype, shape, f"arg{i}")
                for i, (shape, dtype) in enumerate(arg_specs)]
        res = func(*args)
        if res is None:
            raise ValueError(f"graph function {name} returned None")
        flat = list(res) if isinstance(res, (list, tuple)) else [res]
        fg.outputs = [ops_mod.convert_to_tensor(t) for t in flat]
    return fg


def _emit_call(g, op_type, fg, tensors, name):
    """Create the call node for a traced FuncGraph (captures appended)."""
    captures = [outer for outer, _ in fg.captures]
    op = g.create_op(
        op_type, list(tensors) + captures,
        attrs={"func_graph": fg, "n_args": len(tensors),
               "func_name": fg.func_name},
        name=name or fg.func_name,
        output_specs=[(t.shape, t.dtype) for t in fg.outputs])
    outs = list(op.outputs)
    return outs[0] if len(outs) == 1 else outs


class _DefinedFunction:
    """A callable graph function (ref function.py:255 ``_DefinedFunction``).

    The body re-traces per (shape, dtype) signature; each call site becomes
    one GraphFunctionCall node whose lowering inlines the traced body.
    """

    def __init__(self, func, input_types: Sequence[Any], func_name=None,
                 grad_func=None, python_grad_func=None, out_names=None):
        self._func = func
        self._input_types = [dtypes_mod.as_dtype(t) for t in input_types]
        self._name = func_name or getattr(func, "__name__", "function")
        self._grad_func = grad_func
        self._python_grad_func = python_grad_func
        self._out_names = out_names

    @property
    def name(self):
        return self._name

    @property
    def declared_input_types(self):
        return list(self._input_types)

    def _trace(self, arg_specs) -> ops_mod.FuncGraph:
        # Traced FuncGraphs capture tensors from the graph current at trace
        # time, so the cache lives ON that graph (a module-level @Defun
        # outlives reset_default_graph(); reusing a FuncGraph across graphs
        # would splice cross-graph tensors into the call op, and caching on
        # the Defun would keep dead graphs alive).
        import weakref

        g = ops_mod.get_default_graph()
        by_defun = g._scoped_state.setdefault(
            "__defun_cache__", weakref.WeakKeyDictionary())
        per_graph = by_defun.setdefault(self, {})
        key = tuple(arg_specs)
        if key in per_graph:
            return per_graph[key]
        fg = _trace_body(g, self._func, self._name, arg_specs)
        per_graph[key] = fg
        return fg

    def __call__(self, *args, name=None):
        if len(args) != len(self._input_types):
            raise ValueError(
                f"{self._name} takes {len(self._input_types)} arguments, "
                f"got {len(args)}")
        g = ops_mod.get_default_graph()
        tensors = [ops_mod.convert_to_tensor(a, dtype=t)
                   for a, t in zip(args, self._input_types)]
        specs = [(t.shape, t.dtype) for t in tensors]
        fg = self._trace(specs)
        return _emit_call(g, "GraphFunctionCall", fg, tensors, name)


class Defun:
    """Decorator: @Defun(stf.float32, stf.float32) (ref function.py:41).

    TPU note: the body lowers inline into the caller's XLA program — the
    decorator is an API-compat and graph-organization tool, not a runtime
    boundary.
    """

    def __init__(self, *input_types, **kwargs):
        self._input_types = input_types
        self._kwargs = kwargs

    def __call__(self, func):
        return _DefinedFunction(
            func, self._input_types,
            func_name=self._kwargs.get("func_name"),
            grad_func=self._kwargs.get("grad_func"),
            python_grad_func=self._kwargs.get("python_grad_func"),
            out_names=self._kwargs.get("out_names"))


def _prefetch_rng_keys(ctx, fg):
    """Derive per-op RNG keys for every stateful op in fg (and nested
    FuncGraphs) OUTSIDE the checkpoint trace: rng_for caches the derived
    key on the LoweringContext, and a key first created inside
    jax.checkpoint's trace would be a leaked tracer. Pre-derived keys are
    closed-over constants — the recompute replays the identical stream
    (dropout masks match between forward and rematerialized backward)."""
    for inner_op in fg.get_operations():
        if op_registry.exists(inner_op.type) and \
                op_registry.get(inner_op.type).is_stateful:
            ctx.rng_for(inner_op)
        for v in inner_op.attrs.values():
            if isinstance(v, ops_mod.FuncGraph):
                _prefetch_rng_keys(ctx, v)


def _lower_recompute_call(ctx, op, inputs):
    """Lower the traced body under jax.checkpoint: XLA saves only the
    call's INPUTS for the backward pass and re-runs the body to
    rematerialize intermediates — the jax.checkpoint counterpart of the
    reference's (contrib) recompute_grad, promoted to a first-class graph
    op because trading FLOPs for HBM is how TPUs buy batch size."""
    import jax

    fg = op.attrs["func_graph"]
    n = op.attrs["n_args"]
    _prefetch_rng_keys(ctx, fg)

    def body(args, caps):
        return lowering_mod.lower_func_graph(ctx, fg, list(args), list(caps))

    return jax.checkpoint(body)(tuple(inputs[:n]), tuple(inputs[n:]))


op_registry.register("RecomputeGradCall", lower=_lower_recompute_call,
                     n_outputs=None)
optimizer_mod.register_function_op("RecomputeGradCall", mode="call",
                                   bodies=_CALL_BODIES)


def recompute_grad(func, name=None):
    """Wrap ``func`` so reverse-mode AD rematerializes its intermediates
    instead of saving them (jax.checkpoint under the hood). Usage:

        block = stf.recompute_grad(lambda x: expensive_block(x))
        y = block(x)

    The body is traced per input signature (like @Defun); variables it
    reads are captured and re-read on the recompute."""

    def wrapper(*args, **kwargs):
        if kwargs:
            raise TypeError("recompute_grad functions take positional "
                            "tensor arguments only")
        g = ops_mod.get_default_graph()
        tensors = [ops_mod.convert_to_tensor(a) for a in args]
        specs = tuple((t.shape, t.dtype) for t in tensors)
        cache = g._scoped_state.setdefault("__recompute_cache__", {})
        # key on the func OBJECT, not id(func): the dict then holds a
        # strong reference, so a discarded lambda's recycled id can never
        # alias another function's traced body (observed: per-layer
        # lambdas silently sharing one layer's weights)
        key = (func, specs)
        fg = cache.get(key)
        if fg is None:
            fg = _trace_body(g, func,
                             name or getattr(func, "__name__", "recompute"),
                             specs)
            cache[key] = fg
        return _emit_call(g, "RecomputeGradCall", fg, tensors,
                          name or "recompute_grad")

    return wrapper


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6): call
# bodies propagate inline.
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.make_loop_rule("call"),
                      "GraphFunctionCall", "RecomputeGradCall")
