"""Graph functions: @Defun (ref: tensorflow/python/framework/function.py).

The reference registers a FunctionDef and calls it through a Call kernel in
the dynamic executor. TPU-native, a defined function is a FuncGraph (the
same machinery as cond/while bodies): the call node lowers by tracing the
body inline into the enclosing XLA program — so XLA inlines, fuses, and
differentiates through it (jax.vjp); there is no call-frame overhead at
runtime. Bodies are traced per input-signature (shape specialization is
what the MXU wants) and cached.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import dtypes as dtypes_mod
from . import graph as ops_mod
from . import op_registry
from . import lowering as lowering_mod
from . import tensor_shape as shape_mod


def _lower_function_call(ctx, op, inputs):
    fg = op.attrs["func_graph"]
    n_args = op.attrs["n_args"]
    return lowering_mod.lower_func_graph(ctx, fg, inputs[:n_args],
                                         inputs[n_args:])


op_registry.register("GraphFunctionCall", lower=_lower_function_call,
                     n_outputs=None)


class _DefinedFunction:
    """A callable graph function (ref function.py:255 ``_DefinedFunction``).

    The body re-traces per (shape, dtype) signature; each call site becomes
    one GraphFunctionCall node whose lowering inlines the traced body.
    """

    def __init__(self, func, input_types: Sequence[Any], func_name=None,
                 grad_func=None, python_grad_func=None, out_names=None):
        self._func = func
        self._input_types = [dtypes_mod.as_dtype(t) for t in input_types]
        self._name = func_name or getattr(func, "__name__", "function")
        self._grad_func = grad_func
        self._python_grad_func = python_grad_func
        self._out_names = out_names

    @property
    def name(self):
        return self._name

    @property
    def declared_input_types(self):
        return list(self._input_types)

    def _trace(self, arg_specs) -> ops_mod.FuncGraph:
        # Traced FuncGraphs capture tensors from the graph current at trace
        # time, so the cache lives ON that graph (a module-level @Defun
        # outlives reset_default_graph(); reusing a FuncGraph across graphs
        # would splice cross-graph tensors into the call op, and caching on
        # the Defun would keep dead graphs alive).
        import weakref

        g = ops_mod.get_default_graph()
        by_defun = g._scoped_state.setdefault(
            "__defun_cache__", weakref.WeakKeyDictionary())
        per_graph = by_defun.setdefault(self, {})
        key = tuple(arg_specs)
        if key in per_graph:
            return per_graph[key]
        fg = ops_mod.FuncGraph(self._name, outer_graph=g)
        with ops_mod._as_current(fg):
            args = [fg.add_input(dtype, shape, f"arg{i}")
                    for i, (shape, dtype) in enumerate(arg_specs)]
            res = self._func(*args)
            if res is None:
                raise ValueError(
                    f"@Defun function {self._name} returned None")
            flat = list(res) if isinstance(res, (list, tuple)) else [res]
            fg.outputs = [ops_mod.convert_to_tensor(t) for t in flat]
        per_graph[key] = fg
        return fg

    def __call__(self, *args, name=None):
        if len(args) != len(self._input_types):
            raise ValueError(
                f"{self._name} takes {len(self._input_types)} arguments, "
                f"got {len(args)}")
        g = ops_mod.get_default_graph()
        tensors = [ops_mod.convert_to_tensor(a, dtype=t)
                   for a, t in zip(args, self._input_types)]
        specs = [(t.shape, t.dtype) for t in tensors]
        fg = self._trace(specs)
        captures = [outer for outer, _ in fg.captures]
        op = g.create_op(
            "GraphFunctionCall", tensors + captures,
            attrs={"func_graph": fg, "n_args": len(tensors),
                   "func_name": self._name},
            name=name or self._name,
            output_specs=[(t.shape, t.dtype) for t in fg.outputs])
        outs = list(op.outputs)
        return outs[0] if len(outs) == 1 else outs


class Defun:
    """Decorator: @Defun(stf.float32, stf.float32) (ref function.py:41).

    TPU note: the body lowers inline into the caller's XLA program — the
    decorator is an API-compat and graph-organization tool, not a runtime
    boundary.
    """

    def __init__(self, *input_types, **kwargs):
        self._input_types = input_types
        self._kwargs = kwargs

    def __call__(self, func):
        return _DefinedFunction(
            func, self._input_types,
            func_name=self._kwargs.get("func_name"),
            grad_func=self._kwargs.get("grad_func"),
            python_grad_func=self._kwargs.get("python_grad_func"),
            out_names=self._kwargs.get("out_names"))
