"""Symbolic gradients via XLA autodiff.

TPU-native replacement for the reference's backward-graph builder
(ref: tensorflow/python/ops/gradients_impl.py ``gradients`` and the ~60
per-op @RegisterGradient rules in python/ops/*_grad.py, core/ops/*_grad.cc).

Design: ``stf.gradients(ys, xs)`` does NOT build an explicit backward graph
op-by-op. It inserts one ``SymbolicGradient`` node whose lowering re-traces
the forward slice between xs and ys as a pure function and calls ``jax.vjp``
on it. Consequences:

- the backward pass is derived by JAX/XLA's autodiff — provably consistent
  with the forward lowering, zero per-op gradient maintenance;
- forward replay is CSE'd by XLA against the original forward (same traced
  ops, same RNG streams — see random_seed.py), so there is no double
  compute in the compiled program;
- backward fuses with forward in ONE XLA program — on TPU this is the whole
  ballgame (the reference schedules backward kernels dynamically).

tf.gradients-compatible surface: returns None for disconnected xs, supports
grad_ys, stop_gradients handled by the StopGradient op (→ lax.stop_gradient).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from . import errors as errors_mod
from . import graph as ops_mod
from . import op_registry
from . import lowering as lowering_mod
from .indexed_slices import IndexedSlices

Tensor = ops_mod.Tensor


def _as_tensor_list(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


_GRADIENT_REGISTRY = {}
_NOT_DIFFERENTIABLE = set()


class RegisterGradient:
    """Decorator registering a gradient function under a name (ref:
    python/framework/ops.py ``RegisterGradient``). Used with
    ``graph.gradient_override_map({"OpType": "Name"})``: ops of that type
    created inside the map differentiate through ``fn(op, *grads)``
    instead of their normal vjp. The fn builds stf graph ops from
    ``op.inputs``/``op.outputs``; it is traced once into a FuncGraph and
    lowered inside the backward pass."""

    def __init__(self, op_type):
        self._name = op_type

    def __call__(self, fn):
        _GRADIENT_REGISTRY[self._name] = fn
        return fn


def NotDifferentiable(op_type):  # noqa: N802 — TF-1 API name
    """Mark an op type as non-differentiable: its outputs carry zero
    cotangents (ref: ops.py ``NotDifferentiable``)."""
    _NOT_DIFFERENTIABLE.add(op_type)


NoGradient = NotDifferentiable  # deprecated TF-1 alias


def _execute_with_override(child, op, grad_type, lowering):
    """Run ``op`` in the forward replay under a jax.custom_vjp whose
    backward lowers the registered gradient FuncGraph."""
    import jax
    import jax.numpy as jnp

    grad_fn = _GRADIENT_REGISTRY[grad_type]
    opdef = op.op_def
    if opdef.is_stateful or opdef.runs_on_host:
        raise errors_mod.InvalidArgumentError(
            None, op,
            f"gradient_override_map on stateful/host op {op.type} is not "
            "supported (override pure compute ops only)")
    fg = op.attrs.get("_override_fg")
    if fg is None:
        from . import function as function_mod
        from ..ops import array_ops

        def traced(*gys):
            res = grad_fn(op, *gys)
            flat = list(res) if isinstance(res, (list, tuple)) else [res]
            if len(flat) != len(op.inputs):
                raise ValueError(
                    f"@RegisterGradient({grad_type!r}) returned "
                    f"{len(flat)} gradients for {len(op.inputs)} inputs "
                    f"of {op.name}")
            return [g if g is not None else array_ops.zeros_like(x)
                    for g, x in zip(flat, op.inputs)]

        fg = function_mod._trace_body(
            op.graph, traced, f"{op.name}_override_grad",
            [(o.shape, o.dtype) for o in op.outputs])
        op.attrs["_override_fg"] = fg

    invals = [child.value_of(t) for t in op.inputs]

    @jax.custom_vjp
    def f(*xs):
        return tuple(opdef.lower(child, op, list(xs)))

    def f_fwd(*xs):
        outs = tuple(opdef.lower(child, op, list(xs)))
        tmp = dict(zip(op.inputs, xs))
        tmp.update(zip(op.outputs, outs))
        cap_vals = []
        for outer, _ in fg.captures:
            if outer in tmp:
                cap_vals.append(tmp[outer])
            else:
                cap_vals.append(child.value_of(outer))
        return outs, (xs, tuple(cap_vals))

    def f_bwd(res, gys):
        import numpy as np
        from jax import dtypes as jax_dtypes

        xs, cap_vals = res
        ctx2 = lowering.LoweringContext({}, rng_root=None)
        grads = lowering.lower_func_graph(ctx2, fg, list(gys),
                                          list(cap_vals))
        out = []
        for gr, x in zip(grads, xs):
            # integer/bool primals (gather ids, masks) take float0
            # cotangents — custom_vjp rejects a same-dtype zeros array
            if not jnp.issubdtype(jnp.result_type(x), jnp.inexact):
                out.append(np.zeros(jnp.shape(x),
                                    dtype=jax_dtypes.float0))
            elif gr is None:
                out.append(jnp.zeros_like(x))
            else:
                out.append(gr)
        return tuple(out)

    f.defvjp(f_fwd, f_bwd)
    outs = f(*invals)
    for t, v in zip(op.outputs, outs):
        child.env[t] = v


def _while_reaches_ys_differentiably(while_op, ys, stop_set):
    """True iff a While op's output can carry a nonzero cotangent from ys.

    Paths cut by ``stop_gradients``, by a StopGradient op, or passing only
    through non-floating tensors (e.g. argmax/sampled indices feeding a
    gather) receive zero cotangents, so the loop transpose is never invoked
    and the forward-only While is harmless — don't reject those graphs.
    """
    yset = set(ys)
    seen = set()
    work = [t for t in while_op.outputs
            if (t.dtype.is_floating or t.dtype.is_complex)
            and t not in stop_set]
    while work:
        t = work.pop()
        if t in seen:
            continue
        seen.add(t)
        if t in yset:
            return True
        for consumer in t.consumers():
            if consumer.type == "StopGradient":
                continue
            for out in consumer.outputs:
                if ((out.dtype.is_floating or out.dtype.is_complex)
                        and out not in stop_set):
                    work.append(out)
    return False


class _ReadIndex:
    """Lazy var_name -> ReadVariable-output index over a graph.

    ``candidates(x)`` returns every tensor TF-1 considers "the
    variable" for differentiation: the ref anchor, the cached value()
    snapshot, and any explicit read_value() ops (ref gradients_impl
    maps reads to the ref the same way). For a plain Tensor it is just
    ``[x]``. Shared by gradients() and hessians() so first- and
    second-order behavior cannot diverge."""

    def __init__(self, g):
        self._g = g
        self._by_var = None

    def candidates(self, x):
        if not hasattr(x, "_grad_anchor"):
            return [x]
        cands = [x._grad_anchor()]
        base = getattr(x, "_var_name", None)
        if base is not None:
            if self._by_var is None:
                self._by_var = {}
                for op_ in self._g.get_operations():
                    if op_.type == "ReadVariable":
                        self._by_var.setdefault(
                            op_.attrs.get("var_name"),
                            []).append(op_.outputs[0])
            cands.extend(self._by_var.get(base, ()))
        return cands


def gradients(ys, xs, grad_ys=None, name="gradients",
              colocate_gradients_with_ops=False, gate_gradients=False,
              aggregation_method=None, stop_gradients=None) -> List[Optional[Tensor]]:
    """d(sum ys)/d(xs). (ref: python/ops/gradients_impl.py:154 ``gradients``).

    Returns a list aligned with xs; entries are None for xs not reachable
    from ys (reference behavior relied on by Optimizer.compute_gradients).
    """
    ys = _as_tensor_list(ys)
    xs_in = _as_tensor_list(xs)
    g = ops_mod.get_default_graph()

    # Variables passed directly -> differentiate w.r.t. EVERY read of
    # that variable the ys can reach (the ref anchor, the cached
    # value() snapshot, and any explicit read_value() ops — TF-1 treats
    # them all as the variable; ref gradients_impl maps reads to the
    # ref the same way). Contributions from multiple reads sum below.
    xs = []         # flat candidate tensors, deduped
    xs_groups = []  # per xs_in entry: its candidate tensors
    seen_x = set()
    index = _ReadIndex(g)
    for x in xs_in:
        if hasattr(x, "_grad_anchor") or isinstance(x, Tensor):
            cands = index.candidates(x)
        else:
            raise TypeError(
                f"gradients: xs must be Tensors/Variables, got {x!r}")
        xs_groups.append(cands)
        for c in cands:
            if c not in seen_x:
                seen_x.add(c)
                xs.append(c)

    if stop_gradients:
        from ..ops import array_ops  # noqa: F401  (StopGradient registered)

        stop_set = set(_as_tensor_list(stop_gradients))
    else:
        stop_set = set()

    if grad_ys is not None:
        grad_ys = [ops_mod.convert_to_tensor(gy) if gy is not None else None
                   for gy in _as_tensor_list(grad_ys)]
        if len(grad_ys) != len(ys):
            raise ValueError("grad_ys must match ys in length")
    else:
        grad_ys = [None] * len(ys)

    path_ops, connected = lowering_mod.ancestors_between(xs, ys)
    # A While WITH static maximum_iterations is differentiable: the vjp
    # replay lowers it as a masked lax.scan over the bound (see
    # control_flow_ops._lower_while). Only the unbounded form must fail
    # here, at graph construction, with an actionable message — the
    # alternative is an opaque lax.while_loop autodiff error deep inside
    # Session.run lowering.
    while_on_path = [o.name for o in path_ops if o.type == "While"
                     and o.attrs.get("max_iterations") is None
                     and _while_reaches_ys_differentiably(o, ys, stop_set)]
    if while_on_path:
        raise errors_mod.InvalidArgumentError(
            None, None,
            "Reverse-mode gradients cannot cross an UNBOUNDED while_loop "
            f"on TPU (XLA cannot differentiate it; on path: "
            f"{while_on_path[:3]}). Pass maximum_iterations= to "
            "while_loop (the bounded loop replays as a masked, "
            "differentiable lax.scan in the backward pass), or use "
            "stf.scan / stf.foldl / dynamic_rnn (lax.scan-based).")

    with g.name_scope(name):
        connected_xs = [x for x in xs if x in connected
                        and (x.dtype.is_floating or x.dtype.is_complex)]
        if not connected_xs:
            return [None] * len(xs_groups)
        supplied_gys = [gy for gy in grad_ys if gy is not None]
        attrs = {
            "n_ys": len(ys),
            "n_xs": len(connected_xs),
            "grad_ys_mask": tuple(gy is not None for gy in grad_ys),
            "stop_tensors": tuple(stop_set),
        }
        inputs = list(ys) + list(connected_xs) + supplied_gys
        out_specs = [(x.shape, x.dtype) for x in connected_xs]
        op = g.create_op("SymbolicGradient", inputs, attrs=attrs,
                         name="grad", output_specs=out_specs)
        grads_by_x = dict(zip(connected_xs, op.outputs))

        out = []
        for cands in xs_groups:
            parts = [grads_by_x[c] for c in cands if c in grads_by_x]
            if not parts:
                out.append(None)
            elif len(parts) == 1:
                out.append(parts[0])
            else:
                # a variable read through several tensors: the total
                # derivative is the sum of the per-read cotangents
                from ..ops import math_ops as _mm

                out.append(_mm.add_n(parts))
    return out


def _lower_symbolic_gradient(ctx, op, input_values):
    import jax
    import jax.numpy as jnp

    n_ys = op.attrs["n_ys"]
    n_xs = op.attrs["n_xs"]
    gys_mask = op.attrs["grad_ys_mask"]
    ys = list(op.inputs[:n_ys])
    xs = list(op.inputs[n_ys:n_ys + n_xs])
    ys_vals = input_values[:n_ys]
    xs_vals = input_values[n_ys:n_ys + n_xs]
    supplied = list(input_values[n_ys + n_xs:])

    path_ops, _ = lowering_mod.ancestors_between(xs, ys)
    path_set = set(path_ops)
    xset = set(xs)
    stop_set = set(op.attrs.get("stop_tensors", ()))

    def forward(*args):
        # Capture off-path values from the already-lowered env; CRUCIALLY drop
        # on-path values so the slice is re-traced as a function of ``args``
        # (XLA CSEs the replay against the original forward).
        env = {t: v for t, v in ctx.env.items() if t.op not in path_set}
        # Plan-time CSE aliases are valid only under the PLAN's topo order;
        # this replay re-executes path ops in the RAW graph's order, where a
        # dup's canonical may come later than the dup's consumer. So: on-path
        # ops re-execute and self-provide (alias disabled below); off-path
        # dup keys are seeded from their canonical's captured value. A dup
        # whose canonical is on-path shares its inputs, so it is either
        # re-executed itself or unused by the slice.
        for dup, canon in ctx.alias.items():
            if dup.op not in path_set and canon in env:
                env.setdefault(dup, env[canon])
        env.update(zip(xs, args))
        child = ctx.child(env)
        child.alias = {}
        # ops on the replay path must lower in their differentiable form
        # (a bounded While becomes a masked lax.scan)
        child.differentiable = True
        for path_op in path_ops:
            grad_type = path_op.attrs.get("_gradient_op_type")
            if grad_type is not None and grad_type in _GRADIENT_REGISTRY:
                _execute_with_override(child, path_op, grad_type,
                                       lowering_mod)
            else:
                lowering_mod.execute_ops(child, [path_op], fed=xset)
            if path_op.type in _NOT_DIFFERENTIABLE:
                for out in path_op.outputs:
                    if out in child.env:
                        child.env[out] = jax.lax.stop_gradient(
                            child.env[out])
            if stop_set:
                for out in path_op.outputs:
                    if out in stop_set and out in child.env:
                        child.env[out] = jax.lax.stop_gradient(child.env[out])
        return tuple(child.env[y] for y in ys)

    primals_out, vjp_fn = jax.vjp(forward, *xs_vals)

    cotangents = []
    it = iter(supplied)
    for i, y in enumerate(ys):
        if gys_mask[i]:
            cotangents.append(next(it))
        else:
            cotangents.append(jnp.ones_like(primals_out[i]))
    grads = vjp_fn(tuple(cotangents))
    return list(grads)


op_registry.register("SymbolicGradient", lower=_lower_symbolic_gradient,
                     n_outputs=None)


class GradientTape:
    """Minimal TF2-style tape for convenience; builds on stf.gradients."""

    def __init__(self, persistent=False):
        self._persistent = persistent
        self._used = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def gradient(self, target, sources):
        if self._used and not self._persistent:
            raise RuntimeError("Non-persistent tape used twice")
        self._used = True
        res = gradients(target, sources if isinstance(sources, (list, tuple))
                        else [sources])
        if isinstance(sources, (list, tuple)):
            return res
        return res[0]


class AggregationMethod:
    """(ref: gradients_impl.py ``AggregationMethod``) — XLA fuses gradient
    accumulation; these are accepted for API parity and ignored."""

    ADD_N = 0
    DEFAULT = ADD_N
    EXPERIMENTAL_TREE = 1
    EXPERIMENTAL_ACCUMULATE_N = 2


def hessians(ys, xs, name="hessians", colocate_gradients_with_ops=False,
             gate_gradients=False, aggregation_method=None):
    """Full Hessian of the scalar ``ys`` w.r.t. each x (ref:
    gradients_impl.py ``hessians``): output shapes x.shape + x.shape.
    Lowers to ``jax.hessian`` over the forward slice — forward-over-
    reverse in ONE XLA program (the reference builds gradients-of-
    gradients graphs node by node)."""
    ys_l = _as_tensor_list(ys)
    if len(ys_l) != 1:
        raise ValueError("hessians: ys must be a single scalar tensor")
    y = ys_l[0]
    if y.shape.rank not in (0, None):
        raise ValueError(f"hessians: ys must be scalar, got {y.shape}")
    xs_in = _as_tensor_list(xs)
    g = ops_mod.get_default_graph()
    index = _ReadIndex(g)
    outs = []
    with g.name_scope(name):
        for x in xs_in:
            # all reads of a variable bind to the SAME hessian argument
            # in the lowering, so jax.hessian sees the total second
            # derivative (incl. cross terms between reads)
            cands = index.candidates(x)
            xt = cands[0]
            from . import tensor_shape as shape_mod

            hshape = (shape_mod.TensorShape(
                (xt.shape.as_list() or []) + (xt.shape.as_list() or []))
                if xt.shape.rank is not None
                else shape_mod.TensorShape(None))
            # n_ys/n_xs use the SymbolicGradient attr contract so the
            # static cost model prices the replayed slice correctly
            op = g.create_op("SymbolicHessian", [y] + cands,
                             attrs={"n_ys": 1, "n_xs": len(cands)},
                             name="hess",
                             output_specs=[(hshape,
                                            xt.dtype.base_dtype)])
            outs.append(op.outputs[0])
    return outs


def _lower_symbolic_hessian(ctx, op, input_values):
    import jax

    y = op.inputs[0]
    reads = list(op.inputs[1:])  # all reads of the variable (or [x])
    xv = input_values[1]
    path_ops, _ = lowering_mod.ancestors_between(reads, [y])
    path_set = set(path_ops)

    def forward(xval):
        env = {t: v for t, v in ctx.env.items() if t.op not in path_set}
        for dup, canon in ctx.alias.items():
            if dup.op not in path_set and canon in env:
                env.setdefault(dup, env[canon])
        # every read binds the SAME argument: jax.hessian then computes
        # the total second derivative including cross-read terms. All
        # reads are evaluated at the REF's value — a read that observes
        # a different value via control-dep-ordered assigns within the
        # step is approximated at the ref's point (gradients() feeds
        # per-read values; second order does not).
        for r in reads:
            env[r] = xval
        child = ctx.child(env)
        child.alias = {}
        child.differentiable = True
        lowering_mod.execute_ops(child, path_ops, fed=set(reads))
        return child.env[y]

    return [jax.hessian(forward)(xv)]


op_registry.register("SymbolicHessian", lower=_lower_symbolic_hessian,
                     n_outputs=1)


# ---------------------------------------------------------------------------
# sharding propagation rule for SymbolicGradient (stf.analysis.sharding;
# ISSUE 6). Each grad output adopts its x's sharding; the backward
# contraction sums over every mesh axis that shards the forward path
# but not x itself — for dp training that is exactly the per-step
# gradient all-reduce (payload = the gradient's per-device bytes),
# which dominates the bench-validated collective-byte prediction.
# ---------------------------------------------------------------------------

def _sharding_symbolic_gradient(op, in_specs, ctx):
    from ..analysis import sharding as _shard

    n_ys = op.attrs.get("n_ys", 1)
    n_xs = op.attrs.get("n_xs", 1)
    ys = list(op.inputs[:n_ys])
    xs = list(op.inputs[n_ys:n_ys + n_xs])
    path_axes = set()
    for y, s in zip(ys, in_specs[:n_ys]):
        path_axes |= set(_shard.spec_axes(s))
    # the graph walk is the expensive part and the op list is fixed for
    # the analysis: cache the path ops per SymbolicGradient op (the rule
    # runs once per sweep); specs are re-read from the live env
    cache = getattr(ctx, "_engine", None)
    cache = cache._grad_path_cache if cache is not None else {}
    path_ops = cache.get(op)
    if path_ops is None:
        try:
            path_ops, _ = lowering_mod.ancestors_between(xs, ys)
        except Exception:
            path_ops = []
        cache[op] = path_ops
    for p in path_ops:
        for t in p.outputs:
            path_axes |= set(_shard.spec_axes(ctx.spec(t)))
    path_axes = {a for a in path_axes if ctx.mesh_axes.get(a, 1) > 1}
    outs = []
    data_axes = set(getattr(ctx, "data_axes", ()) or ())
    for i, x in enumerate(xs):
        sp = in_specs[n_ys + i]
        if sp is None:
            sp = _shard.replicated(x.shape.rank)
        # Axes sharding the forward path but not x force a cross-shard
        # contraction of x's gradient. For a WEIGHT the batch is the
        # contracted dim, so a DATA axis (sharded batch) crosses
        # devices even when the weight's own spec carries it on another
        # dim — dp-batch + dp-sharded-weight (ZeRO) is the
        # reduce-scatter (payload already divided by x's shard factor
        # below); replicated weights pay the classic full all-reduce; a
        # tp-style axis that shards the weight itself still costs
        # nothing (Megatron column-parallel). A batch-carrying target
        # (input/activation: saliency, adversarial grads) contracts
        # nothing over the batch — its gradient is sharded like the
        # tensor itself and needs no data-axis sync.
        is_weight = x.op.type in ("VariableV2", "ReadVariable")
        red = path_axes - set(_shard.spec_axes(sp))
        if is_weight:
            red |= (path_axes & data_axes)
        if red and i < len(op.outputs):
            g = op.outputs[i]
            # payload at the ACCUMULATOR precision, not the storage
            # dtype: GSPMD places the sync on the dot/conv partial-sum
            # output, which XLA accumulates in >=f32 even for bf16
            # weights — the dp8 bench HLO all-reduces f32[...] for a
            # bf16 model, so a bf16-sized prediction ran exactly 2x low
            gbytes = _shard.tensor_bytes(g)
            try:
                sz = g.dtype.base_dtype.size
                if sz < 4:
                    gbytes = gbytes / sz * 4.0
            except Exception:
                pass
            ctx.collective(
                "all-reduce", tuple(sorted(red)),
                gbytes / ctx.shard_factor(sp),
                note=f"gradient sync for {x.name}", tensor_name=g.name)
        outs.append(sp)
    return outs[:len(op.outputs)]


def _sharding_symbolic_gradient_backward(op, out_specs, in_specs, ctx):
    # cotangents mirror their primals: suggest each x's spec back onto
    # the x input slots (ys/grad_ys stay untouched)
    n_ys = op.attrs.get("n_ys", 1)
    n_xs = op.attrs.get("n_xs", 1)
    out = [None] * len(in_specs)
    for i in range(min(n_xs, len(out_specs))):
        if n_ys + i < len(out):
            out[n_ys + i] = out_specs[i]
    return out


_sharding_symbolic_gradient.backward = _sharding_symbolic_gradient_backward
op_registry.register_sharding_rule("SymbolicGradient",
                                   _sharding_symbolic_gradient)
op_registry.register_sharding_rule("SymbolicHessian",
                                   _sharding_symbolic_gradient)
