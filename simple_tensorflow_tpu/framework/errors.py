"""Error hierarchy matching the reference's tf.errors.

(ref: tensorflow/python/framework/errors_impl.py). The reference derives these
from grpc/absl status codes; here they are plain Python exceptions raised by
the session, lowering, and IO layers.
"""

from __future__ import annotations

OK = 0
CANCELLED = 1
UNKNOWN = 2
INVALID_ARGUMENT = 3
DEADLINE_EXCEEDED = 4
NOT_FOUND = 5
ALREADY_EXISTS = 6
PERMISSION_DENIED = 7
UNAUTHENTICATED = 16
RESOURCE_EXHAUSTED = 8
FAILED_PRECONDITION = 9
ABORTED = 10
OUT_OF_RANGE = 11
UNIMPLEMENTED = 12
INTERNAL = 13
UNAVAILABLE = 14
DATA_LOSS = 15


class OpError(Exception):
    """Base class for errors raised while executing an operation.

    Carries the failing node's name/op like the reference
    (ref: python/framework/errors_impl.py:38 ``class OpError``).
    """

    def __init__(self, node_def, op, message, error_code):
        super().__init__(message)
        self._node_def = node_def
        self._op = op
        self._message = message
        self._error_code = error_code

    @property
    def message(self):
        return self._message

    @property
    def op(self):
        return self._op

    @property
    def node_def(self):
        return self._node_def

    @property
    def error_code(self):
        return self._error_code

    def __str__(self):
        if self._op is not None:
            return f"{self._message}\n\t [[node {getattr(self._op, 'name', self._op)}]]"
        return self._message


def _make(name, code, doc):
    def __init__(self, node_def=None, op=None, message=None):
        if message is None and isinstance(node_def, str):
            # Convenience: Error("message")
            node_def, message = None, node_def
        OpError.__init__(self, node_def, op, message or name, code)

    cls = type(name, (OpError,), {"__init__": __init__, "__doc__": doc})
    return cls


CancelledError = _make("CancelledError", CANCELLED, "Operation was cancelled.")
UnknownError = _make("UnknownError", UNKNOWN, "Unknown error.")
InvalidArgumentError = _make("InvalidArgumentError", INVALID_ARGUMENT,
                             "Op received an invalid argument.")
DeadlineExceededError = _make("DeadlineExceededError", DEADLINE_EXCEEDED,
                              "Deadline expired before operation completed.")
NotFoundError = _make("NotFoundError", NOT_FOUND, "Requested entity not found.")
AlreadyExistsError = _make("AlreadyExistsError", ALREADY_EXISTS,
                           "Entity already exists.")
PermissionDeniedError = _make("PermissionDeniedError", PERMISSION_DENIED,
                              "Caller lacks permission.")
UnauthenticatedError = _make("UnauthenticatedError", UNAUTHENTICATED,
                             "Request lacks valid authentication.")
ResourceExhaustedError = _make("ResourceExhaustedError", RESOURCE_EXHAUSTED,
                               "A resource (e.g. HBM) was exhausted.")
FailedPreconditionError = _make("FailedPreconditionError", FAILED_PRECONDITION,
                                "System not in required state (e.g. uninitialized variable).")
AbortedError = _make("AbortedError", ABORTED, "Operation aborted.")
OutOfRangeError = _make("OutOfRangeError", OUT_OF_RANGE,
                        "Operation iterated past valid range (e.g. end of dataset).")
UnimplementedError = _make("UnimplementedError", UNIMPLEMENTED,
                           "Operation not implemented.")
InternalError = _make("InternalError", INTERNAL, "Internal invariant broken.")
UnavailableError = _make("UnavailableError", UNAVAILABLE,
                         "Runtime currently unavailable (e.g. peer down).")
DataLossError = _make("DataLossError", DATA_LOSS,
                      "Unrecoverable data loss or corruption (e.g. bad CRC).")

_CODE_TO_EXC = {
    CANCELLED: CancelledError, UNKNOWN: UnknownError,
    INVALID_ARGUMENT: InvalidArgumentError, DEADLINE_EXCEEDED: DeadlineExceededError,
    NOT_FOUND: NotFoundError, ALREADY_EXISTS: AlreadyExistsError,
    PERMISSION_DENIED: PermissionDeniedError, UNAUTHENTICATED: UnauthenticatedError,
    RESOURCE_EXHAUSTED: ResourceExhaustedError,
    FAILED_PRECONDITION: FailedPreconditionError, ABORTED: AbortedError,
    OUT_OF_RANGE: OutOfRangeError, UNIMPLEMENTED: UnimplementedError,
    INTERNAL: InternalError, UNAVAILABLE: UnavailableError,
    DATA_LOSS: DataLossError,
}


def exception_type_from_error_code(code):
    return _CODE_TO_EXC[code]


def error_code_from_exception_type(cls):
    for code, c in _CODE_TO_EXC.items():
        if c is cls:
            return code
    raise KeyError(cls)


class raise_exception_on_not_ok_status:
    """Context manager kept for reference-API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
