"""Graph → JAX lowering: the TPU-native "executor".

This replaces the reference's per-node dynamic executor
(ref: tensorflow/core/common_runtime/executor.cc ``ExecutorState::Process``,
direct_session.cc ``DirectSession::Run``). Instead of dispatching one kernel
at a time off a ready queue, we:

  1. prune the graph to the ancestors of the fetches, stopping at fed
     tensors (ref: core/graph/subgraph.cc ``RewriteGraphForExecution``),
  2. topologically order the pruned ops (data + control edges),
  3. *trace* them in order inside one function — each op's lowering rule
     emits jax/lax calls — producing a single pure function
     ``f(feeds, state, rng) -> (fetches, state')``,
  4. hand that function to jax.jit, so XLA compiles and fuses the whole step
     for the MXU (this is the tf2xla "cluster" model, ref
     tensorflow/compiler/tf2xla, promoted to the only execution path).

Statefulness is functionalized: variable reads pull from ``ctx.state``,
writes replace entries and are returned as outputs; random ops derive
per-op PRNG keys from a per-step root key (see random_seed.py).
Control-dependency ordering is preserved because lowering walks ops in
topological order over data+control edges; effects on the same variable are
thus ordered exactly when the graph orders them (the reference has the same
contract, enforced dynamically).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import graph as ops_mod
from . import op_registry
from .errors import FailedPreconditionError, InvalidArgumentError

Operation = ops_mod.Operation
Tensor = ops_mod.Tensor


# ---------------------------------------------------------------------------
# Pruning / ordering
# ---------------------------------------------------------------------------

_NATIVE_PRUNE_MIN_NODES = 512  # below this, ctypes marshalling beats C DFS


def _ancestor_set(target_ops, fed_tensors):
    """Unordered dependency closure of targets (cheap BFS; O(|ancestors|),
    independent of total graph size)."""
    seen = set()
    work = list(target_ops)
    while work:
        op = work.pop()
        if op in seen:
            continue
        seen.add(op)
        for t in op.inputs:
            if t not in fed_tensors and t.op not in seen:
                work.append(t.op)
        for c in op.control_inputs:
            if c not in seen:
                work.append(c)
    return seen


def prune(target_ops: Sequence[Operation],
          fed_tensors: Set[Tensor]) -> List[Operation]:
    """Ops needed to compute ``target_ops`` given ``fed_tensors`` are
    supplied externally. Returns a deterministic topological order
    (data + control edges). Large fetch subgraphs go through the native
    C++ pruner (runtime_cc/graph.cc); this Python DFS is the fallback and
    the cycle-error path. Gating keys on the *ancestor* count, not total
    graph size, so a small fetch in a huge graph stays O(|ancestors|)."""
    if target_ops:
        anc = _ancestor_set(target_ops, fed_tensors)
        if len(anc) >= _NATIVE_PRUNE_MIN_NODES:
            native_order = _prune_native(anc, target_ops, fed_tensors)
            if native_order is not None:
                return native_order
    order: List[Operation] = []
    state: Dict[Operation, int] = {}  # 0=visiting, 1=done

    def deps(op: Operation):
        for t in op.inputs:
            if t not in fed_tensors:
                yield t.op
        yield from op.control_inputs

    # Iterative DFS postorder for deep graphs.
    for root in target_ops:
        if state.get(root) == 1:
            continue
        stack: List[Tuple[Operation, Any]] = [(root, None)]
        while stack:
            op, it = stack[-1]
            if it is None:
                if state.get(op) == 1:
                    stack.pop()
                    continue
                if state.get(op) == 0:
                    stack.pop()
                    continue
                state[op] = 0
                it = iter(list(deps(op)))
                stack[-1] = (op, it)
            advanced = False
            for d in it:
                if state.get(d) is None:
                    stack.append((d, None))
                    advanced = True
                    break
                if state.get(d) == 0 and d is not op:
                    cycle = " -> ".join(o.name for o, _ in stack[-5:])
                    raise InvalidArgumentError(
                        None, op, f"Graph cycle detected near: {cycle}")
            if not advanced:
                state[op] = 1
                order.append(op)
                stack.pop()
    return order


def _prune_native(ancestors, target_ops, fed_tensors):
    """Flat-array edge list over the ancestor region -> runtime_cc
    StfPruneToposort. Returns None (falling back to the Python DFS) when
    the native library is absent or reports a cycle — the Python path
    raises the contextful error."""
    try:
        from ..runtime import native
    except Exception:
        return None
    if not native.available():
        return None
    import numpy as np

    # deterministic node order: graph insertion order via op id
    region = sorted(ancestors, key=lambda op: op._id)
    ids = {op: i for i, op in enumerate(region)}
    edges = []
    for op, i in ids.items():
        for t in op.inputs:
            if t not in fed_tensors:
                edges.append((ids[t.op], i))
        for c in op.control_inputs:
            edges.append((ids[c], i))
    edge_arr = (np.asarray(edges, dtype=np.int32)
                if edges else np.empty((0, 2), np.int32))
    order = native.prune_toposort(
        len(region), edge_arr, [ids[op] for op in target_ops])
    if order is None:
        return None
    return [region[i] for i in order]


def ancestors_between(xs: Sequence[Tensor], ys: Sequence[Tensor]
                      ) -> Tuple[List[Operation], Set[Tensor]]:
    """Ops on a data path from any x to any y, in topological order, plus the
    subset of ``xs`` actually connected to ``ys``. Used by the symbolic
    gradient lowering to re-trace just the differentiated slice (everything
    off-path is captured from the already-lowered environment; XLA CSEs the
    replayed on-path ops against the originals)."""
    xset = set(xs)
    desc: Set[Operation] = set()
    work: List[Operation] = []
    for t in xs:
        work.extend(t.consumers())
    while work:
        op = work.pop()
        if op in desc:
            continue
        desc.add(op)
        for out in op.outputs:
            work.extend(out.consumers())
    anc_order = prune([y.op for y in ys], fed_tensors=xset)
    path = [op for op in anc_order if op in desc]
    path_set = set(path)
    connected = {x for x in xs
                 if any(y is x for y in ys)
                 or any(c in path_set for c in x.consumers())}
    return path, connected


# ---------------------------------------------------------------------------
# Lowering context
# ---------------------------------------------------------------------------

class LoweringContext:
    """Carries the functionalized state while tracing a pruned subgraph.

    state:  var name -> current jax value (mutated as Assign ops lower).
    written: var names assigned during this step (become donated outputs).
    rng_root: per-step PRNG key; ops derive theirs via fold_in.
    env:    Tensor -> traced jax value.
    host:   True when executing the host stage (no jax tracing).
    """

    def __init__(self, state: Dict[str, Any], rng_root, feeds=None,
                 host=False, session=None):
        self.state = state
        self.written: Set[str] = set()
        self.var_metadata: Dict[str, Any] = {}
        self.rng_root = rng_root
        self.env: Dict[Tensor, Any] = dict(feeds or {})
        self.host = host
        self.session = session
        # kernel-registry routing mode for ops traced under this context
        # (stf.kernels): ConfigProto(kernel_registry=...) when the
        # session set one, else None = the process default. execute_ops
        # activates it thread-locally around the trace loop, so every
        # registry decision inside this plan (including FuncGraph bodies,
        # shard_map'd jax helpers, and SymbolicGradient replays) sees the
        # session's mode.
        self.kernel_mode = None
        if session is not None:
            cfg = getattr(session, "_config", None)
            self.kernel_mode = getattr(cfg, "kernel_registry", None) \
                if cfg is not None else None
        self.sharding_env = None  # set by parallel lowering
        self.in_control_flow = False
        self.in_shard_map = False
        # True while tracing the SymbolicGradient forward replay: op
        # lowerings may pick a differentiable form (e.g. a bounded While
        # lowers to a masked lax.scan instead of lax.while_loop)
        self.differentiable = False
        # CSE alias map from the plan-time optimizer: duplicate tensor ->
        # canonical tensor; consulted on every input lookup
        self.alias: Dict[Tensor, Tensor] = {}
        # per-plan FuncGraph body plans (optimizer._plan_function_bodies):
        # fg -> (op_list, const_env, alias). Scoped to THIS compiled
        # plan — never stashed on the FuncGraph, because which captures
        # are constant depends on the plan's feed set.
        self.func_plans: Dict[Any, Any] = {}
        self._rng_cache: Dict[int, Any] = {}
        # CheckNumerics flags gathered during trace: [(message, bool value)];
        # the Session fetches them with the step and raises host-side
        self.numeric_checks: List[Tuple[str, Any]] = []

    def child(self, env: Dict[Tensor, Any],
              in_control_flow: Optional[bool] = None) -> "LoweringContext":
        c = LoweringContext.__new__(LoweringContext)
        c.kernel_mode = self.kernel_mode
        c.state = self.state
        c.written = self.written
        c.var_metadata = self.var_metadata
        c.rng_root = self.rng_root
        c.env = env
        c.host = self.host
        c.session = self.session
        c.sharding_env = self.sharding_env
        c.in_control_flow = (self.in_control_flow if in_control_flow is None
                             else in_control_flow)
        c.in_shard_map = self.in_shard_map
        c.differentiable = self.differentiable
        c.alias = self.alias
        c.func_plans = self.func_plans
        c._rng_cache = self._rng_cache
        c.numeric_checks = self.numeric_checks
        return c

    # -- state ---------------------------------------------------------------
    def read_var(self, name: str, op=None):
        if name not in self.state:
            raise FailedPreconditionError(
                None, op,
                f"Attempting to use uninitialized variable {name!r}. "
                "Run stf.global_variables_initializer() first.")
        return self.state[name]

    def write_var(self, name: str, value):
        if self.in_control_flow:
            raise InvalidArgumentError(
                None, None,
                f"Variable {name!r} is assigned inside a cond/while/scan "
                "body. XLA structured control flow cannot write cross-step "
                "state from a branch; carry the value as a loop variable and "
                "assign it after the loop (TPU-native pattern).")
        self.state[name] = value
        self.written.add(name)

    def var_exists(self, name: str) -> bool:
        return name in self.state

    # -- rng -----------------------------------------------------------------
    def rng_for(self, op: Operation):
        """Per-op key: deterministic within a step, so jax.vjp forward replay
        reuses the same stream (dropout masks match fwd/bwd) and XLA CSEs the
        replayed ops."""
        from . import random_seed

        fold = random_seed.fold_in_value(op)
        if fold not in self._rng_cache:
            import jax

            self._rng_cache[fold] = jax.random.fold_in(self.rng_root, fold)
        return self._rng_cache[fold]

    # -- values --------------------------------------------------------------
    def value_of(self, tensor: Tensor):
        tensor = self.alias.get(tensor, tensor)
        if tensor in self.env:
            return self.env[tensor]
        raise InternalLoweringError(
            f"Tensor {tensor.name} has no value in the lowering env — "
            "pruning/ordering bug.")


class InternalLoweringError(Exception):
    pass


def check_step_read_write_races(
        op_list: Sequence[Operation],
        alias: Optional[Dict[Tensor, Tensor]] = None) -> None:
    """SURVEY §5 ordering detector — now a thin wrapper over the
    stf.analysis variable-hazard engine (analysis/hazards.py), which
    generalizes the original read-your-write check to full RAW/WAR/WAW
    detection over the op registry's declared effect sets and adds the
    warn/auto_deps modes. Kept for direct callers: raises
    InvalidArgumentError on any enforceable unordered hazard, exactly as
    before. Bare-fetch reads stay exempt (observations with documented
    topological-position semantics, see state_ops.py ReadVariable)."""
    from ..analysis import hazards

    hazards.check_plan(op_list, alias, mode="raise")


def execute_ops(ctx: LoweringContext, op_list: Sequence[Operation],
                fed: Optional[Set[Tensor]] = None):
    """Trace ops in topological order, populating ctx.env.

    The kernel-registry mode (stf.kernels) is activated thread-locally
    for the duration of the trace: op lowerings — and any jax-level
    helpers they call under shard_map/scan/vjp — route Pallas vs XLA
    under the session's ConfigProto(kernel_registry=...) (or the
    process default when the context carries None).

    ``fed`` is accepted for call-site compatibility only: fed-tensor
    pruning happened in prune(), and every fed tensor is already bound
    in ctx.env before the trace starts."""
    from ..kernels import registry as _kernels

    with _kernels.activate(ctx.kernel_mode):
        _execute_ops_inner(ctx, op_list)


def _execute_ops_inner(ctx: LoweringContext,
                       op_list: Sequence[Operation]):
    for op in op_list:
        already = all(o in ctx.env for o in op.outputs) and op.outputs
        # CapturedInput/FuncArg are bound values, not effects: when a branch
        # returns a capture directly, its op is a prune target but its value
        # is already in env — skip despite the stateful registration.
        if already and (not op.op_def.is_stateful
                        or op.type in ("CapturedInput", "FuncArg")):
            continue
        input_vals = []
        for t in op.inputs:
            t = ctx.alias.get(t, t)
            input_vals.append(ctx.env[t] if t in ctx.env else ctx.value_of(t))
        outputs = op.op_def.lower(ctx, op, input_vals)
        if len(outputs) != len(op.outputs):
            raise InternalLoweringError(
                f"Op {op.name} ({op.type}) lowered to {len(outputs)} outputs, "
                f"graph says {len(op.outputs)}")
        for t, v in zip(op.outputs, outputs):
            ctx.env[t] = v


def lower_func_graph(ctx: LoweringContext, fg: "ops_mod.FuncGraph",
                     arg_values: Sequence[Any],
                     capture_values: Sequence[Any]) -> List[Any]:
    """Lower a FuncGraph body given values for its declared inputs and its
    captures; returns values for fg.outputs. Used by cond/while/scan/function
    lowering.

    When the plan-time optimizer recorded an optimized plan for this
    body in ctx.func_plans (optimizer._plan_function_bodies), that plan
    drives the trace instead of a fresh prune: constant-folded interior
    values seed the env as host constants, CSE-duplicate tensors resolve
    through the body's alias map, and DCE'd ops never trace — so
    in-body fold/CSE wins apply on EVERY iteration of a while/scan
    body."""
    env: Dict[Tensor, Any] = {}
    if len(arg_values) != len(fg.inputs):
        raise InternalLoweringError(
            f"FuncGraph {fg.func_name}: {len(arg_values)} args for "
            f"{len(fg.inputs)} inputs")
    for t, v in zip(fg.inputs, arg_values):
        env[t] = v
    for (outer, inner), v in zip(fg.captures, capture_values):
        env[inner] = v
    child = ctx.child(env, in_control_flow=True)
    plan = ctx.func_plans.get(fg)
    if plan is not None:
        needed, body_consts, body_alias = plan
        if body_alias:
            # replace (never mutate) the shared alias dict
            merged = dict(child.alias)
            merged.update(body_alias)
            child.alias = merged
        for t, v in body_consts.items():
            env.setdefault(t, v)  # bound args/captures win over seeds
    else:
        needed = prune([t.op for t in fg.outputs],
                       fed_tensors=set(env.keys()))
    execute_ops(child, needed, fed=set(env.keys()))
    return [child.value_of(t) for t in fg.outputs]


def capture_values_for(ctx: LoweringContext, fg: "ops_mod.FuncGraph") -> List[Any]:
    """Resolve a FuncGraph's captured outer tensors against the current env."""
    vals = []
    for outer, _ in fg.captures:
        vals.append(ctx.value_of(outer))
    return vals
