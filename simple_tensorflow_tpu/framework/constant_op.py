"""Constant and placeholder ops (ref: python/framework/constant_op.py,
core/kernels/constant_op.cc).

Constants are stored as numpy arrays in the op's attrs and become XLA
literals at lowering; XLA constant-folds them aggressively, which subsumes
most of the reference's ConstantFolding pass
(ref: core/common_runtime/constant_folding.cc).
"""

from __future__ import annotations

import numpy as np

from . import dtypes as dtypes_mod
from . import graph as ops
from . import op_registry
from . import tensor_shape as shape_mod


def _to_numpy(value, dtype=None):
    if dtype is not None:
        dtype = dtypes_mod.as_dtype(dtype)
    if dtype is not None and dtype.name == "string":
        return np.asarray(value, dtype=object)
    if isinstance(value, np.ndarray):
        arr = value
    else:
        arr = np.asarray(value)
    if arr.dtype.kind in "USO" and (dtype is None or dtype.name == "string"):
        return np.asarray(arr, dtype=object)
    if dtype is not None:
        arr = arr.astype(dtype.np_dtype)
    elif arr.dtype == np.float64 and not isinstance(value, np.ndarray):
        # Python floats default to float32 (TPU-friendly), like jax.
        arr = arr.astype(np.float32)
    elif arr.dtype == np.int64 and not isinstance(value, np.ndarray):
        arr = arr.astype(np.int32)
    return arr


def constant(value, dtype=None, shape=None, name="Const", verify_shape=False):
    """Create a constant tensor (ref: python/framework/constant_op.py:102)."""
    g = ops.get_default_graph()
    if isinstance(value, ops.Tensor):
        return value
    arr = _to_numpy(value, dtype)
    if shape is not None:
        shape = shape_mod.as_shape(shape)
        n_target = shape.num_elements()
        if arr.size == 1 and n_target is not None and n_target != arr.size:
            arr = np.full(shape.as_list(), arr.reshape(()), dtype=arr.dtype)
        elif verify_shape and list(arr.shape) != shape.as_list():
            raise TypeError(f"Expected shape {shape}, got {list(arr.shape)}")
        else:
            arr = arr.reshape(shape.as_list())
    dt = dtypes_mod.as_dtype(dtype) if dtype is not None else dtypes_mod.as_dtype(arr.dtype) \
        if arr.dtype.kind not in "USO" else dtypes_mod.string
    op = g.create_op("Const", [], attrs={"value": arr, "dtype": dt},
                     name=name,
                     output_specs=[(shape_mod.TensorShape(list(arr.shape)), dt)])
    return op.outputs[0]


def is_constant(tensor_or_op) -> bool:
    op = tensor_or_op.op if isinstance(tensor_or_op, ops.Tensor) else tensor_or_op
    return op.type == "Const"


def constant_value(tensor, partial=False):
    """Best-effort static value of a tensor
    (ref: python/framework/tensor_util.py ``constant_value``)."""
    if not isinstance(tensor, ops.Tensor):
        return np.asarray(tensor)
    op = tensor.op
    if op.type == "Const":
        return op.attrs["value"]
    if op.type == "Identity":
        return constant_value(op.inputs[0], partial)
    if op.type == "Shape":
        sh = op.inputs[0].shape
        if sh.is_fully_defined():
            return np.asarray(sh.as_list(), dtype=np.int32)
    if op.type == "Rank":
        sh = op.inputs[0].shape
        if sh.rank is not None:
            return np.asarray(sh.rank, dtype=np.int32)
    if op.type == "Size":
        sh = op.inputs[0].shape
        if sh.is_fully_defined():
            return np.asarray(sh.num_elements(), dtype=np.int32)
    if op.type in ("Pack", "Stack"):
        vals = [constant_value(i, partial) for i in op.inputs]
        if all(v is not None for v in vals):
            return np.stack(vals, axis=op.attrs.get("axis", 0))
    if op.type == "Cast":
        v = constant_value(op.inputs[0], partial)
        if v is not None:
            return v.astype(op.attrs["dtype"].np_dtype)
    return None


def constant_value_as_shape(tensor) -> shape_mod.TensorShape:
    v = constant_value(tensor)
    if v is None:
        sh = tensor.shape
        if sh.rank == 1 and sh[0].value is not None:
            return shape_mod.unknown_shape(rank=sh[0].value)
        return shape_mod.TensorShape(None)
    return shape_mod.TensorShape([int(d) for d in np.ravel(v)])


# -- op registrations --------------------------------------------------------

def _lower_const(ctx, op, inputs):
    import jax.numpy as jnp

    val = op.attrs["value"]
    if op.attrs["dtype"].name == "string":
        return [val]  # host-only value; never enters the XLA program
    return [jnp.asarray(val)]


op_registry.register("Const", lower=_lower_const)


def _lower_placeholder(ctx, op, inputs):
    raise RuntimeError(
        f"Placeholder {op.name} was not fed. You must feed a value for it "
        "via Session.run(..., feed_dict={...}).")


op_registry.register("Placeholder", lower=_lower_placeholder, is_stateful=True)


def _lower_placeholder_with_default(ctx, op, inputs):
    return [inputs[0]]


op_registry.register("PlaceholderWithDefault", lower=_lower_placeholder_with_default)


def _lower_unbound(kind):
    def lower(ctx, op, inputs):
        raise RuntimeError(
            f"{kind} {op.name} lowered outside its binding context — "
            "this is an internal control-flow lowering bug.")

    return lower


op_registry.register("CapturedInput", lower=_lower_unbound("CapturedInput"),
                     is_stateful=True)
op_registry.register("FuncArg", lower=_lower_unbound("FuncArg"), is_stateful=True)
