"""Graph/op seed plumbing → deterministic JAX PRNG key derivation.

(ref: tensorflow/python/framework/random_seed.py). The reference combines
graph-level and op-level seeds into the kernel's Philox state. Here the same
two-level API derives *fold-in values* for a functional PRNG: every session
step has a root key (advanced once per Session.run), and each random op folds
in a stable per-op value, so:

- two random ops in one step draw independent streams,
- the same op re-lowered (e.g. inside jax.vjp forward replay) reuses the SAME
  stream — dropout masks agree between forward and backward, and XLA CSEs the
  replayed subgraph,
- with op_seed set, the op's stream is reproducible across runs regardless of
  graph construction order (TF-1.0 parity).
"""

from __future__ import annotations

import zlib

from . import graph as ops

DEFAULT_GRAPH_SEED = 87654321


def get_seed(op_seed):
    """Return (graph_seed, op_seed) like the reference
    (ref: python/framework/random_seed.py:27 ``get_seed``)."""
    g = ops.get_default_graph()
    graph_seed = g.seed
    if graph_seed is not None:
        if op_seed is None:
            op_seed = g._op_counter
        return graph_seed, op_seed
    if op_seed is not None:
        return DEFAULT_GRAPH_SEED, op_seed
    return None, None


def set_random_seed(seed):
    """(ref: random_seed.py:75 ``set_random_seed``)."""
    ops.get_default_graph().seed = seed


def fold_in_value(op) -> int:
    """Stable 32-bit stream id for a random op, from its seeds or its name."""
    graph_seed = op.attrs.get("_graph_seed")
    op_seed = op.attrs.get("seed")
    if graph_seed is not None or op_seed is not None:
        return ((graph_seed or 0) * 1000003 + (op_seed or 0)) & 0x7FFFFFFF
    # Unseeded: derive from the op name — stable across re-lowerings within
    # this graph, varies between distinct ops.
    return zlib.crc32(op.name.encode()) & 0x7FFFFFFF
