"""DType system for simple_tensorflow_tpu.

TPU-native rework of the reference dtype registry
(ref: tensorflow/python/framework/dtypes.py): the set of user-visible dtypes
matches the reference, but the backing representation is a numpy/ml_dtypes
dtype that JAX understands directly — no proto enum, no quantized side-band
types (int8/uint8 + scale factors are plain tensors here, as XLA wants them).
bfloat16 is a first-class citizen (it's the TPU MXU's native input type).
"""

from __future__ import annotations

import builtins
import dataclasses
from typing import Any, Optional

import numpy as np

try:  # ml_dtypes ships with jax
    import ml_dtypes as _mld

    _BFLOAT16_NP = np.dtype(_mld.bfloat16)
    _FP8_E4M3_NP = np.dtype(_mld.float8_e4m3fn)
    _FP8_E5M2_NP = np.dtype(_mld.float8_e5m2)
except Exception:  # pragma: no cover - ml_dtypes is always present with jax
    _BFLOAT16_NP = np.dtype(np.float32)
    _FP8_E4M3_NP = np.dtype(np.float32)
    _FP8_E5M2_NP = np.dtype(np.float32)


@dataclasses.dataclass(frozen=True)
class DType:
    """A tensor element type.

    Thin, hashable wrapper over a numpy dtype with the reference API surface:
    ``is_floating``, ``is_integer``, ``min``/``max``, ``base_dtype``,
    ``as_numpy_dtype`` etc. (ref: python/framework/dtypes.py:31 ``class DType``).
    ``_is_ref`` mirrors the reference's ``*_ref`` variants used for variable
    endpoints; on TPU variables are functional state so refs only matter for
    API fidelity.
    """

    name: str
    np_dtype: np.dtype
    _is_ref: bool = False

    # -- classification ------------------------------------------------------
    @property
    def is_floating(self) -> bool:
        return self.np_dtype.kind == "f" or self.name.startswith(("bfloat", "float8"))

    @property
    def is_integer(self) -> bool:
        return self.np_dtype.kind in ("i", "u")

    @property
    def is_unsigned(self) -> bool:
        return self.np_dtype.kind == "u"

    @property
    def is_complex(self) -> bool:
        return self.np_dtype.kind == "c"

    @property
    def is_bool(self) -> bool:
        return self.np_dtype.kind == "b"

    @property
    def is_numpy_compatible(self) -> bool:
        return True

    @property
    def is_quantized(self) -> bool:
        return self.name.startswith("q")

    # -- conversion ----------------------------------------------------------
    @property
    def as_numpy_dtype(self):
        return self.np_dtype.type

    @property
    def base_dtype(self) -> "DType":
        if self._is_ref:
            return DType(self.name[: -len("_ref")], self.np_dtype)
        return self

    @property
    def real_dtype(self) -> "DType":
        if self.name == "complex64":
            return float32
        if self.name == "complex128":
            return float64
        return self

    @property
    def is_ref_dtype(self) -> bool:
        return self._is_ref

    @property
    def _ref(self) -> "DType":
        if self._is_ref:
            return self
        return DType(self.name + "_ref", self.np_dtype, True)

    # -- limits --------------------------------------------------------------
    @property
    def min(self):
        if self.is_bool:
            return False
        if self.name == "bfloat16":
            return float(_mld.finfo(_mld.bfloat16).min)
        if self.is_floating:
            return float(np.finfo(self.np_dtype).min)
        return int(np.iinfo(self.np_dtype).min)

    @property
    def max(self):
        if self.is_bool:
            return True
        if self.name == "bfloat16":
            return float(_mld.finfo(_mld.bfloat16).max)
        if self.is_floating:
            return float(np.finfo(self.np_dtype).max)
        return int(np.iinfo(self.np_dtype).max)

    @property
    def limits(self):
        return (self.min, self.max)

    @property
    def size(self) -> int:
        return self.np_dtype.itemsize

    def is_compatible_with(self, other) -> bool:
        other = as_dtype(other)
        return self.base_dtype == other.base_dtype

    def __str__(self):
        return f"<dtype: '{self.name}'>"

    def __repr__(self):
        return f"stf.{self.name}"

    def __eq__(self, other):
        if other is None:
            return False
        try:
            other = as_dtype(other)
        except TypeError:
            return NotImplemented
        return self.name == other.name

    def __ne__(self, other):
        eq = self.__eq__(other)
        if eq is NotImplemented:
            return eq
        return not eq

    def __hash__(self):
        return hash(self.name)


# Registry -------------------------------------------------------------------

float16 = DType("float16", np.dtype(np.float16))
half = float16
bfloat16 = DType("bfloat16", _BFLOAT16_NP)
float32 = DType("float32", np.dtype(np.float32))
float64 = DType("float64", np.dtype(np.float64))
double = float64
float8_e4m3fn = DType("float8_e4m3fn", _FP8_E4M3_NP)
float8_e5m2 = DType("float8_e5m2", _FP8_E5M2_NP)
int8 = DType("int8", np.dtype(np.int8))
int16 = DType("int16", np.dtype(np.int16))
int32 = DType("int32", np.dtype(np.int32))
int64 = DType("int64", np.dtype(np.int64))
uint8 = DType("uint8", np.dtype(np.uint8))
uint16 = DType("uint16", np.dtype(np.uint16))
uint32 = DType("uint32", np.dtype(np.uint32))
uint64 = DType("uint64", np.dtype(np.uint64))
bool_ = DType("bool", np.dtype(np.bool_))
complex64 = DType("complex64", np.dtype(np.complex64))
complex128 = DType("complex128", np.dtype(np.complex128))
# Strings are host-side only (parsing, filenames); represented as numpy object
# arrays and never shipped to the TPU.
string = DType("string", np.dtype(object))
# Quantized dtypes (ref: framework/types.h DT_QINT8 etc.). On TPU the MXU
# consumes plain s8/u8/s32 with separate scale tensors, so these are
# distinct *names* over the native widths — exactly how the int8 Pallas
# quant_matmul wants its operands.
qint8 = DType("qint8", np.dtype(np.int8))
quint8 = DType("quint8", np.dtype(np.uint8))
qint32 = DType("qint32", np.dtype(np.int32))
qint16 = DType("qint16", np.dtype(np.int16))
quint16 = DType("quint16", np.dtype(np.uint16))

_ALL = [
    float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2,
    int8, int16, int32, int64, uint8, uint16, uint32, uint64,
    bool_, complex64, complex128, string,
    qint8, quint8, qint32, qint16, quint16,
]
_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME.update({d.name + "_ref": d._ref for d in _ALL})
_BY_NAME["bool"] = bool_
_BY_NAME["half"] = float16
_BY_NAME["double"] = float64

_NP_TO_DTYPE = {}
for _d in _ALL:
    if _d.name == "string":
        continue
    _NP_TO_DTYPE.setdefault(_d.np_dtype, _d)
# Python scalar defaults: int -> int32 (TPU-friendly; jax default), float -> float32.
_PY_DEFAULTS = {builtins.int: int32, builtins.float: float32, builtins.bool: bool_,
                builtins.complex: complex64, builtins.str: string, bytes: string}


def as_dtype(value) -> DType:
    """Convert ``value`` (DType, string, numpy dtype, python type, jax dtype)
    to a DType. (ref: python/framework/dtypes.py:580 ``as_dtype``)."""
    if isinstance(value, DType):
        return value
    if value is None:
        raise TypeError("Cannot convert None to DType")
    if isinstance(value, str):
        if value in _BY_NAME:
            return _BY_NAME[value]
        raise TypeError(f"Cannot convert {value!r} to a DType")
    if value in _PY_DEFAULTS:
        return _PY_DEFAULTS[value]
    try:
        np_dt = np.dtype(value)
    except TypeError:
        raise TypeError(f"Cannot convert {value!r} to a DType")
    if np_dt in _NP_TO_DTYPE:
        return _NP_TO_DTYPE[np_dt]
    if np_dt.kind in ("U", "S", "O"):
        return string
    raise TypeError(f"Cannot convert {value!r} to a DType")


# -- 64-bit narrowing (VERDICT weak #6) --------------------------------------
#
# TPUs have no int64/float64 datapath; with jax_enable_x64 off (the
# default), 64-bit requests compute in 32 bits. The divergence is
# documented loudly in docs/MIGRATION.md; at runtime it surfaces as ONE
# warning at the session/feed boundary — never a per-op warning storm.

_64BIT_NARROWING = {"int64": "int32", "uint64": "uint32",
                    "float64": "float32"}
_narrowing_warned = [False]


def narrowed_if_no_x64(dtype) -> DType:
    """The dtype 64-bit requests actually compute with: narrowed to its
    32-bit sibling when jax_enable_x64 is off, unchanged otherwise. Op
    lowerings that honor an explicit 64-bit out_type route through this
    so jax never emits its per-callsite truncation warning."""
    d = as_dtype(dtype)
    base = d.base_dtype.name
    if base not in _64BIT_NARROWING:
        return d
    import jax

    if jax.config.jax_enable_x64:
        return d
    return as_dtype(_64BIT_NARROWING[base])


def warn_64bit_narrowing_once(where: str) -> None:
    """Emit the single process-wide 64-bit narrowing notice (the
    session/feed boundary calls this when a 64-bit tensor first crosses
    it). Replaces the per-op jax truncation warnings."""
    if _narrowing_warned[0]:
        return
    import jax

    if jax.config.jax_enable_x64:
        return
    _narrowing_warned[0] = True
    import warnings

    warnings.warn(
        f"stf: {where} uses a 64-bit dtype, but TPU (and this runtime "
        "with jax_enable_x64 off) computes int64/uint64/float64 as "
        "32-bit. Values past 2**31 or needing f64 precision will be "
        "WRONG, not an error. See docs/MIGRATION.md '64-bit dtypes' "
        "for details and JAX_ENABLE_X64=1 for CPU-only full-width "
        "runs. (This warning is emitted once per process.)",
        UserWarning, stacklevel=3)


def infer_dtype(value) -> DType:
    """Infer the stf dtype of a concrete python/numpy/jax value."""
    import jax

    if isinstance(value, (jax.Array, np.ndarray, np.generic)):
        return as_dtype(value.dtype)
    if isinstance(value, builtins.bool):
        return bool_
    if isinstance(value, builtins.int):
        return int32
    if isinstance(value, builtins.float):
        return float32
    if isinstance(value, builtins.complex):
        return complex64
    if isinstance(value, (builtins.str, bytes)):
        return string
    if isinstance(value, (list, tuple)):
        arr = np.asarray(value)
        return as_dtype(arr.dtype) if arr.dtype.kind not in "USO" else string
    raise TypeError(f"Cannot infer dtype of {type(value)}")
