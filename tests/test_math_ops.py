"""Math op numeric tests vs numpy + gradient checks
(mirrors ref python/kernel_tests/cwise_ops_test.py etc., SURVEY §4)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _run(t, feed=None):
    with stf.Session() as sess:
        return sess.run(t, feed)


RNG = np.random.RandomState(7)


class TestElementwise:
    def test_binary_ops_vs_numpy(self):
        a = RNG.rand(3, 4).astype(np.float32) + 0.5
        b = RNG.rand(3, 4).astype(np.float32) + 0.5
        ta, tb = stf.constant(a), stf.constant(b)
        cases = {
            "add": (stf.add(ta, tb), a + b),
            "sub": (stf.subtract(ta, tb), a - b),
            "mul": (stf.multiply(ta, tb), a * b),
            "div": (stf.divide(ta, tb), a / b),
            "floordiv": (stf.floordiv(ta, tb), a // b),
            "mod": (stf.mod(ta, tb), np.mod(a, b)),
            "pow": (stf.pow(ta, tb), a ** b),
            "max": (stf.maximum(ta, tb), np.maximum(a, b)),
            "min": (stf.minimum(ta, tb), np.minimum(a, b)),
            "sqdiff": (stf.squared_difference(ta, tb), (a - b) ** 2),
            "atan2": (stf.atan2(ta, tb), np.arctan2(a, b)),
        }
        out = _run({k: v[0] for k, v in cases.items()})
        for k, (_, expect) in cases.items():
            np.testing.assert_allclose(out[k], expect, rtol=1e-5, atol=1e-5,
                                       err_msg=k)

    def test_unary_ops_vs_numpy(self):
        a = RNG.rand(2, 5).astype(np.float32) * 0.8 + 0.1
        ta = stf.constant(a)
        cases = {
            "neg": (stf.negative(ta), -a),
            "abs": (stf.abs(ta), np.abs(a)),
            "square": (stf.square(ta), a * a),
            "sqrt": (stf.sqrt(ta), np.sqrt(a)),
            "rsqrt": (stf.rsqrt(ta), 1 / np.sqrt(a)),
            "exp": (stf.exp(ta), np.exp(a)),
            "expm1": (stf.expm1(ta), np.expm1(a)),
            "log": (stf.log(ta), np.log(a)),
            "log1p": (stf.log1p(ta), np.log1p(a)),
            "sin": (stf.sin(ta), np.sin(a)),
            "cos": (stf.cos(ta), np.cos(a)),
            "tanh": (stf.tanh(ta), np.tanh(a)),
            "sigmoid": (stf.sigmoid(ta), 1 / (1 + np.exp(-a))),
            "erf": (stf.erf(ta), None),  # checked for finiteness below
            "floor": (stf.floor(ta), np.floor(a)),
            "ceil": (stf.ceil(ta), np.ceil(a)),
            "sign": (stf.sign(ta), np.sign(a)),
            "reciprocal": (stf.reciprocal(ta), 1 / a),
        }
        out = _run({k: v[0] for k, v in cases.items()})
        for k, (_, expect) in cases.items():
            if expect is not None:
                np.testing.assert_allclose(out[k], expect, rtol=1e-5,
                                           atol=1e-5, err_msg=k)
        assert np.isfinite(out["erf"]).all()

    def test_comparisons_and_logical(self):
        a = np.array([1, 2, 3], np.int32)
        b = np.array([2, 2, 2], np.int32)
        ta, tb = stf.constant(a), stf.constant(b)
        out = _run({
            "eq": stf.equal(ta, tb), "ne": stf.not_equal(ta, tb),
            "lt": stf.less(ta, tb), "le": stf.less_equal(ta, tb),
            "gt": stf.greater(ta, tb), "ge": stf.greater_equal(ta, tb),
        })
        assert out["eq"].tolist() == [False, True, False]
        assert out["lt"].tolist() == [True, False, False]
        assert out["ge"].tolist() == [False, True, True]
        x = stf.constant([True, False])
        y = stf.constant([True, True])
        out2 = _run({"and": stf.logical_and(x, y),
                     "or": stf.logical_or(x, y),
                     "xor": stf.logical_xor(x, y),
                     "not": stf.logical_not(x)})
        assert out2["and"].tolist() == [True, False]
        assert out2["xor"].tolist() == [False, True]

    def test_mixed_dtype_rejected(self):
        with pytest.raises(TypeError):
            stf.add(stf.constant(1.0), stf.constant(1))


class TestReductions:
    def test_reduce_vs_numpy(self):
        a = RNG.rand(3, 4, 5).astype(np.float32)
        t = stf.constant(a)
        out = _run({
            "sum": stf.reduce_sum(t), "sum0": stf.reduce_sum(t, axis=0),
            "sum_keep": stf.reduce_sum(t, axis=[1], keepdims=True),
            "mean": stf.reduce_mean(t, axis=[0, 2]),
            "prod": stf.reduce_prod(t, axis=2),
            "max": stf.reduce_max(t, axis=1),
            "min": stf.reduce_min(t),
            "lse": stf.reduce_logsumexp(t, axis=-1),
        })
        np.testing.assert_allclose(out["sum"], a.sum(), rtol=1e-5)
        np.testing.assert_allclose(out["sum0"], a.sum(0), rtol=1e-5)
        assert out["sum_keep"].shape == (3, 1, 5)
        np.testing.assert_allclose(out["mean"], a.mean((0, 2)), rtol=1e-5)
        np.testing.assert_allclose(out["lse"],
                                   np.log(np.exp(a).sum(-1)), rtol=1e-5)

    def test_bool_reductions(self):
        m = stf.constant([[True, False], [True, True]])
        out = _run({"all": stf.reduce_all(m, axis=1),
                    "any": stf.reduce_any(m, axis=0)})
        assert out["all"].tolist() == [False, True]
        assert out["any"].tolist() == [True, True]

    def test_argminmax_cumsum(self):
        a = np.array([[3., 1., 2.], [0., 5., 4.]], np.float32)
        t = stf.constant(a)
        out = _run({
            "argmax": stf.argmax(t, 1), "argmin": stf.argmin(t, 0),
            "cumsum": stf.cumsum(t, axis=1),
            "cumsum_ex": stf.cumsum(t, axis=1, exclusive=True),
            "cumsum_rev": stf.cumsum(t, axis=1, reverse=True),
            "cumprod": stf.cumprod(t, axis=0),
        })
        assert out["argmax"].tolist() == [0, 1]
        np.testing.assert_allclose(out["cumsum"], np.cumsum(a, 1))
        np.testing.assert_allclose(out["cumsum_ex"],
                                   [[0, 3, 4], [0, 0, 5]])
        np.testing.assert_allclose(out["cumsum_rev"][:, 0], a.sum(1))

    def test_segment_ops(self):
        data = stf.constant([1., 2., 3., 4.])
        seg = stf.constant([0, 0, 1, 1])
        out = _run({
            "sum": stf.segment_sum(data, seg),
            "mean": stf.segment_mean(data, seg),
            "max": stf.segment_max(data, seg),
            "unsorted": stf.unsorted_segment_sum(data, stf.constant(
                [1, 0, 1, 0]), 2),
        })
        assert out["sum"].tolist() == [3., 7.]
        assert out["mean"].tolist() == [1.5, 3.5]
        assert out["unsorted"].tolist() == [6., 4.]

    def test_bincount(self):
        v = stf.constant([0, 1, 1, 3])
        assert _run(stf.bincount(v)).tolist() == [1, 2, 0, 1]


class TestMatmul:
    def test_matmul_variants(self):
        a = RNG.rand(3, 4).astype(np.float32)
        b = RNG.rand(4, 5).astype(np.float32)
        out = _run({
            "mm": stf.matmul(stf.constant(a), stf.constant(b)),
            "mm_ta": stf.matmul(stf.constant(a.T), stf.constant(b),
                                transpose_a=True),
            "mm_tb": stf.matmul(stf.constant(a), stf.constant(b.T),
                                transpose_b=True),
        })
        np.testing.assert_allclose(out["mm"], a @ b, rtol=1e-5)
        np.testing.assert_allclose(out["mm_ta"], a @ b, rtol=1e-5)
        np.testing.assert_allclose(out["mm_tb"], a @ b, rtol=1e-5)

    def test_batch_matmul_einsum_tensordot(self):
        a = RNG.rand(2, 3, 4).astype(np.float32)
        b = RNG.rand(2, 4, 5).astype(np.float32)
        out = _run({
            "bmm": stf.matmul(stf.constant(a), stf.constant(b)),
            "ein": stf.einsum("bij,bjk->bik", stf.constant(a),
                              stf.constant(b)),
            "td": stf.tensordot(stf.constant(a[0]), stf.constant(b[0]),
                                axes=1),
        })
        np.testing.assert_allclose(out["bmm"], a @ b, rtol=1e-5)
        np.testing.assert_allclose(out["ein"], a @ b, rtol=1e-5)
        np.testing.assert_allclose(out["td"], a[0] @ b[0], rtol=1e-5)

    def test_matmul_gradient(self):
        a = stf.constant(RNG.rand(3, 4).astype(np.float32))
        b = stf.constant(RNG.rand(4, 2).astype(np.float32))
        y = stf.reduce_sum(stf.matmul(a, b))
        ga, gb = stf.gradients(y, [a, b])
        out = _run({"ga": ga, "gb": gb, "b": b, "a": a})
        np.testing.assert_allclose(out["ga"],
                                   np.tile(out["b"].sum(1), (3, 1)),
                                   rtol=1e-5)

    def test_gradient_checker(self):
        x = stf.placeholder(stf.float32, [2, 3], name="gx")
        y = stf.reduce_sum(stf.tanh(x) * stf.constant(
            RNG.rand(2, 3).astype(np.float32)))
        with stf.Session():
            err = stf.compute_gradient_error(x, [2, 3], y, [])
        assert err < 2e-2


class TestCasting:
    def test_cast_chain(self):
        x = stf.constant([1.7, -2.3], stf.float32)
        out = _run({
            "i": stf.cast(x, stf.int32),
            "b16": stf.cast(x, stf.bfloat16),
            "back": stf.cast(stf.cast(x, stf.float64), stf.float32),
        })
        assert out["i"].tolist() == [1, -2]
        assert out["back"].tolist() == list(np.float32([1.7, -2.3]))

    def test_saturate_cast(self):
        x = stf.constant([300.0, -300.0])
        assert _run(stf.saturate_cast(x, stf.int8)).tolist() == [127, -128]

    def test_range_linspace(self):
        out = _run({"r": stf.range(2, 10, 3),
                    "l": stf.linspace(0.0, 1.0, 5)})
        assert out["r"].tolist() == [2, 5, 8]
        np.testing.assert_allclose(out["l"], np.linspace(0, 1, 5))
