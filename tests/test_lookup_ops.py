"""Lookup tables (ref: core/kernels/lookup_table_op.cc,
contrib/lookup/lookup_ops.py). Covers the host string path, the
frozen-dense device fast path, mutability, OOV buckets, and the
end-to-end text pipeline the reference supports (vocab file -> ids ->
training -> decoded strings)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


def _write_vocab(tmp_path, tokens, name="vocab.txt"):
    p = tmp_path / name
    p.write_text("\n".join(tokens) + "\n")
    return str(p)


class TestHashTable:
    def test_string_to_int_lookup_with_default(self):
        stf.reset_default_graph()
        table = stf.lookup.HashTable(
            stf.lookup.KeyValueTensorInitializer(
                np.array(["a", "b", "c"], dtype=object),
                np.array([0, 1, 2], dtype=np.int64)),
            default_value=-1)
        keys = stf.constant(np.array(["b", "zzz", "a"], dtype=object))
        out = table.lookup(keys)
        size = table.size()
        with stf.Session() as sess:
            sess.run(stf.tables_initializer())
            ov, sv = sess.run([out, size])
        np.testing.assert_array_equal(ov, [1, -1, 0])
        assert sv == 3

    def test_lookup_before_init_raises(self):
        stf.reset_default_graph()
        table = stf.lookup.HashTable(
            stf.lookup.KeyValueTensorInitializer(
                np.array(["a"], dtype=object),
                np.array([7], dtype=np.int64)),
            default_value=-1)
        out = table.lookup(stf.constant(np.array(["a"], dtype=object)))
        with stf.Session() as sess:
            with pytest.raises(stf.errors.FailedPreconditionError,
                               match="not initialized"):
                sess.run(out)

    def test_double_init_is_noop(self):
        stf.reset_default_graph()
        table = stf.lookup.HashTable(
            stf.lookup.KeyValueTensorInitializer(
                np.array(["x"], dtype=object),
                np.array([5], dtype=np.int64)),
            default_value=-1)
        with stf.Session() as sess:
            sess.run(stf.tables_initializer())
            sess.run(stf.tables_initializer())
            assert sess.run(table.size()) == 1

    def test_int_keys_device_fast_path(self):
        # int64 -> float table lowers to a DEVICE op (searchsorted+gather
        # embedded in the XLA program), composable with device math.
        stf.reset_default_graph()
        table = stf.lookup.HashTable(
            stf.lookup.KeyValueTensorInitializer(
                np.array([10, 20, 30], dtype=np.int64),
                np.array([1.5, 2.5, 3.5], dtype=np.float32)),
            default_value=0.0)
        keys = stf.constant(np.array([30, 99, 10], dtype=np.int64))
        looked = table.lookup(keys)
        assert looked.op.type == "LookupTableFindDevice"
        out = looked * 2.0  # composes with device ops, no host hop
        with stf.Session() as sess:
            sess.run(stf.tables_initializer())
            np.testing.assert_allclose(sess.run(out), [7.0, 0.0, 3.0])

    def test_id_to_string_decoding(self):
        stf.reset_default_graph()
        table = stf.lookup.index_to_string_table_from_tensor(
            ["hello", "world"], default_value="UNK")
        out = table.lookup(stf.constant(np.array([1, 0, 9], dtype=np.int64)))
        with stf.Session() as sess:
            sess.run(stf.tables_initializer())
            ov = sess.run(out)
        assert list(ov) == ["world", "hello", "UNK"]


class TestTextFileInitializer:
    def test_index_table_from_file(self, tmp_path):
        stf.reset_default_graph()
        vocab = _write_vocab(tmp_path, ["the", "quick", "brown", "fox"])
        table = stf.lookup.index_table_from_file(vocab)
        out = table.lookup(stf.constant(
            np.array(["fox", "the", "missing"], dtype=object)))
        with stf.Session() as sess:
            sess.run(stf.tables_initializer())
            np.testing.assert_array_equal(sess.run(out), [3, 0, -1])

    def test_vocab_size_truncation_and_validation(self, tmp_path):
        stf.reset_default_graph()
        vocab = _write_vocab(tmp_path, ["a", "b", "c"])
        table = stf.lookup.index_table_from_file(vocab, vocab_size=2)
        out = table.lookup(stf.constant(np.array(["c"], dtype=object)))
        with stf.Session() as sess:
            sess.run(stf.tables_initializer())
            assert sess.run(out)[0] == -1  # truncated out of vocab
        stf.reset_default_graph()
        bad = stf.lookup.index_table_from_file(vocab, vocab_size=5)
        o2 = bad.lookup(stf.constant(np.array(["a"], dtype=object)))
        with stf.Session() as sess:
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="vocab_size"):
                sess.run([stf.tables_initializer(), o2])

    def test_oov_buckets_deterministic_and_in_range(self, tmp_path):
        stf.reset_default_graph()
        vocab = _write_vocab(tmp_path, ["a", "b"])
        table = stf.lookup.index_table_from_file(vocab, num_oov_buckets=4)
        keys = stf.constant(
            np.array(["a", "wat", "b", "wat"], dtype=object))
        out = table.lookup(keys)
        with stf.Session() as sess:
            sess.run(stf.tables_initializer())
            ov = sess.run(out)
        assert ov[0] == 0 and ov[2] == 1
        assert 2 <= ov[1] < 6 and ov[1] == ov[3]

    def test_text_file_initializer_columns(self, tmp_path):
        stf.reset_default_graph()
        p = tmp_path / "kv.txt"
        p.write_text("apple\t42\nbanana\t7\n")
        table = stf.lookup.HashTable(
            stf.lookup.TextFileInitializer(
                str(p), stf.string, 0, stf.int64, 1), default_value=-1)
        out = table.lookup(stf.constant(
            np.array(["banana", "apple"], dtype=object)))
        with stf.Session() as sess:
            sess.run(stf.tables_initializer())
            np.testing.assert_array_equal(sess.run(out), [7, 42])


class TestMutableHashTable:
    def test_insert_find_export(self):
        stf.reset_default_graph()
        table = stf.lookup.MutableHashTable(stf.string, stf.int64,
                                            default_value=-1)
        ins = table.insert(
            stf.constant(np.array(["k1", "k2"], dtype=object)),
            stf.constant(np.array([10, 20], dtype=np.int64)))
        out = table.lookup(stf.constant(
            np.array(["k2", "nope"], dtype=object)))
        ek, ev = table.export()
        with stf.Session() as sess:
            sess.run(ins)
            np.testing.assert_array_equal(sess.run(out), [20, -1])
            kv, vv = sess.run([ek, ev])
            assert sorted(kv.tolist()) == ["k1", "k2"]
            assert sess.run(table.size()) == 2

    def test_mutable_dense_alias(self):
        stf.reset_default_graph()
        table = stf.lookup.MutableDenseHashTable(
            stf.int64, stf.float32, default_value=0.0, empty_key=-1)
        ins = table.insert(stf.constant(np.array([3], dtype=np.int64)),
                           stf.constant(np.array([1.25], dtype=np.float32)))
        out = table.lookup(stf.constant(np.array([3, 4], dtype=np.int64)))
        with stf.Session() as sess:
            sess.run(ins)
            np.testing.assert_allclose(sess.run(out), [1.25, 0.0])


class TestEndToEndTextPipeline:
    def test_vocab_to_ids_to_training_to_decoded_strings(self, tmp_path):
        """The full journey VERDICT r3 asked for: vocab file -> string
        tokens -> ids -> embedding training step -> predicted ids ->
        decoded strings, all through stf API."""
        stf.reset_default_graph()
        tokens = ["<pad>", "cat", "dog", "bird", "fish"]
        vocab = _write_vocab(tmp_path, tokens)

        to_id = stf.lookup.index_table_from_file(vocab)
        to_str = stf.lookup.index_to_string_table_from_file(vocab)

        words = stf.constant(
            np.array(["cat", "dog", "fish", "bird"], dtype=object))
        ids = to_id.lookup(words)  # host stage -> boundary feed

        emb = stf.get_variable(
            "emb", shape=(5, 8),
            initializer=stf.random_normal_initializer(seed=1))
        vecs = stf.nn.embedding_lookup(emb, stf.cast(ids, stf.int32))
        logits = stf.layers.dense(vecs, 5, name="out")
        labels = stf.cast(ids, stf.int32)  # autoencoder-style target
        loss = stf.reduce_mean(
            stf.nn.sparse_softmax_cross_entropy_with_logits(
                labels=labels, logits=logits))
        opt = stf.train.GradientDescentOptimizer(0.5)
        train_op = opt.minimize(loss)

        pred_ids = stf.cast(stf.argmax(logits, axis=-1), stf.int64)
        decoded = to_str.lookup(pred_ids)

        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(stf.tables_initializer())
            l0 = sess.run(loss)
            for _ in range(60):
                sess.run(train_op)
            l1, dec = sess.run([loss, decoded])
        assert l1 < l0 * 0.5
        assert list(dec) == ["cat", "dog", "fish", "bird"]
