"""Decode-time tensor parallelism (ISSUE 20): head-sharded KV caches on
a tp mesh axis — token-exactness of greedy/sampling/speculative decode
at tp=4/tp=8 vs the single-device engine through a checkpoint restore,
slot-churn join/leave parity, per-device cache-byte footprint (<= 1/4
of replicated at tp=8), device_memory_budget_bytes admission (refused
replicated, feasible sharded), collective-free head-sharded gathers,
predicted-vs-harvested collective bytes for the column-parallel logits
route, the decode-TP branches of ``lint/serving-decode-cache``, the
``choose_decode_tp`` autoshard objective, and the new
``/stf/serving/tp_*`` metrics."""

import os
import tempfile

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import analysis, parallel, serving
from simple_tensorflow_tpu.analysis.autoshard import choose_decode_tp
from simple_tensorflow_tpu.framework import errors
from simple_tensorflow_tpu.models import causal_lm as clm
from simple_tensorflow_tpu.models import transformer as tr
from simple_tensorflow_tpu.ops import kv_cache_ops as kvc
from simple_tensorflow_tpu.parallel import PartitionSpec as P
from simple_tensorflow_tpu.platform import monitoring

SRC_LEN, L = 8, 8


def _cfg():
    # TransformerConfig.tiny() has num_heads=2 — not divisible by 4/8.
    return tr.TransformerConfig(vocab_size=64, d_model=32, num_heads=8,
                                d_ff=64, num_layers=2, dropout=0.0,
                                max_len=32)


@pytest.fixture(autouse=True)
def _fresh_graph():
    stf.reset_default_graph()
    yield
    stf.reset_default_graph()


def _save_ckpt(model, tmp):
    ckpt = os.path.join(tmp, "model")
    with model.graph.as_default():
        saver = stf.train.Saver()
        saver.save(model.session, ckpt)
    return ckpt


def _run_engine(model, prompts, draft=None, max_new_tokens=6,
                num_slots=4, max_decode_len=L, name="eng"):
    pol = serving.DecodePolicy(num_slots=num_slots,
                               max_decode_len=max_decode_len,
                               max_new_tokens=max_new_tokens)
    with serving.GenerativeEngine(name, model, pol, draft=draft) as eng:
        futs = [eng.generate(p) for p in prompts]
        out = [f.result(timeout=120) for f in futs]
        stats = eng.statusz_info()
    return out, stats


def _model(cfg, tp=None, **kw):
    mesh = parallel.Mesh({"tp": tp}) if tp else None
    kw.setdefault("aot_warmup", False)
    return tr.TransformerGenerativeModel(
        cfg, SRC_LEN, num_slots=kw.pop("num_slots", 4),
        max_decode_len=L, mesh=mesh, tp=tp, **kw)


# ---------------------------------------------------------------------------
# choose_decode_tp: autoshard serving/decode purpose
# ---------------------------------------------------------------------------

class TestChooseDecodeTp:
    def test_free_choice_shards_all_heads(self):
        ch = choose_decode_tp(num_heads=8, cache_bytes=8 << 20)
        assert ch.degree == 8 and ch.feasible
        assert ch.per_device_cache_bytes == (8 << 20) // 8
        # every divisor of num_heads up to the device count is priced
        assert sorted(r["degree"] for r in ch.candidates) == [1, 2, 4, 8]

    def test_unsharded_bytes_stay_per_device(self):
        ch = choose_decode_tp(num_heads=8, cache_bytes=(8 << 20) + 1000,
                              unsharded_bytes=1000)
        assert ch.per_device_cache_bytes == 1000 + (8 << 20) // ch.degree

    def test_budget_selects_feasible_degree(self):
        budget = (8 << 20) // 4 + 1024   # fits tp>=4, not tp<4
        ch = choose_decode_tp(num_heads=8, cache_bytes=8 << 20,
                              budget_bytes=budget)
        assert ch.feasible and ch.degree >= 4
        infeasible = [r for r in ch.candidates if not r["feasible"]]
        assert {r["degree"] for r in infeasible} == {1, 2}

    def test_budget_infeasible_raises(self):
        with pytest.raises(ValueError, match="device_memory_budget"):
            choose_decode_tp(num_heads=8, cache_bytes=8 << 20,
                             budget_bytes=10)

    def test_mesh_pins_degree(self):
        mesh = parallel.Mesh({"tp": 4})
        ch = choose_decode_tp(num_heads=8, cache_bytes=1 << 20, mesh=mesh)
        assert ch.degree == 4
        assert [r["degree"] for r in ch.candidates] == [4]

    def test_mesh_degree_must_divide_heads(self):
        mesh = parallel.Mesh({"tp": 8})
        with pytest.raises(ValueError, match="divide"):
            choose_decode_tp(num_heads=6, cache_bytes=1 << 20, mesh=mesh)


# ---------------------------------------------------------------------------
# Token exactness: tp engine == single-device engine (greedy)
# ---------------------------------------------------------------------------

class TestTpTokenExactGreedy:
    def _base_outputs(self, cfg, tmp, n_prompts=4, **engine_kw):
        base = _model(cfg, init_fresh=True, seed=7)
        ckpt = _save_ckpt(base, tmp)
        batch = tr.synthetic_wmt_batch(n_prompts, SRC_LEN, L,
                                       vocab_size=cfg.vocab_size)
        prompts = [batch["src_ids"][i] for i in range(n_prompts)]
        base_out, _ = _run_engine(base, prompts, name="tp_base",
                                  **engine_kw)
        base.close()
        return ckpt, prompts, base_out

    @pytest.mark.parametrize("tp", [4, 8])
    def test_greedy_engine_exact(self, tp):
        cfg = _cfg()
        tmp = tempfile.mkdtemp(prefix=f"stf_tp{tp}_")
        ckpt, prompts, base_out = self._base_outputs(cfg, tmp)
        m = _model(cfg, tp=tp, checkpoint=ckpt)
        assert m.tp_info()["tp_degree"] == tp
        tp_out, _ = _run_engine(m, prompts, name=f"tp{tp}_eng")
        m.close()
        for b, s in zip(base_out, tp_out):
            assert list(b["tokens"]) == list(s["tokens"])
            assert b["outcome"] == s["outcome"]

    def test_slot_churn_join_leave_parity(self):
        # more prompts than slots: sequences join/leave mid-flight and
        # every slot is recycled across the sharded caches
        cfg = _cfg()
        tmp = tempfile.mkdtemp(prefix="stf_tp_churn_")
        ckpt, prompts, base_out = self._base_outputs(
            cfg, tmp, n_prompts=6, num_slots=2)
        m = _model(cfg, tp=4, checkpoint=ckpt, num_slots=2)
        tp_out, _ = _run_engine(m, prompts, num_slots=2,
                                name="tp_churn")
        m.close()
        for b, s in zip(base_out, tp_out):
            assert list(b["tokens"]) == list(s["tokens"])


# ---------------------------------------------------------------------------
# Token exactness: sampling + speculative under tp
# ---------------------------------------------------------------------------

class TestTpSamplingSpeculative:
    def _decode_seq(self, model, src, steps):
        model.prefill(src[None, :], [0])
        tok = np.array([model.eos_id], np.int32)
        out = []
        for t in range(steps):
            nxt, lp, _b = model.decode(tok, [t], [0])
            out.append(int(nxt[0]))
            tok = nxt
        return out

    def test_sampling_exact_tp4(self):
        cfg = _cfg()
        tmp = tempfile.mkdtemp(prefix="stf_tp_samp_")
        sampling = {"temperature": 0.8, "top_k": 8, "top_p": 0.95,
                    "seed": 123}
        base = _model(cfg, init_fresh=True, seed=11, sampling=sampling)
        ckpt = _save_ckpt(base, tmp)
        batch = tr.synthetic_wmt_batch(1, SRC_LEN, L,
                                       vocab_size=cfg.vocab_size)
        src = batch["src_ids"][0]
        want = self._decode_seq(base, src, 5)
        base.close()
        m = _model(cfg, tp=4, checkpoint=ckpt, seed=11,
                   sampling=sampling)
        got = self._decode_seq(m, src, 5)
        m.close()
        assert want == got

    def test_speculative_exact_tp4(self):
        # tp target + single-device draft: the committed stream must
        # still equal plain single-device cached decode bit-exactly
        cfg = _cfg()
        tmp = tempfile.mkdtemp(prefix="stf_tp_spec_")
        base = _model(cfg, init_fresh=True, seed=7)
        ckpt = _save_ckpt(base, tmp)
        batch = tr.synthetic_wmt_batch(3, SRC_LEN, L,
                                       vocab_size=cfg.vocab_size)
        prompts = [batch["src_ids"][i] for i in range(3)]
        base_out, _ = _run_engine(base, prompts, name="tpspec_base")
        base.close()
        target = _model(cfg, tp=4, checkpoint=ckpt, speculative_k=3)
        draft = _model(cfg, checkpoint=ckpt, draft_steps=2)
        spec_out, stats = _run_engine(target, prompts, draft=draft,
                                      name="tpspec_eng")
        target.close()
        draft.close()
        for b, s in zip(base_out, spec_out):
            assert list(b["tokens"]) == list(s["tokens"])
        assert stats["speculative"]["proposed_tokens"] > 0


# ---------------------------------------------------------------------------
# Paged causal-LM path under tp
# ---------------------------------------------------------------------------

class TestCausalLMTp:
    def _mk(self, cfg, tp=None, **kw):
        mesh = parallel.Mesh({"tp": tp}) if tp else None
        return clm.CausalLMGenerativeModel(
            cfg, page_len=4, pages_per_seq=4, num_pages=16, max_live=2,
            aot_warmup=False, mesh=mesh, tp=tp, **kw)

    def test_paged_decode_exact_tp4(self):
        cfg = _cfg()
        tmp = tempfile.mkdtemp(prefix="stf_tp_clm_")
        base = self._mk(cfg, init_fresh=True)
        ckpt = _save_ckpt(base, tmp)

        def run(model):
            chunk = (np.arange(4, dtype=np.int32)[None, :] % 7) + 1
            table = np.array([[0, 1, 2, 3]], np.int32)
            model.prefill_chunk(chunk, [0], table, [0])
            model.copy_page(5, 0)
            tok = np.array([cfg.eos_id], np.int32)
            out = []
            for t in range(4, 8):
                nxt, lp, _b = model.decode(tok, [t], table)
                out.append(int(nxt[0]))
                tok = nxt
            return out

        want = run(base)
        base.close()
        m = self._mk(cfg, tp=4, checkpoint=ckpt)
        assert m.tp_info()["tp_degree"] == 4
        got = run(m)
        m.close()
        assert want == got


# ---------------------------------------------------------------------------
# Cache footprint + /stf/serving/tp_* metrics
# ---------------------------------------------------------------------------

class TestTpCacheFootprintAndMetrics:
    def test_per_device_cache_bytes_tp8(self):
        cfg = _cfg()
        m = _model(cfg, tp=8, init_fresh=True)
        info = m.tp_info()
        # acceptance: per-device cache bytes <= 1/4 of replicated at tp=8
        assert info["cache_bytes_per_device"] * 4 \
            <= info["cache_bytes_replicated"]
        store = m.session._variable_store
        sharded = 0
        for name, arr in store.values.items():
            if "_kv/" not in name or "src_bias" in name:
                continue
            assert not arr.is_fully_replicated, name
            shard = arr.sharding.shard_shape(arr.shape)
            assert int(np.prod(shard)) * 8 == int(np.prod(arr.shape)), \
                name
            sharded += 1
        assert sharded >= 2 * cfg.num_layers  # k+v per decoder layer
        m.close()

    def test_tp_metrics_exported(self):
        cfg = _cfg()
        m = _model(cfg, tp=4, init_fresh=True)
        info = m.tp_info()
        batch = tr.synthetic_wmt_batch(1, SRC_LEN, L,
                                       vocab_size=cfg.vocab_size)
        _run_engine(m, [batch["src_ids"][0]], max_new_tokens=2,
                    name="tp_metrics_eng")
        m.close()
        for metric, want in [
                ("/stf/serving/tp_degree", 4),
                ("/stf/serving/tp_cache_bytes_per_device",
                 info["cache_bytes_per_device"]),
                ("/stf/serving/tp_collective_bytes_per_token",
                 info["per_token_collective_bytes"])]:
            got = monitoring.get_metric(metric)
            assert got is not None, metric
            cells = got.snapshot()["cells"]
            assert cells.get("tp_metrics_eng") == want, (metric, cells)


# ---------------------------------------------------------------------------
# device_memory_budget_bytes: refused replicated, feasible sharded
# ---------------------------------------------------------------------------

class TestTpBudgetAdmission:
    def test_budget_refuses_tp1_admits_tp8(self):
        from simple_tensorflow_tpu.telemetry import memory as mem

        cfg = _cfg()
        tmp = tempfile.mkdtemp(prefix="stf_tp_budget_")
        base = _model(cfg, init_fresh=True, seed=7)
        ckpt = _save_ckpt(base, tmp)
        base.close()
        src = (np.arange(SRC_LEN, dtype=np.int32)[None, :]
               % cfg.vocab_size)

        def probe(tp, budget=None):
            conf = (stf.ConfigProto(device_memory_budget_bytes=budget)
                    if budget else None)
            m = _model(cfg, tp=tp, checkpoint=ckpt, config=conf)
            try:
                m.prefill(src, [0])
                tok = np.array([cfg.eos_id], np.int32)
                for t in range(3):
                    tok, _, _b = m.decode(tok, [t], [0])
                return mem.get_ledger().total_bytes()
            finally:
                m.close()

        base_live = mem.get_ledger().total_bytes()
        d1 = probe(None) - base_live
        d8 = probe(8) - base_live
        # the sharded footprint must actually be smaller for the budget
        # window to exist (weights replicate; caches shard 8x)
        assert d8 < d1
        budget = mem.get_ledger().total_bytes() + (d1 + d8) // 2
        assert probe(8, budget=budget) > 0    # admitted + served
        with pytest.raises(errors.ResourceExhaustedError,
                           match="budget"):
            probe(None, budget=budget)


# ---------------------------------------------------------------------------
# Collectives: gathers free, logits all-gather priced within 25%
# ---------------------------------------------------------------------------

def _traced_run(sess, fetches, feed):
    opts = stf.RunOptions(trace_level=stf.RunOptions.SOFTWARE_TRACE)
    md = stf.RunMetadata()
    vals = sess.run(fetches, feed_dict=feed, options=opts,
                    run_metadata=md)
    steps = [s for s in sess._cache.values()
             if s.join_sharding() is not None]
    assert steps, "no plan carried a sharding report"
    return vals, md, steps[-1]


class TestTpCollectives:
    def test_head_sharded_gather_collective_free(self):
        # satellite bugfix pin: slot gathers over a head-sharded cache
        # are shard-local — ZERO predicted collective bytes
        mesh = parallel.Mesh({"tp": 4})
        with mesh:
            c = kvc.kv_cache("tpc_kv/l0_k", num_slots=4, max_len=L,
                             inner_shape=(8, 4), dtype=stf.float32,
                             sharding="tp:heads")
            alloc = c.alloc()
            slots = stf.placeholder(stf.int32, [2], "slots")
            g = c.gather(slots)
            with stf.Session() as sess:
                sess.run(alloc.op)
                _, _md, step = _traced_run(
                    sess, g, {slots: np.array([0, 1], np.int32)})
                rep = step.sharding_report
                assert rep.total_collective_bytes() == 0
                spec = rep.spec_of(g)
                assert spec is not None and len(spec) > kvc.HEAD_DIM
                entry = spec[kvc.HEAD_DIM]
                axes = (tuple(entry) if isinstance(entry, (tuple, list))
                        else (entry,))
                assert "tp" in axes

    def test_logits_allgather_predicted_vs_harvested(self):
        # the per-token decode collective: column-parallel projection +
        # one all-gather of the vocab-sharded logits row
        mesh = parallel.Mesh({"tp": 4})
        rng = np.random.RandomState(0)
        with mesh:
            x = stf.placeholder(stf.float32, [4, 32], "x")
            w = stf.get_variable(
                "logits_w", [32, 64],
                initializer=stf.zeros_initializer())
            parallel.shard_variable(w, None, "tp")
            # pin the vocab-sharded intermediate (the decode program's
            # layout by construction) so XLA can't gather the weight
            # instead of the logits row
            y = parallel.with_sharding_constraint(
                stf.matmul(x, w), None, "tp")
            out = parallel.with_sharding_constraint(y, None, None)
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                _, md, step = _traced_run(
                    sess, out,
                    {x: rng.randn(4, 32).astype(np.float32)})
                rep = step.sharding_report
                predicted = rep.total_collective_bytes()
                assert predicted > 0
                harvested = md.cost_graph.get(
                    "collective_bytes", {}).get("total")
                if harvested:
                    assert predicted == pytest.approx(harvested,
                                                      rel=0.25)

    def test_model_prices_decode_collectives(self):
        # decode_tp_collective_bytes is what tp_info/bench report:
        # embed all-reduce + context all-gathers + logits all-gather
        cfg = _cfg()
        got = tr.decode_tp_collective_bytes(cfg, 4, stf.float32,
                                            cross=True)
        csize = 4
        want = (cfg.d_model * csize                      # embed
                + 2 * cfg.num_layers * cfg.d_model * csize  # contexts
                + cfg.vocab_size * 4)                    # logits row
        assert got == want
        assert tr.decode_tp_collective_bytes(cfg, 1, stf.float32) == 0


# ---------------------------------------------------------------------------
# lint/serving-decode-cache: decode-TP branches
# ---------------------------------------------------------------------------

class TestServingDecodeCacheLintTp:
    RULES = ["lint/serving-decode-cache"]

    def _lint(self, fetches):
        return analysis.lint_graph(fetches=fetches, purpose="serving",
                                   rules=self.RULES)

    def test_page_copy_sharding_mismatch_flagged(self):
        c = kvc.kv_cache("lint_kv/l0_k", num_slots=4, max_len=4,
                         inner_shape=(8, 4), dtype=stf.float32,
                         sharding="tp:heads", paged=True)
        alloc = c.alloc()
        cp = c.copy_pages(stf.constant(np.array([2], np.int32)),
                          stf.constant(np.array([1], np.int32)))
        # forge a drifted declaration on the copy (e.g. a copy built
        # from a stale handle after a resharding deploy)
        cp.op.attrs[kvc.SHARDING_ATTR] = "tp"
        diags = self._lint([alloc.op, cp.op])
        assert any("re-commit the store entry" in d.message
                   for d in diags), [d.message for d in diags]

    def test_page_copy_matching_sharding_clean(self):
        c = kvc.kv_cache("lint_kv/l0_k", num_slots=4, max_len=4,
                         inner_shape=(8, 4), dtype=stf.float32,
                         sharding="tp:heads", paged=True)
        alloc = c.alloc()
        cp = c.copy_pages(stf.constant(np.array([2], np.int32)),
                          stf.constant(np.array([1], np.int32)))
        diags = self._lint([alloc.op, cp.op])
        assert not any("re-commit" in d.message for d in diags), \
            [d.message for d in diags]

    def test_head_replicated_gather_flagged(self):
        c = kvc.kv_cache("lint_kv/l0_k", num_slots=4, max_len=4,
                         inner_shape=(8, 4), dtype=stf.float32,
                         sharding="tp:heads")
        alloc = c.alloc()
        slots = stf.placeholder(stf.int32, [2], "slots")
        g = c.gather(slots)
        bad = parallel.with_sharding_constraint(g, None, None, None,
                                                None)
        diags = self._lint([alloc.op, bad])
        assert any("all-gathers the full head dim" in d.message
                   for d in diags), [d.message for d in diags]

    def test_head_sharded_gather_clean(self):
        c = kvc.kv_cache("lint_kv/l0_k", num_slots=4, max_len=4,
                         inner_shape=(8, 4), dtype=stf.float32,
                         sharding="tp:heads")
        alloc = c.alloc()
        slots = stf.placeholder(stf.int32, [2], "slots")
        g = c.gather(slots)
        ok = parallel.with_sharding_constraint(g, None, None, "tp",
                                               None)
        diags = self._lint([alloc.op, ok])
        assert not any("all-gathers" in d.message for d in diags), \
            [d.message for d in diags]
