"""StagingArea / Barrier / SparseConditionalAccumulator / RecordInput
(ref: python/ops/data_flow_ops.py:1384, :805, :1230, :1633). API-parity
tests mirroring the reference's documented semantics."""

import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


class TestStagingArea:
    def test_put_get_fifo_exactly_once(self):
        stf.reset_default_graph()
        area = stf.StagingArea([stf.float32, stf.int32],
                               shapes=[(2,), ()])
        x = stf.placeholder(stf.float32, [2])
        n = stf.placeholder(stf.int32, [])
        put = area.put([x, n])
        got = area.get()
        out = got[0] * stf.cast(got[1], stf.float32)
        with stf.Session() as sess:
            sess.run(put, {x: np.array([1., 2.], np.float32), n: 10})
            sess.run(put, {x: np.array([3., 4.], np.float32), n: 100})
            np.testing.assert_allclose(sess.run(out), [10., 20.])
            np.testing.assert_allclose(sess.run(out), [300., 400.])
            assert sess.run(area.size()) == 0

    def test_dict_mode_names(self):
        stf.reset_default_graph()
        area = stf.StagingArea([stf.float32, stf.float32],
                               names=["a", "b"])
        put = area.put({"a": stf.constant(1.0), "b": stf.constant(2.0)})
        got = area.get()
        assert sorted(got.keys()) == ["a", "b"]
        with stf.Session() as sess:
            sess.run(put)
            vals = sess.run(got)
        assert vals["a"] == 1.0 and vals["b"] == 2.0

    def test_put_validation(self):
        stf.reset_default_graph()
        area = stf.StagingArea([stf.float32], shapes=[(2,)])
        with pytest.raises(ValueError, match="number of inputs"):
            area.put([stf.constant(1.0), stf.constant(2.0)])
        with pytest.raises(ValueError, match="[Ss]hape"):
            area.put([stf.constant(np.zeros((3,), np.float32))])
        with pytest.raises(ValueError, match="dictionary"):
            area.put({"a": stf.constant(1.0)})

    def test_get_stages_to_device(self):
        # the staged component should already be a device array when the
        # step consumes it (jax.Array staged at put time)
        stf.reset_default_graph()
        area = stf.StagingArea([stf.float32], shapes=[(4,)])
        put = area.put([stf.constant(np.arange(4, dtype=np.float32))])
        with stf.Session() as sess:
            sess.run(put)
        staged = area._buf.queue[0][0]
        assert hasattr(staged, "sharding")  # jax.Array, not numpy


class TestBarrier:
    def test_reference_docstring_scenario(self):
        # the exact insert/take sequence documented at ref
        # data_flow_ops.py:820-850
        stf.reset_default_graph()
        b = stf.Barrier((stf.string, stf.int32), shapes=((), ()))
        k = stf.placeholder(stf.string, [None])
        vs = stf.placeholder(stf.string, [None])
        vi = stf.placeholder(stf.int32, [None])
        ins0 = b.insert_many(0, k, vs)
        ins1 = b.insert_many(1, k, vi)
        idx_t, keys_t, (val0_t, val1_t) = b.take_many(2)
        with stf.Session() as sess:
            o = np.array
            sess.run(ins0, {k: o(["k1", "k2"], object),
                            vs: o(["a", "b"], object)})
            sess.run(ins1, {k: o(["k1"], object), vi: o([1], np.int32)})
            sess.run(ins0, {k: o(["k3"], object), vs: o(["c"], object)})
            sess.run(ins1, {k: o(["k3"], object), vi: o([3], np.int32)})
            sess.run(ins1, {k: o(["k2"], object), vi: o([2], np.int32)})
            assert sess.run(b.ready_size()) == 3
            iv, kv, v0, v1 = sess.run([idx_t, keys_t, val0_t, val1_t])
        # k1,k2 first-inserted together (indices -2**63, -2**63+1); k3
        # completed earlier but was first-inserted later -> stays behind
        assert sorted(kv.tolist()) == ["k1", "k2"]
        assert set(iv.tolist()) == {-2**63, -2**63 + 1}
        got = dict(zip(kv.tolist(), zip(v0.tolist(), v1.tolist())))
        assert got["k1"] == ("a", 1) and got["k2"] == ("b", 2)

    def test_double_insert_same_component_raises(self):
        stf.reset_default_graph()
        b = stf.Barrier((stf.int32,), shapes=((),))
        k = stf.constant(np.array(["x"], object))
        v = stf.constant(np.array([1], np.int32))
        ins = b.insert_many(0, k, v)
        with stf.Session() as sess:
            sess.run(ins)
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="already set"):
                sess.run(b.insert_many(0, k, v))

    def test_close_semantics(self):
        stf.reset_default_graph()
        b = stf.Barrier((stf.string, stf.int32), shapes=((), ()))
        o = np.array
        ins0 = b.insert_many(0, stf.constant(o(["k1"], object)),
                             stf.constant(o(["a"], object)))
        close = b.close()
        # completing an existing key after close is allowed (ref contract)
        ins1 = b.insert_many(1, stf.constant(o(["k1"], object)),
                             stf.constant(o([5], np.int32)))
        # a new key after close fails
        new_key = b.insert_many(0, stf.constant(o(["k2"], object)),
                                stf.constant(o(["b"], object)))
        idx_t, keys_t, vals = b.take_many(1)
        with stf.Session() as sess:
            sess.run(ins0)
            sess.run(close)
            sess.run(ins1)
            with pytest.raises(stf.errors.CancelledError, match="closed"):
                sess.run(new_key)
            _, kv, v0, v1 = sess.run([idx_t, keys_t, vals[0], vals[1]])
            assert kv.tolist() == ["k1"] and v1.tolist() == [5]
            assert sess.run(b.incomplete_size()) == 0
            # closed + insufficient elements -> OutOfRange (ref contract)
            i2, k2, _ = b.take_many(1)
            with pytest.raises(stf.errors.OutOfRangeError):
                sess.run(k2)

    def test_allow_small_batch_after_close(self):
        stf.reset_default_graph()
        b = stf.Barrier((stf.int32,), shapes=((),))
        o = np.array
        ins = b.insert_many(0, stf.constant(o(["a", "b"], object)),
                            stf.constant(o([1, 2], np.int32)))
        idx_t, keys_t, (v_t,) = b.take_many(5, allow_small_batch=True)
        with stf.Session() as sess:
            sess.run(ins)
            sess.run(b.close())
            _, kv, vv = sess.run([idx_t, keys_t, v_t])
        assert sorted(kv.tolist()) == ["a", "b"]
        assert sorted(vv.tolist()) == [1, 2]


class TestConditionalAccumulator:
    def test_symbolic_apply_and_average(self):
        # the graph-op contract: apply_grad takes a SYMBOLIC tensor and
        # returns an op; take_grad returns a tensor (ref
        # python/ops/data_flow_ops.py:1384)
        stf.reset_default_graph()
        acc = stf.ConditionalAccumulator(stf.float32, shape=[2])
        g = stf.placeholder(stf.float32, [2])
        apply_op = acc.apply_grad(g, local_step=0)
        take = acc.take_grad(3)
        n = acc.num_accumulated()
        with stf.Session() as sess:
            for v in (1.0, 2.0, 6.0):
                sess.run(apply_op, feed_dict={g: [v, 2 * v]})
            assert int(np.asarray(sess.run(n))) == 3
            avg = np.asarray(sess.run(take))
            np.testing.assert_allclose(avg, [3.0, 6.0])
            assert int(np.asarray(sess.run(n))) == 0

    def test_computed_gradient_accumulates(self):
        # the SyncReplicas shape: accumulate tf.gradients output
        stf.reset_default_graph()
        acc = stf.ConditionalAccumulator(stf.float32, shape=[2])
        v = stf.Variable(np.array([1.0, 2.0], np.float32))
        (grad,) = stf.gradients(stf.reduce_sum(stf.square(v)), [v])
        apply_op = acc.apply_grad(grad, local_step=0)
        take = acc.take_grad(2)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(apply_op)
            sess.run(apply_op)
            np.testing.assert_allclose(np.asarray(sess.run(take)),
                                       [2.0, 4.0])

    def test_unknown_shape_fixed_by_first_gradient(self):
        # shape=None: the first applied gradient fixes the shape; a
        # mismatched later gradient must error, never numpy-broadcast
        stf.reset_default_graph()
        acc = stf.ConditionalAccumulator(stf.float32)  # shape unknown
        g21 = stf.placeholder(stf.float32, [2, 1])
        g12 = stf.placeholder(stf.float32, [1, 2])
        a21 = acc.apply_grad(g21, local_step=0)
        a12 = acc.apply_grad(g12, local_step=0)
        with stf.Session() as sess:
            sess.run(a21, feed_dict={g21: [[1.0], [2.0]]})
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="incompatible"):
                sess.run(a12, feed_dict={g12: [[3.0, 4.0]]})

    def test_stale_gradients_dropped_and_take_blocks(self):
        import threading
        import time as _time

        stf.reset_default_graph()
        acc = stf.ConditionalAccumulator(stf.float32, shape=[])
        g = stf.placeholder(stf.float32, [])
        step_ph = stf.placeholder(stf.int32, [])
        apply_op = acc.apply_grad(g, local_step=step_ph)
        take = acc.take_grad(2)
        set_step = acc.set_global_step(1)
        results = []
        with stf.Session() as sess:
            sess.run(set_step)  # advance the accumulator's time step
            # stale (local_step 0 < global step 1): dropped silently
            sess.run(apply_op, feed_dict={g: 99.0, step_ph: 0})
            t = threading.Thread(target=lambda: results.append(
                np.asarray(sess.run(take))))
            t.start()
            _time.sleep(0.15)
            assert t.is_alive()  # blocking until 2 fresh grads arrive
            sess.run(apply_op, feed_dict={g: 4.0, step_ph: 1})
            sess.run(apply_op, feed_dict={g: 6.0, step_ph: 1})
            t.join(timeout=10)
            assert not t.is_alive()
        np.testing.assert_allclose(results[0], 5.0)


class TestSparseConditionalAccumulator:
    def test_accumulate_average_and_reset(self):
        stf.reset_default_graph()
        acc = stf.SparseConditionalAccumulator(stf.float32, shape=(4, 2))
        apply1 = acc.apply_grad(
            stf.constant(np.array([0, 2], np.int64)),
            stf.constant(np.array([[1., 1.], [2., 2.]], np.float32)),
            grad_shape=stf.constant(np.array([4, 2], np.int64)))
        apply2 = acc.apply_grad(
            stf.constant(np.array([2, 3], np.int64)),
            stf.constant(np.array([[4., 4.], [6., 6.]], np.float32)),
            grad_shape=stf.constant(np.array([4, 2], np.int64)))
        i_t, v_t, s_t = acc.take_grad(2)
        n_t = acc.num_accumulated()
        with stf.Session() as sess:
            sess.run(apply1)
            sess.run(apply2)
            assert sess.run(n_t) == 2
            iv, vv, sv = sess.run([i_t, v_t, s_t])
            assert sess.run(n_t) == 0  # reset after take
        np.testing.assert_array_equal(iv, [0, 2, 3])
        # per-row averaging (ref DivideAccumGradByCounter): row0 appears
        # in 1 gradient -> 1/1; row2 in 2 -> (2+4)/2; row3 in 1 -> 6/1
        np.testing.assert_allclose(vv, [[1., 1.], [3., 3.], [6., 6.]])
        np.testing.assert_array_equal(sv, [4, 2])

    def test_per_row_averaging(self):
        # rows present in only SOME gradients average over the count of
        # gradients containing that row (ref DivideAccumGradByCounter),
        # not the total number taken
        stf.reset_default_graph()
        acc = stf.SparseConditionalAccumulator(stf.float32)
        a1 = acc.apply_grad(stf.constant(np.array([0], np.int64)),
                            stf.constant(np.array([[6.]], np.float32)))
        a2 = acc.apply_grad(stf.constant(np.array([1], np.int64)),
                            stf.constant(np.array([[8.]], np.float32)))
        i_t, v_t, _ = acc.take_grad(2)
        with stf.Session() as sess:
            sess.run(a1)
            sess.run(a2)
            iv, vv = sess.run([i_t, v_t])
        np.testing.assert_array_equal(iv, [0, 1])
        np.testing.assert_allclose(vv, [[6.], [8.]])  # /1 each, not /2

    def test_partial_shape_accumulator(self):
        stf.reset_default_graph()
        acc = stf.SparseConditionalAccumulator(stf.float32,
                                               shape=(None, 2))
        ap = acc.apply_grad(
            stf.constant(np.array([1], np.int64)),
            stf.constant(np.array([[1., 2.]], np.float32)),
            grad_shape=stf.constant(np.array([5, 2], np.int64)))
        i_t, v_t, s_t = acc.take_grad(1)
        with stf.Session() as sess:
            sess.run(ap)
            sv = sess.run(s_t)
        np.testing.assert_array_equal(sv, [5, 2])

    def test_stale_gradients_dropped(self):
        stf.reset_default_graph()
        acc = stf.SparseConditionalAccumulator(stf.float32)
        fresh = acc.apply_grad(stf.constant(np.array([0], np.int64)),
                               stf.constant(np.array([[1.]], np.float32)),
                               local_step=1)
        stale = acc.apply_grad(stf.constant(np.array([0], np.int64)),
                               stf.constant(np.array([[9.]], np.float32)),
                               local_step=0)
        setstep = acc.set_global_step(1)
        n_t = acc.num_accumulated()
        with stf.Session() as sess:
            sess.run(setstep)
            sess.run(stale)   # local_step 0 < global 1: dropped
            assert sess.run(n_t) == 0
            sess.run(fresh)
            assert sess.run(n_t) == 1

    def test_indexed_slices_round_trip(self):
        stf.reset_default_graph()
        acc = stf.SparseConditionalAccumulator(stf.float32)
        grad = stf.IndexedSlices(
            values=stf.constant(np.array([[2., 2.]], np.float32)),
            indices=stf.constant(np.array([1], np.int64)))
        apply_op = acc.apply_indexed_slices_grad(grad)
        out = acc.take_indexed_slices_grad(1)
        with stf.Session() as sess:
            sess.run(apply_op)
            iv, vv = sess.run([out.indices, out.values])
        np.testing.assert_array_equal(iv, [1])
        np.testing.assert_allclose(vv, [[2., 2.]])


class TestRecordInput:
    def _write_tfrecords(self, tmp_path, n_files=2, per_file=6):
        from simple_tensorflow_tpu.lib.io import tf_record

        paths = []
        k = 0
        for f in range(n_files):
            p = str(tmp_path / f"part-{f}.tfrecord")
            with tf_record.TFRecordWriter(p) as w:
                for _ in range(per_file):
                    w.write(f"rec{k}".encode())
                    k += 1
            paths.append(p)
        return str(tmp_path / "part-*.tfrecord"), n_files * per_file

    def test_yields_batches_covering_all_records(self, tmp_path):
        stf.reset_default_graph()
        pattern, total = self._write_tfrecords(tmp_path)
        ri = stf.RecordInput(pattern, batch_size=4, buffer_size=8, seed=7)
        batch = ri.get_yield_op()
        seen = []
        with stf.Session() as sess:
            for _ in range(total // 4):
                seen.extend(sess.run(batch).tolist())
        assert len(seen) == total
        # wraps epochs continuously: every record appears at least once
        assert {f"rec{i}".encode() if isinstance(seen[0], bytes)
                else f"rec{i}" for i in range(total)} <= set(seen)

    def test_exactly_once_per_epoch_across_epochs(self, tmp_path):
        # the reference record_yielder contract: each record appears
        # exactly once per epoch even when the consumer is slow and the
        # reader is ready with the next epoch (the buffer must drain at
        # the boundary — regression for an epoch-interleaving race)
        import collections
        import time as _time

        stf.reset_default_graph()
        pattern, total = self._write_tfrecords(tmp_path)
        ri = stf.RecordInput(pattern, batch_size=4, buffer_size=8, seed=3)
        batch = ri.get_yield_op()
        seen = []
        with stf.Session() as sess:
            for k in range(2 * total // 4):
                seen.extend(sess.run(batch).tolist())
                _time.sleep(0.01)  # give the reader time to race ahead
        counts = collections.Counter(seen)
        assert len(seen) == 2 * total
        assert all(c == 2 for c in counts.values()), counts

    def test_bad_pattern_raises(self):
        stf.reset_default_graph()
        with pytest.raises(ValueError, match="No files match"):
            stf.RecordInput("/nonexistent/xyz-*.tfrecord")

    def test_empty_files_raise_out_of_range(self, tmp_path):
        from simple_tensorflow_tpu.lib.io import tf_record

        stf.reset_default_graph()
        p = str(tmp_path / "empty.tfrecord")
        with tf_record.TFRecordWriter(p):
            pass  # zero records
        ri = stf.RecordInput(p, batch_size=1)
        batch = ri.get_yield_op()
        with stf.Session() as sess:
            with pytest.raises(stf.errors.OutOfRangeError,
                               match="no records"):
                sess.run(batch)


class TestBarrierClosedEmpty:
    def test_allow_small_batch_closed_empty_is_out_of_range(self):
        stf.reset_default_graph()
        b = stf.Barrier((stf.int32,), shapes=((),))
        _, keys_t, _ = b.take_many(1, allow_small_batch=True)
        with stf.Session() as sess:
            sess.run(b.close())
            with pytest.raises(stf.errors.OutOfRangeError):
                sess.run(keys_t)
