"""Training stack: MonitoredTrainingSession, hooks, coordinator
(mirrors ref monitored_session_test.py / basic_session_run_hooks_test.py)."""

import glob
import os
import time

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _linear_problem():
    gs = stf.train.get_or_create_global_step()
    v = stf.Variable(stf.constant([2.0]), name="w")
    loss = stf.reduce_sum(stf.square(v._ref))
    train = stf.train.GradientDescentOptimizer(0.1).minimize(
        loss, global_step=gs)
    return train, loss, gs


class TestMonitoredTrainingSession:
    def test_basic_loop_with_stop_hook(self):
        train, loss, gs = _linear_problem()
        hook = stf.train.StopAtStepHook(num_steps=5)
        with stf.train.MonitoredTrainingSession(hooks=[hook]) as sess:
            n = 0
            while not sess.should_stop():
                sess.run(train)
                n += 1
        assert n == 5

    def test_checkpoint_saver_hook(self, tmp_path):
        train, loss, gs = _linear_problem()
        ckdir = str(tmp_path)
        with stf.train.MonitoredTrainingSession(
                checkpoint_dir=ckdir, save_checkpoint_steps=2,
                hooks=[stf.train.StopAtStepHook(num_steps=5)]) as sess:
            while not sess.should_stop():
                sess.run(train)
        assert stf.train.latest_checkpoint(ckdir) is not None

    def test_resume_from_checkpoint(self, tmp_path):
        ckdir = str(tmp_path)
        train, loss, gs = _linear_problem()
        with stf.train.MonitoredTrainingSession(
                checkpoint_dir=ckdir, save_checkpoint_steps=1,
                hooks=[stf.train.StopAtStepHook(num_steps=3)]) as sess:
            while not sess.should_stop():
                sess.run(train)
        # new graph, same checkpoint dir -> resumes at step 3
        stf.reset_default_graph()
        train, loss, gs = _linear_problem()
        with stf.train.MonitoredTrainingSession(
                checkpoint_dir=ckdir,
                hooks=[stf.train.StopAtStepHook(last_step=5)]) as sess:
            steps = 0
            while not sess.should_stop():
                sess.run(train)
                steps += 1
        assert steps == 2  # resumed from 3, ran to 5

    def test_nan_tensor_hook(self):
        gs = stf.train.get_or_create_global_step()
        v = stf.Variable(stf.constant([1.0]), name="nv")
        loss = stf.reduce_sum(stf.log(v._ref - 1.0))  # log(0) = -inf
        train = stf.train.GradientDescentOptimizer(1.0).minimize(
            loss, global_step=gs)
        hook = stf.train.NanTensorHook(loss, fail_on_nan_loss=True)
        from simple_tensorflow_tpu.train.basic_session_run_hooks import \
            NanLossDuringTrainingError

        with pytest.raises(NanLossDuringTrainingError):
            with stf.train.MonitoredTrainingSession(hooks=[hook]) as sess:
                for _ in range(3):
                    sess.run(train)

    def test_logging_and_step_counter_hooks_run(self, tmp_path):
        train, loss, gs = _linear_problem()
        hooks = [
            stf.train.LoggingTensorHook({"loss": loss}, every_n_iter=2),
            stf.train.StepCounterHook(every_n_steps=2,
                                      output_dir=str(tmp_path)),
            stf.train.StopAtStepHook(num_steps=4),
        ]
        with stf.train.MonitoredTrainingSession(hooks=hooks) as sess:
            while not sess.should_stop():
                sess.run(train)

    def test_summary_saver_hook(self, tmp_path):
        train, loss, gs = _linear_problem()
        s = stf.summary.scalar("loss_s", loss)
        hook = stf.train.SummarySaverHook(save_steps=1, summary_op=s,
                                          output_dir=str(tmp_path))
        with stf.train.MonitoredTrainingSession(
                hooks=[hook, stf.train.StopAtStepHook(num_steps=3)]) as sess:
            while not sess.should_stop():
                sess.run(train)
        files = glob.glob(os.path.join(str(tmp_path),
                                       "events.out.tfevents.*"))
        assert files

    def test_final_ops_hook(self):
        train, loss, gs = _linear_problem()
        hook = stf.train.FinalOpsHook(loss)
        with stf.train.MonitoredTrainingSession(
                hooks=[hook, stf.train.StopAtStepHook(num_steps=2)]) as sess:
            while not sess.should_stop():
                sess.run(train)
        assert np.isfinite(hook.final_ops_values)


class TestScaffold:
    def test_custom_init_op(self):
        v = stf.Variable(stf.zeros([1]), name="sv")
        init = stf.group(stf.variables_initializer([v]),
                         stf.assign(v, stf.constant([42.0])).op)
        scaffold = stf.train.Scaffold(init_op=init)
        with stf.train.MonitoredTrainingSession(scaffold=scaffold) as sess:
            assert sess.run(v.value()).tolist() == [42.0]


class TestCoordinator:
    def test_coordinator_stop_join(self):
        import threading

        coord = stf.train.Coordinator()
        counter = {"n": 0}

        def worker():
            while not coord.should_stop():
                counter["n"] += 1
                if counter["n"] >= 10:
                    coord.request_stop()

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        coord.join(threads)
        assert counter["n"] >= 10

    def test_queue_runner_blocked_enqueue_stops_cleanly(self):
        # a runner blocked on a FULL queue must wake when the coordinator
        # stops (the reference's close-on-stop cancel path) — previously
        # it hung past the join grace period and join raised
        import time

        stf.reset_default_graph()
        q = stf.FIFOQueue(4, dtypes=[stf.int32], shapes=[[]])
        enq = q.enqueue([stf.constant(1)])
        qr = stf.train.QueueRunner(q, [enq])
        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            threads = qr.create_threads(sess, coord=coord, start=True)
            time.sleep(0.3)  # fills the queue; the runner blocks
            coord.request_stop()
            t0 = time.time()
            coord.join(threads, stop_grace_period_secs=5)
            assert time.time() - t0 < 3.0

    def test_shuffle_batch_pipeline_throttles(self):
        # slice_input_producer must return a LIST (ref contract), and a
        # producer outrunning a slow consumer must BLOCK at capacity,
        # not crash the coordinator with ResourceExhausted
        import time

        stf.reset_default_graph()
        data = stf.constant(np.arange(32, dtype=np.int32))
        # num_epochs=1 so epoch-2 duplicates cannot race into the
        # shuffle buffer and break the uniqueness assertion
        slices = stf.train.slice_input_producer([data], shuffle=False,
                                                num_epochs=1)
        assert isinstance(slices, list) and len(slices) == 1
        batch = stf.train.shuffle_batch([slices[0]], batch_size=4,
                                        capacity=12, min_after_dequeue=4)
        batch_t = batch[0] if isinstance(batch, list) else batch
        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            threads = stf.train.start_queue_runners(sess=sess,
                                                    coord=coord)
            vals = []
            for _ in range(6):
                vals.extend(np.asarray(sess.run(batch_t)).tolist())
                time.sleep(0.05)  # let the producer hit capacity
            coord.request_stop()
            coord.join(threads, stop_grace_period_secs=5)
        assert len(vals) == 24 and len(set(vals)) == 24

    def test_coordinator_exception_reraised(self):
        import threading

        coord = stf.train.Coordinator()

        def worker():
            try:
                raise ValueError("boom")
            except Exception as e:
                coord.request_stop(e)

        t = threading.Thread(target=worker)
        t.start()
        with pytest.raises(ValueError):
            coord.join([t])


class TestSupervisorAndLoops:
    def test_basic_train_loop(self):
        train, loss, gs = _linear_problem()

        def train_step_fn(sess, *args):
            _, l = sess.run([train, loss])
            if int(np.asarray(sess.run(gs))) >= 3:
                raise stf.errors.OutOfRangeError(None, None, "done")
            return l

        sv = stf.train.Supervisor(is_chief=True)
        stf.train.basic_train_loop(sv, train_step_fn)

    def test_evaluation_evaluate_once(self, tmp_path):
        v = stf.Variable(stf.constant([6.0]), name="ev")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        from simple_tensorflow_tpu.train import evaluation

        out = evaluation.evaluate_once(
            checkpoint_path=path, eval_ops=None,
            final_ops={"val": v.value()})
        assert out["val"].tolist() == [6.0]


class TestSyncReplicas:
    def test_sync_replicas_wrapper_runs(self):
        gs = stf.train.get_or_create_global_step()
        v = stf.Variable(stf.constant([1.0]), name="sr_v")
        loss = stf.reduce_sum(stf.square(v._ref))
        base = stf.train.GradientDescentOptimizer(0.1)
        opt = stf.train.SyncReplicasOptimizer(base, replicas_to_aggregate=1)
        train = opt.minimize(loss, global_step=gs)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(train)
            assert float(sess.run(v.value())[0]) < 1.0
