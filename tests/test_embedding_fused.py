"""Fused vocab-sharded embedding path (ISSUE 19): dedup-before-lookup
exactness fuzz against the naive dense-gather reference, the
scatter-add backward through ``stf.gradients``, the ragged Example
parser feeding embedding bags, per-shard checkpoint saves, and the
``/stf/embedding/*`` telemetry.

The reference semantics is plain ``np.take`` forward and ``np.add.at``
backward: integer id handling must be EXACT; float gradients compare at
tight tolerance (the fused path reorders the scatter-add sum)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import parallel
from simple_tensorflow_tpu.ops import embedding_ops
from simple_tensorflow_tpu.platform import monitoring


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield
    stf.reset_default_graph()


def _zipf_ids(rng, n, vocab, a=1.4):
    """Head-heavy ids (the dedup pass must see real duplication)."""
    return np.minimum(rng.zipf(a, n) - 1, vocab - 1).astype(np.int32)


def _reference(table, ids, upstream):
    """np.take forward + np.add.at table gradient for loss
    sum(upstream * lookup(ids))."""
    fwd = np.take(table, ids, axis=0)
    grad = np.zeros_like(table)
    np.add.at(grad, ids, upstream)
    return fwd, grad


def _build_fused(vocab, dim, n_ids, dedup):
    table = stf.get_variable(
        f"fuzz/table_{vocab}_{dim}_{n_ids}_{dedup}", [vocab, dim],
        initializer=stf.zeros_initializer())
    ids_ph = stf.placeholder(stf.int32, [n_ids], name="ids")
    up_ph = stf.placeholder(stf.float32, [n_ids, dim], name="up")
    out = embedding_ops.embedding_lookup_fused(table, ids_ph,
                                               dedup=dedup)
    loss = stf.reduce_sum(stf.multiply(out, up_ph))
    (gtab,) = stf.gradients(loss, [table])
    return table, ids_ph, up_ph, out, gtab


@pytest.mark.parametrize("dedup", [True, False])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fused_lookup_fuzz_single_device(seed, dedup):
    rng = np.random.RandomState(seed)
    vocab, dim, n_ids = 96 + 8 * seed, 8, 57
    table_v, ids_ph, up_ph, out, gtab = _build_fused(vocab, dim, n_ids,
                                                     dedup)
    tbl = rng.standard_normal((vocab, dim)).astype(np.float32)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        sess.run(stf.assign(table_v, stf.constant(tbl)))
        ids = _zipf_ids(rng, n_ids, vocab)
        up = rng.standard_normal((n_ids, dim)).astype(np.float32)
        got_out, got_grad = sess.run([out, gtab],
                                     {ids_ph: ids, up_ph: up})
    ref_out, ref_grad = _reference(tbl, ids, up)
    # forward is a pure gather of the stored rows: EXACT
    np.testing.assert_array_equal(got_out, ref_out)
    # backward reorders the duplicate-id sum: tight tolerance
    np.testing.assert_allclose(got_grad, ref_grad, rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("seed", [3, 4])
def test_fused_lookup_fuzz_ep8_mesh(seed):
    """Same exactness bar with the table REALLY vocab-sharded over the
    8 virtual devices (conftest forces
    --xla_force_host_platform_device_count=8): the all-to-all route and
    the owning-shard scatter-add must agree with the dense reference."""
    rng = np.random.RandomState(seed)
    vocab, dim, n_ids = 128, 16, 70  # 128 % 8 == 0: fused shard path
    with parallel.Mesh({"ep": 8}):
        with parallel.shard_variables_along("ep", min_size=1, dim=0):
            table_v = stf.get_variable(
                "fuzz/sharded_table", [vocab, dim],
                initializer=stf.zeros_initializer())
        ids_ph = stf.placeholder(stf.int32, [n_ids], name="ids")
        up_ph = stf.placeholder(stf.float32, [n_ids, dim], name="up")
        out = embedding_ops.embedding_lookup_fused(table_v, ids_ph)
        loss = stf.reduce_sum(stf.multiply(out, up_ph))
        (gtab,) = stf.gradients(loss, [table_v])
        tbl = rng.standard_normal((vocab, dim)).astype(np.float32)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(stf.assign(table_v, stf.constant(tbl)))
            ids = _zipf_ids(rng, n_ids, vocab)
            up = rng.standard_normal((n_ids, dim)).astype(np.float32)
            got_out, got_grad = sess.run([out, gtab],
                                         {ids_ph: ids, up_ph: up})
    ref_out, ref_grad = _reference(tbl, ids, up)
    np.testing.assert_array_equal(got_out, ref_out)
    np.testing.assert_allclose(got_grad, ref_grad, rtol=1e-5, atol=1e-5)


def test_fused_training_in_run_steps_window():
    """The fused path must survive the donation-active run_steps
    window: repeated SGD on the table through the custom-vjp gradient,
    matching the same training loop replayed in numpy."""
    vocab, dim, n_ids, lr = 64, 4, 31, 0.5
    rng = np.random.RandomState(7)
    table_v = stf.get_variable("win/table", [vocab, dim],
                               initializer=stf.zeros_initializer())
    ids_ph = stf.placeholder(stf.int32, [n_ids], name="ids")
    out = embedding_ops.embedding_lookup_fused(table_v, ids_ph)
    loss = stf.reduce_sum(stf.multiply(out, out))
    train = stf.train.GradientDescentOptimizer(lr).minimize(loss)
    tbl = rng.standard_normal((vocab, dim)).astype(np.float32)
    ids = _zipf_ids(rng, n_ids, vocab)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        sess.run(stf.assign(table_v, stf.constant(tbl)))
        sess.run_steps(train, n=6, feed_dict={ids_ph: ids})
        got = sess.run(table_v.value())
    want = tbl.copy()
    for _ in range(6):
        grad = np.zeros_like(want)
        np.add.at(grad, ids, 2.0 * np.take(want, ids, axis=0))
        want -= lr * grad
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_embedding_bag_matches_manual_pooling():
    rng = np.random.RandomState(11)
    vocab, dim, b, L = 50, 6, 9, 5
    table_v = stf.get_variable("bag/table", [vocab, dim],
                               initializer=stf.zeros_initializer())
    ids_ph = stf.placeholder(stf.int32, [b, L], name="ids")
    len_ph = stf.placeholder(stf.int32, [b], name="lens")
    bag_sum = embedding_ops.embedding_bag(table_v, ids_ph, len_ph,
                                          combiner="sum")
    bag_mean = embedding_ops.embedding_bag(table_v, ids_ph, len_ph,
                                           combiner="mean")
    tbl = rng.standard_normal((vocab, dim)).astype(np.float32)
    lens = rng.randint(0, L + 1, b).astype(np.int32)
    ids = np.full((b, L), -1, np.int32)
    for i, ln in enumerate(lens):
        ids[i, :ln] = rng.randint(0, vocab, ln)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        sess.run(stf.assign(table_v, stf.constant(tbl)))
        s, m = sess.run([bag_sum, bag_mean],
                        {ids_ph: ids, len_ph: lens})
    want_sum = np.zeros((b, dim), np.float32)
    for i, ln in enumerate(lens):
        if ln:
            want_sum[i] = np.take(tbl, ids[i, :ln], axis=0).sum(0)
    want_mean = want_sum / np.maximum(lens, 1)[:, None]
    np.testing.assert_allclose(s, want_sum, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(m, want_mean, rtol=1e-5, atol=1e-5)


def test_embedding_metrics_populate():
    before = monitoring.export().get("/stf/embedding/lookups",
                                     {"cells": {}})["cells"]
    before_total = sum(before.values()) if before else 0
    table_v = stf.get_variable("met/table", [32, 4],
                               initializer=stf.zeros_initializer())
    ids = stf.constant(np.array([1, 1, 1, 2, 3, 3], np.int32))
    out = embedding_ops.embedding_lookup_fused(table_v, ids)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        sess.run(out)
    exported = monitoring.export()
    for name in ("/stf/embedding/lookups", "/stf/embedding/unique_ids",
                 "/stf/embedding/dedup_ratio",
                 "/stf/embedding/bytes_moved"):
        assert name in exported, name
    cells = exported["/stf/embedding/lookups"]["cells"]
    assert sum(cells.values()) >= before_total + 6
    uniq = exported["/stf/embedding/unique_ids"]["cells"]
    assert any(v >= 3 for v in uniq.values())


# ---------------------------------------------------------------------------
# ragged Example parsing (the sparse-feature input path)
# ---------------------------------------------------------------------------

def _ragged_examples():
    from simple_tensorflow_tpu.lib import example as example_mod

    exs = [
        example_mod.make_example(ids=[3, 1, 4, 1, 5], w=[0.5, 0.25]),
        example_mod.make_example(ids=[2], dense=[9]),
        example_mod.make_example(ids=list(range(12)), w=[1.0]),
        example_mod.make_example(dense=[7]),
    ]
    return [e.SerializeToString() for e in exs]


def _ragged_specs():
    from simple_tensorflow_tpu.ops import parsing_ops

    return {"ids": parsing_ops.RaggedFeature("int64", max_len=8),
            "w": parsing_ops.RaggedFeature("float32", max_len=4)}


def test_ragged_parse_padding_lengths_truncation():
    from simple_tensorflow_tpu.ops import parsing_ops

    out = parsing_ops.parse_example_py(_ragged_examples(),
                                       _ragged_specs())
    assert out["ids"].shape == (4, 8) and out["w"].shape == (4, 4)
    assert list(out["ids_lengths"]) == [5, 1, 8, 0]  # 12 clamps to 8
    assert list(out["w_lengths"]) == [2, 0, 1, 0]
    assert list(out["ids"][0]) == [3, 1, 4, 1, 5, -1, -1, -1]
    assert list(out["ids"][3]) == [-1] * 8
    np.testing.assert_allclose(out["w"][0], [0.5, 0.25, 0, 0])
    cells = monitoring.export()[
        "/stf/data/ragged_truncated_values"]["cells"]
    assert cells.get("ids", 0) >= 4  # 12 - 8 dropped values counted


def test_ragged_parse_native_and_python_paths_agree():
    from simple_tensorflow_tpu.ops import parsing_ops
    from simple_tensorflow_tpu.runtime import native

    ser = _ragged_examples()
    fast = parsing_ops.parse_example_py(ser, _ragged_specs())
    saved = native.ragged_parse_available
    native.ragged_parse_available = lambda: False
    try:
        slow = parsing_ops.parse_example_py(ser, _ragged_specs())
    finally:
        native.ragged_parse_available = saved
    assert set(fast) == set(slow)
    for k in fast:
        np.testing.assert_array_equal(fast[k], slow[k])


def test_ragged_parse_graph_op_and_threaded_dataset_stage():
    from simple_tensorflow_tpu import data as stf_data
    from simple_tensorflow_tpu.ops import parsing_ops

    ser = _ragged_examples()
    ph = stf.placeholder(stf.string, [4])
    parsed = parsing_ops.parse_example(ph, _ragged_specs())
    with stf.Session() as sess:
        ids, lens = sess.run(
            [parsed["ids"], parsed["ids_lengths"]],
            feed_dict={ph: np.asarray(ser, dtype=object)})
    assert ids.shape == (4, 8) and list(lens) == [5, 1, 8, 0]

    ds = stf_data.Dataset.from_tensor_slices(np.asarray(ser, object)) \
        .batch(2).parse_example(_ragged_specs(), num_parallel_calls=2)
    got = list(ds)
    assert got[0]["ids"].shape == (2, 8)
    assert list(got[1]["ids_lengths"]) == [8, 0]


def test_ragged_batch_feeds_embedding_bag():
    """End-to-end sparse input path: serialized Examples -> ragged
    parse -> embedding_bag pooled lookup (pad ids masked out)."""
    from simple_tensorflow_tpu.ops import parsing_ops

    out = parsing_ops.parse_example_py(_ragged_examples(),
                                       _ragged_specs())
    vocab, dim = 16, 3
    table_v = stf.get_variable("e2e/table", [vocab, dim],
                               initializer=stf.zeros_initializer())
    ids_ph = stf.placeholder(stf.int32, [4, 8], name="ids")
    len_ph = stf.placeholder(stf.int32, [4], name="lens")
    bag = embedding_ops.embedding_bag(table_v, ids_ph, len_ph,
                                      combiner="sum")
    tbl = np.arange(vocab * dim, dtype=np.float32).reshape(vocab, dim)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        sess.run(stf.assign(table_v, stf.constant(tbl)))
        got = sess.run(bag, {ids_ph: out["ids"].astype(np.int32),
                             len_ph: out["ids_lengths"].astype(np.int32)})
    want = np.zeros((4, dim), np.float32)
    for i, ln in enumerate(out["ids_lengths"]):
        if ln:
            want[i] = np.take(tbl, out["ids"][i, :ln], axis=0).sum(0)
    np.testing.assert_allclose(got, want, rtol=1e-6)


# ---------------------------------------------------------------------------
# flat per-shard table checkpointing
# ---------------------------------------------------------------------------

def test_sharded_table_checkpoint_roundtrip(tmp_path):
    import json

    from simple_tensorflow_tpu import train
    from simple_tensorflow_tpu.checkpoint import snapshot as snap

    with parallel.Mesh({"ep": 8}):
        with parallel.shard_variables_along("ep", min_size=1, dim=0):
            v = stf.get_variable(
                "ckpt/table", [64, 8],
                initializer=stf.random_uniform_initializer(-1, 1,
                                                           seed=0))
        small = stf.get_variable("ckpt/small", [3],
                                 initializer=stf.zeros_initializer())
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        name = v.var_name if hasattr(v, "var_name") else v.name
        arr = sess._variable_store.values[name]
        parts = snap.shard_split(arr)
        assert parts is not None and len(parts) == 8
        want = np.asarray(arr)
        saver = train.Saver()
        prefix = saver.save(sess, str(tmp_path / "model"),
                            global_step=1)
        with np.load(prefix + ".stfz") as data:
            keys = sorted(data.files)
        assert sum("@shard" in k for k in keys) == 8, keys
        assert not any(k == "ckpt|table" for k in keys)
        with open(prefix + ".index.json") as f:
            idx = json.load(f)
        lay = idx["tensors"]["ckpt/table"]["sharded_layout"]
        assert lay["num_shards"] == 8
        # integrity check understands shard entries
        assert snap.verify_checkpoint(prefix) == []
        # the tools reader reassembles logical tensors
        vals = train.saver.load_checkpoint_values(prefix)
        np.testing.assert_array_equal(vals["ckpt/table"], want)
        assert not any("@shard" in k for k in vals)
        # restore into a fresh session reproduces the table exactly
        sess2 = stf.Session()
        saver.restore(sess2, prefix)
        got = np.asarray(sess2._variable_store.values[name])
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(
            np.asarray(sess2._variable_store.values[
                small.var_name if hasattr(small, "var_name")
                else small.name]),
            np.zeros([3], np.float32))
        sess.close()
        sess2.close()


def test_replicated_checkpoint_format_unchanged(tmp_path):
    """No mesh: the bundle keeps plain whole-tensor entries (no shard
    suffixes, no sharded_layout in the index)."""
    import json

    from simple_tensorflow_tpu import train

    v = stf.get_variable("plain/w", [4, 4],
                         initializer=stf.zeros_initializer())
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        saver = train.Saver()
        prefix = saver.save(sess, str(tmp_path / "m"), global_step=0)
    with np.load(prefix + ".stfz") as data:
        assert all("@shard" not in k for k in data.files)
    with open(prefix + ".index.json") as f:
        idx = json.load(f)
    assert all("sharded_layout" not in m
               for m in idx["tensors"].values())


# ---------------------------------------------------------------------------
# lint/embedding-replicated-table + graph_lint --embeddings
# ---------------------------------------------------------------------------

def _big_table_graph():
    from simple_tensorflow_tpu.ops import embedding_ops as emb

    table = stf.get_variable("emb/table", [1 << 12, 64],
                             initializer=stf.zeros_initializer())  # 1 MiB
    ids = stf.placeholder(stf.int32, [32], name="ids")
    loss = stf.reduce_sum(emb.embedding_lookup_fused(table, ids))
    return loss


def test_embedding_replicated_table_lint_fires_and_gates():
    from simple_tensorflow_tpu import analysis

    loss = _big_table_graph()
    diags = analysis.analyze(stf.get_default_graph(), fetches=[loss],
                             mesh={"ep": 8}, purpose="embeddings",
                             memory_budget=1 << 20)
    hits = [d for d in diags
            if d.code == "lint/embedding-replicated-table"]
    assert hits and all(d.severity == "error" for d in hits)
    # purpose-gated: an ordinary analyze run stays clean
    diags2 = analysis.analyze(stf.get_default_graph(), fetches=[loss],
                              mesh={"ep": 8})
    assert not any(d.code == "lint/embedding-replicated-table"
                   for d in diags2)


def test_graph_lint_embeddings_cli_verdicts(tmp_path):
    import json

    from simple_tensorflow_tpu.framework import graph_io
    from simple_tensorflow_tpu.tools import graph_lint

    loss = _big_table_graph()
    gd = graph_io.graph_to_graphdef(stf.get_default_graph())
    p = str(tmp_path / "emb.json")
    with open(p, "w") as f:
        json.dump(gd, f)
    loss_name = loss.name

    # replicated table over budget on an 8-way mesh: rc 1
    stf.reset_default_graph()
    rc = graph_lint.main([p, "--fetch", loss_name, "--embeddings",
                          "--mesh", "ep=8", "--budget", str(1 << 20)])
    assert rc == 1
    # generous budget: same layout passes
    stf.reset_default_graph()
    rc = graph_lint.main([p, "--fetch", loss_name, "--embeddings",
                          "--mesh", "ep=8", "--budget", str(1 << 30)])
    assert rc == 0
    # vocab-sharded via partition rules: clean under the tight budget
    stf.reset_default_graph()
    rp = str(tmp_path / "rules.json")
    with open(rp, "w") as f:
        json.dump([["emb/table", ["ep", None]]], f)
    rc = graph_lint.main([p, "--fetch", loss_name, "--embeddings",
                          "--mesh", "ep=8", "--budget", str(1 << 20),
                          "--rules", rp])
    assert rc == 0
