"""Array op tests vs numpy (mirrors ref kernel_tests/*array*, SURVEY §4)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _run(t, feed=None):
    with stf.Session() as sess:
        return sess.run(t, feed)


RNG = np.random.RandomState(11)


class TestShapes:
    def test_reshape_transpose_expand_squeeze(self):
        a = RNG.rand(2, 3, 4).astype(np.float32)
        t = stf.constant(a)
        out = _run({
            "r": stf.reshape(t, [6, 4]),
            "rm1": stf.reshape(t, [2, -1]),
            "tr": stf.transpose(t, [2, 0, 1]),
            "tr_def": stf.transpose(stf.constant(a[0])),
            "ex": stf.expand_dims(t, 1),
            "sq": stf.squeeze(stf.constant(a[:, :1, :]), axis=[1]),
        })
        assert out["r"].shape == (6, 4)
        assert out["rm1"].shape == (2, 12)
        np.testing.assert_allclose(out["tr"], a.transpose(2, 0, 1))
        np.testing.assert_allclose(out["tr_def"], a[0].T)
        assert out["ex"].shape == (2, 1, 3, 4)
        assert out["sq"].shape == (2, 4)

    def test_shape_size_rank(self):
        t = stf.placeholder(stf.float32, [2, 3])
        out = _run({"s": stf.shape(t), "n": stf.size(t), "rk": stf.rank(t)},
                   {t: np.zeros((2, 3), np.float32)})
        assert out["s"].tolist() == [2, 3]
        assert out["n"] == 6 and out["rk"] == 2
        # static shape inference
        assert stf.reshape(t, [3, 2]).shape.as_list() == [3, 2]

    def test_concat_split_stack_unstack(self):
        a = RNG.rand(2, 3).astype(np.float32)
        b = RNG.rand(2, 3).astype(np.float32)
        ta, tb = stf.constant(a), stf.constant(b)
        out = _run({
            "c0": stf.concat([ta, tb], 0), "c1": stf.concat([ta, tb], 1),
            "st": stf.stack([ta, tb], axis=1),
        })
        np.testing.assert_allclose(out["c0"], np.concatenate([a, b], 0))
        np.testing.assert_allclose(out["c1"], np.concatenate([a, b], 1))
        assert out["st"].shape == (2, 2, 3)
        parts = stf.split(stf.constant(a), 3, axis=1)
        assert len(parts) == 3
        np.testing.assert_allclose(_run(parts[1]), a[:, 1:2])
        us = stf.unstack(stf.constant(a), axis=0)
        assert len(us) == 2
        np.testing.assert_allclose(_run(us[1]), a[1])

    def test_pad_tile_reverse(self):
        a = np.array([[1, 2], [3, 4]], np.float32)
        t = stf.constant(a)
        out = _run({
            "pad": stf.pad(t, [[1, 0], [0, 2]]),
            "pad_refl": stf.pad(t, [[1, 1], [0, 0]], mode="REFLECT"),
            "tile": stf.tile(t, [2, 1]),
            "rev": stf.reverse(t, axis=[1]),
        })
        assert out["pad"].shape == (3, 4) and out["pad"][0, 0] == 0
        np.testing.assert_allclose(out["pad_refl"],
                                   np.pad(a, [[1, 1], [0, 0]], "reflect"))
        assert out["tile"].shape == (4, 2)
        np.testing.assert_allclose(out["rev"], a[:, ::-1])


class TestSlicing:
    def test_slice_strided_slice(self):
        a = RNG.rand(4, 5, 6).astype(np.float32)
        t = stf.constant(a)
        out = _run({
            "sl": stf.slice(t, [1, 0, 2], [2, 3, -1]),
            "ss": stf.strided_slice(t, [0, 1, 0], [4, 5, 6], [2, 2, 3]),
            "idx": t[1, :, 2:4],
            "neg": t[:, -1],
        })
        np.testing.assert_allclose(out["sl"], a[1:3, 0:3, 2:])
        np.testing.assert_allclose(out["ss"], a[::2, 1::2, ::3])
        np.testing.assert_allclose(out["idx"], a[1, :, 2:4])
        np.testing.assert_allclose(out["neg"], a[:, -1])

    def test_gather_gather_nd_scatter_nd(self):
        a = RNG.rand(5, 3).astype(np.float32)
        t = stf.constant(a)
        out = _run({
            "g": stf.gather(t, [3, 1]),
            "ga1": stf.gather(t, [0, 2], axis=1),
            "gnd": stf.gather_nd(t, [[0, 1], [4, 2]]),
            "snd": stf.scatter_nd([[1], [3]], [[1., 1., 1.], [2., 2., 2.]],
                                  [5, 3]),
        })
        np.testing.assert_allclose(out["g"], a[[3, 1]])
        np.testing.assert_allclose(out["ga1"], a[:, [0, 2]])
        np.testing.assert_allclose(out["gnd"], [a[0, 1], a[4, 2]])
        assert out["snd"][1].tolist() == [1., 1., 1.]
        assert out["snd"][0].tolist() == [0., 0., 0.]

    def test_boolean_mask_where(self):
        a = np.array([1., 2., 3., 4.], np.float32)
        mask = np.array([True, False, True, False])
        out = _run({
            "bm": stf.boolean_mask(stf.constant(a), stf.constant(mask)),
            "wc": stf.where(stf.constant(mask), stf.constant(a),
                            stf.constant(-a)),
        })
        assert out["bm"].tolist() == [1., 3.]
        assert out["wc"].tolist() == [1., -2., 3., -4.]


class TestConstruction:
    def test_zeros_ones_fill_eye(self):
        out = _run({
            "z": stf.zeros([2, 3]), "o": stf.ones([3], stf.int32),
            "f": stf.fill([2, 2], 7.0), "e": stf.eye(3),
            "zl": stf.zeros_like(stf.constant([[1., 2.]])),
            "ol": stf.ones_like(stf.constant([1, 2, 3])),
        })
        assert out["z"].sum() == 0 and out["z"].shape == (2, 3)
        assert out["o"].tolist() == [1, 1, 1]
        assert out["f"].tolist() == [[7., 7.], [7., 7.]]
        np.testing.assert_allclose(out["e"], np.eye(3))
        assert out["zl"].shape == (1, 2)
        assert out["ol"].tolist() == [1, 1, 1]

    def test_one_hot(self):
        out = _run(stf.one_hot([1, 0, 2], 3, on_value=5.0, off_value=-1.0))
        assert out[0].tolist() == [-1., 5., -1.]
        assert out[2].tolist() == [-1., -1., 5.]

    def test_sequence_mask(self):
        out = _run(stf.sequence_mask([1, 3], maxlen=4))
        assert out.tolist() == [[True, False, False, False],
                                [True, True, True, False]]

    def test_matrix_diag_band(self):
        a = RNG.rand(3, 3).astype(np.float32)
        out = _run({
            "d": stf.matrix_diag(stf.constant([1., 2.])),
            "dp": stf.matrix_diag_part(stf.constant(a)),
            "band": stf.matrix_band_part(stf.constant(a), 0, 0),
        })
        assert out["d"].tolist() == [[1., 0.], [0., 2.]]
        np.testing.assert_allclose(out["dp"], np.diag(a))
        np.testing.assert_allclose(out["band"], np.diag(np.diag(a)))

    def test_unique_invert_permutation(self):
        u, idx = stf.unique(stf.constant([1, 2, 1, 3, 2]))
        out = _run({"u": u, "idx": idx,
                    "inv": stf.invert_permutation(stf.constant([2, 0, 1]))})
        assert out["u"].tolist() == [1, 2, 3]
        assert out["idx"].tolist() == [0, 1, 0, 2, 1]
        assert out["inv"].tolist() == [1, 2, 0]


class TestSpaceBatch:
    def test_space_depth_roundtrip(self):
        a = RNG.rand(1, 4, 4, 3).astype(np.float32)
        t = stf.constant(a)
        s2d = stf.space_to_depth(t, 2)
        back = stf.depth_to_space(s2d, 2)
        out = _run({"s2d": s2d, "back": back})
        assert out["s2d"].shape == (1, 2, 2, 12)
        np.testing.assert_allclose(out["back"], a)

    def test_space_to_batch_roundtrip(self):
        a = RNG.rand(1, 4, 4, 1).astype(np.float32)
        t = stf.constant(a)
        sb = stf.space_to_batch_nd(t, [2, 2], [[0, 0], [0, 0]])
        back = stf.batch_to_space_nd(sb, [2, 2], [[0, 0], [0, 0]])
        out = _run({"sb": sb, "back": back})
        assert out["sb"].shape == (4, 2, 2, 1)
        np.testing.assert_allclose(out["back"], a)


class TestGradients:
    def test_gather_grad_is_indexed(self):
        x = stf.constant(RNG.rand(5, 2).astype(np.float32))
        y = stf.reduce_sum(stf.gather(x, [1, 1, 3]))
        (g,) = stf.gradients(y, [x])
        out = _run(g)
        if hasattr(out, "values"):  # IndexedSlices
            dense = np.zeros((5, 2), np.float32)
            np.add.at(dense, np.asarray(out.indices), np.asarray(out.values))
            out = dense
        assert out[1].tolist() == [2., 2.]
        assert out[3].tolist() == [1., 1.]
        assert out[0].tolist() == [0., 0.]

    def test_concat_slice_grad(self):
        a = stf.constant(RNG.rand(2, 2).astype(np.float32))
        b = stf.constant(RNG.rand(2, 2).astype(np.float32))
        y = stf.reduce_sum(stf.concat([a, b], 0)[1:3])
        ga, gb = stf.gradients(y, [a, b])
        out = _run({"ga": ga, "gb": gb})
        assert out["ga"].tolist() == [[0., 0.], [1., 1.]]
        assert out["gb"].tolist() == [[1., 1.], [0., 0.]]

    def test_stop_gradient(self):
        x = stf.constant([2.0])
        y = stf.reduce_sum(x * stf.stop_gradient(x))
        (g,) = stf.gradients(y, [x])
        assert _run(g).tolist() == [2.0]  # only the differentiable path


class TestMeshgridAndSpaceToBatchPaddings:
    def test_meshgrid_static_xy_ij(self):
        xs, ys = stf.meshgrid(stf.constant([1, 2, 3]), stf.constant([4, 5]))
        ref_x, ref_y = np.meshgrid([1, 2, 3], [4, 5])
        np.testing.assert_array_equal(_run(xs), ref_x)
        np.testing.assert_array_equal(_run(ys), ref_y)
        xi, yi = stf.meshgrid(stf.constant([1, 2, 3]), stf.constant([4, 5]),
                              indexing="ij")
        ri, rj = np.meshgrid([1, 2, 3], [4, 5], indexing="ij")
        np.testing.assert_array_equal(_run(xi), ri)
        np.testing.assert_array_equal(_run(yi), rj)

    def test_meshgrid_dynamic_values(self):
        a = stf.placeholder(stf.float32, [3], name="mga")
        b = stf.placeholder(stf.float32, [2], name="mgb")
        xs, ys = stf.meshgrid(a, b)
        av, bv = np.array([1., 2., 3.], np.float32), np.array([4., 5.],
                                                             np.float32)
        out = _run({"x": xs, "y": ys}, feed={a: av, b: bv})
        rx, ry = np.meshgrid(av, bv)
        np.testing.assert_array_equal(out["x"], rx)
        np.testing.assert_array_equal(out["y"], ry)

    def test_required_space_to_batch_paddings(self):
        pads, crops = stf.required_space_to_batch_paddings(
            stf.constant([5, 7]), stf.constant([3, 4]))
        p, c = _run({"p": pads, "c": crops}).values()
        np.testing.assert_array_equal(p, [[0, 1], [0, 1]])
        np.testing.assert_array_equal(c, [[0, 1], [0, 1]])
        # padded size divisible by block
        assert (5 + p[0].sum()) % 3 == 0 and (7 + p[1].sum()) % 4 == 0
        # with base paddings
        pads2, _ = stf.required_space_to_batch_paddings(
            stf.constant([5]), stf.constant([4]),
            base_paddings=stf.constant([[1, 0]]))
        p2 = _run(pads2)
        assert (5 + p2[0].sum()) % 4 == 0 and p2[0][0] == 1


class TestEditDistance:
    def test_matches_levenshtein(self):
        stf.reset_default_graph()
        from simple_tensorflow_tpu.framework.sparse_tensor import SparseTensor
        # batch of 2 sequences; "abc" vs "ab" -> 1, "kitten" vs "sitting" -> 3
        def coo(seqs, maxlen):
            idx, vals = [], []
            for b, s in enumerate(seqs):
                for i, ch in enumerate(s):
                    idx.append([b, i]); vals.append(ord(ch))
            return SparseTensor(np.array(idx, np.int64),
                                np.array(vals, np.int64),
                                np.array([len(seqs), maxlen], np.int64))
        hyp = coo(["abc", "kitten"], 8)
        tru = coo(["ab", "sitting"], 8)
        d_raw = stf.edit_distance(hyp, tru, normalize=False)
        d_norm = stf.edit_distance(hyp, tru, normalize=True)
        sess = stf.Session()
        raw, norm = sess.run([d_raw, d_norm])
        np.testing.assert_allclose(raw, [1.0, 3.0])
        np.testing.assert_allclose(norm, [1.0 / 2, 3.0 / 7])

    def test_empty_truth_and_empty_slot(self):
        stf.reset_default_graph()
        from simple_tensorflow_tpu.framework.sparse_tensor import SparseTensor
        # batch of 2: row 0 has a hypothesis but empty truth (-> inf when
        # normalized); row 1 is empty in BOTH (-> 0.0, reference zero-fill)
        hyp = SparseTensor(np.array([[0, 0]], np.int64),
                           np.array([7], np.int64),
                           np.array([2, 4], np.int64))
        tru = SparseTensor(np.zeros((0, 2), np.int64),
                           np.zeros((0,), np.int64),
                           np.array([2, 4], np.int64))
        out = stf.Session().run(stf.edit_distance(hyp, tru, normalize=True))
        assert np.isinf(out[0])  # TF semantics: d/0 -> inf
        assert out[1] == 0.0
