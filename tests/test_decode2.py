"""Decode throughput II (ISSUE 16): shared-prefix prompt cache
(refcount trie, CoW divergence, churn fuzz with reconcile drift 0),
speculative decoding (greedy token-exact vs the PR 11 cached decode
path, through a checkpoint round trip), sampling decode determinism
under a fixed seed, KVCachePageCopy / copy_pages conformance,
query-block decode-attention parity, the paged causal-LM serving path,
the new serving-decode-cache lint branches, and the new
/stf/serving/{prefix_cache_*,spec_*} metrics."""

import os
import tempfile

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import analysis, serving
from simple_tensorflow_tpu.models import causal_lm as clm
from simple_tensorflow_tpu.models import transformer as tr
from simple_tensorflow_tpu.ops import kv_cache_ops as kvc
from simple_tensorflow_tpu.platform import monitoring
from simple_tensorflow_tpu.serving.prefix_cache import (
    PagesExhaustedError, PrefixCache)


@pytest.fixture(autouse=True)
def _fresh_graph():
    stf.reset_default_graph()
    yield
    stf.reset_default_graph()


# ---------------------------------------------------------------------------
# KVCachePageCopy op conformance (copy_pages: the CoW primitive)
# ---------------------------------------------------------------------------

class TestKVCachePageCopy:
    def test_copy_pages_duplicates_rows(self):
        c = kvc.kv_cache("pc_cow", num_slots=4, max_len=3,
                         inner_shape=(2,), dtype=stf.float32, paged=True)
        alloc = c.alloc()
        val = stf.placeholder(stf.float32, [1, 3, 2], "cow_val")
        one = stf.constant(np.array([1], np.int32))
        zero = stf.constant(np.array([0], np.int32))
        appended = c.append(val, one, zero)
        copied = c.copy_pages(stf.constant(np.array([2], np.int32)), one)
        slots = stf.placeholder(stf.int32, [2], "cow_slots")
        g = c.gather(slots)
        with stf.Session() as sess:
            sess.run(alloc.op)
            v = np.arange(6, dtype=np.float32).reshape(1, 3, 2)
            sess.run(appended.op, {val: v})
            sess.run(copied.op)
            out = sess.run(g, {slots: np.array([1, 2], np.int32)})
            # dst page is a byte-identical duplicate of src
            assert np.array_equal(out[0], out[1])
            assert np.array_equal(out[1], v[0])
            # an un-copied page is untouched
            out0 = sess.run(g, {slots: np.array([0, 3], np.int32)})
            assert (out0 == 0).all()

    def test_copy_then_diverge_leaves_src_intact(self):
        # the CoW contract: appends into the copy never write through
        # to the shared source page
        c = kvc.kv_cache("pc_div", num_slots=3, max_len=4,
                         inner_shape=(), dtype=stf.float32, paged=True)
        alloc = c.alloc()
        val = stf.placeholder(stf.float32, [1, 2], "div_val")
        s0 = stf.constant(np.array([0], np.int32))
        s1 = stf.constant(np.array([1], np.int32))
        zero = stf.constant(np.array([0], np.int32))
        two = stf.constant(np.array([2], np.int32))
        fill_src = c.append(val, s0, zero)
        cow = c.copy_pages(s1, s0)
        val1 = stf.placeholder(stf.float32, [1, 1], "div_val1")
        diverge = c.append(val1, s1, two)
        slots = stf.placeholder(stf.int32, [2], "div_slots")
        g = c.gather(slots)
        with stf.Session() as sess:
            sess.run(alloc.op)
            sess.run(fill_src.op, {val: np.array([[5., 6.]], np.float32)})
            sess.run(cow.op)
            sess.run(diverge.op, {val1: np.array([[9.]], np.float32)})
            out = sess.run(g, {slots: np.array([0, 1], np.int32)})
            assert np.array_equal(out[0], [5., 6., 0., 0.])   # src intact
            assert np.array_equal(out[1], [5., 6., 9., 0.])   # copy diverged

    def test_effects_declared(self):
        from simple_tensorflow_tpu.framework import op_registry

        c = kvc.kv_cache("pc_eff", 2, 2, (), stf.float32, paged=True)
        t = c.copy_pages(stf.constant(np.array([0], np.int32)),
                         stf.constant(np.array([1], np.int32)))
        eff = op_registry.get("KVCachePageCopy").effects
        assert eff.resolved_writes(t.op) == {"var_name=pc_eff"}
        assert t.op.attrs.get(kvc.PAGED_ATTR) is True


# ---------------------------------------------------------------------------
# PrefixCache: trie, refcounts, CoW probe, eviction, reconcile
# ---------------------------------------------------------------------------

class TestPrefixCacheUnit:
    def test_full_chunk_hit_and_miss_accounting(self):
        pc = PrefixCache(num_pages=8, page_len=4)
        p1 = pc.acquire(list(range(8)))
        assert len(p1.fill) == 2 and not p1.reused_pages
        assert p1.tail_page is None and pc.miss_pages == 2
        p2 = pc.acquire(list(range(8)))
        assert p2.reused_pages == p1.pages and not p2.fill
        assert pc.hit_pages == 2 and pc.shared_pages == 2
        # both sequences hold refs on the same chain
        assert p2.node is p1.node and p2.node.refs == 2

    def test_partial_tail_is_trie_resident_with_cow(self):
        pc = PrefixCache(num_pages=8, page_len=4)
        pa = pc.acquire(list(range(8)))
        pb = pc.acquire(list(range(6)))     # chunk [0:4] + tail [4, 5]
        assert pb.reused_pages == [pa.pages[0]]
        # tail [4, 5] is a proper prefix of A's second chunk (4,5,6,7):
        # served by page copy, not prefill
        assert pb.cow_src == pa.pages[1]
        assert pb.tail_page is not None
        assert pb.tail_page not in pa.pages
        assert pc.cow_hits == 1
        # the tail is TRIE-RESIDENT (ISSUE 20): a leaf node keyed on
        # the partial chunk joins the two full-chunk nodes
        assert pc.shared_pages == 3
        assert pb.node.chunk == (4, 5)
        assert pb.node.page == pb.tail_page
        assert not pb.tail_ready
        assert np.array_equal(pb.tail, [4, 5])
        assert pb.cached_len == 6
        # an identical tail later is an exact-hit: zero prefill, zero
        # copy (tail_ready), sharing the same node/page
        pb2 = pc.acquire(list(range(6)))
        assert pb2.tail_ready and pb2.tail_page == pb.tail_page
        assert pb2.cow_src is None and pb2.node is pb.node
        assert pb.node.refs == 2

    def test_tail_without_extending_child_prefills(self):
        pc = PrefixCache(num_pages=8, page_len=4)
        pc.acquire(list(range(8)))
        pb = pc.acquire([0, 1, 2, 3, 99, 98])   # tail diverges
        assert pb.cow_src is None and pb.tail_page is not None
        assert pc.cow_hits == 0

    def test_release_keeps_pages_resident_until_eviction(self):
        pc = PrefixCache(num_pages=2, page_len=4)
        pa = pc.acquire(list(range(8)))
        pc.release(pa.node)
        # refs dropped to 0 but the pages stay cached (that IS the cache)
        assert pc.shared_pages == 2 and pc.free_count == 0
        # a hit on the released chain revives it with zero prefill
        pb = pc.acquire(list(range(8)))
        assert pb.reused_pages == pa.pages and pc.hit_pages == 2
        pc.release(pb.node)
        # a disjoint admission now EVICTS (LRU refs-0 leaves)
        pcd = pc.acquire([50, 51, 52, 53])
        assert pc.evictions >= 1 and len(pcd.fill) == 1
        assert pc.reconcile([]) == 0

    def test_eviction_is_leaf_first(self):
        pc = PrefixCache(num_pages=2, page_len=2)
        pa = pc.acquire([1, 2, 3, 4])       # chain of two nodes
        pc.release(pa.node)
        pc.acquire([9, 8])                  # needs one page: evicts
        # the LEAF (deeper node) went first; its parent survives
        assert pc.evictions == 1
        resident = {n.chunk for n in pc._iter_nodes()}
        assert (1, 2) in resident and (3, 4) not in resident

    def test_exhaustion_raises_and_rolls_back(self):
        pc = PrefixCache(num_pages=2, page_len=4)
        held = pc.acquire(list(range(8)))   # both pages, refs=1
        before = pc.statusz_info()
        with pytest.raises(PagesExhaustedError):
            pc.acquire([90, 91, 92, 93, 94])
        # full rollback: no leaked refs, pages, or trie nodes
        assert pc.reconcile([]) == 0
        assert pc.shared_pages == before["shared_pages"]
        assert held.node.refs == 1

    def test_reconcile_detects_drift(self):
        pc = PrefixCache(num_pages=4, page_len=4)
        plan = pc.acquire(list(range(4)))
        assert pc.reconcile([]) == 0
        # manufacture a double-owned page: reconcile must flag it
        pc.free_page(plan.pages[0])
        assert pc.reconcile([]) > 0


class TestPrefixChurnFuzz:
    def test_refcount_fuzz_12_request_churn_drift_zero(self):
        # 12 concurrently-live requests churning over a small pool:
        # shared prefixes, CoW tails, private decode pages, eviction
        # pressure. After EVERY transition the three page populations
        # (free / trie / private) must reconcile with drift 0.
        rng = np.random.RandomState(1234)
        pc = PrefixCache(num_pages=24, page_len=4)
        prefixes = [list(rng.randint(2, 64, rng.randint(2, 13)))
                    for _ in range(5)]
        live = []       # (node, private_pages)

        def _reconcile():
            private = [p for _, priv in live for p in priv]
            assert pc.reconcile(private) == 0

        for step in range(300):
            if live and (len(live) >= 12 or rng.rand() < 0.4):
                node, priv = live.pop(rng.randint(len(live)))
                pc.release(node)
                for pg in priv:
                    pc.free_page(pg)
                _reconcile()
                continue
            toks = list(prefixes[rng.randint(len(prefixes))])
            toks += list(rng.randint(2, 64, rng.randint(0, 6)))
            try:
                plan = pc.acquire(toks)
            except PagesExhaustedError:
                _reconcile()
                continue
            priv = []
            if len(plan.tail):
                # the tail page is trie-resident: the first decode
                # append into it copies-on-write into a private page
                try:
                    priv.append(pc.alloc_page())
                except PagesExhaustedError:
                    pass
            # a few decode-time page-fault allocations
            for _ in range(rng.randint(0, 3)):
                try:
                    priv.append(pc.alloc_page())
                except PagesExhaustedError:
                    break
            live.append((plan.node, priv))
            _reconcile()
        # drain everything: the pool must come back whole
        for node, priv in live:
            pc.release(node)
            for pg in priv:
                pc.free_page(pg)
        assert pc.reconcile([]) == 0
        assert pc.hit_pages > 0 and pc.miss_pages > 0


# ---------------------------------------------------------------------------
# Speculative decoding: greedy token-exact through a checkpoint
# ---------------------------------------------------------------------------

def _save_ckpt(model, tmp):
    ckpt = os.path.join(tmp, "model")
    with model.graph.as_default():
        saver = stf.train.Saver()
        saver.save(model.session, ckpt)
    return ckpt


def _run_engine(model, prompts, draft=None, max_new_tokens=6,
                num_slots=4, max_decode_len=8, name="eng"):
    pol = serving.DecodePolicy(num_slots=num_slots,
                               max_decode_len=max_decode_len,
                               max_new_tokens=max_new_tokens)
    with serving.GenerativeEngine(name, model, pol, draft=draft) as eng:
        futs = [eng.generate(p) for p in prompts]
        out = [f.result(timeout=120) for f in futs]
        stats = eng.statusz_info()
    return out, stats


class TestSpeculativeTokenExact:
    SRC_LEN, L = 8, 8

    def _target(self, cfg, ckpt, **kw):
        return tr.TransformerGenerativeModel(
            cfg, self.SRC_LEN, num_slots=4, max_decode_len=self.L,
            checkpoint=ckpt, aot_warmup=False, **kw)

    def test_greedy_token_exact_vs_cached_decode(self):
        # target + SAME-WEIGHTS draft: every proposal agrees, yet the
        # emitted stream must equal plain cached decode exactly (every
        # committed token is the target's own pick by construction)
        cfg = tr.TransformerConfig.tiny()
        tmp = tempfile.mkdtemp(prefix="stf_spec_")
        base_model = tr.TransformerGenerativeModel(
            cfg, self.SRC_LEN, num_slots=4, max_decode_len=self.L,
            init_fresh=True, aot_warmup=False, seed=7)
        ckpt = _save_ckpt(base_model, tmp)
        batch = tr.synthetic_wmt_batch(5, self.SRC_LEN, self.L,
                                       vocab_size=cfg.vocab_size)
        prompts = [batch["src_ids"][i] for i in range(5)]
        base_out, _ = _run_engine(base_model, prompts, name="spec_base")
        base_model.close()

        target = self._target(cfg, ckpt, speculative_k=3)
        draft = self._target(cfg, ckpt, draft_steps=2)
        spec_out, stats = _run_engine(target, prompts, draft=draft,
                                      name="spec_eng")
        target.close()
        draft.close()
        for b, s in zip(base_out, spec_out):
            assert list(b["tokens"]) == list(s["tokens"])
            assert b["outcome"] == s["outcome"]
        spec = stats["speculative"]
        assert spec["proposed_tokens"] > 0
        # identical draft weights: proposals mostly accepted
        assert spec["acceptance_rate"] >= 0.5

    def test_token_exact_even_with_garbage_draft(self):
        # a draft with UNRELATED weights proposes junk; acceptance
        # collapses but the output stream is still bit-exact (the
        # verify step commits only target-agreeing prefixes)
        cfg = tr.TransformerConfig.tiny()
        tmp = tempfile.mkdtemp(prefix="stf_spec_bad_")
        base_model = tr.TransformerGenerativeModel(
            cfg, self.SRC_LEN, num_slots=4, max_decode_len=self.L,
            init_fresh=True, aot_warmup=False, seed=7)
        ckpt = _save_ckpt(base_model, tmp)
        batch = tr.synthetic_wmt_batch(3, self.SRC_LEN, self.L,
                                       vocab_size=cfg.vocab_size, seed=5)
        prompts = [batch["src_ids"][i] for i in range(3)]
        base_out, _ = _run_engine(base_model, prompts, name="specb_base")
        base_model.close()

        target = self._target(cfg, ckpt, speculative_k=3)
        draft = tr.TransformerGenerativeModel(
            cfg, self.SRC_LEN, num_slots=4, max_decode_len=self.L,
            init_fresh=True, aot_warmup=False, seed=999, draft_steps=2)
        spec_out, _ = _run_engine(target, prompts, draft=draft,
                                  name="specb_eng")
        target.close()
        draft.close()
        for b, s in zip(base_out, spec_out):
            assert list(b["tokens"]) == list(s["tokens"])

    def test_draft_target_geometry_validated(self):
        cfg = tr.TransformerConfig.tiny()
        target = tr.TransformerGenerativeModel(
            cfg, self.SRC_LEN, num_slots=4, max_decode_len=self.L,
            init_fresh=True, aot_warmup=False, speculative_k=3)
        draft = tr.TransformerGenerativeModel(
            cfg, self.SRC_LEN, num_slots=4, max_decode_len=self.L,
            init_fresh=True, aot_warmup=False, draft_steps=3)  # k+1 != 3
        pol = serving.DecodePolicy(num_slots=4, max_decode_len=self.L)
        try:
            with pytest.raises(ValueError, match="draft_steps"):
                serving.GenerativeEngine("geom", target, pol, draft=draft)
        finally:
            target.close()
            draft.close()

    def test_verify_matches_chained_single_steps(self):
        # the ONE batched re-score must equal K chained decode() calls
        cfg = tr.TransformerConfig.tiny()
        model = tr.TransformerGenerativeModel(
            cfg, self.SRC_LEN, num_slots=2, max_decode_len=self.L,
            init_fresh=True, aot_warmup=False, seed=3, speculative_k=3)
        try:
            batch = tr.synthetic_wmt_batch(1, self.SRC_LEN, self.L,
                                           vocab_size=cfg.vocab_size)
            model.prefill(batch["src_ids"], [0])
            # chained reference on slot 0
            tok = np.array([cfg.eos_id], np.int32)
            chain = []
            for t in range(3):
                nxt, _lp, _b = model.decode(tok, [t], [0])
                chain.append(int(nxt[0]))
                tok = nxt
            # fresh slot 1, same prompt: verify the SAME block in one go
            model.prefill(batch["src_ids"], [1])
            blk = np.array([[cfg.eos_id, chain[0], chain[1]]], np.int32)
            toks, logps, _b = model.verify(blk, [0], [1])
            assert list(toks[0]) == chain
            assert np.all(logps <= 0.0)
        finally:
            model.close()


# ---------------------------------------------------------------------------
# Sampling decode: seeded determinism
# ---------------------------------------------------------------------------

class TestSamplingDecode:
    def _decode_seq(self, model, src, steps):
        model.prefill(src[None, :], [0])
        tok = np.array([model.eos_id], np.int32)
        out = []
        for t in range(steps):
            nxt, lp, _b = model.decode(tok, [t], [0])
            out.append(int(nxt[0]))
            assert lp[0] <= 0.0
            tok = nxt
        return out

    def test_fixed_seed_reproduces_across_rebuilds(self):
        cfg = tr.TransformerConfig.tiny()
        sampling = {"temperature": 0.8, "top_k": 8, "top_p": 0.95,
                    "seed": 123}
        batch = tr.synthetic_wmt_batch(1, 8, 8,
                                       vocab_size=cfg.vocab_size)
        runs = []
        for _ in range(2):
            model = tr.TransformerGenerativeModel(
                cfg, 8, num_slots=2, max_decode_len=6, init_fresh=True,
                aot_warmup=False, seed=11, sampling=sampling)
            try:
                runs.append(self._decode_seq(model, batch["src_ids"][0],
                                             5))
            finally:
                model.close()
        assert runs[0] == runs[1]

    def test_top_k_one_is_greedy(self):
        # top_k=1 keeps only the argmax token: the sampled stream must
        # equal greedy decode from the same checkpoint
        cfg = tr.TransformerConfig.tiny()
        tmp = tempfile.mkdtemp(prefix="stf_samp_")
        greedy_model = tr.TransformerGenerativeModel(
            cfg, 8, num_slots=2, max_decode_len=6, init_fresh=True,
            aot_warmup=False, seed=11)
        ckpt = _save_ckpt(greedy_model, tmp)
        batch = tr.synthetic_wmt_batch(1, 8, 8,
                                       vocab_size=cfg.vocab_size)
        src = batch["src_ids"][0]
        greedy = self._decode_seq(greedy_model, src, 5)
        greedy_model.close()
        samp_model = tr.TransformerGenerativeModel(
            cfg, 8, num_slots=2, max_decode_len=6, checkpoint=ckpt,
            aot_warmup=False, sampling={"top_k": 1, "seed": 0})
        try:
            sampled = self._decode_seq(samp_model, src, 5)
        finally:
            samp_model.close()
        assert sampled == greedy

    def test_sample_token_respects_top_k_support(self):
        from simple_tensorflow_tpu.ops import sampling_ops

        stf.set_random_seed(0)
        logits_np = np.zeros((4, 16), np.float32)
        logits_np[:, 3] = 5.0
        logits_np[:, 7] = 4.0
        logits = stf.constant(logits_np)
        tok, logp = sampling_ops.sample_token(
            logits, temperature=1.0, top_k=2, seed=42)
        with stf.Session() as sess:
            for _ in range(5):
                t, lp = sess.run([tok, logp])
                assert set(np.asarray(t).tolist()) <= {3, 7}
                assert np.all(np.asarray(lp) <= 0.0)

    def test_unknown_sampling_knob_rejected(self):
        cfg = tr.TransformerConfig.tiny()
        with pytest.raises(ValueError, match="sampling"):
            tr.build_generative_program(
                cfg, 8, num_slots=2, max_decode_len=6,
                sampling={"nucleus": 0.9})


# ---------------------------------------------------------------------------
# Query-block decode attention (causal_offset)
# ---------------------------------------------------------------------------

class TestBlockDecodeAttentionParity:
    def test_rank4_block_equals_per_position_loop(self):
        B, L, H, D, K = 2, 8, 2, 4, 3
        rng = np.random.RandomState(0)
        q_np = rng.randn(B, K, H, D).astype(np.float32)
        k_np = rng.randn(B, L, H, D).astype(np.float32)
        v_np = rng.randn(B, L, H, D).astype(np.float32)
        len_np = np.array([3, 5], np.int32)   # committed prefix lens
        q4 = stf.placeholder(stf.float32, [B, K, H, D], "q4")
        kc = stf.placeholder(stf.float32, [B, L, H, D], "kc")
        vc = stf.placeholder(stf.float32, [B, L, H, D], "vc")
        ln = stf.placeholder(stf.int32, [B], "ln")
        blk = kvc.decode_attention(q4, kc, vc, ln, causal_offset=True)
        q3 = stf.placeholder(stf.float32, [B, H, D], "q3")
        one = kvc.decode_attention(q3, kc, vc, ln)
        with stf.Session() as sess:
            out_blk = sess.run(blk, {q4: q_np, kc: k_np, vc: v_np,
                                     ln: len_np})
            assert out_blk.shape == (B, K, H, D)
            for j in range(K):
                # block query j sees exactly lengths + j + 1 positions
                ref = sess.run(one, {q3: q_np[:, j], kc: k_np,
                                     vc: v_np, ln: len_np + j + 1})
                np.testing.assert_allclose(out_blk[:, j], ref,
                                           rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# Paged causal LM: parity, CoW divergence, engine end-to-end
# ---------------------------------------------------------------------------

PAGE_LEN, PAGES_PER_SEQ, NUM_PAGES, MAX_LIVE = 4, 4, 16, 4


def _clm_model(cfg, **kw):
    kw.setdefault("init_fresh", True)
    return clm.CausalLMGenerativeModel(
        cfg, page_len=PAGE_LEN, pages_per_seq=PAGES_PER_SEQ,
        num_pages=NUM_PAGES, max_live=MAX_LIVE, aot_warmup=False,
        seed=kw.pop("seed", 11), **kw)


def _naive_causal_greedy(sess, ids_ph, logits_t, prompt, steps, pad_id):
    """Full re-forward per emitted token — the reference stream."""
    L = int(ids_ph.shape[1])
    seq = list(prompt)
    out = []
    for _ in range(steps):
        row = np.full((1, L), pad_id, np.int32)
        row[0, :len(seq)] = seq
        logits = sess.run(logits_t, {ids_ph: row})
        tok = int(np.argmax(logits[0, len(seq) - 1]))
        out.append(tok)
        seq.append(tok)
        if len(seq) >= L:
            break
    return out


class TestPagedCausalLM:
    def _naive_handles(self, cfg, ckpt, L):
        g = stf.Graph()
        with g.as_default():
            ids = stf.placeholder(stf.int32, [1, L], "ids")
            logits = clm.causal_lm_logits(ids, cfg, training=False,
                                          compute_dtype=stf.float32)
            sess = stf.Session(graph=g)
            saver = stf.train.Saver()
            saver.restore(sess, ckpt)
        return sess, ids, logits

    def test_engine_matches_naive_reforward_with_shared_prefixes(self):
        cfg = tr.TransformerConfig.tiny()
        tmp = tempfile.mkdtemp(prefix="stf_clm_")
        model = _clm_model(cfg)
        ckpt = _save_ckpt(model, tmp)
        L = model.max_seq_len
        nsess, ids, logits = self._naive_handles(cfg, ckpt, L)
        rng = np.random.RandomState(4)
        shared = list(rng.randint(2, cfg.vocab_size, 6))
        prompts = [shared + list(rng.randint(2, cfg.vocab_size, 3))
                   for _ in range(4)]
        pol = serving.DecodePolicy(num_slots=MAX_LIVE, max_decode_len=L,
                                   bucket_sizes=[1, MAX_LIVE],
                                   max_new_tokens=5)
        with serving.GenerativeEngine("paged_eng", model, pol) as eng:
            futs = [eng.generate(p, max_new_tokens=5) for p in prompts]
            results = [f.result(timeout=120) for f in futs]
            stats = eng.statusz_info()
            drift = eng._prefix.reconcile([])    # all retired: no private
        model.close()
        try:
            for p, r in zip(prompts, results):
                budget = min(5, L - len(p))
                naive = _naive_causal_greedy(nsess, ids, logits, p,
                                             budget, cfg.pad_id)
                got = list(r["tokens"])
                if r["outcome"] == "eos":
                    assert got == naive[:len(got)]
                else:
                    assert got == naive
        finally:
            nsess.close()
        assert drift == 0
        pc = stats["prefix_cache"]
        # 4 prompts sharing a 6-token prefix: later admissions hit the
        # first one's resident chunk
        assert pc["hit_pages"] >= 3

    def test_cow_divergence_bit_exact(self):
        # B's cached span ends INSIDE A's second page: the tail page is
        # built by KVCachePageCopy (copy_pages) of A's page, then B
        # diverges in place — stream must equal a from-scratch decode
        cfg = tr.TransformerConfig.tiny()
        tmp = tempfile.mkdtemp(prefix="stf_cow_")
        model = _clm_model(cfg)
        ckpt = _save_ckpt(model, tmp)
        L = model.max_seq_len
        nsess, ids, logits = self._naive_handles(cfg, ckpt, L)
        rng = np.random.RandomState(9)
        base = list(rng.randint(2, cfg.vocab_size, 9))
        prompt_a = base                       # cached 8 = 2 full pages
        prompt_b = base[:6] + [int(rng.randint(2, cfg.vocab_size))]
        # cached(B) = base[:6] = page [0:4] hit + tail [4:6], a proper
        # prefix of A's second chunk base[4:8] -> CoW
        pol = serving.DecodePolicy(num_slots=MAX_LIVE, max_decode_len=L,
                                   bucket_sizes=[1, MAX_LIVE],
                                   max_new_tokens=4)
        with serving.GenerativeEngine("cow_eng", model, pol) as eng:
            ra = eng.generate(prompt_a, max_new_tokens=4).result(120)
            rb = eng.generate(prompt_b, max_new_tokens=4).result(120)
            pc = eng.statusz_info()["prefix_cache"]
        model.close()
        try:
            for p, r in zip((prompt_a, prompt_b), (ra, rb)):
                naive = _naive_causal_greedy(nsess, ids, logits, p, 4,
                                             cfg.pad_id)
                got = list(r["tokens"])
                if r["outcome"] == "eos":
                    assert got == naive[:len(got)]
                else:
                    assert got == naive
        finally:
            nsess.close()
        assert pc["cow_hits"] == 1
        assert pc["hit_pages"] >= 1

    def test_churn_reconciles_and_rejects_oversize(self):
        cfg = tr.TransformerConfig.tiny()
        model = _clm_model(cfg)
        L = model.max_seq_len
        rng = np.random.RandomState(7)
        shared = list(rng.randint(2, cfg.vocab_size, 4))
        pol = serving.DecodePolicy(num_slots=MAX_LIVE, max_decode_len=L,
                                   bucket_sizes=[1, MAX_LIVE],
                                   max_new_tokens=3)
        with serving.GenerativeEngine("churn_eng", model, pol) as eng:
            # oversize prompt: leaves no decode position
            from simple_tensorflow_tpu.framework import errors
            bad = eng.generate(list(range(2, 2 + L)))
            with pytest.raises(errors.InvalidArgumentError):
                bad.result(timeout=10)
            # 12 requests over 4 live slots / 16 pages
            prompts = [shared + list(rng.randint(2, cfg.vocab_size,
                                                 1 + (i % 4)))
                       for i in range(12)]
            futs = [eng.generate(p, max_new_tokens=3) for p in prompts]
            results = [f.result(timeout=240) for f in futs]
            drift = eng._prefix.reconcile([])
            stats = eng.statusz_info()
        model.close()
        assert drift == 0
        assert all(r["outcome"] in ("eos", "length") for r in results)
        assert all(len(r["tokens"]) >= 1 for r in results)
        assert stats["prefix_cache"]["hit_pages"] > 0

    def test_prefix_and_spec_metrics_exported(self):
        exported = monitoring.export()
        for name in ("/stf/serving/prefix_cache_hits",
                     "/stf/serving/prefix_cache_evictions",
                     "/stf/serving/prefix_cache_shared_pages",
                     "/stf/serving/spec_proposed_tokens",
                     "/stf/serving/spec_accepted_tokens",
                     "/stf/serving/spec_acceptance_rate_pct"):
            assert name in exported, name
        hits = exported["/stf/serving/prefix_cache_hits"]["cells"]
        assert any(v > 0 for v in hits.values())


# ---------------------------------------------------------------------------
# Lint: shared-page host-sink reachability + unguarded verify writes
# ---------------------------------------------------------------------------

class TestDecode2Lint:
    RULE = ["lint/serving-decode-cache"]

    def test_paged_transitive_host_sink_is_error(self):
        c = kvc.kv_cache("lp1", 2, 4, (2,), stf.float32, paged=True)
        g = c.gather(stf.placeholder(stf.int32, [1], "lp1_s"))
        h = stf.reduce_sum(g)                 # one device hop
        stf.Print(h, [h], "leak:")
        diags = analysis.lint_graph(purpose="serving", rules=self.RULE)
        assert any("shared-page" in d.message and
                   d.severity == "error" for d in diags)

    def test_unpaged_transitive_sink_not_flagged(self):
        # the reachability contract is the PAGED tightening; per-slot
        # caches only error on DIRECT host sinks (fetch derived scalars
        # is the documented idiom)
        c = kvc.kv_cache("lp2", 2, 4, (2,), stf.float32)
        g = c.gather(stf.placeholder(stf.int32, [1], "lp2_s"))
        h = stf.reduce_sum(g)
        stf.Print(h, [h], "ok:")
        diags = analysis.lint_graph(purpose="serving", rules=self.RULE)
        assert not diags

    def test_paged_clean_decode_graph_passes(self):
        c = kvc.kv_cache("lp3", 2, 4, (2,), stf.float32, paged=True)
        g = c.gather(stf.placeholder(stf.int32, [1], "lp3_s"))
        _ = stf.reduce_sum(g)
        assert not analysis.lint_graph(purpose="serving",
                                       rules=self.RULE)

    def test_unguarded_verify_write_is_error(self):
        c = kvc.kv_cache("lv1", 2, 4, (2,), stf.float32)
        val = stf.placeholder(stf.float32, [1, 1, 2], "lv1_v")
        s = stf.constant(np.array([0], np.int32))
        c.append(val, s, s, verify_plan=True)   # refcount_guarded=False
        diags = analysis.lint_graph(purpose="serving", rules=self.RULE)
        assert any("refcount-guarded" in d.message and
                   d.severity == "error" for d in diags)

    def test_guarded_verify_write_passes(self):
        c = kvc.kv_cache("lv2", 2, 4, (2,), stf.float32)
        val = stf.placeholder(stf.float32, [1, 1, 2], "lv2_v")
        s = stf.constant(np.array([0], np.int32))
        c.append(val, s, s, verify_plan=True, refcount_guarded=True)
        assert not analysis.lint_graph(purpose="serving",
                                       rules=self.RULE)

    def test_shipped_verify_programs_lint_clean(self):
        # the transformer VERIFY programs stamp their cache writes
        # refcount_guarded=True: the rule must pass the real thing
        cfg = tr.TransformerConfig.tiny()
        model = tr.TransformerGenerativeModel(
            cfg, 8, num_slots=2, max_decode_len=6, init_fresh=True,
            aot_warmup=False, speculative_k=2)
        try:
            with model.graph.as_default():
                diags = analysis.lint_graph(purpose="serving",
                                            rules=self.RULE)
            assert not [d for d in diags if d.severity == "error"]
        finally:
            model.close()
