"""Op-registry conformance sweep (VERDICT r4 item 4; ref: the 175
kernel_test files under tensorflow/python/kernel_tests/).

Coverage is ENFORCED by enumeration: every name in the op registry must
be either (a) in ``CASES`` — auto-expanded into numeric tests against an
independent numpy oracle over a dtype × rank × degenerate-shape grid,
with a finite-difference gradient check for float ops — or (b) in
``COVERED_ELSEWHERE`` with a ``file::test`` pointer that this module
verifies actually exists. A newly registered op with neither fails
``test_registry_fully_covered``.

Oracle rules: numpy/scipy only (never jax) so the comparison is
independent of the implementation under test. Gradient checks compare
``jax.grad`` of the registered pure_fn against central differences — the
same autodiff path SymbolicGradient lowers through.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pytest

import simple_tensorflow_tpu as stf  # noqa: F401 — registers all ops
# lazily-imported op modules whose registrations must be DETERMINISTIC
# here: whether the enumeration guard sees these ops must not depend on
# which test modules happened to run earlier in the process
import simple_tensorflow_tpu.ops.kv_cache_ops  # noqa: F401,E501 — KVCache*/DecodeAttention
from simple_tensorflow_tpu.framework import op_registry

_HERE = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# case machinery
# ---------------------------------------------------------------------------

@dataclass
class Case:
    """One executable conformance case for an op."""

    inputs: List[np.ndarray]
    oracle: Callable[..., Any]           # numpy fn over the inputs
    attrs: Dict[str, Any] = field(default_factory=dict)
    tol: float = 1e-5
    grad: bool = False                   # finite-difference check input 0
    grad_tol: float = 2e-2
    name: str = ""


def _rng(seed):
    return np.random.RandomState(seed)


_FLOAT_SHAPES = [(7,), (3, 4), (2, 3, 4), (0, 4)]  # incl. degenerate


def _unary_cases(np_fn, dtypes=("float32",), positive=False,
                 lo=-2.0, hi=2.0, grad=True, tol=1e-5,
                 attrs=None) -> List[Case]:
    cases = []
    for di, dt in enumerate(dtypes):
        for si, shape in enumerate(_FLOAT_SHAPES):
            r = _rng(100 * di + si)
            if np.dtype(dt).kind in "fc":
                x = r.uniform(lo, hi, size=shape).astype(dt)
                if positive:
                    x = np.abs(x) + 0.1
            elif dt == "bool":
                x = r.rand(*shape) > 0.5
            else:
                x = r.randint(1 if positive else -5, 6,
                              size=shape).astype(dt)
            g = grad and np.dtype(dt).kind == "f" and x.size > 0
            cases.append(Case([x], np_fn, attrs=dict(attrs or {}),
                              tol=tol, grad=g))
    return cases


def _binary_cases(np_fn, dtypes=("float32",), positive_b=False,
                  grad=True, tol=1e-5, integer_ok=True,
                  shapes=None) -> List[Case]:
    cases = []
    shapes = shapes or [((3, 4), (3, 4)), ((2, 3, 4), (3, 4)),  # broadcast
                        ((5,), ()), ((0, 3), (3,))]
    for di, dt in enumerate(dtypes):
        for si, (sa, sb) in enumerate(shapes):
            r = _rng(200 * di + si)
            if np.dtype(dt).kind in "fc":
                a = r.uniform(-2, 2, size=sa).astype(dt)
                b = r.uniform(-2, 2, size=sb).astype(dt)
            elif dt == "bool":
                a = r.rand(*sa) > 0.5
                b = r.rand(*sb) > 0.5
            else:
                a = r.randint(-5, 6, size=sa).astype(dt)
                b = r.randint(-5, 6, size=sb).astype(dt)
            if positive_b:
                b = (np.abs(b) + 1).astype(dt)
            g = grad and np.dtype(dt).kind == "f" \
                and a.size > 0 and b.size > 0
            cases.append(Case([a, b], np_fn, tol=tol, grad=g))
    return cases


def _reduction_cases(np_fn, dtypes=("float32",), grad=True,
                     tol=1e-5) -> List[Case]:
    cases = []
    for di, dt in enumerate(dtypes):
        r = _rng(300 + di)
        x = r.uniform(0.5, 2.0, size=(3, 4, 5)).astype(dt) \
            if np.dtype(dt).kind == "f" \
            else r.randint(1, 5, size=(3, 4, 5)).astype(dt)
        for axis, keep in [(None, False), (1, False), ((0, 2), True),
                           (-1, False)]:
            def oracle(v, axis=axis, keep=keep):
                return np_fn(v, axis=axis, keepdims=keep)

            g = grad and np.dtype(dt).kind == "f"
            cases.append(Case([x], oracle,
                              attrs={"axis": axis, "keepdims": keep},
                              tol=tol, grad=g))
    return cases


def run_case(op_name: str, case: Case):
    import jax

    od = op_registry.get(op_name)
    assert od.pure_fn is not None, f"{op_name} has no pure_fn"
    with jax.default_device(jax.devices("cpu")[0]):
        got = od.pure_fn(*case.inputs, **case.attrs)
    expected = case.oracle(*case.inputs)
    got_list = list(got) if isinstance(got, (list, tuple)) else [got]
    exp_list = (list(expected) if isinstance(expected, (list, tuple))
                else [expected])
    assert len(got_list) == len(exp_list), (
        f"{op_name}: {len(got_list)} outputs vs oracle {len(exp_list)}")
    for g, e in zip(got_list, exp_list):
        g = np.asarray(g)
        e = np.asarray(e)
        assert g.shape == e.shape, (
            f"{op_name}: shape {g.shape} vs oracle {e.shape}")
        if e.dtype.kind in "fc":
            np.testing.assert_allclose(g.astype(e.dtype), e,
                                       rtol=case.tol, atol=case.tol,
                                       err_msg=op_name)
        else:
            np.testing.assert_array_equal(g, e, err_msg=op_name)

    if case.grad:
        _check_grad(op_name, od, case)


def _check_grad(op_name, od, case):
    """jax.grad of sum(output) wrt input 0 vs central differences."""
    import jax

    x0 = case.inputs[0]
    rest = case.inputs[1:]

    def f(x):
        out = od.pure_fn(x, *rest, **case.attrs)
        out0 = out[0] if isinstance(out, (list, tuple)) else out
        return jax.numpy.sum(out0.astype("float32"))

    with jax.default_device(jax.devices("cpu")[0]):
        sym = np.asarray(jax.grad(f)(x0.astype(np.float32)))
    eps = 1e-3
    flat = x0.astype(np.float64).ravel()
    idxs = (range(flat.size) if flat.size <= 8
            else _rng(7).choice(flat.size, 8, replace=False))
    for i in idxs:
        xp = flat.copy()
        xp[i] += eps
        xm = flat.copy()
        xm[i] -= eps
        fp = float(f(xp.reshape(x0.shape).astype(np.float32)))
        fm = float(f(xm.reshape(x0.shape).astype(np.float32)))
        num = (fp - fm) / (2 * eps)
        scale = max(1.0, abs(num), abs(float(sym.ravel()[i])))
        assert abs(num - float(sym.ravel()[i])) <= case.grad_tol * scale, (
            f"{op_name} grad mismatch at {i}: numeric {num} vs "
            f"symbolic {sym.ravel()[i]}")


# ---------------------------------------------------------------------------
# the case table — numpy/scipy oracles only
# ---------------------------------------------------------------------------

import scipy.linalg as sp_linalg  # noqa: E402  (scipy is a jax dependency)
import scipy.special as sp_special  # noqa: E402

_FI = ("float32", "int32")
_F = ("float32",)
_F2 = ("float32", "float64")
_I = ("int32", "int64")
_B = ("bool",)

CASES: Dict[str, List[Case]] = {}


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


CASES.update({
    # ---- unary, full-domain ----
    "Abs": _unary_cases(np.abs, _FI),
    "Neg": _unary_cases(np.negative, _FI),
    "Sign": _unary_cases(np.sign, _FI, grad=False),
    "Square": _unary_cases(np.square, _FI),
    "Ceil": _unary_cases(np.ceil, _F, grad=False),
    "Floor": _unary_cases(np.floor, _F, grad=False),
    "Rint": _unary_cases(np.rint, _F, grad=False),
    "Round": _unary_cases(np.round, _F, grad=False),
    "Exp": _unary_cases(np.exp, _F),
    "Expm1": _unary_cases(np.expm1, _F),
    "Sin": _unary_cases(np.sin, _F),
    "Cos": _unary_cases(np.cos, _F),
    "Tan": _unary_cases(np.tan, _F, lo=-1.2, hi=1.2),
    "Sinh": _unary_cases(np.sinh, _F),
    "Cosh": _unary_cases(np.cosh, _F),
    "Tanh": _unary_cases(np.tanh, _F),
    "Asin": _unary_cases(np.arcsin, _F, lo=-0.9, hi=0.9),
    "Acos": _unary_cases(np.arccos, _F, lo=-0.9, hi=0.9),
    "Atan": _unary_cases(np.arctan, _F),
    "Asinh": _unary_cases(np.arcsinh, _F),
    "Acosh": _unary_cases(np.arccosh, _F, lo=1.1, hi=3.0),
    "Atanh": _unary_cases(np.arctanh, _F, lo=-0.9, hi=0.9),
    "Sigmoid": _unary_cases(_sigmoid, _F),
    "Erf": _unary_cases(sp_special.erf, _F),
    "Erfc": _unary_cases(sp_special.erfc, _F),
    "Relu": _unary_cases(lambda x: np.maximum(x, 0), _FI),
    "Relu6": _unary_cases(lambda x: np.clip(x, 0, 6), _F),
    "Selu": _unary_cases(
        lambda x: np.where(x > 0, 1.0507009873554805 * x,
                           1.0507009873554805 * 1.6732632423543772
                           * (np.exp(x) - 1)).astype(x.dtype), _F,
        tol=1e-4),
    "Elu": _unary_cases(
        lambda x: np.where(x > 0, x, np.exp(x) - 1).astype(x.dtype), _F),
    "Softplus": _unary_cases(lambda x: np.log1p(np.exp(x)), _F, tol=1e-4),
    "Softsign": _unary_cases(lambda x: x / (1 + np.abs(x)), _F),
    "Swish": _unary_cases(lambda x: x * _sigmoid(x), _F),
    "Gelu": _unary_cases(
        lambda x: 0.5 * x * (1 + sp_special.erf(x / np.sqrt(2.0))), _F,
        tol=2e-3),
    "LeakyRelu": _unary_cases(
        lambda x: np.where(x > 0, x, 0.2 * x).astype(x.dtype), _F),
    "LogicalNot": _unary_cases(np.logical_not, _B, grad=False),
    "Invert": _unary_cases(np.invert, _I, grad=False),
    "OnesLike": _unary_cases(np.ones_like, _FI, grad=False),
    "ZerosLike": _unary_cases(np.zeros_like, _FI, grad=False),
    "Identity": _unary_cases(lambda x: x, _FI),
    "Snapshot": _unary_cases(lambda x: x, _F),
    "StopGradient": _unary_cases(lambda x: x, _F, grad=False),
    "PreventGradient": _unary_cases(lambda x: x, _F, grad=False),
    "Digamma": _unary_cases(sp_special.digamma, _F, positive=True,
                            tol=1e-4),
    "Lgamma": _unary_cases(sp_special.gammaln, _F, positive=True,
                           tol=1e-4),
    # ---- unary, positive-domain ----
    "Log": _unary_cases(np.log, _F, positive=True),
    "Log1p": _unary_cases(np.log1p, _F, positive=True),
    "Sqrt": _unary_cases(np.sqrt, _F, positive=True),
    "Rsqrt": _unary_cases(lambda x: 1.0 / np.sqrt(x), _F, positive=True),
    "Reciprocal": _unary_cases(lambda x: 1.0 / x, _F, positive=True),
    # ---- special-value predicates ----
    "IsFinite": [Case([np.array([1.0, np.inf, -np.inf, np.nan, 0.0],
                                np.float32)], np.isfinite)],
    "IsInf": [Case([np.array([1.0, np.inf, -np.inf, np.nan], np.float32)],
                   np.isinf)],
    "IsNan": [Case([np.array([1.0, np.inf, np.nan, 0.0], np.float32)],
                   np.isnan)],
    # ---- binary ----
    "Add": _binary_cases(np.add, _FI),
    "Sub": _binary_cases(np.subtract, _FI),
    "Mul": _binary_cases(np.multiply, _FI),
    "Div": _binary_cases(np.true_divide, _F, positive_b=True),
    "TrueDiv": _binary_cases(np.true_divide, _F, positive_b=True),
    "RealDiv": _binary_cases(np.true_divide, _F, positive_b=True),
    "FloorDiv": _binary_cases(np.floor_divide, _FI, positive_b=True,
                              grad=False),
    "FloorMod": _binary_cases(np.mod, _FI, positive_b=True, grad=False),
    "Mod": _binary_cases(np.mod, _FI, positive_b=True, grad=False),
    "TruncateDiv": _binary_cases(
        lambda a, b: np.trunc(a / b).astype(a.dtype), _I,
        positive_b=True, grad=False,
        shapes=[((3, 4), (3, 4)), ((5,), (5,))]),
    "TruncateMod": _binary_cases(np.fmod, _I, positive_b=True,
                                 grad=False,
                                 shapes=[((3, 4), (3, 4)), ((5,), (5,))]),
    "Maximum": _binary_cases(np.maximum, _FI),
    "Minimum": _binary_cases(np.minimum, _FI),
    "SquaredDifference": _binary_cases(lambda a, b: (a - b) ** 2, _F),
    "Atan2": _binary_cases(np.arctan2, _F),
    "Xdivy": _binary_cases(
        lambda a, b: np.where(a == 0, 0.0, a / b).astype(a.dtype), _F,
        positive_b=True, grad=False),
    "Xlogy": _binary_cases(
        lambda a, b: np.where(a == 0, 0.0, a * np.log(b)).astype(a.dtype),
        _F, positive_b=True, grad=False),
    "Equal": _binary_cases(np.equal, _FI, grad=False),
    "NotEqual": _binary_cases(np.not_equal, _FI, grad=False),
    "Less": _binary_cases(np.less, _FI, grad=False),
    "LessEqual": _binary_cases(np.less_equal, _FI, grad=False),
    "Greater": _binary_cases(np.greater, _FI, grad=False),
    "GreaterEqual": _binary_cases(np.greater_equal, _FI, grad=False),
    "LogicalAnd": _binary_cases(np.logical_and, _B, grad=False),
    "LogicalOr": _binary_cases(np.logical_or, _B, grad=False),
    "LogicalXor": _binary_cases(np.logical_xor, _B, grad=False),
    "BitwiseAnd": _binary_cases(np.bitwise_and, _I, grad=False),
    "BitwiseOr": _binary_cases(np.bitwise_or, _I, grad=False),
    "BitwiseXor": _binary_cases(np.bitwise_xor, _I, grad=False),
    "ApproximateEqual": [Case(
        [np.array([1.0, 2.0, 3.0], np.float32),
         np.array([1.0000001, 2.5, 3.0], np.float32)],
        lambda a, b: np.abs(a - b) < 1e-5)],
    "Pow": [Case([np.abs(_rng(1).randn(3, 4)).astype(np.float32) + 0.5,
                  _rng(2).uniform(-2, 2, (3, 4)).astype(np.float32)],
                 np.power, grad=True)],
    "LeftShift": [Case([_rng(3).randint(0, 100, (6,)).astype(np.int32),
                        _rng(4).randint(0, 5, (6,)).astype(np.int32)],
                       np.left_shift)],
    "RightShift": [Case([_rng(5).randint(0, 100, (6,)).astype(np.int32),
                         _rng(6).randint(0, 5, (6,)).astype(np.int32)],
                        np.right_shift)],
    "Igamma": [Case([np.abs(_rng(7).randn(5)).astype(np.float32) + 0.5,
                     np.abs(_rng(8).randn(5)).astype(np.float32) + 0.5],
                    sp_special.gammainc, tol=1e-4)],
    "Igammac": [Case([np.abs(_rng(9).randn(5)).astype(np.float32) + 0.5,
                      np.abs(_rng(10).randn(5)).astype(np.float32) + 0.5],
                     sp_special.gammaincc, tol=1e-4)],
    "Zeta": [Case([np.array([2.0, 3.0, 4.0], np.float32),
                   np.array([1.0, 2.0, 3.0], np.float32)],
                  sp_special.zeta, tol=1e-4)],
    "Polygamma": [Case([np.array([1.0, 2.0], np.float32),
                        np.array([2.0, 3.0], np.float32)],
                       sp_special.polygamma, tol=1e-3)],
    "Betainc": [Case([np.array([1.5, 2.0], np.float32),
                      np.array([2.5, 1.0], np.float32),
                      np.array([0.3, 0.7], np.float32)],
                     sp_special.betainc, tol=1e-4)],
    # ---- reductions ----
    "Sum": _reduction_cases(np.sum, _FI),
    "Mean": _reduction_cases(np.mean, _F),
    "Prod": _reduction_cases(np.prod, _F),
    "Max": _reduction_cases(np.max, _FI),
    "Min": _reduction_cases(np.min, _FI),
    "All": _reduction_cases(lambda x, axis=None, keepdims=False:
                            np.all(x > 2, axis=axis, keepdims=keepdims)
                            if False else np.all(x, axis=axis,
                                                 keepdims=keepdims),
                            _B, grad=False),
    "Any": _reduction_cases(np.any, _B, grad=False),
    "LogSumExp": _reduction_cases(sp_special.logsumexp, _F, tol=1e-4),
    "EuclideanNorm": _reduction_cases(
        lambda x, axis=None, keepdims=False:
        np.sqrt(np.sum(np.square(x), axis=axis, keepdims=keepdims)), _F,
        tol=1e-4),
})


def _psd(n, seed):
    a = _rng(seed).randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def _np_segment(np_red, init):
    def oracle(data, ids, num_segments=None):
        n = int(num_segments if num_segments is not None
                else (ids.max() + 1 if ids.size else 0))
        out = np.full((n,) + data.shape[1:], init, data.dtype)
        for i, s in enumerate(ids):
            out[s] = np_red(out[s], data[i])
        return out
    return oracle


def _np_conv2d_valid(x, w):
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    oh, ow = h - kh + 1, wd - kw + 1
    out = np.zeros((n, oh, ow, cout), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, i:i + kh, j:j + kw, :].reshape(n, -1)
            out[:, i, j, :] = patch @ w.reshape(-1, cout)
    return out


def _np_maxpool_valid(x, k):
    n, h, w, c = x.shape
    oh, ow = h // k, w // k
    out = np.zeros((n, oh, ow, c), x.dtype)
    for i in range(oh):
        for j in range(ow):
            out[:, i, j, :] = x[:, i * k:(i + 1) * k,
                                j * k:(j + 1) * k, :].max(axis=(1, 2))
    return out


_x34 = _rng(20).randn(3, 4).astype(np.float32)
_x234 = _rng(21).randn(2, 3, 4).astype(np.float32)
_x345 = _rng(22).randn(3, 4, 5).astype(np.float32)
_ids6 = np.array([0, 0, 1, 2, 2, 2], np.int32)
_data6 = _rng(23).randn(6, 3).astype(np.float32)
_sq33 = _rng(24).randn(3, 3).astype(np.float32)
_img = np.abs(_rng(25).randn(2, 6, 6, 3)).astype(np.float32)
_kern = _rng(26).randn(3, 3, 3, 4).astype(np.float32) * 0.3
_cplx = (_rng(27).randn(4, 8) + 1j * _rng(28).randn(4, 8)) \
    .astype(np.complex64)

CASES.update({
    # ---- shape / array ----
    "Reshape": [Case([_x234], lambda x: x.reshape(4, 6),
                     attrs={"shape": (4, 6)}, grad=True),
                Case([_x234], lambda x: x.reshape(-1),
                     attrs={"shape": (-1,)})],
    "ExpandDims": [Case([_x34], lambda x: x[:, None, :],
                        attrs={"axis": 1}, grad=True)],
    "Squeeze": [Case([_x34[:, None, :]], lambda x: x.squeeze(1),
                     attrs={"axis": 1}),
                Case([_x34[None, :, None]], lambda x: x.squeeze(),
                     attrs={"axis": None})],
    "Transpose": [Case([_x234], lambda x: x.transpose(2, 0, 1),
                       attrs={"perm": (2, 0, 1)}, grad=True),
                  Case([_x34], lambda x: x.T, attrs={"perm": None})],
    "Concat": [Case([_x34, _x34 * 2], lambda a, b:
                    np.concatenate([a, b], 1), attrs={"axis": 1},
                    grad=True)],
    "Pack": [Case([_x34, _x34 * 2], lambda a, b: np.stack([a, b], 1),
                  attrs={"axis": 1}, grad=True)],
    "Unpack": [Case([_x234], lambda x: tuple(np.moveaxis(x, 1, 0)),
                    attrs={"num": 3, "axis": 1})],
    "Split": [Case([_x34], lambda x: tuple(np.split(x, 2, 1)),
                   attrs={"num_or_sections": 2, "axis": 1})],
    "Slice": [Case([_x234], lambda x: x[1:2, 0:2, 1:4],
                   attrs={"begin": (1, 0, 1), "size": (1, 2, 3)},
                   grad=True)],
    "Tile": [Case([_x34], lambda x: np.tile(x, (2, 3)),
                  attrs={"multiples": (2, 3)}, grad=True)],
    "Reverse": [Case([_x234], lambda x: x[:, ::-1, :],
                     attrs={"axis": (1,)}, grad=True)],
    "Fill": [Case([np.float32(2.5)], lambda v: np.full((2, 3), 2.5,
                                                       np.float32),
                  attrs={"dims": (2, 3)})],
    "Range": [Case([np.int32(2), np.int32(10), np.int32(3)],
                   lambda a, b, c: np.arange(2, 10, 3, np.int32))],
    "LinSpace": [Case([np.float32(0.0), np.float32(1.0), np.int32(5)],
                      lambda a, b, n: np.linspace(0, 1, 5,
                                                  dtype=np.float32))],
    "Cast": [Case([_x34], lambda x: x.astype(np.int32),
                  attrs={"dtype": stf.int32}),
             Case([np.array([0, 1, 2], np.int32)],
                  lambda x: x.astype(np.float32),
                  attrs={"dtype": stf.float32})],
    "Bitcast": [Case([np.array([1.0, -2.5], np.float32)],
                     lambda x: x.view(np.int32),
                     attrs={"dtype": stf.int32})],
    "Select": [Case([_x34 > 0, _x34, _x34 * 10],
                    lambda c, a, b: np.where(c, a, b))],
    "ClipByValue": [Case([_x34, np.float32(-0.5), np.float32(0.5)],
                         lambda x, lo, hi: np.clip(x, -0.5, 0.5),
                         grad=True)],
    "Pad": [Case([_x34], lambda x: np.pad(x, ((1, 2), (0, 1))),
                 attrs={"paddings": ((1, 2), (0, 1))}, grad=True),
            Case([_x34], lambda x: np.pad(x, ((1, 1), (1, 1)),
                                          mode="reflect"),
                 attrs={"paddings": ((1, 1), (1, 1)),
                        "mode": "reflect"})],
    "BroadcastTo": [Case([_x34[0]], lambda x: np.broadcast_to(x, (3, 4)),
                         attrs={"shape": (3, 4)})],
    "BroadcastArgs": [Case([np.array([3, 1], np.int32),
                            np.array([1, 4], np.int32)],
                           lambda a, b: np.array([3, 4], np.int32))],
    "Shape": [Case([_x234], lambda x: np.array(x.shape, np.int32))],
    "Size": [Case([_x234], lambda x: np.int32(x.size))],
    "Rank": [Case([_x234], lambda x: np.int32(x.ndim))],
    "InvertPermutation": [Case([np.array([2, 0, 1, 3], np.int32)],
                               lambda p: np.argsort(p).astype(np.int32))],
    "SequenceMask": [Case([np.array([1, 3, 0], np.int32)],
                          lambda ln: np.arange(4) < ln[:, None],
                          attrs={"maxlen": 4})],
    "Rot90": [Case([_x234[..., None]],
                   lambda x: np.rot90(x, axes=(1, 2)), attrs={"k": 1})],
    "OneHot": [Case([np.array([0, 2, 1], np.int32)],
                    lambda i: np.eye(4, dtype=np.float32)[i],
                    attrs={"depth": 4})],
    "Gather": [Case([_x34, np.array([2, 0], np.int32)],
                    lambda p, i: p[i], attrs={"axis": 0}, grad=True),
               Case([_x34, np.array([1, 3, 1], np.int32)],
                    lambda p, i: p[:, [1, 3, 1]], attrs={"axis": 1})],
    "GatherNd": [Case([_x34, np.array([[0, 1], [2, 3]], np.int32)],
                      lambda p, i: p[[0, 2], [1, 3]], grad=True)],
    "ScatterNd": [Case([np.array([[1], [3]], np.int32),
                        np.array([9.0, 8.0], np.float32)],
                       lambda i, u: np.array([0, 9, 0, 8, 0],
                                             np.float32),
                       attrs={"shape": (5,)})],
    "SparseToDense": [Case([np.array([[0, 1], [2, 2]], np.int32),
                            np.array([5.0, 6.0], np.float32)],
                           lambda i, v: np.array(
                               [[0, 5, 0], [0, 0, 0], [0, 0, 6]],
                               np.float32),
                           attrs={"shape": (3, 3)})],
    "DynamicPartition": [Case(
        # static-shape TPU semantics: each partition keeps the full
        # leading dim with non-member rows zero-masked in place
        [_data6, np.array([0, 1, 0, 1, 1, 0], np.int32)],
        lambda d, p: (np.where((p == 0)[:, None], d, 0.0),
                      np.where((p == 1)[:, None], d, 0.0)),
        attrs={"num_partitions": 2})],
    "DynamicStitch": [Case(
        [np.array([0, 2], np.int32), np.array([1, 3], np.int32),
         np.array([[1.0], [3.0]], np.float32),
         np.array([[2.0], [4.0]], np.float32)],
        lambda i1, i2, d1, d2: np.array([[1.], [2.], [3.], [4.]],
                                        np.float32),
        attrs={"n": 2})],
    "StridedSlice": [],  # spec-attr driven; covered via public slicing
    # ---- matmul / linalg ----
    "MatMul": [Case([_x34, _x34.T @ np.eye(3, dtype=np.float32)],
                    lambda a, b: a @ b, grad=True),
               Case([_x34, _x34], lambda a, b: a.T @ b,
                    attrs={"transpose_a": True}),
               Case([_x34, _x34], lambda a, b: a @ b.T,
                    attrs={"transpose_b": True})],
    "BatchMatMul": [Case([_x234, np.moveaxis(_x234, 1, 2)],
                         lambda a, b: a @ b, grad=True)],
    "Einsum": [Case([_x34, _x34.T], lambda a, b: a @ b,
                    attrs={"equation": "ij,jk->ik"}, grad=True)],
    "Tensordot": [Case([_x234, _x345], lambda a, b:
                       np.tensordot(a, b, axes=([2], [1])),
                       attrs={"axes": ((2,), (1,))}, grad=True)],
    "Cross": [Case([_rng(30).randn(4, 3).astype(np.float32),
                    _rng(31).randn(4, 3).astype(np.float32)],
                   np.cross, grad=True)],
    "L2Loss": [Case([_x34], lambda x: np.float32(np.sum(x * x) / 2),
                    grad=True)],
    "Moments": [Case([_x234], lambda x: (x.mean((0, 1)),
                                         x.var((0, 1))),
                     attrs={"axes": (0, 1)})],
    "Diag": [Case([np.array([1.0, 2.0, 3.0], np.float32)],
                  np.diag, grad=True)],
    "DiagPart": [Case([np.diag([1.0, 2.0, 3.0]).astype(np.float32)],
                      np.diag)],
    "MatrixDiag": [Case([_x34], lambda x:
                        np.stack([np.diag(r) for r in x]))],
    "MatrixDiagPart": [Case([_rng(33).randn(2, 3, 3)
                             .astype(np.float32)],
                            lambda x: np.stack([np.diag(m)
                                                for m in x]))],
    "MatrixBandPart": [Case([_sq33], lambda x: np.triu(np.tril(x, 1),
                                                       -1),
                            attrs={"num_lower": 1, "num_upper": 1})],
    "Cholesky": [Case([_psd(4, 40)], np.linalg.cholesky, tol=1e-3,
                      grad=True, grad_tol=5e-2)],
    "MatrixDeterminant": [Case([_psd(3, 41)], np.linalg.det,
                               tol=1e-2, grad=True, grad_tol=5e-2)],
    "LogMatrixDeterminant": [Case(
        [_psd(3, 42)],
        lambda x: (np.float32(np.linalg.slogdet(x)[0]),
                   np.float32(np.linalg.slogdet(x)[1])), tol=1e-3)],
    "MatrixInverse": [Case([_psd(3, 43)], np.linalg.inv, tol=1e-3,
                           grad=True, grad_tol=5e-2)],
    "MatrixSolve": [Case([_psd(3, 44),
                          _rng(45).randn(3, 2).astype(np.float32)],
                         np.linalg.solve, tol=1e-3, grad=True,
                         grad_tol=5e-2)],
    "MatrixExponential": [Case([_sq33 * 0.3], sp_linalg.expm,
                               tol=1e-3, grad=True, grad_tol=5e-2)],
    "SelfAdjointEigV2": [Case(
        [_psd(3, 46)],
        lambda x: (np.linalg.eigvalsh(x),),  # eigenvalues only: vectors
        attrs={"compute_v": False}, tol=1e-3)],
    # ---- FFT family ----
    "FFT": [Case([_cplx], np.fft.fft, tol=1e-3)],
    "IFFT": [Case([_cplx], np.fft.ifft, tol=1e-3)],
    "FFT2D": [Case([_cplx], np.fft.fft2, tol=1e-3)],
    "IFFT2D": [Case([_cplx], np.fft.ifft2, tol=1e-3)],
    "RFFT": [Case([_x34], np.fft.rfft, tol=1e-3)],
    "IRFFT": [Case([_cplx[:, :5]], lambda x: np.fft.irfft(x, 8),
                   tol=1e-3)],
    "RFFT2D": [Case([_x34], np.fft.rfft2, tol=1e-3)],
    # ---- complex parts ----
    "Complex": [Case([_x34, _x34 * 2],
                     lambda re, im: (re + 1j * im).astype(np.complex64))],
    "Real": [Case([_cplx], np.real)],
    "Imag": [Case([_cplx], np.imag)],
    "Conj": [Case([_cplx], np.conj)],
    "Angle": [Case([_cplx], np.angle, tol=1e-4)],
    "ConjugateTranspose": [Case([_cplx], lambda x: np.conj(x.T),
                                attrs={"perm": (1, 0)})],
    # ---- segment / argminmax / search ----
    "ArgMax": [Case([_x34], lambda x: x.argmax(0), attrs={"axis": 0}),
               Case([_x34], lambda x: x.argmax(1), attrs={"axis": 1})],
    "ArgMin": [Case([_x34], lambda x: x.argmin(1), attrs={"axis": 1})],
    "SegmentSum": [Case([_data6, _ids6],
                        _np_segment(np.add, 0.0),
                        attrs={"num_segments": 3}, grad=True)],
    "SegmentMean": [Case([_data6, _ids6], lambda d, i: np.stack(
        [d[i == s].mean(0) for s in range(3)]),
        attrs={"num_segments": 3})],
    "SegmentMax": [Case([_data6, _ids6], lambda d, i: np.stack(
        [d[i == s].max(0) for s in range(3)]),
        attrs={"num_segments": 3})],
    "SegmentMin": [Case([_data6, _ids6], lambda d, i: np.stack(
        [d[i == s].min(0) for s in range(3)]),
        attrs={"num_segments": 3})],
    "SegmentProd": [Case([_data6, _ids6],
                         _np_segment(np.multiply, 1.0),
                         attrs={"num_segments": 3})],
    "UnsortedSegmentSum": [Case(
        [_data6, np.array([2, 0, 1, 0, 2, 1], np.int32)],
        _np_segment(np.add, 0.0), attrs={"num_segments": 3},
        grad=True)],
    "UnsortedSegmentMax": [Case(
        [np.abs(_data6), np.array([1, 0, 1, 0, 1, 0], np.int32)],
        _np_segment(np.maximum, -np.inf), attrs={"num_segments": 2})],
    "UnsortedSegmentMin": [Case(
        [np.abs(_data6), np.array([1, 0, 1, 0, 1, 0], np.int32)],
        _np_segment(np.minimum, np.inf), attrs={"num_segments": 2})],
    "UnsortedSegmentProd": [Case(
        [_data6, np.array([1, 0, 1, 0, 1, 0], np.int32)],
        _np_segment(np.multiply, 1.0), attrs={"num_segments": 2})],
    "TopKV2": [Case([_x34], lambda x: (np.sort(x, 1)[:, ::-1][:, :2],
                                       np.argsort(-x, 1)[:, :2]),
                    attrs={"k": 2})],
    "InTopK": [Case([_x34, np.array([1, 0, 3], np.int32)],
                    lambda p, t: np.array(
                        [t[i] in np.argsort(-p[i])[:2]
                         for i in range(p.shape[0])]),
                    attrs={"k": 2})],
    "Bincount": [Case([np.array([1, 1, 3, 0], np.int32)],
                      lambda a: np.bincount(a, minlength=4)
                      .astype(np.int32), attrs={"size": 4})],
    "HistogramFixedWidth": [Case(
        [np.array([-1.0, 0.1, 0.5, 0.9, 2.0], np.float32),
         np.float32(0.0), np.float32(1.0)],
        lambda v, lo, hi: np.array([1, 1, 1, 2, 0], np.int32)
        if False else np.histogram(
            np.clip(v, 0.0, np.nextafter(np.float32(1.0),
                                         np.float32(0.0))),
            bins=5, range=(0.0, 1.0))[0].astype(np.int32),
        attrs={"nbins": 5})],
    "ConfusionMatrix": [Case(
        [np.array([0, 1, 2, 1], np.int32),
         np.array([0, 2, 2, 1], np.int32)],
        lambda l, p: np.array([[1, 0, 0], [0, 1, 1], [0, 0, 1]]),
        attrs={"num_classes": 3})],
    "Cumsum": [Case([_x34], lambda x: np.cumsum(x, 1),
                    attrs={"axis": 1}, grad=True),
               Case([_x34], lambda x: np.cumsum(x[:, ::-1], 1)[:, ::-1],
                    attrs={"axis": 1, "reverse": True}),
               Case([_x34], lambda x: np.concatenate(
                   [np.zeros((3, 1), np.float32),
                    np.cumsum(x, 1)[:, :-1]], 1),
                   attrs={"axis": 1, "exclusive": True})],
    "Cumprod": [Case([np.abs(_x34) + 0.5],
                     lambda x: np.cumprod(x, 0), attrs={"axis": 0},
                     grad=True)],
    # ---- nn ----
    "BiasAdd": [Case([_x234, np.array([1., 2., 3., 4.], np.float32)],
                     lambda x, b: x + b, grad=True)],
    "Softmax": [Case([_x34], lambda x: sp_special.softmax(x, 1),
                     tol=1e-4, grad=True)],
    "LogSoftmax": [Case([_x34],
                        lambda x: sp_special.log_softmax(x, 1),
                        tol=1e-4, grad=True)],
    "SigmoidCrossEntropyWithLogits": [Case(
        [_x34, (_rng(50).rand(3, 4) > 0.5).astype(np.float32)],
        lambda lo, la: np.maximum(lo, 0) - lo * la
        + np.log1p(np.exp(-np.abs(lo))), tol=1e-4, grad=True)],
    "Conv2D": [Case([_img, _kern], _np_conv2d_valid,
                    attrs={"strides": (1, 1, 1, 1), "padding": "VALID"},
                    tol=1e-3, grad=True)],
    "MaxPool": [Case([_img], lambda x: _np_maxpool_valid(x, 2),
                     attrs={"ksize": (1, 2, 2, 1),
                            "strides": (1, 2, 2, 1),
                            "padding": "VALID"}, grad=True)],
    "AvgPool": [Case([_img], lambda x: x.reshape(2, 3, 2, 3, 2, 3)
                     .mean(axis=(2, 4)),
                     attrs={"ksize": (1, 2, 2, 1),
                            "strides": (1, 2, 2, 1),
                            "padding": "VALID"}, tol=1e-4)],
    "SpaceToDepth": [Case([_img[:, :4, :4, :1]],
                          lambda x: x.reshape(2, 2, 2, 2, 2, 1)
                          .transpose(0, 1, 3, 2, 4, 5)
                          .reshape(2, 2, 2, 4),
                          attrs={"block_size": 2})],
    "DepthToSpace": [Case([_img[:, :2, :2, :].reshape(2, 2, 2, 3)[:, :, :, :2]
                           .reshape(2, 2, 2, 2).astype(np.float32)
                           if False else
                           np.arange(2 * 2 * 2 * 4, dtype=np.float32)
                           .reshape(2, 2, 2, 4)],
                          lambda x: x.reshape(2, 2, 2, 2, 2, 1)
                          .transpose(0, 1, 3, 2, 4, 5)
                          .reshape(2, 4, 4, 1),
                          attrs={"block_size": 2})],
})
COVERED_ELSEWHERE = {
    "AddN": ("test_runtime_cc.py", "add_n"),
    "AdjustBrightness": ("test_image_linalg_sparse.py", "adjust_brightness"),
    "AdjustContrast": ("test_image_linalg_sparse.py", "adjust_contrast"),
    "AllGather": ("test_parallel.py", "all_gather"),
    "AllReduce": ("test_parallel.py", "all_reduce"),
    "AsString": ("test_image_linalg_sparse.py", "as_string"),
    "Assert": ("test_api_parity.py", "assert"),
    "Assign": ("test_graph.py", "assign"),
    "AssignAdd": ("test_graph.py", "assign_add"),
    "AssignSub": ("test_variables.py", "assign_sub"),
    "AxisIndex": ("test_parallel.py", "axis_index"),
    "BarrierClose": ("test_data_flow_structures.py", "BarrierClose"),
    "CentralCrop": ("test_image_linalg_sparse.py", "central_crop"),
    "CholeskySolve": ("test_image_linalg_sparse.py", "cholesky_solve"),
    "ComputeAccidentalHits": ("test_image_linalg_sparse.py", "compute_accidental_hits"),
    "Cond": ("test_control_flow.py", "cond"),
    "Const": ("test_array_ops.py", "const"),
    "Conv3D": ("test_nn_ops.py", "Conv3D"),
    "CropAndResize": ("test_parity_fills.py", "crop_and_resize"),
    "CropToBoundingBox": ("test_image_linalg_sparse.py", "crop_to_bounding_box"),
    "DecodeImage": ("test_image_linalg_sparse.py", "decode_image"),
    "DecodeJpeg": ("test_image_linalg_sparse.py", "decode_jpeg"),
    "DecodePng": ("test_image_linalg_sparse.py", "decode_png"),
    "DeleteSessionTensor": ("test_session_handles.py", "delete_session_tensor"),
    "Dequantize": ("test_quantization_ops.py", "dequantize"),
    "Dropout": ("test_byte_budget.py", "dropout"),
    "EditDistance": ("test_array_ops.py", "edit_distance"),
    "EncodeJpeg": ("test_image_linalg_sparse.py", "encode_jpeg"),
    "EncodePng": ("test_image_linalg_sparse.py", "encode_png"),
    "FakeQuantWithMinMaxArgs": ("test_quantization_ops.py", "fake_quant_with_min_max_args"),
    "FakeQuantWithMinMaxVars": ("test_quantization_ops.py", "fake_quant_with_min_max_vars"),
    "FakeQuantWithMinMaxVarsPerChannel": ("test_quantization_ops.py", "fake_quant_with_min_max_vars_per_channel"),
    "FlashAttention": ("test_models.py", "flash_attention"),
    "FlashAttentionDropout": ("test_models.py", "FlashAttentionDropout"),
    "FlipLeftRight": ("test_image_linalg_sparse.py", "flip_left_right"),
    "FlipUpDown": ("test_image_linalg_sparse.py", "flip_up_down"),
    "Foldl": ("test_control_flow.py", "foldl"),
    "FusedBatchNorm": ("test_cost_model.py", "FusedBatchNorm"),
    "FusedAdamUpdate": ("test_kernel_registry.py", "FusedAdamUpdate"),
    "FusedDropoutBiasResidual": ("test_kernel_registry.py",
                                 "FusedDropoutBiasResidual"),
    "FusedLayerNorm": ("test_pallas_kernels.py", "FusedLayerNorm"),
    "FusedMomentumUpdate": ("test_kernel_registry.py",
                            "FusedMomentumUpdate"),
    "FusedSoftmaxXent": ("test_pallas_kernels.py", "FusedSoftmaxXent"),
    "GetSessionHandle": ("test_session_handles.py", "get_session_handle"),
    "GetSessionTensor": ("test_session_handles.py", "get_session_tensor"),
    "Group": ("test_api_parity.py", "group"),
    "HistogramSummary": ("test_summary.py", "histogram_summary"),
    "IsVariableInitialized": ("test_variables.py", "is_variable_initialized"),
    "IteratorGetNext": ("test_data.py", "iterator_get_next"),
    "LookupTableFind": ("test_lookup_ops.py", "LookupTableFind"),
    "LookupTableFindDevice": ("test_lookup_ops.py", "LookupTableFindDevice"),
    "MapFn": ("test_control_flow.py", "map_fn"),
    "MatchingFiles": ("test_io_ops.py", "matching_files"),
    "MatrixSolveLs": ("test_parity_fills.py", "matrix_solve_ls"),
    "MatrixTriangularSolve": ("test_image_linalg_sparse.py", "matrix_triangular_solve"),
    "MaxPoolWithArgmax": ("test_parity_fills.py", "max_pool_with_argmax"),
    "Multinomial": ("test_image_linalg_sparse.py", "multinomial"),
    "NoOp": ("test_runtime_cc.py", "NoOp"),
    "NonMaxSuppression": ("test_parity_fills.py", "non_max_suppression"),
    "ParseExample": ("test_data.py", "parse_example"),
    "ParseTensor": ("test_array_ops.py", "parse_tensor"),
    "PerImageStandardization": ("test_image_linalg_sparse.py", "per_image_standardization"),
    "Pipeline": ("test_byte_budget.py", "pipeline"),
    "PipelineTrain": ("test_cost_model.py", "pipeline_train"),
    "Placeholder": ("test_array_ops.py", "placeholder"),
    "Print": ("test_cost_model.py", "print"),
    "PyFunc": ("test_control_flow.py", "py_func"),
    "Qr": ("test_image_linalg_sparse.py", "qr"),
    "QuantMatMul": ("test_pallas_kernels.py", "QuantMatMul"),
    "QuantizeV2": ("test_quantization_ops.py", "quantize_v2"),
    "RandomShuffle": ("test_image_linalg_sparse.py", "random_shuffle"),
    "RandomUniform": ("test_image_linalg_sparse.py", "random_uniform"),
    "ReadFile": ("test_io_ops.py", "read_file"),
    "ReadVariable": ("test_tools.py", "ReadVariable"),
    "ReaderRead": ("test_io_ops.py", "reader_read"),
    "ReaderReadUpTo": ("test_io_ops.py", "reader_read_up_to"),
    "RecomputeGradCall": ("test_framework_extras.py", "RecomputeGradCall"),
    "ReduceScatter": ("test_parallel.py", "reduce_scatter"),
    "ReportUninitialized": ("test_variables.py", "report_uninitialized"),
    "ResizeBilinear": ("test_image_linalg_sparse.py", "resize_bilinear"),
    "ResizeImages": ("test_image_linalg_sparse.py", "resize_images"),
    "ResizeNearestNeighbor": ("test_image_linalg_sparse.py", "resize_nearest_neighbor"),
    "RingAttention": ("test_ring_attention.py", "ring_attention"),
    "SampleDistortedBoundingBox": ("test_image_linalg_sparse.py", "sample_distorted_bounding_box"),
    "ScalarSummary": ("test_summary.py", "scalar_summary"),
    "Scan": ("test_control_flow.py", "scan"),
    "ScatterAdd": ("test_variables.py", "scatter_add"),
    "ScatterUpdate": ("test_variables.py", "scatter_update"),
    "SdcaFprint": ("test_sdca_ops.py", "sdca_fprint"),
    "SdcaOptimizer": ("test_sdca_ops.py", "sdca_optimizer"),
    "SdcaShrinkL1": ("test_sdca_ops.py", "sdca_shrink_l1"),
    "SerializeTensor": ("test_parity_fills.py", "serialize_tensor"),
    "ShardMap": ("test_models.py", "shard_map"),
    "SoftmaxCrossEntropyWithLogits": ("test_lookup_ops.py", "softmax_cross_entropy_with_logits"),
    "SparseSegmentSum": ("test_parity_fills.py", "sparse_segment_sum"),
    "SparseSoftmaxCrossEntropyWithLogits": ("test_lookup_ops.py", "sparse_softmax_cross_entropy_with_logits"),
    "Stage": ("test_cost_model.py", "stage"),
    "StringJoin": ("test_image_linalg_sparse.py", "string_join"),
    "StringLength": ("test_image_linalg_sparse.py", "string_length"),
    "StringUpper": ("test_image_linalg_sparse.py", "string_upper"),
    "Substr": ("test_dtype_hygiene.py", "substr"),
    "Svd": ("test_image_linalg_sparse.py", "svd"),
    "TruncatedNormal": ("test_image_linalg_sparse.py", "truncated_normal"),
    "VariableV2": ("test_tools.py", "VariableV2"),
    "While": ("test_control_flow.py", "while"),
    "WriteFile": ("test_io_ops.py", "write_file"),
}


# ---- second-wave cases for ops the auto-matcher couldn't place ----------

def _np_pool3d(x, k, red):
    n, d, h, w, c = x.shape
    out = np.zeros((n, d // k, h // k, w // k, c), x.dtype)
    for a in range(d // k):
        for b in range(h // k):
            for e in range(w // k):
                out[:, a, b, e, :] = red(
                    x[:, a * k:(a + 1) * k, b * k:(b + 1) * k,
                      e * k:(e + 1) * k, :], (1, 2, 3))
    return out


_vol = _rng(60).randn(1, 4, 4, 4, 2).astype(np.float32)
_x3344 = _rng(61).randn(2, 3, 3).astype(np.float32)


def _ctc_dense_oracle(logits, labels):
    """Brute-force CTC loss: enumerate all T-length paths, sum those
    collapsing to the label (blank=0)."""
    T, C = logits.shape
    probs = sp_special.softmax(logits, axis=-1)
    import itertools

    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        collapsed = []
        prev = None
        for s in path:
            if s != prev and s != 0:
                collapsed.append(s)
            prev = s
        if collapsed == list(labels):
            p = 1.0
            for t, s in enumerate(path):
                p *= probs[t, s]
            total += p
    return np.float32(-np.log(total))


CASES.update({
    "AddN": [Case([_x34, _x34 * 2, _x34 * 3],
                  lambda a, b, c2: a + b + c2, grad=True)],
    "ReverseSequence": [Case(
        [_x34, np.array([2, 4, 1], np.int32)],
        lambda x, ln: np.stack([np.concatenate(
            [row[:n][::-1], row[n:]]) for row, n in zip(x, ln)]),
        attrs={"seq_axis": 1, "batch_axis": 0})],
    "SegmentSumStatic": [Case(
        [_data6, _ids6], _np_segment(np.add, 0.0),
        attrs={"n_segments": 3})],
    "MaxPool3D": [Case([_vol], lambda x: _np_pool3d(x, 2, np.max),
                       attrs={"ksize": (1, 2, 2, 2, 1),
                              "strides": (1, 2, 2, 2, 1),
                              "padding": "VALID"})],
    "AvgPool3D": [Case([_vol], lambda x: _np_pool3d(x, 2, np.mean),
                       attrs={"ksize": (1, 2, 2, 2, 1),
                              "strides": (1, 2, 2, 2, 1),
                              "padding": "VALID"}, tol=1e-4)],
    "MatrixSetDiag": [Case(
        [_x3344, np.array([[9., 8., 7.], [6., 5., 4.]], np.float32)],
        lambda x, d: np.stack([m - np.diag(np.diag(m)) + np.diag(dv)
                               for m, dv in zip(x, d)]))],
    "FFT3D": [Case([(_rng(62).randn(2, 4, 4) + 1j
                     * _rng(63).randn(2, 4, 4)).astype(np.complex64)],
                   lambda x: np.fft.fftn(x, axes=(-3, -2, -1)),
                   tol=1e-3)],
    "IFFT3D": [Case([(_rng(64).randn(2, 4, 4) + 1j
                      * _rng(65).randn(2, 4, 4)).astype(np.complex64)],
                    lambda x: np.fft.ifftn(x, axes=(-3, -2, -1)),
                    tol=1e-3)],
    "RFFT3D": [Case([_rng(66).randn(2, 4, 4).astype(np.float32)],
                    lambda x: np.fft.rfftn(x, axes=(-3, -2, -1)),
                    tol=1e-3)],
    "IRFFT2D": [Case([(_rng(67).randn(4, 5) + 1j
                       * _rng(68).randn(4, 5)).astype(np.complex64)],
                     lambda x: np.fft.irfft2(x, s=(4, 8)), tol=1e-3)],
    "IRFFT3D": [Case([(_rng(69).randn(2, 4, 3) + 1j
                       * _rng(70).randn(2, 4, 3)).astype(np.complex64)],
                     lambda x: np.fft.irfftn(x, s=(2, 4, 4),
                                             axes=(-3, -2, -1)),
                     tol=1e-3)],
    "CholeskySolve": [Case(
        [np.linalg.cholesky(_psd(3, 71)).astype(np.float32),
         _rng(72).randn(3, 2).astype(np.float32)],
        lambda l, rhs: np.linalg.solve(l @ l.T, rhs), tol=1e-3,
        grad=True, grad_tol=5e-2)],
    "ConvertImageDtype": [Case(
        [np.array([[0, 128, 255]], np.uint8)],
        lambda x: (x / 255.0).astype(np.float32),
        attrs={"dtype": stf.float32}, tol=1e-6)],
    "GrayscaleToRGB": [Case(
        [np.abs(_rng(73).randn(2, 3, 3, 1)).astype(np.float32)],
        lambda x: np.repeat(x, 3, axis=-1))],
    "RGBToGrayscale": [Case(
        [np.abs(_rng(74).randn(2, 3, 3, 3)).astype(np.float32)],
        lambda x: (x @ np.array([0.2989, 0.587, 0.114],
                                np.float32))[..., None], tol=1e-4)],
    "PadToBoundingBox": [Case(
        [np.ones((1, 2, 2, 1), np.float32)],
        lambda x: np.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0))),
        attrs={"offset_height": 1, "offset_width": 1,
               "target_height": 4, "target_width": 4})],
    "ExtractImagePatches": [Case(
        [np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)],
        lambda x: np.stack(
            [[np.concatenate([x[0, i:i + 2, j:j + 2, 0].ravel()])
              for j in range(3)] for i in range(3)])[None],
        attrs={"ksizes": (1, 2, 2, 1), "strides": (1, 1, 1, 1),
               "rates": (1, 1, 1, 1), "padding": "VALID"})],
    "CTCLossDense": [Case(
        # logits are TIME-major [T, B, C] (ctc_ops.py:28)
        [_rng(75).randn(3, 4).astype(np.float32)[:, None, :],
         np.array([[2, 1]], np.int32)],
        lambda lo, la: _ctc_dense_oracle(lo[:, 0, :], la[0])[None],
        tol=1e-4)],
    "CTCGreedyDecode": [Case(
        # returns the raw per-frame argmax path [T, B]; blank/repeat
        # collapse happens in the ctc_greedy_decoder wrapper
        [np.log(np.array(
            [[[.1, .8, .05, .05], [.1, .8, .05, .05],
              [.7, .1, .1, .1], [.05, .05, .8, .1]]], np.float32)
            .transpose(1, 0, 2)),
         np.array([4], np.int32)],
        lambda lo, sl: np.array([[1], [1], [0], [2]], np.int32),
        attrs={"merge_repeated": True})],
})


# ---- hand-assigned pointers (markers verified by the coverage test) -----

COVERED_ELSEWHERE.update({
    "HSVToRGB": ("test_image_linalg_sparse.py", "hsv_to_rgb"),
    "RGBToHSV": ("test_image_linalg_sparse.py", "rgb_to_hsv"),
    "ResizeBilinear": ("test_image_linalg_sparse.py", "resize_"),
    "ResizeImages": ("test_image_linalg_sparse.py", "resize_"),
    "ResizeNearestNeighbor": ("test_image_linalg_sparse.py", "resize_"),
    "Conv3D": ("test_nn_ops.py", "conv3d"),
    "Conv3DBackpropInput": ("test_nn_ops.py", "conv3d"),
    "DepthwiseConv2dNative": ("test_nn_ops.py", "depthwise"),
    "Dilation2D": ("test_nn_ops.py", "dilation2d"),
    "Erosion2D": ("test_nn_ops.py", "erosion2d"),
    "LRN": ("test_nn_ops.py", "lrn"),
    "FakeQuantWithMinMaxArgs": ("test_quantization_ops.py", "fake_quant"),
    "FakeQuantWithMinMaxVars": ("test_quantization_ops.py", "fake_quant"),
    "FakeQuantWithMinMaxVarsPerChannel": ("test_quantization_ops.py",
                                          "fake_quant"),
    "FakeQuantArgsGrad": ("test_quantization_ops.py", "fake_quant"),
    "FakeQuantPerChannelGrad": ("test_quantization_ops.py", "fake_quant"),
    "FakeQuantVarsGrad": ("test_quantization_ops.py", "fake_quant"),
    "QuantizeV2": ("test_quantization_ops.py", "quantize"),
    "ReaderNumRecordsProduced": ("test_io_ops.py", "reader_"),
    "ReaderNumWorkUnitsCompleted": ("test_io_ops.py", "reader_"),
    "ReaderReset": ("test_io_ops.py", "reader_"),
    "QueueClose": ("test_io_ops.py", "queue_"),
    "QueueDequeue": ("test_io_ops.py", "queue_"),
    "QueueDequeueMany": ("test_io_ops.py", "queue_"),
    "QueueEnqueue": ("test_io_ops.py", "queue_"),
    "QueueEnqueueMany": ("test_io_ops.py", "queue_"),
    "QueueEnqueueMaybe": ("test_io_ops.py", "queue_"),
    "QueueSize": ("test_io_ops.py", "queue_"),
    "ScatterDiv": ("test_variables.py", "scatter_"),
    "ScatterMax": ("test_variables.py", "scatter_"),
    "ScatterMin": ("test_variables.py", "scatter_"),
    "ScatterMul": ("test_variables.py", "scatter_"),
    "ScatterSub": ("test_variables.py", "scatter_"),
    "ScatterNdAdd": ("test_variables.py", "scatter_"),
    "ScatterNdSub": ("test_variables.py", "scatter_"),
    "ScatterNdUpdate": ("test_variables.py", "scatter_"),
    "TensorArrayRead": ("test_framework_extras.py", "tensor_array"),
    "TensorArrayScatter": ("test_framework_extras.py", "tensor_array"),
    "TensorArrayWrite": ("test_framework_extras.py", "tensor_array"),
    "AccumulatorApplyGradient": ("test_data_flow_structures.py",
                                 "TestConditionalAccumulator"),
    "AccumulatorNumAccumulated": ("test_data_flow_structures.py",
                                  "TestConditionalAccumulator"),
    "AccumulatorSetGlobalStep": ("test_data_flow_structures.py",
                                 "TestConditionalAccumulator"),
    "AccumulatorTakeGradient": ("test_data_flow_structures.py",
                                "TestConditionalAccumulator"),
    "SparseAccumulatorApplyGradient": ("test_data_flow_structures.py",
                                       "accumulator"),
    "SparseAccumulatorNumAccumulated": ("test_data_flow_structures.py",
                                        "accumulator"),
    "SparseAccumulatorSetGlobalStep": ("test_data_flow_structures.py",
                                       "accumulator"),
    "SparseAccumulatorTakeGradient": ("test_data_flow_structures.py",
                                      "accumulator"),
    "UlyssesAttention": ("test_ring_attention.py", "ulysses"),
    "SymbolicHessian": ("test_parity_fills.py", "hessian"),
    "SymbolicGradient": ("test_math_ops.py", "stf.gradients"),
    "MatrixSolveLs": ("test_parity_fills.py", "matrix_solve_ls"),
    "MatrixTriangularSolve": ("test_image_linalg_sparse.py",
                              "matrix_triangular"),
    "Qr": ("test_image_linalg_sparse.py", "qr_"),
    "Svd": ("test_image_linalg_sparse.py", "svd"),
    "Multinomial": ("test_image_linalg_sparse.py", "multinomial"),
    "RandomShuffle": ("test_image_linalg_sparse.py", "random_shuffle"),
    "RandomStandardNormal": ("test_image_linalg_sparse.py",
                             "random_normal"),
    "RandomUniform": ("test_image_linalg_sparse.py", "random_uniform"),
    "TruncatedNormal": ("test_image_linalg_sparse.py",
                        "truncated_normal"),
    "PerImageStandardization": ("test_image_linalg_sparse.py",
                                "per_image"),
    "SparseSegmentSum": ("test_parity_fills.py", "sparse_segment"),
    "SparseSegmentValueTransform": ("test_parity_fills.py",
                                    "sparse_segment"),
    "LookupTableExport": ("test_lookup_ops.py", "lookup_table"),
    "LookupTableInsert": ("test_lookup_ops.py", "lookup_table"),
    "LookupTableSize": ("test_lookup_ops.py", "lookup_table"),
    "InitializeTable": ("test_lookup_ops.py", "lookup_table"),
    "IteratorInit": ("test_data.py", "iterator"),
    "EditDistance": ("test_array_ops.py", "edit_distance"),
    "ReportUninitialized": ("test_variables.py", "report_uninitialized"),
    "DecodeCSV": ("test_parity_fills.py", "decode_csv"),
    "NonMaxSuppression": ("test_parity_fills.py", "non_max"),
    "ComputeAccidentalHits": ("test_image_linalg_sparse.py",
                              "compute_accidental"),
    "SampleDistortedBoundingBox": ("test_image_linalg_sparse.py",
                                   "sample_distorted"),
    "EncodePng": ("test_image_linalg_sparse.py", "encode_png"),
    "DecodePng": ("test_image_linalg_sparse.py", "decode_png"),
    "DecodeJpeg": ("test_image_linalg_sparse.py", "decode_jpeg"),
    "RecomputeGradCall": ("test_example_end_to_end.py", "recompute"),
    "Pipeline": ("test_parallel.py", "pipeline"),
    "PipelineTrain": ("test_parallel.py", "pipeline"),
    "ScalarSummary": ("test_summary.py", "scalar_summary"),
    "MergeSummary": ("test_summary.py", "merge_all"),
    "ImageSummary": ("test_summary.py", "summary.image"),
    "MaxPoolWithArgmax": ("test_parity_fills.py", "with_argmax"),
    "PoolV2": ("test_nn_ops.py", "pool"),
    "StringLength": ("test_image_linalg_sparse.py", "string_length"),
    "StringJoin": ("test_image_linalg_sparse.py", "string_join"),
    "AsString": ("test_image_linalg_sparse.py", "as_string"),
})

COVERED_ELSEWHERE.update({
    # generative decode substrate (ISSUE 12): cache-op conformance
    # (alloc reset, multi-position append, gather layout, effects
    # ordering) and decode-attention parity both live in
    # tests/test_generative.py
    "KVCacheAlloc": ("test_generative.py", "KVCache"),
    "KVCacheAppend": ("test_generative.py", "KVCache"),
    "KVCacheGather": ("test_generative.py", "KVCache"),
    "KVCachePageCopy": ("test_decode2.py", "copy_pages"),
    "DecodeAttention": ("test_generative.py", "decode_attention"),
    "BarrierIncompleteSize": ("test_data_flow_structures.py", "Barrier"),
    "BarrierInsertMany": ("test_data_flow_structures.py", "Barrier"),
    "BarrierReadySize": ("test_data_flow_structures.py", "Barrier"),
    "BarrierTakeMany": ("test_data_flow_structures.py", "Barrier"),
    "StagingSize": ("test_data_flow_structures.py", "StagingArea"),
    "Unstage": ("test_data_flow_structures.py", "StagingArea"),
    "RecordInputYield": ("test_data_flow_structures.py", "RecordInput"),
    "FuncArg": ("test_framework_extras.py", "Defun"),
    "GraphFunctionCall": ("test_framework_extras.py", "Defun"),
    "CapturedInput": ("test_framework_extras.py", "Defun"),
    "DecodeGif": ("test_image_linalg_sparse.py", "decode_image"),
    "BatchToSpaceND": ("test_array_ops.py", "batch_to_space"),
    "SpaceToBatchND": ("test_array_ops.py", "space_to_batch"),
    "CTCBeamSearch": ("test_parity_fills.py", "ctc"),
    "CollectivePermute": ("test_parallel.py", "ppermute"),
})

COVERED_ELSEWHERE.update({
    # numerics-health plane (ISSUE 17): packed-stat semantics (nonfinite
    # count, finite max_abs, l2, zero fraction) and the device-side
    # histogram bucketization (fused-window no-split + event round trip)
    # live in tests/test_numerics_health.py
    "NumericSummary": ("test_numerics_health.py", "NumericSummary"),
    "HistogramBucketCounts": ("test_numerics_health.py", "histogram"),
})

COVERED_ELSEWHERE.update({
    # fused sharded-embedding path (ISSUE 19): forward exactness vs the
    # dense-gather reference and the scatter-add backward through
    # stf.gradients (single-device AND real ep=8 mesh) live in
    # tests/test_embedding_fused.py; LookupTableSizeDevice is the
    # frozen-table size() fast path driven by every table.size() call
    # in tests/test_lookup_ops.py
    "EmbeddingLookupFused": ("test_embedding_fused.py",
                             "embedding_lookup_fused"),
    "EmbeddingScatterAddGrad": ("test_embedding_fused.py",
                                "stf.gradients"),
    "LookupTableSizeDevice": ("test_lookup_ops.py", "table.size()"),
})


# ---------------------------------------------------------------------------
# MISC: direct mini-tests for everything the table and pointers don't
# reach — each runs the op for real (Session or pure fn) with a
# non-vacuous assertion.
# ---------------------------------------------------------------------------

def _sess_run(build, feed=None):
    stf.reset_default_graph()
    out = build()
    sess = stf.Session()
    return sess.run(out, feed_dict=feed or {})


def _misc_adjust_hue():
    import colorsys

    from simple_tensorflow_tpu.framework import op_registry as reg

    img = np.abs(_rng(80).rand(1, 2, 2, 3)).astype(np.float32)
    for op, delta in (("AdjustHue", 0.2), ("AdjustHueDyn",
                                           np.float32(0.2))):
        if op == "AdjustHue":
            got = np.asarray(reg.get(op).pure_fn(img, delta=0.2))
        else:
            got = np.asarray(reg.get(op).pure_fn(img, np.float32(0.2)))
        exp = np.zeros_like(img)
        for i in range(2):
            for j in range(2):
                h, s, v = colorsys.rgb_to_hsv(*img[0, i, j])
                exp[0, i, j] = colorsys.hsv_to_rgb((h + 0.2) % 1.0, s, v)
        np.testing.assert_allclose(got, exp, atol=1e-3)


def _misc_adjust_saturation():
    import colorsys

    from simple_tensorflow_tpu.framework import op_registry as reg

    img = np.abs(_rng(81).rand(1, 2, 2, 3)).astype(np.float32)
    for op in ("AdjustSaturation", "AdjustSaturationDyn"):
        if op == "AdjustSaturation":
            got = np.asarray(reg.get(op).pure_fn(img, factor=0.5))
        else:
            got = np.asarray(reg.get(op).pure_fn(img, np.float32(0.5)))
        exp = np.zeros_like(img)
        for i in range(2):
            for j in range(2):
                h, s, v = colorsys.rgb_to_hsv(*img[0, i, j])
                exp[0, i, j] = colorsys.hsv_to_rgb(h, s * 0.5, v)
        np.testing.assert_allclose(got, exp, atol=1e-3)


def _misc_set_ops():
    from simple_tensorflow_tpu.framework import op_registry as reg

    a = np.array([[1, 2, 2, 3]], np.int32)
    b = np.array([[2, 3, 5, 0]], np.int32)
    inter = reg.get("SetIntersection").pure_fn(a, b)
    union = reg.get("SetUnion").pure_fn(a, b)
    diff = reg.get("SetDifference").pure_fn(a, b)
    size = reg.get("SetSize").pure_fn(a)

    def dense_row(res):
        arr = np.asarray(res[0] if isinstance(res, (list, tuple))
                         else res).ravel()
        return sorted(int(v) for v in arr if v >= 0)

    assert dense_row(inter) == [2, 3], inter
    assert set(dense_row(union)) == {0, 1, 2, 3, 5}, union
    assert dense_row(diff) == [1], diff
    assert int(np.asarray(size).ravel()[0]) == 3, size


def _misc_conv2d_backprop_input():
    from simple_tensorflow_tpu.framework import op_registry as reg

    # dgrad == numerical d(sum(conv))/dx against the Conv2D oracle
    x = _rng(82).randn(1, 4, 4, 1).astype(np.float32)
    w = _rng(83).randn(2, 2, 1, 1).astype(np.float32)
    dy = np.ones((1, 3, 3, 1), np.float32)
    got = np.asarray(reg.get("Conv2DBackpropInput").pure_fn(
        dy, w, output_shape=(1, 4, 4, 1), strides=(1, 1, 1, 1),
        padding="VALID"))
    eps = 1e-2
    num = np.zeros_like(x)
    for i in range(4):
        for j in range(4):
            xp = x.copy()
            xp[0, i, j, 0] += eps
            xm = x.copy()
            xm[0, i, j, 0] -= eps
            num[0, i, j, 0] = (_np_conv2d_valid(xp, w).sum()
                               - _np_conv2d_valid(xm, w).sum()) / (2 * eps)
    np.testing.assert_allclose(got, num, atol=1e-2)


def _misc_cholesky_grad():
    from simple_tensorflow_tpu.framework import op_registry as reg

    a = _psd(3, 84)
    l = np.linalg.cholesky(a).astype(np.float32)
    gbar = np.tril(_rng(85).randn(3, 3)).astype(np.float32)
    got = np.asarray(reg.get("CholeskyGrad").pure_fn(l, gbar))
    # numeric: d sum(tril(chol(A)) * gbar) / dA (symmetric perturbation)
    eps = 1e-3
    num = np.zeros((3, 3), np.float64)
    for i in range(3):
        for j in range(3):
            ap = a.astype(np.float64).copy()
            ap[i, j] += eps / 2
            ap[j, i] += eps / 2
            am = a.astype(np.float64).copy()
            am[i, j] -= eps / 2
            am[j, i] -= eps / 2
            fp = (np.linalg.cholesky(ap) * gbar).sum()
            fm = (np.linalg.cholesky(am) * gbar).sum()
            num[i, j] = (fp - fm) / eps
    # impl returns the symmetrized gradient G (TF convention); the
    # symmetric central difference above measures dF under
    # dS = eps*(E_ij+E_ji), i.e. 2*G everywhere
    np.testing.assert_allclose(2.0 * got, num, atol=5e-2)


def _misc_embedding_lookup_mixed():
    from simple_tensorflow_tpu.framework import op_registry as reg

    table = _rng(86).randn(10, 4).astype(np.float32)
    ids = np.array([3, 0, 7], np.int32)
    got = np.asarray(reg.get("EmbeddingLookupMixed").pure_fn(
        table, ids, stf.bfloat16))
    assert got.dtype == np.dtype("bfloat16") or str(got.dtype) == "bfloat16"
    np.testing.assert_allclose(got.astype(np.float32),
                               table[ids].astype("bfloat16")
                               .astype(np.float32))


def _misc_extract_glimpse():
    from simple_tensorflow_tpu.framework import op_registry as reg

    img = np.arange(36, dtype=np.float32).reshape(1, 6, 6, 1)
    got = np.asarray(reg.get("ExtractGlimpse").pure_fn(
        img, np.zeros((1, 2), np.float32), size=(2, 2), centered=True,
        normalized=True))
    np.testing.assert_allclose(got[0, :, :, 0], img[0, 2:4, 2:4, 0])


def _misc_draw_bounding_boxes():
    from simple_tensorflow_tpu.framework import op_registry as reg

    img = np.zeros((1, 6, 6, 3), np.float32)
    boxes = np.array([[[0.0, 0.0, 0.5, 0.5]]], np.float32)
    got = np.asarray(reg.get("DrawBoundingBoxes").pure_fn(img, boxes))
    assert got.shape == img.shape
    assert got.max() > 0, "box was not drawn"
    assert got[0, 5, 5].max() == 0, "pixel outside the box changed"


def _misc_placeholder_with_default():
    v = _sess_run(lambda: stf.placeholder_with_default(
        np.float32(7.0), shape=[], name="pwd"))
    assert float(v) == 7.0
    stf.reset_default_graph()
    p = stf.placeholder_with_default(np.float32(7.0), shape=[],
                                     name="pwd2")
    out = stf.Session().run(p, {p: np.float32(3.0)})
    assert float(out) == 3.0


def _misc_check_numerics():
    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [2], name="cn_x")
    y = stf.check_numerics(x, "bad value")
    sess = stf.Session()
    np.testing.assert_allclose(
        sess.run(y, {x: np.array([1.0, 2.0], np.float32)}), [1.0, 2.0])
    with pytest.raises(Exception, match="bad value|NaN|Inf"):
        sess.run(y, {x: np.array([1.0, np.nan], np.float32)})


def _misc_count_up_to():
    stf.reset_default_graph()
    v = stf.Variable(np.int32(0), name="cut_v")
    c = stf.count_up_to(v, 2)
    sess = stf.Session()
    sess.run(stf.global_variables_initializer())
    assert int(sess.run(c)) == 0
    assert int(sess.run(c)) == 1
    from simple_tensorflow_tpu.framework import errors

    with pytest.raises(errors.OutOfRangeError):
        sess.run(c)


def _misc_strings():
    from simple_tensorflow_tpu.ops import string_ops

    stf.reset_default_graph()
    s = stf.constant(np.array([" Ab c ", "XYZ"], object))
    low = string_ops.string_lower(s)
    stripped = string_ops.string_strip(s)
    num = string_ops.string_to_number(
        stf.constant(np.array(["1.5", "-2"], object)))
    h1 = string_ops.string_to_hash_bucket_fast(s, 17)
    h2 = string_ops.string_to_hash_bucket_strong(s, 17, key=[1, 2])
    reg = string_ops.regex_replace(s, "[A-Z]", "#")
    sess = stf.Session()
    lo, st, nu, hv1, hv2, rg = sess.run([low, stripped, num, h1, h2, reg])
    assert list(lo) == [" ab c ", "xyz"]
    assert list(st) == ["Ab c", "XYZ"]
    np.testing.assert_allclose(nu, [1.5, -2.0])
    assert all(0 <= int(v) < 17 for v in np.ravel(hv1))
    assert all(0 <= int(v) < 17 for v in np.ravel(hv2))
    assert list(rg) == [" #b c ", "###"]


def _misc_base64_json():
    from simple_tensorflow_tpu.ops import string_ops

    stf.reset_default_graph()
    raw = stf.constant(np.array(["hello world"], object))
    enc = string_ops.encode_base64(raw)
    dec = string_ops.decode_base64(enc)
    sess = stf.Session()
    e, d = sess.run([enc, dec])
    import base64 as b64

    assert list(d) in ([b"hello world"], ["hello world"])
    e0 = e[0].encode() if isinstance(e[0], str) else e[0]
    assert b64.urlsafe_b64decode(e0 + b"=" * (-len(e0) % 4)) \
        == b"hello world"
    # DecodeJSONExample: json -> serialized Example bytes
    stf.reset_default_graph()
    from simple_tensorflow_tpu.ops import parsing_ops

    js = stf.constant(np.array(
        ['{"features": {"feature": {"v": {"floatList": '
         '{"value": [1.0]}}}}}'], object))
    ex = parsing_ops.decode_json_example(js)
    out = stf.Session().run(ex)
    assert isinstance(out[0], bytes) and len(out[0]) > 0


def _misc_random_ops():
    from simple_tensorflow_tpu.framework import op_registry as reg

    stf.reset_default_graph()
    g_ = stf.random_gamma([2000], alpha=3.0, seed=1)
    p_ = stf.random_poisson(4.0, [2000], seed=2)
    sess = stf.Session()
    gv, pv = sess.run([g_, p_])
    assert abs(float(np.mean(gv)) - 3.0) < 0.3, np.mean(gv)
    assert abs(float(np.mean(pv)) - 4.0) < 0.3, np.mean(pv)
    _ = reg  # registry import kept for symmetry


def _misc_random_flip():
    stf.reset_default_graph()
    img = np.arange(12, dtype=np.float32).reshape(1, 3, 4, 1)
    f = stf.image.random_flip_left_right(stf.constant(img), seed=3)
    out = np.asarray(stf.Session().run(f))
    ok_same = np.allclose(out, img)
    ok_flip = np.allclose(out, img[:, :, ::-1, :])
    assert ok_same or ok_flip


def _misc_candidate_samplers():
    stf.reset_default_graph()
    from simple_tensorflow_tpu.ops import candidate_sampling_ops as cso

    true_cls = stf.constant(np.array([[1], [5]], np.int64))
    s1, e1, e2 = cso.uniform_candidate_sampler(
        true_cls, num_true=1, num_sampled=8, unique=True, range_max=20,
        seed=4)
    s2, _, _ = cso.log_uniform_candidate_sampler(
        true_cls, num_true=1, num_sampled=8, unique=True, range_max=20,
        seed=5)
    sess = stf.Session()
    v1, v2 = sess.run([s1, s2])
    for v in (v1, v2):
        v = np.asarray(v)
        assert v.shape == (8,)
        assert ((0 <= v) & (v < 20)).all()
        assert len(set(int(x) for x in v)) == 8  # unique=True


def _misc_summaries():
    stf.reset_default_graph()
    t = stf.summary.text("note", stf.constant("hello"))
    a = stf.summary.audio(
        "tone", stf.constant(np.zeros((1, 100, 1), np.float32)),
        sample_rate=8000)
    sess = stf.Session()
    tv, av = sess.run([t, a])
    assert isinstance(np.asarray(tv).item(), bytes)
    assert isinstance(np.asarray(av).item(), bytes)


def _misc_sharding_constraint():
    import jax

    from simple_tensorflow_tpu import parallel

    stf.reset_default_graph()
    devices = jax.devices("cpu")[:8]
    mesh = parallel.Mesh({"dp": 8}, devices=devices)
    with mesh:
        x = stf.constant(_rng(90).randn(8, 4).astype(np.float32))
        y = parallel.with_sharding_constraint(x * 2.0, "dp", None)
        out = stf.Session().run(y)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(stf.Session()._variable_store
                                          and 2.0) * 0 +
                               2.0 * np.asarray(_rng(90)
                                                .randn(8, 4)
                                                .astype(np.float32)),
                               rtol=1e-6)


def _misc_collectives():
    """AllToAll inside a shard_map body: head-scatter/seq-gather
    transpose across the axis (the Ulysses building block)."""
    import jax

    from simple_tensorflow_tpu import parallel

    stf.reset_default_graph()
    devices = jax.devices("cpu")[:4]
    mesh = parallel.Mesh({"sp": 4}, devices=devices)
    with mesh:
        x = stf.constant(np.arange(16, dtype=np.float32).reshape(4, 4))

        def body(xs):
            # per-device shard (1, 4): all_to_all splits dim 1 over sp
            # and concatenates shards along dim 0 -> global transpose
            return parallel.all_to_all(xs, "sp", split_axis=1,
                                       concat_axis=0)

        out = parallel.shard_map(body, [x], in_specs=[("sp", None)],
                                 out_specs=[("sp", None)])
        got = np.asarray(stf.Session().run(out))
    expected = np.arange(16, dtype=np.float32).reshape(4, 4).T \
        .reshape(16, 1)
    np.testing.assert_allclose(got, expected)


def _misc_dynamic_slice_crop():
    stf.reset_default_graph()
    img = stf.constant(np.arange(36, dtype=np.float32)
                       .reshape(6, 6, 1))
    crop = stf.random_crop(img, [2, 2, 1], seed=7)
    out = np.asarray(stf.Session().run(crop))
    assert out.shape == (2, 2, 1)
    # every cropped window of the source contains consecutive values
    base = np.arange(36, dtype=np.float32).reshape(6, 6)
    found = any(np.allclose(out[:, :, 0], base[i:i + 2, j:j + 2])
                for i in range(5) for j in range(5))
    assert found


MISC_TESTS: Dict[str, Callable[[], None]] = {
    "AdjustHue": _misc_adjust_hue,
    "AdjustHueDyn": _misc_adjust_hue,
    "AdjustSaturation": _misc_adjust_saturation,
    "AdjustSaturationDyn": _misc_adjust_saturation,
    "SetIntersection": _misc_set_ops,
    "SetUnion": _misc_set_ops,
    "SetDifference": _misc_set_ops,
    "SetSize": _misc_set_ops,
    "Conv2DBackpropInput": _misc_conv2d_backprop_input,
    "CholeskyGrad": _misc_cholesky_grad,
    "EmbeddingLookupMixed": _misc_embedding_lookup_mixed,
    "ExtractGlimpse": _misc_extract_glimpse,
    "DrawBoundingBoxes": _misc_draw_bounding_boxes,
    "PlaceholderWithDefault": _misc_placeholder_with_default,
    "CheckNumerics": _misc_check_numerics,
    "CountUpTo": _misc_count_up_to,
    "StringLower": _misc_strings,
    "StringStrip": _misc_strings,
    "StringToHashBucketFast": _misc_strings,
    "StringToHashBucketStrong": _misc_strings,
    "StringToNumber": _misc_strings,
    "RegexReplace": _misc_strings,
    "EncodeBase64": _misc_base64_json,
    "DecodeBase64": _misc_base64_json,
    "DecodeJSONExample": _misc_base64_json,
    "RandomGamma": _misc_random_ops,
    "RandomPoisson": _misc_random_ops,
    "RandomFlip": _misc_random_flip,
    "UniformCandidateSampler": _misc_candidate_samplers,
    "LogUniformCandidateSampler": _misc_candidate_samplers,
    "TextSummary": _misc_summaries,
    "AudioSummary": _misc_summaries,
    "ShardingConstraint": _misc_sharding_constraint,
    "AllToAll": _misc_collectives,
    "DynamicSliceCrop": _misc_dynamic_slice_crop,
}


# ---- round-5 upgrade: independent oracles for image ops that were
# previously pointer-covered only ------------------------------------------

import colorsys  # noqa: E402  (image-op oracles)


def _colorsys_map(img, fn):
    out = np.zeros_like(img)
    flat_in = img.reshape(-1, 3)
    flat_out = out.reshape(-1, 3)
    for i in range(flat_in.shape[0]):
        flat_out[i] = fn(*flat_in[i])
    return out


_img443 = _rng(95).rand(2, 4, 4, 3).astype(np.float32)

CASES.update({
    "AdjustBrightness": [Case([_img443],
                              lambda x: x + np.float32(0.3),
                              attrs={"delta": 0.3}, grad=True)],
    "AdjustContrast": [Case(
        [_img443],
        lambda x: (x - x.mean(axis=(1, 2), keepdims=True)) * 1.7
        + x.mean(axis=(1, 2), keepdims=True),
        attrs={"contrast_factor": 1.7}, tol=1e-4, grad=True)],
    "FlipLeftRight": [Case([_img443], lambda x: x[:, :, ::-1, :],
                           grad=True)],
    "FlipUpDown": [Case([_img443], lambda x: x[:, ::-1, :, :],
                        grad=True)],
    "CentralCrop": [Case([np.arange(2 * 8 * 8 * 1, dtype=np.float32)
                          .reshape(2, 8, 8, 1) / 100.0],
                         lambda x: x[:, 2:6, 2:6, :],
                         attrs={"fraction": 0.5}, grad=True)],
    "CropToBoundingBox": [Case(
        # scaled down: f32 central differences at |x|~70 lose the 2%
        # gradient tolerance to rounding
        [np.arange(2 * 6 * 6 * 1, dtype=np.float32).reshape(2, 6, 6, 1)
         / 100.0],
        lambda x: x[:, 1:4, 2:6, :],
        attrs={"offset_height": 1, "offset_width": 2,
               "target_height": 3, "target_width": 4}, grad=True)],
    "ResizeNearestNeighbor": [Case(
        [np.arange(1 * 2 * 2 * 1, dtype=np.float32).reshape(1, 2, 2, 1)],
        lambda x: x.repeat(2, axis=1).repeat(2, axis=2),
        attrs={"size": (4, 4)})],
    "PerImageStandardization": [Case(
        [_img443],
        lambda x: (x - x.mean(axis=(1, 2, 3), keepdims=True))
        / np.maximum(x.std(axis=(1, 2, 3), keepdims=True),
                     1.0 / np.sqrt(np.float32(x[0].size))),
        tol=1e-4, grad=True, grad_tol=5e-2)],
    "RGBToHSV": [Case(
        [_img443], lambda x: _colorsys_map(x, colorsys.rgb_to_hsv),
        tol=1e-4)],
    "HSVToRGB": [Case(
        # rand() is already in [0, 1); cap H below 1.0 (wrap point)
        [np.stack([np.minimum(_img443[..., 0], 0.99),
                   _img443[..., 1], _img443[..., 2]], axis=-1)],
        lambda x: _colorsys_map(x, colorsys.hsv_to_rgb), tol=1e-4)],
})
# these were pointer-covered; the direct oracle supersedes the pointer
for _op in ("AdjustBrightness", "AdjustContrast", "FlipLeftRight",
            "FlipUpDown", "CentralCrop", "CropToBoundingBox",
            "ResizeNearestNeighbor", "PerImageStandardization",
            "RGBToHSV", "HSVToRGB"):
    COVERED_ELSEWHERE.pop(_op, None)


# ---------------------------------------------------------------------------
# generated tests + the enumeration guard
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op_name", sorted(CASES))
def test_op_cases(op_name):
    cases = CASES[op_name]
    if not cases:
        pytest.skip(f"{op_name}: covered via public-API slicing tests")
    for i, case in enumerate(cases):
        try:
            run_case(op_name, case)
        except AssertionError as e:
            raise AssertionError(f"{op_name} case {i}: {e}") from e


@pytest.mark.parametrize("op_name", sorted(MISC_TESTS))
def test_op_misc(op_name):
    MISC_TESTS[op_name]()


def test_registry_fully_covered():
    """The enumeration guard: every registered op has coverage. A new op
    without a CASES entry, a MISC test, or a VERIFIED pointer to an
    existing test fails here (VERDICT r4 item 4 'done' criterion:
    0 registered ops untested)."""
    all_ops = set(op_registry.registered_ops())
    # parametric families registered lazily on first use (one concrete
    # name per dtype/flag combo): covered as a family, pointer-verified
    # like COVERED_ELSEWHERE below
    lazy_families = {"DecodeRaw_": ("test_framework_extras.py",
                                    "decode_raw")}
    lazy = {o for o in all_ops
            if any(o.startswith(p) for p in lazy_families)}
    for fname, marker in lazy_families.values():
        with open(os.path.join(_HERE, fname)) as f:
            assert marker in f.read(), (
                f"lazy-family marker {marker!r} missing from {fname}")
    uncovered = sorted(all_ops - set(CASES) - set(COVERED_ELSEWHERE)
                       - set(MISC_TESTS) - lazy)
    assert not uncovered, (
        f"{len(uncovered)} registered ops have no conformance coverage: "
        f"{uncovered}")
    # pointers must be real: file exists and contains the marker
    for op, (fname, marker) in sorted(COVERED_ELSEWHERE.items()):
        path = os.path.join(_HERE, fname)
        assert os.path.exists(path), f"{op}: pointer file {fname} missing"
        with open(path) as f:
            text = f.read()
        assert marker in text, (
            f"{op}: marker {marker!r} not found in {fname} — the "
            "covering test moved; update the pointer")
    # and pointers must not shadow stale registry entries
    unknown = (set(CASES) | set(COVERED_ELSEWHERE)
               | set(MISC_TESTS)) - all_ops
    assert not unknown, f"coverage entries for unregistered ops: {unknown}"
