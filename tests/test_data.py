"""stf.data pipeline tests (SURVEY §2.8)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import data as stf_data


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


class TestDataset:
    def test_from_tensor_slices_batch(self):
        ds = stf_data.Dataset.from_tensor_slices(
            np.arange(10, dtype=np.int32)).batch(4, drop_remainder=False)
        batches = list(ds)
        assert batches[0].tolist() == [0, 1, 2, 3]
        assert batches[-1].tolist() == [8, 9]

    def test_dict_structure(self):
        ds = stf_data.Dataset.from_tensor_slices(
            {"x": np.arange(4), "y": np.arange(4) * 2}).batch(2)
        b = next(iter(ds))
        assert b["x"].tolist() == [0, 1]
        assert b["y"].tolist() == [0, 2]

    def test_map_filter_like_chain(self):
        ds = (stf_data.Dataset.from_tensor_slices(np.arange(6))
              .map(lambda x: x * 10).batch(3))
        assert next(iter(ds)).tolist() == [0, 10, 20]

    def test_padded_batch_max_in_batch(self):
        rows = [np.arange(n, dtype=np.int32) + 1 for n in (2, 4, 3)]
        ds = stf_data.Dataset.from_generator(lambda: iter(rows)) \
            .padded_batch(3)
        b = next(iter(ds))
        assert b.shape == (3, 4)
        np.testing.assert_array_equal(
            b, [[1, 2, 0, 0], [1, 2, 3, 4], [1, 2, 3, 0]])

    def test_padded_batch_static_shape_and_value(self):
        rows = [np.arange(n, dtype=np.float32) for n in (2, 3)]
        ds = stf_data.Dataset.from_generator(lambda: iter(rows)) \
            .padded_batch(2, padded_shapes=[5], padding_values=-1.0)
        b = next(iter(ds))
        assert b.shape == (2, 5)
        assert b[0].tolist() == [0.0, 1.0, -1.0, -1.0, -1.0]
        assert b[1].tolist() == [0.0, 1.0, 2.0, -1.0, -1.0]

    def test_padded_batch_dict_structure(self):
        rows = [{"ids": np.arange(n, dtype=np.int64),
                 "label": np.int64(n)} for n in (1, 3)]
        ds = stf_data.Dataset.from_generator(lambda: iter(rows)) \
            .padded_batch(2, padded_shapes={"ids": [4]})
        b = next(iter(ds))
        assert b["ids"].shape == (2, 4)
        assert b["label"].tolist() == [1, 3]

    def test_padded_batch_ragged_strings_pad_empty(self):
        rows = [np.array([b"a", b"bb"], dtype=object),
                np.array([b"c"], dtype=object)]
        ds = stf_data.Dataset.from_generator(lambda: iter(rows)) \
            .padded_batch(2)
        b = next(iter(ds))
        assert b.dtype == object
        assert b[0].tolist() == [b"a", b"bb"]
        assert b[1].tolist() == [b"c", b""]  # b"", never an int 0

    def test_padded_batch_too_small_target_raises(self):
        rows = [np.arange(5, dtype=np.int32)]
        ds = stf_data.Dataset.from_generator(lambda: iter(rows)) \
            .padded_batch(1, padded_shapes=[3], drop_remainder=False)
        with pytest.raises(ValueError, match="larger than"):
            next(iter(ds))

    def test_padded_batch_feeds_training(self):
        # the standard NLP path: variable-length ids -> static padded
        # shape -> embedding + mask, one compile for every batch
        rows = [np.arange(1, n + 2, dtype=np.int32) for n in range(6)]
        ds = stf_data.Dataset.from_generator(lambda: iter(rows)) \
            .padded_batch(2, padded_shapes=[8])
        it = ds.make_one_shot_iterator()
        nxt = it.get_next()
        emb = stf.Variable(np.ones((16, 4), np.float32))
        vecs = stf.nn.embedding_lookup(emb, nxt)
        mask = stf.cast(stf.not_equal(nxt, 0), stf.float32)
        pooled = stf.reduce_sum(
            vecs * stf.expand_dims(mask, -1), axis=1)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            out = np.asarray(sess.run(pooled))
        assert out.shape == (2, 4)
        # row 0 has 1 real token, row 1 has 2 (padding masked out)
        np.testing.assert_allclose(out[:, 0], [1.0, 2.0])

    def test_shuffle_deterministic_seed(self):
        mk = lambda: [int(x) for x in stf_data.Dataset.from_tensor_slices(
            np.arange(20)).shuffle(10, seed=3)]
        a, b = mk(), mk()
        assert a == b
        assert sorted(a) == list(range(20))
        assert a != list(range(20))

    def test_repeat_epochs(self):
        ds = stf_data.Dataset.from_tensor_slices(np.arange(3)).repeat(2)
        assert [int(x) for x in ds] == [0, 1, 2, 0, 1, 2]

    def test_prefetch_preserves_order(self):
        ds = stf_data.Dataset.from_tensor_slices(
            np.arange(50)).prefetch(4)
        assert [int(x) for x in ds] == list(range(50))

    def test_make_one_shot_iterator_get_next(self):
        ds = stf_data.Dataset.from_tensor_slices(
            np.float32([1, 2, 3])).batch(1)
        it = ds.make_one_shot_iterator()
        nxt = it.get_next()
        with stf.Session() as sess:
            assert sess.run(nxt).tolist() == [1.0]
            assert sess.run(nxt).tolist() == [2.0]
            assert sess.run(nxt).tolist() == [3.0]
            with pytest.raises(stf.errors.OutOfRangeError):
                sess.run(nxt)

    def test_tfrecord_dataset(self, tmp_path):
        from simple_tensorflow_tpu.lib.io import tf_record

        path = str(tmp_path / "d.tfrecord")
        with tf_record.TFRecordWriter(path) as w:
            for i in range(5):
                w.write(np.int32([i]).tobytes())
        ds = stf_data.TFRecordDataset(path).map(
            lambda b: int(np.frombuffer(b, np.int32)[0]))
        assert list(ds) == [0, 1, 2, 3, 4]

    def test_feed_into_training(self):
        """The canonical input pipeline -> feed_dict -> train loop."""
        rng = np.random.RandomState(0)
        X = rng.rand(32, 3).astype(np.float32)
        Y = (X @ rng.rand(3, 1)).astype(np.float32)
        ds = (stf_data.Dataset.from_tensor_slices({"x": X, "y": Y})
              .repeat().batch(8))
        x = stf.placeholder(stf.float32, [8, 3])
        y = stf.placeholder(stf.float32, [8, 1])
        w = stf.Variable(stf.zeros([3, 1]), name="w")
        loss = stf.reduce_mean(stf.square(stf.matmul(x, w) - y))
        train = stf.train.GradientDescentOptimizer(0.5).minimize(loss)
        it = iter(ds)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            losses = []
            for _ in range(40):
                b = next(it)
                _, l = sess.run([train, loss], {x: b["x"], y: b["y"]})
                losses.append(float(l))
        assert losses[-1] < losses[0] * 0.2


class TestDatasetDictStructures:
    def test_get_next_dict(self):
        from simple_tensorflow_tpu import data as stf_data

        ds = stf_data.Dataset.from_tensor_slices(
            {"x": np.float32([[1, 2], [3, 4]]),
             "y": np.int32([0, 1])}).batch(1)
        nxt = ds.make_one_shot_iterator().get_next()
        assert set(nxt.keys()) == {"x", "y"}
        with stf.Session() as sess:
            b = sess.run(nxt)
        assert b["x"].tolist() == [[1.0, 2.0]]
        assert b["y"].tolist() == [0]

    def test_unbatch_dict(self):
        from simple_tensorflow_tpu import data as stf_data

        ds = stf_data.Dataset.from_tensor_slices(
            {"x": np.arange(4)}).batch(2).unbatch()
        assert [int(e["x"]) for e in ds] == [0, 1, 2, 3]

    def test_from_tensor_slices_validation(self):
        from simple_tensorflow_tpu import data as stf_data

        with pytest.raises(ValueError):
            stf_data.Dataset.from_tensor_slices({})
        with pytest.raises(ValueError):
            stf_data.Dataset.from_tensor_slices(
                {"x": np.zeros(10), "y": np.zeros(5)})

    def test_estimator_checkpoints_by_steps(self, tmp_path):
        from simple_tensorflow_tpu import estimator as est

        def input_fn():
            X = np.random.RandomState(0).rand(16, 2).astype(np.float32)
            Y = X.sum(1, keepdims=True).astype(np.float32)
            ds = stf.data.Dataset.from_tensor_slices(
                {"x": X, "y": Y}).repeat().batch(8)
            f = ds.make_one_shot_iterator().get_next()
            return {"x": f["x"]}, f["y"]

        def model_fn(features, labels, mode, params=None, config=None):
            w = stf.get_variable("w", [2, 1],
                                 initializer=stf.zeros_initializer())
            pred = stf.matmul(features["x"], w)
            loss = stf.reduce_mean(stf.square(pred - labels))
            gs = stf.train.get_or_create_global_step()
            train_op = stf.train.GradientDescentOptimizer(0.1).minimize(
                loss, global_step=gs)
            return est.EstimatorSpec(mode, loss=loss, train_op=train_op,
                                     predictions=pred)

        e = est.Estimator(model_fn, model_dir=str(tmp_path),
                          config=est.RunConfig(save_checkpoints_steps=2))
        e.train(input_fn, steps=5)
        assert stf.train.latest_checkpoint(str(tmp_path)) is not None


class TestDatasetParseExample:
    def test_batched_parse_pipeline(self, tmp_path):
        from simple_tensorflow_tpu.lib.io import tf_record
        from simple_tensorflow_tpu.lib.example import make_example
        import simple_tensorflow_tpu.ops.parsing_ops as po

        path = str(tmp_path / "p.tfrecord")
        with tf_record.TFRecordWriter(path) as w:
            for i in range(10):
                w.write(make_example(
                    x=[float(i), float(i) + 0.5],
                    y=[i]).SerializeToString())
        spec = {"x": po.FixedLenFeature([2], stf.float32),
                "y": po.FixedLenFeature([1], stf.int64)}
        ds = stf_data.TFRecordDataset(path).batch(4).parse_example(spec)
        batches = list(ds)
        assert len(batches) == 2  # drop_remainder
        assert batches[0]["x"].shape == (4, 2)
        np.testing.assert_allclose(batches[1]["x"][0], [4.0, 4.5])
        np.testing.assert_array_equal(batches[0]["y"].ravel(),
                                      [0, 1, 2, 3])

    def test_unbatched_parse_single_records(self, tmp_path):
        from simple_tensorflow_tpu.lib.io import tf_record
        from simple_tensorflow_tpu.lib.example import make_example
        import simple_tensorflow_tpu.ops.parsing_ops as po

        path = str(tmp_path / "q.tfrecord")
        with tf_record.TFRecordWriter(path) as w:
            w.write(make_example(v=[7.0]).SerializeToString())
        spec = {"v": po.FixedLenFeature([1], stf.float32)}
        rows = list(stf_data.TFRecordDataset(path).parse_example(spec))
        assert len(rows) == 1
        np.testing.assert_allclose(rows[0]["v"], [7.0])

    def test_varlen_needs_batched_elements(self, tmp_path):
        from simple_tensorflow_tpu.lib.io import tf_record
        from simple_tensorflow_tpu.lib.example import make_example
        import simple_tensorflow_tpu.ops.parsing_ops as po

        path = str(tmp_path / "v.tfrecord")
        with tf_record.TFRecordWriter(path) as w:
            w.write(make_example(t=[1, 2, 3]).SerializeToString())
            w.write(make_example(t=[4]).SerializeToString())
        spec = {"t": po.VarLenFeature(stf.int64)}
        # unbatched: actionable error
        with pytest.raises(ValueError, match="batch"):
            list(stf_data.TFRecordDataset(path).parse_example(spec))
        # batched: proper batch-level COO triple
        (out,) = list(stf_data.TFRecordDataset(path).batch(2)
                      .parse_example(spec))
        idx, vals, shape = out["t"]
        np.testing.assert_array_equal(shape, [2, 3])
        np.testing.assert_array_equal(vals, [1, 2, 3, 4])
        np.testing.assert_array_equal(idx[:3, 0], [0, 0, 0])
