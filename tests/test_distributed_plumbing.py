"""ClusterSpec/Server mapping and failure-detection behavior
(ref: python/training/server_lib.py:189 ClusterSpec,
core/distributed_runtime session-management failure semantics)."""

import os
import time

import numpy as np
import pytest

from simple_tensorflow_tpu.framework.errors import (DeadlineExceededError,
                                                    UnavailableError)
from simple_tensorflow_tpu.parallel.failure_detection import (Heartbeat,
                                                              StepWatchdog)
from simple_tensorflow_tpu.train import server_lib

# jax's CPU backend cannot run computations that span processes — the
# two-process smoke tests bootstrap fine but any cross-process program
# fails with this runtime error. Skip (with the reason) instead of
# failing: the code path under test is exercised for real on TPU pods.
_NO_MULTIPROCESS_MARKER = "computations aren't implemented"


def _skip_if_backend_lacks_multiprocess(err: str):
    if _NO_MULTIPROCESS_MARKER in err:
        pytest.skip("backend does not support multiprocess computations "
                    "(jax CPU backend: \"Multiprocess computations aren't "
                    "implemented\")")


class TestClusterSpec:
    def test_from_dict_lists(self):
        cs = server_lib.ClusterSpec(
            {"worker": ["w0:2222", "w1:2222"], "eval": ["e0:2222"]})
        assert sorted(cs.jobs) == ["eval", "worker"]
        assert cs.num_tasks("worker") == 2
        assert cs.task_indices("worker") == [0, 1]
        assert cs.task_address("worker", 1) == "w1:2222"
        assert cs.job_tasks("worker") == ["w0:2222", "w1:2222"]
        assert cs.as_dict() == {"worker": ["w0:2222", "w1:2222"],
                                "eval": ["e0:2222"]}

    def test_from_sparse_task_dict(self):
        # TF allows sparse task indices: {"worker": {1: "w1", 3: "w3"}}
        cs = server_lib.ClusterSpec({"worker": {3: "w3:2222", 1: "w1:2222"}})
        assert cs.task_indices("worker") == [1, 3]
        assert cs.job_tasks("worker") == ["w1:2222", "w3:2222"]
        assert cs.task_address("worker", 3) == "w3:2222"

    def test_copy_and_equality(self):
        a = server_lib.ClusterSpec({"worker": ["w0"]})
        b = server_lib.ClusterSpec(a)
        assert a == b and a is not b
        assert bool(a)
        assert not bool(server_lib.ClusterSpec({}))

    def test_rejects_non_dict(self):
        with pytest.raises(TypeError):
            server_lib.ClusterSpec(["w0:2222"])


class TestServer:
    def test_ps_job_rejected_with_guidance(self):
        with pytest.raises(ValueError, match="fsdp"):
            server_lib.Server({"worker": ["w0:1"], "ps": ["p0:1"]},
                              start=False)

    def test_single_worker_start_is_local_noop(self):
        # one worker: no jax.distributed.initialize, start() succeeds
        old = server_lib.Server._started
        server_lib.Server._started = False
        try:
            s = server_lib.Server({"worker": ["localhost:0"]}, start=True)
            assert server_lib.Server._started
            assert s.target == "stf://worker:0"
            sd = s.server_def
            assert sd.job_name == "worker" and sd.task_index == 0
            assert sd.cluster.as_dict() == {"worker": ["localhost:0"]}
        finally:
            server_lib.Server._started = old

    def test_create_local_server(self):
        old = server_lib.Server._started
        server_lib.Server._started = False
        try:
            s = server_lib.Server.create_local_server()
            assert s.target.startswith("stf://worker")
        finally:
            server_lib.Server._started = old


class TestHeartbeat:
    def test_beat_and_check(self):
        hb = Heartbeat(interval_secs=0.01)
        hb.beat()
        hb.check(hb.last_beat, max_age_secs=5.0)  # fresh: no raise
        stale = time.time() - 60.0
        with pytest.raises(UnavailableError, match="presumed dead"):
            hb.check(stale, max_age_secs=10.0)

    def test_background_thread_stamps(self):
        hb = Heartbeat(interval_secs=0.01).start()
        try:
            before = hb.last_beat
            time.sleep(0.1)
            assert hb.last_beat > before
        finally:
            hb.stop()


class TestStepWatchdog:
    def test_fires_on_stall_and_raises_at_step_done(self):
        fired = []
        wd = StepWatchdog(deadline_secs=0.05, poll_secs=0.01,
                          on_timeout=lambda stalled: fired.append(stalled))
        wd.start()
        try:
            time.sleep(0.2)  # stall past the deadline
            assert wd.timed_out
            assert fired and fired[0] > 0.05
            with pytest.raises(DeadlineExceededError, match="deadline"):
                wd.step_done()
        finally:
            wd.stop()

    def test_regular_steps_keep_it_quiet(self):
        wd = StepWatchdog(deadline_secs=0.2, poll_secs=0.01).start()
        try:
            for _ in range(5):
                time.sleep(0.02)
                wd.step_done()
            assert not wd.timed_out
        finally:
            wd.stop()


class TestTwoProcessDistributed:
    """2-process jax.distributed CPU smoke (VERDICT r3 item 10): Server ->
    jax.distributed.initialize across REAL processes, coordinator on
    worker:0; each process must see the global device view."""

    def test_two_process_server_init(self, tmp_path):
        import socket
        import subprocess
        import sys

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        cluster = f"127.0.0.1:{port}"
        script = (
            "import os, sys, json\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from simple_tensorflow_tpu.train import server_lib\n"
            "server_lib.Server._started = False\n"
            "idx = int(sys.argv[1])\n"
            "s = server_lib.Server(\n"
            "    {'worker': ['%s', '%s']},\n"
            "    job_name='worker', task_index=idx, start=True)\n"
            "print(json.dumps({'pid': idx,\n"
            "                  'n_proc': jax.process_count(),\n"
            "                  'n_dev': len(jax.devices()),\n"
            "                  'target': s.target}))\n" % (cluster, cluster))
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # one device per process
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                             "")
        procs = [subprocess.Popen(
            [sys.executable, "-c", script, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=str(tmp_path))
            for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=120)
                if p.returncode != 0:
                    _skip_if_backend_lacks_multiprocess(err)
                assert p.returncode == 0, f"rc={p.returncode}: {err[-1500:]}"
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        import json as _json

        for out in outs:
            line = [l for l in out.splitlines() if l.startswith("{")][-1]
            d = _json.loads(line)
            assert d["n_proc"] == 2, d
            assert d["n_dev"] == 2, d  # global view: both processes' devices
            assert d["target"].startswith("stf://worker:")


class TestSessionTargetRouting:
    """VERDICT r4 item 5: Session(target) must route or raise — silently
    running local on a non-empty target is the one forbidden outcome
    (ref: core/distributed_runtime/rpc/grpc_session.cc)."""

    def _fresh(self):
        old = (server_lib.Server._started, server_lib.Server._coordinator)
        server_lib.Server._started = False
        server_lib.Server._coordinator = None
        return old

    def _restore(self, old):
        server_lib.Server._started, server_lib.Server._coordinator = old

    def test_unknown_scheme_raises_unimplemented(self):
        import simple_tensorflow_tpu as stf
        from simple_tensorflow_tpu.framework import errors

        with pytest.raises(errors.UnimplementedError, match="not supported"):
            stf.Session("ipc:///tmp/sock")

    def test_stf_target_requires_server(self):
        import simple_tensorflow_tpu as stf
        from simple_tensorflow_tpu.framework import errors

        old = self._fresh()
        try:
            with pytest.raises(errors.FailedPreconditionError,
                               match="no Server has started"):
                stf.Session("stf://worker:0")
        finally:
            self._restore(old)

    def test_grpc_target_without_bootstrap_raises(self):
        import simple_tensorflow_tpu as stf
        from simple_tensorflow_tpu.framework import errors

        old = self._fresh()
        try:
            with pytest.raises(errors.FailedPreconditionError,
                               match="bootstrap"):
                stf.Session("grpc://10.0.0.1:2222")
        finally:
            self._restore(old)

    def test_grpc_target_mismatched_coordinator_raises(self):
        import simple_tensorflow_tpu as stf
        from simple_tensorflow_tpu.framework import errors

        old = self._fresh()
        try:
            server_lib.Server._started = True
            server_lib.Server._coordinator = "127.0.0.1:1111"
            with pytest.raises(errors.InvalidArgumentError,
                               match="does not match"):
                stf.Session("grpc://127.0.0.1:2222")
            stf.Session("grpc://127.0.0.1:1111").close()  # match: accepted
        finally:
            self._restore(old)

    def test_server_target_accepted_after_local_server(self):
        import simple_tensorflow_tpu as stf

        old = self._fresh()
        try:
            s = server_lib.Server.create_local_server()
            sess = stf.Session(s.target)
            stf.reset_default_graph()
            sess.close()
        finally:
            self._restore(old)
    def test_two_process_session_step_on_global_mesh(self, tmp_path):
        """Process B (and A — SPMD) runs stf.Session(server.target) and
        executes a training step on the GLOBAL 2-device mesh: a variable
        sharded across both processes' devices updates, loss decreases
        (VERDICT r4 item 5 'done' criterion)."""
        import socket
        import subprocess
        import sys

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        cluster = f"127.0.0.1:{port}"
        script = (
            "import os, sys, json\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import numpy as np\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import simple_tensorflow_tpu as stf\n"
            "from simple_tensorflow_tpu import parallel\n"
            "from simple_tensorflow_tpu.train import server_lib\n"
            "server_lib.Server._started = False\n"
            "idx = int(sys.argv[1])\n"
            "srv = server_lib.Server(\n"
            "    {'worker': ['%s', '%s']},\n"
            "    job_name='worker', task_index=idx, start=True)\n"
            "devices = jax.devices()\n"
            "assert len(devices) == 2, devices\n"
            "mesh = parallel.Mesh({'dp': 2}, devices=devices)\n"
            "with mesh:\n"
            "    w0 = np.arange(8, dtype=np.float32).reshape(4, 2) * 0.3\n"
            "    W = stf.Variable(w0, name='W')\n"
            "    parallel.shard_variable(W, 'dp', None)\n"
            "    loss = stf.reduce_mean(stf.square(W._ref))\n"
            "    train = stf.train.GradientDescentOptimizer(0.5)"
            ".minimize(loss)\n"
            "    sess = stf.Session(srv.target)\n"
            "    sess.run(stf.global_variables_initializer())\n"
            "    l0 = float(np.asarray(sess.run(loss)))\n"
            "    sess.run(train)\n"
            "    l1 = float(np.asarray(sess.run(loss)))\n"
            "    arr = sess._variable_store.values['W']\n"
            "    n_dev = len(arr.sharding.device_set)\n"
            "print(json.dumps({'pid': idx, 'l0': l0, 'l1': l1,\n"
            "                  'w_devices': n_dev,\n"
            "                  'n_proc': jax.process_count()}))\n"
            % (cluster, cluster))
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # one device per process
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                             "")
        procs = [subprocess.Popen(
            [sys.executable, "-c", script, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=str(tmp_path))
            for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=180)
                if p.returncode != 0:
                    _skip_if_backend_lacks_multiprocess(err)
                assert p.returncode == 0, f"rc={p.returncode}: {err[-2000:]}"
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        import json as _json

        for out in outs:
            line = [l for l in out.splitlines() if l.startswith("{")][-1]
            d = _json.loads(line)
            assert d["n_proc"] == 2, d
            assert d["w_devices"] == 2, d  # W really spans both processes
            assert d["l1"] < d["l0"], d   # the global-mesh step trained
