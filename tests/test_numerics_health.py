"""Training numerics-health plane (ISSUE 17): device-side NaN/Inf
sentinels, first-bad-op forensics, /trainz.

The acceptance spine is NaN-injection fuzzing: an in-graph op (Log of
a value that reaches 0) is the injected poison, and dump-mode
forensics must name exactly that op — under plain ``Session.run`` AND
inside a fused ``run_steps`` window (with the offending window step
index). Around it: metrics mode feeds /stf/train/* and /trainz without
splitting fusion, raise mode leaves checkpoints resumable bit-exactly,
``summary.histogram`` no longer splits fused windows (device-side
bucketing + host_sink_pure), the lint/numeric-risk static rule, and
the ``health_inspect`` CLI pinned as a literal subprocess invocation.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import telemetry
from simple_tensorflow_tpu.debug import numerics as numerics_mod
from simple_tensorflow_tpu.platform import monitoring

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh(monkeypatch):
    stf.reset_default_graph()
    monkeypatch.delenv("STF_NUMERICS", raising=False)
    monkeypatch.delenv("STF_NUMERICS_DUMP_ROOT", raising=False)
    yield
    numerics_mod.set_numerics_mode(None)
    numerics_mod.get_plane().reset()


def _counter_cells(name):
    return monitoring.export().get(name, {}).get("cells", {})


def _fallbacks():
    return dict(_counter_cells("/stf/session/loop_fusion_fallbacks"))


def _train_graph(lr=0.1):
    """Deterministic train step whose loss goes through Log: feeding a
    0 anywhere in x makes the Log op (and nothing upstream of it) emit
    the first nonfinite value — the injected poison site."""
    x = stf.placeholder(stf.float32, [4], name="x")
    w = stf.Variable(np.ones(4, np.float32), name="w")
    logx = stf.log(x, name="poison_log")
    loss = stf.reduce_sum(logx * w, name="loss")
    train = stf.train.GradientDescentOptimizer(lr).minimize(loss)
    init = stf.global_variables_initializer()
    return x, w, loss, train, init


CLEAN = np.array([1.0, 2.0, 0.5, 3.0], np.float32)
POISON = np.array([1.0, 2.0, 0.0, 3.0], np.float32)  # log(0) = -inf


# ---------------------------------------------------------------------------
# NumericSummary op
# ---------------------------------------------------------------------------

class TestNumericSummaryOp:
    def test_packed_stats(self):
        from simple_tensorflow_tpu.ops import numerics as num_ops

        x = stf.placeholder(stf.float32, [6], name="x")
        s = num_ops.numeric_summary(x, name="s")
        with stf.Session() as sess:
            v = sess.run(s, feed_dict={
                x: np.array([0.0, -2.0, np.nan, np.inf, 1.0, 0.0],
                            np.float32)})
        stats = dict(zip(num_ops.STAT_NAMES, v))
        assert stats["nonfinite_count"] == 2.0
        assert stats["max_abs"] == 2.0          # over FINITE values
        assert stats["zero_fraction"] == pytest.approx(2.0 / 6.0)
        assert stats["l2_norm"] == pytest.approx(np.sqrt(4.0 + 1.0))


# ---------------------------------------------------------------------------
# metrics mode
# ---------------------------------------------------------------------------

class TestMetricsMode:
    def test_plain_run_observes_taps(self):
        x, w, loss, train, init = _train_graph()
        config = stf.ConfigProto(numerics="metrics")
        numerics_mod.get_plane().reset()
        before = _counter_cells("/stf/train/health_steps").get("", 0)
        with stf.Session(config=config) as sess:
            sess.run(init)
            for _ in range(3):
                sess.run([loss, train], feed_dict={x: CLEAN})
        info = numerics_mod.get_plane().info()
        assert info["steps_observed"] >= 3
        assert info["anomalies"] == 0
        kinds = {t["kind"] for t in info["taps"]}
        assert {"gradient", "update", "loss"} <= kinds
        last = info["history"][-1]
        assert last["grad_norm"] is not None and last["grad_norm"] > 0
        assert np.isfinite(last["max_abs"])
        assert _counter_cells("/stf/train/health_steps").get("", 0) \
            >= before + 3

    def test_fused_window_observes_every_step_without_splitting(self):
        x, w, loss, train, init = _train_graph()
        config = stf.ConfigProto(numerics="metrics")
        numerics_mod.get_plane().reset()
        fall0 = _fallbacks()
        fused0 = _counter_cells(
            "/stf/session/fused_steps_amortized").get("", 0)
        with stf.Session(config=config) as sess:
            sess.run(init)
            sess.run_steps([loss, train], n=4, feed_dict={x: CLEAN})
        assert _fallbacks() == fall0, \
            "the health plane must ride INSIDE the fused window"
        assert _counter_cells(
            "/stf/session/fused_steps_amortized").get("", 0) == fused0 + 4
        info = numerics_mod.get_plane().info()
        assert info["steps_observed"] >= 4  # every window step observed

    def test_nonfinite_counted_not_raised(self):
        x, w, loss, train, init = _train_graph()
        config = stf.ConfigProto(numerics="metrics")
        numerics_mod.get_plane().reset()
        with stf.Session(config=config) as sess:
            sess.run(init)
            sess.run([loss, train], feed_dict={x: POISON})  # no raise
        info = numerics_mod.get_plane().info()
        assert info["anomalies"] == 1
        assert info["last_anomaly"]["taps"]
        cells = _counter_cells("/stf/train/nonfinite_events")
        assert sum(cells.values()) >= 1


# ---------------------------------------------------------------------------
# raise mode
# ---------------------------------------------------------------------------

class TestRaiseMode:
    def test_plain_raise_names_tap_and_site(self):
        x, w, loss, train, init = _train_graph()
        config = stf.ConfigProto(numerics="raise")
        with stf.Session(config=config) as sess:
            sess.run(init)
            sess.run([loss, train], feed_dict={x: CLEAN})
            with pytest.raises(stf.errors.InvalidArgumentError) as ei:
                sess.run([loss, train], feed_dict={x: POISON})
        msg = str(ei.value)
        assert "nonfinite" in msg
        assert "loss" in msg  # the tapped tensor's op is named
        assert "created at" in msg  # creation traceback site

    def test_fused_raise_localizes_window_step(self):
        x, w, loss, train, init = _train_graph()
        config = stf.ConfigProto(numerics="raise")
        sb = np.stack([CLEAN, CLEAN, POISON, CLEAN])
        with stf.Session(config=config) as sess:
            sess.run(init)
            with pytest.raises(stf.errors.InvalidArgumentError) as ei:
                sess.run_steps([loss, train], n=4,
                               stacked_feeds={x: sb})
        # the FIRST anomalous window step is the one raised on (the
        # poison also corrupts the weights, so later steps are
        # anomalous too — the plane history records all of them)
        assert "fused window index 2" in str(ei.value)
        history = numerics_mod.get_plane().info()["history"]
        bad_steps = [e["window_index"] for e in history
                     if e.get("nonfinite_taps")]
        assert bad_steps and bad_steps[0] == 2

    def test_resume_from_checkpoint_after_raise_is_bit_exact(
            self, tmp_path):
        """raise fires post-commit, so recovery is: restore the last
        checkpoint, replay with clean data — and that trajectory must
        be bit-identical to one that never saw the poison."""
        x, w, loss, train, init = _train_graph()
        saver = stf.train.Saver()
        ckpt = str(tmp_path / "model.ckpt")

        # reference: clean steps only, no numerics plane
        with stf.Session() as ref:
            ref.run(init)
            ref.run([loss, train], feed_dict={x: CLEAN})
            ref_mid = ref.run(w)
            for _ in range(2):
                ref.run([loss, train], feed_dict={x: CLEAN})
            ref_final = ref.run(w)

        config = stf.ConfigProto(numerics="raise")
        with stf.Session(config=config) as sess:
            sess.run(init)
            sess.run([loss, train], feed_dict={x: CLEAN})
            saver.save(sess, ckpt)
            np.testing.assert_array_equal(sess.run(w), ref_mid)
            with pytest.raises(stf.errors.InvalidArgumentError):
                sess.run([loss, train], feed_dict={x: POISON})
            # poisoned state was committed; recover via the checkpoint
            saver.restore(sess, ckpt)
            np.testing.assert_array_equal(sess.run(w), ref_mid)
            for _ in range(2):
                sess.run([loss, train], feed_dict={x: CLEAN})
            np.testing.assert_array_equal(sess.run(w), ref_final)


# ---------------------------------------------------------------------------
# dump mode: first-bad-op forensics (the NaN-injection fuzz)
# ---------------------------------------------------------------------------

def _dump_root_from(msg):
    m = re.search(r"dump written to (\S+)", msg)
    assert m, f"no dump path in error message:\n{msg}"
    return m.group(1)


class TestDumpForensics:
    def _poisoned_run(self, tmp_path, monkeypatch, fused=False,
                      bad_step=2):
        x, w, loss, train, init = _train_graph()
        config = stf.ConfigProto(numerics="dump")
        monkeypatch.setenv("STF_NUMERICS_DUMP_ROOT", str(tmp_path))
        with stf.Session(config=config) as sess:
            sess.run(init)
            with pytest.raises(stf.errors.InvalidArgumentError) as ei:
                if fused:
                    feeds = [CLEAN] * 4
                    feeds[bad_step] = POISON
                    sess.run_steps([loss, train], n=4,
                                   stacked_feeds={x: np.stack(feeds)})
                else:
                    sess.run([loss, train], feed_dict={x: POISON})
        root = _dump_root_from(str(ei.value))
        with open(os.path.join(root, "bisect_report.json")) as f:
            report = json.load(f)
        return str(ei.value), root, report

    def test_plain_run_bisector_names_injected_op(self, tmp_path,
                                                  monkeypatch):
        msg, root, report = self._poisoned_run(tmp_path, monkeypatch,
                                               fused=False)
        assert report["first_bad_op"] == "poison_log"
        assert report["op_type"] == "Log"
        assert "first bad op: poison_log (Log)" in msg
        # the pinned CLI invocation appears verbatim in the message
        assert ("python -m simple_tensorflow_tpu.tools.health_inspect"
                in msg)
        # tfdbg-layout dump: inputs finite, outputs nonfinite
        man = os.path.join(root, "run_0", "manifest.json")
        with open(man) as f:
            tensors = json.load(f)["tensors"]
        assert any(m["has_inf_or_nan"] for m in tensors.values())

    def test_fused_run_bisector_names_injected_op_and_step(
            self, tmp_path, monkeypatch):
        msg, root, report = self._poisoned_run(tmp_path, monkeypatch,
                                               fused=True, bad_step=2)
        assert report["first_bad_op"] == "poison_log"
        assert report["op_type"] == "Log"
        assert report["window_index"] == 2
        assert "first bad op: poison_log (Log)" in msg

    def test_fed_nonfinite_blames_the_placeholder(self, tmp_path,
                                                  monkeypatch):
        """Poison arriving FROM a feed is attributed to the
        placeholder, not to the first op that consumed it."""
        x = stf.placeholder(stf.float32, [4], name="x")
        w = stf.Variable(np.ones(4, np.float32), name="w")
        loss = stf.reduce_sum(x * w, name="loss")
        train = stf.train.GradientDescentOptimizer(0.1).minimize(loss)
        init = stf.global_variables_initializer()
        monkeypatch.setenv("STF_NUMERICS_DUMP_ROOT", str(tmp_path))
        config = stf.ConfigProto(numerics="dump")
        bad = np.array([1.0, np.nan, 1.0, 1.0], np.float32)
        with stf.Session(config=config) as sess:
            sess.run(init)
            with pytest.raises(stf.errors.InvalidArgumentError) as ei:
                sess.run([loss, train], feed_dict={x: bad})
        root = _dump_root_from(str(ei.value))
        with open(os.path.join(root, "bisect_report.json")) as f:
            report = json.load(f)
        assert report["first_bad_op"] == "x"

    def test_flight_recorder_numeric_event(self, tmp_path, monkeypatch):
        rec = telemetry.get_recorder()
        self._poisoned_run(tmp_path, monkeypatch, fused=False)
        evs = rec.events(kind="numeric")
        assert evs, "dump-mode anomaly must land a flight event"
        ev = evs[-1]
        assert ev["first_bad_op"] == "poison_log"
        assert ev["n_bad_taps"] >= 1
        assert ev["dump_root"]

    def test_health_inspect_cli_subprocess(self, tmp_path, monkeypatch):
        """The literal invocation the raise message prints must work as
        a subprocess and exit 1 on a nonfinite dump."""
        _, root, _ = self._poisoned_run(tmp_path, monkeypatch,
                                        fused=False)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep \
            + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-m",
             "simple_tensorflow_tpu.tools.health_inspect", root],
            capture_output=True, text=True, env=env, timeout=120)
        assert proc.returncode == 1, proc.stderr
        assert "first bad op 'poison_log' (Log)" in proc.stdout
        assert "NONFINITE" in proc.stdout
        pj = subprocess.run(
            [sys.executable, "-m",
             "simple_tensorflow_tpu.tools.health_inspect", root,
             "--json"],
            capture_output=True, text=True, env=env, timeout=120)
        assert pj.returncode == 1
        payload = json.loads(pj.stdout)
        assert payload["report"]["first_bad_op"] == "poison_log"
        assert payload["nonfinite_tensors"] >= 1


# ---------------------------------------------------------------------------
# /trainz
# ---------------------------------------------------------------------------

class TestTrainz:
    def test_trainz_payload(self):
        import urllib.request

        x, w, loss, train, init = _train_graph()
        config = stf.ConfigProto(numerics="metrics")
        numerics_mod.get_plane().reset()
        srv = telemetry.start(port=0)
        try:
            with stf.Session(config=config) as sess:
                sess.run(init)
                sess.run([loss, train], feed_dict={x: CLEAN})
                sess.run([loss, train], feed_dict={x: POISON})
            with urllib.request.urlopen(srv.url + "/trainz",
                                        timeout=10) as r:
                assert r.status == 200
                body = json.loads(r.read().decode("utf-8"))
        finally:
            telemetry.shutdown()
        assert body["mode"] == "off"  # process default; plane still fed
        assert body["steps_observed"] >= 2
        assert body["anomalies"] >= 1
        assert {t["kind"] for t in body["taps"]} >= {"gradient", "loss"}
        assert body["last_anomaly"]["step"] >= 1
        assert body["history"], "per-step history must be served"


# ---------------------------------------------------------------------------
# summary.histogram no longer splits fused windows
# ---------------------------------------------------------------------------

class TestHistogramFusion:
    def test_histogram_rides_fused_window(self, tmp_path):
        x = stf.placeholder(stf.float32, [4], name="x")
        v = stf.Variable(np.zeros(4, np.float32), name="acc")
        upd = stf.assign_add(v, x)
        s = stf.summary.histogram("acc_hist", upd)
        fall0 = _fallbacks()
        fused0 = _counter_cells(
            "/stf/session/fused_steps_amortized").get("", 0)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            out = sess.run_steps([upd, s], n=3,
                                 feed_dict={x: np.ones(4, np.float32)},
                                 output_mode="last")
        assert _fallbacks() == fall0, \
            "histogram summaries must not split the fused window"
        assert _counter_cells(
            "/stf/session/fused_steps_amortized").get("", 0) == fused0 + 3
        np.testing.assert_array_equal(out[0], np.full(4, 3.0))
        # the summary proto decodes and carries the tag — and it is the
        # LAST window step's histogram (all values == 3.0)
        import glob

        writer = stf.summary.FileWriter(str(tmp_path))
        writer.add_summary(out[1], global_step=3)
        writer.close()
        files = sorted(glob.glob(
            os.path.join(str(tmp_path), "events.out.tfevents.*")))
        histos = [val for f in files
                  for e in stf.summary.summary_iterator(f)
                  if e.summary for val in e.summary.value
                  if val.histo is not None]
        assert histos and histos[0].tag == "acc_hist"
        assert histos[0].histo.max == pytest.approx(3.0)

    def test_histogram_stacked_mode_still_falls_back(self):
        """output_mode='stacked' needs the sink once per step — that
        combination keeps the sequential fallback, with a reason."""
        x = stf.placeholder(stf.float32, [4], name="x")
        v = stf.Variable(np.zeros(4, np.float32), name="acc")
        upd = stf.assign_add(v, x)
        s = stf.summary.histogram("acc_hist2", upd)
        fall0 = _fallbacks()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            out = sess.run_steps([upd, s], n=2,
                                 feed_dict={x: np.ones(4, np.float32)},
                                 output_mode="stacked")
        assert sum(_fallbacks().values()) > sum(fall0.values())
        assert out[0].shape[0] == 2  # per-step values still correct


# ---------------------------------------------------------------------------
# lint/numeric-risk + graph_lint --numerics
# ---------------------------------------------------------------------------

class TestNumericRiskLint:
    def _risky_graph(self):
        g = stf.Graph()
        with g.as_default():
            x = stf.placeholder(stf.float32, [4], name="x")
            stf.log(x, name="bad_log")
            stf.log(stf.maximum(x, 1e-6), name="ok_log")
            stf.log(x + 1e-6, name="eps_log")
            stf.divide(x, x, name="bad_div")
            stf.divide(x, x + 1e-9, name="ok_div")
            stf.exp(x, name="bad_exp")
            stf.exp(stf.minimum(x, 80.0), name="ok_exp")
            h16 = stf.cast(
                stf.placeholder(stf.float32, [8, 4096], name="h"),
                stf.bfloat16)
            stf.reduce_sum(h16, axis=1, name="bad_sum")
            stf.reduce_sum(
                stf.cast(stf.placeholder(stf.float32, [8, 16],
                                         name="s"), stf.bfloat16),
                axis=1, name="small_sum")
        return g

    def test_rule_flags_unguarded_and_spares_guarded(self):
        from simple_tensorflow_tpu.analysis import lint as lint_mod

        g = self._risky_graph()
        diags = [d for d in lint_mod.lint_graph(g, purpose="numerics")
                 if d.code == "lint/numeric-risk"]
        msgs = " ".join(d.message for d in diags)
        for flagged in ("'bad_log'", "'bad_div'", "'bad_exp'",
                        "'bad_sum'"):
            assert flagged in msgs
        for spared in ("'ok_log'", "'eps_log'", "'ok_div'", "'ok_exp'",
                       "'small_sum'"):
            assert spared not in msgs
        assert all(d.severity == "warning" for d in diags)

    def test_rule_is_purpose_gated(self):
        from simple_tensorflow_tpu.analysis import lint as lint_mod

        g = self._risky_graph()
        assert not [d for d in lint_mod.lint_graph(g)
                    if d.code == "lint/numeric-risk"]

    def test_graph_lint_cli_numerics(self, tmp_path, capsys):
        from simple_tensorflow_tpu.framework import graph_io
        from simple_tensorflow_tpu.tools import graph_lint

        g = self._risky_graph()
        path = graph_io.write_graph(g, str(tmp_path), "risky.json")
        rc = graph_lint.main([path, "--numerics"])
        out = capsys.readouterr().out
        assert rc == 0  # warnings don't trip the default error gate
        assert "lint/numeric-risk" in out
        assert "bad_log" in out
        rc = graph_lint.main([path, "--numerics",
                              "--max-severity", "warning"])
        capsys.readouterr()
        assert rc == 1  # but CI can gate on them

    def test_cli_purposes_are_mutually_exclusive(self, tmp_path,
                                                 capsys):
        from simple_tensorflow_tpu.framework import graph_io
        from simple_tensorflow_tpu.tools import graph_lint

        g = self._risky_graph()
        path = graph_io.write_graph(g, str(tmp_path), "risky2.json")
        with pytest.raises(SystemExit):
            graph_lint.main([path, "--numerics", "--serving"])
        capsys.readouterr()


# ---------------------------------------------------------------------------
# stf.train.health: hook + mode resolution
# ---------------------------------------------------------------------------

class TestHealthHook:
    def test_resolved_mode_precedence(self, monkeypatch):
        from simple_tensorflow_tpu.train import health

        assert health.resolved_mode() == "off"
        monkeypatch.setenv("STF_NUMERICS", "metrics")
        # module is imported in this process, so process default wins
        # over env only when explicitly set
        numerics_mod.set_numerics_mode("raise")
        assert health.resolved_mode() == "raise"
        numerics_mod.set_numerics_mode(None)
        config = stf.ConfigProto(numerics="dump")
        assert health.resolved_mode(config) == "dump"

    def test_hook_logs_heartbeat_and_summary(self):
        x, w, loss, train, init = _train_graph()
        config = stf.ConfigProto(numerics="metrics")
        numerics_mod.get_plane().reset()
        lines = []
        hook = stf.train.NumericsHealthHook(every_n_steps=1,
                                            log_fn=lines.append)
        hook.begin()
        with stf.Session(config=config) as sess:
            sess.run(init)
            for _ in range(2):
                sess.run([loss, train], feed_dict={x: CLEAN})
                hook.after_run(None, None)
            hook.end(sess)
        assert any("numerics health @ step" in ln and "grad_norm="
                   in ln for ln in lines)
        assert any("observed" in ln and "mode=" in ln for ln in lines)

    def test_hook_never_caps_fusion_window(self):
        hook = stf.train.NumericsHealthHook()
        assert hook.until_next_trigger(0) >= (1 << 20)
