"""Summary / event-file tests: TF-compatible wire format read back by our
own summary_iterator (mirrors ref summary tests, SURVEY §4)."""

import glob
import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _events(logdir):
    files = sorted(glob.glob(os.path.join(logdir, "events.out.tfevents.*")))
    assert files, f"no event files in {logdir}"
    out = []
    for f in files:
        out.extend(stf.summary.summary_iterator(f))
    return out


class TestFileWriter:
    def test_scalar_summary_roundtrip(self, tmp_path):
        x = stf.placeholder(stf.float32, [], name="x")
        s = stf.summary.scalar("loss", x)
        writer = stf.summary.FileWriter(str(tmp_path))
        with stf.Session() as sess:
            for step, val in enumerate([3.0, 2.0, 1.0]):
                data = sess.run(s, {x: np.float32(val)})
                writer.add_summary(data, global_step=step)
        writer.close()
        evs = _events(str(tmp_path))
        scalars = [(e.step, v.tag, v.simple_value)
                   for e in evs if e.summary
                   for v in e.summary.value]
        assert ("loss" in t for _, t, _ in scalars)
        vals = [v for _, t, v in scalars if "loss" in t]
        np.testing.assert_allclose(vals, [3.0, 2.0, 1.0], rtol=1e-6)

    def test_histogram_summary(self, tmp_path):
        x = stf.placeholder(stf.float32, [100], name="hx")
        s = stf.summary.histogram("weights", x)
        writer = stf.summary.FileWriter(str(tmp_path))
        with stf.Session() as sess:
            data = sess.run(s, {x: np.random.RandomState(0).randn(
                100).astype(np.float32)})
            writer.add_summary(data, global_step=0)
        writer.close()
        evs = _events(str(tmp_path))
        histos = [v for e in evs if e.summary for v in e.summary.value
                  if v.histo is not None]
        assert histos and histos[0].histo.num == 100

    def test_merge_all(self, tmp_path):
        x = stf.placeholder(stf.float32, [], name="mx")
        stf.summary.scalar("a", x)
        stf.summary.scalar("b", x * 2.0)
        merged = stf.summary.merge_all()
        writer = stf.summary.FileWriter(str(tmp_path))
        with stf.Session() as sess:
            writer.add_summary(sess.run(merged, {x: np.float32(1.0)}), 0)
        writer.close()
        evs = _events(str(tmp_path))
        tags = [v.tag for e in evs if e.summary for v in e.summary.value]
        assert any("a" in t for t in tags) and any("b" in t for t in tags)

    def test_add_summary_value_direct(self, tmp_path):
        writer = stf.summary.FileWriter(str(tmp_path))
        writer.add_summary_value("direct", 42.0, global_step=7)
        writer.close()
        evs = _events(str(tmp_path))
        hits = [(e.step, v.simple_value) for e in evs if e.summary
                for v in e.summary.value if v.tag == "direct"]
        assert hits == [(7, 42.0)]

    def test_event_file_has_version_event(self, tmp_path):
        writer = stf.summary.FileWriter(str(tmp_path))
        writer.add_summary_value("x", 1.0, 0)
        writer.close()
        evs = _events(str(tmp_path))
        assert evs[0].file_version  # "brain.Event:2"

    def test_text_and_image_summaries_run(self, tmp_path):
        img = stf.placeholder(stf.float32, [1, 4, 4, 3], name="img")
        si = stf.summary.image("im", img)
        writer = stf.summary.FileWriter(str(tmp_path))
        with stf.Session() as sess:
            writer.add_summary(
                sess.run(si, {img: np.zeros((1, 4, 4, 3), np.float32)}), 0)
        writer.close()
        assert _events(str(tmp_path))


class TestEventFileFormat:
    def test_records_are_valid_tfrecords(self, tmp_path):
        """Event files are TFRecord-framed — the reference's readers parse
        them; verify with our own record reader (CRC-checked)."""
        writer = stf.summary.FileWriter(str(tmp_path))
        writer.add_summary_value("t", 1.5, 3)
        writer.close()
        from simple_tensorflow_tpu.lib.io import tf_record

        f = glob.glob(os.path.join(str(tmp_path),
                                   "events.out.tfevents.*"))[0]
        records = list(tf_record.tf_record_iterator(f))
        assert len(records) >= 2  # version event + our summary
