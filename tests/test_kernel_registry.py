"""stf.kernels — the Pallas/XLA kernel routing tier (ISSUE 11).

Covers the registry contract end to end on the CPU test mesh (Pallas in
interpret mode):

- registry fuzz: random (shape, dtype, mode) draws assert the routed
  and fallback lowerings agree — bit-identical where the two
  implementations share elementwise-only math (fused optimizer
  updates, fused dropout+bias+residual), tight float tolerances where
  reduction order legitimately differs (attention/layer-norm/xent) —
  and that every non-routed decision is explained by exactly one
  ``/stf/kernels/fallback{op, reason}`` cell;
- ``off`` mode (STF_PALLAS=0) restores the pre-registry lowerings
  exactly: fused graph ops keep Pallas, optimizers rebuild the
  per-variable assign tail, trajectories match bit-for-bit;
- the measured autotune cache: verdicts override the static gate,
  measurements persist alongside the compile cache;
- the zoo force gate: transformer + long_context route their attention
  ops under ``force``;
- seeded dropout reproducibility across implementation swaps.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.kernels import registry as kreg


@pytest.fixture(autouse=True)
def _clean_registry_state():
    stf.reset_default_graph()
    kreg.set_mode(None)
    kreg.clear_decisions()
    yield
    kreg.set_mode(None)
    kreg.clear_decisions()
    stf.reset_default_graph()


def _counter_totals():
    routed = sum(c.value() for c in kreg.metric_routed.cells().values())
    fallback = {labels: cell.value()
                for labels, cell in kreg.metric_fallback.cells().items()}
    return routed, fallback


_KNOWN_REASONS = {"mode_off", "forced", "ineligible_dtype",
                  "ineligible_shape", "ineligible_bias",
                  "interpret_backend", "cost_model",
                  "cost_model_uncertain", "autotune", "no_graph_key",
                  "unknown_shape"}


# ---------------------------------------------------------------------------
# modes
# ---------------------------------------------------------------------------

class TestModes:
    def test_env_kill_switch_parsing(self, monkeypatch):
        monkeypatch.delenv("STF_KERNELS", raising=False)
        monkeypatch.setenv("STF_PALLAS", "0")
        assert kreg._env_mode() == "off"
        monkeypatch.setenv("STF_PALLAS", "force")
        assert kreg._env_mode() == "force"
        monkeypatch.setenv("STF_PALLAS", "1")
        assert kreg._env_mode() == "auto"
        monkeypatch.delenv("STF_PALLAS")
        monkeypatch.setenv("STF_KERNELS", "off")
        assert kreg._env_mode() == "off"
        monkeypatch.delenv("STF_KERNELS")
        assert kreg._env_mode() == "auto"

    def test_off_mode_picks_legacy_impl(self):
        # fused graph ops lowered through Pallas before the registry
        # existed; composed ops through jnp — off reproduces both
        key = kreg.aval_key(
            np.zeros((1, 2, 8, 4), np.float32),
            np.zeros((1, 2, 8, 4), np.float32),
            np.zeros((1, 2, 8, 4), np.float32), None,
            causal=False, dropout=False)
        assert kreg.decide("FlashAttention", key, mode="off") == (
            "pallas", "mode_off")
        xkey = kreg.aval_key(np.zeros((4, 16), np.float32),
                             np.zeros((4,), np.int32))
        assert kreg.decide("SparseSoftmaxCrossEntropyWithLogits", xkey,
                           mode="off") == ("xla", "mode_off")

    def test_force_routes_eligible_and_respects_ineligibility(self):
        key = kreg.aval_key(
            np.zeros((1, 2, 8, 4), np.float32),
            np.zeros((1, 2, 8, 4), np.float32),
            np.zeros((1, 2, 8, 4), np.float32), None,
            causal=False, dropout=False)
        assert kreg.decide("FlashAttention", key, mode="force") == (
            "pallas", "forced")
        # per-head bias: the kernel cannot express it, force falls back
        bad = kreg.aval_key(
            np.zeros((1, 2, 8, 4), np.float32),
            np.zeros((1, 2, 8, 4), np.float32),
            np.zeros((1, 2, 8, 4), np.float32),
            np.zeros((1, 2, 8, 8), np.float32),
            causal=False, dropout=False)
        impl, reason = kreg.decide("FlashAttention", bad, mode="force")
        assert impl == "xla" and reason == "ineligible_bias"

    def test_auto_on_cpu_falls_back_interpret(self):
        key = kreg.aval_key(np.zeros((8, 32), np.float32),
                            np.zeros((32,), np.float32),
                            np.zeros((32,), np.float32))
        impl, reason = kreg.decide("FusedLayerNorm", key, mode="auto")
        assert impl == "xla" and reason == "interpret_backend"

    def test_session_config_scopes_mode(self):
        a = [np.random.RandomState(i).randn(1, 2, 16, 8).astype(np.float32)
             for i in range(3)]
        t = stf.nn.fused_attention(*[stf.constant(x) for x in a])
        routed0, _ = _counter_totals()
        with stf.Session(config=stf.ConfigProto(
                kernel_registry="force")) as sess:
            sess.run(t)
        routed1, _ = _counter_totals()
        assert routed1 > routed0  # traced under force -> Pallas


# ---------------------------------------------------------------------------
# registry fuzz (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def _draw_case(rng):
    """One random (kernel, key) draw; returns (op_type, key, exact)
    where exact marks elementwise-only kernels (bit-identical impls)."""
    kind = rng.choice(["flash", "ln", "xent", "qmm", "dbr", "adam",
                       "momentum"])
    f_dt = rng.choice(["float32", "bfloat16"])
    if kind == "flash":
        b, h = int(rng.randint(1, 3)), int(rng.randint(1, 3))
        s = int(rng.randint(3, 40))
        d = int(rng.choice([4, 8, 12]))
        causal = bool(rng.randint(2))
        shape = (b, h, s, d)
        key = kreg.aval_key(
            np.zeros(shape, np.float32).astype(f_dt == "bfloat16" and
                                               np.float32 or np.float32),
            np.zeros(shape, np.float32), np.zeros(shape, np.float32),
            None, causal=causal, dropout=False)
        return "FlashAttention", key, False
    if kind == "ln":
        rows, n = int(rng.randint(1, 24)), int(rng.randint(3, 96))
        key = kreg.aval_key(np.zeros((rows, n), np.float32),
                            np.zeros((n,), np.float32),
                            np.zeros((n,), np.float32))
        return "FusedLayerNorm", key, False
    if kind == "xent":
        rows, v = int(rng.randint(1, 12)), int(rng.randint(4, 260))
        key = kreg.aval_key(np.zeros((rows, v), np.float32),
                            np.zeros((rows,), np.int32),
                            label_smoothing=bool(rng.randint(2)))
        return "FusedSoftmaxXent", key, False
    if kind == "qmm":
        m, k, n = (int(rng.randint(1, 48)) for _ in range(3))
        key = kreg.aval_key(np.zeros((m, k), np.float32),
                            np.zeros((k, n), np.int8),
                            np.zeros((n,), np.float32))
        return "QuantMatMul", key, False
    if kind == "dbr":
        rows, n = int(rng.randint(1, 24)), int(rng.randint(2, 48))
        has_bias = bool(rng.randint(2))
        key = kreg.aval_key(
            np.zeros((rows, n), np.float32),
            np.zeros((rows, n), np.float32),
            np.zeros((n,), np.float32) if has_bias else None,
            rate=float(rng.choice([0.1, 0.37])))
        return "FusedDropoutBiasResidual", key, True
    from simple_tensorflow_tpu.ops.pallas import flat_group_key

    n = int(rng.randint(1, 4000))
    key = flat_group_key(n, "float32", "float32")
    return ("FusedAdamUpdate" if kind == "adam"
            else "FusedMomentumUpdate"), key, True


def test_registry_fuzz_parity_and_counters():
    """Random (shape, dtype, mode) draws: the two lowerings agree on
    every eligible key, and the routed/fallback counters explain every
    decision (one increment each, reason from the documented set)."""
    import jax

    rng = np.random.RandomState(1234)
    for draw in range(18):
        op_type, key, exact = _draw_case(rng)
        mode = str(rng.choice(["off", "auto", "force"]))
        kd = kreg._KERNELS[op_type]
        if kd.eligible(key):
            continue  # ineligible draws covered by the mode tests
        args, kwargs = kd.make_case(key)
        out_p = jax.block_until_ready(kd.impls["pallas"](*args, **kwargs))
        out_x = jax.block_until_ready(kd.impls["xla"](*args, **kwargs))
        flat_p = jax.tree_util.tree_leaves(out_p)
        flat_x = jax.tree_util.tree_leaves(out_x)
        assert len(flat_p) == len(flat_x)
        for a, b in zip(flat_p, flat_x):
            a = np.asarray(a)
            b = np.asarray(b)
            if np.issubdtype(a.dtype, np.integer):
                # int outputs: bit-identical, no excuses
                np.testing.assert_array_equal(a, b, err_msg=op_type)
                continue
            a = a.astype(np.float32)
            b = b.astype(np.float32)
            if exact:
                # elementwise-only kernels: identical op sequence; the
                # only permitted divergence is FMA contraction (XLA
                # fuses multiply-adds differently across the two
                # compilations), which compounds to a few ulps through
                # the m/v/param chain — measured ≤7; budget 8. True
                # bit-exactness across modes is pinned end-to-end by
                # test_fused_optimizer_bitexact_and_killable.
                ai = a.view(np.int32).astype(np.int64)
                bi = b.view(np.int32).astype(np.int64)
                am = np.where(ai < 0, np.int64(-2**31) - ai, ai)
                bm = np.where(bi < 0, np.int64(-2**31) - bi, bi)
                assert np.abs(am - bm).max() <= 8, op_type
            else:
                # reduction-bearing kernels (online softmax, row stats,
                # int8 accumulation): summation order differs
                np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                           err_msg=op_type)
        routed0, fb0 = _counter_totals()
        impl, reason = kreg.decide(op_type, key, mode=mode)
        routed1, fb1 = _counter_totals()
        assert reason in _KNOWN_REASONS, (op_type, reason)
        if impl == "pallas":
            assert routed1 == routed0 + 1
            assert fb1 == fb0
        else:
            assert routed1 == routed0
            diff = {k: fb1.get(k, 0) - fb0.get(k, 0) for k in fb1}
            bumped = {k: v for k, v in diff.items() if v}
            assert bumped == {(op_type, reason): 1}


# ---------------------------------------------------------------------------
# fused optimizer tail: bit-exact vs the per-variable chains
# ---------------------------------------------------------------------------

def _train_weights(mode, optimizer_fn, steps=3):
    kreg.set_mode(mode)
    kreg.clear_decisions()
    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [4, 8], "x")
    w = stf.get_variable(
        "w", [8, 5], initializer=stf.random_normal_initializer(seed=1))
    wb = stf.get_variable("wb", [8, 5], dtype=stf.bfloat16,
                          initializer=stf.zeros_initializer())
    y = (stf.matmul(x, w) +
         stf.cast(stf.matmul(stf.cast(x, stf.bfloat16), wb), stf.float32))
    loss = stf.reduce_mean(stf.square(y))
    opt = optimizer_fn()
    gs = stf.train.get_or_create_global_step()
    train = opt.minimize(loss, global_step=gs)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        xv = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        losses = [np.asarray(sess.run([loss, train], {x: xv})[0])
                  for _ in range(steps)]
        ops = {o.type for o in stf.get_default_graph().get_operations()}
        slots = {f"{sn}/{v.name}": np.asarray(sess.run(opt.get_slot(v, sn)))
                 for sn in opt.get_slot_names() for v in (w, wb)
                 if opt.get_slot(v, sn) is not None}
        return (np.asarray(losses), np.asarray(sess.run(w)),
                np.asarray(sess.run(wb)).astype(np.float32), slots, ops,
                int(np.asarray(sess.run(gs))))


@pytest.mark.parametrize("opt_fn,fused_type", [
    (lambda: stf.train.AdamOptimizer(0.01), "FusedAdamUpdate"),
    (lambda: stf.train.MomentumOptimizer(0.05, 0.9), "FusedMomentumUpdate"),
    (lambda: stf.train.MomentumOptimizer(0.05, 0.9, use_nesterov=True),
     "FusedMomentumUpdate"),
])
def test_fused_optimizer_bitexact_and_killable(opt_fn, fused_type):
    la, wa, wba, sa, opsa, gsa = _train_weights("auto", opt_fn)
    lf, wf, wbf, sf, opsf, gsf = _train_weights("force", opt_fn)
    lo, wo, wbo, so, opso, gso = _train_weights("off", opt_fn)
    # graph shape: fused op present under auto/force, ABSENT under off
    # (STF_PALLAS=0 restores the per-variable assign tail exactly)
    assert fused_type in opsa and fused_type in opsf
    assert fused_type not in opso
    assert "AssignSub" in opso and "AssignSub" not in opsa
    # trajectories bit-exact across all three modes (params, bf16
    # params, every slot), global step advances identically
    for got in ((la, wa, wba, sa, gsa), (lf, wf, wbf, sf, gsf)):
        np.testing.assert_array_equal(got[0], lo)
        np.testing.assert_array_equal(got[1], wo)
        np.testing.assert_array_equal(got[2], wbo)
        assert got[4] == gso
        for k, v in so.items():
            np.testing.assert_array_equal(got[3][k], v, err_msg=k)


def test_fused_adam_with_tensor_lr_schedule():
    def make():
        gs = stf.train.get_or_create_global_step()
        lr = stf.train.exponential_decay(0.01, gs, 2, 0.5)
        return stf.train.AdamOptimizer(lr)

    la, wa, _, _, opsa, _ = _train_weights("auto", make)
    lo, wo, _, _, opso, _ = _train_weights("off", make)
    assert "FusedAdamUpdate" in opsa and "FusedAdamUpdate" not in opso
    np.testing.assert_array_equal(la, lo)
    np.testing.assert_array_equal(wa, wo)


def test_fused_update_read_after_write_visible():
    # a read with a control dep on the fused op observes the NEW value
    # (read-your-write contract, state_ops.ReadVariable semantics)
    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [2, 3], "x")
    w = stf.get_variable("w", [3, 2],
                         initializer=stf.ones_initializer())
    loss = stf.reduce_sum(stf.matmul(x, w))
    opt = stf.train.AdamOptimizer(0.1)
    train = opt.minimize(loss)
    g = stf.get_default_graph()
    with g.control_dependencies([train]):
        w_after = w.read_value()
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        before = np.asarray(sess.run(w))
        after = np.asarray(sess.run(
            w_after, {x: np.ones((2, 3), np.float32)}))
    assert not np.array_equal(before, after)


# ---------------------------------------------------------------------------
# autotune cache
# ---------------------------------------------------------------------------

class TestAutotune:
    def test_measured_verdict_overrides_static_gate(self):
        key = kreg.aval_key(np.zeros((8, 32), np.float32),
                            np.zeros((32,), np.float32),
                            np.zeros((32,), np.float32))
        bk = kreg.backend()
        # the CPU static gate says xla (interpret_backend); a measured
        # verdict must win anyway — auto never contradicts a measurement
        kreg._measured[("FusedLayerNorm", key, bk)] = {
            "verdict": "pallas", "pallas_s": 1e-6, "xla_s": 1e-3}
        try:
            assert kreg.decide("FusedLayerNorm", key, mode="auto") == (
                "pallas", "autotune")
        finally:
            del kreg._measured[("FusedLayerNorm", key, bk)]

    def test_uncertain_gate_measures_once_and_caches(self):
        calls = []

        def gate(key, bk):
            return (None, "cost_model_uncertain")

        def case(key):
            return ((np.ones((4,), np.float32),), {})

        kd = kreg.register_kernel(
            "TestKernelUncertain",
            impls={"pallas": lambda x: x * 2.0, "xla": lambda x: x + x},
            legacy="xla", cost_gate=gate, make_case=case)
        try:
            n0 = kreg.metric_autotune_runs.get_cell(
                "TestKernelUncertain").value()
            key = kreg.aval_key(np.zeros((4,), np.float32))
            impl1, reason1 = kreg.decide("TestKernelUncertain", key,
                                         mode="auto")
            impl2, reason2 = kreg.decide("TestKernelUncertain", key,
                                         mode="auto")
            assert reason1 == reason2 == "autotune"
            assert impl1 == impl2
            n1 = kreg.metric_autotune_runs.get_cell(
                "TestKernelUncertain").value()
            assert n1 == n0 + 1  # measured exactly once, then cached
            assert ("TestKernelUncertain", key,
                    kreg.backend()) in kreg.measured_verdicts()
        finally:
            del kreg._KERNELS["TestKernelUncertain"]
            kreg._measured.pop(
                ("TestKernelUncertain", key, kreg.backend()), None)

    def test_persistence_roundtrip(self, tmp_path, monkeypatch):
        from simple_tensorflow_tpu.compiler import aot

        monkeypatch.setattr(aot, "_persistent_cache_dir", str(tmp_path))
        monkeypatch.setattr(kreg, "_measured_loaded_from", None)
        key = kreg.aval_key(np.zeros((3, 3), np.float32), probe=True)
        cache_key = ("FusedLayerNorm", key, "cpu")
        kreg._measured[cache_key] = {"verdict": "pallas",
                                     "pallas_s": 1e-6, "xla_s": 1e-3}
        try:
            kreg._persist()
            assert (tmp_path / "stf_kernel_autotune.json").exists()
            del kreg._measured[cache_key]
            kreg._load_persisted()
            assert kreg._measured[cache_key]["verdict"] == "pallas"
        finally:
            kreg._measured.pop(cache_key, None)


# ---------------------------------------------------------------------------
# seeded dropout reproducibility across implementation swaps
# ---------------------------------------------------------------------------

class TestSeededSwap:
    def _run_attention(self, mode):
        kreg.set_mode(mode)
        kreg.clear_decisions()
        stf.reset_default_graph()
        stf.set_random_seed(99)
        a = [np.random.RandomState(i).randn(1, 2, 16, 8).astype(np.float32)
             for i in range(3)]
        t = stf.nn.fused_attention(*[stf.constant(x) for x in a],
                                   dropout_rate=0.4)
        with stf.Session() as sess:
            return np.asarray(sess.run(t))

    def test_flash_dropout_mask_survives_impl_swap(self):
        # force = Pallas kernel, auto(cpu) = composed XLA: the
        # counter-based mask is identical, so the outputs agree to
        # float tolerance (a single differing mask element at rate 0.4
        # would diverge by O(1))
        o_force = self._run_attention("force")
        o_auto = self._run_attention("auto")
        np.testing.assert_allclose(o_force, o_auto, atol=5e-5, rtol=5e-5)

    def test_flash_dropout_folds_graph_seed(self):
        # same graph seed -> identical masks; different seed -> different
        o1 = self._run_attention("auto")
        o2 = self._run_attention("auto")
        np.testing.assert_array_equal(o1, o2)
        kreg.set_mode("auto")
        stf.reset_default_graph()
        stf.set_random_seed(100)
        a = [np.random.RandomState(i).randn(1, 2, 16, 8).astype(np.float32)
             for i in range(3)]
        t = stf.nn.fused_attention(*[stf.constant(x) for x in a],
                                   dropout_rate=0.4)
        with stf.Session() as sess:
            o3 = np.asarray(sess.run(t))
        assert not np.array_equal(o1, o3)

    def test_dropout_bias_residual_bitexact_across_modes(self):
        outs = {}
        for mode in ("force", "auto"):
            kreg.set_mode(mode)
            kreg.clear_decisions()
            stf.reset_default_graph()
            stf.set_random_seed(7)
            x = stf.constant(np.random.RandomState(0).randn(
                6, 10).astype(np.float32))
            r = stf.constant(np.random.RandomState(1).randn(
                6, 10).astype(np.float32))
            b = stf.constant(np.random.RandomState(2).randn(
                10).astype(np.float32))
            y = stf.nn.fused_bias_dropout_residual(x, r, b, rate=0.3)
            with stf.Session() as sess:
                outs[mode] = np.asarray(sess.run(y))
        np.testing.assert_array_equal(outs["force"], outs["auto"])

    def test_dropout_bias_residual_gradients(self):
        kreg.set_mode("force")
        stf.reset_default_graph()
        stf.set_random_seed(3)
        xv = np.random.RandomState(0).randn(5, 8).astype(np.float32)
        rv = np.random.RandomState(1).randn(5, 8).astype(np.float32)
        bv = np.random.RandomState(2).randn(8).astype(np.float32)
        x, r, b = (stf.constant(v) for v in (xv, rv, bv))
        y = stf.nn.fused_bias_dropout_residual(x, r, b, rate=0.25)
        loss = stf.reduce_sum(stf.square(y))
        gx, gr, gb = stf.gradients(loss, [x, r, b])
        with stf.Session() as sess:
            y_v, gx_v, gr_v, gb_v = (
                np.asarray(v) for v in sess.run([y, gx, gr, gb]))
        # dropout zeroed elements contribute zero dx; residual grad is
        # the full cotangent; dbias sums dx rows
        g = 2.0 * y_v
        np.testing.assert_allclose(gr_v, g, atol=1e-5)
        kept = gx_v != 0.0
        np.testing.assert_allclose(gx_v[kept], (g / (1 - 0.25))[kept],
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(gb_v, gx_v.sum(axis=0), atol=1e-4,
                                   rtol=1e-4)


# ---------------------------------------------------------------------------
# zoo force gate + offline report (graph_lint --kernels)
# ---------------------------------------------------------------------------

_ATTENTION_TYPES = {"FlashAttention", "FlashAttentionDropout",
                    "RingAttention"}


class TestRoutingReport:
    def test_transformer_zoo_routes_attention_under_force(self):
        from simple_tensorflow_tpu.models import transformer

        transformer.transformer_train_model(
            batch_size=2, src_len=8, tgt_len=8,
            cfg=transformer.TransformerConfig.tiny())
        ops = stf.get_default_graph().get_operations()
        recs = [r for r in kreg.routing_report(ops, mode="force")
                if r.get("type") in _ATTENTION_TYPES
                and r["verdict"] != "no-kernel"]
        assert recs, "transformer zoo graph lost its attention ops?"
        bad = [r for r in recs if r["verdict"] != "routed"]
        assert not bad, f"attention ops not routed under force: {bad}"

    def test_long_context_zoo_routes_attention_under_force(self):
        from simple_tensorflow_tpu.models import long_context

        long_context.lm_train_model(
            batch_size=1, seq_len=32,
            cfg=long_context.LongContextConfig.tiny())
        ops = stf.get_default_graph().get_operations()
        recs = [r for r in kreg.routing_report(ops, mode="force")
                if r.get("type") in _ATTENTION_TYPES
                and r["verdict"] != "no-kernel"]
        assert recs, "long_context zoo graph lost its attention ops?"
        bad = [r for r in recs if r["verdict"] != "routed"]
        assert not bad, f"attention ops not routed under force: {bad}"

    def test_graph_lint_kernels_cli(self, tmp_path):
        from simple_tensorflow_tpu.framework import graph_io
        from simple_tensorflow_tpu.models import transformer

        transformer.transformer_train_model(
            batch_size=2, src_len=8, tgt_len=8,
            cfg=transformer.TransformerConfig.tiny())
        gd_path = graph_io.write_graph(stf.get_default_graph(),
                                       str(tmp_path), "tf.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-m",
             "simple_tensorflow_tpu.tools.graph_lint", gd_path,
             "--kernels", "force", "--json",
             "--max-severity", "error"],
            capture_output=True, text=True, timeout=300, env=env)
        assert out.returncode == 0, out.stdout + out.stderr
        summaries = [json.loads(line)
                     for line in out.stdout.strip().splitlines()
                     if line.startswith("{")]
        kr = [s["kernel_routing"] for s in summaries
              if "kernel_routing" in s]
        assert kr, out.stdout[-2000:]
        table = kr[0]["by_op_type"]
        assert any(t in table for t in _ATTENTION_TYPES), table
        for t in _ATTENTION_TYPES & set(table):
            assert set(table[t]) == {"routed"}, table

    def test_statusz_snapshot_shape(self):
        snap = kreg.snapshot()
        assert snap["mode"] in ("off", "auto", "force")
        for k in ("routed", "fallback", "autotune_runs", "kernels"):
            assert k in snap
