"""stf.serving (ISSUE 7): export -> ModelServer.load -> serve round
trips, continuous-batching correctness under concurrent clients,
per-request deadline semantics, signature validation errors, AOT bucket
warmup, and batcher unit behavior.

Float bitwise caveat pinned here deliberately: XLA CPU changes matmul
accumulation order across PHYSICAL batch sizes (bucket 1 vs 8 differ in
the last ulp), but at a FIXED physical batch size row results are
bitwise independent of the other rows — so padding and coalescing can
never change an answer. The bit-for-bit acceptance test therefore runs
(a) an exact-arithmetic int32 model across MIXED buckets against
unbatched Session.run, and (b) a float MLP at a single fixed bucket
against a same-physical-shape reference, plus unbatched agreement to
float tolerance. docs/SERVING.md documents the contract.
"""

import threading
import time

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import saved_model as sm
from simple_tensorflow_tpu import serving
from simple_tensorflow_tpu.serving.batcher import (ContinuousBatcher,
                                                   ServeFuture,
                                                   ServeRequest)


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield
    stf.reset_default_graph()


def _export_float_mlp(path, in_dim=16, hidden=8, classes=4, seed=7):
    """Export softmax(tanh(x@w1)@w2); returns (export_dir, w1, w2)."""
    rng = np.random.RandomState(seed)
    w1_np = rng.randn(in_dim, hidden).astype(np.float32)
    w2_np = rng.randn(hidden, classes).astype(np.float32)
    x = stf.placeholder(stf.float32, [None, in_dim], name="x")
    w1 = stf.Variable(stf.constant(w1_np), name="w1")
    w2 = stf.Variable(stf.constant(w2_np), name="w2")
    h = stf.tanh(stf.matmul(x, w1))
    y = stf.nn.softmax(stf.matmul(h, w2), name="probs")
    export_dir = str(path)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        sm.simple_save(sess, export_dir, inputs={"x": x},
                       outputs={"probs": y})
    stf.reset_default_graph()
    return export_dir, w1_np, w2_np


def _export_int_model(path, in_dim=6, out_dim=5, seed=3):
    """Exact-arithmetic model: y = x @ W + b, all int32 (bitwise
    reproducible whatever the physical batch size)."""
    rng = np.random.RandomState(seed)
    w_np = rng.randint(-9, 9, size=(in_dim, out_dim)).astype(np.int32)
    b_np = rng.randint(-9, 9, size=(out_dim,)).astype(np.int32)
    x = stf.placeholder(stf.int32, [None, in_dim], name="xi")
    w = stf.Variable(stf.constant(w_np), name="wi")
    b = stf.Variable(stf.constant(b_np), name="bi")
    y = stf.add(stf.matmul(x, w), b, name="yi")
    export_dir = str(path)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        sm.simple_save(sess, export_dir, inputs={"x": x},
                       outputs={"y": y})
    stf.reset_default_graph()
    return export_dir, w_np, b_np


class TestRoundTrip:
    def test_export_load_serve(self, tmp_path):
        export_dir, w1, w2 = _export_float_mlp(tmp_path / "m")
        with serving.ModelServer() as server:
            name = server.load(export_dir)
            assert name == "m"
            assert server.model_names == ["m"]
            assert server.signature_keys() == ["serving_default"]
            x = np.random.RandomState(0).randn(16).astype(np.float32)
            out = server.predict({"x": x}).result(timeout=30)
            assert set(out) == {"probs"}
            assert out["probs"].shape == (4,)
            expect = _softmax(np.tanh(x @ w1) @ w2)
            np.testing.assert_allclose(out["probs"], expect, rtol=1e-5,
                                       atol=1e-6)

    def test_aot_buckets_compiled_at_load(self, tmp_path):
        export_dir, _, _ = _export_float_mlp(tmp_path / "m")
        pol = serving.BatchingPolicy(max_batch_size=4,
                                     bucket_sizes=[1, 2, 4])
        with serving.ModelServer(policy=pol) as server:
            server.load(export_dir)
            sig = server._model("m").signatures["serving_default"]
            assert len(sig.plan.compiled_buckets()) == 3
            # every bucket shape serves correctly (request counts 1..4)
            for k in (1, 2, 3, 4):
                futs = [server.predict(
                    {"x": np.full(16, i, np.float32)}) for i in range(k)]
                for f in futs:
                    assert f.result(timeout=30)["probs"].shape == (4,)

    def test_multi_model_ownership(self, tmp_path):
        d1, w1, w2 = _export_float_mlp(tmp_path / "a")
        d2, wi, bi = _export_int_model(tmp_path / "b")
        with serving.ModelServer() as server:
            server.load(d1, name="float_model")
            server.load(d2, name="int_model")
            assert server.model_names == ["float_model", "int_model"]
            # per-model VariableStore: distinct sessions, shared device
            sa = server._model("float_model").session
            sb = server._model("int_model").session
            assert sa is not sb
            assert sa._variable_store is not sb._variable_store
            # ambiguous model=None with two models
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="pass model"):
                server.predict({"x": np.zeros(16, np.float32)})
            xf = np.ones(16, np.float32)
            xi = np.arange(6, dtype=np.int32)
            of = server.predict({"x": xf}, model="float_model") \
                .result(timeout=30)
            oi = server.predict({"x": xi}, model="int_model") \
                .result(timeout=30)
            assert of["probs"].dtype == np.float32
            np.testing.assert_array_equal(oi["y"], xi @ wi + bi)
            # duplicate name refused
            with pytest.raises(stf.errors.AlreadyExistsError):
                server.load(d1, name="float_model")
            server.unload("float_model")
            assert server.model_names == ["int_model"]


def _softmax(v):
    e = np.exp(v - v.max())
    return (e / e.sum()).astype(np.float32)


class TestSignatureErrors:
    def test_input_key_mismatch(self, tmp_path):
        export_dir, _, _ = _export_float_mlp(tmp_path / "m")
        with serving.ModelServer() as server:
            server.load(export_dir)
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="expects inputs"):
                server.predict({"wrong": np.zeros(16, np.float32)})

    def test_input_shape_mismatch(self, tmp_path):
        export_dir, _, _ = _export_float_mlp(tmp_path / "m")
        with serving.ModelServer() as server:
            server.load(export_dir)
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="per-example shape"):
                server.predict({"x": np.zeros(7, np.float32)})
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="per-example shape"):
                # a BATCH of examples is also a per-request shape error
                server.predict({"x": np.zeros((2, 16), np.float32)})

    def test_unknown_signature_and_model(self, tmp_path):
        export_dir, _, _ = _export_float_mlp(tmp_path / "m")
        with serving.ModelServer() as server:
            server.load(export_dir)
            with pytest.raises(stf.errors.NotFoundError,
                               match="serving_default"):
                server.predict({"x": np.zeros(16, np.float32)},
                               signature_key="nope")
            with pytest.raises(stf.errors.NotFoundError,
                               match="available"):
                server.predict({"x": np.zeros(16, np.float32)},
                               model="ghost")

    def test_get_signature_def_not_found(self):
        with pytest.raises(stf.errors.NotFoundError, match="available"):
            sm.get_signature_def({"signature_def": {"a": {}}}, "b")
        assert sm.get_signature_def(
            {"signature_def": {"a": {"x": 1}}}, "a") == {"x": 1}

    def test_closed_server_unavailable(self, tmp_path):
        export_dir, _, _ = _export_float_mlp(tmp_path / "m")
        server = serving.ModelServer()
        server.load(export_dir)
        server.close()
        with pytest.raises(stf.errors.UnavailableError):
            server.predict({"x": np.zeros(16, np.float32)})
        with pytest.raises(stf.errors.UnavailableError):
            server.load(export_dir, name="again")
        server.close()  # idempotent


class TestConcurrentClients:
    def test_int_model_bitwise_vs_unbatched_mixed_buckets(self, tmp_path):
        """The acceptance contract: responses match unbatched
        Session.run bit-for-bit despite padding/bucketing — pinned on
        exact arithmetic so it holds across MIXED physical buckets."""
        export_dir, w_np, b_np = _export_int_model(tmp_path / "m")
        rng = np.random.RandomState(11)
        examples = rng.randint(-50, 50, size=(24, 6)).astype(np.int32)

        # unbatched reference: one Session.run per example, batch dim 1
        with stf.Session() as sess:
            meta = sm.loader.load(sess, [sm.tag_constants.SERVING],
                                  export_dir)
            sig = meta["signature_def"]["serving_default"]
            xn, yn = sig["inputs"]["x"]["name"], sig["outputs"]["y"]["name"]
            refs = [sess.run(yn, {xn: ex[None, :]})[0] for ex in examples]
        stf.reset_default_graph()

        pol = serving.BatchingPolicy(max_batch_size=8,
                                     bucket_sizes=[1, 2, 4, 8],
                                     batch_timeout_ms=3.0)
        with serving.ModelServer(policy=pol) as server:
            server.load(export_dir)
            results = [None] * len(examples)
            errs = []

            def client(i):
                try:
                    # staggered arrivals -> varied live batch sizes
                    time.sleep((i % 5) * 0.002)
                    results[i] = server.predict(
                        {"x": examples[i]}).result(timeout=60)["y"]
                except BaseException as e:  # noqa: BLE001
                    errs.append((i, e))

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(examples))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
            for i, (got, ref) in enumerate(zip(results, refs)):
                np.testing.assert_array_equal(
                    got, ref, err_msg=f"request {i} diverged from "
                                      "unbatched Session.run")
            snap = server.stats()
            fills = snap["/stf/serving/batch_fill"]["cells"]
            assert fills["m/serving_default"]["count"] >= 1

    def test_float_fixed_bucket_bitwise_and_padding_independence(
            self, tmp_path):
        """At ONE physical bucket size, responses are bitwise equal to
        a Session.run of the same physical batch shape, however the
        batcher coalesced or padded them — padding rows can never
        perturb a live row."""
        export_dir, w1, w2 = _export_float_mlp(tmp_path / "m")
        rng = np.random.RandomState(5)
        examples = rng.randn(16, 16).astype(np.float32)

        with stf.Session() as sess:
            meta = sm.loader.load(sess, [sm.tag_constants.SERVING],
                                  export_dir)
            sig = meta["signature_def"]["serving_default"]
            xn = sig["inputs"]["x"]["name"]
            yn = sig["outputs"]["probs"]["name"]
            # reference at the SAME physical batch size the server pads
            # to (8): two full batches
            ref8 = np.concatenate([sess.run(yn, {xn: examples[:8]}),
                                   sess.run(yn, {xn: examples[8:]})])
            # unbatched single-example reference (physical batch 1)
            ref1 = np.stack([sess.run(yn, {xn: ex[None]})[0]
                             for ex in examples])
        stf.reset_default_graph()

        pol = serving.BatchingPolicy(max_batch_size=8, bucket_sizes=[8],
                                     batch_timeout_ms=5.0)
        with serving.ModelServer(policy=pol) as server:
            server.load(export_dir)
            results = [None] * 16

            def client(i):
                time.sleep((i % 3) * 0.003)
                results[i] = server.predict(
                    {"x": examples[i]}).result(timeout=60)["probs"]

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            got = np.stack(results)
            # bitwise vs the fixed-physical-shape reference
            np.testing.assert_array_equal(got, ref8)
            # and float-tolerance agreement with the unbatched run
            # (XLA CPU retiles matmuls across physical batch sizes;
            # see module docstring)
            np.testing.assert_allclose(got, ref1, rtol=1e-5, atol=1e-6)


class TestDeadlines:
    def test_expired_in_queue_structured_error_batch_proceeds(self):
        """ISSUE 7 satellite: RunOptions.timeout_in_ms semantics in the
        request path — an expired request resolves with
        DeadlineExceededError while the rest of its would-be batch
        executes normally."""
        gate = threading.Event()
        buckets = []

        def exec_fn(feeds, bucket):
            gate.wait(10)
            buckets.append(bucket)
            return {"y": feeds["x"] * 2.0}

        pol = serving.BatchingPolicy(max_batch_size=2, batch_timeout_ms=1,
                                     max_queue_depth=8)
        b = ContinuousBatcher("t/deadline", exec_fn, pol)
        try:
            f1 = ServeFuture("t/deadline")
            b.submit(ServeRequest({"x": np.float32([1.0])}, f1, None))
            time.sleep(0.05)  # batcher holds batch 1 at the gate
            f2 = ServeFuture("t/deadline")
            b.submit(ServeRequest({"x": np.float32([2.0])}, f2,
                                  time.perf_counter() + 0.05))
            f3 = ServeFuture("t/deadline")
            b.submit(ServeRequest({"x": np.float32([3.0])}, f3, None))
            time.sleep(0.15)  # f2's deadline expires while queued
            gate.set()
            assert f1.result(timeout=10)["y"][0] == 2.0
            with pytest.raises(stf.errors.DeadlineExceededError,
                               match="timeout_in_ms"):
                f2.result(timeout=10)
            assert f2.done() and f2.exception() is not None
            # f3 rode the next batch untouched by f2's expiry
            assert f3.result(timeout=10)["y"][0] == 6.0
        finally:
            gate.set()
            b.close()

    def test_admission_backpressure_deadline(self):
        """A full admission queue blocks submitters (backpressure); a
        deadline bounds the wait with a structured error."""
        gate = threading.Event()

        def exec_fn(feeds, bucket):
            gate.wait(10)
            return {"y": feeds["x"]}

        pol = serving.BatchingPolicy(max_batch_size=1, batch_timeout_ms=0,
                                     max_queue_depth=1)
        b = ContinuousBatcher("t/backpressure", exec_fn, pol)
        try:
            f1 = ServeFuture("t/backpressure")
            b.submit(ServeRequest({"x": np.float32([1.0])}, f1, None))
            time.sleep(0.05)  # batcher took f1, is blocked at the gate
            f2 = ServeFuture("t/backpressure")
            b.submit(ServeRequest({"x": np.float32([2.0])}, f2, None))
            # queue now full: a deadline-bounded submit must fail fast
            f3 = ServeFuture("t/backpressure")
            t0 = time.perf_counter()
            b.submit(ServeRequest({"x": np.float32([3.0])}, f3,
                                  time.perf_counter() + 0.08))
            assert time.perf_counter() - t0 < 5.0
            with pytest.raises(stf.errors.DeadlineExceededError,
                               match="admission"):
                f3.result(timeout=10)
            gate.set()
            assert f1.result(timeout=10)["y"][0] == 1.0
            assert f2.result(timeout=10)["y"][0] == 2.0
        finally:
            gate.set()
            b.close()

    def test_run_options_wiring_through_predict(self, tmp_path):
        """options=RunOptions(timeout_in_ms=...) reaches the request
        deadline (generous deadline -> success; the deadline plumbing
        itself is pinned by the batcher tests above)."""
        export_dir, _, _ = _export_float_mlp(tmp_path / "m")
        with serving.ModelServer() as server:
            server.load(export_dir)
            out = server.predict(
                {"x": np.zeros(16, np.float32)},
                options=stf.RunOptions(timeout_in_ms=60000)) \
                .result(timeout=60)
            assert out["probs"].shape == (4,)

    def test_policy_default_timeout(self):
        pol = serving.BatchingPolicy(default_timeout_ms=25.0)
        assert pol.default_timeout_ms == 25.0
        # the batcher marks queue-expired requests without executing
        gate = threading.Event()

        def exec_fn(feeds, bucket):
            gate.wait(10)
            return {"y": feeds["x"]}

        b = ContinuousBatcher(
            "t/default_to", exec_fn,
            serving.BatchingPolicy(max_batch_size=1, batch_timeout_ms=0))
        try:
            f1 = ServeFuture("t/default_to")
            b.submit(ServeRequest({"x": np.float32([1.0])}, f1, None))
            time.sleep(0.05)
            f2 = ServeFuture("t/default_to")
            b.submit(ServeRequest({"x": np.float32([2.0])}, f2,
                                  time.perf_counter() + 0.02))
            time.sleep(0.1)
            gate.set()
            with pytest.raises(stf.errors.DeadlineExceededError):
                f2.result(timeout=10)
        finally:
            gate.set()
            b.close()


class TestBatcherMechanics:
    def test_batch_closes_on_max_size(self):
        seen = []

        def exec_fn(feeds, bucket):
            seen.append((len(feeds["x"]), bucket))
            return {"y": feeds["x"]}

        pol = serving.BatchingPolicy(max_batch_size=4,
                                     batch_timeout_ms=10_000,
                                     bucket_sizes=[4])
        b = ContinuousBatcher("t/maxsize", exec_fn, pol)
        try:
            futs = []
            for i in range(4):
                f = ServeFuture("t/maxsize")
                futs.append(f)
                b.submit(ServeRequest({"x": np.float32([i])}, f, None))
            # a full batch must close LONG before the 10 s timeout
            for f in futs:
                f.result(timeout=5)
            assert seen and seen[0] == (4, 4)
        finally:
            b.close()

    def test_batch_closes_on_timeout(self):
        def exec_fn(feeds, bucket):
            return {"y": feeds["x"]}

        pol = serving.BatchingPolicy(max_batch_size=64,
                                     batch_timeout_ms=10.0,
                                     bucket_sizes=[2, 64])
        b = ContinuousBatcher("t/timeout", exec_fn, pol)
        try:
            f = ServeFuture("t/timeout")
            t0 = time.perf_counter()
            b.submit(ServeRequest({"x": np.float32([1.0])}, f, None))
            out = f.result(timeout=5)
            assert out["y"][0] == 1.0
            # closed by timeout (~10ms), nowhere near a full batch
            assert time.perf_counter() - t0 < 4.0
        finally:
            b.close()

    def test_pad_modes(self):
        captured = {}

        def exec_fn(feeds, bucket):
            captured["x"] = feeds["x"].copy()
            return {"y": feeds["x"]}

        for mode, expect_row in (("repeat", 7.0), ("zero", 0.0)):
            pol = serving.BatchingPolicy(max_batch_size=1,
                                         batch_timeout_ms=0,
                                         bucket_sizes=[4], pad_mode=mode)
            b = ContinuousBatcher(f"t/pad_{mode}", exec_fn, pol)
            try:
                f = ServeFuture("t/pad")
                b.submit(ServeRequest({"x": np.float32([7.0])}, f, None))
                assert f.result(timeout=5)["y"][0] == 7.0
                assert captured["x"].shape == (4, 1)
                assert captured["x"][3, 0] == expect_row
            finally:
                b.close()

    def test_bucket_for(self):
        pol = serving.BatchingPolicy(max_batch_size=16)
        assert pol.bucket_sizes == [1, 2, 4, 8, 16]
        assert pol.bucket_for(1) == 1
        assert pol.bucket_for(3) == 4
        assert pol.bucket_for(16) == 16
        pol2 = serving.BatchingPolicy(max_batch_size=6,
                                      bucket_sizes=[4])
        assert pol2.bucket_sizes == [4, 6]

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            serving.BatchingPolicy(max_batch_size=0)
        with pytest.raises(ValueError):
            serving.BatchingPolicy(pad_mode="extrapolate")
        with pytest.raises(ValueError):
            serving.BatchingPolicy(batch_timeout_ms=-1)

    def test_close_drains_queued_requests(self):
        def exec_fn(feeds, bucket):
            time.sleep(0.01)
            return {"y": feeds["x"] + 1.0}

        pol = serving.BatchingPolicy(max_batch_size=2, batch_timeout_ms=1)
        b = ContinuousBatcher("t/drain", exec_fn, pol)
        futs = []
        for i in range(6):
            f = ServeFuture("t/drain")
            futs.append(f)
            b.submit(ServeRequest({"x": np.float32([i])}, f, None))
        b.close()  # queued requests still execute (drain semantics)
        for i, f in enumerate(futs):
            assert f.result(timeout=10)["y"][0] == i + 1.0
        # post-close submits fail structured
        f = ServeFuture("t/drain")
        b.submit(ServeRequest({"x": np.float32([0.0])}, f, None))
        with pytest.raises(stf.errors.UnavailableError):
            f.result(timeout=5)


class TestMetricsAndLifecycle:
    def test_windowed_rate_decays_to_zero(self):
        from simple_tensorflow_tpu.platform.monitoring import WindowedRate

        wr = WindowedRate(window_s=10.0)
        wr.add(100, now=1000.0)
        assert wr.rate(now=1005.0) == pytest.approx(10.0)
        # idle past the window: the rate must decay to 0, not stick
        assert wr.rate(now=1020.0) == 0.0

    def test_stats_refreshes_qps_gauge(self, tmp_path):
        export_dir, _, _ = _export_float_mlp(tmp_path / "m")
        with serving.ModelServer() as server:
            server.load(export_dir)
            server.predict({"x": np.zeros(16, np.float32)}) \
                .result(timeout=30)
            bt = server._model("m").signatures["serving_default"].batcher
            # simulate the last-batch write going stale: traffic stopped
            # long ago but the gauge still holds the old rate
            bt._qps_gauge.set(12345)
            snap = server.stats()
            cell = snap["/stf/serving/qps"]["cells"]["m/serving_default"]
            assert cell != 12345  # refreshed from the trailing window

    def test_close_during_load_aborts_cleanly(self, tmp_path):
        """A load that completes after close() must not insert a model
        whose session/batcher threads nothing would ever tear down."""
        export_dir, _, _ = _export_float_mlp(tmp_path / "m")
        server = serving.ModelServer()
        orig_warmup = serving.ModelServer._warmup
        entered = threading.Event()
        release = threading.Event()

        def slow_warmup(self, model):
            entered.set()
            release.wait(10)
            return orig_warmup(self, model)

        result = {}

        def do_load():
            try:
                server.load(export_dir, name="raced")
                result["ok"] = True
            except stf.errors.UnavailableError as e:
                result["err"] = e

        serving.ModelServer._warmup = slow_warmup
        try:
            th = threading.Thread(target=do_load)
            th.start()
            assert entered.wait(10)
            server.close()  # snapshots (empty) models, sets _closed
            release.set()
            th.join(20)
        finally:
            serving.ModelServer._warmup = orig_warmup
        assert "err" in result and "ok" not in result
        time.sleep(0.3)
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("stf_serving_")]
