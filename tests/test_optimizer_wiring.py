"""Plan-time graph optimizer on the Session hot path (VERDICT round-1 #5:
fold/CSE/DCE must actually run in _plan) + device-scope placement."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _only_step(sess):
    steps = list(sess._cache.values())
    assert len(steps) == 1
    return steps[0]


class TestPlanTimeFolding:
    def test_const_subgraph_folds_to_fewer_device_ops(self):
        x = stf.placeholder(stf.float32, [2], name="x")
        # (2*3)+4 is a 3-op constant subtree; after folding the device
        # program should contain just the final Add on x.
        c = stf.add(stf.multiply(stf.constant(2.0), stf.constant(3.0)),
                    stf.constant(4.0))
        y = stf.add(x, c)
        with stf.Session() as sess:
            out = sess.run(y, {x: np.float32([1.0, 2.0])})
            step = _only_step(sess)
        assert out.tolist() == [11.0, 12.0]
        assert step.const_env  # something folded at plan time
        assert len(step.device_ops) == 1, [o.type for o in step.device_ops]
        assert step.device_ops[0].type == "Add"

    def test_fetch_of_fully_folded_value(self):
        y = stf.multiply(stf.constant(6.0), stf.constant(7.0))
        with stf.Session() as sess:
            out = sess.run(y)
            step = _only_step(sess)
        assert float(out) == 42.0
        assert not step.has_device_stage  # nothing left to compile

    def test_cse_merges_duplicate_pure_ops(self):
        x = stf.placeholder(stf.float32, [3], name="x")
        y = stf.add(stf.exp(x), stf.exp(x))  # two distinct Exp nodes
        with stf.Session() as sess:
            v = np.float32([0.0, 1.0, 2.0])
            out = sess.run(y, {x: v})
            step = _only_step(sess)
        assert np.allclose(out, 2.0 * np.exp(v), rtol=1e-5)
        assert sum(1 for o in step.device_ops if o.type == "Exp") == 1
        assert step.alias  # duplicate was aliased, not traced

    def test_fold_does_not_touch_random_or_variables(self):
        v = stf.Variable(stf.constant([1.0, 2.0]), name="nv")
        r = stf.random_normal([2], seed=1)
        y = v.value() + r
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            a = np.asarray(sess.run(y))
            b = np.asarray(sess.run(y))
        assert a.shape == (2,)
        assert not np.array_equal(a, b)  # rng still advances per run

    def test_gradients_through_cse_and_folding(self):
        x = stf.placeholder(stf.float32, [2], name="x")
        k = stf.multiply(stf.constant(2.0), stf.constant(1.5))  # folds to 3
        y = stf.reduce_sum(stf.square(x) * k + stf.square(x))
        (gx,) = stf.gradients(y, [x])
        with stf.Session() as sess:
            g = sess.run(gx, {x: np.float32([1.0, 2.0])})
        # d/dx (3x^2 + x^2) = 8x
        assert np.allclose(g, [8.0, 16.0], rtol=1e-5)


class TestDeviceScopePlacement:
    def test_cpu_scope_pins_op_to_host_stage(self):
        x = stf.placeholder(stf.float32, [2], name="x")
        with stf.device("/cpu:0"):
            h = stf.add(x, stf.constant(1.0), name="host_add")
        y = stf.multiply(h, stf.constant(2.0))
        with stf.Session() as sess:
            out = sess.run(y, {x: np.float32([1.0, 2.0])})
            step = _only_step(sess)
        assert out.tolist() == [4.0, 6.0]
        host_types = [o.name for o in step.host_plan]
        assert any("host_add" in n for n in host_types), host_types
        assert all("host_add" not in o.name for o in step.device_ops)

    def test_device_scope_recorded_on_op(self):
        with stf.device("/device:CPU:0"):
            c = stf.add(stf.constant(1.0), stf.constant(2.0), name="dev_rec")
        assert "CPU" in c.op.device

    def test_tpu_scope_stays_in_device_stage(self):
        x = stf.placeholder(stf.float32, [2], name="x")
        with stf.device("/device:TPU:0"):
            y = stf.add(x, stf.constant(1.0), name="tpu_add")
        with stf.Session() as sess:
            out = sess.run(y, {x: np.float32([0.0, 1.0])})
            step = _only_step(sess)
        assert out.tolist() == [1.0, 2.0]
        assert any("tpu_add" in o.name for o in step.device_ops)

    def test_host_pinned_consumer_of_device_result(self):
        x = stf.placeholder(stf.float32, [2], name="x")
        dev = stf.square(x)  # device stage
        with stf.device("/cpu:0"):
            post = stf.add(dev, stf.constant(1.0), name="post_add")
        with stf.Session() as sess:
            out = sess.run(post, {x: np.float32([2.0, 3.0])})
            step = _only_step(sess)
        assert out.tolist() == [5.0, 10.0]
        assert any("post_add" in o.name for o in step.post_host_plan)
