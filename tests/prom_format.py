"""Minimal Prometheus text-exposition (0.0.4) validator for tests.

Not a full parser — a line-grammar + consistency checker strong enough
to catch every bug class the exposition unit tests pin: malformed
series lines, bad metric/label names, raw newlines mid-series,
non-cumulative histogram buckets, missing ``+Inf`` edges, and
``_count``/``+Inf`` mismatches. Raises AssertionError with the
offending line on any violation; returns the parsed series.
"""

import re

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')
_SERIES_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(?:[^{}\n]*)\})? "
    r"(NaN|[+-]?Inf|[-+0-9.eE]+)$")


def validate_prometheus_text(text):
    """Validate an exposition payload; returns
    {series_name: [(labels_dict, value_str), ...]}."""
    assert text.endswith("\n"), "exposition must end with a newline"
    series = {}
    typed = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name = rest.split(" ", 1)[0]
            assert _NAME_RE.fullmatch(name), f"bad HELP name: {line!r}"
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, f"bad TYPE line: {line!r}"
            assert _NAME_RE.fullmatch(parts[2]), f"bad TYPE name: {line!r}"
            assert parts[3] in ("counter", "gauge", "histogram",
                                "summary", "untyped"), f"bad type: {line!r}"
            typed[parts[2]] = parts[3]
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = _SERIES_RE.match(line)
        assert m, f"malformed series line: {line!r}"
        name, labelblock, value = m.group(1), m.group(2), m.group(3)
        labels = {}
        if labelblock:
            inner = labelblock[1:-1]
            consumed = 0
            for lm in _LABEL_RE.finditer(inner):
                labels[lm.group(1)] = lm.group(2)
                consumed += len(lm.group(0))
            # every byte of the block must belong to a well-formed pair
            # (or the separating commas): torn/unescaped values fail here
            n_commas = max(len(labels) - 1, 0)
            assert consumed + n_commas == len(inner), \
                f"malformed label block: {line!r}"
        series.setdefault(name, []).append((labels, value))

    # histogram consistency: cumulative buckets ending in +Inf == _count
    for name, typ in typed.items():
        if typ != "histogram":
            continue
        buckets = series.get(name + "_bucket", [])
        counts = dict((tuple(sorted((k, v) for k, v in lb.items())), val)
                      for lb, val in series.get(name + "_count", []))
        groups = {}
        for lb, val in buckets:
            key = tuple(sorted((k, v) for k, v in lb.items()
                               if k != "le"))
            groups.setdefault(key, []).append((lb["le"], val))
        for key, seq in groups.items():
            values = [float(v) for _, v in seq]
            assert values == sorted(values), \
                f"histogram {name} buckets not cumulative: {seq}"
            assert seq[-1][0] == "+Inf", \
                f"histogram {name} missing +Inf bucket: {seq}"
            if key in counts:
                assert float(seq[-1][1]) == float(counts[key]), \
                    f"histogram {name} +Inf != _count: {seq[-1]} vs " \
                    f"{counts[key]}"
    return series
