"""stf.analysis: graph verifier, variable-hazard detector, lint
framework, op-source attribution (ISSUE 3)."""

import json
import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import analysis
from simple_tensorflow_tpu.framework import graph as graph_mod
from simple_tensorflow_tpu.framework import graph_io, lowering, op_registry
from simple_tensorflow_tpu.ops import state_ops
from simple_tensorflow_tpu.platform import monitoring


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    prev = analysis.get_hazard_mode()
    yield
    analysis.set_hazard_mode(prev)
    stf.reset_default_graph()


# ---------------------------------------------------------------------------
# effects + traceback capture
# ---------------------------------------------------------------------------

class TestEffectsAndTraceback:
    def test_declared_effect_sets(self):
        v = stf.Variable(1.0, name="v")
        read = v.read_value()
        wr = stf.assign(v, 2.0)
        aa = stf.assign_add(v, 1.0)
        assert analysis.op_effects(read.op).reads == {"var_name=v"}
        assert analysis.op_effects(wr.op).writes == {"var_name=v"}
        ra = analysis.op_effects(aa.op)
        assert ra.writes == {"var_name=v"} and ra.update == "add"
        rnd = stf.random_uniform([2])
        assert analysis.op_effects(rnd.op).rng
        pure = analysis.op_effects((read + 1.0).op)
        assert not pure and pure.describe() == "pure"

    def test_effects_imply_stateful(self):
        od = op_registry.get("Assign")
        assert od.is_stateful and od.effects_declared

    def test_traceback_points_at_user_code(self):
        x = stf.constant(1.0)  # <- this line is the creation site
        assert x.op.traceback, "traceback capture should be on by default"
        fname, lineno, func = x.op.traceback[0]
        assert fname.endswith("test_analysis.py")
        assert func == "test_traceback_points_at_user_code"
        assert x.op.source_site == f"{fname}:{lineno}"

    def test_traceback_capture_off_switch(self):
        prev = analysis.set_traceback_capture(False)
        try:
            x = stf.constant(2.0)
            assert x.op.traceback == () and x.op.source_site is None
        finally:
            analysis.set_traceback_capture(prev)

    def test_source_survives_serialization_roundtrip(self):
        y = stf.constant(3.0, name="roundtrip_c")
        gd = graph_io.graph_to_graphdef(stf.get_default_graph())
        stf.reset_default_graph()
        graph_io.import_graph_def(json.dumps(gd), name="")
        op = stf.get_default_graph().get_operation_by_name("roundtrip_c")
        assert op.source_site and "test_analysis.py" in op.source_site


# ---------------------------------------------------------------------------
# verifier
# ---------------------------------------------------------------------------

class TestVerifier:
    def test_clean_graph_has_no_errors(self):
        x = stf.placeholder(stf.float32, [2, 2], name="x")
        stf.matmul(x, x)
        diags = analysis.verify_graph(stf.get_default_graph(),
                                      level="full")
        assert analysis.errors(diags) == []

    def test_infer_mismatch_dtype_is_error(self):
        g = stf.get_default_graph()
        a = stf.constant(np.ones((2,), np.float32))
        b = stf.constant(np.ones((2,), np.float32))
        # lie about the output dtype: abstract eval derives float32
        g.create_op("Add", [a, b], name="liar",
                    output_specs=[(a.shape, stf.int32)])
        diags = analysis.verify_graph(g, level="full")
        errs = analysis.errors(diags)
        assert any(d.code == "verifier/infer-mismatch" for d in errs)
        d = next(d for d in errs if d.code == "verifier/infer-mismatch")
        assert d.op_name == "liar" and d.source \
            and "test_analysis.py" in d.source

    def test_structural_level_skips_abstract_eval(self):
        g = stf.get_default_graph()
        a = stf.constant(np.ones((2,), np.float32))
        g.create_op("Add", [a, a], name="liar2",
                    output_specs=[(a.shape, stf.int32)])
        diags = analysis.verify_graph(g, level="structural")
        assert analysis.errors(diags) == []

    def test_unreachable_stateful_note(self):
        v = stf.Variable(1.0, name="uv")
        stf.assign(v, 7.0, name="orphan_assign")
        fetch = v.read_value() + 1.0
        diags = analysis.verify_graph(stf.get_default_graph(),
                                      fetches=[fetch])
        notes = [d for d in diags
                 if d.code == "verifier/unreachable-stateful"]
        assert any("orphan_assign" in (d.op_name or "") for d in notes)

    def test_device_scope_warning_for_host_op_on_device(self):
        v = stf.Variable(1.0, name="dv")
        with stf.device("/device:TPU:0"):
            state_ops.is_variable_initialized(v)
        diags = analysis.verify_graph(stf.get_default_graph())
        assert any(d.code == "verifier/device-scope" for d in diags)

    def test_graphdef_dangling_and_duplicate(self):
        stf.constant(1.0, name="c1")
        gd = graph_io.graph_to_graphdef(stf.get_default_graph())
        node = dict(gd["node"][0])
        gd["node"].append(node)  # duplicate name
        diags = analysis.verify_graphdef(gd)
        assert any(d.code == "verifier/duplicate-name" for d in diags)
        gd2 = {"node": [{"name": "n", "op": "Add",
                         "input": ["ghost:0", "ghost:1"],
                         "control_input": [], "attr": {}}]}
        diags2 = analysis.verify_graphdef(gd2)
        assert any(d.code == "verifier/dangling-input" for d in diags2)

    def test_graphdef_cycle_detected(self):
        gd = {"node": [
            {"name": "a", "op": "Neg", "input": ["b:0"],
             "control_input": [], "attr": {}},
            {"name": "b", "op": "Neg", "input": ["a:0"],
             "control_input": [], "attr": {}},
        ]}
        diags = analysis.verify_graphdef(gd)
        assert any(d.code == "verifier/cycle" for d in diags)

    def test_graphdef_funcgraph_signature_checked(self):
        x = stf.constant(2.0)
        r = stf.cond(x > 1.0, lambda: x * 2.0, lambda: x)
        gd = graph_io.graph_to_graphdef(stf.get_default_graph())
        assert analysis.errors(analysis.verify_graphdef(gd)) == []
        # break one branch body: drop its output node
        for n in gd["node"]:
            for k, v in (n.get("attr") or {}).items():
                if isinstance(v, dict) and v.get("__kind__") == "funcgraph":
                    body = v["v"]
                    out_node = body["outputs"][0].split(":")[0]
                    body["node"] = [bn for bn in body["node"]
                                    if bn["name"] != out_node]
                    diags = analysis.verify_graphdef(gd)
                    assert any(d.code == "verifier/funcgraph-signature"
                               for d in analysis.errors(diags))
                    return
        pytest.fail("no funcgraph found in cond graphdef")


# ---------------------------------------------------------------------------
# hazard detector
# ---------------------------------------------------------------------------

def _plan_for(fetches, extra_ops=()):
    targets = [t.op for t in fetches] + list(extra_ops)
    return lowering.prune(targets, set())


class TestHazards:
    def _racy(self):
        v = stf.Variable(1.0, name="hv")
        read = v.read_value()
        consumed = read + 0.0
        wr = stf.assign(v, 5.0)
        return v, consumed, wr

    def test_unordered_read_write_detected(self):
        _, consumed, wr = self._racy()
        plan = _plan_for([consumed], [wr.op])
        hz = analysis.find_hazards(plan)
        assert len(hz) == 1 and hz[0].kind in ("raw", "war")
        assert hz[0].resource == "var_name=hv"
        d = hz[0].to_diagnostic(analysis.WARNING)
        assert d.op_name and d.source and "test_analysis.py" in d.source

    def test_ordered_pair_is_clean(self):
        v = stf.Variable(1.0, name="ov")
        wr = stf.assign(v, 5.0)
        with stf.control_dependencies([wr]):
            read = v.read_value()
        consumed = read + 0.0
        plan = _plan_for([consumed], [wr.op])
        assert analysis.find_hazards(plan) == []

    def test_bare_fetch_read_exempt(self):
        v = stf.Variable(1.0, name="bv")
        read = v.read_value()   # fetched raw, consumed by nothing
        wr = stf.assign(v, 5.0)
        plan = _plan_for([read], [wr.op])
        assert analysis.find_hazards(plan) == []

    def test_waw_detected_and_commuting_waw_not(self):
        v = stf.Variable(1.0, name="wv")
        a1 = stf.assign(v, 5.0)
        a2 = stf.assign(v, 9.0)
        plan = _plan_for([], [a1.op, a2.op])
        hz = analysis.find_hazards(plan)
        assert [h.kind for h in hz] == ["waw"]
        stf.reset_default_graph()
        w = stf.Variable(1.0, name="wv2")
        b1 = stf.assign_add(w, 5.0)
        b2 = stf.assign_sub(w, 2.0)
        plan2 = _plan_for([], [b1.op, b2.op])
        assert analysis.find_hazards(plan2) == []

    def test_raise_mode_in_session(self):
        _, consumed, wr = self._racy()
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        with pytest.raises(stf.errors.InvalidArgumentError) as ei:
            sess.run([consumed, wr])
        msg = str(ei.value)
        assert "hazard" in msg and "control_dependencies" in msg
        assert "test_analysis.py" in msg  # op-source attribution

    def test_warn_mode_runs(self):
        analysis.set_hazard_mode("warn")
        _, consumed, wr = self._racy()
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        out = sess.run([consumed, wr])
        assert np.asarray(out[1]) == 5.0

    def test_session_config_overrides_process_mode(self):
        analysis.set_hazard_mode("raise")
        _, consumed, wr = self._racy()
        sess = stf.Session(config=stf.ConfigProto(
            variable_hazard_mode="off"))
        sess.run(stf.global_variables_initializer())
        sess.run([consumed, wr])  # does not raise

    def test_auto_deps_deterministic_across_runs(self):
        analysis.set_hazard_mode("auto_deps")
        v, consumed, wr = self._racy()
        init = stf.global_variables_initializer()
        sess = stf.Session()
        seen = set()
        for _ in range(10):
            sess.run(init)  # identical state every iteration
            r, w = sess.run([consumed, wr])
            seen.add((float(r), float(w)))
        assert len(seen) == 1, f"auto_deps must be deterministic: {seen}"
        # program order: the read was created first, so it observes the
        # initial value
        assert seen == {(1.0, 5.0)}

    def test_hazard_counters_emitted(self):
        before = {k: c for k, c in _hazard_counter_values().items()}
        _, consumed, wr = self._racy()
        plan = _plan_for([consumed], [wr.op])
        analysis.check_plan(plan, mode="warn")
        after = _hazard_counter_values()
        grew = sum(after.values()) - sum(before.values())
        assert grew >= 1


def _hazard_counter_values():
    fam = monitoring.export().get("/stf/analysis/hazards", {})
    return dict(fam.get("cells", {}))


# ---------------------------------------------------------------------------
# hazard fuzz: detected hazards <=> order-dependent results
# ---------------------------------------------------------------------------

def _interpret(plan, order, init_state):
    """Reference numpy interpreter over the tiny fuzz op vocabulary;
    returns (fetchable op -> value, final state)."""
    state = dict(init_state)
    env = {}
    for op in order:
        t = op.type
        if t == "Const":
            env[op.outputs[0]] = float(np.asarray(op.attrs["value"]))
        elif t == "ReadVariable":
            env[op.outputs[0]] = state[op.attrs["var_name"]]
        elif t == "Assign":
            val = env[op.inputs[0]]
            state[op.attrs["var_name"]] = val
            env[op.outputs[0]] = val
        elif t == "AssignAdd":
            val = state[op.attrs["var_name"]] + env[op.inputs[0]]
            state[op.attrs["var_name"]] = val
            env[op.outputs[0]] = val
        elif t in ("Add", "AddV2"):
            env[op.outputs[0]] = env[op.inputs[0]] + env[op.inputs[1]]
        else:
            raise AssertionError(f"fuzz interpreter: unexpected op {t}")
    return env, state


def _topo_orders_swapping(plan, first, second):
    """Two topological orders of ``plan``: one scheduling ``first``
    before ``second``, one the reverse. Kahn, prioritizing the preferred
    op's whole ancestor cone (just preferring the op itself is not
    enough — its inputs must overtake the other op too); remaining ties
    break by plan position. For an unordered pair this guarantees the
    preferred op really does run first: nothing in its cone can be
    blocked behind the other op, or the pair would be ordered."""
    pos = {op: i for i, op in enumerate(plan)}
    plan_set = set(plan)

    def deps(op):
        for t in op.inputs:
            if t.op in plan_set:
                yield t.op
        for c in op.control_inputs:
            if c in plan_set:
                yield c

    def cone(root):
        out = set()
        work = [root]
        while work:
            op = work.pop()
            if op in out:
                continue
            out.add(op)
            work.extend(deps(op))
        return out

    def order(prefer):
        cone_set = cone(prefer)
        indeg = {op: 0 for op in plan}
        succ = {op: [] for op in plan}
        for op in plan:
            for d in set(deps(op)):
                indeg[op] += 1
                succ[d].append(op)
        ready = [op for op in plan if indeg[op] == 0]
        out = []
        while ready:
            ready.sort(key=lambda op: (0 if op in cone_set else 1,
                                       pos[op]))
            op = ready.pop(0)
            out.append(op)
            for s in succ[op]:
                indeg[s] -= 1
                if indeg[s] == 0:
                    ready.append(s)
        assert len(out) == len(plan)
        return out

    # NOTE: for a pair that is actually ordered by the graph, both
    # orders necessarily agree on the pair's direction — the no-hazard
    # soundness sweep passes arbitrary pairs through here
    return order(first), order(second)


class TestHazardFuzz:
    N_GRAPHS = 25

    def _random_graph(self, rng):
        n_vars = rng.randint(1, 3)
        init = {f"fz{i}": float(101 + 13 * i) for i in range(n_vars)}
        vars_ = [stf.Variable(init[f"fz{i}"], name=f"fz{i}")
                 for i in range(n_vars)]
        const_val = [1000.0]
        stateful = []
        reads = []
        fetch_ops = []
        writes = []
        assigned = set()
        for _ in range(rng.randint(3, 9)):
            v = vars_[rng.randint(0, n_vars)]
            kind = rng.randint(0, 3)
            ctx = None
            if stateful and rng.rand() < 0.4:
                ctx = stf.control_dependencies(
                    [stateful[rng.randint(0, len(stateful))]])
            if ctx is not None:
                ctx.__enter__()
            try:
                if kind == 0:
                    r = v.read_value()
                    reads.append(r)
                    stateful.append(r.op)
                else:
                    const_val[0] += 7.0  # unique write values
                    # overwrite only as a variable's FIRST write: a later
                    # overwrite can mask an unordered pair's effect
                    # entirely (dead write), making a structurally real
                    # WAW hazard unobservable — this generator keeps
                    # every hazard observable so the iff-assertion is
                    # strict
                    if v.op.name in assigned:
                        w = stf.assign_add(v, const_val[0])
                    else:
                        assigned.add(v.op.name)
                        w = stf.assign(v, const_val[0])
                    stateful.append(w.op)
                    fetch_ops.append(w.op)
                    writes.append(w)
            finally:
                if ctx is not None:
                    ctx.__exit__(None, None, None)
        fetch = None
        if reads:
            # start the chain from a constant so EVERY read (even a
            # lone one) is consumed inside the step — bare-fetch reads
            # are exempt from hazard detection by design
            fetch = stf.constant(0.0)
            for r in reads:
                fetch = fetch + r
        targets = ([fetch.op] if fetch is not None else []) + fetch_ops
        plan = lowering.prune(targets, set())
        return plan, init, fetch, writes

    @staticmethod
    def _result(plan, order, init, fetch):
        env, state = _interpret(plan, order, init)
        fval = env[fetch] if fetch is not None else None
        return (fval, tuple(sorted(state.items())))

    def test_fuzz_hazards_iff_order_dependent(self):
        rng = np.random.RandomState(1234)
        n_with_hazards = 0
        for gi in range(self.N_GRAPHS):
            stf.reset_default_graph()
            plan, init, fetch, _writes = self._random_graph(rng)
            if len(plan) < 2:
                continue
            hazards = analysis.find_hazards(plan)
            if not hazards:
                # soundness: no hazard => every topological order agrees
                results = set()
                for a in plan:
                    for b in plan:
                        if a is b:
                            continue
                        o1, o2 = _topo_orders_swapping(plan, a, b)
                        results.add(self._result(plan, o1, init, fetch))
                        results.add(self._result(plan, o2, init, fetch))
                assert len(results) == 1, (
                    f"graph {gi}: no hazard detected but orders "
                    f"disagree: {results}")
            else:
                n_with_hazards += 1
                # every detected hazard corresponds to an
                # order-dependent result: swapping just that pair
                # changes the outcome
                for h in hazards:
                    o1, o2 = _topo_orders_swapping(plan, h.first,
                                                   h.second)
                    r1 = self._result(plan, o1, init, fetch)
                    r2 = self._result(plan, o2, init, fetch)
                    assert r1 != r2, (
                        f"graph {gi}: hazard {h} reported but both "
                        f"orders agree: {r1}")
        assert n_with_hazards >= 3, (
            "fuzz generator produced too few hazardous graphs for the "
            f"test to be meaningful ({n_with_hazards})")

    def test_fuzz_auto_deps_matches_program_order_semantics(self):
        """auto_deps makes hazardous graphs run deterministically, with
        the program-order semantics the reference's auto-control-deps
        define: the session result must equal the reference interpreter
        on the program-ordered plan, across repeated runs."""
        rng = np.random.RandomState(99)
        checked = 0
        for _ in range(10):
            stf.reset_default_graph()
            plan, init, fetch, writes = self._random_graph(rng)
            if fetch is None or not analysis.find_hazards(plan):
                continue
            checked += 1
            ordered, _ = analysis.check_plan(plan, mode="auto_deps")
            expect_env, _ = _interpret(plan, ordered, init)
            analysis.set_hazard_mode("auto_deps")
            sess = stf.Session()
            init_op = stf.global_variables_initializer()
            seen = set()
            for _run in range(4):
                sess.run(init_op)
                got = sess.run([fetch] + writes)
                seen.add(tuple(float(np.asarray(x)) for x in got))
            assert len(seen) == 1, f"auto_deps nondeterministic: {seen}"
            assert next(iter(seen))[0] == expect_env[fetch]
            analysis.set_hazard_mode("raise")
        assert checked >= 2


# ---------------------------------------------------------------------------
# lint rules
# ---------------------------------------------------------------------------

class TestLintRules:
    def _codes(self, diags):
        return {d.code for d in diags}

    def test_int_div_float_fires(self):
        a = stf.constant(np.array([7], np.int32))
        b = stf.constant(np.array([2], np.int32))
        q = stf.floordiv(a, b)
        stf.cast(q, stf.float32)
        diags = analysis.lint_graph(stf.get_default_graph())
        assert "lint/int-div-float" in self._codes(diags)

    def test_int_div_float_quiet_on_int_consumers(self):
        a = stf.constant(np.array([7], np.int32))
        q = stf.floordiv(a, stf.constant(np.array([2], np.int32)))
        q + stf.constant(np.array([1], np.int32))
        diags = analysis.lint_graph(stf.get_default_graph())
        assert "lint/int-div-float" not in self._codes(diags)

    def test_narrow_64bit_flags_wide_placeholder(self):
        stf.placeholder(stf.int64, [2], name="wide")
        diags = analysis.lint_graph(stf.get_default_graph())
        hits = [d for d in diags if d.code == "lint/narrow-64bit"]
        assert hits and hits[0].severity == analysis.NOTE

    def test_narrow_64bit_quiet_on_int32(self):
        stf.placeholder(stf.int32, [2])
        diags = analysis.lint_graph(stf.get_default_graph())
        assert "lint/narrow-64bit" not in self._codes(diags)

    def test_unseeded_rng_fires_and_seeding_silences(self):
        stf.random_uniform([2])
        diags = analysis.lint_graph(stf.get_default_graph())
        assert "lint/unseeded-rng" in self._codes(diags)
        stf.reset_default_graph()
        stf.set_random_seed(7)
        stf.random_uniform([2])
        diags2 = analysis.lint_graph(stf.get_default_graph())
        assert "lint/unseeded-rng" not in self._codes(diags2)

    def test_const_fetch_fires_only_with_fetches(self):
        c = stf.constant(2.0) * stf.constant(3.0)
        diags = analysis.lint_graph(stf.get_default_graph())
        assert "lint/const-fetch" not in self._codes(diags)
        diags2 = analysis.lint_graph(stf.get_default_graph(),
                                     fetches=[c])
        assert "lint/const-fetch" in self._codes(diags2)

    def test_const_fetch_quiet_on_fed_graphs(self):
        x = stf.placeholder(stf.float32, [2])
        y = x * stf.constant(2.0)
        diags = analysis.lint_graph(stf.get_default_graph(),
                                    fetches=[y])
        assert "lint/const-fetch" not in self._codes(diags)

    def test_transpose_pair_fires(self):
        x = stf.placeholder(stf.float32, [1, 2, 3, 4])
        t1 = stf.transpose(x, [0, 3, 1, 2])
        stf.transpose(t1, [0, 2, 3, 1])
        diags = analysis.lint_graph(stf.get_default_graph())
        assert "lint/transpose-pair" in self._codes(diags)

    def test_transpose_pair_quiet_on_non_inverse(self):
        x = stf.placeholder(stf.float32, [1, 2, 3, 4])
        t1 = stf.transpose(x, [0, 3, 1, 2])
        stf.transpose(t1, [0, 3, 1, 2])
        diags = analysis.lint_graph(stf.get_default_graph())
        assert "lint/transpose-pair" not in self._codes(diags)

    def test_severity_override_and_off(self):
        stf.random_uniform([2])
        diags = analysis.lint_graph(
            stf.get_default_graph(),
            severities={"lint/unseeded-rng": "error"})
        assert any(d.code == "lint/unseeded-rng" and d.is_error
                   for d in diags)
        diags2 = analysis.lint_graph(
            stf.get_default_graph(),
            severities={"unseeded-rng": "off"})
        assert "lint/unseeded-rng" not in self._codes(diags2)

    def test_custom_rule_registration(self):
        @analysis.register_lint_rule("test-no-matmul", analysis.WARNING)
        def _no_matmul(ctx):
            for op in ctx.ops:
                if op.type == "MatMul":
                    yield op, "matmul forbidden by test rule"

        try:
            x = stf.placeholder(stf.float32, [2, 2])
            stf.matmul(x, x)
            diags = analysis.lint_graph(stf.get_default_graph(),
                                        rules=["lint/test-no-matmul"])
            assert [d.code for d in diags] == ["lint/test-no-matmul"]
        finally:
            from simple_tensorflow_tpu.analysis import lint as lint_mod

            lint_mod._RULES.pop("lint/test-no-matmul", None)


# ---------------------------------------------------------------------------
# session + passmanager wiring
# ---------------------------------------------------------------------------

class TestWiring:
    def test_strict_session_rejects_broken_graph(self):
        g = stf.get_default_graph()
        a = stf.constant(np.ones((2,), np.float32))
        g.create_op("Add", [a, a], name="bad_specs",
                    output_specs=[(a.shape, stf.int32)])
        with pytest.raises(stf.errors.InvalidArgumentError):
            stf.Session(config=stf.ConfigProto(graph_analysis="strict"))

    def test_strict_session_accepts_clean_graph(self):
        x = stf.placeholder(stf.float32, [2])
        y = x * 2.0
        sess = stf.Session(config=stf.ConfigProto(
            graph_analysis="strict"))
        out = sess.run(y, {x: np.ones(2, np.float32)})
        assert np.allclose(out, 2.0)

    def test_passmanager_detects_breaking_pass(self):
        from simple_tensorflow_tpu.framework import optimizer

        stf.constant(1.0, name="keepme")
        gd = graph_io.graph_to_graphdef(stf.get_default_graph())

        def breaker(graph_def, keep):
            import copy

            out = copy.deepcopy(graph_def)
            out["node"].append({"name": "broken", "op": "Add",
                                "input": ["nowhere:0", "nowhere:1"],
                                "control_input": [], "attr": {}})
            return out

        pm = optimizer.PassManager(
            [optimizer.GraphPass("breaker", breaker)], verify=True)
        with pytest.raises(stf.errors.InternalError) as ei:
            pm.run(gd, keep=["keepme"])
        assert "breaker" in str(ei.value)

    def test_passmanager_default_pipeline_verifies_clean(self):
        from simple_tensorflow_tpu.framework import optimizer

        x = stf.placeholder(stf.float32, [2, 2], name="pmx")
        y = stf.matmul(x, x)
        r = stf.reduce_sum(y, name="pmr")
        gd = graph_io.graph_to_graphdef(stf.get_default_graph())
        pm = optimizer.PassManager(verify=True)
        out = pm.run(gd, keep=["pmr", "pmx"])
        assert analysis.errors(analysis.verify_graphdef(out)) == []


# ---------------------------------------------------------------------------
# graph_lint CLI + debug CLI rendering
# ---------------------------------------------------------------------------

class TestTools:
    def test_graph_lint_cli(self, tmp_path, capsys):
        from simple_tensorflow_tpu.tools import graph_lint

        x = stf.placeholder(stf.float32, [2, 2], name="gx")
        stf.matmul(x, x, name="gy")
        gd = graph_io.graph_to_graphdef(stf.get_default_graph())
        p = tmp_path / "g.json"
        p.write_text(json.dumps(gd))
        rc = graph_lint.main([str(p), "--fetch", "gy:0"])
        out = capsys.readouterr().out
        assert rc == 0 and "0 error(s)" in out
        # break it
        gd["node"][-1]["input"] = ["ghost:0", "ghost:0"]
        p.write_text(json.dumps(gd))
        rc2 = graph_lint.main([str(p)])
        out2 = capsys.readouterr().out
        assert rc2 == 1 and "verifier/dangling-input" in out2

    def test_debug_cli_renders_effects_and_traceback(self, tmp_path):
        from simple_tensorflow_tpu.debug.cli import AnalyzerCLI

        v = stf.Variable(1.0, name="cliv")
        stf.assign(v, 2.0, name="cliw")
        cli = AnalyzerCLI(str(tmp_path), graph=stf.get_default_graph())
        out = cli.run_command("ni cliw")
        assert "effects: writes={var_name=cliv}" in out
        assert "created at:" in out and "test_analysis.py" in out
