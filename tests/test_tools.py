"""Serving-story tools: Estimator.export_savedmodel, freeze_graph,
inspect_checkpoint, strip_unused, optimize_for_inference
(ref: python/tools/{freeze_graph,inspect_checkpoint,strip_unused,
optimize_for_inference}.py, estimator export path)."""

import io
import json
import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.framework import graph_io
from simple_tensorflow_tpu import tools


def _train_small_model(tmp_path):
    """Train y = x @ w + b briefly; save checkpoint + graph; return paths
    and the final weights."""
    stf.reset_default_graph()
    rng = np.random.RandomState(0)
    X = rng.rand(64, 3).astype(np.float32)
    W_true = np.float32([[1.0], [-2.0], [0.5]])
    Y = X @ W_true

    x = stf.placeholder(stf.float32, [None, 3], name="x")
    w = stf.Variable(np.zeros((3, 1), np.float32), name="w")
    b = stf.Variable(np.zeros((1,), np.float32), name="b")
    pred = stf.add(stf.matmul(x, w), b, name="pred")
    y = stf.placeholder(stf.float32, [None, 1], name="y")
    loss = stf.reduce_mean(stf.square(pred - y))
    train_op = stf.train.GradientDescentOptimizer(0.5).minimize(loss)

    sess = stf.Session()
    sess.run(stf.global_variables_initializer())
    for _ in range(60):
        sess.run(train_op, {x: X, y: Y})
    w_val, b_val = sess.run([w, b])
    ckpt = stf.train.Saver().save(sess, str(tmp_path / "model"),
                                  global_step=60)
    gd = graph_io.graph_to_graphdef(stf.get_default_graph())
    graph_path = str(tmp_path / "graph.json")
    with open(graph_path, "w") as f:
        json.dump(gd, f)
    return graph_path, ckpt, w_val, b_val, X, Y


class TestFreezeGraph:
    def test_freeze_and_run_without_checkpoint(self, tmp_path):
        graph_path, ckpt, w_val, b_val, X, Y = _train_small_model(tmp_path)
        frozen_path = str(tmp_path / "frozen.json")
        frozen = tools.freeze_graph(graph_path, ckpt, "pred",
                                    output_graph=frozen_path)
        ops = {n["op"] for n in frozen["node"]}
        assert "VariableV2" not in ops and "ReadVariable" not in ops
        assert "Assign" not in ops  # optimizer/init machinery pruned

        # import the frozen graph into a fresh graph and run WITHOUT any
        # variable initialization or restore
        stf.reset_default_graph()
        with open(frozen_path) as f:
            frozen_loaded = json.load(f)
        (pred_t,) = graph_io.import_graph_def(
            frozen_loaded, return_elements=["pred:0"], name="")
        x_t = stf.get_default_graph().as_graph_element("x:0")
        with stf.Session() as sess:
            out = sess.run(pred_t, {x_t: X})
        np.testing.assert_allclose(out, X @ w_val + b_val, rtol=1e-5)
        np.testing.assert_allclose(out, Y, atol=0.15)  # it did train

    def test_missing_variable_raises(self, tmp_path):
        graph_path, ckpt, *_ = _train_small_model(tmp_path)
        with open(graph_path) as f:
            gd = json.load(f)
        with pytest.raises(ValueError, match="not in"):
            tools.freeze_graph_def(gd, {"only_this": np.zeros(1)}, "pred")


class TestInspectCheckpoint:
    def test_lists_tensors(self, tmp_path):
        _, ckpt, w_val, b_val, _, _ = _train_small_model(tmp_path)
        buf = io.StringIO()
        tensors = tools.print_tensors_in_checkpoint_file(ckpt, out=buf)
        listing = buf.getvalue()
        assert "w" in tensors and "b" in tensors
        assert "dtype=float32" in listing and "shape=[3, 1]" in listing
        np.testing.assert_allclose(tensors["w"], w_val)

    def test_single_tensor_with_values(self, tmp_path):
        _, ckpt, w_val, _, _, _ = _train_small_model(tmp_path)
        buf = io.StringIO()
        out = tools.print_tensors_in_checkpoint_file(
            ckpt, tensor_name="w", out=buf)
        assert list(out) == ["w"]
        assert str(float(w_val[0, 0]))[:4] in buf.getvalue()


class TestCkptInspectCLI:
    """ISSUE 10 satellite: ``python -m simple_tensorflow_tpu.tools.
    ckpt_inspect <dir>`` lists checkpoints, tensors/shapes/shardings,
    verifies checksums, and exits 1 on corruption."""

    def _checkpoint_dir(self, tmp_path):
        import simple_tensorflow_tpu as stf
        from simple_tensorflow_tpu import checkpoint as ckpt_mod

        stf.reset_default_graph()
        stf.Variable(stf.constant(np.ones((4, 2), np.float32)),
                     name="ci/kernel")
        gs = stf.train.get_or_create_global_step()
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        d = str(tmp_path / "ckpts")
        mgr = ckpt_mod.CheckpointManager(d, max_to_keep=3)
        mgr.save(sess, global_step=3, blocking=True)
        mgr.save(sess, global_step=7, blocking=True)
        return d, mgr

    def test_lists_and_verifies_in_process(self, tmp_path):
        d, mgr = self._checkpoint_dir(tmp_path)
        from simple_tensorflow_tpu.tools import ckpt_inspect

        out = io.StringIO()
        rc = ckpt_inspect.run(d, tensors=True, out=out)
        text = out.getvalue()
        assert rc == 0
        assert "step=3" in text and "step=7" in text
        assert "ci/kernel  dtype=float32 shape=[4, 2]" in text
        assert "all verified" in text
        # --json shape
        out = io.StringIO()
        rc = ckpt_inspect.run(d, as_json=True, out=out)
        doc = json.loads(out.getvalue())
        assert rc == 0 and doc["ok"]
        assert [c["step"] for c in doc["checkpoints"]] == [3, 7]
        assert doc["checkpoints"][0]["host_state"][
            "rng_run_counter"] is not None

    def test_cli_subprocess_exit_codes(self, tmp_path):
        import subprocess
        import sys

        d, mgr = self._checkpoint_dir(tmp_path)
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        cmd = [sys.executable, "-m",
               "simple_tensorflow_tpu.tools.ckpt_inspect", d]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "all verified" in proc.stdout
        # flip one byte -> CORRUPT + exit 1
        latest = mgr.latest_checkpoint
        with open(latest + ".stfz", "r+b") as f:
            f.seek(25)
            b = f.read(1)
            f.seek(25)
            f.write(bytes([b[0] ^ 0xFF]))
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300, env=env)
        assert proc.returncode == 1, proc.stdout
        assert "CORRUPT" in proc.stdout
        assert "checksum" in proc.stdout

    def test_empty_dir_exits_nonzero(self, tmp_path):
        from simple_tensorflow_tpu.tools import ckpt_inspect

        out = io.StringIO()
        assert ckpt_inspect.run(str(tmp_path), out=out) == 1
        assert "no checkpoints found" in out.getvalue()


class TestStripUnused:
    def test_prunes_to_subgraph(self, tmp_path):
        graph_path, ckpt, *_ = _train_small_model(tmp_path)
        frozen = tools.freeze_graph(graph_path, ckpt, "pred")
        # strip with x as the input: everything else (y, loss, grads chain
        # leftovers) must be gone
        stripped = tools.strip_unused_nodes(frozen, "x", "pred")
        names = {n["name"] for n in stripped["node"]}
        assert "pred" in names and "x" in names
        assert not any("grad" in n or n == "y" for n in names), names
        x_node = next(n for n in stripped["node"] if n["name"] == "x")
        assert x_node["op"] == "Placeholder"

    def test_missing_input_raises(self, tmp_path):
        graph_path, ckpt, *_ = _train_small_model(tmp_path)
        frozen = tools.freeze_graph(graph_path, ckpt, "pred")
        with pytest.raises(ValueError, match="not in graph"):
            tools.strip_unused_nodes(frozen, "nope", "pred")


class TestOptimizeForInference:
    def test_folds_frozen_conv_bn(self, tmp_path):
        stf.reset_default_graph()
        rng = np.random.RandomState(1)
        x = stf.placeholder(stf.float32, [2, 8, 8, 3], name="img")
        h = stf.layers.conv2d(x, 4, 3, padding="same", use_bias=False,
                              name="c1")
        # inference-mode BN: running stats become Consts after freezing
        h = stf.layers.batch_normalization(h, training=False, fused=True,
                                           name="bn1")
        out = stf.identity(h, name="out")
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        # give the stats non-trivial values so folding is actually tested
        for vname, val in [("bn1/moving_mean", rng.rand(4)),
                           ("bn1/moving_variance", 1.0 + rng.rand(4)),
                           ("bn1/gamma", 1.0 + 0.3 * rng.rand(4)),
                           ("bn1/beta", rng.rand(4))]:
            var = [v for v in stf.global_variables()
                   if v.var_name == vname][0]
            sess.run(stf.assign(var, val.astype(np.float32)))
        img = rng.rand(2, 8, 8, 3).astype(np.float32)
        ref = sess.run(out, {x: img})
        ckpt = stf.train.Saver().save(sess, str(tmp_path / "m"))
        gd = graph_io.graph_to_graphdef(stf.get_default_graph())

        frozen = tools.freeze_graph_def(
            gd, {k.replace("|", "/"): v
                 for k, v in np.load(ckpt + ".stfz").items()}, "out")
        opt = tools.optimize_for_inference(frozen, "img", "out")
        ops = [n["op"] for n in opt["node"]]
        assert "FusedBatchNorm" not in ops, ops
        # pass-through removal: the only Identity left is the protected
        # output node itself
        identities = [n["name"] for n in opt["node"]
                      if n["op"] == "Identity"]
        assert identities == ["out"], identities
        assert "BiasAdd" in ops and "Conv2D" in ops

        stf.reset_default_graph()
        (out_t,) = graph_io.import_graph_def(opt, return_elements=["out:0"],
                                             name="")
        x_t = stf.get_default_graph().as_graph_element("img:0")
        with stf.Session() as s2:
            folded = s2.run(out_t, {x_t: img})
        np.testing.assert_allclose(folded, ref, rtol=1e-4, atol=1e-5)


class TestEstimatorExport:
    def _model_fn(self, features, labels, mode, params=None):
        from simple_tensorflow_tpu import estimator as est

        w = stf.get_variable("w", [2, 1], initializer=stf.zeros_initializer())
        pred = stf.matmul(features["x"], w)
        if mode == est.ModeKeys.PREDICT:
            return est.EstimatorSpec(mode, predictions={"pred": pred})
        loss = stf.reduce_mean(stf.square(pred - labels))
        gs = stf.train.get_or_create_global_step()
        train_op = stf.train.GradientDescentOptimizer(0.2).minimize(
            loss, global_step=gs)
        return est.EstimatorSpec(mode, loss=loss, train_op=train_op,
                                 predictions={"pred": pred})

    def test_export_load_predict_roundtrip(self, tmp_path):
        from simple_tensorflow_tpu import estimator as est
        from simple_tensorflow_tpu import saved_model as sm

        rng = np.random.RandomState(0)
        X = rng.rand(32, 2).astype(np.float32)
        Y = X @ np.float32([[1.0], [2.0]])

        def input_fn():
            from simple_tensorflow_tpu import data as stf_data

            ds = stf_data.Dataset.from_tensor_slices(
                {"x": X, "y": Y}).repeat().batch(8)
            f = ds.make_one_shot_iterator().get_next()
            return {"x": f["x"]}, f["y"]

        e = est.Estimator(self._model_fn, model_dir=str(tmp_path / "md"))
        e.train(input_fn, steps=50)

        receiver_fn = est.build_raw_serving_input_receiver_fn(
            {"x": ([None, 2], stf.float32)})
        export_dir = e.export_savedmodel(str(tmp_path / "export"),
                                         receiver_fn)
        assert os.path.isdir(export_dir)

        # load the SavedModel in a fresh graph and serve
        stf.reset_default_graph()
        with stf.Session() as sess:
            meta = sm.load(sess, [sm.tag_constants.SERVING], export_dir)
            sig = meta["signature_def"][
                sm.signature_constants.DEFAULT_SERVING_SIGNATURE_DEF_KEY]
            x_name = sig["inputs"]["x"]["name"]
            pred_name = sig["outputs"]["pred"]["name"]
            out = sess.run(pred_name, {x_name: X})
        np.testing.assert_allclose(out, Y, atol=0.2)


def test_remove_training_nodes_follows_control_deps(tmp_path):
    """Control deps on a spliced-out Identity must redirect to its
    producer, not dangle (would fail the prune)."""
    from simple_tensorflow_tpu.tools.optimize_for_inference import (
        remove_training_nodes)

    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [2], name="cx")
    a = stf.identity(x, name="id1")
    g = stf.get_default_graph()
    with g.control_dependencies([a.op]):
        out = stf.add(x, stf.constant(np.float32([1, 1])), name="cout")
    gd = graph_io.graph_to_graphdef(g)
    cleaned = remove_training_nodes(gd, protected=["cout"])
    names = {n["name"] for n in cleaned["node"]}
    assert "id1" not in names
    cout = next(n for n in cleaned["node"] if n["name"] == "cout")
    assert all(c in names for c in cout["control_input"]), cout
    # and the prune that optimize_for_inference runs afterwards succeeds
    from simple_tensorflow_tpu.tools import graph_rewrite as gr

    pruned = gr.prune_to(cleaned, ["cout"])
    assert "cout" in {n["name"] for n in pruned["node"]}


class TestDebugAnalyzerCLI:
    """tfdbg-style CLI (ref: python/debug/cli/analyzer_cli.py) driven
    programmatically through run_command."""

    def _make_dump(self, tmp_path):
        from simple_tensorflow_tpu import debug as stf_debug

        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [2, 2], name="cli_x")
        y = stf.square(x, name="cli_sq")
        z = stf.reduce_sum(y, name="cli_sum")
        sess = stf.Session()
        wrapped = stf_debug.DumpingDebugWrapperSession(
            sess, str(tmp_path / "dumps"))
        wrapped.run(z, {x: np.array([[1., 2.], [3., np.inf]], np.float32)})
        return stf_debug.AnalyzerCLI(
            stf_debug.DebugDumpDir(str(tmp_path / "dumps")),
            graph=stf.get_default_graph())

    def test_lt_pt_runs_nan(self, tmp_path):
        cli = self._make_dump(tmp_path)
        lt = cli.run_command("lt")
        assert "cli_sq" in lt and "shape=(2, 2)" in lt
        assert "run_1" in cli.run_command("runs")
        pt = cli.run_command("pt cli_sq:0")
        assert "dtype=float32" in pt and "9." in pt
        pt_sliced = cli.run_command("pt cli_sq:0 -s [0]")
        assert "1." in pt_sliced and "4." in pt_sliced
        nan = cli.run_command("nan")
        assert "cli_sq" in nan or "cli_sum" in nan  # inf propagates

    def test_node_topology_commands(self, tmp_path):
        cli = self._make_dump(tmp_path)
        ni = cli.run_command("ni cli_sq")
        assert "op: Square" in ni and "cli_x" in ni
        li = cli.run_command("li cli_sq")
        assert "cli_x:0" in li
        lo = cli.run_command("lo cli_sq")
        assert "cli_sum" in lo

    def test_errors_and_aliases(self, tmp_path):
        from simple_tensorflow_tpu.debug.cli import CommandError

        cli = self._make_dump(tmp_path)
        assert cli.run_command("list_tensors") == cli.run_command("lt")
        import pytest as _pytest
        with _pytest.raises(CommandError, match="unknown command"):
            cli.run_command("wat")
        with _pytest.raises(CommandError, match="not dumped"):
            cli.run_command("pt nope:0")
        assert "commands" in cli.run_command("help")

    def test_interactive_loop(self, tmp_path):
        import io

        cli = self._make_dump(tmp_path)
        out = io.StringIO()
        cli.interactive(stdin=io.StringIO("runs\nbadcmd\nexit\n"),
                        stdout=out)
        s = out.getvalue()
        assert "run_1" in s and "error:" in s


class TestDebugSinks:
    """URL debug sinks (VERDICT r4 item 7; ref: core/debug/
    debug_io_utils.h, debug_service.proto): watched tensors stream to
    file:// dirs and tcp:// readers in other processes."""

    def _run_watched(self, debug_urls, tmp_path):
        import numpy as np

        import simple_tensorflow_tpu as stf
        from simple_tensorflow_tpu import debug as stf_debug

        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [4], name="dbg_x")
        y = stf.multiply(x, 2.0, name="dbg_y")
        sess = stf.Session()
        wrapped = stf_debug.DumpingDebugWrapperSession(
            sess, str(tmp_path / "dumps"), debug_urls=debug_urls)
        xv = np.arange(4, dtype=np.float32)
        out = wrapped.run(y, feed_dict={x: xv})
        wrapped.close()
        return xv, np.asarray(out)

    def test_file_url_sink(self, tmp_path):
        import json as _json

        import numpy as np

        sink_dir = tmp_path / "sinkdir"
        xv, out = self._run_watched([f"file://{sink_dir}"], tmp_path)
        np.testing.assert_allclose(out, xv * 2.0)
        man = _json.loads((sink_dir / "run_1" / "manifest.json")
                          .read_text())
        assert "dbg_y:0" in man["tensors"]
        got = np.load(sink_dir / "run_1" /
                      man["tensors"]["dbg_y:0"]["file"])
        np.testing.assert_allclose(got, xv * 2.0)

    def test_tcp_sink_to_in_process_listener(self, tmp_path):
        import numpy as np

        from simple_tensorflow_tpu.debug import io_utils

        listener = io_utils.DebugListener()
        try:
            xv, _ = self._run_watched(
                [f"tcp://127.0.0.1:{listener.port}"], tmp_path)
            listener.wait(timeout=30)
            names = {h["name"] for h, _ in listener.events}
            assert "dbg_y:0" in names, names
            for h, arr in listener.events:
                if h["name"] == "dbg_y:0":
                    np.testing.assert_allclose(arr, xv * 2.0)
        finally:
            listener.close()

    def test_tcp_sink_to_reader_subprocess(self, tmp_path):
        """The cross-process contract: a reader SUBPROCESS receives the
        streamed tensors (ref debug_gateway / grpc_debug_server)."""
        import json as _json
        import socket as _socket
        import subprocess
        import sys

        import numpy as np

        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        out_dir = str(tmp_path / "received")
        proc = subprocess.Popen(
            [sys.executable, "-m", "simple_tensorflow_tpu.debug.io_utils",
             "--listen", str(port), "--out", out_dir],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            # wait for the listener to come up
            line = proc.stdout.readline()
            assert "listening" in line, line
            xv, _ = self._run_watched([f"tcp://127.0.0.1:{port}"],
                                      tmp_path)
            out_text, _ = proc.communicate(timeout=60)
            lines = [_json.loads(l) for l in out_text.splitlines() if l]
            assert lines[-1].get("done", 0) >= 1, lines
            by_name = {d["name"]: d for d in lines if "name" in d}
            assert "dbg_y:0" in by_name
            got = np.load(os.path.join(out_dir, "run1_dbg_y_0.npy"))
            np.testing.assert_allclose(got, xv * 2.0)
        finally:
            if proc.poll() is None:
                proc.kill()

    def test_bad_url_raises(self):
        from simple_tensorflow_tpu.debug import io_utils

        with pytest.raises(ValueError, match="unsupported debug URL"):
            io_utils.sink_for_url("ftp://nope:1")


class TestAotCompileCLI:
    """tfcompile-equivalent CLI (VERDICT r4 item 9; ref:
    compiler/aot/compile.cc): frozen GraphDef-JSON -> self-contained
    serialized executable + manifest + servable SavedModel twin."""

    def _write_frozen_graph(self, tmp_path):
        import simple_tensorflow_tpu as stf

        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [3, 4], name="aot_x")
        w = stf.constant(
            np.arange(12, dtype=np.float32).reshape(4, 3) * 0.1,
            name="aot_w")
        y = stf.tanh(stf.matmul(x, w), name="aot_y")
        from simple_tensorflow_tpu.framework import graph_io

        gd = graph_io.graph_to_graphdef(stf.get_default_graph())
        path = str(tmp_path / "g.json")
        with open(path, "w") as f:
            json.dump(gd, f)
        xv = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        expected = stf.Session().run(y, {x: xv})
        return path, xv, np.asarray(expected)

    def test_cli_compile_load_run(self, tmp_path):
        import subprocess
        import sys

        graph_path, xv, expected = self._write_frozen_graph(tmp_path)
        out_dir = str(tmp_path / "prog")
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-m",
             "simple_tensorflow_tpu.tools.aot_compile",
             "--graph", graph_path, "--feed", "aot_x:0",
             "--fetch", "aot_y:0", "--out", out_dir],
            capture_output=True, text=True, timeout=300, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
        assert summary["n_fetches"] == 1

        # artifact layout
        assert os.path.exists(os.path.join(out_dir, "program.stablehlo"))
        manifest = json.load(open(os.path.join(out_dir, "manifest.json")))
        assert manifest["format"] == "stf-aot-v1"
        assert manifest["feeds"][0]["shape"] == [3, 4]
        assert os.path.isdir(os.path.join(out_dir, "saved_model"))

        # load + run the serialized program
        from simple_tensorflow_tpu import tools

        prog = tools.load_aot_program(out_dir)
        (got,) = prog(xv)
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5)

    def test_artifact_serves_through_savedmodel(self, tmp_path):
        """The saved_model twin loads through the ordinary loader (the
        same path StfSessionLoad drives from C)."""
        import simple_tensorflow_tpu as stf
        from simple_tensorflow_tpu import saved_model as sm
        from simple_tensorflow_tpu import tools

        graph_path, xv, expected = self._write_frozen_graph(tmp_path)
        out_dir = str(tmp_path / "prog2")
        with open(graph_path) as f:
            tools.aot_compile(f.read(), ["aot_x:0"], ["aot_y:0"], out_dir)
        stf.reset_default_graph()
        sess = stf.Session()
        sm.load(sess, [sm.tag_constants.SERVING],
                os.path.join(out_dir, "saved_model"))
        g = sess.graph
        got = sess.run(
            g.as_graph_element("aot_y:0", True, False),
            {g.as_graph_element("aot_x:0", True, False): xv})
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5)

    def test_stateful_graph_rejected(self, tmp_path):
        import simple_tensorflow_tpu as stf
        from simple_tensorflow_tpu import tools
        from simple_tensorflow_tpu.framework import graph_io

        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [2], name="sx")
        v = stf.Variable(np.ones(2, np.float32), name="sv")
        y = stf.add(x, v._ref, name="sy")
        gd = json.dumps(graph_io.graph_to_graphdef(
            stf.get_default_graph()))
        with pytest.raises(ValueError, match="stateful"):
            tools.aot_compile(gd, ["sx:0"], ["sy:0"],
                              str(tmp_path / "bad"))


class TestSelectiveRegistrationHeader:
    def test_header_lists_graph_ops(self):
        from simple_tensorflow_tpu import tools

        gd = {"node": [
            {"name": "a", "op": "Const", "attr": {}},
            {"name": "b", "op": "MatMul", "attr": {}},
            {"name": "c", "op": "Relu", "attr": {}},
        ]}
        ops = tools.required_ops([gd])
        assert ops == ["Const", "MatMul", "Relu"]
        header = tools.header_for_graphs([gd])
        assert '"MatMul",' in header
        # graph ops + the always-registered defaults (NoOp/_Recv/_Send)
        assert "kNumNecessaryOps = 6" in header
        assert '"NoOp",' in header
        assert "SHOULD_REGISTER_OP" in header

    def test_warns_on_unregistered(self):
        from simple_tensorflow_tpu import tools

        header = tools.header_for_graphs(
            [{"node": [{"name": "z", "op": "NotARealOp", "attr": {}}]}])
        assert "WARNING" in header and "NotARealOp" in header

    def test_cli(self, tmp_path):
        import subprocess
        import sys

        gd = {"node": [{"name": "a", "op": "Const", "attr": {}}]}
        p = tmp_path / "g.json"
        p.write_text(json.dumps(gd))
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-m",
             "simple_tensorflow_tpu.tools."
             "print_selective_registration_header",
             "--graphs", str(p)],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc.returncode == 0, proc.stderr[-1500:]
        assert '"Const",' in proc.stdout
