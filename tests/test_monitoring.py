"""stf.monitoring tests: metric cells, sampler buckets, concurrent
increments, export round-trips, tracing (ISSUE 2 tentpole)."""

import json
import threading
import uuid

import pytest

from simple_tensorflow_tpu.platform import monitoring


def _name(suffix):
    # the registry is process-global: every test gets fresh family names
    return f"/test/{uuid.uuid4().hex[:8]}/{suffix}"


class TestCounter:
    def test_unlabeled_cell(self):
        c = monitoring.Counter(_name("runs"), "desc")
        assert c.get_cell().value() == 0
        c.get_cell().increase_by(1)
        c.get_cell().increase_by(4)
        assert c.get_cell().value() == 5

    def test_labeled_cells_are_independent(self):
        c = monitoring.Counter(_name("miss"), "desc", "reason")
        c.get_cell("a").increase_by(2)
        c.get_cell("b").increase_by(3)
        assert c.get_cell("a").value() == 2
        assert c.get_cell("b").value() == 3

    def test_wrong_label_arity(self):
        c = monitoring.Counter(_name("l"), "desc", "reason")
        with pytest.raises(ValueError, match="label"):
            c.get_cell()
        with pytest.raises(ValueError, match="label"):
            c.get_cell("a", "b")

    def test_counter_cannot_decrease(self):
        c = monitoring.Counter(_name("dec"), "desc")
        with pytest.raises(ValueError, match="increase"):
            c.get_cell().increase_by(-1)

    def test_duplicate_same_shape_adopts_cells(self):
        name = _name("dup")
        a = monitoring.Counter(name, "desc")
        a.get_cell().increase_by(7)
        b = monitoring.Counter(name, "desc")
        assert b.get_cell().value() == 7

    def test_duplicate_different_shape_raises(self):
        name = _name("clash")
        monitoring.Counter(name, "desc")
        with pytest.raises(ValueError, match="already registered"):
            monitoring.IntGauge(name, "desc")
        with pytest.raises(ValueError, match="already registered"):
            monitoring.Counter(name, "desc", "extra_label")

    def test_duplicate_sampler_with_different_buckets_raises(self):
        name = _name("hclash")
        monitoring.Sampler(name, monitoring.ExponentialBuckets(1.0, 2.0, 4),
                           "desc")
        # identical buckets adopt; different edges must NOT mix series
        monitoring.Sampler(name, monitoring.ExponentialBuckets(1.0, 2.0, 4),
                           "desc")
        with pytest.raises(ValueError, match="already registered"):
            monitoring.Sampler(name,
                               monitoring.ExponentialBuckets(1.0, 10.0, 4),
                               "desc")

    def test_concurrent_increments(self):
        c = monitoring.Counter(_name("conc"), "desc")
        cell = c.get_cell()

        def worker():
            for _ in range(1000):
                cell.increase_by(1)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert cell.value() == 8000


class TestGauges:
    def test_int_gauge(self):
        g = monitoring.IntGauge(_name("g"), "desc")
        assert g.get_cell().value() == 0
        g.get_cell().set(42)
        assert g.get_cell().value() == 42

    def test_string_gauge(self):
        g = monitoring.StringGauge(_name("s"), "desc", "which")
        g.get_cell("v").set("hello")
        assert g.get_cell("v").value() == "hello"

    def test_bool_gauge(self):
        g = monitoring.BoolGauge(_name("b"), "desc")
        g.get_cell().set(True)
        assert g.get_cell().value() is True


class TestSampler:
    def test_exponential_bucket_boundaries(self):
        b = monitoring.ExponentialBuckets(1.0, 2.0, 4)
        assert b.boundaries == [1.0, 2.0, 4.0, 8.0]

    def test_exponential_bucket_validation(self):
        with pytest.raises(ValueError):
            monitoring.ExponentialBuckets(0.0, 2.0, 4)
        with pytest.raises(ValueError):
            monitoring.ExponentialBuckets(1.0, 1.0, 4)
        with pytest.raises(ValueError):
            monitoring.ExplicitBuckets([1.0, 1.0])

    def test_samples_land_in_buckets(self):
        s = monitoring.Sampler(_name("h"),
                               monitoring.ExponentialBuckets(1.0, 10.0, 3),
                               "desc")
        cell = s.get_cell()
        # edges 1, 10, 100, +inf -> buckets (-inf,1], (1,10], (10,100], rest
        for v in (0.5, 5.0, 50.0, 500.0, 5000.0):
            cell.add(v)
        snap = cell.value()
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(5555.5)
        counts = [c for _, c in snap["buckets"]]
        assert counts == [1, 1, 1, 2]
        assert snap["buckets"][-1][0] == float("inf")
        assert snap["min"] == 0.5 and snap["max"] == 5000.0

    def test_boundary_exact_sample_is_le_inclusive(self):
        s = monitoring.Sampler(_name("edge"),
                               monitoring.ExplicitBuckets([1.0, 2.0]),
                               "desc")
        cell = s.get_cell()
        cell.add(1.0)  # == first edge: counts at-or-below it (le)
        counts = [c for _, c in cell.value()["buckets"]]
        assert counts == [1, 0, 0]

    def test_labeled_sampler(self):
        s = monitoring.Sampler(_name("hp"),
                               monitoring.ExponentialBuckets(1e-6, 4.0, 8),
                               "desc", "phase")
        s.get_cell("prune").add(1e-5)
        s.get_cell("optimize").add(1e-4)
        assert s.get_cell("prune").value()["count"] == 1
        assert s.get_cell("optimize").value()["count"] == 1


class TestPercentileSampler:
    def test_percentiles(self):
        p = monitoring.PercentileSampler(_name("p"), "desc",
                                         percentiles=(50.0, 90.0))
        cell = p.get_cell()
        for v in range(1, 101):
            cell.add(float(v))
        snap = cell.value()
        assert snap["count"] == 100
        assert snap["percentiles"][50.0] == pytest.approx(50.0, abs=2)
        assert snap["percentiles"][90.0] == pytest.approx(90.0, abs=2)

    def test_labels_are_positional_like_other_families(self):
        # PercentileSampler(name, desc, "label") must bind "label" as a
        # label name, never as the percentile list
        p = monitoring.PercentileSampler(_name("plbl"), "desc", "phase")
        assert p.label_names == ("phase",)
        p.get_cell("compile").add(1.0)
        assert p.get_cell("compile").value()["count"] == 1

    def test_ring_buffer_bounds_memory(self):
        p = monitoring.PercentileSampler(_name("ring"), "desc",
                                         percentiles=(50.0,), max_samples=16)
        cell = p.get_cell()
        for v in range(1000):
            cell.add(float(v))
        snap = cell.value()
        assert snap["count"] == 1000
        # only the most recent 16 samples are retained
        assert snap["percentiles"][50.0] >= 984


class TestExport:
    def test_export_round_trip(self):
        name = _name("exp")
        c = monitoring.Counter(name, "my description", "kind")
        c.get_cell("x").increase_by(3)
        exp = monitoring.export()
        assert exp[name]["type"] == "Counter"
        assert exp[name]["description"] == "my description"
        assert exp[name]["labels"] == ["kind"]
        assert exp[name]["cells"]["x"] == 3
        # to_json parses back and still contains the cell
        parsed = json.loads(monitoring.to_json())
        assert parsed[name]["cells"]["x"] == 3

    def test_prometheus_output(self):
        cname = _name("prom")
        c = monitoring.Counter(cname, "prom desc", "reason")
        c.get_cell("new").increase_by(2)
        sname = _name("promh")
        s = monitoring.Sampler(sname,
                               monitoring.ExponentialBuckets(1.0, 2.0, 2),
                               "hist desc")
        s.get_cell().add(1.5)
        text = monitoring.to_prometheus()
        pc = monitoring._prom_name(cname)
        ps = monitoring._prom_name(sname)
        assert f"# TYPE {pc} counter" in text
        assert f'{pc}{{reason="new"}} 2' in text
        assert f"# TYPE {ps} histogram" in text
        assert f"{ps}_count 1" in text

    def test_pipe_in_label_values_does_not_collide(self):
        name = _name("pipe")
        c = monitoring.Counter(name, "d", "a", "b")
        c.get_cell("x|y", "z").increase_by(1)
        c.get_cell("x", "y|z").increase_by(2)
        cells = monitoring.export()[name]["cells"]
        assert len(cells) == 2 and sorted(cells.values()) == [1, 2]
        # prometheus splits the escaped key back into the right values
        text = monitoring.to_prometheus()
        pn = monitoring._prom_name(name)
        assert f'{pn}{{a="x|y",b="z"}} 1' in text
        assert f'{pn}{{a="x",b="y|z"}} 2' in text

    def test_prometheus_escapes_label_values(self):
        name = _name("esc")
        c = monitoring.Counter(name, "line1\nline2", "path")
        c.get_cell('a"b\\c\nd').increase_by(1)
        text = monitoring.to_prometheus()
        pn = monitoring._prom_name(name)
        assert f'{pn}{{path="a\\"b\\\\c\\nd"}} 1' in text
        assert f"# HELP {pn} line1\\nline2" in text
        # no raw newline leaks into the middle of a series line
        for line in text.splitlines():
            assert not line.endswith('\\')

    def test_prometheus_histogram_buckets_are_cumulative_with_inf(self):
        # satellite (ISSUE 8): the native histogram contract —
        # _bucket series are CUMULATIVE, end at le="+Inf", and the
        # +Inf bucket equals _count
        name = _name("cum")
        s = monitoring.Sampler(name,
                               monitoring.ExplicitBuckets([1.0, 10.0]),
                               "d")
        cell = s.get_cell()
        for v in (0.5, 0.7, 5.0, 50.0):
            cell.add(v)
        text = monitoring.to_prometheus()
        pn = monitoring._prom_name(name)
        assert f'{pn}_bucket{{le="1.0"}} 2' in text
        assert f'{pn}_bucket{{le="10.0"}} 3' in text
        assert f'{pn}_bucket{{le="+Inf"}} 4' in text
        assert f"{pn}_count 4" in text
        from prom_format import validate_prometheus_text

        validate_prometheus_text(text)

    def test_prometheus_empty_label_value_keeps_pair(self):
        # a cell whose label VALUE is "" must still emit the label pair
        # (the old export()-keyed path dropped it, colliding with an
        # unlabeled series)
        name = _name("emptyv")
        c = monitoring.Counter(name, "d", "shard")
        c.get_cell("").increase_by(3)
        c.get_cell("a").increase_by(4)
        text = monitoring.to_prometheus()
        pn = monitoring._prom_name(name)
        assert f'{pn}{{shard=""}} 3' in text
        assert f'{pn}{{shard="a"}} 4' in text

    def test_prometheus_name_sanitization(self):
        # /stf/... path style -> underscores; leading digit guarded
        assert monitoring._prom_name(
            "/stf/session/executable_cache/misses") \
            == "stf_session_executable_cache_misses"
        assert monitoring._prom_name("/9lives/x") == "_9lives_x"
        assert monitoring._prom_name("///") == "_"
        name = _name("weird")
        c = monitoring.Counter(name + "/with-dash.dot", "d")
        c.get_cell().increase_by(1)
        from prom_format import validate_prometheus_text

        validate_prometheus_text(monitoring.to_prometheus())

    def test_prometheus_help_escapes_backslash(self):
        name = _name("bs")
        monitoring.Counter(name, "path C:\\tmp\nnext", )
        text = monitoring.to_prometheus()
        pn = monitoring._prom_name(name)
        assert f"# HELP {pn} path C:\\\\tmp\\nnext" in text

    def test_prometheus_summary_quantiles(self):
        name = _name("sq")
        p = monitoring.PercentileSampler(name, "d",
                                         percentiles=(50.0, 99.0))
        cell = p.get_cell()
        for v in range(1, 101):
            cell.add(float(v))
        text = monitoring.to_prometheus()
        pn = monitoring._prom_name(name)
        assert f"# TYPE {pn} summary" in text
        assert f'{pn}{{quantile="0.5"}}' in text
        assert f'{pn}{{quantile="0.99"}}' in text
        assert f"{pn}_count 100" in text

    def test_prometheus_whole_registry_validates(self):
        # whatever this process has registered so far must render as a
        # well-formed exposition (torn lines, raw newlines, bad label
        # blocks all fail the validator)
        from prom_format import validate_prometheus_text

        series = validate_prometheus_text(monitoring.to_prometheus())
        assert series  # the library's own /stf/ metrics are present

    def test_to_json_is_strict_json(self):
        name = _name("strict")
        s = monitoring.Sampler(name,
                               monitoring.ExponentialBuckets(1.0, 2.0, 2),
                               "d")
        s.get_cell().add(1.5)
        parsed = json.loads(monitoring.to_json())  # RFC-8259 parse
        edges = [e for e, _ in parsed[name]["cells"][""]["buckets"]]
        assert edges[-1] == "inf"

    def test_unregister(self):
        name = _name("gone")
        monitoring.Counter(name, "d")
        assert monitoring.get_metric(name) is not None
        monitoring.unregister(name)
        assert monitoring.get_metric(name) is None


class TestTracing:
    def test_traceme_without_collection_is_noop(self):
        with monitoring.traceme("nothing", k=1):
            pass  # no sink installed: must not raise or record

    def test_traceme_records_into_active_buffer(self):
        with monitoring.trace_collection() as buf:
            with monitoring.traceme("phase_a", detail="x"):
                pass
            with monitoring.traceme("phase_b"):
                pass
        spans = buf.drain()
        names = [s["name"] for s in spans]
        assert names == ["phase_a", "phase_b"]
        assert spans[0]["meta"] == {"detail": "x"}
        assert all(s["dur_s"] >= 0 for s in spans)
        # buffer detached after the with block
        with monitoring.traceme("after"):
            pass
        assert len(buf) == 0

    def test_nested_collections_both_record(self):
        with monitoring.trace_collection() as outer:
            with monitoring.trace_collection() as inner:
                with monitoring.traceme("span"):
                    pass
            assert len(inner) == 1
            assert len(outer) == 1

    def test_record_span_manual(self):
        with monitoring.trace_collection() as buf:
            monitoring.record_span("manual", 1.0, 0.5, n=3)
        (span,) = buf.drain()
        assert span["name"] == "manual"
        assert span["dur_s"] == 0.5
        assert span["meta"] == {"n": 3}

    def test_tracing_active(self):
        assert not monitoring.tracing_active()
        with monitoring.trace_collection():
            assert monitoring.tracing_active()
        assert not monitoring.tracing_active()
