"""Variable / variable_scope semantics (mirrors ref variables_test.py,
variable_scope_test.py)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


class TestVariable:
    def test_init_read_assign(self):
        v = stf.Variable(stf.constant([1.0, 2.0]), name="v")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(v.value()).tolist() == [1.0, 2.0]
            sess.run(stf.assign(v, stf.constant([5.0, 6.0])))
            assert sess.run(v.value()).tolist() == [5.0, 6.0]
            sess.run(stf.assign_add(v, stf.constant([1.0, 1.0])))
            assert sess.run(v.value()).tolist() == [6.0, 7.0]
            sess.run(stf.assign_sub(v, stf.constant([2.0, 2.0])))
            assert sess.run(v.value()).tolist() == [4.0, 5.0]

    def test_uninitialized_raises(self):
        v = stf.Variable(stf.ones([2]), name="u")
        with stf.Session() as sess:
            with pytest.raises(stf.errors.FailedPreconditionError):
                sess.run(v.value())

    def test_initialized_value_chain(self):
        v = stf.Variable(stf.constant(3.0), name="a")
        w = stf.Variable(v.initialized_value() * 2.0, name="b")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert float(sess.run(w.value())) == 6.0

    def test_trainable_collections(self):
        a = stf.Variable(stf.zeros([1]), name="t1")
        b = stf.Variable(stf.zeros([1]), trainable=False, name="t2")
        tv = stf.trainable_variables()
        gv = stf.global_variables()
        assert a in tv and b not in tv
        assert a in gv and b in gv

    def test_scatter_update(self):
        v = stf.Variable(stf.zeros([4]), name="sc")
        up = stf.scatter_update(v, stf.constant([1, 3]),
                                stf.constant([9.0, 8.0]))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(up)
            assert sess.run(v.value()).tolist() == [0.0, 9.0, 0.0, 8.0]

    def test_scatter_add(self):
        v = stf.Variable(stf.ones([3]), name="sa")
        up = stf.scatter_add(v, stf.constant([0, 0, 2]),
                             stf.constant([1.0, 1.0, 5.0]))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(up)
            assert sess.run(v.value()).tolist() == [3.0, 1.0, 6.0]

    def test_report_uninitialized(self):
        v1 = stf.Variable(stf.zeros([1]), name="r1")
        v2 = stf.Variable(stf.zeros([1]), name="r2")
        with stf.Session() as sess:
            sess.run(stf.variables_initializer([v1]))
            names = [str(n) for n in
                     np.ravel(sess.run(stf.report_uninitialized_variables()))]
            assert any("r2" in n for n in names)
            assert not any("r1" in n for n in names)

    def test_is_variable_initialized(self):
        v = stf.Variable(stf.zeros([1]), name="iv")
        with stf.Session() as sess:
            assert not bool(sess.run(stf.is_variable_initialized(v)))
            sess.run(v.initializer)
            assert bool(sess.run(stf.is_variable_initialized(v)))

    def test_assign_in_multiple_steps_is_isolated(self):
        """Two Sessions own independent variable state (ref: per-session
        resource manager)."""
        v = stf.Variable(stf.zeros([]), name="iso")
        s1, s2 = stf.Session(), stf.Session()
        s1.run(stf.global_variables_initializer())
        s2.run(stf.global_variables_initializer())
        s1.run(stf.assign(v, stf.constant(5.0)))
        assert float(s1.run(v.value())) == 5.0
        assert float(s2.run(v.value())) == 0.0
        s1.close(), s2.close()


class TestVariableScope:
    def test_get_variable_creates_and_reuses(self):
        with stf.variable_scope("layer"):
            w1 = stf.get_variable("w", [2, 2],
                                  initializer=stf.ones_initializer())
        with stf.variable_scope("layer", reuse=True):
            w2 = stf.get_variable("w")
        assert w1 is w2
        assert w1.var_name.startswith("layer/w")

    def test_reuse_false_conflict_raises(self):
        with stf.variable_scope("s1"):
            stf.get_variable("x", [1])
        with pytest.raises(ValueError):
            with stf.variable_scope("s1"):
                stf.get_variable("x", [1])

    def test_reuse_missing_raises(self):
        with pytest.raises(ValueError):
            with stf.variable_scope("empty", reuse=True):
                stf.get_variable("nope", [1])

    def test_auto_reuse(self):
        for _ in range(2):
            with stf.variable_scope("ar", reuse=stf.AUTO_REUSE):
                v = stf.get_variable("w", [3])
        assert len([x for x in stf.global_variables()
                    if "ar/w" in x.var_name]) == 1

    def test_nested_scopes_and_initializer_inheritance(self):
        with stf.variable_scope("a", initializer=stf.constant_initializer(
                7.0)):
            with stf.variable_scope("b"):
                v = stf.get_variable("w", [2])
        assert v.var_name.startswith("a/b/w")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(v.value()).tolist() == [7.0, 7.0]

    def test_custom_getter(self):
        calls = []

        def getter(orig, name, *args, **kwargs):
            calls.append(name)
            return orig(name, *args, **kwargs)

        with stf.variable_scope("cg", custom_getter=getter):
            stf.get_variable("w", [1])
        assert calls and "cg/w" in calls[0]

    def test_partitioned_variable(self):
        with stf.variable_scope("pv"):
            v = stf.get_variable(
                "big", [8, 2],
                partitioner=stf.ops.variable_scope.fixed_size_partitioner(2))
        from simple_tensorflow_tpu.ops.variables import PartitionedVariable

        if isinstance(v, PartitionedVariable):
            assert len(list(v)) == 2


class TestInitializers:
    def _init_val(self, init, shape=(64, 64)):
        v = stf.get_variable(f"iv_{init.__class__.__name__}_{np.random.randint(1e9)}",
                             shape, initializer=init)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            return sess.run(v.value())

    def test_constant_zeros_ones(self):
        assert (self._init_val(stf.zeros_initializer()) == 0).all()
        assert (self._init_val(stf.ones_initializer()) == 1).all()
        assert (self._init_val(stf.constant_initializer(3.5)) == 3.5).all()

    def test_random_uniform_range(self):
        vals = self._init_val(stf.random_uniform_initializer(-2.0, 2.0))
        assert vals.min() >= -2.0 and vals.max() <= 2.0
        assert vals.std() > 0.5

    def test_truncated_normal_bounds(self):
        vals = self._init_val(stf.truncated_normal_initializer(stddev=1.0))
        assert np.abs(vals).max() <= 2.0 + 1e-5

    def test_glorot_scale(self):
        vals = self._init_val(stf.glorot_uniform_initializer())
        limit = np.sqrt(6.0 / (64 + 64))
        assert np.abs(vals).max() <= limit + 1e-6

    def test_orthogonal(self):
        vals = self._init_val(stf.orthogonal_initializer(), (32, 32))
        np.testing.assert_allclose(vals @ vals.T, np.eye(32), atol=1e-4)

    def test_variables_reproducible_with_seed(self):
        stf.set_random_seed(42)
        v1 = stf.Variable(stf.random_normal([4]), name="seed_v1")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            a = sess.run(v1.value())
        stf.reset_default_graph()
        stf.set_random_seed(42)
        v2 = stf.Variable(stf.random_normal([4]), name="seed_v1")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            b = sess.run(v2.value())
        np.testing.assert_allclose(a, b)


class TestReadWriteRaceDetector:
    """SURVEY §5 ordering detector: unordered read/write of one variable
    in one step raises at plan time; control_dependencies is the escape."""

    def test_unordered_read_write_raises(self):
        stf.reset_default_graph()
        v = stf.Variable(np.float32(1.0), name="race_v")
        write = v.assign(stf.constant(np.float32(5.0)))
        # read feeds computation, unordered w.r.t. the write
        doubled = v.read_value() * 2.0
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            import pytest as _pytest
            with _pytest.raises(stf.errors.InvalidArgumentError,
                                match="race"):
                sess.run([write, doubled])

    def test_control_dependency_escape_read_after_write(self):
        stf.reset_default_graph()
        v = stf.Variable(np.float32(1.0), name="race_v2")
        write = v.assign(stf.constant(np.float32(5.0)))
        with stf.control_dependencies([write]):
            doubled = v.read_value() * 2.0
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(doubled) == 10.0  # observes the write

    def test_control_dependency_escape_write_after_read(self):
        stf.reset_default_graph()
        v = stf.Variable(np.float32(1.0), name="race_v3")
        read = v.read_value()
        doubled = read * 2.0
        with stf.control_dependencies([doubled.op]):
            write = v.assign(stf.constant(np.float32(5.0)))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            d, _ = sess.run([doubled, write])
            assert d == 2.0  # observes the pre-write value
            assert sess.run(v.read_value()) == 5.0

    def test_bare_fetch_with_write_is_allowed(self):
        # fetching the variable alongside its update is observation, not
        # a compute race (the MonitoredTrainingSession global_step
        # pattern) — allowed
        stf.reset_default_graph()
        v = stf.Variable(np.float32(1.0), name="race_v4")
        write = v.assign_add(stf.constant(np.float32(1.0)))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run([write, v.read_value()])  # must not raise

    def test_data_path_read_into_write_is_allowed(self):
        # the normal training pattern: read -> grad -> assign
        stf.reset_default_graph()
        v = stf.Variable(np.float32(2.0), name="race_v5")
        write = v.assign(v.read_value() * 3.0)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(write)
            assert sess.run(v.read_value()) == 6.0


class TestResourceVariable:
    """ref: python/ops/resource_variable_ops.py:36 — the API class over
    stf's (already resource-semantics) variables."""

    def test_handle_and_sparse_read(self):
        stf.reset_default_graph()
        v = stf.ResourceVariable(
            np.arange(12, dtype=np.float32).reshape(4, 3), name="rv")
        assert v.handle is v._ref
        rows = v.sparse_read(stf.constant(np.array([2, 0], np.int32)))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            rv = sess.run(rows)
        np.testing.assert_allclose(rv, [[6, 7, 8], [0, 1, 2]])
        assert stf.is_resource_variable(v)
        assert not stf.is_resource_variable(
            stf.Variable(np.float32(0.0), name="plain"))

    def test_get_variable_use_resource(self):
        stf.reset_default_graph()
        v = stf.get_variable("res_w", shape=(2,), use_resource=True,
                             initializer=stf.zeros_initializer())
        assert isinstance(v, stf.ResourceVariable)
        # trains like any variable
        loss_v = stf.reduce_sum(stf.square(v - 3.0))
        train = stf.train.GradientDescentOptimizer(0.1).minimize(loss_v)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(50):
                sess.run(train)
            np.testing.assert_allclose(sess.run(v.read_value()),
                                       [3.0, 3.0], atol=1e-3)

    def test_read_after_write_guarantee(self):
        stf.reset_default_graph()
        v = stf.ResourceVariable(np.float32(1.0), name="rv2")
        w = v.assign(stf.constant(np.float32(42.0)))
        with stf.control_dependencies([w]):
            r = v.read_value()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(r) == 42.0

    def test_cse_aliased_path_is_not_a_false_race(self):
        # regression: a fully data-ordered read->write graph whose write
        # input got CSE-deduplicated must NOT raise (detector must follow
        # edges through the alias map)
        stf.reset_default_graph()
        v = stf.Variable(np.float32(3.0), name="cse_v")
        r = v.read_value()
        c = stf.constant(np.float32(2.0))
        a = r * c
        b = r * c          # CSE dup of a
        w = v.assign(b)
        out = a + 1.0
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            ov, _ = sess.run([out, w])  # fetch order that tickled the bug
            assert ov == 7.0
            assert sess.run(v.read_value()) == 6.0
