"""Variable / variable_scope semantics (mirrors ref variables_test.py,
variable_scope_test.py)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


class TestVariable:
    def test_init_read_assign(self):
        v = stf.Variable(stf.constant([1.0, 2.0]), name="v")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(v.value()).tolist() == [1.0, 2.0]
            sess.run(stf.assign(v, stf.constant([5.0, 6.0])))
            assert sess.run(v.value()).tolist() == [5.0, 6.0]
            sess.run(stf.assign_add(v, stf.constant([1.0, 1.0])))
            assert sess.run(v.value()).tolist() == [6.0, 7.0]
            sess.run(stf.assign_sub(v, stf.constant([2.0, 2.0])))
            assert sess.run(v.value()).tolist() == [4.0, 5.0]

    def test_uninitialized_raises(self):
        v = stf.Variable(stf.ones([2]), name="u")
        with stf.Session() as sess:
            with pytest.raises(stf.errors.FailedPreconditionError):
                sess.run(v.value())

    def test_initialized_value_chain(self):
        v = stf.Variable(stf.constant(3.0), name="a")
        w = stf.Variable(v.initialized_value() * 2.0, name="b")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert float(sess.run(w.value())) == 6.0

    def test_trainable_collections(self):
        a = stf.Variable(stf.zeros([1]), name="t1")
        b = stf.Variable(stf.zeros([1]), trainable=False, name="t2")
        tv = stf.trainable_variables()
        gv = stf.global_variables()
        assert a in tv and b not in tv
        assert a in gv and b in gv

    def test_scatter_update(self):
        v = stf.Variable(stf.zeros([4]), name="sc")
        up = stf.scatter_update(v, stf.constant([1, 3]),
                                stf.constant([9.0, 8.0]))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(up)
            assert sess.run(v.value()).tolist() == [0.0, 9.0, 0.0, 8.0]

    def test_scatter_add(self):
        v = stf.Variable(stf.ones([3]), name="sa")
        up = stf.scatter_add(v, stf.constant([0, 0, 2]),
                             stf.constant([1.0, 1.0, 5.0]))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(up)
            assert sess.run(v.value()).tolist() == [3.0, 1.0, 6.0]

    def test_report_uninitialized(self):
        v1 = stf.Variable(stf.zeros([1]), name="r1")
        v2 = stf.Variable(stf.zeros([1]), name="r2")
        with stf.Session() as sess:
            sess.run(stf.variables_initializer([v1]))
            names = [str(n) for n in
                     np.ravel(sess.run(stf.report_uninitialized_variables()))]
            assert any("r2" in n for n in names)
            assert not any("r1" in n for n in names)

    def test_is_variable_initialized(self):
        v = stf.Variable(stf.zeros([1]), name="iv")
        with stf.Session() as sess:
            assert not bool(sess.run(stf.is_variable_initialized(v)))
            sess.run(v.initializer)
            assert bool(sess.run(stf.is_variable_initialized(v)))

    def test_assign_in_multiple_steps_is_isolated(self):
        """Two Sessions own independent variable state (ref: per-session
        resource manager)."""
        v = stf.Variable(stf.zeros([]), name="iso")
        s1, s2 = stf.Session(), stf.Session()
        s1.run(stf.global_variables_initializer())
        s2.run(stf.global_variables_initializer())
        s1.run(stf.assign(v, stf.constant(5.0)))
        assert float(s1.run(v.value())) == 5.0
        assert float(s2.run(v.value())) == 0.0
        s1.close(), s2.close()


class TestVariableScope:
    def test_get_variable_creates_and_reuses(self):
        with stf.variable_scope("layer"):
            w1 = stf.get_variable("w", [2, 2],
                                  initializer=stf.ones_initializer())
        with stf.variable_scope("layer", reuse=True):
            w2 = stf.get_variable("w")
        assert w1 is w2
        assert w1.var_name.startswith("layer/w")

    def test_reuse_false_conflict_raises(self):
        with stf.variable_scope("s1"):
            stf.get_variable("x", [1])
        with pytest.raises(ValueError):
            with stf.variable_scope("s1"):
                stf.get_variable("x", [1])

    def test_reuse_missing_raises(self):
        with pytest.raises(ValueError):
            with stf.variable_scope("empty", reuse=True):
                stf.get_variable("nope", [1])

    def test_auto_reuse(self):
        for _ in range(2):
            with stf.variable_scope("ar", reuse=stf.AUTO_REUSE):
                v = stf.get_variable("w", [3])
        assert len([x for x in stf.global_variables()
                    if "ar/w" in x.var_name]) == 1

    def test_nested_scopes_and_initializer_inheritance(self):
        with stf.variable_scope("a", initializer=stf.constant_initializer(
                7.0)):
            with stf.variable_scope("b"):
                v = stf.get_variable("w", [2])
        assert v.var_name.startswith("a/b/w")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(v.value()).tolist() == [7.0, 7.0]

    def test_custom_getter(self):
        calls = []

        def getter(orig, name, *args, **kwargs):
            calls.append(name)
            return orig(name, *args, **kwargs)

        with stf.variable_scope("cg", custom_getter=getter):
            stf.get_variable("w", [1])
        assert calls and "cg/w" in calls[0]

    def test_partitioned_variable(self):
        with stf.variable_scope("pv"):
            v = stf.get_variable(
                "big", [8, 2],
                partitioner=stf.ops.variable_scope.fixed_size_partitioner(2))
        from simple_tensorflow_tpu.ops.variables import PartitionedVariable

        if isinstance(v, PartitionedVariable):
            assert len(list(v)) == 2


class TestInitializers:
    def _init_val(self, init, shape=(64, 64)):
        v = stf.get_variable(f"iv_{init.__class__.__name__}_{np.random.randint(1e9)}",
                             shape, initializer=init)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            return sess.run(v.value())

    def test_constant_zeros_ones(self):
        assert (self._init_val(stf.zeros_initializer()) == 0).all()
        assert (self._init_val(stf.ones_initializer()) == 1).all()
        assert (self._init_val(stf.constant_initializer(3.5)) == 3.5).all()

    def test_random_uniform_range(self):
        vals = self._init_val(stf.random_uniform_initializer(-2.0, 2.0))
        assert vals.min() >= -2.0 and vals.max() <= 2.0
        assert vals.std() > 0.5

    def test_truncated_normal_bounds(self):
        vals = self._init_val(stf.truncated_normal_initializer(stddev=1.0))
        assert np.abs(vals).max() <= 2.0 + 1e-5

    def test_glorot_scale(self):
        vals = self._init_val(stf.glorot_uniform_initializer())
        limit = np.sqrt(6.0 / (64 + 64))
        assert np.abs(vals).max() <= limit + 1e-6

    def test_orthogonal(self):
        vals = self._init_val(stf.orthogonal_initializer(), (32, 32))
        np.testing.assert_allclose(vals @ vals.T, np.eye(32), atol=1e-4)

    def test_variables_reproducible_with_seed(self):
        stf.set_random_seed(42)
        v1 = stf.Variable(stf.random_normal([4]), name="seed_v1")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            a = sess.run(v1.value())
        stf.reset_default_graph()
        stf.set_random_seed(42)
        v2 = stf.Variable(stf.random_normal([4]), name="seed_v1")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            b = sess.run(v2.value())
        np.testing.assert_allclose(a, b)
