"""Static cost model vs XLA cost analysis (VERDICT r4 item 3; ref:
tensorflow/core/grappler/costs/{cost_estimator.h,op_level_cost_estimator.cc,
graph_memory.cc}).

The contract on the five BASELINE bench configs:

- **FLOPs**: within 2x of XLA's own cost analysis of the lowered step
  (``lowered.cost_analysis()``) — in practice within a few percent.
- **Bytes**: the static model counts per-STF-op operand+result traffic,
  which approximates the *fused* program (one FusedBatchNorm node ≈ one
  fused HLO region), so the honest comparator is the measured on-chip
  bytes-accessed where it exists: ResNet-b256 77.1 GB and BERT-b24-s512
  66 GB (artifacts/bench_measured_r3_onchip.json, TPU v5e, r3) — within
  2x. Where no on-chip number exists, the prediction must sit in the
  bracket [pre-fusion/16, pre-fusion]: XLA's pre-fusion analysis counts
  every decomposed elementwise op's full traffic (ResNet: 874 GB vs
  77 GB fused — 11x), so a sane fused estimate lands well inside it and
  a broken rule (dropped op family, dtype-size bug) falls out of it.
"""

import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.framework import cost_model


def _xla_lowered_cost(train_op, loss, feed_np):
    """Lower (never compile) the session step; return XLA's analysis."""
    sess = stf.Session()
    sess.run(stf.global_variables_initializer())
    feeds = sess._normalize_feeds(feed_np)
    step = sess._plan([train_op, loss], feeds)
    feed_args = {t.name: feeds[t] for t in step.feed_tensors}
    state = dict(sess._variable_store.values)
    lowered = step.jitted.lower(dict(state), feed_args,
                                sess._base_key, np.uint32(0))
    ca = lowered.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return float(ca.get("flops", 0.0)), float(ca.get("bytes accessed", 0.0))


def _assert_within_2x(name, predicted, xla):
    assert xla > 0, f"{name}: XLA reported zero"
    ratio = predicted / xla
    assert 0.5 <= ratio <= 2.0, (
        f"{name}: predicted {predicted:.3e} vs XLA {xla:.3e} "
        f"(ratio {ratio:.2f}) outside [0.5, 2]")


def _check(m, feed, feeds_list, config_name, measured_bytes=None):
    est = cost_model.estimate([m["train_op"], m["loss"]], feeds=feeds_list)
    xla_flops, xla_bytes = _xla_lowered_cost(m["train_op"], m["loss"], feed)
    _assert_within_2x(f"{config_name} flops", est.flops, xla_flops)
    if measured_bytes is not None:
        _assert_within_2x(f"{config_name} bytes(vs on-chip)",
                          est.bytes_accessed, measured_bytes)
    else:
        assert xla_bytes / 16 <= est.bytes_accessed <= xla_bytes, (
            f"{config_name} bytes {est.bytes_accessed:.3e} outside "
            f"[{xla_bytes / 16:.3e}, {xla_bytes:.3e}] (pre-fusion bracket)")
    # peak memory must at least hold the resident params
    assert est.peak_bytes >= est.resident_bytes
    return est


def test_mnist_softmax_config():
    from simple_tensorflow_tpu.models import mnist

    stf.reset_default_graph()
    m = mnist.softmax_model(batch_size=100)
    X = np.random.RandomState(0).rand(100, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[
        np.random.RandomState(1).randint(0, 10, 100)]
    _check(m, {m["x"]: X, m["y_"]: y}, [m["x"], m["y_"]], "mnist")


def test_resnet50_b256_config():
    from simple_tensorflow_tpu.models import resnet

    stf.reset_default_graph()
    m = resnet.resnet50_train_model(batch_size=256, image_size=224,
                                    dtype=stf.bfloat16, learning_rate=0.1)
    images, labels = resnet.synthetic_imagenet(256, 224)
    feed = {m["images"]: images.astype(np.float32), m["labels"]: labels}
    est = _check(m, feed, [m["images"], m["labels"]], "resnet50_b256",
                 measured_bytes=77.1e9)  # TPU v5e, r3 on-chip
    # sanity against the known numbers: ~6.1 TF of model math -> the
    # static model must land in the same decade
    assert 3e12 < est.flops < 2e13, est.flops


def test_bert_b24_s512_config():
    from simple_tensorflow_tpu.models import bert

    cfg = bert.BertConfig.base()
    batch, seq, max_pred = 24, 512, 76
    stf.reset_default_graph()
    m = bert.bert_pretrain_model(
        batch_size=batch, seq_len=seq, max_predictions=max_pred, cfg=cfg,
        compute_dtype=stf.bfloat16, use_input_mask=True)
    b = bert.synthetic_pretrain_batch(batch, seq, max_pred,
                                      vocab_size=cfg.vocab_size)
    b["input_mask"] = np.ones((batch, seq), np.int32)
    feed = {m[k]: v for k, v in b.items()}
    _check(m, feed, list(feed.keys()), "bert_b24_s512",
           measured_bytes=66e9)  # TPU v5e, r3 on-chip


def test_transformer_big_config():
    from simple_tensorflow_tpu.models import transformer

    cfg = transformer.TransformerConfig.big()
    batch, src_len, tgt_len = 16, 64, 64
    stf.reset_default_graph()
    m = transformer.transformer_train_model(
        batch_size=batch, src_len=src_len, tgt_len=tgt_len, cfg=cfg)
    b = transformer.synthetic_wmt_batch(batch, src_len, tgt_len,
                                        vocab_size=cfg.vocab_size)
    feed = {m[k]: v for k, v in b.items()}
    _check(m, feed, list(feed.keys()), "transformer_big")


def test_resnet_dp8_config():
    """dp8 sharding config: the static model is sharding-agnostic (counts
    global work); XLA's pre-partitioning analysis counts the same global
    shapes, so the 2x contract holds on the mesh-lowered step too."""
    import jax

    from simple_tensorflow_tpu import parallel
    from simple_tensorflow_tpu.models import resnet

    stf.reset_default_graph()
    devices = jax.devices("cpu")[:8]
    mesh = parallel.Mesh({"dp": 8}, devices=devices)
    with mesh:
        m = resnet.resnet50_train_model(batch_size=32, image_size=32,
                                        dtype=stf.float32,
                                        learning_rate=0.1)
        parallel.shard_feed(m["images"], "dp")
        parallel.shard_feed(m["labels"], "dp")
        images, labels = resnet.synthetic_imagenet(32, 32,
                                                   dtype=np.float32)
        feed = {m["images"]: images, m["labels"]: labels}
        _check(m, feed, [m["images"], m["labels"]], "resnet_dp8")


# ---------------------------------------------------------------------------
# planning helpers
# ---------------------------------------------------------------------------

def test_suggest_microbatches_fits_budget():
    # 8 GB of activations, 4 stages, 3 GB budget: 1F1B stashes 4 slices,
    # need per-micro <= 0.75 GB -> m >= 8/0.75/... smallest pow2 with
    # (8/m)*4 <= 3 -> m >= 10.7 -> 16
    m = cost_model.suggest_microbatches(8e9, 4, 3e9, schedule="1f1b")
    assert m == 16
    assert (8e9 / m) * 4 <= 3e9
    # gpipe stashes all m microbatches: footprint is m-independent
    # (m * per_micro = total), so it can never fit -> maxes out
    assert cost_model.suggest_microbatches(8e9, 4, 3e9,
                                           schedule="gpipe") == 256
    assert cost_model.suggest_microbatches(1e9, 4, 8e9) == 1


def test_suggest_remat():
    # residuals alone blow the budget -> remat
    assert cost_model.suggest_remat(15e9, 16e9)
    # bandwidth-bound (low intensity vs balance point) -> remat
    assert cost_model.suggest_remat(
        1e9, 16e9, forward_flops=10e9, peak_flops=197e12, peak_bw=819e9)
    # compute-bound and fits -> no remat
    assert not cost_model.suggest_remat(
        1e9, 16e9, forward_flops=1e12, peak_flops=197e12, peak_bw=819e9)


def test_resolve_recompute_auto():
    class _V5e:  # explicit stub: independent of the attached backend
        platform = "tpu"
        device_kind = "TPU v5 lite"

    # pass-through for booleans
    assert cost_model.resolve_recompute(True, 0.0) is True
    assert cost_model.resolve_recompute(False, 1e30) is False
    # v5e: 16 GB HBM, activations may claim half -> 0.7*8 GB trigger
    assert cost_model.resolve_recompute("auto", 7e9, device=_V5e()) \
        is True
    # small + compute-bound: no remat
    assert cost_model.resolve_recompute(
        "auto", 1e6, forward_flops=1e12, device=_V5e()) is False
    # bandwidth-bound (intensity far below the balance point): remat
    assert cost_model.resolve_recompute(
        "auto", 1e9, forward_flops=10e9, device=_V5e()) is True
    # the transformer estimate scales linearly in every factor
    small = cost_model.transformer_activation_bytes(8, 128, 256, 2)
    assert cost_model.transformer_activation_bytes(16, 128, 256, 2) == \
        2 * small
    # no mesh active -> shard factor 1
    assert cost_model.mesh_shard_factor(["dp", "sp"]) == 1


def test_resnet_auto_remat_decision():
    class _V5e:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    # bench config b256/224 bf16: ~14 GB of saved block activations on a
    # 16 GB chip -> remat (consistent with the r3 on-chip diagnosis)
    act = cost_model.resnet_activation_bytes(256, 224, dtype_bytes=2)
    assert act > 10e9
    assert cost_model.resolve_recompute("auto", act, device=_V5e())
    # tiny config fits with headroom and is compute-dense -> no remat
    tiny = cost_model.resnet_activation_bytes(8, 32, dtype_bytes=2)
    assert not cost_model.resolve_recompute(
        "auto", tiny, forward_flops=6.7e8, device=_V5e())


def test_bert_accepts_recompute_auto():
    # "auto" must resolve to a bool BEFORE reaching maybe_recompute (a
    # truthy string would silently force remat on) and the graph builds
    import simple_tensorflow_tpu as stf
    from simple_tensorflow_tpu.models import bert

    stf.reset_default_graph()
    cfg = bert.BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position=16)
    ids = stf.constant(np.zeros((2, 8), np.int32))
    seq, pooled, emb = bert.bert_encoder(
        ids, stf.constant(np.zeros((2, 8), np.int32)),
        stf.constant(np.ones((2, 8), np.int32)), cfg,
        training=False, recompute="auto")
    assert tuple(int(d) for d in seq.shape) == (2, 8, 32)


def test_pipeline_auto_microbatches_runs():
    import jax

    from simple_tensorflow_tpu import parallel

    stf.reset_default_graph()
    devices = jax.devices("cpu")[:4]
    mesh = parallel.Mesh({"pp": 4}, devices=devices)
    with mesh:
        D = 8
        ws = np.random.RandomState(2).randn(4, D, D).astype(np.float32) * .3
        wp = stf.Variable(ws, name="wp_auto")
        parallel.shard_variable(wp, "pp")
        xp = stf.constant(np.random.RandomState(3).randn(8, D)
                          .astype(np.float32))
        tp = stf.constant(np.random.RandomState(4).randn(8, D)
                          .astype(np.float32))

        def stage(w_s, h):
            return stf.tanh(stf.matmul(h, w_s))

        def loss_fn(yy, tt):
            return stf.reduce_sum(stf.square(yy - tt))

        lossp, (gwp,) = parallel.pipeline_train(
            stage, loss_fn, [wp], xp, tp, n_microbatches="auto")
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        p0, g_val = sess.run([lossp, gwp])
        assert np.isfinite(p0) and np.isfinite(g_val).all()


def test_timeline_predicted_vs_measured():
    from simple_tensorflow_tpu.client import timeline

    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [8, 4], name="x")
    W = stf.Variable(np.ones((4, 4), np.float32), name="W")
    loss = stf.reduce_mean(stf.square(stf.matmul(x, W._ref)))
    train = stf.train.GradientDescentOptimizer(0.1).minimize(loss)
    out = timeline.predicted_vs_measured(
        [train, loss], feeds=[x], measured_seconds=0.01)
    assert out["predicted_sec_per_step"] > 0
    assert out["measured_over_predicted"] > 0
    assert "predicted_gbytes" in out
