"""CI gate: the stf.analysis verifier + linter must be clean over every
graph the model zoo (and the example training flows built from it)
produces — zero ERROR diagnostics; warnings are snapshotted per model so
new smells surface as a diff, not silently (ISSUE 3 satellite).

Build-only: graphs are constructed and analyzed, never executed, so the
gate stays fast and hermetic.
"""

import collections

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import analysis


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield
    stf.reset_default_graph()


# warning/note codes each model graph is allowed to produce today. A new
# code appearing is a lint regression (fix the graph or extend the
# snapshot deliberately); ERRORS are never allowed.
ALLOWED_WARNINGS = {
    "mnist_softmax": set(),
    "mnist_convnet": {"lint/unseeded-rng"},          # dropout, seed opt-in
    "resnet_tiny": {"lint/unseeded-rng"},            # kernel initializers
    "bert_tiny": {"lint/unseeded-rng"},              # dropout
    "transformer_tiny": {"lint/unseeded-rng"},       # dropout
    "causal_lm_tiny": {"lint/unseeded-rng"},         # dropout
    "word2vec": {"lint/unseeded-rng"},               # NCE sampler
    "seq2seq_tiny": {"lint/unseeded-rng"},           # dropout
    "ptb_lstm_tiny": {"lint/unseeded-rng"},          # dropout
    "example_mnist_end_to_end": {"lint/unseeded-rng"},
    "dlrm_tiny": set(),                              # seeded initializers
}
# note-severity codes tolerated everywhere (informational)
ALLOWED_NOTES = {"lint/narrow-64bit", "verifier/unreachable-stateful",
                 "lint/const-fetch"}


def _analyze(model_key, fetches):
    # mesh={'dp': 1} also runs the sharding analyzer (ISSUE 6
    # satellite): every op type in the zoo gets its propagation rule
    # executed — a rule that raises surfaces as a sharding/rule-error
    # note, an op consumed conservatively as sharding/no-rule — so rule
    # gaps show up op-by-op in the snapshot diff, while the 1-device
    # mesh keeps the gate hermetic (no collectives, no real sharding).
    diags = analysis.analyze(stf.get_default_graph(), fetches=fetches,
                             level="full", mesh={"dp": 1})
    errs = analysis.errors(diags)
    assert errs == [], (
        f"{model_key}: analysis errors:\n"
        + analysis.format_report(errs))
    warn_codes = {d.code for d in analysis.warnings(diags)}
    extra = warn_codes - ALLOWED_WARNINGS[model_key]
    assert not extra, (
        f"{model_key}: new warning codes {sorted(extra)} — fix the "
        "graph or extend the snapshot deliberately:\n"
        + analysis.format_report(analysis.warnings(diags)))
    note_codes = {d.code for d in diags if d.severity == analysis.NOTE}
    extra_notes = note_codes - ALLOWED_NOTES
    assert not extra_notes, (
        f"{model_key}: new note codes {sorted(extra_notes)}")
    # every diagnostic must carry op + source attribution (acceptance
    # criterion: diagnostics point at user code)
    for d in diags:
        assert d.op_name, f"{model_key}: diagnostic without op: {d}"
        assert d.source, f"{model_key}: diagnostic without source: {d}"
    return collections.Counter(d.code for d in diags)


def test_mnist_softmax_clean():
    from simple_tensorflow_tpu.models import mnist

    m = mnist.softmax_model(learning_rate=0.01)
    _analyze("mnist_softmax", [m["train_op"], m["loss"]])


def test_mnist_convnet_clean():
    from simple_tensorflow_tpu.models import mnist

    m = mnist.convnet_model(batch_size=8)
    _analyze("mnist_convnet", [m["train_op"], m["loss"]])


def test_resnet_tiny_clean():
    from simple_tensorflow_tpu.models import resnet

    m = resnet.resnet50_train_model(batch_size=2, image_size=32,
                                    num_classes=10)
    _analyze("resnet_tiny", [m["train_op"], m["loss"]])


def test_bert_tiny_clean():
    from simple_tensorflow_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    m = bert.bert_pretrain_model(batch_size=2, seq_len=16,
                                 max_predictions=4, cfg=cfg,
                                 compute_dtype=stf.float32)
    _analyze("bert_tiny", [m["train_op"], m["loss"]])


def test_transformer_tiny_clean():
    from simple_tensorflow_tpu.models import transformer as tr

    cfg = tr.TransformerConfig.tiny()
    m = tr.transformer_train_model(batch_size=2, src_len=8, tgt_len=8,
                                   cfg=cfg, compute_dtype=stf.float32)
    _analyze("transformer_tiny", [m["train_op"], m["loss"]])


def test_causal_lm_tiny_clean():
    from simple_tensorflow_tpu.models import causal_lm as clm

    cfg = clm.CausalLMConfig.tiny()
    m = clm.causal_lm_train_model(batch_size=2, seq_len=8, cfg=cfg,
                                  compute_dtype=stf.float32)
    _analyze("causal_lm_tiny", [m["train_op"], m["loss"]])


def test_word2vec_clean():
    from simple_tensorflow_tpu.models import word2vec as w2v

    m = w2v.skipgram_model(vocab_size=50, embedding_size=8, batch_size=8,
                           num_sampled=4, learning_rate=0.5)
    _analyze("word2vec", [m["train_op"], m["loss"]])


def test_seq2seq_tiny_clean():
    from simple_tensorflow_tpu.models import rnn_seq2seq as s2s

    cfg = s2s.Seq2SeqConfig.tiny()
    m = s2s.seq2seq_model(4, cfg)
    _analyze("seq2seq_tiny", [m["train_op"], m["loss"], m["decoded"]])


def test_ptb_lstm_tiny_clean():
    from simple_tensorflow_tpu.models import ptb_lstm

    cfg = ptb_lstm.PTBConfig.tiny()
    m = ptb_lstm.ptb_lm_model(4, cfg, training=True)
    fetches = [v for k, v in m.items()
               if k in ("train_op", "loss", "cost") and v is not None]
    assert fetches
    _analyze("ptb_lstm_tiny", fetches)


def test_example_mnist_end_to_end_graph_clean():
    """The training graph examples/train_mnist_end_to_end.py builds
    (convnet + global step + summaries), analyzed build-only."""
    from simple_tensorflow_tpu.models import mnist

    m = mnist.convnet_model(batch_size=8)
    stf.summary.scalar("loss", m["loss"])
    summaries = stf.summary.merge_all()
    fetches = [m["train_op"], m["loss"]]
    if summaries is not None:
        fetches.append(summaries)
    _analyze("example_mnist_end_to_end", fetches)


def test_graph_lint_cli_clean_on_model_graphdef(tmp_path):
    """The serialized-graph path (tools.graph_lint) agrees with the
    in-process gate on a model graph."""
    import json

    from simple_tensorflow_tpu.framework import graph_io
    from simple_tensorflow_tpu.tools import graph_lint

    from simple_tensorflow_tpu.models import mnist

    m = mnist.softmax_model(learning_rate=0.01)
    gd = graph_io.graph_to_graphdef(stf.get_default_graph())
    p = tmp_path / "mnist_softmax.json"
    p.write_text(json.dumps(gd))
    stf.reset_default_graph()
    diags, graph, report = graph_lint.run_lint(
        json.loads(p.read_text()),
        fetch_names=[m["train_op"].name, m["loss"].name])
    assert graph is not None
    assert report is None  # no --mesh: sharding analysis not requested
    assert analysis.errors(diags) == []


# ---------------------------------------------------------------------------
# serving-export gate (ISSUE 7 satellite): inference graphs the zoo
# exports must pass the serving-compatibility lint; the rule must fire
# on each incompatibility class; training-purpose runs never see it.
# ---------------------------------------------------------------------------

def test_mnist_softmax_inference_serving_clean():
    from simple_tensorflow_tpu.models import mnist

    m = mnist.softmax_model(learning_rate=0.01)
    diags = analysis.lint_graph(fetches=[m["logits"]], purpose="serving",
                                rules=["lint/serving-incompatible"])
    assert diags == [], analysis.format_report(diags)


def test_serving_rule_flags_each_incompatibility_class():
    x = stf.placeholder(stf.float32, [None, 4], name="x")
    w = stf.Variable(stf.constant(np.ones((4, 2), np.float32)), name="w")
    h = stf.matmul(x, w)
    # io effect: Print fires per batch, not per request
    h = stf.Print(h, [h], message="serving me")
    # unseeded RNG: batch-composition-dependent responses
    y = stf.nn.dropout(h, keep_prob=0.9)
    # host sink: summary write forces a post-host stage
    stf.summary.scalar("y0", stf.reduce_sum(y))
    merged = stf.summary.merge_all()
    diags = analysis.lint_graph(fetches=[y, merged], purpose="serving",
                                rules=["lint/serving-incompatible"])
    codes = [d.code for d in diags]
    assert codes and set(codes) == {"lint/serving-incompatible"}
    msgs = " | ".join(d.message for d in diags)
    assert "host-stage op" in msgs
    assert "io effect" in msgs
    assert "unseeded stateful RNG" in msgs
    # every diagnostic carries op + source attribution
    for d in diags:
        assert d.op_name and d.source
    # the SAME graph lints clean without the serving purpose (training
    # graphs legitimately contain all three)
    assert analysis.lint_graph(
        fetches=[y, merged], rules=["lint/serving-incompatible"]) == []


def test_graph_lint_cli_serving_flag(tmp_path):
    import json

    from simple_tensorflow_tpu.framework import graph_io
    from simple_tensorflow_tpu.tools import graph_lint

    x = stf.placeholder(stf.float32, [None, 4], name="x")
    w = stf.Variable(stf.constant(np.ones((4, 2), np.float32)), name="w")
    y = stf.nn.dropout(stf.matmul(x, w), keep_prob=0.5, name="drop")
    gd = graph_io.graph_to_graphdef(stf.get_default_graph())
    p = tmp_path / "inference.json"
    p.write_text(json.dumps(gd))
    y_name = y.name
    stf.reset_default_graph()
    diags, graph, _ = graph_lint.run_lint(
        json.loads(p.read_text()), fetch_names=[y_name],
        purpose="serving")
    assert graph is not None
    assert any(d.code == "lint/serving-incompatible" for d in diags)
    # without --serving the rule stays silent
    stf.reset_default_graph()
    diags2, _, _ = graph_lint.run_lint(
        json.loads(p.read_text()), fetch_names=[y_name])
    assert not any(d.code == "lint/serving-incompatible" for d in diags2)
    # the argparse surface accepts --serving and exits nonzero at
    # warning threshold
    rc = graph_lint.main([str(p), "--fetch", y_name, "--serving",
                          "--max-severity", "warning"])
    assert rc == 1


def test_serving_rule_respects_graph_seed_and_input_boundary():
    # graph-seeded RNG is reproducible (fold_in bakes _graph_seed):
    # the serving rule must not flag it
    stf.set_random_seed(42)
    x = stf.placeholder(stf.float32, [None, 4], name="x")
    w = stf.Variable(stf.constant(np.ones((4, 2), np.float32)), name="w")
    y = stf.nn.dropout(stf.matmul(x, w), keep_prob=0.9)
    diags = analysis.lint_graph(fetches=[y], purpose="serving",
                                rules=["lint/serving-incompatible"])
    assert not any("RNG" in d.message for d in diags), (
        analysis.format_report(diags))
    # input-boundary: ops UPSTREAM of the serving input are not part of
    # the served plan — a pre-pruned op set must never be widened
    stf.reset_default_graph()
    raw = stf.Print(stf.constant(np.ones((2, 4), np.float32)),
                    [stf.constant(1.0)], message="preprocess")
    out = stf.matmul(raw, stf.constant(np.ones((4, 2), np.float32)))
    from simple_tensorflow_tpu.framework import lowering

    pruned = lowering.prune([out.op], {raw})  # raw is the fed input
    diags = analysis.lint_graph(ops=pruned, fetches=[out],
                                purpose="serving",
                                rules=["lint/serving-incompatible"])
    assert diags == [], analysis.format_report(diags)


def test_decode_plan_graph_lint_serving(tmp_path):
    # ISSUE 12 satellite: graph_lint --serving knows the decode plan
    # shape. A well-formed generative decode graph (KV-cache ops with
    # committed shardings, no cache host-sink) round-trips through
    # GraphDef and lints CLEAN; stripping the sharding declaration or
    # sinking a cache tensor to host is an ERROR.
    import json

    from simple_tensorflow_tpu.framework import graph_io
    from simple_tensorflow_tpu.models import transformer as tr
    from simple_tensorflow_tpu.ops import kv_cache_ops as kvc
    from simple_tensorflow_tpu.tools import graph_lint

    cfg = tr.TransformerConfig.tiny()
    prog = tr.build_generative_program(
        cfg, 8, num_slots=2, max_decode_len=4, decode_bucket_sizes=[2],
        compute_dtype=stf.float32)
    dec = prog["decode"][2]
    diags = analysis.lint_graph(
        fetches=[dec["next_tok"], dec["logp"]], purpose="serving",
        rules=["lint/serving-decode-cache"])
    assert diags == [], analysis.format_report(diags)

    # GraphDef round trip through the CLI entry point
    gd = graph_io.graph_to_graphdef(stf.get_default_graph())
    p = tmp_path / "decode.json"
    p.write_text(json.dumps(gd))
    fetches = [dec["next_tok"].name, dec["logp"].name]
    stf.reset_default_graph()
    diags2, graph, _ = graph_lint.run_lint(
        json.loads(p.read_text()), fetch_names=fetches,
        purpose="serving")
    assert graph is not None
    assert not any(d.code == "lint/serving-decode-cache"
                   for d in diags2), analysis.format_report(diags2)

    # negative: a cache gather that escapes to host is an ERROR
    stf.reset_default_graph()
    c = kvc.kv_cache("gate_cache", 2, 4, (2,), stf.float32)
    g = c.gather(stf.placeholder(stf.int32, [1], "s"))
    diags3 = analysis.lint_graph(
        fetches=[g], purpose="serving",
        rules=["lint/serving-decode-cache"])
    assert any(d.severity == "error" for d in diags3)


# ---------------------------------------------------------------------------
# memory-budget gate (ISSUE 13 satellite): graph_lint --memory over the
# model zoo — the per-plan peak table exists for every zoo model, the
# lint/memory-budget rule fires only over budget (and only under the
# "memory" purpose), and the CLI exit code gates CI.
# ---------------------------------------------------------------------------

def _autoshard_snapshot(fetches, mesh, **kw):
    from simple_tensorflow_tpu import analysis

    res = analysis.search_sharding(mesh=mesh, fetches=fetches,
                                   anneal_steps=16, **kw)
    sharded = {}
    replicated = set()
    for g in res.groups:
        if g["kind"] != "var":
            continue
        spec = tuple(g["spec"])
        if any(e is not None for e in spec):
            sharded[g["pattern"]] = spec
        else:
            replicated.add(g["pattern"])
    feeds = {k: tuple(v) for k, v in res.feed_specs.items()}
    return {"sharded": sharded, "replicated": replicated,
            "feeds": feeds}, res


# The chosen rule sets per model/mesh — reviewed like the lint
# snapshots above: a search/cost-model change that moves a spec shows
# up here as a diff to be accepted deliberately, not silently.
AUTOSHARD_SNAPSHOTS = {
    ("resnet_tiny", "dp8"): {
        "sharded": {},
        "feeds": {"images": (None, None, None, None),
                  "labels": ("dp",)},
    },
    ("bert_tiny", "dp8"): {
        "sharded": {},
        "feeds": {"input_ids": ("dp", None),
                  "token_type_ids": ("dp", None),
                  "mlm_positions": ("dp", None),
                  "mlm_ids": (None, None),
                  "mlm_weights": (None, None),
                  "nsp_labels": (None,)},
    },
    ("transformer_tiny", "dp8"): {
        "sharded": {},
        "feeds": {"src_ids": ("dp", None), "tgt_in": ("dp", None),
                  "tgt_out": ("dp", None)},
    },
    ("transformer_tiny", "dp2_tp4"): {
        # Megatron-style: every kernel column-parallel on tp, the
        # shared embedding tp on d_model, feeds dp on batch
        "sharded": {
            "transformer/shared_embedding": (None, "tp"),
            **{f"transformer/{side}/layer_\\d+/{mod}/kernel":
               (None, "tp")
               for side in ("encoder", "decoder")
               for mod in (("self_attn/q", "self_attn/k",
                            "self_attn/v", "self_attn/out",
                            "ffn/in", "ffn/out")
                           + (("cross_attn/q", "cross_attn/k",
                               "cross_attn/v", "cross_attn/out")
                              if side == "decoder" else ()))},
            **{f"transformer/{side}/layer_\\d+/{mod}/bias": ("tp",)
               for side in ("encoder", "decoder")
               for mod in (("self_attn/q", "self_attn/k",
                            "self_attn/v", "self_attn/out",
                            "ffn/in", "ffn/out")
                           + (("cross_attn/q", "cross_attn/k",
                               "cross_attn/v", "cross_attn/out")
                              if side == "decoder" else ()))},
            **{f"transformer/{side}/layer_\\d+/ln\\d+/{p}": ("tp",)
               for side in ("encoder", "decoder")
               for p in ("beta", "gamma")},
        },
        "feeds": {"src_ids": ("dp", None), "tgt_in": ("dp", None),
                  "tgt_out": ("dp", None)},
    },
    ("dlrm_tiny", "ep8"): {
        # ISSUE 19 acceptance: the per-shard HBM budget makes
        # replicated tables infeasible and the fused-lookup rule makes
        # the VOCAB layout the cheap one — the search lands on
        # ('ep', None) with no hand-placed specs. The small MLP params
        # ride the ep axis too (free under the same budget pressure).
        "sharded": {
            "dlrm/bottom/b\\d+": ("ep",),
            "dlrm/bottom/w\\d+": (None, "ep"),
            "dlrm/embedding/table_\\d+": ("ep", None),
            "dlrm/top/b\\d+": ("ep",),
        },
        "feeds": {"dense_features": (None, None),
                  "labels": (None, None),
                  "cat0_ids": (None, None), "cat1_ids": (None, None),
                  "cat0_lengths": (None,), "cat1_lengths": (None,)},
    },
}


def _check_autoshard_snapshot(key, fetches, mesh, **kw):
    got, res = _autoshard_snapshot(fetches, mesh, **kw)
    want = AUTOSHARD_SNAPSHOTS[key]
    assert got["sharded"] == want["sharded"], (
        f"{key}: chosen SHARDED specs moved — review like a lint "
        f"snapshot diff:\n got {got['sharded']}\nwant "
        f"{want['sharded']}")
    assert got["feeds"] == want["feeds"], (
        f"{key}: chosen feed specs moved:\n got {got['feeds']}\n"
        f"want {want['feeds']}")
    # sanity on the result object itself
    assert res.search_seconds > 0
    assert res.rules()[-1] == [".*", []]
    return res


def test_zoo_autoshard_resnet_dp8_snapshot():
    from simple_tensorflow_tpu.models import resnet

    m = resnet.resnet50_train_model(batch_size=8, image_size=32,
                                    num_classes=10)
    _check_autoshard_snapshot(("resnet_tiny", "dp8"),
                              [m["train_op"], m["loss"]], {"dp": 8})


def test_zoo_autoshard_bert_dp8_snapshot():
    from simple_tensorflow_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    m = bert.bert_pretrain_model(batch_size=8, seq_len=16,
                                 max_predictions=4, cfg=cfg,
                                 compute_dtype=stf.float32)
    _check_autoshard_snapshot(("bert_tiny", "dp8"),
                              [m["train_op"], m["loss"]], {"dp": 8})


def test_zoo_autoshard_transformer_snapshots():
    from simple_tensorflow_tpu.models import transformer as tr

    cfg = tr.TransformerConfig.tiny()
    m = tr.transformer_train_model(batch_size=8, src_len=8, tgt_len=8,
                                   cfg=cfg, compute_dtype=stf.float32)
    fetches = [m["train_op"], m["loss"]]
    _check_autoshard_snapshot(("transformer_tiny", "dp8"), fetches,
                              {"dp": 8})
    res = _check_autoshard_snapshot(("transformer_tiny", "dp2_tp4"),
                                    fetches, {"dp": 2, "tp": 4})
    # the searched tp layout must price BELOW the all-replicated
    # baseline's step time (the whole point of choosing it)
    assert res.predicted["step_seconds"] \
        <= res.baseline["step_seconds"] + 1e-12


def test_zoo_memory_budget_gate(tmp_path):
    import json

    from simple_tensorflow_tpu.framework import graph_io
    from simple_tensorflow_tpu.models import mnist
    from simple_tensorflow_tpu.models import transformer as tr
    from simple_tensorflow_tpu.tools import graph_lint

    zoo = {}
    m = mnist.softmax_model(learning_rate=0.01)
    zoo["mnist_softmax"] = (stf.get_default_graph(),
                            [m["train_op"], m["loss"]])
    g2 = stf.Graph()
    with g2.as_default():
        cfg = tr.TransformerConfig.tiny()
        mt = tr.transformer_train_model(batch_size=2, src_len=8,
                                        tgt_len=8, cfg=cfg,
                                        compute_dtype=stf.float32)
    zoo["transformer_tiny"] = (g2, [mt["train_op"], mt["loss"]])

    for key, (graph, fetches) in zoo.items():
        rows = graph_lint.memory_summary(
            graph, fetches=[f for f in fetches], budget=1 << 34)
        assert rows, f"{key}: no memory rows"
        for r in rows:
            assert "error" not in r, f"{key}: uncostable plan: {r}"
            assert r["predicted_peak_bytes"] > 0
            assert r["within_budget"], f"{key}: {r}"

    # CLI round trip on one zoo graph: generous budget exits 0, a
    # 1-byte budget exits 1 via the lint/memory-budget ERROR
    gd = graph_io.graph_to_graphdef(zoo["mnist_softmax"][0])
    p = tmp_path / "mnist_mem.json"
    p.write_text(json.dumps(gd))
    loss_name = m["loss"].name
    stf.reset_default_graph()
    rc = graph_lint.main([str(p), "--fetch", loss_name, "--memory",
                          "--budget", str(1 << 34)])
    assert rc == 0
    rc = graph_lint.main([str(p), "--fetch", loss_name, "--memory",
                          "--budget", "1"])
    assert rc == 1


# ---------------------------------------------------------------------------
# DLRM ranking gates (ISSUE 19): lint/verifier clean, autoshard picks
# the vocab sharding off the memory budget alone, memory rows costable.
# ---------------------------------------------------------------------------

def _dlrm_tiny():
    from simple_tensorflow_tpu.models import dlrm

    return dlrm.dlrm_model(batch_size=8, num_dense=8,
                           table_sizes=(4096, 2048), embedding_dim=64,
                           bottom_mlp=(32, 64), top_mlp=(32, 1),
                           max_ids_per_feature=8)


def test_dlrm_tiny_clean():
    m = _dlrm_tiny()
    _analyze("dlrm_tiny", [m["train_op"], m["loss"]])


def test_zoo_autoshard_dlrm_ep8_snapshot():
    # table_0 is 4096*64*4 B = 1 MiB; the 512 KiB/shard budget means
    # replicating it is over budget on every device, so the search
    # must shard it — and the fused-lookup collective pricing makes
    # ('ep', None) the layout that wins. No rules= seed specs.
    m = _dlrm_tiny()
    res = _check_autoshard_snapshot(
        ("dlrm_tiny", "ep8"), [m["train_op"], m["loss"]], {"ep": 8},
        budget_bytes=1 << 19)
    # the chosen layout must beat the all-replicated baseline
    assert res.predicted["step_seconds"] \
        <= res.baseline["step_seconds"] + 1e-12


def test_dlrm_memory_rows_costable():
    from simple_tensorflow_tpu.tools import graph_lint

    m = _dlrm_tiny()
    rows = graph_lint.memory_summary(
        stf.get_default_graph(), fetches=[m["train_op"], m["loss"]],
        budget=1 << 34)
    assert rows
    for r in rows:
        assert "error" not in r, r
        assert r["predicted_peak_bytes"] > 0
        assert r["within_budget"], r
