"""NN op tests: conv/pool/softmax/xent/norm vs numpy references
(mirrors ref kernel_tests/conv_ops_test.py etc., SURVEY §4)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _run(t, feed=None):
    with stf.Session() as sess:
        return sess.run(t, feed)


RNG = np.random.RandomState(3)


def _np_conv2d_valid(x, w):
    n, h, ww, cin = x.shape
    kh, kw, _, cout = w.shape
    oh, ow = h - kh + 1, ww - kw + 1
    out = np.zeros((n, oh, ow, cout), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, i:i + kh, j:j + kw, :].reshape(n, -1)
            out[:, i, j, :] = patch @ w.reshape(-1, cout)
    return out


class TestConv:
    def test_conv2d_valid_vs_numpy(self):
        x = RNG.rand(2, 5, 5, 3).astype(np.float32)
        w = RNG.rand(3, 3, 3, 4).astype(np.float32)
        y = stf.nn.conv2d(stf.constant(x), stf.constant(w),
                          strides=[1, 1, 1, 1], padding="VALID")
        np.testing.assert_allclose(_run(y), _np_conv2d_valid(x, w),
                                   rtol=1e-4, atol=1e-4)

    def test_conv2d_same_shape(self):
        x = stf.constant(RNG.rand(1, 8, 8, 2).astype(np.float32))
        w = stf.constant(RNG.rand(3, 3, 2, 5).astype(np.float32))
        y = stf.nn.conv2d(x, w, strides=[1, 2, 2, 1], padding="SAME")
        assert _run(y).shape == (1, 4, 4, 5)

    def test_conv2d_gradient(self):
        x = stf.constant(RNG.rand(1, 4, 4, 1).astype(np.float32))
        w = stf.constant(RNG.rand(2, 2, 1, 1).astype(np.float32))
        y = stf.reduce_sum(stf.nn.conv2d(x, w, [1, 1, 1, 1], "VALID"))
        gx, gw = stf.gradients(y, [x, w])
        out = _run({"gx": gx, "gw": gw})
        # d(sum)/dw[i,j] = sum of x patches
        assert np.isfinite(out["gx"]).all()
        np.testing.assert_allclose(out["gw"].ravel()[0],
                                   _run(stf.reduce_sum(x[:, :3, :3, :])),
                                   rtol=1e-4)

    def test_depthwise_conv(self):
        x = stf.constant(RNG.rand(1, 5, 5, 2).astype(np.float32))
        w = stf.constant(RNG.rand(3, 3, 2, 2).astype(np.float32))
        y = stf.nn.depthwise_conv2d(x, w, [1, 1, 1, 1], "VALID")
        assert _run(y).shape == (1, 3, 3, 4)

    def test_conv2d_transpose_shape(self):
        x = stf.constant(RNG.rand(1, 4, 4, 3).astype(np.float32))
        w = stf.constant(RNG.rand(3, 3, 2, 3).astype(np.float32))
        y = stf.nn.conv2d_transpose(x, w, [1, 8, 8, 2], [1, 2, 2, 1],
                                    "SAME")
        assert _run(y).shape == (1, 8, 8, 2)


class TestPooling:
    def test_max_avg_pool(self):
        x = RNG.rand(1, 4, 4, 1).astype(np.float32)
        t = stf.constant(x)
        out = _run({
            "mx": stf.nn.max_pool(t, [1, 2, 2, 1], [1, 2, 2, 1], "VALID"),
            "av": stf.nn.avg_pool(t, [1, 2, 2, 1], [1, 2, 2, 1], "VALID"),
        })
        expect_mx = x.reshape(1, 2, 2, 2, 2, 1).max((2, 4))
        expect_av = x.reshape(1, 2, 2, 2, 2, 1).mean((2, 4))
        np.testing.assert_allclose(out["mx"], expect_mx, rtol=1e-6)
        np.testing.assert_allclose(out["av"], expect_av, rtol=1e-6)

    def test_max_pool_grad_routes_to_max(self):
        x = stf.constant(np.array(
            [[[[1.], [5.]], [[2.], [0.]]]], np.float32))
        y = stf.reduce_sum(stf.nn.max_pool(x, [1, 2, 2, 1], [1, 2, 2, 1],
                                           "VALID"))
        (g,) = stf.gradients(y, [x])
        assert _run(g).ravel().tolist() == [0., 1., 0., 0.]


class TestActivations:
    def test_relu_family(self):
        a = np.array([-2., -0.5, 0., 1.5], np.float32)
        t = stf.constant(a)
        out = _run({
            "relu": stf.nn.relu(t), "relu6": stf.nn.relu6(t * 5.0),
            "elu": stf.nn.elu(t), "softplus": stf.nn.softplus(t),
            "softsign": stf.nn.softsign(t), "crelu": stf.nn.crelu(t),
        })
        assert out["relu"].tolist() == [0., 0., 0., 1.5]
        assert out["relu6"].tolist() == [0., 0., 0., 6.]
        np.testing.assert_allclose(out["elu"][0], np.expm1(-2.0), rtol=1e-5)
        np.testing.assert_allclose(out["softplus"], np.log1p(np.exp(a)),
                                   rtol=1e-5)
        assert out["crelu"].shape == (8,)

    def test_softmax_logsoftmax(self):
        a = RNG.rand(3, 5).astype(np.float32) * 4
        t = stf.constant(a)
        out = _run({"sm": stf.nn.softmax(t), "lsm": stf.nn.log_softmax(t)})
        e = np.exp(a - a.max(1, keepdims=True))
        np.testing.assert_allclose(out["sm"], e / e.sum(1, keepdims=True),
                                   rtol=1e-5)
        np.testing.assert_allclose(out["lsm"], np.log(out["sm"]), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(out["sm"].sum(1), np.ones(3), rtol=1e-5)


class TestXent:
    def test_sparse_softmax_xent_vs_manual(self):
        logits = RNG.rand(4, 7).astype(np.float32) * 3
        labels = np.array([0, 3, 6, 2], np.int32)
        t = stf.nn.sparse_softmax_cross_entropy_with_logits(
            labels=stf.constant(labels), logits=stf.constant(logits))
        lsm = logits - logits.max(1, keepdims=True)
        lsm = lsm - np.log(np.exp(lsm).sum(1, keepdims=True))
        np.testing.assert_allclose(_run(t), -lsm[np.arange(4), labels],
                                   rtol=1e-5)

    def test_softmax_xent_gradient_is_p_minus_y(self):
        logits = stf.constant(RNG.rand(2, 3).astype(np.float32))
        labels_np = np.array([[1., 0., 0.], [0., 1., 0.]], np.float32)
        loss = stf.reduce_sum(stf.nn.softmax_cross_entropy_with_logits(
            labels=stf.constant(labels_np), logits=logits))
        (g,) = stf.gradients(loss, [logits])
        out = _run({"g": g, "p": stf.nn.softmax(logits)})
        np.testing.assert_allclose(out["g"], out["p"] - labels_np,
                                   rtol=1e-4, atol=1e-5)

    def test_sigmoid_xent(self):
        logits = RNG.randn(6).astype(np.float32)
        labels = (RNG.rand(6) > 0.5).astype(np.float32)
        t = stf.nn.sigmoid_cross_entropy_with_logits(
            labels=stf.constant(labels), logits=stf.constant(logits))
        expect = np.maximum(logits, 0) - logits * labels + np.log1p(
            np.exp(-np.abs(logits)))
        np.testing.assert_allclose(_run(t), expect, rtol=1e-5, atol=1e-6)


class TestNorm:
    def test_moments(self):
        x = RNG.rand(4, 6).astype(np.float32)
        m, v = stf.nn.moments(stf.constant(x), axes=[0])
        out = _run({"m": m, "v": v})
        np.testing.assert_allclose(out["m"], x.mean(0), rtol=1e-5)
        np.testing.assert_allclose(out["v"], x.var(0), rtol=1e-4)

    def test_batch_normalization(self):
        x = RNG.rand(8, 3).astype(np.float32)
        mean, var = x.mean(0), x.var(0)
        y = stf.nn.batch_normalization(
            stf.constant(x), stf.constant(mean), stf.constant(var),
            offset=stf.constant(np.ones(3, np.float32)),
            scale=stf.constant(np.full(3, 2.0, np.float32)),
            variance_epsilon=1e-5)
        expect = (x - mean) / np.sqrt(var + 1e-5) * 2.0 + 1.0
        np.testing.assert_allclose(_run(y), expect, rtol=1e-4, atol=1e-5)

    def test_fused_batch_norm_training_stats(self):
        x = RNG.rand(16, 4, 4, 3).astype(np.float32)
        y, m, v = stf.nn.fused_batch_norm(
            stf.constant(x), scale=stf.constant(np.ones(3, np.float32)),
            offset=stf.constant(np.zeros(3, np.float32)), is_training=True)
        out = _run({"y": y, "m": m, "v": v})
        np.testing.assert_allclose(out["m"], x.mean((0, 1, 2)), rtol=1e-4)
        np.testing.assert_allclose(out["y"].mean((0, 1, 2)), np.zeros(3),
                                   atol=1e-4)

    def test_fused_batch_norm_large_mean_f32_stable(self):
        # f32 inputs take the centered two-pass variance: with mean >> std,
        # the one-pass E[x^2]-E[x]^2 form cancels catastrophically in f32
        # and would report var ~ 0 here.
        x = (RNG.randn(64, 2, 2, 3) + 1e4).astype(np.float32)
        y, m, v = stf.nn.fused_batch_norm(
            stf.constant(x), scale=stf.constant(np.ones(3, np.float32)),
            offset=stf.constant(np.zeros(3, np.float32)), is_training=True)
        out = _run({"y": y, "v": v})
        np.testing.assert_allclose(out["v"], x.var((0, 1, 2)), rtol=1e-2)
        assert np.abs(out["y"]).max() < 10.0

    def test_fused_batch_norm_gradient_matches_reference(self):
        # the custom VJP (ops/nn_impl.py _bn_train_bwd) against plain
        # autodiff of an equivalent composed expression
        import jax
        import jax.numpy as jnp

        from simple_tensorflow_tpu.ops.nn_impl import _bn_train

        x = jnp.asarray(RNG.randn(8, 3, 3, 4).astype(np.float32)) * 2 + 1
        s = jnp.asarray(RNG.randn(4).astype(np.float32))
        o = jnp.asarray(RNG.randn(4).astype(np.float32))

        def ref(x, s, o):
            m = jnp.mean(x, axis=(0, 1, 2))
            v = jnp.mean((x - m) ** 2, axis=(0, 1, 2))
            y = (x - m) * jax.lax.rsqrt(v + 1e-3) * s + o
            return y, m, v

        cot = (jnp.asarray(RNG.randn(8, 3, 3, 4).astype(np.float32)),
               jnp.asarray(RNG.randn(4).astype(np.float32)),
               jnp.asarray(RNG.randn(4).astype(np.float32)))
        _, vjp1 = jax.vjp(lambda *a: _bn_train(*a, 1e-3, (0, 1, 2)), x, s, o)
        _, vjp2 = jax.vjp(ref, x, s, o)
        for g1, g2 in zip(vjp1(cot), vjp2(cot)):
            np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-4)

    def test_fused_batch_norm_nchw_training(self):
        x = RNG.rand(8, 3, 4, 4).astype(np.float32)
        y, m, v = stf.nn.fused_batch_norm(
            stf.constant(x), scale=stf.constant(np.ones(3, np.float32)),
            offset=stf.constant(np.zeros(3, np.float32)),
            is_training=True, data_format="NCHW")
        out = _run({"y": y, "m": m, "v": v})
        np.testing.assert_allclose(out["m"], x.mean((0, 2, 3)), rtol=1e-4)
        np.testing.assert_allclose(out["y"].mean((0, 2, 3)), np.zeros(3),
                                   atol=1e-4)

    def test_l2_normalize_l2_loss(self):
        x = np.array([3., 4.], np.float32)
        out = _run({"n": stf.nn.l2_normalize(stf.constant(x), 0),
                    "l": stf.nn.l2_loss(stf.constant(x))})
        np.testing.assert_allclose(out["n"], [0.6, 0.8], rtol=1e-5)
        assert float(out["l"]) == 12.5

    def test_lrn_finite(self):
        x = stf.constant(RNG.rand(1, 3, 3, 8).astype(np.float32))
        assert np.isfinite(_run(stf.nn.lrn(x))).all()


class TestEmbeddingDropout:
    def test_embedding_lookup(self):
        table = RNG.rand(10, 4).astype(np.float32)
        e = stf.nn.embedding_lookup(stf.constant(table),
                                    stf.constant([[1, 3], [5, 1]]))
        np.testing.assert_allclose(_run(e), table[[[1, 3], [5, 1]]])

    def test_dropout_scaling_and_determinism_within_step(self):
        x = stf.constant(np.ones((1000,), np.float32))
        y = stf.nn.dropout(x, keep_prob=0.5)
        out = _run(y)
        kept = out[out > 0]
        assert np.allclose(kept, 2.0)  # inverted dropout scales by 1/p
        assert 350 < len(kept) < 650

    def test_in_top_k(self):
        pred = stf.constant(np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]],
                                     np.float32))
        t = stf.nn.in_top_k(pred, stf.constant([1, 2]), 1)
        assert _run(t).tolist() == [True, False]

    def test_top_k_sorted(self):
        v, i = stf.nn.top_k(stf.constant([3., 1., 4., 1., 5.]), k=3)
        out = _run({"v": v, "i": i})
        assert out["v"].tolist() == [5., 4., 3.]
        assert out["i"].tolist() == [4, 2, 0]

    def test_bias_add(self):
        x = RNG.rand(2, 3).astype(np.float32)
        y = stf.nn.bias_add(stf.constant(x), stf.constant([1., 2., 3.]))
        np.testing.assert_allclose(_run(y), x + [1., 2., 3.], rtol=1e-6)


class TestMorphologyAndConv3DTranspose:
    """dilation2d/erosion2d (ref core/kernels/dilation_ops.cc) and
    conv3d_transpose."""

    @staticmethod
    def _ref_dilation(x, f, sh, sw, rh, rw, padding):
        n, h, w, c = x.shape
        kh, kw, _ = f.shape
        eh, ew = (kh - 1) * rh + 1, (kw - 1) * rw + 1
        if padding == "SAME":
            out_h = -(-h // sh)
            out_w = -(-w // sw)
            ph = max((out_h - 1) * sh + eh - h, 0)
            pw = max((out_w - 1) * sw + ew - w, 0)
            pt, pl = ph // 2, pw // 2
        else:
            out_h = (h - eh) // sh + 1
            out_w = (w - ew) // sw + 1
            pt = pl = 0
        out = np.full((n, out_h, out_w, c), -np.inf, np.float32)
        for b in range(n):
            for y in range(out_h):
                for xx in range(out_w):
                    for ch in range(c):
                        for i in range(kh):
                            for j in range(kw):
                                yy = y * sh + i * rh - pt
                                xj = xx * sw + j * rw - pl
                                if 0 <= yy < h and 0 <= xj < w:
                                    v = x[b, yy, xj, ch] + f[i, j, ch]
                                    out[b, y, xx, ch] = max(
                                        out[b, y, xx, ch], v)
        return out

    @pytest.mark.parametrize("padding,stride,rate", [
        ("SAME", 1, 1), ("VALID", 1, 1), ("SAME", 2, 1), ("VALID", 1, 2)])
    def test_dilation2d_matches_reference(self, padding, stride, rate):
        rng = np.random.RandomState(0)
        x = rng.rand(2, 9, 9, 3).astype(np.float32)
        f = rng.rand(3, 3, 3).astype(np.float32) * 0.1
        out_t = stf.nn.dilation2d(
            stf.constant(x), stf.constant(f),
            strides=[1, stride, stride, 1], rates=[1, rate, rate, 1],
            padding=padding)
        with stf.Session() as sess:
            out = sess.run(out_t)
        ref = self._ref_dilation(x, f, stride, stride, rate, rate, padding)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_erosion2d_duality(self):
        rng = np.random.RandomState(1)
        x = rng.rand(1, 8, 8, 2).astype(np.float32)
        f = rng.rand(3, 3, 2).astype(np.float32) * 0.1
        ero_t = stf.nn.erosion2d(stf.constant(x), stf.constant(f),
                                 strides=[1, 1, 1, 1], rates=[1, 1, 1, 1],
                                 padding="SAME")
        dil_t = stf.nn.dilation2d(stf.constant(-x),
                                  stf.constant(f[::-1, ::-1].copy()),
                                  strides=[1, 1, 1, 1],
                                  rates=[1, 1, 1, 1], padding="SAME")
        with stf.Session() as sess:
            ero, dil = sess.run([ero_t, dil_t])
        np.testing.assert_allclose(ero, -dil, rtol=1e-5)

    def test_dilation_zero_filter_is_maxpool(self):
        rng = np.random.RandomState(2)
        x = rng.rand(1, 8, 8, 2).astype(np.float32)
        out_t = stf.nn.dilation2d(
            stf.constant(x), stf.constant(np.zeros((2, 2, 2), np.float32)),
            strides=[1, 2, 2, 1], rates=[1, 1, 1, 1], padding="VALID")
        mp_t = stf.nn.max_pool(stf.constant(x), [1, 2, 2, 1], [1, 2, 2, 1],
                               "VALID")
        with stf.Session() as sess:
            out, mp = sess.run([out_t, mp_t])
        np.testing.assert_allclose(out, mp, rtol=1e-6)

    def test_conv3d_transpose_matches_jax_reference(self):
        import jax

        rng = np.random.RandomState(3)
        # TF transpose-conv filter layout: (d,h,w,OUT,IN) — read as DHWIO
        # with transpose_kernel=True, like the conv2d_transpose lowering
        x = rng.rand(1, 4, 4, 4, 3).astype(np.float32)
        w = rng.rand(3, 3, 3, 5, 3).astype(np.float32) * 0.1
        out_t = stf.nn.conv3d_transpose(
            stf.constant(x), stf.constant(w),
            strides=[1, 2, 2, 2, 1], padding="SAME")
        with stf.Session() as sess:
            out = sess.run(out_t)
        assert out.shape == (1, 8, 8, 8, 5)
        ref = jax.lax.conv_transpose(
            x, w, strides=(2, 2, 2), padding="SAME",
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            transpose_kernel=True)
        np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5,
                                   atol=1e-6)

    def test_dilation2d_integer_dtypes_border_correct(self):
        # int32 + SAME: padded taps must be EXCLUDED, not wrap around
        x = np.arange(16, dtype=np.int32).reshape(1, 4, 4, 1)
        f = np.ones((3, 3, 1), np.int32)
        out_t = stf.nn.dilation2d(stf.constant(x), stf.constant(f),
                                  strides=[1, 1, 1, 1], rates=[1, 1, 1, 1],
                                  padding="SAME")
        with stf.Session() as sess:
            out = sess.run(out_t)
        ref = self._ref_dilation(x.astype(np.float32),
                                 f.astype(np.float32), 1, 1, 1, 1, "SAME")
        np.testing.assert_array_equal(out, ref.astype(np.int32))
        # uint8: sentinel 0 must not leak filter values at borders
        xu = np.zeros((1, 4, 4, 1), np.uint8)
        fu = np.full((3, 3, 1), 7, np.uint8)
        out_u = stf.nn.dilation2d(stf.constant(xu), stf.constant(fu),
                                  strides=[1, 1, 1, 1], rates=[1, 1, 1, 1],
                                  padding="SAME")
        ero_u = stf.nn.erosion2d(stf.constant(xu), stf.constant(fu),
                                 strides=[1, 1, 1, 1], rates=[1, 1, 1, 1],
                                 padding="SAME")
        with stf.Session() as sess:
            ou, eu = sess.run([out_u, ero_u])
        np.testing.assert_array_equal(ou, np.full_like(xu, 7))
        assert eu.dtype == np.uint8 and np.isfinite(
            eu.astype(np.float32)).all()

    def test_conv2d_transpose_explicit_output_shape(self):
        import jax

        rng = np.random.RandomState(5)
        # stride-2 SAME: input 4 could come from forward size 7 OR 8 —
        # output_shape disambiguates (the vjp-of-forward definition)
        x = rng.rand(1, 4, 4, 2).astype(np.float32)
        w = rng.rand(3, 3, 5, 2).astype(np.float32) * 0.1  # (h,w,OUT,IN)
        out_t = stf.nn.conv2d_transpose(
            stf.constant(x), stf.constant(w),
            output_shape=[1, 7, 7, 5], strides=[1, 2, 2, 1],
            padding="SAME")
        with stf.Session() as sess:
            out = sess.run(out_t)
        assert out.shape == (1, 7, 7, 5)

        def fwd(y):
            return jax.lax.conv_general_dilated(
                y, w, window_strides=(2, 2), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        _, vjp = jax.vjp(fwd, np.zeros((1, 7, 7, 5), np.float32))
        (ref,) = vjp(x)
        np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5,
                                   atol=1e-6)

    def test_conv_transpose_inconsistent_output_shape_raises(self):
        rng = np.random.RandomState(6)
        x = rng.rand(1, 4, 4, 2).astype(np.float32)
        w = rng.rand(3, 3, 5, 2).astype(np.float32)
        out_t = stf.nn.conv2d_transpose(
            stf.constant(x), stf.constant(w),
            output_shape=[1, 20, 20, 5], strides=[1, 2, 2, 1],
            padding="SAME")
        with stf.Session() as sess:
            with pytest.raises(Exception, match="inconsistent"):
                sess.run(out_t)
