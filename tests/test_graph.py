"""Graph construction semantics (mirrors ref framework/ops_test.py)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


class TestGraphConstruction:
    def test_default_graph_and_reset(self):
        g = stf.get_default_graph()
        c = stf.constant(1.0)
        assert c.graph is g
        stf.reset_default_graph()
        assert stf.get_default_graph() is not g

    def test_as_default_nesting(self):
        g1, g2 = stf.Graph(), stf.Graph()
        with g1.as_default():
            a = stf.constant(1.0, name="a")
            with g2.as_default():
                b = stf.constant(2.0, name="b")
            c = stf.constant(3.0, name="c")
        assert a.graph is g1 and c.graph is g1 and b.graph is g2

    def test_unique_names(self):
        a = stf.constant(1.0, name="x")
        b = stf.constant(2.0, name="x")
        assert a.op.name == "x" and b.op.name == "x_1"

    def test_name_scope(self):
        with stf.name_scope("outer"):
            a = stf.constant(1.0, name="a")
            with stf.name_scope("inner"):
                b = stf.constant(2.0, name="b")
        assert a.op.name == "outer/a"
        assert b.op.name == "outer/inner/b"

    def test_get_operation_and_tensor_by_name(self):
        c = stf.constant(5.0, name="five")
        g = stf.get_default_graph()
        assert g.get_operation_by_name("five") is c.op
        assert g.get_tensor_by_name("five:0") is c
        with pytest.raises(KeyError):
            g.get_operation_by_name("nonexistent")

    def test_graph_finalize(self):
        g = stf.get_default_graph()
        stf.constant(1.0)
        g.finalize()
        with pytest.raises(RuntimeError):
            stf.constant(2.0)

    def test_collections(self):
        c = stf.constant(1.0)
        stf.add_to_collection("my_coll", c)
        stf.add_to_collections(["a", "b"], c)
        assert stf.get_collection("my_coll") == [c]
        assert stf.get_collection("a") == [c]
        assert stf.get_collection("nope") == []
        ref = stf.get_collection_ref("my_coll")
        ref.append("extra")
        assert len(stf.get_collection("my_coll")) == 2

    def test_operations_listing(self):
        stf.constant(1.0, name="c1")
        stf.constant(2.0, name="c2")
        names = [op.name for op in stf.get_default_graph().get_operations()]
        assert names == ["c1", "c2"]


class TestControlDependencies:
    def test_assign_ordering(self):
        v = stf.Variable(stf.zeros([]), name="cd_v")
        a1 = stf.assign(v, stf.constant(1.0))
        with stf.control_dependencies([a1]):
            a2 = stf.assign_add(v, stf.constant(10.0))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(a2)
            assert float(sess.run(v.value())) == 11.0

    def test_with_dependencies(self):
        v = stf.Variable(stf.zeros([]), name="wd_v")
        a = stf.assign(v, stf.constant(3.0))
        out = stf.control_flow_ops.with_dependencies([a], stf.constant(7.0))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert float(sess.run(out)) == 7.0
            assert float(sess.run(v.value())) == 3.0

    def test_group_runs_all(self):
        v1 = stf.Variable(stf.zeros([]), name="g_v1")
        v2 = stf.Variable(stf.zeros([]), name="g_v2")
        g = stf.group(stf.assign(v1, stf.constant(1.0)),
                      stf.assign(v2, stf.constant(2.0)))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(g)
            assert float(sess.run(v1.value())) == 1.0
            assert float(sess.run(v2.value())) == 2.0


class TestDeviceScopes:
    def test_device_recorded(self):
        with stf.device("/job:worker/task:0"):
            c = stf.constant(1.0)
        assert "worker" in c.op.device

    def test_colocate_with(self):
        a = stf.constant(1.0)
        with stf.colocate_with(a.op):
            b = stf.constant(2.0)
        assert b.op.device == a.op.device


class TestGraphIO:
    def test_graphdef_roundtrip_executes(self):
        x = stf.placeholder(stf.float32, [2], name="x")
        y = stf.add(stf.multiply(x, stf.constant(2.0)), stf.constant(1.0),
                    name="y")
        from simple_tensorflow_tpu.framework import graph_io

        gd = graph_io.graph_to_graphdef(stf.get_default_graph())
        g2 = stf.Graph()
        with g2.as_default():
            graph_io.import_graph_def(gd, name="imp")
            with stf.Session() as sess:
                out = sess.run("imp/y:0",
                               {"imp/x:0": np.float32([1.0, 2.0])})
        assert out.tolist() == [3.0, 5.0]

    def test_write_graph(self, tmp_path):
        stf.constant(1.0, name="c")
        from simple_tensorflow_tpu.framework import graph_io

        path = graph_io.write_graph(stf.get_default_graph(), str(tmp_path),
                                    "g.pbtxt")
        import json

        gd = json.load(open(path))
        assert gd["node"][0]["name"] == "c"

    def test_control_flow_survives_roundtrip(self):
        x = stf.placeholder(stf.float32, [], name="x")
        y = stf.cond(stf.less(x, stf.constant(0.0)),
                     lambda: stf.negative(x), lambda: x, name="absy")
        with stf.Session() as sess:
            assert float(sess.run(y, {x: np.float32(-4.0)})) == 4.0


class TestTensorProperties:
    def test_shape_dtype_name(self):
        t = stf.placeholder(stf.float32, [None, 3], name="p")
        assert t.dtype == stf.float32
        assert t.shape.as_list() == [None, 3]
        assert t.name == "p:0"
        assert t.op.type == "Placeholder"

    def test_operator_overloads(self):
        a = stf.constant([2.0])
        with stf.Session() as sess:
            assert sess.run(a + 1.0).tolist() == [3.0]
            assert sess.run(1.0 + a).tolist() == [3.0]
            assert sess.run(a * 3.0).tolist() == [6.0]
            assert sess.run(-a).tolist() == [-2.0]
            assert sess.run(a / 2.0).tolist() == [1.0]
            assert sess.run(a ** 2.0).tolist() == [4.0]
            assert sess.run(a > 1.0).tolist() == [True]

    def test_convert_to_tensor(self):
        t = stf.convert_to_tensor(np.float32([1, 2]))
        assert isinstance(t, stf.Tensor)
        t2 = stf.convert_to_tensor(t)
        assert t2 is t
