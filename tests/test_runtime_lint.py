"""stf.analysis.concurrency static prong (ISSUE 18): the runtime
thread-safety lint — per-rule fixtures against synthetic files, the
CLI contract, and the CI gate: the WHOLE package lints clean with the
allowlist EMPTY (like the metrics-catalog drift gate, the ratchet only
tightens).
"""

import json
import os
import subprocess
import sys

from simple_tensorflow_tpu.tools import runtime_lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_src(tmp_path, src, name="mod.py"):
    p = tmp_path / name
    p.write_text(src)
    return runtime_lint.lint_file(str(p), package_root=str(tmp_path))


class TestRules:
    def test_raw_lock_flagged(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "import threading\n"
            "l = threading.Lock()\n"
            "r = threading.RLock()\n"
            "c = threading.Condition()\n"))
        assert [v["rule"] for v in vios] == ["raw-lock"] * 3
        assert vios[0]["line"] == 2

    def test_sync_layer_lock_passes(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "from simple_tensorflow_tpu.platform import sync as _sync\n"
            "l = _sync.Lock('x/y', rank=_sync.RANK_STATE)\n"))
        assert vios == []

    def test_unnamed_thread_flagged(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "import threading\n"
            "t = threading.Thread(target=print)\n"
            "u = threading.Thread(target=print, name='worker-1')\n"))
        assert [v["rule"] for v in vios] == ["unnamed-thread"] * 2

    def test_stf_named_thread_passes(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "import threading\n"
            "_NAME = 'stf_via_constant'\n"
            "a = threading.Thread(target=print, name='stf_ok')\n"
            "b = threading.Thread(target=print,\n"
            "                     name=f'stf_worker_{3}')\n"
            "c = threading.Thread(target=print, name=_NAME)\n"))
        assert vios == []

    def test_executor_needs_prefix(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "from concurrent.futures import ThreadPoolExecutor\n"
            "bad = ThreadPoolExecutor(4)\n"
            "ok = ThreadPoolExecutor(\n"
            "    4, thread_name_prefix='stf_pool')\n"))
        assert len(vios) == 1
        assert vios[0]["rule"] == "unnamed-thread"
        assert vios[0]["line"] == 2

    def test_blocking_under_lock_flagged(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "from simple_tensorflow_tpu.platform import sync as _sync\n"
            "import time\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = _sync.Lock('t/l',\n"
            "                                rank=_sync.RANK_STATE)\n"
            "    def bad(self, t, q):\n"
            "        with self._lock:\n"
            "            t.join()\n"
            "            q.get()\n"
            "            time.sleep(0.5)\n"
            "    def fine(self, t, q):\n"
            "        with self._lock:\n"
            "            q.get(timeout=0.1)\n"
            "            time.sleep(0.01)\n"
            "        t.join()\n"))
        assert [v["rule"] for v in vios] == ["blocking-under-lock"] * 3
        assert [v["line"] for v in vios] == [9, 10, 11]
        assert "'t/l'" in vios[0]["detail"]
        assert "held since line 8" in vios[0]["detail"]

    def test_blocking_ok_exempts(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "from simple_tensorflow_tpu.platform import sync as _sync\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = _sync.RLock('t/l',\n"
            "                                 rank=_sync.RANK_SESSION,\n"
            "                                 blocking_ok=True)\n"
            "    def by_design(self, fut):\n"
            "        with self._lock:\n"
            "            fut.join()\n"))
        assert vios == []

    def test_rank_order_inversion_flagged(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "from simple_tensorflow_tpu.platform import sync as _sync\n"
            "hi = _sync.Lock('t/hi', rank=_sync.RANK_METRICS)\n"
            "lo = _sync.Lock('t/lo', rank=_sync.RANK_SESSION)\n"
            "def inverted():\n"
            "    with hi:\n"
            "        with lo:\n"
            "            pass\n"
            "def ordered():\n"
            "    with lo:\n"
            "        with hi:\n"
            "            pass\n"))
        assert len(vios) == 1
        assert vios[0]["rule"] == "rank-order"
        assert "'t/lo'" in vios[0]["detail"]
        assert "'t/hi'" in vios[0]["detail"]

    def test_nested_under_leaf_flagged(self, tmp_path):
        vios = _lint_src(tmp_path, (
            "from simple_tensorflow_tpu.platform import sync as _sync\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = _sync.leaf_lock('t/cell')\n"
            "        self._other = _sync.Lock('t/state',\n"
            "                                 rank=_sync.RANK_STATE)\n"
            "    def bad(self, raw):\n"
            "        with self._lock:\n"
            "            with self._other:\n"
            "                pass\n"
            "            raw.acquire()\n"
            "    def fine(self):\n"
            "        with self._other:\n"
            "            with self._lock:\n"
            "                pass\n"))
        assert [v["rule"] for v in vios] == ["nested-under-leaf"] * 2
        assert [v["line"] for v in vios] == [9, 11]
        assert "'t/cell'" in vios[0]["detail"]
        # ordered the right way round (leaf innermost) does NOT fire
        # rank-order either: leaf rank is the maximum

    def test_allowlist_key_is_line_number_free(self, tmp_path):
        (vio,) = _lint_src(tmp_path, (
            "import threading\nx = threading.Lock()\n"))
        assert str(vio["line"]) not in vio.key().split(":", 2)[2]
        assert vio.key().startswith("raw-lock:")


class TestGate:
    def test_package_lints_clean(self):
        """THE gate: zero violations across the whole package."""
        vios = runtime_lint.lint_package()
        assert vios == [], "\n".join(str(v) for v in vios)

    def test_allowlist_is_empty(self):
        """The ratchet: exemptions live in reviewed source
        (blocking_ok=True), never in the allowlist."""
        assert runtime_lint.load_allowlist() == [], (
            "docs/runtime_lint_allowlist.txt must stay empty — declare "
            "blocking_ok=True on the lock (reviewed code) instead")

    def test_cli_subprocess_green(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep \
            + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-m",
             "simple_tensorflow_tpu.tools.runtime_lint", "--json"],
            capture_output=True, text=True, env=env, timeout=120,
            cwd=REPO_ROOT)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        payload = json.loads(proc.stdout)
        assert payload["count"] == 0
        assert payload["violations"] == []
        assert payload["stale_allowlist"] == []

    def test_cli_exit_1_on_violation(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import threading\nx = threading.Lock()\n")
        rc = runtime_lint.main([str(bad)])
        assert rc == 1
        out = capsys.readouterr().out
        assert "raw-lock" in out

    def test_stale_allowlist_entry_fails(self, tmp_path, capsys):
        ok = tmp_path / "ok.py"
        ok.write_text("x = 1\n")
        allow = tmp_path / "allow.txt"
        allow.write_text("raw-lock:gone.py:threading.Lock() removed\n")
        rc = runtime_lint.main([str(ok), "--allowlist", str(allow)])
        assert rc == 1
        assert "stale allowlist entry" in capsys.readouterr().out
