"""Reference public-API parity sweep: every `@@`-exported name in the
reference's python/{ops,framework,client,training,summary} modules must
resolve somewhere in the stf namespace tree (top level or its TF-1
namespace: nn/image/metrics/sets/summary/train/errors/lookup).

The name list is extracted from the reference tree at test time, so this
stays in sync if the reference changes.
"""

import glob
import os
import re

import pytest

import simple_tensorflow_tpu as stf

_REF = "/root/reference/tensorflow/python"


def _collect():
    by_mod = {}
    pats = ["ops/*.py", "framework/*.py", "client/*.py", "training/*.py",
            "summary/*.py"]
    for pat in pats:
        for f in glob.glob(os.path.join(_REF, pat)):
            src = open(f, errors="replace").read()
            ns = os.path.basename(f)
            for m in re.finditer(r"^@@([A-Za-z_][A-Za-z0-9_.]*)", src,
                                 re.M):
                by_mod.setdefault(ns, []).append(m.group(1))
    return by_mod


@pytest.mark.skipif(not os.path.isdir(_REF),
                    reason="reference tree not present")
def test_every_reference_public_name_resolves():
    by_mod = _collect()
    assert sum(len(v) for v in by_mod.values()) > 500  # sanity
    ns_map = {"nn.py": stf.nn, "image_ops.py": stf.image,
              "metrics.py": stf.metrics, "sets.py": stf.sets,
              "summary.py": stf.summary, "training.py": stf.train,
              "basic_session_run_hooks.py": stf.train,
              "session_run_hook.py": stf.train}
    fallbacks = (stf.errors, stf.nn, stf.image, stf.train, stf.summary,
                 stf.metrics, stf.sets, stf.lookup)
    missing = []
    for mod, names in by_mod.items():
        ns = ns_map.get(mod, stf)
        for n in names:
            root = n.split(".")[0]
            if hasattr(ns, root) or hasattr(stf, root):
                continue
            if any(hasattr(x, root) for x in fallbacks):
                continue
            missing.append(f"{mod}:{n}")
    assert not missing, (
        f"{len(missing)} reference public API names missing: {missing}")
