"""stf.nest conformance against reference tensorflow/python/util/nest.py
semantics (VERDICT missing #5): flatten order, dict key sorting,
namedtuple preservation, None-as-atom, error types."""

import collections

import numpy as np
import pytest

import simple_tensorflow_tpu as stf

nest = stf.nest

Point = collections.namedtuple("Point", ["x", "y"])


class TestFlatten:
    def test_atom_flattens_to_singleton(self):
        assert nest.flatten(5) == [5]
        assert nest.flatten("abc") == ["abc"]

    def test_none_is_an_atom(self):
        # reference nest: flatten(None) == [None]; jax's default treats
        # None as an empty subtree — stf.nest pins the reference behavior
        assert nest.flatten(None) == [None]
        assert nest.flatten([1, None, 2]) == [1, None, 2]

    def test_nested_list_tuple(self):
        assert nest.flatten([[1, 2], (3, [4])]) == [1, 2, 3, 4]

    def test_dict_sorted_key_order(self):
        # reference nest flattens dicts in sorted-key order
        assert nest.flatten({"b": 2, "a": 1, "c": 3}) == [1, 2, 3]

    def test_namedtuple(self):
        assert nest.flatten(Point(x=1, y=[2, 3])) == [1, 2, 3]

    def test_mixed_deep(self):
        s = {"w": Point(1, (2,)), "a": [3, {"z": 4, "y": 5}]}
        assert nest.flatten(s) == [3, 5, 4, 1, 2]

    def test_ordereddict_flattens_sorted_not_insertion(self):
        # reference nest sorts keys for EVERY mapping; jax.tree_util
        # flattens OrderedDict in insertion order — pinned here so
        # map_structure can never silently mispair atoms (r1 review fix)
        od = collections.OrderedDict([("b", 1), ("a", 2)])
        assert nest.flatten(od) == [2, 1]
        assert nest.flatten({"b": 1, "a": 2}) == [2, 1]

    def test_ordereddict_map_structure_pairs_by_key(self):
        od = collections.OrderedDict([("b", 1), ("a", 2)])
        out = nest.map_structure(lambda x, y: x + y, od,
                                 {"a": 10, "b": 20})
        assert dict(out) == {"a": 12, "b": 21}
        assert isinstance(out, collections.OrderedDict)
        assert list(out.keys()) == ["b", "a"]  # original order kept

    def test_defaultdict_packs_without_crashing(self):
        dd = collections.defaultdict(list, {"b": 1, "a": 2})
        flat = nest.flatten(dd)
        assert flat == [2, 1]
        packed = nest.pack_sequence_as(dd, [20, 10])
        assert dict(packed) == {"a": 20, "b": 10}


class TestPackSequenceAs:
    def test_roundtrip(self):
        for s in ([1, [2, 3]], (1, 2), {"a": 1, "b": (2, 3)},
                  Point(1, [2, 3]), 7):
            flat = nest.flatten(s)
            assert nest.pack_sequence_as(s, flat) == s

    def test_namedtuple_type_preserved(self):
        packed = nest.pack_sequence_as(Point(0, 0), [10, 20])
        assert isinstance(packed, Point)
        assert packed == Point(10, 20)

    def test_scalar_structure(self):
        assert nest.pack_sequence_as("ignored", [42]) == 42
        with pytest.raises(ValueError):
            nest.pack_sequence_as(5, [1, 2])

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            nest.pack_sequence_as([1, 2], [1, 2, 3])


class TestMapStructure:
    def test_single(self):
        assert nest.map_structure(lambda x: x * 2, [1, (2, {"a": 3})]) \
            == [2, (4, {"a": 6})]

    def test_multi(self):
        out = nest.map_structure(lambda a, b: a + b,
                                 {"a": 1, "b": [2, 3]},
                                 {"a": 10, "b": [20, 30]})
        assert out == {"a": 11, "b": [22, 33]}

    def test_structure_mismatch_raises(self):
        with pytest.raises(ValueError):
            nest.map_structure(lambda a, b: a, [1, 2], [1, [2, 3]])

    def test_type_mismatch_raises_typeerror(self):
        with pytest.raises(TypeError):
            nest.map_structure(lambda a, b: a, [1, 2], (1, 2))

    def test_check_types_false_allows_list_vs_tuple(self):
        out = nest.map_structure(lambda a, b: a + b, [1, 2], (10, 20),
                                 check_types=False)
        assert out == [11, 22]

    def test_non_callable_raises(self):
        with pytest.raises(TypeError):
            nest.map_structure("not-a-fn", [1])


class TestAssertSameStructure:
    def test_ok(self):
        nest.assert_same_structure([1, {"a": (2,)}], [9, {"a": (8,)}])

    def test_shape_mismatch(self):
        with pytest.raises(ValueError):
            nest.assert_same_structure([1, 2, 3], [1, 2])

    def test_dict_key_mismatch(self):
        with pytest.raises(ValueError):
            nest.assert_same_structure({"a": 1}, {"b": 1})

    def test_namedtuple_vs_tuple(self):
        with pytest.raises(TypeError):
            nest.assert_same_structure(Point(1, 2), (1, 2))
        nest.assert_same_structure(Point(1, 2), (1, 2),
                                   check_types=False)


class TestIsSequence:
    def test_values(self):
        assert nest.is_sequence([1])
        assert nest.is_sequence((1,))
        assert nest.is_sequence({"a": 1})
        assert nest.is_sequence(Point(1, 2))
        assert not nest.is_sequence("abc")
        assert not nest.is_sequence(1)
        assert not nest.is_sequence(np.zeros(3))
        assert not nest.is_sequence(None)

    def test_is_nested_alias(self):
        assert nest.is_nested([1]) and not nest.is_nested(3)


def test_works_with_tensors():
    stf.reset_default_graph()
    a = stf.constant([1.0, 2.0])
    b = stf.constant([3.0, 4.0])
    s = {"p": a, "q": [b, a]}
    flat = nest.flatten(s)
    assert len(flat) == 3 and all(hasattr(t, "dtype") for t in flat)
    doubled = nest.map_structure(lambda t: t * 2.0, s)
    with stf.Session() as sess:
        out = sess.run(doubled)
    np.testing.assert_allclose(out["p"], [2.0, 4.0])
    np.testing.assert_allclose(out["q"][0], [6.0, 8.0])
