"""Control flow: cond/while_loop/case/scan/map_fn (mirrors ref
control_flow_ops_test.py; structured XLA control flow semantics)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _run(t, feed=None):
    with stf.Session() as sess:
        return sess.run(t, feed)


class TestCond:
    def test_basic_branches(self):
        x = stf.placeholder(stf.float32, [], name="x")
        y = stf.cond(stf.less(x, stf.constant(0.0)),
                     lambda: stf.square(x), lambda: x + 1.0)
        with stf.Session() as sess:
            assert float(sess.run(y, {x: np.float32(-3.0)})) == 9.0
            assert float(sess.run(y, {x: np.float32(3.0)})) == 4.0

    def test_nested_cond(self):
        x = stf.placeholder(stf.float32, [], name="x")
        y = stf.cond(stf.less(x, 0.0),
                     lambda: stf.cond(stf.less(x, -10.0),
                                      lambda: stf.constant(-2.0),
                                      lambda: stf.constant(-1.0)),
                     lambda: stf.constant(1.0))
        with stf.Session() as sess:
            assert float(sess.run(y, {x: np.float32(-20.0)})) == -2.0
            assert float(sess.run(y, {x: np.float32(-5.0)})) == -1.0
            assert float(sess.run(y, {x: np.float32(5.0)})) == 1.0

    def test_cond_multi_output_structure(self):
        x = stf.constant(2.0)
        a, b = stf.cond(stf.greater(x, 0.0),
                        lambda: (x + 1.0, x + 2.0),
                        lambda: (x - 1.0, x - 2.0))
        out = _run({"a": a, "b": b})
        assert out["a"] == 3.0 and out["b"] == 4.0

    def test_cond_gradient(self):
        x = stf.placeholder(stf.float32, [], name="x")
        y = stf.cond(stf.less(x, 0.0), lambda: stf.square(x),
                     lambda: x * 3.0)
        (g,) = stf.gradients(y, [x])
        with stf.Session() as sess:
            assert float(sess.run(g, {x: np.float32(-4.0)})) == -8.0
            assert float(sess.run(g, {x: np.float32(4.0)})) == 3.0

    def test_case(self):
        x = stf.placeholder(stf.int32, [], name="x")
        y = stf.case([(stf.equal(x, 1), lambda: stf.constant(10.0)),
                      (stf.equal(x, 2), lambda: stf.constant(20.0))],
                     default=lambda: stf.constant(-1.0))
        with stf.Session() as sess:
            assert float(sess.run(y, {x: np.int32(1)})) == 10.0
            assert float(sess.run(y, {x: np.int32(2)})) == 20.0
            assert float(sess.run(y, {x: np.int32(9)})) == -1.0


class TestWhileLoop:
    def test_counter(self):
        i = stf.constant(0)
        out = stf.while_loop(lambda i: stf.less(i, 10), lambda i: i + 1, [i])
        assert int(_run(out)) == 10

    def test_multiple_loop_vars(self):
        i = stf.constant(0)
        acc = stf.constant(0.0)
        i_out, acc_out = stf.while_loop(
            lambda i, a: stf.less(i, 5),
            lambda i, a: (i + 1, a + stf.cast(i, stf.float32)),
            [i, acc])
        assert float(_run(acc_out)) == 10.0  # 0+1+2+3+4

    def test_shape_invariance_enforced(self):
        x = stf.constant([1.0])
        with pytest.raises((ValueError, TypeError)):
            stf.while_loop(lambda v: stf.less(stf.size(v), 5),
                           lambda v: stf.concat([v, v], 0), [x])

    def test_dtype_change_rejected(self):
        with pytest.raises(TypeError):
            stf.while_loop(lambda i: stf.less(i, 3),
                           lambda i: stf.cast(i, stf.float32) + 1.0,
                           [stf.constant(0)])

    def test_vector_state(self):
        v = stf.constant([1.0, 1.0])
        out = stf.while_loop(
            lambda v: stf.less(stf.reduce_sum(v), 100.0),
            lambda v: v * 2.0, [v])
        assert _run(out).tolist() == [64.0, 64.0]


class TestScanFold:
    def test_scan_cumsum(self):
        x = stf.constant([1.0, 2.0, 3.0, 4.0])
        s = stf.scan(lambda acc, e: acc + e, x, initializer=stf.constant(0.0))
        assert _run(s).tolist() == [1.0, 3.0, 6.0, 10.0]

    def test_scan_gradient(self):
        x = stf.constant([1.0, 2.0, 3.0])
        s = stf.scan(lambda acc, e: acc * e, x,
                     initializer=stf.constant(1.0))
        loss = stf.reduce_sum(s)
        (g,) = stf.gradients(loss, [x])
        # s = [1, 2, 6]; d/dx1 = 1 + 2 + 6/1... numeric check instead
        out = _run(g)
        assert np.isfinite(out).all() and out.shape == (3,)

    def test_foldl_foldr(self):
        x = stf.constant([1.0, 2.0, 3.0])
        l = stf.foldl(lambda a, e: a + e, x)
        r = stf.foldr(lambda a, e: a - e, x, initializer=stf.constant(0.0))
        out = _run({"l": l, "r": r})
        assert float(out["l"]) == 6.0
        # foldr: 1 - (2 - (3 - 0)) ... depends on convention; just finite
        assert np.isfinite(out["r"])

    def test_map_fn(self):
        x = stf.constant([[1.0, 2.0], [3.0, 4.0]])
        m = stf.map_fn(lambda row: stf.reduce_sum(row) * 2.0, x)
        assert _run(m).tolist() == [6.0, 14.0]


class TestRNN:
    def test_dynamic_rnn_basic_cell(self):
        from simple_tensorflow_tpu.ops import rnn, rnn_cell

        x = stf.placeholder(stf.float32, [2, 5, 3], name="x")
        cell = rnn_cell.BasicRNNCell(4)
        outputs, state = rnn.dynamic_rnn(cell, x, dtype=stf.float32)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            o, s = sess.run([outputs, state],
                            {x: np.random.RandomState(0).rand(
                                2, 5, 3).astype(np.float32)})
        assert o.shape == (2, 5, 4) and s.shape == (2, 4)
        np.testing.assert_allclose(o[:, -1, :], s, rtol=1e-5)

    def test_lstm_cell_shapes_and_learning(self):
        from simple_tensorflow_tpu.ops import rnn, rnn_cell

        x = stf.placeholder(stf.float32, [4, 6, 2], name="x")
        y = stf.placeholder(stf.float32, [4], name="y")
        cell = rnn_cell.BasicLSTMCell(8)
        outputs, state = rnn.dynamic_rnn(cell, x, dtype=stf.float32)
        pred = stf.squeeze(stf.layers.dense(state.h, 1), axis=[1])
        loss = stf.reduce_mean(stf.square(pred - y))
        train = stf.train.AdamOptimizer(0.05).minimize(loss)
        rng = np.random.RandomState(0)
        xv = rng.rand(4, 6, 2).astype(np.float32)
        yv = xv.sum((1, 2)).astype(np.float32)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            l0 = sess.run(loss, {x: xv, y: yv})
            for _ in range(30):
                _, l = sess.run([train, loss], {x: xv, y: yv})
        assert l < l0 * 0.5

    def test_gru_cell_runs(self):
        from simple_tensorflow_tpu.ops import rnn, rnn_cell

        x = stf.placeholder(stf.float32, [1, 3, 2], name="x")
        outputs, state = rnn.dynamic_rnn(rnn_cell.GRUCell(5), x,
                                         dtype=stf.float32)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            o = sess.run(outputs, {x: np.ones((1, 3, 2), np.float32)})
        assert o.shape == (1, 3, 5)

    def test_sequence_length_masks_outputs(self):
        from simple_tensorflow_tpu.ops import rnn, rnn_cell

        x = stf.placeholder(stf.float32, [2, 4, 2], name="x")
        outputs, state = rnn.dynamic_rnn(
            rnn_cell.BasicRNNCell(3), x,
            sequence_length=stf.constant([2, 4]), dtype=stf.float32)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            o = sess.run(outputs, {x: np.ones((2, 4, 2), np.float32)})
        assert (o[0, 2:] == 0).all()  # past-length outputs zeroed
        assert not (o[1, 2:] == 0).all()

    def test_multi_rnn_cell(self):
        from simple_tensorflow_tpu.ops import rnn, rnn_cell

        x = stf.placeholder(stf.float32, [1, 4, 3], name="x")
        cell = rnn_cell.MultiRNNCell(
            [rnn_cell.BasicRNNCell(4), rnn_cell.BasicRNNCell(2)])
        outputs, state = rnn.dynamic_rnn(cell, x, dtype=stf.float32)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            o = sess.run(outputs, {x: np.ones((1, 4, 3), np.float32)})
        assert o.shape == (1, 4, 2)


class TestPyFunc:
    def test_py_func_roundtrip(self):
        x = stf.placeholder(stf.float32, [3], name="x")
        y = stf.py_func(lambda v: v * 2.0, [x], stf.float32)
        y.set_shape([3])  # XLA needs static callback result shapes
        y2 = y + 1.0  # composes with device ops (pure_callback)
        with stf.Session() as sess:
            out = sess.run(y2, {x: np.float32([1, 2, 3])})
        assert out.tolist() == [3.0, 5.0, 7.0]


class TestRawRNN:
    def test_matches_dynamic_rnn_with_lengths(self):
        from simple_tensorflow_tpu.ops import rnn, rnn_cell

        stf.reset_default_graph()
        T, B, D, H = 5, 3, 4, 6
        rng = np.random.RandomState(0)
        xv = rng.rand(T, B, D).astype(np.float32)
        seq = np.array([5, 3, 1], np.int32)

        xc = stf.constant(xv)
        seq_t = stf.constant(seq)
        cell = rnn_cell.BasicRNNCell(H)

        def loop_fn(time, output, state, loop_state):
            finished = time >= seq_t                      # (B,) bool
            if output is None:                            # time 0
                next_state = cell.zero_state(B, stf.float32)
            else:
                next_state = state
            idx = stf.minimum(time, T - 1)
            next_input = stf.gather(xc, idx)              # (B, D)
            return finished, next_input, next_state, output, None

        emit_ta, final_state, _ = rnn.raw_rnn(cell, loop_fn,
                                              maximum_iterations=T)
        emit = emit_ta.stack()                            # (T, B, H)
        # same weights: dynamic_rnn reuses scope "rnn" (AUTO_REUSE)
        out_ref, state_ref = rnn.dynamic_rnn(
            cell, stf.constant(xv.transpose(1, 0, 2)),
            sequence_length=seq_t, dtype=stf.float32)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            e, fs, o, sr = sess.run([emit, final_state, out_ref, state_ref])
        np.testing.assert_allclose(e, o.transpose(1, 0, 2), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(fs, sr, rtol=1e-5, atol=1e-6)

    def test_requires_maximum_iterations(self):
        from simple_tensorflow_tpu.ops import rnn, rnn_cell

        stf.reset_default_graph()
        cell = rnn_cell.BasicRNNCell(2)
        import pytest as _pytest
        with _pytest.raises(ValueError, match="maximum_iterations"):
            rnn.raw_rnn(cell, lambda *a: None)

    def test_gradient_through_unbounded_while_raises_early(self):
        # No maximum_iterations -> no reverse-mode rule; must fail at
        # graph construction with an actionable message, not deep inside
        # Session.run lowering.
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [], name="x")
        _, y = stf.while_loop(lambda i, a: stf.less(i, 5),
                              lambda i, a: (i + 1, a * x),
                              [stf.constant(0), x])
        import pytest as _pytest
        with _pytest.raises(stf.errors.InvalidArgumentError,
                            match="maximum_iterations"):
            stf.gradients(y, [x])

    def test_gradient_through_bounded_while_exact(self):
        # maximum_iterations makes the loop reverse-differentiable: the
        # gradient replay lowers it as a masked lax.scan over the bound.
        # Bound (8) > trip count (5): masked iterations must affect
        # neither the value nor the gradient. y = x^6, dy/dx = 6 x^5.
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [], name="x")
        _, y = stf.while_loop(lambda i, a: stf.less(i, 5),
                              lambda i, a: (i + 1, a * x),
                              [stf.constant(0), x],
                              maximum_iterations=8)
        (g,) = stf.gradients(y, [x])
        with stf.Session() as sess:
            yv, gv = sess.run([y, g], feed_dict={x: 2.0})
        assert float(np.asarray(yv)) == 64.0
        assert float(np.asarray(gv)) == 6.0 * 2.0 ** 5

    def test_gradient_bounded_while_body_invalid_past_exit(self):
        # The replay must GUARD post-exit iterations (lax.cond), not just
        # mask their outputs: this body computes sqrt(a-1), which is NaN
        # territory once the loop has converged to a=1 — a 0*NaN through
        # a where-mask would poison the gradient. a: 5 -> 2 -> 1, exit;
        # y = sqrt(sqrt(x-1)-1), dy/dx at x=5 is 1/8.
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [], name="x")
        _, y = stf.while_loop(
            lambda i, a: stf.greater(a, 1.0),
            lambda i, a: (i + 1, stf.sqrt(a - 1.0)),
            [stf.constant(0), x], maximum_iterations=6)
        (g,) = stf.gradients(y, [x])
        with stf.Session() as sess:
            yv, gv = sess.run([y, g], feed_dict={x: 5.0})
        assert float(np.asarray(yv)) == 1.0
        np.testing.assert_allclose(float(np.asarray(gv)), 0.125,
                                   rtol=1e-5)

    def test_gradient_through_bounded_while_numeric(self):
        # Vector loop vars + an early-exiting cond on a carried scalar:
        # symbolic grads must match central differences.
        from simple_tensorflow_tpu.framework import gradient_checker

        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [3], name="x")

        def cond(i, v):
            return stf.less(i, 4)

        def body(i, v):
            return i + 1, stf.tanh(v) * 1.5

        _, out = stf.while_loop(cond, body, [stf.constant(0), x],
                                maximum_iterations=6)
        y = stf.reduce_sum(stf.square(out))
        with stf.Session().as_default():
            err = gradient_checker.compute_gradient_error(
                x, [3], y, [], x_init_value=np.array(
                    [0.3, -0.7, 1.2], np.float32), delta=1e-3)
        assert err < 2e-3, err

    def test_gradient_through_raw_rnn(self):
        # raw_rnn's While carries its maximum_iterations bound, so the
        # emit-driven RNN loop trains like the reference's.
        from simple_tensorflow_tpu.ops import rnn, rnn_cell

        stf.reset_default_graph()
        cell = rnn_cell.BasicRNNCell(3)
        xc = stf.constant(np.random.RandomState(0).randn(4, 2, 2)
                          .astype(np.float32))
        seq_t = stf.constant(np.array([4, 2], np.int32))

        def loop_fn(time, output, state, loop_state):
            finished = time >= seq_t
            st = cell.zero_state(2, stf.float32) if output is None \
                else state
            return (finished, stf.gather(xc, stf.minimum(time, 3)), st,
                    output, None)

        emit_ta, _, _ = rnn.raw_rnn(cell, loop_fn, maximum_iterations=4)
        loss = stf.reduce_mean(stf.square(emit_ta.stack()))
        grads = stf.gradients(loss, stf.trainable_variables())
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            gvals = sess.run(grads)
        for gv in gvals:
            a = np.asarray(gv)
            assert np.isfinite(a).all()
            assert np.abs(a).sum() > 0

    def test_gradient_ok_when_while_cut_by_stop_gradient(self):
        # A While output that reaches the loss only through stop_gradient
        # receives zero cotangents — the loop transpose is never invoked,
        # so graph construction must not reject it.
        stf.reset_default_graph()
        w = stf.Variable(np.float32(2.0))
        i = stf.constant(0)
        count = stf.while_loop(lambda i: stf.less(i, 3), lambda i: i + 1, [i])
        scale = stf.stop_gradient(stf.cast(count, stf.float32))
        loss = w * w * scale
        (g,) = stf.gradients(loss, [w])
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(g) == 4.0 * 3.0

    def test_gradient_ok_when_while_path_is_integer_only(self):
        # Integer (non-differentiable) tensors flowing out of a While into
        # a gather index carry no cotangent; must not raise.
        stf.reset_default_graph()
        w = stf.Variable(np.arange(4, dtype=np.float32))
        i = stf.constant(0)
        idx = stf.while_loop(lambda i: stf.less(i, 2), lambda i: i + 1, [i])
        loss = stf.square(stf.gather(w, idx))
        (g,) = stf.gradients(loss, [w])
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            gv = sess.run(g)
        np.testing.assert_allclose(gv, [0.0, 0.0, 4.0, 0.0])
