"""Generative inference engine (ISSUE 12): KV-cache op conformance,
cached-vs-naive beam-search parity, decode-attention kernel parity,
token-level continuous batching (mid-decode join/leave bit-for-bit,
EOS retirement and slot reuse under churn, per-token deadlines), the
int8 decode route, and the serving-decode-cache lint rule."""

import os
import tempfile
import threading
import time

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import analysis, serving
from simple_tensorflow_tpu.framework import errors, op_registry
from simple_tensorflow_tpu.kernels import registry as kreg
from simple_tensorflow_tpu.models import transformer as tr
from simple_tensorflow_tpu.ops import kv_cache_ops as kvc


@pytest.fixture(autouse=True)
def _fresh_graph():
    stf.reset_default_graph()
    yield
    stf.reset_default_graph()


# ---------------------------------------------------------------------------
# KV-cache op conformance
# ---------------------------------------------------------------------------

class TestKVCacheOps:
    def test_alloc_append_gather_roundtrip(self):
        c = kvc.kv_cache("c_rt", num_slots=4, max_len=8,
                         inner_shape=(2, 3), dtype=stf.float32)
        alloc = c.alloc()
        val = stf.placeholder(stf.float32, [2, 1, 2, 3], "val")
        slots = stf.placeholder(stf.int32, [2], "slots")
        pos = stf.placeholder(stf.int32, [2], "pos")
        gathered = c.append_and_gather(val, slots, pos)
        with stf.Session() as sess:
            sess.run(alloc.op)
            v = np.arange(12, dtype=np.float32).reshape(2, 1, 2, 3)
            g = sess.run(gathered, {val: v,
                                    slots: np.array([1, 3], np.int32),
                                    pos: np.array([0, 5], np.int32)})
            assert g.shape == (2, 8, 2, 3)
            assert np.array_equal(g[0, 0], v[0, 0])
            assert np.array_equal(g[1, 5], v[1, 0])
            assert (g[0, 1:] == 0).all() and (g[1, :5] == 0).all()
            # append is an accumulating in-place update across runs
            g2 = sess.run(gathered, {val: v + 100.0,
                                     slots: np.array([1, 3], np.int32),
                                     pos: np.array([1, 6], np.int32)})
            assert np.array_equal(g2[0, 0], v[0, 0])       # survives
            assert np.array_equal(g2[0, 1], v[0, 0] + 100.0)

    def test_multi_position_prefill_append(self):
        # P > 1: the prefill path writes a whole prompt's rows at once
        c = kvc.kv_cache("c_pf", num_slots=3, max_len=6,
                         inner_shape=(), dtype=stf.float32)
        alloc = c.alloc()
        val = stf.placeholder(stf.float32, [2, 4], "valp")
        slots = stf.placeholder(stf.int32, [2], "slotsp")
        pos = stf.placeholder(stf.int32, [2], "posp")
        gathered = c.append_and_gather(val, slots, pos)
        with stf.Session() as sess:
            sess.run(alloc.op)
            v = np.arange(8, dtype=np.float32).reshape(2, 4)
            g = sess.run(gathered, {val: v,
                                    slots: np.array([2, 0], np.int32),
                                    pos: np.array([0, 2], np.int32)})
            assert np.array_equal(g[0, :4], v[0])
            assert np.array_equal(g[1, 2:6], v[1])
            assert (g[1, :2] == 0).all()

    def test_alloc_resets_slots(self):
        c = kvc.kv_cache("c_reset", num_slots=2, max_len=2,
                         inner_shape=(), dtype=stf.float32)
        alloc = c.alloc()
        val = stf.placeholder(stf.float32, [1, 1], "valr")
        one = stf.constant(np.array([0], np.int32))
        gathered = c.append_and_gather(val, one, one * 0)
        with stf.Session() as sess:
            sess.run(alloc.op)
            sess.run(gathered, {val: np.ones((1, 1), np.float32)})
            sess.run(alloc.op)  # engine reset: back to zeros
            g = sess.run(c.gather(one))
            assert (g == 0).all()

    def test_effects_declared(self):
        # the hazard engine sees cache ops as resource accesses on the
        # SAME selector space as Assign/ReadVariable
        c = kvc.kv_cache("c_eff", 2, 2, (), stf.float32)
        a = c.alloc()
        g = c.gather(stf.constant(np.array([0], np.int32)))
        eff_a = op_registry.get("KVCacheAlloc").effects
        eff_g = op_registry.get("KVCacheGather").effects
        eff_ap = op_registry.get("KVCacheAppend").effects
        assert eff_a.resolved_writes(a.op) == {"var_name=c_eff"}
        assert eff_g.resolved_reads(g.op) == {"var_name=c_eff"}
        assert eff_ap.update == "update"

    def test_gather_before_alloc_fails(self):
        c = kvc.kv_cache("c_uninit", 2, 2, (), stf.float32)
        g = c.gather(stf.constant(np.array([0], np.int32)))
        with stf.Session() as sess:
            with pytest.raises(errors.FailedPreconditionError):
                sess.run(g)


# ---------------------------------------------------------------------------
# DecodeAttention kernel parity
# ---------------------------------------------------------------------------

class TestDecodeAttention:
    def _case(self, B=3, L=8, H=2, D=4, seed=0):
        rng = np.random.RandomState(seed)
        q = rng.randn(B, H, D).astype(np.float32)
        k = rng.randn(B, L, H, D).astype(np.float32)
        v = rng.randn(B, L, H, D).astype(np.float32)
        return q, k, v

    def _reference(self, q, k, v, lengths, bias=None):
        from simple_tensorflow_tpu.ops.pallas import mha_reference

        B, H, D = q.shape
        out = np.zeros_like(q)
        for b in range(B):
            n = int(lengths[b])
            qr = q[b].reshape(1, H, 1, D)
            kr = k[b, :n].transpose(1, 0, 2).reshape(1, H, n, D)
            vr = v[b, :n].transpose(1, 0, 2).reshape(1, H, n, D)
            bb = bias[b:b + 1, :n] if bias is not None else None
            out[b] = np.asarray(mha_reference(qr, kr, vr, bias=bb)
                                )[0, :, 0, :]
        return out

    def test_both_impls_match_reference(self):
        from simple_tensorflow_tpu.ops.pallas.decode_attention import (
            decode_attention, decode_attention_xla)

        q, k, v = self._case()
        lengths = np.array([3, 8, 5], np.int32)
        ref = self._reference(q, k, v, lengths)
        for fn in (decode_attention, decode_attention_xla):
            out = np.asarray(fn(q, k, v, lengths))
            np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_bias_parity(self):
        from simple_tensorflow_tpu.ops.pallas.decode_attention import (
            decode_attention, decode_attention_xla)

        q, k, v = self._case(B=2, L=8)
        bias = np.where(np.arange(8)[None, :] % 3 == 0, 0.0,
                        -1e9).astype(np.float32).repeat(2, 0).reshape(2, 8)
        lengths = np.full(2, 8, np.int32)
        ref = self._reference(q, k, v, lengths, bias=bias)
        for fn in (decode_attention, decode_attention_xla):
            out = np.asarray(fn(q, k, v, lengths, bias=bias))
            np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_graph_op_force_routed(self):
        # acceptance: the registry reports the decode kernel as routed
        # under force (interpret mode on this CPU mesh)
        q, k, v = self._case()
        lengths = np.array([3, 8, 5], np.int32)
        qp = stf.placeholder(stf.float32, [3, 2, 4], "q")
        kp = stf.placeholder(stf.float32, [3, 8, 2, 4], "k")
        vp = stf.placeholder(stf.float32, [3, 8, 2, 4], "v")
        lp = stf.placeholder(stf.int32, [3], "len")
        out_t = stf.nn.decode_attention(qp, kp, vp, lp)
        before = {r["op"]: r for r in kreg.decisions_snapshot()}
        kreg.set_mode("force")
        try:
            kreg.clear_decisions()
            with stf.Session() as sess:
                out = sess.run(out_t, {qp: q, kp: k, vp: v, lp: lengths})
            np.testing.assert_allclose(
                out, self._reference(q, k, v, lengths), atol=1e-5)
            routed = [r for r in kreg.decisions_snapshot()
                      if r["op"] == "DecodeAttention"]
            assert routed and routed[0]["impl"] == "pallas"
            # offline report agrees (graph_lint --kernels path)
            rep = kreg.routing_report([out_t.op], mode="force")
            assert rep[0]["verdict"] == "routed"
        finally:
            kreg.set_mode(None)
            kreg.clear_decisions()

    def test_auto_mode_falls_back_off_tpu(self):
        q, k, v = self._case()
        impl, reason = kreg.decide(
            "DecodeAttention",
            kreg.aval_key(q, k, v, None, has_bias=False), mode="auto",
            count=False)
        assert impl == "xla" and reason in ("interpret_backend",
                                            "autotune")


# ---------------------------------------------------------------------------
# Cached beam search == naive re-forward search
# ---------------------------------------------------------------------------

class TestCachedBeamParity:
    def test_token_for_token_and_scores(self):
        cfg = tr.TransformerConfig.tiny()
        src = stf.placeholder(stf.int32, [2, 8], "src")
        ids_n, sc_n = tr.beam_search_decode(
            src, cfg, beam_size=3, decode_len=8,
            compute_dtype=stf.float32)
        ids_c, sc_c = tr.beam_search_decode(
            src, cfg, beam_size=3, decode_len=8,
            compute_dtype=stf.float32, use_cache=True)
        batch = tr.synthetic_wmt_batch(2, 8, 8,
                                       vocab_size=cfg.vocab_size)
        # pad a few source positions: the cross-attention bias must ride
        # the cache path identically
        src_ids = batch["src_ids"].copy()
        src_ids[:, -2:] = cfg.pad_id
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            a_ids, a_sc, b_ids, b_sc = sess.run(
                [ids_n, sc_n, ids_c, sc_c], {src: src_ids})
        # int-exact ids, tight-tolerance scores (ISSUE 12 acceptance)
        assert np.array_equal(a_ids, b_ids)
        np.testing.assert_allclose(a_sc, b_sc, atol=1e-4)

    def test_bf16_compute_dtype_runs(self):
        cfg = tr.TransformerConfig.tiny()
        src = stf.placeholder(stf.int32, [1, 8], "src")
        ids, scores = tr.beam_search_decode(
            src, cfg, beam_size=2, decode_len=6,
            compute_dtype=stf.bfloat16, use_cache=True)
        batch = tr.synthetic_wmt_batch(1, 8, 8,
                                       vocab_size=cfg.vocab_size)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            out_ids, out_sc = sess.run([ids, scores],
                                       {src: batch["src_ids"]})
        assert out_ids.shape == (1, 2, 6)
        assert (out_ids[:, :, 0] == cfg.eos_id).all()
        assert np.isfinite(out_sc).all()


# ---------------------------------------------------------------------------
# Serving decode program: greedy parity through a checkpoint
# ---------------------------------------------------------------------------

def _naive_greedy(sess, handles, src_row, steps, cfg):
    """Greedy re-forward decode: full decode() per emitted token."""
    seq = np.full((1, handles["L"]), cfg.pad_id, np.int32)
    seq[0, 0] = cfg.eos_id
    out = []
    for t in range(steps):
        logits = sess.run(handles["logits"],
                          {handles["src"]: src_row[None, :],
                           handles["tgt"]: seq})
        tok = int(np.argmax(logits[0, t]))
        out.append(tok)
        if t + 1 < handles["L"]:
            seq[0, t + 1] = tok
    return out


class TestServingDecodeParity:
    def test_greedy_matches_naive_reforward_via_checkpoint(self):
        cfg = tr.TransformerConfig.tiny()
        src_len, L = 8, 8
        tmp = tempfile.mkdtemp(prefix="stf_gen_ckpt_")
        ckpt = os.path.join(tmp, "model")
        g1 = stf.Graph()
        with g1.as_default():
            stf.set_random_seed(7)
            src = stf.placeholder(stf.int32, [1, src_len], "src")
            tgt = stf.placeholder(stf.int32, [1, L], "tgt")
            enc_out, enc_bias = tr.encode(src, cfg, training=False,
                                          compute_dtype=stf.float32)
            logits = tr.decode(tgt, enc_out, enc_bias, cfg,
                               training=False,
                               compute_dtype=stf.float32)
            with stf.Session(graph=g1) as sess:
                sess.run(stf.global_variables_initializer())
                saver = stf.train.Saver()
                saver.save(sess, ckpt)
                batch = tr.synthetic_wmt_batch(
                    1, src_len, L, vocab_size=cfg.vocab_size)
                src_row = batch["src_ids"][0].copy()
                src_row[-2:] = cfg.pad_id  # exercise the bias cache
                naive = _naive_greedy(
                    sess, {"src": src, "tgt": tgt, "logits": logits,
                           "L": L}, src_row, steps=L - 1, cfg=cfg)
        model = tr.TransformerGenerativeModel(
            cfg, src_len, num_slots=2, max_decode_len=L,
            checkpoint=ckpt, aot_warmup=False)
        try:
            model.prefill(src_row[None, :], [0])
            tok = np.array([cfg.eos_id], np.int32)
            cached = []
            for t in range(L - 1):
                nxt, _lp, _b = model.decode(tok, [t], [0])
                cached.append(int(nxt[0]))
                tok = nxt
        finally:
            model.close()
        assert cached == naive

    def test_int8_decode_path(self):
        cfg = tr.TransformerConfig.tiny()
        model = tr.TransformerGenerativeModel(
            cfg, 8, num_slots=2, max_decode_len=6, init_fresh=True,
            int8=True, aot_warmup=False)
        try:
            batch = tr.synthetic_wmt_batch(1, 8, 8,
                                           vocab_size=cfg.vocab_size)
            model.prefill(batch["src_ids"], [0])
            tok = np.array([cfg.eos_id], np.int32)
            toks = []
            for t in range(4):
                nxt, lp, _b = model.decode(tok, [t], [0])
                toks.append(int(nxt[0]))
                tok = nxt
            assert all(0 <= t < cfg.vocab_size for t in toks)
        finally:
            model.close()

    def test_int8_force_routes_quant_matmul(self):
        cfg = tr.TransformerConfig.tiny()
        kreg.set_mode("force")
        try:
            kreg.clear_decisions()
            model = tr.TransformerGenerativeModel(
                cfg, 8, num_slots=2, max_decode_len=6, init_fresh=True,
                int8=True, aot_warmup=False)
            try:
                batch = tr.synthetic_wmt_batch(
                    1, 8, 8, vocab_size=cfg.vocab_size)
                model.prefill(batch["src_ids"], [0])
                model.decode([cfg.eos_id], [0], [0])
            finally:
                model.close()
            decided = {r["op"]: r["impl"]
                       for r in kreg.decisions_snapshot()}
            assert decided.get("DecodeAttention") == "pallas"
            assert decided.get("QuantMatMul") == "pallas"
        finally:
            kreg.set_mode(None)
            kreg.clear_decisions()


# ---------------------------------------------------------------------------
# Token-level continuous batching: the engine
# ---------------------------------------------------------------------------

class _FakeModel:
    """Deterministic duck-typed model: sequence for slot s emits tokens
    100+s repeatedly and EOS after ``eos_after[prompt_id]`` tokens.
    Decode is independent per row — like the real decode program."""

    eos_id = 1
    pad_id = 0
    src_len = 4
    num_slots = 4
    max_decode_len = 16

    def __init__(self, eos_after, delay_s=0.0):
        self.eos_after = dict(eos_after)   # prompt id -> #tokens pre-EOS
        self.delay_s = delay_s
        self.prompt_of_slot = {}
        self.emitted = {}
        self.prefills = 0
        self.decode_calls = []
        self.closed = False

    def prefill(self, src_rows, slots):
        self.prefills += 1
        for row, slot in zip(np.asarray(src_rows), np.asarray(slots)):
            pid = int(row[0])
            self.prompt_of_slot[int(slot)] = pid
            self.emitted[int(slot)] = 0

    def decode(self, tokens, positions, slots):
        if self.delay_s:
            time.sleep(self.delay_s)
        n = len(slots)
        bucket = 1
        while bucket < n:
            bucket *= 2
        bucket = min(bucket, self.num_slots)
        self.decode_calls.append((n, bucket))
        out, lps = [], []
        for slot in np.asarray(slots):
            slot = int(slot)
            pid = self.prompt_of_slot[slot]
            self.emitted[slot] += 1
            if self.emitted[slot] > self.eos_after.get(pid, 10 ** 9):
                out.append(self.eos_id)
            else:
                out.append(100 + pid)
            lps.append(-0.5)
        return np.asarray(out, np.int32), np.asarray(lps, np.float32), \
            bucket

    def close(self):
        self.closed = True


def _prompt(pid):
    return np.array([pid, 0, 0, 0], np.int32)


class TestGenerativeEngine:
    def test_eos_retirement_and_slot_reuse_under_churn(self):
        fake = _FakeModel({i: (i % 5) + 1 for i in range(12)})
        pol = serving.DecodePolicy(num_slots=4, max_decode_len=16,
                                   max_new_tokens=12)
        with serving.GenerativeEngine("churn", fake, pol) as eng:
            futs = [eng.generate(_prompt(i)) for i in range(12)]
            results = [f.result(timeout=30) for f in futs]
        for i, r in enumerate(results):
            want = (i % 5) + 1
            assert r["outcome"] == "eos"
            assert list(r["tokens"]) == [100 + i] * want + [fake.eos_id]
        assert fake.closed
        # slots were REUSED: 12 sequences over 4 slots
        assert len({s for s in fake.prompt_of_slot}) <= 4
        # churn kept fill high: most steps ran multiple live sequences
        fills = [n / b for n, b in fake.decode_calls]
        assert sum(fills) / len(fills) > 0.5

    def test_join_leave_bitexact_vs_solo(self):
        cfg = tr.TransformerConfig.tiny()
        model = tr.TransformerGenerativeModel(
            cfg, 8, num_slots=4, max_decode_len=8,
            decode_bucket_sizes=[4], init_fresh=True, aot_warmup=False)
        pol = serving.DecodePolicy(num_slots=4, max_decode_len=8,
                                   bucket_sizes=[4], max_new_tokens=6)
        batch = tr.synthetic_wmt_batch(4, 8, 8,
                                       vocab_size=cfg.vocab_size)
        with serving.GenerativeEngine("bitexact", model, pol) as eng:
            # solo: one at a time through the SAME bucket-4 program
            solo = []
            for i in range(4):
                r = eng.generate(batch["src_ids"][i],
                                 max_new_tokens=4 + i % 3
                                 ).result(timeout=60)
                solo.append(list(r["tokens"]))
            # churning: all four at once, staggered budgets so they
            # LEAVE at different steps (and later ones decode in a
            # partially-filled batch)
            futs = [eng.generate(batch["src_ids"][i],
                                 max_new_tokens=4 + i % 3)
                    for i in range(4)]
            joined = [list(f.result(timeout=60)["tokens"]) for f in futs]
        assert joined == solo

    def test_per_token_deadline_no_batch_stall(self):
        fake = _FakeModel({0: 100, 1: 2}, delay_s=0.02)
        pol = serving.DecodePolicy(num_slots=2, max_decode_len=16,
                                   max_new_tokens=50)
        with serving.GenerativeEngine("deadline", fake, pol) as eng:
            slow = eng.generate(_prompt(0), timeout_ms=120)
            fast = eng.generate(_prompt(1))
            r_fast = fast.result(timeout=30)
            assert r_fast["outcome"] == "eos"
            with pytest.raises(errors.DeadlineExceededError):
                slow.result(timeout=30)
            # the expired request emitted SOME tokens before retiring
            # mid-decode (per-token deadline, not per-request)
            assert slow.exception() is not None

    def test_streaming_and_queue_backpressure(self):
        fake = _FakeModel({i: 3 for i in range(6)})
        pol = serving.DecodePolicy(num_slots=2, max_decode_len=16)
        tokens_seen = []
        with serving.GenerativeEngine("stream", fake, pol) as eng:
            futs = [eng.generate(
                _prompt(i),
                on_token=(lambda t, lp: tokens_seen.append(t))
                if i == 0 else None) for i in range(6)]
            results = [f.result(timeout=30) for f in futs]
        assert all(r["outcome"] == "eos" for r in results)
        assert tokens_seen == list(results[0]["tokens"])

    def test_close_rejects_new_drains_queued(self):
        fake = _FakeModel({i: 2 for i in range(3)})
        pol = serving.DecodePolicy(num_slots=2, max_decode_len=16)
        eng = serving.GenerativeEngine("drain", fake, pol)
        futs = [eng.generate(_prompt(i)) for i in range(3)]
        eng.close()
        for f in futs:
            assert f.result(timeout=30)["outcome"] == "eos"
        late = eng.generate(_prompt(0))
        with pytest.raises(errors.UnavailableError):
            late.result(timeout=5)

    def test_prompt_too_long_rejected(self):
        fake = _FakeModel({})
        pol = serving.DecodePolicy(num_slots=2, max_decode_len=16)
        with serving.GenerativeEngine("toolong", fake, pol) as eng:
            fut = eng.generate(np.zeros(99, np.int32))
            with pytest.raises(errors.InvalidArgumentError):
                fut.result(timeout=5)

    def test_decode_metrics_populated(self):
        from simple_tensorflow_tpu.platform import monitoring

        fake = _FakeModel({i: 2 for i in range(4)})
        pol = serving.DecodePolicy(num_slots=4, max_decode_len=16)
        with serving.GenerativeEngine("metrics_eng", fake, pol) as eng:
            futs = [eng.generate(_prompt(i)) for i in range(4)]
            [f.result(timeout=30) for f in futs]
        exported = monitoring.export()
        toks = exported["/stf/serving/decode_tokens"]["cells"]
        assert any("metrics_eng" in str(k) and v >= 4
                   for k, v in toks.items())
        seqs = exported["/stf/serving/decode_sequences"]["cells"]
        assert any("metrics_eng" in str(k) and "eos" in str(k) and v == 4
                   for k, v in seqs.items())
        assert "/stf/serving/decode_fill" in exported
        assert "/stf/serving/decode_step_seconds" in exported


class TestReviewRegressions:
    def test_decode_len_beyond_pos_table_raises(self):
        # the position-encoding gather would silently CLAMP past
        # cfg.max_len (wrong tokens, no error) — both cached surfaces
        # must refuse up front
        cfg = tr.TransformerConfig.tiny()  # max_len=32
        src = stf.placeholder(stf.int32, [1, 8], "src")
        with pytest.raises(ValueError, match="max_len"):
            tr.beam_search_decode(src, cfg, decode_len=cfg.max_len + 1,
                                  use_cache=True)
        with pytest.raises(ValueError, match="max_len"):
            tr.build_generative_program(cfg, 8, num_slots=2,
                                        max_decode_len=cfg.max_len + 1)

    def test_zero_and_negative_max_new_tokens(self):
        fake = _FakeModel({0: 5})
        pol = serving.DecodePolicy(num_slots=2, max_decode_len=16)
        with serving.GenerativeEngine("budget0", fake, pol) as eng:
            r = eng.generate(_prompt(0), max_new_tokens=0).result(5)
            assert r["outcome"] == "length" and len(r["tokens"]) == 0
            neg = eng.generate(_prompt(0), max_new_tokens=-1)
            with pytest.raises(errors.InvalidArgumentError):
                neg.result(5)

    def test_policy_bucket_mismatch_rejected(self):
        cfg = tr.TransformerConfig.tiny()
        model = tr.TransformerGenerativeModel(
            cfg, 8, num_slots=4, max_decode_len=6,
            decode_bucket_sizes=[4], init_fresh=True, aot_warmup=False)
        try:
            with pytest.raises(ValueError, match="decode plan"):
                serving.GenerativeEngine(
                    "mismatch", model,
                    serving.DecodePolicy(num_slots=4, max_decode_len=6,
                                         bucket_sizes=[1, 4]))
        finally:
            model.close()

    def test_load_generative_failure_closes_factory_model(self):
        fake = _FakeModel({})
        with serving.ModelServer() as server:
            with pytest.raises(ValueError):
                # policy asks for more slots than the model has: the
                # engine ctor raises AFTER the factory built the model
                server.load_generative(
                    lambda: fake, "leaky",
                    policy=serving.DecodePolicy(num_slots=99,
                                                max_decode_len=16))
        assert fake.closed


class TestModelServerGenerative:
    def test_load_generate_unload(self):
        cfg = tr.TransformerConfig.tiny()
        model = tr.TransformerGenerativeModel(
            cfg, 8, num_slots=2, max_decode_len=6, init_fresh=True,
            aot_warmup=False)
        pol = serving.DecodePolicy(num_slots=2, max_decode_len=6,
                                   max_new_tokens=4)
        batch = tr.synthetic_wmt_batch(2, 8, 8,
                                       vocab_size=cfg.vocab_size)
        with serving.ModelServer() as server:
            server.load_generative(model, "gen", policy=pol)
            assert "gen" in server.model_names
            fut = server.generate(batch["src_ids"][0], model="gen")
            r = fut.result(timeout=60)
            assert len(r["tokens"]) == 4
            rows = server.statusz_info()
            assert any(row.get("kind") == "generative" for row in rows)
            with pytest.raises(errors.AlreadyExistsError):
                server.load_generative(model, "gen")
            server.unload("gen")
            assert "gen" not in server.model_names
            with pytest.raises(errors.NotFoundError):
                server.generate(batch["src_ids"][0], model="gen")


# ---------------------------------------------------------------------------
# lint/serving-decode-cache
# ---------------------------------------------------------------------------

class TestDecodeCacheLint:
    RULE = ["lint/serving-decode-cache"]

    def test_clean_decode_graph_passes(self):
        c = kvc.kv_cache("lc1", 2, 4, (2,), stf.float32)
        c.alloc()
        g = c.gather(stf.placeholder(stf.int32, [1], "s"))
        _ = stf.reduce_sum(g)
        assert not analysis.lint_graph(purpose="serving",
                                       rules=self.RULE)

    def test_missing_committed_sharding_is_error(self):
        g = stf.get_default_graph()
        g.create_op(
            "KVCacheAlloc", [],
            attrs={"var_name": "x", "shape": [2, 4],
                   "dtype": "float32", kvc.CACHE_ATTR: True},
            name="bad_alloc",
            output_specs=[(stf.TensorShape([2, 4]), stf.float32)])
        diags = analysis.lint_graph(purpose="serving", rules=self.RULE)
        assert diags and diags[0].severity == "error"
        assert "committed sharding" in diags[0].message

    def test_cache_host_sink_is_error(self):
        c = kvc.kv_cache("lc2", 2, 4, (2,), stf.float32)
        g = c.gather(stf.placeholder(stf.int32, [1], "s2"))
        stf.Print(g, [g], "cache:")
        diags = analysis.lint_graph(purpose="serving", rules=self.RULE)
        assert any("host-sink" in d.message for d in diags)

    def test_fetched_cache_tensor_is_error(self):
        c = kvc.kv_cache("lc3", 2, 4, (2,), stf.float32)
        g = c.gather(stf.placeholder(stf.int32, [1], "s3"))
        diags = analysis.lint_graph(purpose="serving", fetches=[g],
                                    rules=self.RULE)
        assert any("fetched" in d.message for d in diags)

    def test_gated_off_outside_serving_purpose(self):
        g = stf.get_default_graph()
        g.create_op(
            "KVCacheAlloc", [],
            attrs={"var_name": "y", "shape": [2], "dtype": "float32"},
            name="ungated",
            output_specs=[(stf.TensorShape([2]), stf.float32)])
        assert not analysis.lint_graph(rules=self.RULE)
