"""Reader ops: WholeFile/TextLine/TFRecord/FixedLength/Identity readers,
read_file/matching_files, maybe_batch, and the queue-runner-driven
TFRecord training loop (SURVEY §2.8, ref python/ops/io_ops.py)."""

import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.lib import example as example_mod
from simple_tensorflow_tpu.lib.io import tf_record


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _run_queue_runners(sess, coord):
    threads = stf.train.start_queue_runners(sess, coord=coord)
    return threads


class TestFileOps:
    def test_read_file(self, tmp_path):
        p = tmp_path / "a.txt"
        p.write_bytes(b"hello stf")
        out = stf.read_file(str(p))
        with stf.Session() as sess:
            v = sess.run(out)
        assert bytes(v.item() if hasattr(v, "item") else v) == b"hello stf"

    def test_write_file(self, tmp_path):
        p = str(tmp_path / "sub" / "out.txt")
        op = stf.write_file(p, "written")
        with stf.Session() as sess:
            sess.run(op)
        assert open(p).read() == "written"

    def test_matching_files(self, tmp_path):
        for n in ("x1.dat", "x2.dat", "y.dat"):
            (tmp_path / n).write_text("")
        out = stf.matching_files(str(tmp_path / "x*.dat"))
        with stf.Session() as sess:
            v = sess.run(out)
        names = [os.path.basename(str(s)) for s in np.ravel(v)]
        assert names == ["x1.dat", "x2.dat"]


class TestReaders:
    def _file_queue(self, files):
        return stf.train.string_input_producer(
            [str(f) for f in files], shuffle=False, num_epochs=1)

    def test_whole_file_reader(self, tmp_path):
        f1, f2 = tmp_path / "1.bin", tmp_path / "2.bin"
        f1.write_bytes(b"one")
        f2.write_bytes(b"two")
        q = self._file_queue([f1, f2])
        reader = stf.WholeFileReader()
        key, value = reader.read(q)
        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            _run_queue_runners(sess, coord)
            k1, v1 = sess.run([key, value])
            k2, v2 = sess.run([key, value])
            coord.request_stop()
        got = {str(k1): bytes(v1.item()), str(k2): bytes(v2.item())}
        assert got == {str(f1): b"one", str(f2): b"two"}

    def test_text_line_reader_skips_header(self, tmp_path):
        f = tmp_path / "t.csv"
        f.write_text("header\nrow1\nrow2\n")
        q = self._file_queue([f])
        reader = stf.TextLineReader(skip_header_lines=1)
        key, value = reader.read(q)
        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            _run_queue_runners(sess, coord)
            vals = [str(sess.run(value).item()) for _ in range(2)]
            n = int(sess.run(reader.num_records_produced()))
            coord.request_stop()
        assert vals == ["row1", "row2"]
        assert n == 2

    def test_tfrecord_reader_and_reset(self, tmp_path):
        path = tmp_path / "r.tfrecord"
        with tf_record.TFRecordWriter(str(path)) as w:
            for i in range(3):
                w.write(np.int32([i]).tobytes())
        q = self._file_queue([path])
        reader = stf.TFRecordReader()
        key, value = reader.read(q)
        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            _run_queue_runners(sess, coord)
            recs = [int(np.frombuffer(sess.run(value).item(), np.int32)[0])
                    for _ in range(3)]
            assert recs == [0, 1, 2]
            assert int(sess.run(reader.num_work_units_completed())) >= 0
            sess.run(reader.reset())
            assert int(sess.run(reader.num_records_produced())) == 0
            coord.request_stop()

    def test_fixed_length_record_reader(self, tmp_path):
        f = tmp_path / "f.bin"
        f.write_bytes(b"HD" + b"aaaabbbbcccc" + b"FT")
        q = self._file_queue([f])
        reader = stf.FixedLengthRecordReader(record_bytes=4, header_bytes=2,
                                             footer_bytes=2)
        key, value = reader.read(q)
        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            _run_queue_runners(sess, coord)
            vals = [bytes(sess.run(value).item()) for _ in range(3)]
            coord.request_stop()
        assert vals == [b"aaaa", b"bbbb", b"cccc"]

    def test_identity_reader_read_up_to(self, tmp_path):
        q = stf.train.string_input_producer(["a", "b", "c"], shuffle=False,
                                            num_epochs=1)
        reader = stf.IdentityReader()
        keys, values = reader.read_up_to(q, 2)
        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            _run_queue_runners(sess, coord)
            k, v = sess.run([keys, values])
            coord.request_stop()
        assert [str(x) for x in np.ravel(v)] == ["a", "b"]


class TestEndToEndTFRecordTraining:
    def test_queue_runner_tfrecord_training_loop(self, tmp_path):
        """VERDICT #4 done-criterion: queue-runner-driven training loop
        reading TFRecords end-to-end (reader -> parse_example -> model)."""
        rng = np.random.RandomState(0)
        W_true = np.float32([[1.0], [2.0]])
        path = str(tmp_path / "train.tfrecord")
        with tf_record.TFRecordWriter(path) as w:
            for _ in range(64):
                xv = rng.rand(2).astype(np.float32)
                yv = float(xv @ W_true[:, 0])
                ex = example_mod.Example(example_mod.Features({
                    "x": example_mod.Feature(
                        float_list=example_mod.FloatList(xv.tolist())),
                    "y": example_mod.Feature(
                        float_list=example_mod.FloatList([yv])),
                }))
                w.write(ex.SerializeToString())

        fq = stf.train.string_input_producer([path], shuffle=False)
        reader = stf.TFRecordReader()
        _, serialized = reader.read(fq)
        feats = stf.parse_single_example(serialized, {
            "x": stf.FixedLenFeature([2], stf.float32),
            "y": stf.FixedLenFeature([1], stf.float32),
        })
        x, y = feats["x"], feats["y"]

        w_var = stf.Variable(stf.zeros([2, 1]), name="w_e2e")
        pred = stf.matmul(stf.reshape(x, [1, 2]), w_var)
        loss = stf.reduce_mean(stf.square(pred - stf.reshape(y, [1, 1])))
        train_op = stf.train.GradientDescentOptimizer(0.5).minimize(loss)

        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            _run_queue_runners(sess, coord)
            l0 = float(sess.run(loss))
            for _ in range(60):
                sess.run(train_op)
            l1 = float(sess.run(loss))
            w_fit = np.asarray(sess.run(w_var.value()))
            coord.request_stop()
        assert l1 < l0
        assert np.allclose(w_fit, W_true, atol=0.35), w_fit


class TestMaybeBatch:
    def test_maybe_batch_filters(self):
        counter = stf.Variable(stf.constant(0.0), name="mb_count")
        bump = stf.assign_add(counter, stf.constant(1.0))
        with stf.get_default_graph().control_dependencies([bump]):
            item = counter.read_value()
        keep = stf.greater(item, stf.constant(2.0))  # drop 1.0, 2.0
        batched = stf.train.maybe_batch([item], keep, batch_size=2)
        coord = stf.train.Coordinator()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            _run_queue_runners(sess, coord)
            out = np.ravel(sess.run(batched))
            coord.request_stop()
        assert out.tolist() == [3.0, 4.0]


class TestNativeExampleFastParse:
    """C++ batch Example parser (ref core/util/
    example_proto_fast_parsing.cc) must agree exactly with the Python wire
    parser and honor FixedLen defaults/errors."""

    def _examples(self, n=6):
        from simple_tensorflow_tpu.lib.example import make_example

        out = []
        for i in range(n):
            feats = {"x": (np.arange(4, dtype=np.float32) + i).tolist(),
                     "y": [int(i)]}
            if i != 3:  # example 3 lacks 'z' -> default must apply
                feats["z"] = [i * 10, i * 10 + 1]
            out.append(make_example(**feats).SerializeToString())
        return out

    def test_fast_path_matches_python_path(self):
        import simple_tensorflow_tpu.ops.parsing_ops as po
        from simple_tensorflow_tpu.runtime import native

        if not native.available():
            import pytest as _pytest
            _pytest.skip("native runtime not built")
        serialized = self._examples()
        feats = {"x": po.FixedLenFeature([4], stf.float32),
                 "y": po.FixedLenFeature([1], stf.int64),
                 "z": po.FixedLenFeature([2], stf.int64,
                                         default_value=[-7, -7])}
        fast = po._parse_examples_fast(serialized, feats)
        assert fast is not None, "fast path did not engage"
        # force the python path for comparison
        slow = {}
        from simple_tensorflow_tpu.lib import example as example_mod

        batch = [example_mod.Example.FromString(s) for s in serialized]
        for name, spec in feats.items():
            rows = []
            for ex in batch:
                f = ex.features.feature.get(name)
                if f is None:
                    rows.append(np.asarray(spec.default_value))
                elif spec.dtype == stf.float32:
                    rows.append(np.asarray(f.float_list.value, np.float32))
                else:
                    rows.append(np.asarray(f.int64_list.value, np.int64))
            slow[name] = np.stack(rows).reshape([len(batch)] + spec.shape)
        for name in feats:
            np.testing.assert_array_equal(fast[name], slow[name],
                                          err_msg=name)

    def test_fast_path_errors(self):
        import pytest as _pytest

        import simple_tensorflow_tpu.ops.parsing_ops as po
        from simple_tensorflow_tpu.runtime import native

        if not native.available():
            _pytest.skip("native runtime not built")
        serialized = self._examples()
        # missing without default raises with the example index
        with _pytest.raises(ValueError, match="missing"):
            po._parse_examples_fast(
                serialized, {"z": po.FixedLenFeature([2], stf.int64)})
        # wrong size -> InvalidArgumentError (canonical code mapping)
        with _pytest.raises(stf.errors.InvalidArgumentError,
                            match="values|expected"):
            po._parse_examples_fast(
                serialized, {"x": po.FixedLenFeature([3], stf.float32)})
        # declared-kind mismatch reads as MISSING (slow-path semantics):
        # default applies when present, missing-error otherwise
        got = po._parse_examples_fast(
            serialized, {"x": po.FixedLenFeature([4], stf.int64,
                                                 default_value=[0] * 4)})
        np.testing.assert_array_equal(got["x"][0], [0, 0, 0, 0])
        with _pytest.raises(ValueError, match="missing"):
            po._parse_examples_fast(
                serialized, {"x": po.FixedLenFeature([4], stf.int64)})
        # malformed proto
        with _pytest.raises(stf.errors.InvalidArgumentError,
                            match="malformed"):
            po._parse_examples_fast(
                [b"\x0a\xff\xff\xff\xff\xff"],
                {"x": po.FixedLenFeature([4], stf.float32)})
        # bad default length names the feature
        with _pytest.raises(ValueError, match="default_value"):
            po._parse_examples_fast(
                serialized, {"z": po.FixedLenFeature(
                    [2], stf.int64, default_value=[1, 2, 3])})
        # >64 features falls back to the slow path (returns None)
        many = {f"f{i}": po.FixedLenFeature([1], stf.int64,
                                            default_value=[0])
                for i in range(70)}
        assert po._parse_examples_fast(serialized, many) is None
        # string / VarLen specs decline the fast path (None, no crash)
        assert po._parse_examples_fast(
            serialized, {"s": po.FixedLenFeature([1], stf.string)}) is None
        assert po._parse_examples_fast(
            serialized, {"x": po.VarLenFeature(stf.float32)}) is None

    def test_graph_parse_example_uses_it(self):
        # end to end through the graph op (fast path engages silently)
        import simple_tensorflow_tpu.ops.parsing_ops as po

        stf.reset_default_graph()
        serialized = self._examples(4)
        ph = stf.placeholder(stf.string, [None], name="ser")
        parsed = stf.parse_example(
            ph, {"x": po.FixedLenFeature([4], stf.float32),
                 "y": po.FixedLenFeature([1], stf.int64)})
        total = stf.reduce_sum(parsed["x"])
        with stf.Session() as sess:
            xv, tv = sess.run(
                [parsed["x"], total],
                {ph: np.array(serialized, dtype=object)})
        assert xv.shape == (4, 4)
        np.testing.assert_allclose(xv[2], [2., 3., 4., 5.])
