"""Parallel subsystem tests on the 8-device virtual CPU mesh (SURVEY §4):
dp == single-device numerics, tp MLP == dense, fsdp sharding + training,
pipeline == sequential, shard_map collectives."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import parallel


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _train_mlp(mesh=None, setup=None, steps=3, seed=0):
    """Build + train a small MLP; returns per-step losses. ``setup(x, y)``
    applies sharding annotations."""
    rng = np.random.RandomState(seed)
    xs = rng.randn(16, 8).astype(np.float32)
    ys = rng.randn(16, 4).astype(np.float32)

    import contextlib
    ctx = mesh if mesh is not None else contextlib.nullcontext()
    with ctx:
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        y = stf.placeholder(stf.float32, [16, 4], name="y")
        if setup:
            setup(x, y)
        stf.set_random_seed(42)
        w1 = stf.Variable(stf.random_normal([8, 32], stddev=0.1, seed=1),
                          name="w1")
        b1 = stf.Variable(stf.zeros([32]), name="b1")
        w2 = stf.Variable(stf.random_normal([32, 4], stddev=0.1, seed=2),
                          name="w2")
        b2 = stf.Variable(stf.zeros([4]), name="b2")
        h = stf.nn.relu(stf.matmul(x, w1) + b1)
        pred = stf.matmul(h, w2) + b2
        loss = stf.reduce_mean(stf.square(pred - y))
        opt = stf.train.GradientDescentOptimizer(0.1)
        train_op = opt.minimize(loss)

        losses = []
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(steps):
                l, _ = sess.run([loss, train_op], feed_dict={x: xs, y: ys})
                losses.append(float(l))
    return losses


def test_dp_matches_single_device():
    ref = _train_mlp()
    stf.reset_default_graph()
    mesh = parallel.Mesh({"dp": 8})
    dp = _train_mlp(mesh=mesh,
                    setup=lambda x, y: parallel.DataParallel(mesh)
                    .shard_batch([x, y]))
    np.testing.assert_allclose(ref, dp, rtol=1e-5)
    assert dp[-1] < dp[0]


def test_fsdp_matches_and_shards():
    ref = _train_mlp()
    stf.reset_default_graph()
    mesh = parallel.Mesh({"fsdp": 8})
    f = parallel.FSDP(mesh, min_size=1)

    losses = []
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 8).astype(np.float32)
    ys = rng.randn(16, 4).astype(np.float32)
    with mesh, f.scope():
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        y = stf.placeholder(stf.float32, [16, 4], name="y")
        f.shard_batch([x, y])
        stf.set_random_seed(42)
        w1 = stf.Variable(stf.random_normal([8, 32], stddev=0.1, seed=1),
                          name="w1")
        b1 = stf.Variable(stf.zeros([32]), name="b1")
        w2 = stf.Variable(stf.random_normal([32, 4], stddev=0.1, seed=2),
                          name="w2")
        b2 = stf.Variable(stf.zeros([4]), name="b2")
        h = stf.nn.relu(stf.matmul(x, w1) + b1)
        pred = stf.matmul(h, w2) + b2
        loss = stf.reduce_mean(stf.square(pred - y))
        train_op = stf.train.GradientDescentOptimizer(0.1).minimize(loss)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(3):
                l, _ = sess.run([loss, train_op], feed_dict={x: xs, y: ys})
                losses.append(float(l))
            w1_arr = sess._variable_store.values["w1"]
            assert len(w1_arr.sharding.device_set) == 8
    np.testing.assert_allclose(ref, losses, rtol=1e-5)


def test_tp_mlp_matches_dense():
    rng = np.random.RandomState(1)
    xs = rng.randn(4, 16).astype(np.float32)

    mesh = parallel.Mesh({"tp": 8})
    with mesh:
        x = stf.constant(xs)
        h = parallel.column_parallel_dense(
            x, 32, activation=stf.nn.relu, name="up",
            kernel_initializer=stf.constant_initializer(0.02))
        y = parallel.row_parallel_dense(
            h, 8, name="down", kernel_initializer=stf.constant_initializer(0.03))
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            out = sess.run(y)

    h_ref = np.maximum(xs @ np.full((16, 32), 0.02, np.float32), 0)
    y_ref = h_ref @ np.full((32, 8), 0.03, np.float32)
    np.testing.assert_allclose(out, y_ref, rtol=1e-5)


def test_shard_map_collectives():
    mesh = parallel.Mesh({"dp": 8})
    data = np.arange(8, dtype=np.float32).reshape(8, 1)
    with mesh:
        x = stf.constant(data)

        def body(xs):
            s = parallel.all_reduce(xs, "dp")
            idx = parallel.axis_index("dp")
            shifted = parallel.ppermute(
                xs, "dp", [(i, (i + 1) % 8) for i in range(8)])
            return s, shifted + 0.0 * stf.cast(idx, stf.float32)

        s, shifted = parallel.shard_map(
            body, [x], in_specs=[("dp", None)],
            out_specs=[("dp", None), ("dp", None)])
        with stf.Session() as sess:
            s_v, sh_v = sess.run([s, shifted])
    np.testing.assert_allclose(s_v, np.full((8, 1), 28.0))
    np.testing.assert_allclose(sh_v.ravel(),
                               np.roll(np.arange(8, dtype=np.float32), 1))


def test_all_gather_reduce_scatter_shard_map():
    mesh = parallel.Mesh({"dp": 8})
    data = np.arange(16, dtype=np.float32).reshape(16, 1)
    with mesh:
        x = stf.constant(data)

        def body(xs):
            g = parallel.all_gather(xs, "dp")            # (16,1) per device
            return parallel.reduce_scatter(g, "dp")      # back to (2,1), x8

        out = parallel.shard_map(body, [x], in_specs=[("dp", None)],
                                 out_specs=[("dp", None)])
        with stf.Session() as sess:
            val = sess.run(out)
    # reduce_scatter(all_gather(x)) = 8 * x
    np.testing.assert_allclose(val, 8 * data)


def test_pipeline_matches_sequential():
    mesh = parallel.Mesh({"pp": 8})
    rng = np.random.RandomState(3)
    ws = rng.randn(8, 6, 6).astype(np.float32) * 0.3
    xs = rng.randn(16, 6).astype(np.float32)

    with mesh:
        w = stf.constant(ws)
        x = stf.constant(xs)

        def stage(w_s, h):
            return stf.tanh(stf.matmul(h, w_s))

        y = parallel.pipeline(stage, [w], x, n_microbatches=4)
        with stf.Session() as sess:
            out = sess.run(y)

    ref = xs
    for s in range(8):
        ref = np.tanh(ref @ ws[s])
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_pipeline_1f1b_train_matches_sequential():
    """1F1B combined fwd/bwd schedule: loss and grads must equal the
    unpipelined computation (mean of per-microbatch sum losses)."""
    mesh = parallel.Mesh({"pp": 4})
    rng = np.random.RandomState(5)
    S, B, D, n_micro = 4, 8, 6, 4
    ws = rng.randn(S, D, D).astype(np.float32) * 0.3
    xs = rng.randn(B, D).astype(np.float32)
    ts = rng.randn(B, D).astype(np.float32)

    with mesh:
        w = stf.Variable(ws, name="w_1f1b")
        parallel.shard_variable(w, "pp")
        x = stf.constant(xs)
        t = stf.constant(ts)

        def stage(w_s, h):
            return stf.tanh(stf.matmul(h, w_s))

        def loss_fn(y, tgt):
            return stf.reduce_sum(stf.square(y - tgt))

        loss, (gw,) = parallel.pipeline_train(
            stage, loss_fn, [w], x, t, n_microbatches=n_micro)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            l_val, g_val = sess.run([loss, gw])

    import jax
    import jax.numpy as jnp

    def ref(w_all):
        mb = B // n_micro
        total = 0.0
        for m in range(n_micro):
            h = jnp.asarray(xs[m * mb:(m + 1) * mb])
            for s in range(S):
                h = jnp.tanh(h @ w_all[s])
            total = total + jnp.sum((h - ts[m * mb:(m + 1) * mb]) ** 2)
        return total / n_micro

    rl, rg = jax.value_and_grad(ref)(jnp.asarray(ws))
    np.testing.assert_allclose(l_val, float(rl), rtol=1e-4)
    np.testing.assert_allclose(g_val, np.asarray(rg), rtol=1e-3, atol=1e-4)


def test_pipeline_heterogeneous_stages_1f1b():
    """Per-stage DIFFERENT computations (lax.switch path): transformer-ish
    4-stage pipeline — embedding-scale stage, two residual blocks, head —
    trained 1F1B across the virtual mesh (BASELINE config 5 shape)."""
    mesh = parallel.Mesh({"pp": 4})
    rng = np.random.RandomState(6)
    S, B, D, n_micro = 4, 8, 8, 4
    ws = rng.randn(S, D, D).astype(np.float32) * 0.3
    bs = rng.randn(S, D).astype(np.float32) * 0.1
    xs = rng.randn(B, D).astype(np.float32)
    ts = rng.randn(B, D).astype(np.float32)

    def mk_stage(kind):
        def f(w_s, b_s, h):
            if kind == "in":
                return stf.tanh(stf.matmul(h, w_s) + b_s)
            if kind == "block":
                return h + stf.nn.relu(stf.matmul(h, w_s) + b_s)
            return stf.matmul(h, w_s) + b_s  # head
        return f

    kinds = ["in", "block", "block", "head"]
    with mesh:
        w = stf.constant(ws)
        b = stf.constant(bs)
        x = stf.constant(xs)
        t = stf.constant(ts)

        def loss_fn(y, tgt):
            return stf.reduce_sum(stf.square(y - tgt))

        loss, (gw, gb) = parallel.pipeline_train(
            [mk_stage(k) for k in kinds], loss_fn, [w, b], x, t,
            n_microbatches=n_micro)
        with stf.Session() as sess:
            l_val, gw_val, gb_val = sess.run([loss, gw, gb])

    import jax
    import jax.numpy as jnp

    def apply(kind, w_s, b_s, h):
        if kind == "in":
            return jnp.tanh(h @ w_s + b_s)
        if kind == "block":
            return h + jax.nn.relu(h @ w_s + b_s)
        return h @ w_s + b_s

    def ref(w_all, b_all):
        mb = B // n_micro
        total = 0.0
        for m in range(n_micro):
            h = jnp.asarray(xs[m * mb:(m + 1) * mb])
            for s in range(S):
                h = apply(kinds[s], w_all[s], b_all[s], h)
            total = total + jnp.sum((h - ts[m * mb:(m + 1) * mb]) ** 2)
        return total / n_micro

    rl, (rgw, rgb) = jax.value_and_grad(ref, argnums=(0, 1))(
        jnp.asarray(ws), jnp.asarray(bs))
    np.testing.assert_allclose(l_val, float(rl), rtol=1e-4)
    np.testing.assert_allclose(gw_val, np.asarray(rgw), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(gb_val, np.asarray(rgb), rtol=1e-3, atol=1e-4)


def test_pipeline_gradients():
    mesh = parallel.Mesh({"pp": 8})
    rng = np.random.RandomState(4)
    ws = rng.randn(8, 4, 4).astype(np.float32) * 0.3
    xs = rng.randn(8, 4).astype(np.float32)

    with mesh:
        w = stf.Variable(ws, name="stacked_w")
        parallel.shard_variable(w, "pp")
        x = stf.constant(xs)

        def stage(w_s, h):
            return stf.tanh(stf.matmul(h, w_s))

        y = parallel.pipeline(stage, [w], x, n_microbatches=2)
        loss = stf.reduce_sum(y * y)
        (gw,) = stf.gradients(loss, [w])
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            g_val, l_val = sess.run([gw, loss])

    # numeric check against pure-numpy finite differences on one element
    def loss_np(w_all):
        h = xs
        for s in range(8):
            h = np.tanh(h @ w_all[s])
        return np.sum(h * h)

    eps = 1e-3
    wp = ws.copy(); wp[3, 1, 2] += eps
    wm = ws.copy(); wm[3, 1, 2] -= eps
    num = (loss_np(wp) - loss_np(wm)) / (2 * eps)
    np.testing.assert_allclose(g_val[3, 1, 2], num, rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(l_val, loss_np(ws), rtol=1e-4)


def test_watchdog_and_heartbeat():
    from simple_tensorflow_tpu.parallel import failure_detection as fd

    wd = fd.StepWatchdog(deadline_secs=0.05, poll_secs=0.01).start()
    import time

    time.sleep(0.2)
    with pytest.raises(stf.errors.DeadlineExceededError):
        wd.step_done()
    wd.stop()

    hb = fd.Heartbeat(interval_secs=0.01).start()
    time.sleep(0.05)
    hb.check(hb.last_beat, max_age_secs=5.0)
    with pytest.raises(stf.errors.UnavailableError):
        hb.check(time.monotonic() - 100.0, max_age_secs=5.0)
    hb.stop()


def test_make_callable_fast_path_applies_declared_shardings():
    """Regression: the make_callable hot path must apply declared variable
    shardings after committing state, like Session.run does — a callable
    warmed before a sharding declaration must still place the variable on
    the mesh from the fast path."""
    mesh = parallel.Mesh({"dp": 8})
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 8).astype(np.float32)
    with mesh:
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        w = stf.Variable(stf.random_normal([8, 4], stddev=0.1, seed=1),
                         name="wcb")
        loss = stf.reduce_mean(stf.square(stf.matmul(x, w)))
        train_op = stf.train.GradientDescentOptimizer(0.1).minimize(loss)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            step = sess.make_callable([loss, train_op], feed_list=[x])
            step(xs)  # slow warmup call adopts the cached plan
            # declare the sharding AFTER warmup: only the fast path runs
            # from here on, so it must be the one to apply it
            w.set_sharding(("dp", None))
            l1, _ = step(xs)
            l2, _ = step(xs)
            assert np.isfinite(l1) and l2 < l1
            arr = sess._variable_store.values["wcb"]
            assert len(arr.sharding.device_set) == 8
