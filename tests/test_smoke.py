import numpy as np


def test_import():
    import simple_tensorflow_tpu as stf

    assert stf.float32.name == "float32"


def test_constant_session():
    import simple_tensorflow_tpu as stf

    stf.reset_default_graph()
    a = stf.constant(2.0)
    b = stf.constant(3.0)
    c = a * b
    with stf.Session() as sess:
        assert float(sess.run(c)) == 6.0
