"""Model zoo smoke tests (tiny configs; mirrors ref model tutorials)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def test_mnist_softmax_trains():
    from simple_tensorflow_tpu.models import mnist

    m = mnist.softmax_model(learning_rate=0.01)
    rng = np.random.RandomState(0)
    images = rng.rand(256, 784).astype(np.float32)
    w_true = rng.randn(784, 10).astype(np.float32)
    labels = np.argmax(images @ w_true, axis=1)
    onehot = np.zeros((256, 10), np.float32)
    onehot[np.arange(256), labels] = 1.0
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        first = None
        for _ in range(50):
            _, l = sess.run([m["train_op"], m["loss"]],
                            feed_dict={m["x"]: images, m["y_"]: onehot})
            if first is None:
                first = l
        assert l < first * 0.7


def test_mnist_convnet_trains():
    from simple_tensorflow_tpu.models import mnist

    m = mnist.convnet_model(batch_size=16)
    rng = np.random.RandomState(0)
    images = rng.rand(16, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, 16).astype(np.int32)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        losses = []
        for _ in range(10):
            _, l = sess.run([m["train_op"], m["loss"]],
                            feed_dict={m["x"]: images, m["y_"]: labels,
                                       m["keep_prob"]: 0.9})
            losses.append(float(l))
        assert losses[-1] < losses[0]
        assert int(np.asarray(sess.run(m["global_step"]))) == 10


def test_resnet_tiny_forward_and_step():
    from simple_tensorflow_tpu.models import resnet

    # batch 4 / 64px keeps late-stage BN statistics sane (batch 2 at 1x1
    # spatial degenerates BN variance and legitimately explodes gradients)
    m = resnet.resnet50_train_model(batch_size=4, image_size=64,
                                    num_classes=10, dtype=stf.float32,
                                    learning_rate=1e-2)
    images, labels = resnet.synthetic_imagenet(4, 64)
    labels = labels % 10
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        _, l1 = sess.run([m["train_op"], m["loss"]],
                         feed_dict={m["images"]: images,
                                    m["labels"]: labels})
        _, l2 = sess.run([m["train_op"], m["loss"]],
                         feed_dict={m["images"]: images,
                                    m["labels"]: labels})
        assert np.isfinite(l1) and np.isfinite(l2)
        assert l2 < l1 * 10  # sanity: not exploding
