"""Model zoo smoke tests (tiny configs; mirrors ref model tutorials)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def test_mnist_softmax_trains():
    from simple_tensorflow_tpu.models import mnist

    m = mnist.softmax_model(learning_rate=0.01)
    rng = np.random.RandomState(0)
    images = rng.rand(256, 784).astype(np.float32)
    w_true = rng.randn(784, 10).astype(np.float32)
    labels = np.argmax(images @ w_true, axis=1)
    onehot = np.zeros((256, 10), np.float32)
    onehot[np.arange(256), labels] = 1.0
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        first = None
        for _ in range(50):
            _, l = sess.run([m["train_op"], m["loss"]],
                            feed_dict={m["x"]: images, m["y_"]: onehot})
            if first is None:
                first = l
        assert l < first * 0.7


def test_mnist_convnet_trains():
    from simple_tensorflow_tpu.models import mnist

    m = mnist.convnet_model(batch_size=16)
    rng = np.random.RandomState(0)
    images = rng.rand(16, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, 16).astype(np.int32)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        losses = []
        for _ in range(10):
            _, l = sess.run([m["train_op"], m["loss"]],
                            feed_dict={m["x"]: images, m["y_"]: labels,
                                       m["keep_prob"]: 0.9})
            losses.append(float(l))
        assert losses[-1] < losses[0]
        assert int(np.asarray(sess.run(m["global_step"]))) == 10


def test_resnet_tiny_forward_and_step():
    from simple_tensorflow_tpu.models import resnet

    # batch 4 / 64px keeps late-stage BN statistics sane (batch 2 at 1x1
    # spatial degenerates BN variance and legitimately explodes gradients)
    m = resnet.resnet50_train_model(batch_size=4, image_size=64,
                                    num_classes=10, dtype=stf.float32,
                                    learning_rate=1e-2)
    images, labels = resnet.synthetic_imagenet(4, 64)
    labels = labels % 10
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        _, l1 = sess.run([m["train_op"], m["loss"]],
                         feed_dict={m["images"]: images,
                                    m["labels"]: labels})
        _, l2 = sess.run([m["train_op"], m["loss"]],
                         feed_dict={m["images"]: images,
                                    m["labels"]: labels})
        assert np.isfinite(l1) and np.isfinite(l2)
        assert l2 < l1 * 10  # sanity: not exploding


def test_bert_tiny_trains():
    from simple_tensorflow_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    m = bert.bert_pretrain_model(batch_size=4, seq_len=16, max_predictions=4,
                                 cfg=cfg, compute_dtype=stf.float32,
                                 learning_rate=1e-3)
    batch = bert.synthetic_pretrain_batch(4, 16, 4, vocab_size=cfg.vocab_size)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        feed = {m[k]: v for k, v in batch.items()}
        l0 = sess.run(m["loss"], feed)
        for _ in range(10):
            _, l = sess.run([m["train_op"], m["loss"]], feed)
        assert np.isfinite(l) and l < l0


def test_bert_with_input_mask():
    from simple_tensorflow_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    m = bert.bert_pretrain_model(batch_size=2, seq_len=16, max_predictions=4,
                                 cfg=cfg, compute_dtype=stf.float32,
                                 use_input_mask=True)
    batch = bert.synthetic_pretrain_batch(2, 16, 4, vocab_size=cfg.vocab_size)
    batch["input_mask"] = np.concatenate(
        [np.ones((2, 12), np.int32), np.zeros((2, 4), np.int32)], axis=1)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        l = sess.run(m["loss"], {m[k]: v for k, v in batch.items()})
        assert np.isfinite(l)


def test_bert_pretrain_config_lowers_to_flash_attention():
    """The HEADLINE config — padded batches AND attention dropout — must
    run the Pallas flash kernel, not an XLA fallback (VERDICT r2 weak #2)."""
    from simple_tensorflow_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    cfg.attention_dropout = 0.1  # the pretraining setting
    cfg.hidden_dropout = 0.1
    m = bert.bert_pretrain_model(batch_size=2, seq_len=16, max_predictions=4,
                                 cfg=cfg, compute_dtype=stf.float32,
                                 use_input_mask=True)
    g = stf.get_default_graph()
    flash_ops = [op for op in g.get_operations()
                 if op.type in ("FlashAttention", "FlashAttentionDropout")]
    assert len(flash_ops) == cfg.num_layers, [op.type for op in flash_ops]
    # training graph with dropout -> the stateful dropout variant, with the
    # padding bias wired as a 4th input
    assert all(op.type == "FlashAttentionDropout" for op in flash_ops)
    assert all(len(op.inputs) == 4 for op in flash_ops)
    # and the whole thing trains
    batch = bert.synthetic_pretrain_batch(2, 16, 4, vocab_size=cfg.vocab_size)
    batch["input_mask"] = np.concatenate(
        [np.ones((2, 12), np.int32), np.zeros((2, 4), np.int32)], axis=1)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        feed = {m[k]: v for k, v in batch.items()}
        l0 = sess.run(m["loss"], feed)
        for _ in range(5):
            _, l = sess.run([m["train_op"], m["loss"]], feed)
        assert np.isfinite(l)
        # dropout masks must differ between runs (stateful RNG stream):
        # two loss evals in different runs may differ, but training should
        # still make progress on average
        assert l < l0 * 1.5


def test_transformer_tiny_trains():
    from simple_tensorflow_tpu.models import transformer as tr

    cfg = tr.TransformerConfig.tiny()
    m = tr.transformer_train_model(batch_size=4, src_len=8, tgt_len=8,
                                   cfg=cfg, compute_dtype=stf.float32)
    batch = tr.synthetic_wmt_batch(4, 8, 8, vocab_size=cfg.vocab_size)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        feed = {m[k]: v for k, v in batch.items() if k in m}
        l0 = sess.run(m["loss"], feed)
        for _ in range(15):
            _, l = sess.run([m["train_op"], m["loss"]], feed)
        assert np.isfinite(l) and l < l0


def test_transformer_beam_search():
    from simple_tensorflow_tpu.models import transformer as tr

    cfg = tr.TransformerConfig.tiny()
    src = stf.placeholder(stf.int32, [2, 8], "src")
    # default bf16 compute dtype: the decode logits are bf16 and the beam
    # scoring must cast up itself (regression: f32 one_hot * bf16 logits
    # was a strict-dtype TypeError)
    ids, scores = tr.beam_search_decode(src, cfg, beam_size=3, decode_len=8,
                                        compute_dtype=stf.bfloat16)
    batch = tr.synthetic_wmt_batch(2, 8, 8, vocab_size=cfg.vocab_size)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        out_ids, out_scores = sess.run([ids, scores],
                                       {src: batch["src_ids"]})
    assert out_ids.shape == (2, 3, 8)
    assert out_scores.shape == (2, 3)
    assert (out_ids[:, :, 0] == cfg.eos_id).all()
    # beams sorted by score
    assert (np.diff(out_scores, axis=1) <= 1e-5).all()


def test_word2vec_trains():
    from simple_tensorflow_tpu.models import word2vec as w2v

    m = w2v.skipgram_model(vocab_size=100, embedding_size=16, batch_size=8,
                           num_sampled=4, learning_rate=0.5)
    xi, yi = w2v.synthetic_skipgram_batch(8, 100)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        feed = {m["train_inputs"]: xi, m["train_labels"]: yi}
        l0 = sess.run(m["loss"], feed)
        for _ in range(20):
            _, l = sess.run([m["train_op"], m["loss"]], feed)
        assert l < l0
        sim = w2v.similarity(m["normalized_embeddings"], [1, 2, 3])
        assert sess.run(sim).shape == (3, 100)


def test_rnn_seq2seq_trains_and_decodes():
    from simple_tensorflow_tpu.models import rnn_seq2seq as s2s

    cfg = s2s.Seq2SeqConfig.tiny()
    m = s2s.seq2seq_model(8, cfg)
    src, lens, ti, to = s2s.synthetic_copy_batch(8, cfg, seed=1)
    feed = {m["src"]: src, m["src_len"]: lens, m["tgt_in"]: ti,
            m["tgt_out"]: to}
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        l0 = float(np.asarray(sess.run(m["loss"], feed)))
        for _ in range(60):
            sess.run(m["train_op"], feed)
        l1 = float(np.asarray(sess.run(m["loss"], feed)))
        assert l1 < l0 * 0.5, (l0, l1)
        dec = np.asarray(sess.run(m["decoded"], feed))
    assert dec.shape == (8, cfg.tgt_len)
    # the copy task is learnable to high accuracy even in 60 steps
    msk = to > 0
    assert (dec[msk] == to[msk]).mean() > 0.5


def test_long_context_lm_on_sp_mesh():
    from simple_tensorflow_tpu import parallel
    from simple_tensorflow_tpu.models import long_context as lc

    cfg = lc.LongContextConfig.tiny()
    with parallel.Mesh({"dp": 2, "sp": 4}):
        m = lc.lm_train_model(batch_size=2, seq_len=32, cfg=cfg,
                              compute_dtype=stf.float32)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            feed_ids, feed_tg = lc.synthetic_lm_batch(2, 32, cfg.vocab_size)
            feed = {m["input_ids"]: feed_ids, m["targets"]: feed_tg}
            l0 = sess.run(m["loss"], feed)
            for _ in range(5):
                _, l = sess.run([m["train_op"], m["loss"]], feed)
            assert np.isfinite(l) and l < l0


def test_long_context_single_device_fallback():
    from simple_tensorflow_tpu.models import long_context as lc

    cfg = lc.LongContextConfig.tiny()
    m = lc.lm_train_model(batch_size=1, seq_len=16, cfg=cfg,
                          compute_dtype=stf.float32)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        ids, tg = lc.synthetic_lm_batch(1, 16, cfg.vocab_size)
        l = sess.run(m["loss"], {m["input_ids"]: ids, m["targets"]: tg})
        assert np.isfinite(l)


def test_transformer_bf16_train_step():
    """Backward-pass coverage for the mixed-precision embedding lookup and
    the bf16 tied-logits head (regression: custom_vjp residuals held
    non-JAX types and crashed gradient tracing).

    Deflaked (ISSUE 4 satellite): the default noam schedule
    (warmup_steps=4000) leaves the first few steps with a learning rate
    below bf16 update resolution, so 4 steps sometimes wobbled UP.
    Pinning the seed and shortening warmup makes the 8-step decrease
    large (>1.0 nats across seeds, measured) and deterministic."""
    from simple_tensorflow_tpu.models import transformer as tr

    stf.reset_default_graph()
    stf.set_random_seed(0)
    cfg = tr.TransformerConfig.tiny()
    m = tr.transformer_train_model(batch_size=2, src_len=8, tgt_len=8,
                                   cfg=cfg, compute_dtype=stf.bfloat16,
                                   warmup_steps=8)
    batch = tr.synthetic_wmt_batch(2, 8, 8, vocab_size=cfg.vocab_size)
    feed = {m[k]: v for k, v in batch.items()}
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        l0 = sess.run(m["loss"], feed)
        for _ in range(8):
            sess.run(m["train_op"], feed)
        l1 = sess.run(m["loss"], feed)
    assert np.isfinite(l0) and np.isfinite(l1), (l0, l1)
    assert l1 < l0 - 0.5, (l0, l1)


def test_bert_recompute_trains():
    """recompute=True (per-layer jax.checkpoint) trains end-to-end with
    the full pretraining config (dropout inside the checkpointed blocks —
    the RNG prefetch must keep fwd/remat streams identical). Exact
    gradient parity on SHARED weights is covered by
    test_framework_extras.TestRecomputeGrad; cross-graph loss equality is
    not testable (initializer seeds derive from op counters, which the
    extra remat call ops shift)."""
    from simple_tensorflow_tpu.models import bert

    stf.reset_default_graph()
    cfg = bert.BertConfig.tiny()
    cfg.attention_dropout = 0.1
    cfg.hidden_dropout = 0.1
    m = bert.bert_pretrain_model(batch_size=2, seq_len=16,
                                 max_predictions=4, cfg=cfg,
                                 compute_dtype=stf.float32,
                                 learning_rate=1e-3, use_input_mask=True,
                                 recompute=True)
    batch = bert.synthetic_pretrain_batch(2, 16, 4,
                                          vocab_size=cfg.vocab_size)
    batch["input_mask"] = np.ones((2, 16), np.int32)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        feed = {m[k]: v for k, v in batch.items()}
        l0 = float(np.asarray(sess.run(m["loss"], feed)))
        for _ in range(8):
            sess.run(m["train_op"], feed)
        l1 = float(np.asarray(sess.run(m["loss"], feed)))
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0, (l0, l1)


def test_transformer_recompute_trains():
    from simple_tensorflow_tpu.models import transformer as tr

    stf.reset_default_graph()
    cfg = tr.TransformerConfig.tiny()
    m = tr.transformer_train_model(batch_size=2, src_len=8, tgt_len=8,
                                   cfg=cfg, compute_dtype=stf.bfloat16,
                                   recompute=True)
    batch = tr.synthetic_wmt_batch(2, 8, 8, vocab_size=cfg.vocab_size)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        feed = {m[k]: v for k, v in batch.items() if k in m}
        l0 = sess.run(m["loss"], feed)
        for _ in range(8):
            sess.run(m["train_op"], feed)
        l1 = sess.run(m["loss"], feed)
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0, (l0, l1)


def test_long_context_recompute_on_sp_mesh():
    """Remat composes with ring attention: jax.checkpoint replays the
    shard_map/ppermute body in the backward on the sp mesh."""
    from simple_tensorflow_tpu import parallel
    from simple_tensorflow_tpu.models import long_context as lc

    stf.reset_default_graph()
    cfg = lc.LongContextConfig.tiny()
    mesh = parallel.Mesh({"sp": 8})
    with mesh:
        m = lc.lm_train_model(batch_size=2, seq_len=128, cfg=cfg,
                              compute_dtype=stf.bfloat16, recompute=True)
        ids, tg = lc.synthetic_lm_batch(2, 128, vocab_size=cfg.vocab_size)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            feed = {m["input_ids"]: ids, m["targets"]: tg}
            l0 = sess.run(m["loss"], feed)
            for _ in range(3):
                sess.run(m["train_op"], feed)
            l1 = sess.run(m["loss"], feed)
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0, (l0, l1)


def test_resnet_recompute_matches_baseline_losses():
    """recompute=True (per-block remat) must change bytes, not math: with
    IDENTICAL weights loaded, the training-step losses match the
    non-remat graph."""
    from simple_tensorflow_tpu.models import resnet

    images, labels = resnet.synthetic_imagenet(4, 64)
    labels = labels % 10
    losses = {}
    saved_vars = None
    for rc in (False, True):
        stf.reset_default_graph()
        stf.set_random_seed(7)
        m = resnet.resnet50_train_model(batch_size=4, image_size=64,
                                        num_classes=10, dtype=stf.float32,
                                        learning_rate=1e-2, recompute=rc)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            if saved_vars is None:
                saved_vars = {v.var_name: np.asarray(
                    sess.variable_value(v))
                    for v in stf.global_variables()}
            else:
                for v in stf.global_variables():
                    v.load(saved_vars[v.var_name], session=sess)
            _, l1 = sess.run([m["train_op"], m["loss"]],
                             feed_dict={m["images"]: images,
                                        m["labels"]: labels})
            l2 = sess.run(m["loss"], feed_dict={m["images"]: images,
                                                m["labels"]: labels})
        losses[rc] = (float(l1), float(l2))
        assert np.isfinite(l1) and np.isfinite(l2)
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=2e-4, atol=2e-4)


def test_ptb_lstm_trains_with_state_carry():
    """PTB LSTM LM (TF-1.0 tutorial family): stacked LSTM via one
    lax.scan, truncated BPTT carrying state across session.run calls,
    global-norm clipping, assignable lr."""
    from simple_tensorflow_tpu.models import ptb_lstm

    stf.reset_default_graph()
    stf.set_random_seed(3)
    cfg = ptb_lstm.PTBConfig.tiny()
    B = 8
    m = ptb_lstm.ptb_lm_model(B, cfg, training=True)
    x, y = ptb_lstm.synthetic_ptb_batch(B, cfg.seq_len, cfg.vocab_size)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        state = ptb_lstm.zero_state(B, cfg)
        feed0 = {m["input_ids"]: x, m["target_ids"]: y,
                 **ptb_lstm.state_feed(m, state)}
        l0 = sess.run(m["loss"], feed0)
        losses = []
        for step in range(120):
            feed = {m["input_ids"]: x, m["target_ids"]: y,
                    **ptb_lstm.state_feed(m, state)}
            fetched = sess.run(
                [m["train_op"], m["loss"]] + [t for st in m["state_out"]
                                              for t in (st.c, st.h)], feed)
            losses.append(fetched[1])
            flat = fetched[2:]
            state = [(flat[2 * i], flat[2 * i + 1])
                     for i in range(cfg.layers)]
        # state actually carries (non-zero after a step)
        assert np.abs(state[0][1]).max() > 0
        assert losses[-1] < l0 * 0.8, (l0, losses[-1])
        # lr assignment (epoch decay idiom)
        sess.run(m["lr_update"], {m["new_lr"]: 0.25})
        assert sess.run(m["lr"].value()) == 0.25


def test_ptb_lstm_eval_mode_no_dropout_deterministic():
    from simple_tensorflow_tpu.models import ptb_lstm

    stf.reset_default_graph()
    cfg = ptb_lstm.PTBConfig.tiny()
    m = ptb_lstm.ptb_lm_model(4, cfg, training=False)
    x, y = ptb_lstm.synthetic_ptb_batch(4, cfg.seq_len, cfg.vocab_size)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        state = ptb_lstm.zero_state(4, cfg)
        feed = {m["input_ids"]: x, m["target_ids"]: y,
                **ptb_lstm.state_feed(m, state)}
        a = sess.run(m["loss"], feed)
        b = sess.run(m["loss"], feed)
    assert a == b  # no dropout in eval: bit-deterministic


def test_conv0_space_to_depth_equivalence_and_training():
    """The S2D stem is an exact reformulation: an 8x8/s2 VALID conv on
    the image equals a 4x4/s1 VALID conv on space_to_depth(image, 2)
    with re-laid-out weights (channel order (dy*2+dx)*C + c). Also: the
    full model trains with conv0_space_to_depth=True."""
    rng = np.random.RandomState(0)
    img = rng.randn(2, 16, 16, 3).astype(np.float32)
    w8 = rng.randn(8, 8, 3, 5).astype(np.float32)
    # re-layout: w4[py, px, (dy*2+dx)*3 + c, o] = w8[2py+dy, 2px+dx, c, o]
    w4 = np.zeros((4, 4, 12, 5), np.float32)
    for py in range(4):
        for px in range(4):
            for dy in range(2):
                for dx in range(2):
                    w4[py, px, (dy * 2 + dx) * 3:(dy * 2 + dx) * 3 + 3] = \
                        w8[2 * py + dy, 2 * px + dx]
    stf.reset_default_graph()
    x = stf.constant(img)
    ref = stf.nn.conv2d(x, stf.constant(w8), [1, 2, 2, 1], "VALID")
    s2d = stf.space_to_depth(x, 2)
    alt = stf.nn.conv2d(s2d, stf.constant(w4), [1, 1, 1, 1], "VALID")
    with stf.Session() as sess:
        rv, av = sess.run([ref, alt])
    np.testing.assert_allclose(rv, av, rtol=1e-4, atol=1e-4)

    # model trains with the S2D stem
    from simple_tensorflow_tpu.models import resnet

    stf.reset_default_graph()
    m = resnet.resnet50_train_model(batch_size=4, image_size=64,
                                    num_classes=10, dtype=stf.float32,
                                    learning_rate=1e-2,
                                    conv0_space_to_depth=True)
    images, labels = resnet.synthetic_imagenet(4, 64)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        _, l1 = sess.run([m["train_op"], m["loss"]],
                         feed_dict={m["images"]: images,
                                    m["labels"]: labels % 10})
    assert np.isfinite(l1)


def test_dlrm_trains():
    from simple_tensorflow_tpu.models import dlrm

    m = dlrm.dlrm_model(batch_size=16, num_dense=4,
                        table_sizes=(200, 100), embedding_dim=8,
                        max_ids_per_feature=6, bottom_mlp=(16, 8),
                        top_mlp=(16, 1), learning_rate=0.2)
    batch = dlrm.synthetic_dlrm_batch(16, num_dense=4,
                                      table_sizes=(200, 100),
                                      max_ids_per_feature=6, seed=3)
    feed = dlrm.feed_dict_for(m, batch)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        l0 = float(np.asarray(sess.run(m["loss"], feed)))
        for _ in range(30):
            sess.run(m["train_op"], feed)
        l1 = float(np.asarray(sess.run(m["loss"], feed)))
    assert np.isfinite(l1) and l1 < l0 * 0.9, (l0, l1)
    # prediction head stays a probability
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        p = sess.run(m["prediction"], feed)
    assert p.shape == (16, 1) and (p >= 0).all() and (p <= 1).all()


def test_dlrm_trains_on_ep_mesh():
    """Same graph, ep=8 mesh: the fused vocab-sharded lookup path."""
    from simple_tensorflow_tpu import parallel
    from simple_tensorflow_tpu.models import dlrm

    with parallel.Mesh({"ep": 8}):
        m = dlrm.dlrm_model(batch_size=16, num_dense=4,
                            table_sizes=(512, 256), embedding_dim=8,
                            max_ids_per_feature=6, bottom_mlp=(16, 8),
                            top_mlp=(16, 1), learning_rate=0.2)
        batch = dlrm.synthetic_dlrm_batch(16, num_dense=4,
                                          table_sizes=(512, 256),
                                          max_ids_per_feature=6, seed=5)
        feed = dlrm.feed_dict_for(m, batch)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            l0 = float(np.asarray(sess.run(m["loss"], feed)))
            for _ in range(20):
                sess.run(m["train_op"], feed)
            l1 = float(np.asarray(sess.run(m["loss"], feed)))
        assert np.isfinite(l1) and l1 < l0, (l0, l1)
