"""Quantization op family (ref: core/ops/array_ops.cc:4490 QuantizeV2,
:4892 FakeQuantWithMinMax*, kernels core/kernels/fake_quant_ops.cc).
Covers quantize/dequantize round trips, fake-quant grid values, QAT
gradients (straight-through + trainable range), and the int8 serving
path through the Pallas quantized_matmul."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


class TestQuantizeDequantize:
    def test_quint8_min_combined_round_trip(self):
        stf.reset_default_graph()
        x = np.array([0.0, 1.0, 3.0, 6.0], np.float32)
        q, mn, mx = stf.quantize_v2(stf.constant(x), 0.0, 6.0, stf.quint8)
        deq = stf.dequantize(q, mn, mx)
        with stf.Session() as sess:
            qv, dv = sess.run([q, deq])
        assert qv.dtype == np.uint8
        np.testing.assert_array_equal(qv, [0, 42, 128, 255])  # x*255/6
        np.testing.assert_allclose(dv, x, atol=6.0 / 255 + 1e-6)

    def test_qint8_centered(self):
        stf.reset_default_graph()
        q, mn, mx = stf.quantize_v2(
            stf.constant(np.array([0.0, 6.0], np.float32)), 0.0, 6.0,
            stf.qint8)
        with stf.Session() as sess:
            qv = sess.run(q)
        assert qv.dtype == np.int8
        np.testing.assert_array_equal(qv, [-128, 127])

    def test_min_first_round_trip(self):
        stf.reset_default_graph()
        x = np.linspace(-1.0, 1.0, 9).astype(np.float32)
        q, mn, mx = stf.quantize_v2(stf.constant(x), -1.0, 1.0,
                                    stf.quint8, mode="MIN_FIRST")
        deq = stf.dequantize(q, mn, mx, mode="MIN_FIRST")
        with stf.Session() as sess:
            dv = sess.run(deq)
        np.testing.assert_allclose(dv, x, atol=2.0 / 255 + 1e-6)

    def test_degenerate_range_no_nan(self):
        stf.reset_default_graph()
        q, _, _ = stf.quantize_v2(
            stf.constant(np.array([0.5], np.float32)), 0.5, 0.5)
        with stf.Session() as sess:
            assert np.isfinite(sess.run(q)).all()


class TestFakeQuant:
    def test_args_snaps_to_grid(self):
        stf.reset_default_graph()
        x = stf.constant(np.array([-0.1, 0.0, 0.33, 5.9, 7.0], np.float32))
        y = stf.fake_quant_with_min_max_args(x, min=0.0, max=6.0)
        with stf.Session() as sess:
            yv = sess.run(y)
        step = 6.0 / 255
        # clamped to [0, 6], then snapped to the 255-step grid
        assert yv[0] == 0.0 and yv[-1] == pytest.approx(6.0)
        np.testing.assert_allclose(yv[2] / step, round(0.33 / step),
                                   atol=1e-4)

    def test_args_gradient_gated_to_range(self):
        stf.reset_default_graph()
        x = stf.constant(np.array([-1.0, 3.0, 7.0], np.float32))
        y = stf.fake_quant_with_min_max_args(x, min=0.0, max=6.0)
        (gx,) = stf.gradients(stf.reduce_sum(y), [x])
        with stf.Session() as sess:
            gv = sess.run(gx)
        np.testing.assert_allclose(gv, [0.0, 1.0, 0.0])

    def test_vars_gradients_route_to_min_max(self):
        stf.reset_default_graph()
        x = stf.constant(np.array([-2.0, 1.0, 9.0, 10.0], np.float32))
        mn = stf.Variable(np.float32(0.0))
        mx = stf.Variable(np.float32(8.0))
        y = stf.fake_quant_with_min_max_vars(x, mn, mx)
        gx, gmn, gmx = stf.gradients(stf.reduce_sum(y), [x, mn, mx])
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            gxv, gmnv, gmxv = sess.run([gx, gmn, gmx])
        np.testing.assert_allclose(gxv, [0.0, 1.0, 0.0, 0.0])
        assert gmnv == 1.0   # one element below range
        assert gmxv == 2.0   # two elements above range

    def test_explicit_gradient_functions_match_autodiff(self):
        stf.reset_default_graph()
        xv = np.array([-1.0, 2.0, 7.0], np.float32)
        gv = np.array([1.0, 1.0, 1.0], np.float32)
        x = stf.constant(xv)
        g = stf.constant(gv)
        bp = stf.fake_quant_with_min_max_args_gradient(g, x, min=0.0,
                                                       max=6.0)
        bx, bmn, bmx = stf.fake_quant_with_min_max_vars_gradient(
            g, x, stf.constant(np.float32(0.0)),
            stf.constant(np.float32(6.0)))
        with stf.Session() as sess:
            bpv, bxv, bmnv, bmxv = sess.run([bp, bx, bmn, bmx])
        np.testing.assert_allclose(bpv, [0.0, 1.0, 0.0])
        np.testing.assert_allclose(bxv, [0.0, 1.0, 0.0])
        assert bmnv == 1.0 and bmxv == 1.0

    def test_per_channel(self):
        stf.reset_default_graph()
        x = stf.constant(np.array([[1.0, 50.0], [3.0, -50.0]], np.float32))
        mn = stf.constant(np.array([0.0, -40.0], np.float32))
        mx = stf.constant(np.array([4.0, 40.0], np.float32))
        y = stf.fake_quant_with_min_max_vars_per_channel(x, mn, mx)
        gx, gmn, gmx = stf.gradients(stf.reduce_sum(y),
                                     [x, mn, mx])
        with stf.Session() as sess:
            yv, gxv, gmnv, gmxv = sess.run([y, gx, gmn, gmx])
        assert yv[0, 1] == pytest.approx(40.0, abs=0.2)   # clamped ch 1
        assert yv[1, 1] == pytest.approx(-40.0, abs=0.2)
        np.testing.assert_allclose(gxv, [[1., 0.], [1., 0.]])
        np.testing.assert_allclose(gmnv, [0., 1.])
        np.testing.assert_allclose(gmxv, [0., 1.])

    def test_narrow_range_and_num_bits(self):
        stf.reset_default_graph()
        x = stf.constant(np.linspace(0, 1, 7).astype(np.float32))
        y4 = stf.fake_quant_with_min_max_args(x, min=0.0, max=1.0,
                                              num_bits=4)
        with stf.Session() as sess:
            yv = sess.run(y4)
        # 4-bit: 15 steps
        np.testing.assert_allclose(yv * 15, np.round(yv * 15), atol=1e-4)


class TestQATEndToEnd:
    def test_train_with_fake_quant_then_serve_int8(self):
        """QAT smoke: train a linear layer with fake_quant on weights,
        quantize the trained weights, serve through the int8 Pallas
        quantized_matmul, and check outputs agree with float serving."""
        stf.reset_default_graph()
        rng = np.random.RandomState(0)
        xv = rng.randn(32, 16).astype(np.float32)
        true_w = rng.randn(16, 8).astype(np.float32)
        yv = xv @ true_w

        x = stf.placeholder(stf.float32, [None, 16])
        y = stf.placeholder(stf.float32, [None, 8])
        w = stf.get_variable("w_qat", shape=(16, 8),
                             initializer=stf.zeros_initializer())
        w_fq = stf.fake_quant_with_min_max_args(w, min=-4.0, max=4.0)
        pred = stf.matmul(x, w_fq)
        loss = stf.reduce_mean(stf.square(pred - y))
        train = stf.train.AdamOptimizer(0.05).minimize(loss)

        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(150):
                sess.run(train, {x: xv, y: yv})
            final_loss, wv = sess.run([loss, w], {x: xv, y: yv})
        assert final_loss < 0.05

        # export: quantize trained weights to int8 per-column
        w_scale = (np.abs(wv).max(axis=0) / 127).astype(np.float32)
        wq = np.clip(np.round(wv / w_scale), -127, 127).astype(np.int8)

        # serve int8
        stf.reset_default_graph()
        from simple_tensorflow_tpu.ops import fused_ops

        xs = stf.placeholder(stf.float32, [32, 16])
        out_q = fused_ops.quantized_matmul(
            xs, stf.constant(wq), stf.constant(w_scale))
        with stf.Session() as sess:
            served = sess.run(out_q, {xs: xv})
        float_ref = xv @ wv
        err = np.abs(served - float_ref).max()
        scale_bound = np.abs(xv).sum(1).max() * w_scale.max()
        assert err < scale_bound  # int8-quantization-level agreement
        np.testing.assert_allclose(
            served, float_ref,
            atol=max(0.1, 0.05 * np.abs(float_ref).max()))
