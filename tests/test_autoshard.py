"""stf.analysis.autoshard test matrix (ISSUE 14).

- grouping / candidate-generation units,
- GOLDEN searches: dp8 and dp2xtp4 MLP + transformer — the searched
  assignment must match-or-beat the hand specs on analyzer-priced
  collective bytes,
- analyzer-honesty pins for the rule hardening the search relies on
  (ZeRO-layout weight all-gather + data-axis gradient sync),
- numerics parity: searched layout vs replicated run, through both the
  explicit ``parallel.auto_shard`` API (with forced cut points) and
  ``ConfigProto(auto_shard=True)``,
- a fuzz loop: every emitted/in-graph ``ShardingConstraint`` survives
  the full PassManager pipeline and round-trips GraphDef JSON,
- ``match_partition_rules`` unmatched-large-var diagnostics,
- rule-set JSON round trip (``--rules`` format) and the graph_lint
  ``--autoshard [--emit-rules] [--budget]`` CLI,
- the MLPerf-pod one-line entry (dp×tp mesh + gradient accumulation).
"""

import json
import random

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import analysis, parallel
from simple_tensorflow_tpu.analysis import autoshard as auto_mod
from simple_tensorflow_tpu.analysis import sharding as shard_mod
from simple_tensorflow_tpu.parallel import P


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield
    stf.reset_default_graph()


def _build_mlp(batch=16, din=8, hidden=32, dout=4, name_x="x", name_y="y"):
    x = stf.placeholder(stf.float32, [batch, din], name=name_x)
    y = stf.placeholder(stf.float32, [batch, dout], name=name_y)
    stf.set_random_seed(42)
    w1 = stf.Variable(stf.random_normal([din, hidden], stddev=0.1, seed=1),
                      name="w1")
    b1 = stf.Variable(stf.zeros([hidden]), name="b1")
    w2 = stf.Variable(stf.random_normal([hidden, dout], stddev=0.1,
                                        seed=2), name="w2")
    b2 = stf.Variable(stf.zeros([dout]), name="b2")
    h = stf.nn.relu(stf.matmul(x, w1) + b1)
    pred = stf.matmul(h, w2) + b2
    loss = stf.reduce_mean(stf.square(pred - y))
    train_op = stf.train.GradientDescentOptimizer(0.1).minimize(loss)
    return {"x": x, "y": y, "loss": loss, "train_op": train_op}


def _priced_bytes(mesh, seed_specs, fetches):
    rep = analysis.analyze_sharding(mesh=mesh, seed_specs=seed_specs,
                                    fetches=fetches)
    return rep.total_collective_bytes()


# ---------------------------------------------------------------------------
# grouping / candidates
# ---------------------------------------------------------------------------

class TestGrouping:
    def test_group_pattern_collapses_digits(self):
        assert auto_mod.group_pattern("block3/conv_12/kernel") == \
            "block\\d+/conv_\\d+/kernel"
        assert auto_mod.group_pattern("bias") == "bias"

    def test_candidates_respect_divisibility(self):
        cands = auto_mod._spec_candidates(
            [[16, 12]], ["dp"], {"dp": 8})
        # dim0 divisible by 8, dim1 not
        assert ((), ()) in cands
        assert (("dp",), ()) in cands
        assert ((), ("dp",)) not in cands

    def test_candidates_multi_axis_product(self):
        cands = auto_mod._spec_candidates(
            [[8, 64]], ["dp", "tp"], {"dp": 2, "tp": 4})
        # both axes on dim1: 64 % 8 == 0 -> allowed
        assert any(set(e) == {"dp", "tp"} for spec in cands
                   for e in spec)
        # unknown dims accept any axis (runtime uneven lint polices)
        cands2 = auto_mod._spec_candidates([[None, 4]], ["dp"],
                                           {"dp": 8})
        assert (("dp",), ()) in cands2

    def test_group_members_constrain_jointly(self):
        # one member's indivisible dim blocks the whole group
        cands = auto_mod._spec_candidates(
            [[16, 8], [16, 12]], ["dp"], {"dp": 8})
        assert ((), ("dp",)) not in cands
        assert (("dp",), ()) in cands

    def test_same_pattern_different_rank_never_swap_specs(self):
        # 'in1' (rank 2) and 'in2' (rank 3) collapse to one pattern
        # 'in\d+' but are searched as separate (pattern, rank) groups:
        # the result must keep a rank-correct spec for EACH (a shared
        # pattern key would commit the last group's spec on both)
        x1 = stf.placeholder(stf.float32, [16, 8], name="in1")
        x2 = stf.placeholder(stf.float32, [16, 8, 4], name="in2")
        w = stf.Variable(stf.zeros([8, 4]), name="w")
        loss = stf.reduce_sum(stf.matmul(x1, w)) + \
            stf.reduce_sum(x2)
        res = analysis.search_sharding(mesh={"dp": 8}, fetches=[loss])
        assert len(res.feed_specs["in1"]) == 2
        assert len(res.feed_specs["in2"]) == 3
        res.apply()
        g = stf.get_default_graph()
        for name, rank in (("in1", 2), ("in2", 3)):
            spec = g.get_operation_by_name(name).attrs.get("sharding")
            assert spec is None or len(tuple(spec)) == rank, \
                (name, spec)

    def test_same_pattern_different_rank_var_rules_stay_rank_exact(self):
        # same collision on the variable side: the emitted rule set
        # must resolve each var to a spec of ITS rank (exact-name rules
        # shadow the collapsed \d+ pattern, match is first-wins)
        from simple_tensorflow_tpu.parallel import match_partition_rules

        x = stf.placeholder(stf.float32, [16, 64], name="x")
        p1 = stf.Variable(stf.zeros([64, 32]), name="p1")
        p2 = stf.Variable(stf.zeros([16, 8, 4]), name="p2")
        loss = stf.reduce_sum(stf.matmul(x, p1)) + stf.reduce_sum(p2)
        res = analysis.search_sharding(mesh={"dp": 8}, fetches=[loss])
        seeds = match_partition_rules(
            res.rules(), {"p1": p1, "p2": p2}, on_missing="replicate")
        for name, var in (("p1", p1), ("p2", p2)):
            spec = tuple(seeds[name])
            assert len(spec) in (0, var.shape.rank), (name, spec)


# ---------------------------------------------------------------------------
# golden searches: match-or-beat the hand specs on priced bytes
# ---------------------------------------------------------------------------

class TestGoldenSearch:
    def test_dp8_mlp_matches_hand_dp(self):
        m = _build_mlp()
        fetches = [m["train_op"], m["loss"]]
        res = analysis.search_sharding(mesh={"dp": 8}, fetches=fetches)
        # the searched layout: batch on dp, weights replicated — the
        # hand dp8 recipe, found without any hand-placed spec
        assert res.feed_specs["x"] == ("dp", None)
        assert res.var_specs["w\\d+"] == (None, None)
        hand = {"x": ("dp", None), "y": ("dp", None)}
        hand_bytes = _priced_bytes({"dp": 8}, hand, fetches)
        searched = _priced_bytes({"dp": 8}, res.seed_specs(), fetches)
        assert searched <= hand_bytes + 1e-6
        # objective: searched step time must beat the replicated
        # baseline (sharding pays for itself or is not chosen)
        assert res.predicted["step_seconds"] \
            <= res.baseline["step_seconds"] + 1e-12

    def test_dp2_tp4_mlp_beats_hand_megatron(self):
        m = _build_mlp(batch=16, din=64, hidden=256, dout=64)
        fetches = [m["train_op"], m["loss"]]
        mesh = {"dp": 2, "tp": 4}
        res = analysis.search_sharding(mesh=mesh, fetches=fetches)
        hand = {"w1": (None, "tp"), "b1": ("tp",), "w2": ("tp", None),
                "b2": (), "x": ("dp", None), "y": ("dp", None)}
        hand_bytes = _priced_bytes(mesh, hand, fetches)
        searched = _priced_bytes(mesh, res.seed_specs(), fetches)
        assert searched <= hand_bytes + 1e-6
        # the tp axis must actually be used on the weights
        assert any("tp" in str(s) for s in res.var_specs.values())

    def test_dp8_transformer_matches_hand(self):
        from simple_tensorflow_tpu.models import transformer as tr

        cfg = tr.TransformerConfig.tiny()
        m = tr.transformer_train_model(batch_size=8, src_len=8,
                                       tgt_len=8, cfg=cfg,
                                       compute_dtype=stf.float32)
        fetches = [m["train_op"], m["loss"]]
        res = analysis.search_sharding(mesh={"dp": 8}, fetches=fetches,
                                       anneal_steps=16)
        hand = {m["src_ids"].op.name: ("dp", None),
                m["tgt_in"].op.name: ("dp", None),
                m["tgt_out"].op.name: ("dp", None)}
        hand_bytes = _priced_bytes({"dp": 8}, hand, fetches)
        searched = _priced_bytes({"dp": 8}, res.seed_specs(), fetches)
        assert searched <= hand_bytes + 1e-6

    def test_rules_seed_search(self):
        m = _build_mlp()
        res = analysis.search_sharding(
            mesh={"dp": 8}, fetches=[m["train_op"], m["loss"]],
            rules=[("w\\d+", (None, None))])
        assert res.var_specs["w\\d+"] == (None, None)

    def test_user_declared_specs_are_fixed(self):
        m = _build_mlp()
        g = stf.get_default_graph()
        reg = g._scoped_state["__vars_by_store_name__"]
        reg["w1"].set_sharding(P(None, None))
        res = analysis.search_sharding(mesh={"dp": 8},
                                       fetches=[m["train_op"],
                                                m["loss"]])
        # w1 never entered the search (fixed seed), w2 still grouped
        members = [mm for gr in res.groups for mm in gr["members"]]
        assert "w1" not in members
        assert "w2" in members

    def test_fixed_same_pattern_different_specs_keep_own_rules(self):
        # two USER-declared vars collapsing to one pattern with
        # different specs: the rule set must resolve each by exact
        # name (a shared pattern rule would misapply the first spec)
        from simple_tensorflow_tpu.parallel import match_partition_rules

        x = stf.placeholder(stf.float32, [16, 64], name="x")
        k1 = stf.Variable(stf.zeros([64, 32]), name="layer_1/kernel")
        k2 = stf.Variable(stf.zeros([32, 64]), name="layer_2/kernel")
        loss = stf.reduce_sum(
            stf.matmul(stf.matmul(x, k1), k2))
        g = stf.get_default_graph()
        reg = g._scoped_state["__vars_by_store_name__"]
        reg["layer_1/kernel"].set_sharding(P(None, "dp"))
        reg["layer_2/kernel"].set_sharding(P("dp", None))
        res = analysis.search_sharding(mesh={"dp": 8}, fetches=[loss])
        seeds = match_partition_rules(
            res.rules(), {"layer_1/kernel": k1, "layer_2/kernel": k2},
            on_missing="replicate")
        assert tuple(seeds["layer_1/kernel"]) == (None, "dp")
        assert tuple(seeds["layer_2/kernel"]) == ("dp", None)

    def test_operation_only_fetch_still_prices_peak(self):
        # the canonical sess.run(train_op) fetch is an OPERATION: the
        # budget feasibility check must still price per-shard peak
        # (cost_model.estimate takes ops) instead of silently passing
        m = _build_mlp()
        res = analysis.search_sharding(
            mesh={"dp": 8}, fetches=[m["train_op"]], budget_bytes=1)
        assert res.predicted["per_shard_peak_bytes"] is not None
        assert res.predicted["over_budget"] is True

    def test_budget_marks_infeasible(self):
        m = _build_mlp()
        res = analysis.search_sharding(
            mesh={"dp": 8}, fetches=[m["train_op"], m["loss"]],
            budget_bytes=1)
        assert res.predicted["over_budget"] is True
        res2 = analysis.search_sharding(
            mesh={"dp": 8}, fetches=[m["train_op"], m["loss"]],
            budget_bytes=1 << 40)
        assert res2.predicted["over_budget"] is False

    def test_deterministic(self):
        m = _build_mlp()
        r1 = analysis.search_sharding(mesh={"dp": 8},
                                      fetches=[m["train_op"]])
        r2 = analysis.search_sharding(mesh={"dp": 8},
                                      fetches=[m["train_op"]])
        assert r1.rules() == r2.rules()
        assert r1.feed_specs == r2.feed_specs


# ---------------------------------------------------------------------------
# analyzer honesty: the rule hardening the objective relies on
# ---------------------------------------------------------------------------

class TestAnalyzerHonesty:
    def test_zero_layout_prices_weight_allgather(self):
        # dp shards the batch AND a weight's cout: GSPMD must gather
        # the weight every step (axis collision) — priced, not free
        m = _build_mlp(din=64, hidden=256, dout=64)
        rep = analysis.analyze_sharding(
            mesh={"dp": 8},
            seed_specs={"w1": (None, "dp"), "x": ("dp", None)},
            fetches=[m["train_op"], m["loss"]])
        kinds = rep.bytes_by_kind()
        assert kinds.get("all-gather", 0) >= 64 * 256 * 4  # full w1

    def test_zero_layout_grad_sync_is_reduce_scatter_sized(self):
        # the batch (data axis) is the contracted dim of every weight
        # grad: sync needed even when the weight itself carries dp —
        # at the SHARDED payload (reduce-scatter), not the full bytes
        m = _build_mlp(din=64, hidden=256, dout=64)
        fetches = [m["train_op"], m["loss"]]
        rep = analysis.analyze_sharding(
            mesh={"dp": 8},
            seed_specs={"w1": (None, "dp"), "x": ("dp", None)},
            fetches=fetches)
        w1_sync = [e for e in rep.collective_edges()
                   if e.kind == "all-reduce" and "w1" in (e.note or "")]
        assert w1_sync, "gradient sync for sharded w1 not priced"
        assert w1_sync[0].nbytes == pytest.approx(64 * 256 * 4 / 8)

    def test_batch_sharded_input_grad_needs_no_sync(self):
        # dL/dx of a batch-carrying input is sharded exactly like x —
        # nothing contracts the batch — so the data-axis term must not
        # price a sync for it (saliency/adversarial-grad plans), while
        # the replicated weight's grad in the SAME plan still syncs
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        w = stf.Variable(stf.zeros([8, 4]), name="w")
        loss = stf.reduce_sum(stf.matmul(x, w))
        gx, gw = stf.gradients(loss, [x, w])
        rep = analysis.analyze_sharding(
            mesh={"dp": 8}, seed_specs={"x": ("dp", None)},
            fetches=[gx, gw])
        syncs = [e for e in rep.collective_edges()
                 if "gradient sync" in (e.note or "")]
        assert not [e for e in syncs if "for x" in e.note], syncs
        assert [e for e in syncs if "for w" in e.note], syncs

    def test_megatron_tp_weight_needs_no_tp_grad_sync(self):
        # column-parallel: tp shards the weight and its activations —
        # the tp axis must NOT appear in that weight's gradient sync
        m = _build_mlp(din=64, hidden=256, dout=64)
        rep = analysis.analyze_sharding(
            mesh={"dp": 2, "tp": 4},
            seed_specs={"w1": (None, "tp"), "x": ("dp", None)},
            fetches=[m["train_op"], m["loss"]])
        w1_sync = [e for e in rep.collective_edges()
                   if "gradient sync" in (e.note or "")
                   and "w1" in (e.note or "")]
        for e in w1_sync:
            assert "tp" not in e.axes


# ---------------------------------------------------------------------------
# numerics parity: searched layout vs replicated run
# ---------------------------------------------------------------------------

def _train_losses(mesh=None, config=None, setup=None, steps=3):
    stf.reset_default_graph()
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 8).astype(np.float32)
    ys = rng.randn(16, 4).astype(np.float32)
    import contextlib

    ctx = mesh if mesh is not None else contextlib.nullcontext()
    with ctx:
        m = _build_mlp()
        if setup is not None:
            setup(m)
        losses = []
        with stf.Session(config=config) as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(steps):
                l, _ = sess.run([m["loss"], m["train_op"]],
                                feed_dict={m["x"]: xs, m["y"]: ys})
                losses.append(float(l))
    return losses


class TestNumericsParity:
    def test_config_auto_shard_matches_replicated(self):
        ref = _train_losses()
        got = _train_losses(mesh=parallel.Mesh({"dp": 8}),
                            config=stf.ConfigProto(auto_shard=True))
        # f32 dtype contract: the dp-sharded program reduces in the
        # same order per shard; losses match to float32 resolution
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)

    def test_explicit_auto_shard_with_cut_points(self):
        ref = _train_losses()

        def setup(m):
            res = parallel.auto_shard(
                fetches=[m["train_op"], m["loss"]], cut_min_bytes=1)
            assert res.cuts, "expected forced cut points"
            reg = stf.get_default_graph()._scoped_state.get(
                "__autoshard_constraints__")
            assert reg, "commit constraints not registered"

        got = _train_losses(mesh=parallel.Mesh({"dp": 8}), setup=setup)
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)

    def test_apply_declares_variable_shardings(self):
        # searched-replicated weights must get an EXPLICIT P() mesh
        # placement (an undeclared variable stays committed to one
        # device and is re-broadcast every step); sharded specs commit
        # verbatim
        mesh = parallel.Mesh({"dp": 8})
        with mesh:
            m = _build_mlp()
            parallel.auto_shard(fetches=[m["train_op"], m["loss"]])
            reg = stf.get_default_graph()._scoped_state[
                "__vars_by_store_name__"]
            for name in ("w1", "w2", "b1", "b2"):
                assert reg[name].sharding is not None, (
                    f"{name}: searched spec not declared")
            assert tuple(reg["w1"].sharding) == ()

    def test_auto_shard_applied_once_per_graph(self):
        mesh = parallel.Mesh({"dp": 8})
        with mesh:
            m = _build_mlp()
            xs = np.zeros((16, 8), np.float32)
            ys = np.zeros((16, 4), np.float32)
            with stf.Session(
                    config=stf.ConfigProto(auto_shard=True)) as sess:
                sess.run(stf.global_variables_initializer())
                sess.run(m["train_op"],
                         feed_dict={m["x"]: xs, m["y"]: ys})
                res = stf.get_default_graph()._scoped_state[
                    "__autoshard_applied__"]
                sess.run(m["loss"],
                         feed_dict={m["x"]: xs, m["y"]: ys})
                assert stf.get_default_graph()._scoped_state[
                    "__autoshard_applied__"] is res


# ---------------------------------------------------------------------------
# ShardingConstraint: PassManager survival + GraphDef round trip (fuzz)
# ---------------------------------------------------------------------------

def _count_constraints(gd):
    return [n for n in gd["node"] if n["op"] == "ShardingConstraint"]


class TestConstraintSurvival:
    def test_fuzz_constraints_survive_passes_and_roundtrip(self):
        from simple_tensorflow_tpu.framework import graph_io, optimizer

        rng = random.Random(7)
        for trial in range(6):
            stf.reset_default_graph()
            n = rng.randint(1, 3)
            x = stf.placeholder(stf.float32, [16, 8], name="x")
            t = x
            n_constraints = 0
            for i in range(rng.randint(2, 5)):
                kind = rng.choice(["matmul", "relu", "add", "constraint"])
                if kind == "matmul":
                    w = stf.constant(
                        np.ones((int(t.shape[1]), 8), np.float32))
                    t = stf.matmul(t, w)
                elif kind == "relu":
                    t = stf.nn.relu(t)
                elif kind == "add":
                    t = t + 1.0
                else:
                    t = parallel.with_sharding_constraint(t, "dp", None)
                    n_constraints += 1
            for _ in range(n):
                t = parallel.with_sharding_constraint(t, "dp", None)
                n_constraints += 1
            out = stf.reduce_sum(t, name="out")
            gd = graph_io.graph_to_graphdef(stf.get_default_graph())
            opt = optimizer.optimize(gd, keep=[out.name])
            kept = _count_constraints(opt)
            assert len(kept) == n_constraints, (
                f"trial {trial}: {n_constraints} constraints in, "
                f"{len(kept)} out of the PassManager pipeline")
            # GraphDef JSON round trip preserves the spec attr
            blob = json.dumps(opt)
            stf.reset_default_graph()
            graph_io.import_graph_def(json.loads(blob), name="")
            g = stf.get_default_graph()
            cops = [op for op in g.get_operations()
                    if op.type == "ShardingConstraint"]
            assert len(cops) == n_constraints
            for cop in cops:
                spec = tuple(cop.attrs["spec"])
                assert spec == ("dp", None), spec
            # and the analyzer still commits the round-tripped spec
            out2 = g.as_graph_element("out:0", allow_tensor=True)
            rep = analysis.analyze_sharding(
                graph=g, mesh={"dp": 8}, fetches=[out2])
            assert rep.spec_of(cops[-1].outputs[0]) == ("dp", None)

    def test_plan_optimizer_keeps_consumed_constraint(self):
        from simple_tensorflow_tpu.framework import lowering, optimizer

        x = stf.placeholder(stf.float32, [16, 8], name="x")
        t = parallel.with_sharding_constraint(x + 1.0, "dp", None)
        out = stf.reduce_sum(t)
        pruned = lowering.prune([out.op], set())
        plan, _const, _alias = optimizer.optimize_pruned(
            pruned, set(), [out])
        assert any(op.type == "ShardingConstraint" for op in plan)

    def test_constraint_infers_shape_without_output_specs(self):
        # abstract-eval: the op must infer identity shape/dtype even
        # when a producer omits output_specs (imported C-client graphs)
        g = stf.get_default_graph()
        x = stf.placeholder(stf.float32, [4, 4], name="x")
        op = g.create_op("ShardingConstraint", [x],
                         attrs={"spec": P("dp", None)},
                         name="bare_constraint")
        assert op.outputs[0].shape.as_list() == [4, 4]
        assert op.outputs[0].dtype == stf.float32


# ---------------------------------------------------------------------------
# match_partition_rules: unmatched-large-var diagnostics
# ---------------------------------------------------------------------------

class TestUnmatchedLargeVar:
    def test_warns_on_large_unmatched(self):
        big = stf.Variable(stf.zeros([512, 1024]), name="embedding")
        small = stf.Variable(stf.zeros([4]), name="tiny_bias")
        diags = []
        out = parallel.match_partition_rules(
            [("nothing_matches", ("dp", None))],
            diagnostics=diags)
        assert out["embedding"] == P()
        codes = [d.code for d in diags]
        assert codes == ["sharding/unmatched-large-var"]
        assert "embedding" in diags[0].message
        # small var replicates silently
        assert not any("tiny_bias" in d.message for d in diags)
        del big, small

    def test_no_warning_when_matched_or_skipped(self):
        stf.Variable(stf.zeros([512, 1024]), name="embedding")
        diags = []
        parallel.match_partition_rules([(".*", ("dp", None))],
                                       diagnostics=diags)
        assert diags == []
        diags2 = []
        parallel.match_partition_rules([("nope", ())],
                                       on_missing="skip",
                                       diagnostics=diags2)
        assert diags2 == []


# ---------------------------------------------------------------------------
# rule-set round trip + CLI
# ---------------------------------------------------------------------------

class TestRulesAndCLI:
    def test_rules_roundtrip_through_match_partition_rules(self):
        m = _build_mlp()
        res = analysis.search_sharding(mesh={"dp": 2, "tp": 4},
                                       fetches=[m["train_op"]])
        rules = [(pat, tuple(spec)) for pat, spec in res.rules()]
        seeded = parallel.match_partition_rules(rules)
        assert set(seeded) >= {"w1", "w2", "b1", "b2"}
        parsed = json.loads(res.to_json())
        assert parsed["rules"] == [
            [pat, [None if e is None else e for e in spec]]
            for pat, spec in res.rules()]

    def test_graph_lint_autoshard_cli(self, tmp_path):
        from simple_tensorflow_tpu.framework import graph_io
        from simple_tensorflow_tpu.tools import graph_lint

        m = _build_mlp()
        gd = graph_io.graph_to_graphdef(stf.get_default_graph())
        p = tmp_path / "mlp.json"
        p.write_text(json.dumps(gd))
        rules_out = tmp_path / "rules.json"
        fetches = [m["train_op"].name, m["loss"].name]
        stf.reset_default_graph()
        rc = graph_lint.main(
            [str(p), "--fetch", fetches[0], "--fetch", fetches[1],
             "--mesh", "8", "--autoshard",
             "--emit-rules", str(rules_out),
             "--budget", str(1 << 40)])
        assert rc == 0
        emitted = json.loads(rules_out.read_text())
        assert emitted[-1] == [".*", []]  # catch-all present
        # the emitted rule file is valid --rules input
        stf.reset_default_graph()
        rc2 = graph_lint.main(
            [str(p), "--fetch", fetches[1], "--mesh", "8",
             "--rules", str(rules_out)])
        assert rc2 == 0
        # 1-byte budget: predicted per-shard peak exceeds it -> exit 1
        stf.reset_default_graph()
        rc3 = graph_lint.main(
            [str(p), "--fetch", fetches[1], "--mesh", "8",
             "--autoshard", "--budget", "1"])
        assert rc3 == 1

    def test_cli_flag_validation(self, tmp_path):
        from simple_tensorflow_tpu.tools import graph_lint

        p = tmp_path / "empty.json"
        p.write_text(json.dumps({"versions": {}, "node": []}))
        with pytest.raises(SystemExit):
            graph_lint.main([str(p), "--autoshard"])  # needs --mesh
        with pytest.raises(SystemExit):
            graph_lint.main([str(p), "--emit-rules", "x.json"])
        # --budget without a resolvable --fetch must be LOUD: per-shard
        # peak is priced over the fetch closure, so an empty closure
        # would green-light any layout
        with pytest.raises(SystemExit):
            graph_lint.main([str(p), "--mesh", "dp=8", "--autoshard",
                             "--budget", "1000"])
        with pytest.raises(SystemExit):
            graph_lint.main([str(p), "--mesh", "dp=8", "--autoshard",
                             "--budget", "1000", "--fetch", "typo"])


# ---------------------------------------------------------------------------
# MLPerf-pod one-line entry
# ---------------------------------------------------------------------------

class TestPodEntry:
    def test_pod_train_accumulation_matches_single_step(self):
        rng = np.random.RandomState(0)
        xs = rng.randn(16, 8).astype(np.float32)
        ys = rng.randn(16, 4).astype(np.float32)

        # reference: one plain SGD step on the batch
        m = _build_mlp()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(m["train_op"], feed_dict={m["x"]: xs, m["y"]: ys})
            ref = sess.run(m["loss"],
                           feed_dict={m["x"]: xs, m["y"]: ys})

        # pod entry, accumulation=2 over the SAME micro-batch: the
        # mean-scaled accumulated gradient equals the single-step
        # gradient, so the post-apply loss must match
        stf.reset_default_graph()
        mesh = parallel.Mesh({"dp": 2, "tp": 4})
        with mesh:
            x = stf.placeholder(stf.float32, [16, 8], name="x")
            y = stf.placeholder(stf.float32, [16, 4], name="y")
            stf.set_random_seed(42)
            w1 = stf.Variable(stf.random_normal([8, 32], stddev=0.1,
                                                seed=1), name="w1")
            b1 = stf.Variable(stf.zeros([32]), name="b1")
            w2 = stf.Variable(stf.random_normal([32, 4], stddev=0.1,
                                                seed=2), name="w2")
            b2 = stf.Variable(stf.zeros([4]), name="b2")
            h = stf.nn.relu(stf.matmul(x, w1) + b1)
            pred = stf.matmul(h, w2) + b2
            loss = stf.reduce_mean(stf.square(pred - y))
            prog = parallel.mlperf_pod_train(
                loss, mesh=mesh,
                optimizer=stf.train.GradientDescentOptimizer(0.1),
                gradient_accumulation_steps=2)
            assert prog.autoshard is not None
            assert prog.steps == 2
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                prog.run(sess, feed_dict={x: xs, y: ys})
                got = sess.run(loss, feed_dict={x: xs, y: ys})
        np.testing.assert_allclose(float(got), float(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_apply_resets_poisoned_accumulator(self):
        # an overflowed micro-batch leaves inf in the accumulator; the
        # apply-op reset must CLEAR it (assign zeros) — the old
        # acc * 0.0 reset computed inf * 0.0 = nan and the accumulator
        # never recovered
        from simple_tensorflow_tpu.ops import state_ops

        mesh = parallel.Mesh({"dp": 8})
        rng = np.random.RandomState(0)
        xs = rng.randn(16, 8).astype(np.float32)
        ys = rng.randn(16, 4).astype(np.float32)
        with mesh:
            x = stf.placeholder(stf.float32, [16, 8], name="x")
            y = stf.placeholder(stf.float32, [16, 4], name="y")
            w = stf.Variable(stf.zeros([8, 4]), name="w")
            loss = stf.reduce_mean(stf.square(stf.matmul(x, w) - y))
            prog = parallel.mlperf_pod_train(
                loss, mesh=mesh,
                optimizer=stf.train.GradientDescentOptimizer(0.1),
                gradient_accumulation_steps=2)
            accs = [v for v in stf.global_variables()
                    if v.op.name.endswith("_accum")]
            assert accs
            poison = [state_ops.assign(
                a, stf.fill(a.shape.as_list(), np.float32(np.inf)))
                for a in accs]
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                sess.run(poison)
                sess.run(prog.apply_op, feed_dict={x: xs, y: ys})
                for a in accs:
                    np.testing.assert_array_equal(
                        sess.run(a.value()), 0.0)

    def test_pod_train_single_step_mode(self):
        mesh = parallel.Mesh({"dp": 8})
        rng = np.random.RandomState(0)
        xs = rng.randn(16, 8).astype(np.float32)
        ys = rng.randn(16, 4).astype(np.float32)
        with mesh:
            m = _build_mlp()
            # minimize() was already called by _build_mlp; the entry
            # builds its own train op from the loss
            prog = parallel.mlperf_pod_train(
                m["loss"], mesh=mesh,
                optimizer=stf.train.GradientDescentOptimizer(0.1))
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                l0 = prog.run(sess, feed_dict={m["x"]: xs,
                                               m["y"]: ys})
                l1 = prog.run(sess, feed_dict={m["x"]: xs,
                                               m["y"]: ys})
        assert np.isfinite(l0) and np.isfinite(l1)
        assert float(l1) < float(l0)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_autoshard_metrics_populated():
    from simple_tensorflow_tpu.platform import monitoring

    m = _build_mlp()
    analysis.search_sharding(mesh={"dp": 8},
                             fetches=[m["train_op"], m["loss"]])
    exported = monitoring.export()
    assert exported["/stf/analysis/autoshard_seconds"]["cells"]
    cands = exported["/stf/analysis/autoshard_candidates"]["cells"]
    assert sum(cands.values()) > 0
    assert "searched" in \
        exported["/stf/analysis/autoshard_predicted_bytes"]["cells"]
