"""Parallel input-pipeline engine tests (ISSUE 5): stage-graph executor,
sharded C++ TFRecord reads, batch Example parsing, AUTOTUNE, and the
determinism/checkpoint contracts of docs/DATA.md."""

import os
import threading
import time

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import data as stf_data
from simple_tensorflow_tpu.data import AUTOTUNE
from simple_tensorflow_tpu.lib.example import make_example
from simple_tensorflow_tpu.lib.io import tf_record
from simple_tensorflow_tpu.ops import parsing_ops as po
from simple_tensorflow_tpu.platform import monitoring


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _write_shards(tmp_path, n_shards=4, n_records=20, prefix="s"):
    files = []
    for s in range(n_shards):
        p = str(tmp_path / f"{prefix}{s}.tfrecord")
        with tf_record.TFRecordWriter(p) as w:
            for i in range(n_records):
                w.write(make_example(
                    x=[float(s * 1000 + i), float(i) + 0.5],
                    y=[s * 1000 + i]).SerializeToString())
        files.append(p)
    return files


class TestPrefetchErrorPropagation:
    def test_source_error_not_swallowed(self):
        """Regression (satellite 1): the seed's prefetch worker wrapped
        the source loop in ``finally: q.put(DONE)`` — any source error
        became silent end-of-data."""
        def bad():
            yield np.int32(1)
            yield np.int32(2)
            raise ValueError("source exploded")

        ds = stf_data.Dataset.from_generator(bad).prefetch(2)
        got = []
        with pytest.raises(ValueError, match="source exploded"):
            for x in ds:
                got.append(int(x))
        assert got == [1, 2]  # elements before the error still arrive

    def test_parallel_map_delivers_inflight_before_source_error(self):
        """A SOURCE error behind a parallel map must not drop mapped
        elements already in flight — sequential delivers all produced
        elements then the error; parallel must match (at-position
        contract, docs/DATA.md)."""
        def src():
            for i in range(20):
                yield np.int64(i)
            raise RuntimeError("tail corrupt")

        for det in (True, False):
            ds = stf_data.Dataset.from_generator(src).map(
                lambda x: x * 2, num_parallel_calls=4, deterministic=det)
            got = []
            with pytest.raises(RuntimeError, match="tail corrupt"):
                for x in ds:
                    got.append(int(x))
            assert sorted(got) == [2 * i for i in range(20)]
            if det:
                assert got == [2 * i for i in range(20)]

    def test_explicit_prefetch_capacity_honored(self):
        """prefetch(64) must build a 64-slot ring — the 16 cap bounds
        only AUTOTUNE growth (regression: fixed sizes were clamped)."""
        list(stf_data.Dataset.from_tensor_slices(
            np.arange(5)).map(lambda x: x, num_parallel_calls=2)
            .prefetch(64))
        cells = monitoring.get_metric(
            "/stf/data/parallelism").snapshot()["cells"]
        assert cells["prefetch:0"] == 64

    def test_map_func_error_positioned(self):
        def boom(x):
            if int(x) == 5:
                raise RuntimeError("bad element")
            return x * 2

        ds = stf_data.Dataset.from_tensor_slices(
            np.arange(10)).map(boom, num_parallel_calls=3)
        got = []
        with pytest.raises(RuntimeError, match="bad element"):
            for x in ds:
                got.append(int(x))
        # ordered mode: every element before the failing one was emitted
        assert got == [0, 2, 4, 6, 8]


class TestTFRecordDatasetOptions:
    def test_unsupported_compression_raises(self, tmp_path):
        p = str(tmp_path / "x.tfrecord")
        with tf_record.TFRecordWriter(p) as w:
            w.write(b"r")
        with pytest.raises(stf.errors.UnimplementedError,
                           match="compression_type"):
            stf_data.TFRecordDataset(p, compression_type="ZLIB")

    def test_gzip_compression_supported(self, tmp_path):
        p = str(tmp_path / "g.tfrecord.gz")
        opts = tf_record.TFRecordOptions(
            tf_record.TFRecordCompressionType.GZIP)
        with tf_record.TFRecordWriter(p, opts) as w:
            for i in range(7):
                w.write(f"z{i}".encode())
        got = list(stf_data.TFRecordDataset(p, compression_type="GZIP"))
        assert got == [f"z{i}".encode() for i in range(7)]

    def test_buffer_size_honored(self, tmp_path):
        files = _write_shards(tmp_path, n_shards=2, n_records=10)
        base = list(stf_data.TFRecordDataset(files))
        small = list(stf_data.TFRecordDataset(files, buffer_size=4096))
        assert small == base
        with pytest.raises(ValueError, match="buffer_size"):
            stf_data.TFRecordDataset(files, buffer_size=0)

    def test_bad_parallel_arg(self, tmp_path):
        files = _write_shards(tmp_path, n_shards=1, n_records=1)
        with pytest.raises(ValueError, match="num_parallel_reads"):
            stf_data.TFRecordDataset(files, num_parallel_reads=-3)


class TestShardedReadDeterminism:
    def test_parallel_reads_match_sequential_stream(self, tmp_path):
        files = _write_shards(tmp_path, n_shards=6, n_records=15)
        seq = list(stf_data.TFRecordDataset(files))
        for n in (2, 4, AUTOTUNE):
            par = list(stf_data.TFRecordDataset(files,
                                                num_parallel_reads=n))
            assert par == seq  # byte-identical, strict shard order

    def test_full_chain_determinism(self, tmp_path):
        """Ordered map + seeded shuffle + parallel reads + prefetch
        reproduce the sequential chain's element stream exactly
        (acceptance criterion)."""
        files = _write_shards(tmp_path, n_shards=4, n_records=16)
        spec = {"x": po.FixedLenFeature([2], stf.float32),
                "y": po.FixedLenFeature([1], stf.int64)}

        def chain(parallel):
            ds = stf_data.TFRecordDataset(
                files,
                num_parallel_reads=(AUTOTUNE if parallel else None))
            ds = ds.shuffle(8, seed=42)
            ds = ds.batch(4).parse_example(spec)
            ds = ds.map(lambda d: {"x": d["x"] * 2.0, "y": d["y"]},
                        num_parallel_calls=(4 if parallel else None))
            if parallel:
                ds = ds.prefetch(AUTOTUNE)
            return list(ds)

        seq, par = chain(False), chain(True)
        assert len(seq) == len(par) == 16
        for a, b in zip(seq, par):
            np.testing.assert_array_equal(a["x"], b["x"])
            np.testing.assert_array_equal(a["y"], b["y"])

    def test_unordered_map_same_multiset(self):
        ds = stf_data.Dataset.from_tensor_slices(np.arange(40)).map(
            lambda x: x + 100, num_parallel_calls=4, deterministic=False)
        assert sorted(int(x) for x in ds) == [i + 100 for i in range(40)]


class TestInterleave:
    def test_cycle_semantics(self):
        ds = stf_data.Dataset.range(4).interleave(
            lambda x: stf_data.Dataset.from_tensor_slices(
                np.arange(int(x) * 10, int(x) * 10 + 3)),
            cycle_length=2, block_length=1)
        assert [int(v) for v in ds] == [
            0, 10, 1, 11, 2, 12, 20, 30, 21, 31, 22, 32]

    def test_parallel_matches_sequential(self, tmp_path):
        files = _write_shards(tmp_path, n_shards=5, n_records=9)

        def mk(n):
            return stf_data.Dataset.from_tensor_slices(
                np.array(files, dtype=object)).interleave(
                    lambda f: stf_data.TFRecordDataset(
                        f.decode() if isinstance(f, bytes) else str(f)),
                    cycle_length=3, block_length=2, num_parallel_calls=n)

        seq = list(mk(None))
        assert len(seq) == 45
        for n in (2, AUTOTUNE):
            assert list(mk(n)) == seq

    def test_bad_args(self):
        with pytest.raises(ValueError, match="cycle_length"):
            stf_data.Dataset.range(2).interleave(lambda x: None,
                                                 cycle_length=0)


class TestParseParity:
    """C++ batch parse vs pure-Python parse on golden TFRecord shards
    (satellite: parity gate for the one-C-call-per-batch parser)."""

    def _golden(self, tmp_path, n=13):
        p = str(tmp_path / "golden.tfrecord")
        rng = np.random.RandomState(0)
        rows = []
        with tf_record.TFRecordWriter(p) as w:
            for i in range(n):
                x = rng.randn(3).astype(np.float32)
                y = rng.randint(-5, 5, size=2)
                rows.append((x, y))
                w.write(make_example(x=list(map(float, x)),
                                     y=list(map(int, y)))
                        .SerializeToString())
        return p, rows

    def test_native_vs_python_parity(self, tmp_path, monkeypatch):
        from simple_tensorflow_tpu.runtime import native

        if not native.available():
            pytest.skip("native runtime not built")
        p, rows = self._golden(tmp_path)
        spec = {"x": po.FixedLenFeature([3], stf.float32),
                "y": po.FixedLenFeature([2], stf.int64)}
        serialized = list(tf_record.tf_record_iterator(p))
        fast = po.parse_example_py(serialized, spec)
        assert fast is not None
        monkeypatch.setattr(po, "_parse_examples_fast",
                            lambda *a, **k: None)
        slow = po.parse_example_py(serialized, spec)
        np.testing.assert_array_equal(fast["x"], slow["x"])
        np.testing.assert_array_equal(fast["y"], slow["y"])
        assert fast["x"].dtype == slow["x"].dtype == np.float32
        assert fast["y"].dtype == slow["y"].dtype == np.int64
        for i, (x, y) in enumerate(rows):
            np.testing.assert_allclose(fast["x"][i], x)
            np.testing.assert_array_equal(fast["y"][i], y)

    def test_defaults_and_missing_parity(self, tmp_path, monkeypatch):
        from simple_tensorflow_tpu.runtime import native

        if not native.available():
            pytest.skip("native runtime not built")
        serialized = [make_example(a=[1.0, 2.0]).SerializeToString(),
                      make_example(b=[7]).SerializeToString()]
        spec = {"a": po.FixedLenFeature([2], stf.float32,
                                        default_value=[0.5, 0.5]),
                "b": po.FixedLenFeature([1], stf.int64, default_value=9)}
        fast = po.parse_example_py(serialized, spec)
        monkeypatch.setattr(po, "_parse_examples_fast",
                            lambda *a, **k: None)
        slow = po.parse_example_py(serialized, spec)
        np.testing.assert_array_equal(fast["a"], slow["a"])
        np.testing.assert_array_equal(fast["b"], slow["b"])

    def test_parse_path_counters(self, tmp_path):
        before = monitoring.get_metric(
            "/stf/data/parse_example_batches").snapshot()["cells"]
        serialized = [make_example(v=[1.0]).SerializeToString()]
        po.parse_example_py(serialized,
                            {"v": po.FixedLenFeature([1], stf.float32)})
        after = monitoring.get_metric(
            "/stf/data/parse_example_batches").snapshot()["cells"]
        assert sum(after.values()) == sum(before.values()) + 1


class TestIteratorCheckpointParallel:
    def test_save_restore_mid_stream_with_parallel_stages(self, tmp_path):
        """Iterator position checkpoint/restore while sharded reads +
        parallel map + prefetch are active (satellite test matrix)."""
        files = _write_shards(tmp_path, n_shards=3, n_records=8)
        spec = {"x": po.FixedLenFeature([2], stf.float32),
                "y": po.FixedLenFeature([1], stf.int64)}

        def mk():
            return (stf_data.TFRecordDataset(files, num_parallel_reads=2)
                    .batch(4).parse_example(spec)
                    .map(lambda d: d["y"], num_parallel_calls=2)
                    .prefetch(2))

        ref = list(mk())
        it = stf_data.Iterator(mk())
        consumed = [it._next_value() for _ in range(2)]
        for got, want in zip(consumed, ref[:2]):
            np.testing.assert_array_equal(got, want)
        state = it.save_state()
        assert state == {"position": 2}
        it.close()  # abandoning the half-consumed stream leaks it

        it2 = stf_data.Iterator(mk())
        it2.restore_state(state)
        rest = []
        while True:
            try:
                rest.append(it2._next_value())
            except stf.errors.OutOfRangeError:
                break
        assert len(rest) == len(ref) - 2
        for got, want in zip(rest, ref[2:]):
            np.testing.assert_array_equal(got, want)

    def test_session_driven_get_next_parallel(self, tmp_path):
        files = _write_shards(tmp_path, n_shards=2, n_records=6)
        spec = {"y": po.FixedLenFeature([1], stf.int64)}
        ds = (stf_data.TFRecordDataset(files, num_parallel_reads=2)
              .batch(3).parse_example(spec).prefetch(2))
        nxt = ds.make_one_shot_iterator().get_next()
        with stf.Session() as sess:
            a = sess.run(nxt)
            b = sess.run(nxt)
        np.testing.assert_array_equal(np.asarray(a["y"]).ravel(),
                                      [0, 1, 2])
        np.testing.assert_array_equal(np.asarray(b["y"]).ravel(),
                                      [3, 4, 5])


class TestAutotuneAndMetrics:
    def test_autotune_accepted_everywhere(self):
        ds = (stf_data.Dataset.from_tensor_slices(np.arange(30))
              .map(lambda x: x * 2, num_parallel_calls=AUTOTUNE)
              .prefetch(AUTOTUNE))
        assert [int(x) for x in ds] == [2 * i for i in range(30)]

    def test_autotune_thread_starts_and_widens_bottleneck(self):
        # Regression: knobs register lazily (inside stage generator
        # bodies, on the first element), so gating the autotuner spawn
        # on the knob list at pipeline-build time left AUTOTUNE
        # permanently pinned at initial parallelism.
        adj = monitoring.get_metric("/stf/data/autotune_adjustments")
        before = sum(adj.snapshot()["cells"].values())
        ds = (stf_data.Dataset.from_tensor_slices(np.arange(120))
              .map(lambda x: (time.sleep(0.005), x * 2)[1],
                   num_parallel_calls=AUTOTUNE)
              .prefetch(AUTOTUNE))
        it = iter(ds)
        got = [int(next(it)) for _ in range(60)]
        assert any(t.name == "stf_data_autotune"
                   for t in threading.enumerate())
        got += [int(x) for x in it]
        assert got == [2 * i for i in range(120)]
        after = sum(adj.snapshot()["cells"].values())
        assert after > before  # the slow map stage got widened

    def test_ring_occupancy_reported(self):
        # Regression: /stf/data/buffer_occupancy was only written by the
        # autotuner tick (AUTOTUNE prefetch rings), never by fixed-size
        # rings — the ring itself must report occupancy on put/get.
        ds = stf_data.Dataset.from_tensor_slices(np.arange(40)).prefetch(4)
        it = iter(ds)
        occ = 0
        deadline = time.time() + 5.0
        while occ < 1 and time.time() < deadline:
            next(it)
            cells = monitoring.get_metric(
                "/stf/data/buffer_occupancy").snapshot()["cells"]
            occ = max((v for k, v in cells.items()
                       if k.startswith("prefetch")), default=0)
            time.sleep(0.01)
        it.close()
        assert occ >= 1

    def test_stage_metrics_populated(self, tmp_path):
        files = _write_shards(tmp_path, n_shards=2, n_records=10)
        rec0 = monitoring.get_metric(
            "/stf/data/records_read").get_cell().value()
        list(stf_data.TFRecordDataset(files, num_parallel_reads=2)
             .map(lambda b: b, num_parallel_calls=2).prefetch(2))
        assert monitoring.get_metric(
            "/stf/data/records_read").get_cell().value() == rec0 + 20
        cells = monitoring.get_metric(
            "/stf/data/elements").snapshot()["cells"]
        assert any(k.startswith("tfrecord") for k in cells)
        assert any(k.startswith("pmap") for k in cells)
        assert any(k.startswith("prefetch") for k in cells)
        par = monitoring.get_metric(
            "/stf/data/parallelism").snapshot()["cells"]
        assert par  # gauges registered for parallel stages

    def test_worker_spans_land_in_parent_trace(self, tmp_path):
        files = _write_shards(tmp_path, n_shards=2, n_records=5)
        with monitoring.trace_collection() as buf:
            list(stf_data.TFRecordDataset(files, num_parallel_reads=2)
                 .batch(5).parse_example(
                     {"y": po.FixedLenFeature([1], stf.int64)}))
        names = {s["name"] for s in buf.spans}
        assert "data_read_shard" in names
        assert "parse_example_batch" in names

    def test_pipeline_iterator_close_idempotent(self):
        ds = stf_data.Dataset.from_tensor_slices(
            np.arange(100)).prefetch(2)
        it = iter(ds)
        assert int(next(it)) == 0
        it.close()
        it.close()
        with pytest.raises(StopIteration):
            next(it)


class TestSharedPoolNoDeadlock:
    def test_two_unordered_stages_saturating_pool(self):
        """Regression: unordered-map completion callbacks used to block
        in ring.put ON POOL WORKER THREADS; once the ring filled, up to
        pool_size callbacks parked and occupied every worker, so a
        second pool-using stage could never execute and the pipeline
        hung permanently. Callbacks must never block."""
        import threading
        import time

        from simple_tensorflow_tpu.data import pipeline as pl

        p = pl.pool_size()
        n = 6 * p + 40

        def slow_double(x):
            time.sleep(0.002)
            return x * 2

        ds = (stf_data.Dataset.from_tensor_slices(np.arange(n))
              .map(lambda x: x + 1, num_parallel_calls=p,
                   deterministic=False)
              .map(slow_double, num_parallel_calls=2, deterministic=False))
        got = []

        def consume():
            for x in ds:
                got.append(int(x))

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        t.join(timeout=60)
        assert not t.is_alive(), "pipeline deadlocked (pool wedged)"
        assert sorted(got) == [(i + 1) * 2 for i in range(n)]


class TestArenaBatchAssembly:
    def test_batch_assembles_into_arena_slots(self):
        """The zero-copy handoff: a batch node with an alloc_pool stacks
        straight into C++ arena memory (pipeline.ArenaBatch carries the
        slot for post-transfer recycling)."""
        from simple_tensorflow_tpu.data import pipeline as pl
        from simple_tensorflow_tpu.runtime import native

        if not native.available():
            pytest.skip("native runtime not built")
        ds = stf_data.Dataset.from_tensor_slices(
            np.arange(24, dtype=np.float32)).batch(4)
        pool = native.ArenaPool(slots=8)
        node = pl.Node("batch", ds._node.parent, ds._node.args)
        node.alloc_pool = pool
        out = list(pl.build_iterator(node, sequential=True))
        assert all(isinstance(b, pl.ArenaBatch) for b in out)
        for i, b in enumerate(out):
            np.testing.assert_array_equal(
                b.value, np.arange(i * 4, i * 4 + 4, dtype=np.float32))
        pool.close()

    def test_padded_batch_not_arena_flagged(self):
        """Regression: prefetch_to_device keyed arena direct-assembly on
        node kind "batch"; padded_batch shares that kind but its stack
        fn ignores the allocator, so slots were acquired and transfer-
        gated while the batch was built in ordinary memory. Only
        alloc-capable stack fns may be cloned with an alloc_pool."""
        from simple_tensorflow_tpu.data.dataset import _stack_batch

        assert _stack_batch.supports_alloc is True
        b = stf_data.Dataset.from_tensor_slices(np.arange(8)).batch(4)
        sb = stf_data.Dataset.from_tensor_slices(np.arange(8)).superbatch(2)
        pb = stf_data.Dataset.from_tensor_slices(
            np.arange(8)).padded_batch(4)
        assert getattr(b._node.args[2], "supports_alloc", False)
        assert getattr(sb._node.args[2], "supports_alloc", False)
        assert not getattr(pb._node.args[2], "supports_alloc", False)


class TestCompileCacheWiring:
    def test_config_param_and_env(self, tmp_path, monkeypatch):
        import jax

        try:
            cache_dir = str(tmp_path / "cc")
            cfg = stf.ConfigProto(compile_cache_dir=cache_dir)
            with stf.Session(config=cfg):
                pass
            assert os.path.isdir(cache_dir)
            assert jax.config.jax_compilation_cache_dir == cache_dir
            env_dir = str(tmp_path / "env_cc")
            monkeypatch.setenv("STF_COMPILE_CACHE", env_dir)
            with stf.Session():
                pass
            assert jax.config.jax_compilation_cache_dir == env_dir
        finally:
            # tmp_path is deleted after the test — don't leave the
            # process-global cache pointing into it
            jax.config.update("jax_compilation_cache_dir", None)
