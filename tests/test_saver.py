"""Saver round-trips (mirrors ref saver_test.py, SURVEY §4)."""

import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


class TestSaver:
    def test_save_restore_roundtrip(self, tmp_path):
        v = stf.Variable(stf.constant([1.0, 2.0]), name="v")
        w = stf.Variable(stf.constant(3.0), name="w")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "model"))
            sess.run(stf.assign(v, stf.constant([9.0, 9.0])))
            sess.run(stf.assign(w, stf.constant(9.0)))
            saver.restore(sess, path)
            assert sess.run(v.value()).tolist() == [1.0, 2.0]
            assert float(sess.run(w.value())) == 3.0

    def test_restore_into_fresh_session(self, tmp_path):
        v = stf.Variable(stf.constant([5.0]), name="rv")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        with stf.Session() as sess2:
            saver.restore(sess2, path)  # no initializer needed
            assert sess2.run(v.value()).tolist() == [5.0]

    def test_global_step_suffix_and_latest(self, tmp_path):
        v = stf.Variable(stf.zeros([]), name="gs_v")
        gs = stf.train.get_or_create_global_step()
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            p1 = saver.save(sess, str(tmp_path / "ck"), global_step=gs)
            sess.run(stf.assign_add(gs, stf.constant(5, stf.int64)))
            p2 = saver.save(sess, str(tmp_path / "ck"), global_step=gs)
        assert p1.endswith("-0") and p2.endswith("-5")
        assert stf.train.latest_checkpoint(str(tmp_path)) == p2

    def test_max_to_keep(self, tmp_path):
        stf.Variable(stf.zeros([]), name="k_v")
        saver = stf.train.Saver(max_to_keep=2)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            paths = [saver.save(sess, str(tmp_path / "ck"), global_step=i)
                     for i in range(4)]
        # first two deleted, last two kept
        assert not any(os.path.exists(p + ".stfckpt") or
                       os.path.exists(p) or
                       any(f.startswith(os.path.basename(p))
                           for f in os.listdir(tmp_path))
                       for p in paths[:1])
        assert stf.train.latest_checkpoint(str(tmp_path)) == paths[-1]

    def test_var_list_subset(self, tmp_path):
        a = stf.Variable(stf.constant(1.0), name="sub_a")
        b = stf.Variable(stf.constant(2.0), name="sub_b")
        saver = stf.train.Saver(var_list=[a])
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "s"))
            sess.run(stf.assign(a, stf.constant(7.0)))
            sess.run(stf.assign(b, stf.constant(7.0)))
            saver.restore(sess, path)
            assert float(sess.run(a.value())) == 1.0
            assert float(sess.run(b.value())) == 7.0  # untouched

    def test_name_remap(self, tmp_path):
        a = stf.Variable(stf.constant([4.0]), name="orig")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        stf.reset_default_graph()
        b = stf.Variable(stf.zeros([1]), name="renamed")
        restorer = stf.train.Saver(var_list={"orig": b})
        with stf.Session() as sess:
            restorer.restore(sess, path)
            assert sess.run(b.value()).tolist() == [4.0]


class TestCheckpointUtils:
    def test_list_variables_and_load(self, tmp_path):
        stf.Variable(stf.constant([[1.0, 2.0]]), name="lv")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        from simple_tensorflow_tpu.train import checkpoint_utils

        names = dict(checkpoint_utils.list_variables(path))
        assert "lv" in names and names["lv"] == [1, 2]
        reader = checkpoint_utils.load_checkpoint(path)
        np.testing.assert_allclose(reader.get_tensor("lv"), [[1.0, 2.0]])

    def test_init_from_checkpoint(self, tmp_path):
        stf.Variable(stf.constant([8.0]), name="src")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        stf.reset_default_graph()
        dst = stf.Variable(stf.zeros([1]), name="dst")
        from simple_tensorflow_tpu.train import checkpoint_utils

        checkpoint_utils.init_from_checkpoint(path, {"src": dst})
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(dst.value()).tolist() == [8.0]


class TestSaverWithOptimizerState:
    def test_slots_roundtrip(self, tmp_path):
        v = stf.Variable(stf.constant([1.0]), name="ov")
        loss = stf.reduce_sum(stf.square(v._ref))
        train = stf.train.AdamOptimizer(0.1).minimize(loss)
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(3):
                sess.run(train)
            val3 = sess.run(v.value())
            path = saver.save(sess, str(tmp_path / "m"))
            for _ in range(2):
                sess.run(train)
            val5 = sess.run(v.value())
            saver.restore(sess, path)
            for _ in range(2):
                sess.run(train)
            val5_replay = sess.run(v.value())
        # deterministic replay incl. Adam m/v slots
        np.testing.assert_allclose(val5, val5_replay, rtol=1e-6)
        assert not np.allclose(val3, val5)
