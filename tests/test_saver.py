"""Saver round-trips (mirrors ref saver_test.py, SURVEY §4)."""

import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


class TestSaver:
    def test_save_restore_roundtrip(self, tmp_path):
        v = stf.Variable(stf.constant([1.0, 2.0]), name="v")
        w = stf.Variable(stf.constant(3.0), name="w")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "model"))
            sess.run(stf.assign(v, stf.constant([9.0, 9.0])))
            sess.run(stf.assign(w, stf.constant(9.0)))
            saver.restore(sess, path)
            assert sess.run(v.value()).tolist() == [1.0, 2.0]
            assert float(sess.run(w.value())) == 3.0

    def test_restore_into_fresh_session(self, tmp_path):
        v = stf.Variable(stf.constant([5.0]), name="rv")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        with stf.Session() as sess2:
            saver.restore(sess2, path)  # no initializer needed
            assert sess2.run(v.value()).tolist() == [5.0]

    def test_global_step_suffix_and_latest(self, tmp_path):
        v = stf.Variable(stf.zeros([]), name="gs_v")
        gs = stf.train.get_or_create_global_step()
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            p1 = saver.save(sess, str(tmp_path / "ck"), global_step=gs)
            sess.run(stf.assign_add(gs, stf.constant(5, stf.int64)))
            p2 = saver.save(sess, str(tmp_path / "ck"), global_step=gs)
        assert p1.endswith("-0") and p2.endswith("-5")
        assert stf.train.latest_checkpoint(str(tmp_path)) == p2

    def test_max_to_keep(self, tmp_path):
        stf.Variable(stf.zeros([]), name="k_v")
        saver = stf.train.Saver(max_to_keep=2)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            paths = [saver.save(sess, str(tmp_path / "ck"), global_step=i)
                     for i in range(4)]
        # first two deleted, last two kept
        assert not any(os.path.exists(p + ".stfckpt") or
                       os.path.exists(p) or
                       any(f.startswith(os.path.basename(p))
                           for f in os.listdir(tmp_path))
                       for p in paths[:1])
        assert stf.train.latest_checkpoint(str(tmp_path)) == paths[-1]

    def test_var_list_subset(self, tmp_path):
        a = stf.Variable(stf.constant(1.0), name="sub_a")
        b = stf.Variable(stf.constant(2.0), name="sub_b")
        saver = stf.train.Saver(var_list=[a])
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "s"))
            sess.run(stf.assign(a, stf.constant(7.0)))
            sess.run(stf.assign(b, stf.constant(7.0)))
            saver.restore(sess, path)
            assert float(sess.run(a.value())) == 1.0
            assert float(sess.run(b.value())) == 7.0  # untouched

    def test_name_remap(self, tmp_path):
        a = stf.Variable(stf.constant([4.0]), name="orig")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        stf.reset_default_graph()
        b = stf.Variable(stf.zeros([1]), name="renamed")
        restorer = stf.train.Saver(var_list={"orig": b})
        with stf.Session() as sess:
            restorer.restore(sess, path)
            assert sess.run(b.value()).tolist() == [4.0]


class TestCheckpointUtils:
    def test_list_variables_and_load(self, tmp_path):
        stf.Variable(stf.constant([[1.0, 2.0]]), name="lv")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        from simple_tensorflow_tpu.train import checkpoint_utils

        names = dict(checkpoint_utils.list_variables(path))
        assert "lv" in names and names["lv"] == [1, 2]
        reader = checkpoint_utils.load_checkpoint(path)
        np.testing.assert_allclose(reader.get_tensor("lv"), [[1.0, 2.0]])

    def test_init_from_checkpoint(self, tmp_path):
        stf.Variable(stf.constant([8.0]), name="src")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        stf.reset_default_graph()
        dst = stf.Variable(stf.zeros([1]), name="dst")
        from simple_tensorflow_tpu.train import checkpoint_utils

        checkpoint_utils.init_from_checkpoint(path, {"src": dst})
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(dst.value()).tolist() == [8.0]


class TestSaverWithOptimizerState:
    def test_slots_roundtrip(self, tmp_path):
        v = stf.Variable(stf.constant([1.0]), name="ov")
        loss = stf.reduce_sum(stf.square(v._ref))
        train = stf.train.AdamOptimizer(0.1).minimize(loss)
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(3):
                sess.run(train)
            val3 = sess.run(v.value())
            path = saver.save(sess, str(tmp_path / "m"))
            for _ in range(2):
                sess.run(train)
            val5 = sess.run(v.value())
            saver.restore(sess, path)
            for _ in range(2):
                sess.run(train)
            val5_replay = sess.run(v.value())
        # deterministic replay incl. Adam m/v slots
        np.testing.assert_allclose(val5, val5_replay, rtol=1e-6)
        assert not np.allclose(val3, val5)


class TestOrbaxBackend:
    def test_sharded_roundtrip_preserves_sharding(self, tmp_path):
        """8-device mesh: save sharded variables via orbax, restore into a
        fresh session with the shardings intact — no host gather."""
        from simple_tensorflow_tpu import parallel

        mesh = parallel.Mesh({"tp": 8})
        with mesh:
            w = stf.Variable(stf.random_normal([16, 8], seed=3), name="ow")
            parallel.shard_variable(w, "tp", None)
            b = stf.Variable(stf.zeros([8]), name="ob")
            saver = stf.train.Saver(backend="orbax")
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                w0 = np.asarray(sess.run(w.value()))
                arr = sess._variable_store.values["ow"]
                assert len(arr.sharding.device_set) == 8
                path = saver.save(sess, str(tmp_path / "om"))
            assert os.path.isdir(path + ".orbax")
            assert not os.path.exists(path + ".stfz")  # no npz host bundle
            with stf.Session() as sess2:
                saver.restore(sess2, path)
                arr2 = sess2._variable_store.values["ow"]
                # restored straight into the mesh sharding, not replicated
                assert len(arr2.sharding.device_set) == 8
                assert np.allclose(np.asarray(sess2.run(w.value())), w0)
        assert stf.train.latest_checkpoint(str(tmp_path)) == path

    def test_bad_backend_rejected(self):
        with pytest.raises(ValueError):
            stf.train.Saver(backend="protobuf")


class TestHostStateResume:
    def test_rng_stream_resumes_identically(self, tmp_path):
        """Dropout masks after restore must equal the masks the original
        run would have produced (SURVEY §5 RNG-key resume)."""
        x = stf.constant(np.ones((4, 64), np.float32))
        y = stf.nn.dropout(x, keep_prob=0.5)
        v = stf.Variable(stf.constant(1.0), name="hv")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(y)  # advance the RNG stream
            path = saver.save(sess, str(tmp_path / "h"))
            expected = [np.asarray(sess.run(y)) for _ in range(3)]
        with stf.Session() as sess2:
            saver.restore(sess2, path)
            resumed = [np.asarray(sess2.run(y)) for _ in range(3)]
        for a, b in zip(expected, resumed):
            assert np.array_equal(a, b)

    def test_iterator_position_resumes(self, tmp_path):
        from simple_tensorflow_tpu import data as stf_data

        ds = stf_data.Dataset.from_tensor_slices(
            np.arange(10, dtype=np.int32)).repeat()
        it = ds.make_one_shot_iterator()
        nxt = it.get_next()
        v = stf.Variable(stf.constant(0.0), name="iv")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            seen = [int(sess.run(nxt)) for _ in range(4)]
            assert seen == [0, 1, 2, 3]
            path = saver.save(sess, str(tmp_path / "it"))
            assert int(sess.run(nxt)) == 4
        with stf.Session() as sess2:
            saver.restore(sess2, path)
            assert int(sess2.run(nxt)) == 4  # resumes where save happened


class TestAtomicCheckpointWrites:
    """ISSUE 10 satellite: the .stfz/.index.json writers and
    update_checkpoint_state commit through temp+fsync+os.replace with a
    content checksum in the index."""

    def test_index_carries_checksum_and_sharding_fields(self, tmp_path):
        stf.Variable(stf.constant([1.0]), name="at_v")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        import json

        doc = json.load(open(path + ".index.json"))
        assert doc["version"] >= 2
        assert doc["checksum"].startswith("sha256:")
        assert doc["data_bytes"] == os.path.getsize(path + ".stfz")
        assert "sharding" in doc["tensors"]["at_v"]
        from simple_tensorflow_tpu.checkpoint import atomic

        assert atomic.checksum_file(path + ".stfz") == doc["checksum"]

    def test_interrupted_state_update_keeps_previous_pointer(
            self, tmp_path):
        from simple_tensorflow_tpu.checkpoint import atomic

        stf.Variable(stf.constant([1.0]), name="sp_v")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            p1 = saver.save(sess, str(tmp_path / "ck"), global_step=1)

            def boom(point):
                if point == "state:synced_tmp":
                    raise OSError("yanked mid-commit")

            atomic.set_fault_hook(boom)
            try:
                with pytest.raises(OSError):
                    saver.save(sess, str(tmp_path / "ck"), global_step=2)
            finally:
                atomic.set_fault_hook(None)
        # the step-2 bundle is on disk, but the pointer never moved —
        # and it still parses (no truncated JSON)
        assert stf.train.latest_checkpoint(str(tmp_path)) == p1
        assert stf.train.get_checkpoint_state(str(tmp_path)) is not None

    def test_restore_rejects_corrupted_bundle(self, tmp_path):
        v = stf.Variable(stf.constant([3.0]), name="cr_v")
        saver = stf.train.Saver()
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            path = saver.save(sess, str(tmp_path / "m"))
        with open(path + ".stfz", "r+b") as f:
            f.seek(20)
            b = f.read(1)
            f.seek(20)
            f.write(bytes([b[0] ^ 0xFF]))
        with stf.Session() as sess2:
            with pytest.raises(stf.errors.DataLossError):
                saver.restore(sess2, path)


class TestKeepEveryNHours:
    def test_keep_forever_based_on_checkpoint_time(self, tmp_path, monkeypatch):
        """ref semantics: a checkpoint whose save time crosses the keep
        interval is kept forever when evicted; others are deleted."""
        import simple_tensorflow_tpu.train.saver as saver_mod

        t = [1000.0]
        monkeypatch.setattr(saver_mod.time, "time", lambda: t[0])
        v = stf.Variable(stf.constant(1.0), name="kv")
        saver = stf.train.Saver(max_to_keep=1,
                                keep_checkpoint_every_n_hours=1.0)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            p1 = saver.save(sess, str(tmp_path / "ck"), global_step=1)
            t[0] += 1800.0  # p1 evicted next save: 1000 < 4600 -> delete
            p2 = saver.save(sess, str(tmp_path / "ck"), global_step=2)
            t[0] += 3600.0  # p2 evicted next save: 2800 < 4600 -> delete
            p3 = saver.save(sess, str(tmp_path / "ck"), global_step=3)
            t[0] += 600.0   # p3 evicted next save: 6400 > 4600 -> keep
            p4 = saver.save(sess, str(tmp_path / "ck"), global_step=4)
        assert not stf.train.checkpoint_exists(p1)  # deleted
        assert not stf.train.checkpoint_exists(p2)  # deleted
        assert stf.train.checkpoint_exists(p3)      # kept forever
        assert stf.train.checkpoint_exists(p4)      # newest
