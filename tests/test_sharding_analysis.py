"""stf.analysis.sharding test matrix (ISSUE 6).

- unit tests per propagation rule (abstract {axis: size} meshes — no
  devices, no Session),
- lint rules (replicated-large-tensor / resharding-hotspot /
  mesh-axis-unused / uneven-shard),
- match_partition_rules (the regex rule -> PartitionSpec seeder),
- Session wiring (per-plan report, RunMetadata.predicted_collectives,
  init plans skipped),
- GOLDEN tests on the 8-way virtual mesh: jit-lowered train steps where
  the analyzer's predicted output shardings must match JAX's committed
  shardings and predicted collective bytes must track XLA's harvested
  cost,
- a fuzz test over random graphs: analyzer-predicted replication must
  imply XLA commits a replicated output sharding (the analyzer may be
  conservative, never optimistic),
- the graph_lint CLI acceptance path (--json --mesh --rules
  --max-severity on a deliberately mis-sharded GraphDef).
"""

import json
import subprocess
import sys

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import analysis, parallel
from simple_tensorflow_tpu.analysis import sharding as shard_mod
from simple_tensorflow_tpu.parallel import P


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield
    stf.reset_default_graph()


def _analyze(mesh, seed_specs=None, fetches=None, graph=None, **kw):
    return analysis.analyze_sharding(
        graph=graph or stf.get_default_graph(), mesh=mesh,
        seed_specs=seed_specs, fetches=fetches, **kw)


def _edges(rep, kind=None):
    es = rep.collective_edges()
    if kind is not None:
        es = [e for e in es if e.kind == kind]
    return es


def _codes(rep):
    return {d.code for d in rep.diagnostics}


DP8 = {"dp": 8}


# ---------------------------------------------------------------------------
# spec algebra
# ---------------------------------------------------------------------------

class TestSpecAlgebra:
    def test_normalize_and_display(self):
        n = shard_mod.normalize_spec(P("dp", None), 3)
        assert n == (("dp",), (), ())
        assert shard_mod.to_partition_spec(n) == ("dp", None, None)
        assert shard_mod.format_spec(n) == "P(dp, None, None)"
        assert shard_mod.normalize_spec(None, 2) == ((), ())
        assert shard_mod.normalize_spec(("dp",), 1) == (("dp",),)
        # multi-axis entry
        assert shard_mod.normalize_spec((("dp", "tp"),), 1) == \
            (("dp", "tp"),)

    def test_dedupe_axes_first_occurrence_wins(self):
        assert shard_mod._dedupe_axes((("dp",), ("dp",), ())) == \
            (("dp",), (), ())

    def test_shard_factor(self):
        axes = {"dp": 8, "tp": 4}
        assert shard_mod.shard_factor((("dp",), ("tp",)), axes) == 32
        assert shard_mod.shard_factor(((), ()), axes) == 1
        assert shard_mod.shard_factor(None, axes) == 1

    def test_parse_mesh_arg(self):
        assert shard_mod.parse_mesh_arg("8") == {"dp": 8}
        assert shard_mod.parse_mesh_arg("2x4") == {"dp": 2, "tp": 4}
        assert shard_mod.parse_mesh_arg("dp=2,tp=4") == {"dp": 2,
                                                        "tp": 4}
        with pytest.raises(ValueError):
            shard_mod.parse_mesh_arg("2x2x2x2x2")


# ---------------------------------------------------------------------------
# propagation rules (abstract mesh, no devices)
# ---------------------------------------------------------------------------

class TestPropagationRules:
    def test_elementwise_broadcast_carries_sharding(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        b = stf.placeholder(stf.float32, [8], name="b")
        y = x + b
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert rep.spec_of(y) == ("dp", None)
        assert _edges(rep) == []  # broadcast needs no comms

    def test_elementwise_conflict_joins_replicated(self):
        x = stf.placeholder(stf.float32, [16, 16], name="x")
        y = stf.placeholder(stf.float32, [16, 16], name="y")
        z = x + y
        rep = _analyze({"dp": 4, "tp": 2},
                       seed_specs={"x": ("dp", None),
                                   "y": ("tp", None)})
        assert rep.spec_of(z) == (None, None)
        assert "sharding/conflict" in _codes(rep)

    def test_matmul_batch_sharded(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        w = stf.placeholder(stf.float32, [8, 4], name="w")
        y = stf.matmul(x, w)
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert rep.spec_of(y) == ("dp", None)
        assert _edges(rep) == []

    def test_matmul_contracted_sharded_implies_allreduce(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        w = stf.placeholder(stf.float32, [8, 4], name="w")
        y = stf.matmul(x, w)
        rep = _analyze(DP8, seed_specs={"x": (None, "dp"),
                                        "w": ("dp", None)})
        ar = _edges(rep, "all-reduce")
        assert len(ar) == 1
        assert ar[0].axes == ("dp",)
        assert ar[0].nbytes == 16 * 4 * 4  # output replicated

    def test_matmul_tp_output_sharding(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        w = stf.placeholder(stf.float32, [8, 32], name="w")
        y = stf.matmul(x, w)
        rep = _analyze({"dp": 4, "tp": 2},
                       seed_specs={"x": ("dp", None),
                                   "w": (None, "tp")})
        assert rep.spec_of(y) == ("dp", "tp")
        assert _edges(rep) == []

    def test_reduce_over_sharded_dim(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        s = stf.reduce_sum(x, axis=0)
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert rep.spec_of(s) == (None,)
        ar = _edges(rep, "all-reduce")
        assert len(ar) == 1 and ar[0].nbytes == 8 * 4

    def test_reduce_over_unsharded_dim_keeps_sharding(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        s = stf.reduce_sum(x, axis=1)
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert rep.spec_of(s) == ("dp",)
        assert _edges(rep) == []

    def test_transpose_permutes_spec(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        t = stf.transpose(x)
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert rep.spec_of(t) == (None, "dp")

    def test_reshape_carries_outer_factor(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        r = stf.reshape(x, [16, 2, 4])
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert rep.spec_of(r) == ("dp", None, None)

    def test_reshape_murky_gathers(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        r = stf.reshape(x, [8, 16])
        rep = _analyze(DP8, seed_specs={"x": (None, "dp")})
        assert rep.spec_of(r) == (None, None)
        assert "sharding/reshape-gather" in _codes(rep)
        assert _edges(rep, "all-gather")

    def test_concat_along_sharded_dim_gathers(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        y = stf.placeholder(stf.float32, [16, 8], name="y")
        c = stf.concat([x, y], axis=0)
        rep = _analyze(DP8, seed_specs={"x": ("dp", None),
                                        "y": ("dp", None)})
        assert rep.spec_of(c) == (None, None)
        assert len(_edges(rep, "all-gather")) == 2

    def test_concat_along_other_dim_keeps_sharding(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        y = stf.placeholder(stf.float32, [16, 8], name="y")
        c = stf.concat([x, y], axis=1)
        rep = _analyze(DP8, seed_specs={"x": ("dp", None),
                                        "y": ("dp", None)})
        assert rep.spec_of(c) == ("dp", None)
        assert _edges(rep) == []

    def test_gather_vocab_sharded_implies_allreduce(self):
        emb = stf.placeholder(stf.float32, [64, 16], name="emb")
        ids = stf.placeholder(stf.int32, [8], name="ids")
        g = stf.gather(emb, ids)
        rep = _analyze(DP8, seed_specs={"emb": ("dp", None)})
        assert rep.spec_of(g) == (None, None)
        assert _edges(rep, "all-reduce")

    def test_conv_batch_passthrough_spatial_gathered(self):
        x = stf.placeholder(stf.float32, [8, 8, 8, 3], name="x")
        w = stf.placeholder(stf.float32, [3, 3, 3, 4], name="w")
        y = stf.nn.conv2d(x, w, strides=[1, 1, 1, 1], padding="SAME")
        rep = _analyze(DP8, seed_specs={"x": ("dp", None, None, None)})
        assert rep.spec_of(y) == ("dp", None, None, None)
        assert _edges(rep) == []
        # sharded spatial dim is consumed gathered
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [8, 8, 8, 3], name="x")
        w = stf.placeholder(stf.float32, [3, 3, 3, 4], name="w")
        y = stf.nn.conv2d(x, w, strides=[1, 1, 1, 1], padding="SAME")
        rep = _analyze(DP8, seed_specs={"x": (None, "dp", None, None)})
        assert _edges(rep, "all-gather")

    def test_softmax_sharded_class_dim_small_allreduce(self):
        x = stf.placeholder(stf.float32, [16, 32], name="x")
        s = stf.nn.softmax(x)
        rep = _analyze(DP8, seed_specs={"x": (None, "dp")})
        assert rep.spec_of(s) == (None, "dp")
        ar = _edges(rep, "all-reduce")
        assert len(ar) == 1
        assert ar[0].nbytes < 16 * 32 * 4  # stats, not the tensor

    def test_slice_changed_dim_loses_sharding(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        s = x[:8]
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert rep.spec_of(s) == (None, None)
        assert _edges(rep, "all-gather")

    def test_stack_unstack(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        y = stf.placeholder(stf.float32, [16, 8], name="y")
        st = stf.stack([x, y])
        rep = _analyze(DP8, seed_specs={"x": ("dp", None),
                                        "y": ("dp", None)})
        assert rep.spec_of(st) == (None, "dp", None)

    def test_assign_commits_variable_sharding(self):
        v = stf.get_variable("w", [16, 8],
                             initializer=stf.zeros_initializer())
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        a = stf.assign(v, x)
        rep = _analyze(DP8, seed_specs={"w": ("dp", None)})
        assert rep.spec_of(a) == ("dp", None)
        # replicated value resharding into the sharded variable is a
        # local slice (no wire traffic), not a gather
        assert _edges(rep, "slice") or _edges(rep) == []

    def test_einsum_contraction(self):
        a = stf.placeholder(stf.float32, [16, 8], name="a")
        b = stf.placeholder(stf.float32, [8, 4], name="b")
        y = stf.einsum("ij,jk->ik", a, b)
        rep = _analyze(DP8, seed_specs={"a": (None, "dp"),
                                        "b": ("dp", None)})
        assert _edges(rep, "all-reduce")

    def test_sharding_constraint_seeds_both_directions(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        y = x * 2.0
        z = parallel.with_sharding_constraint(y, "dp", None)
        w = z + 1.0
        rep = _analyze(DP8)
        assert rep.spec_of(z) == ("dp", None)
        assert rep.spec_of(w) == ("dp", None)     # forward
        assert rep.spec_of(x) == ("dp", None)     # backward sweep

    def test_no_rule_conservative_gather_and_note(self):
        from simple_tensorflow_tpu.framework import op_registry

        if not op_registry.is_registered("ShardingTestRulelessOp"):
            op_registry.register("ShardingTestRulelessOp",
                                 lower=lambda ctx, op, inputs: inputs)
        g = stf.get_default_graph()
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        op = g.create_op("ShardingTestRulelessOp", [x], name="unk",
                         output_specs=[(x.shape, x.dtype)])
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert "sharding/no-rule" in _codes(rep)
        assert rep.spec_of(op.outputs[0]) == (None, None)
        assert _edges(rep, "all-gather")

    def test_rule_registered_alongside_op_registry(self):
        from simple_tensorflow_tpu.framework import op_registry

        assert op_registry.sharding_rule("MatMul") is not None
        assert op_registry.sharding_rule("Conv2D") is not None
        assert op_registry.sharding_rule("NoSuchOpType") is None


class TestControlFlow:
    def test_while_body_reshard_is_trip_weighted_hotspot(self):
        v = stf.get_variable("w", [64, 64],
                             initializer=stf.zeros_initializer())
        x = stf.placeholder(stf.float32, [8, 64], name="x")

        def cond(i, y):
            return stf.less(i, 8)

        def body(i, y):
            return i + 1, stf.matmul(y, v.value())

        _, yn = stf.while_loop(cond, body, [stf.constant(0), x],
                               maximum_iterations=8)
        rep = _analyze(DP8, seed_specs={"w": ("dp", None)})
        gathers = [e for e in _edges(rep) if e.in_loop]
        assert gathers, "expected an in-loop collective edge"
        assert all(e.trip == 8 for e in gathers)
        assert "lint/resharding-hotspot" in _codes(rep)

    def test_nonconverging_carry_records_edges_once(self):
        """Regression: a carry whose spec changes during the fixpoint
        (round 2 re-analyzes the body) must not double-record the
        body's collective edges — only the final sweep records."""
        x = stf.placeholder(stf.float32, [16, 8], name="x")

        def cond(i, y):
            return stf.less(i, 4)

        def body(i, y):
            y2 = parallel.with_sharding_constraint(y, "dp", None)
            s = stf.reduce_sum(y2, axis=0, keepdims=True)
            return i + 1, y2 + s

        _, yn = stf.while_loop(cond, body, [stf.constant(0), x],
                               maximum_iterations=4)
        rep = _analyze(DP8)  # carry: replicated -> dp after round 1
        assert rep.spec_of(yn) == ("dp", None)
        ar = [e for e in _edges(rep, "all-reduce") if e.in_loop]
        assert len(ar) == 1, [e.to_dict() for e in ar]
        assert ar[0].trip == 4

    def test_scan_carry_fixpoint(self):
        xs = stf.placeholder(stf.float32, [4, 16, 8], name="xs")
        init = stf.placeholder(stf.float32, [16, 8], name="init")
        from simple_tensorflow_tpu.ops import functional_ops

        out = functional_ops.scan(lambda c, e: c + e, xs,
                                  initializer=init)
        rep = _analyze(DP8, seed_specs={"init": ("dp", None),
                                        "xs": (None, "dp", None)})
        # stacked output regains the leading iteration dim
        assert rep.spec_of(out) == (None, "dp", None)
        assert _edges(rep) == []

    def test_cond_branches_join(self):
        p = stf.placeholder(stf.bool, [], name="p")
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        y = stf.cond(p, lambda: x * 2.0, lambda: x + 1.0)
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        assert rep.spec_of(y) == ("dp", None)


class TestLintRules:
    def test_replicated_large_tensor(self):
        stf.get_variable("big", [1024, 512],
                         initializer=stf.zeros_initializer())  # 2 MiB
        stf.get_variable("small", [4, 4],
                         initializer=stf.zeros_initializer())
        rep = _analyze(DP8)
        msgs = [d for d in rep.diagnostics
                if d.code == "lint/replicated-large-tensor"]
        assert len(msgs) == 1
        assert "big" in msgs[0].message

    def test_replicated_large_tensor_quiet_when_sharded(self):
        stf.get_variable("big", [1024, 512],
                         initializer=stf.zeros_initializer())
        rep = _analyze(DP8, seed_specs={"big": ("dp", None)})
        assert "lint/replicated-large-tensor" not in _codes(rep)

    def test_replicated_large_tensor_quiet_on_one_device(self):
        stf.get_variable("big", [1024, 512],
                         initializer=stf.zeros_initializer())
        rep = _analyze({"dp": 1})
        assert "lint/replicated-large-tensor" not in _codes(rep)

    def test_mesh_axis_unused(self):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        _ = x * 2.0
        rep = _analyze({"dp": 4, "tp": 2}, seed_specs={"x": ("dp",
                                                             None)})
        msgs = [d for d in rep.diagnostics
                if d.code == "lint/mesh-axis-unused"]
        assert len(msgs) == 1 and "'tp'" in msgs[0].message

    def test_uneven_shard(self):
        x = stf.placeholder(stf.float32, [12, 8], name="x")  # 12 % 8
        _ = x * 2.0
        rep = _analyze(DP8, seed_specs={"x": ("dp", None)})
        msgs = [d for d in rep.diagnostics
                if d.code == "lint/uneven-shard"]
        assert msgs and "padding" in msgs[0].message


class TestMatchPartitionRules:
    def _vars(self):
        a = stf.get_variable("encoder/attn/wq", [64, 64],
                             initializer=stf.zeros_initializer())
        b = stf.get_variable("encoder/mlp/kernel", [64, 256],
                             initializer=stf.zeros_initializer())
        c = stf.get_variable("global_step", [],
                             initializer=stf.zeros_initializer(),
                             dtype=stf.int64)
        return a, b, c

    def test_first_match_wins_and_scalars_replicate(self):
        self._vars()
        specs = parallel.match_partition_rules(
            [(r"attn/w[qkv]", P(None, "tp")),
             (r"mlp/kernel", P(None, "tp")),
             (r".*", P())])
        assert specs["encoder/attn/wq"] == P(None, "tp")
        assert specs["encoder/mlp/kernel"] == P(None, "tp")
        assert specs["global_step"] == P()

    def test_on_missing_modes(self):
        self._vars()
        with pytest.raises(ValueError, match="no rule matches"):
            parallel.match_partition_rules([(r"attn", P(None, "tp"))],
                                           on_missing="error")
        out = parallel.match_partition_rules(
            [(r"attn/w[qkv]", P(None, "tp"))], on_missing="skip")
        assert "encoder/mlp/kernel" not in out
        out = parallel.match_partition_rules(
            [(r"attn/w[qkv]", P(None, "tp"))], on_missing="replicate")
        assert out["encoder/mlp/kernel"] == P()

    def test_apply_commits_to_variables(self):
        a, b, _ = self._vars()
        parallel.match_partition_rules(
            [(r"attn/w[qkv]", P(None, "tp"))], apply=True)
        assert tuple(a.sharding) == (None, "tp")

    def test_rules_feed_analyzer_as_seeds(self):
        a, b, _ = self._vars()
        x = stf.placeholder(stf.float32, [16, 64], name="x")
        y = stf.matmul(x, a.value())
        specs = parallel.match_partition_rules(
            [(r"attn/w[qkv]", P(None, "tp"))])
        rep = _analyze({"dp": 4, "tp": 2}, seed_specs=specs)
        assert rep.spec_of(y) == (None, "tp")


# ---------------------------------------------------------------------------
# Session wiring + golden committed shardings (8-device virtual mesh)
# ---------------------------------------------------------------------------

def _traced_run(sess, fetches, feed):
    opts = stf.RunOptions(trace_level=stf.RunOptions.SOFTWARE_TRACE)
    md = stf.RunMetadata()
    vals = sess.run(fetches, feed_dict=feed, options=opts,
                    run_metadata=md)
    # the analysis overlaps compile on a worker thread; join for asserts
    steps = [s for s in sess._cache.values()
             if s.join_sharding() is not None]
    assert steps, "no plan carried a sharding report"
    return vals, md, steps[-1]


def _assert_fetches_match_committed(step, mesh):
    """Analyzer-predicted device-fetch specs == JAX committed output
    shardings of the AOT-compiled executable."""
    import jax

    if step.compiled is None:
        pytest.skip("AOT compile path unavailable")
    fetch_shardings = step.compiled.output_shardings[0]
    rep = step.sharding_report
    checked = 0
    for t, sh in zip(step.device_fetches, fetch_shardings):
        pred = rep.spec_of(t)
        if pred is None:
            continue
        expected = jax.sharding.NamedSharding(
            mesh.jax_mesh, jax.sharding.PartitionSpec(*pred))
        assert sh.is_equivalent_to(expected, len(pred)), (
            f"{t.name}: predicted {pred}, XLA committed {sh}")
        checked += 1
    return checked


class TestSessionWiring:
    def test_plan_report_and_run_metadata(self):
        mesh = parallel.Mesh(DP8)
        with mesh:
            x = stf.placeholder(stf.float32, [16, 8], name="x")
            parallel.shard_feed(x, "dp")
            w = stf.get_variable("w", [8, 4],
                                 initializer=stf.zeros_initializer())
            loss = stf.reduce_mean(stf.matmul(x, w))
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                # the initializer plan must NOT be sharding-analyzed
                # (no feeds, nothing sharded: every diagnostic would be
                # noise)
                assert all(s.sharding_report is None
                           and s.sharding_thread is None
                           for s in sess._cache.values())
                _, md, step = _traced_run(
                    sess, loss,
                    {x: np.ones((16, 8), np.float32)})
                rep = step.sharding_report
                assert rep.mesh_axes == {"dp": 8}
                pc = md.cost_graph["predicted_collectives"]
                assert pc["total_bytes"] == rep.total_collective_bytes()
                assert pc["per_op"]
                # harvested comparator present under SOFTWARE_TRACE
                assert "collective_bytes" in md.cost_graph

    def test_no_mesh_no_report(self):
        x = stf.placeholder(stf.float32, [4], name="x")
        y = x * 2.0
        with stf.Session() as sess:
            sess.run(y, feed_dict={x: np.ones(4, np.float32)})
            assert all(s.sharding_report is None
                       for s in sess._cache.values())

    def test_sharding_metrics_counted(self):
        from simple_tensorflow_tpu import monitoring

        before = monitoring.get_metric(
            "/stf/analysis/sharding_collectives")
        n0 = sum(before.snapshot()["cells"].values()) if before else 0
        mesh = parallel.Mesh(DP8)
        with mesh:
            x = stf.placeholder(stf.float32, [16, 8], name="x")
            parallel.shard_feed(x, "dp")
            s = stf.reduce_sum(x, axis=0)
            with stf.Session() as sess:
                sess.run(s, feed_dict={x: np.ones((16, 8),
                                                  np.float32)})
                for st in sess._cache.values():
                    st.join_sharding()
        after = monitoring.get_metric(
            "/stf/analysis/sharding_collectives")
        assert sum(after.snapshot()["cells"].values()) > n0


class TestGoldenCommitted:
    def test_mlp_dp8_train_step(self):
        """dp8 MLP: predicted fetch shardings match committed; predicted
        collective bytes match XLA's harvested bytes (exactly: this
        program's only collectives are the loss + gradient syncs)."""
        mesh = parallel.Mesh(DP8)
        rng = np.random.RandomState(0)
        with mesh:
            x = stf.placeholder(stf.float32, [16, 8], name="x")
            y = stf.placeholder(stf.float32, [16, 4], name="y")
            parallel.shard_feed(x, "dp")
            parallel.shard_feed(y, "dp")
            w1 = stf.get_variable(
                "w1", [8, 32], initializer=stf.zeros_initializer())
            w2 = stf.get_variable(
                "w2", [32, 4], initializer=stf.zeros_initializer())
            h = stf.nn.relu(stf.matmul(x, w1))
            pred = stf.matmul(h, w2)
            loss = stf.reduce_mean(stf.square(pred - y))
            opt = stf.train.GradientDescentOptimizer(0.1)
            train_op = opt.minimize(loss)
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                feed = {x: rng.randn(16, 8).astype(np.float32),
                        y: rng.randn(16, 4).astype(np.float32)}
                _, md, step = _traced_run(sess, [train_op, loss], feed)
                assert _assert_fetches_match_committed(step, mesh) >= 1
                predicted = step.sharding_report \
                    .total_collective_bytes()
                harvested = md.cost_graph.get(
                    "collective_bytes", {}).get("total")
                if harvested:  # backend exposed HLO text
                    assert predicted == pytest.approx(harvested,
                                                      rel=0.25)

    def test_transformer_dp8_train_step(self):
        """Golden satellite: a jit-lowered transformer train step on the
        8-way mesh. Committed output shardings match; the all-reduce
        prediction (gradient/batch-stat sync, the dominant wire cost)
        tracks XLA within 25%. (Total bytes are NOT compared here: XLA
        all-gathers the scan-stacked residuals on its dynamic-slice
        layout choice — resnet, scan-free, pins the total in bench.py.)
        """
        from simple_tensorflow_tpu.models import transformer as tr

        mesh = parallel.Mesh(DP8)
        rng = np.random.RandomState(0)
        with mesh:
            cfg = tr.TransformerConfig.tiny()
            m = tr.transformer_train_model(batch_size=8, src_len=8,
                                           tgt_len=8, cfg=cfg,
                                           compute_dtype=stf.float32)
            for k in ("src_ids", "tgt_in", "tgt_out"):
                parallel.shard_feed(m[k], "dp")
            feed = {
                m["src_ids"]: rng.randint(
                    1, 30, (8, 8)).astype(np.int32),
                m["tgt_in"]: rng.randint(
                    1, 30, (8, 8)).astype(np.int32),
                m["tgt_out"]: rng.randint(
                    1, 30, (8, 8)).astype(np.int32)}
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                _, md, step = _traced_run(
                    sess, [m["train_op"], m["loss"]], feed)
                rep = step.sharding_report
                # every zoo op type must have a rule by now: the fused
                # kernels were the last gaps (FlashAttention &co)
                assert "sharding/no-rule" not in _codes(rep)
                assert _assert_fetches_match_committed(step, mesh) >= 1
                harvested = md.cost_graph.get("collective_bytes", {})
                if harvested.get("all-reduce"):
                    assert rep.bytes_by_kind().get("all-reduce", 0) == \
                        pytest.approx(harvested["all-reduce"], rel=0.25)


class TestGoldenResnet:
    def test_resnet_dp8_train_step(self):
        """Golden satellite: the resnet50 train step on the 8-way mesh
        (the bench config at reduced batch). Committed fetch shardings
        match the prediction and total predicted collective bytes track
        the harvested HLO bytes within 25% (scan-free model: the total
        IS comparable; the bench row pins the full-size config)."""
        from simple_tensorflow_tpu.models import resnet

        mesh = parallel.Mesh(DP8)
        with mesh:
            m = resnet.resnet50_train_model(batch_size=8, image_size=32,
                                            num_classes=10)
            parallel.shard_feed(m["images"], "dp")
            parallel.shard_feed(m["labels"], "dp")
            xv, yv = resnet.synthetic_imagenet(8, 32, dtype=np.float32)
            feed = {m["images"]: xv, m["labels"]: yv}
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                _, md, step = _traced_run(
                    sess, [m["train_op"], m["loss"]], feed)
                rep = step.sharding_report
                assert "sharding/no-rule" not in _codes(rep)
                assert _assert_fetches_match_committed(step, mesh) >= 1
                harvested = md.cost_graph.get(
                    "collective_bytes", {}).get("total")
                if harvested:
                    assert rep.total_collective_bytes() == \
                        pytest.approx(harvested, rel=0.25)


class TestFuzzReplicationSound:
    """Random graphs: wherever the analyzer predicts a REPLICATED device
    fetch, XLA must commit a replicated output sharding. (The analyzer
    is allowed to be conservative — predicting replicated where XLA
    keeps a sharding would fail the golden tests' exact checks but not
    this soundness property; predicting sharded where XLA replicates is
    what this hunts.)"""

    def _random_graph(self, rng):
        x = stf.placeholder(stf.float32, [16, 8], name="x")
        parallel.shard_feed(x, "dp")
        vals = [x]
        for i in range(rng.randint(2, 6)):
            t = vals[rng.randint(len(vals))]
            k = rng.randint(6)
            if k == 0:
                vals.append(t * 2.0 + 1.0)
            elif k == 1:
                vals.append(stf.nn.relu(t))
            elif k == 2 and t.shape.rank == 2:
                w = stf.constant(
                    rng.randn(int(t.shape[1]), 8).astype(np.float32))
                vals.append(stf.matmul(t, w))
            elif k == 3 and t.shape.rank == 2:
                vals.append(stf.reduce_sum(t, axis=rng.randint(2)))
            elif k == 4 and t.shape.rank == 2:
                vals.append(stf.transpose(t))
            else:
                vals.append(stf.exp(-t))
        # always end host-small so the program has a fetchable scalar
        vals.append(stf.reduce_mean(vals[-1]))
        return x, vals[-1], vals[len(vals) // 2]

    @pytest.mark.parametrize("seed", range(6))
    def test_predicted_replication_is_sound(self, seed):
        rng = np.random.RandomState(seed)
        mesh = parallel.Mesh(DP8)
        with mesh:
            x, out, mid = self._random_graph(rng)
            fetches = [out]
            if mid.shape.rank is not None:
                fetches.append(mid)
            with stf.Session() as sess:
                _, _md, step = _traced_run(
                    sess, fetches,
                    {x: rng.randn(16, 8).astype(np.float32)})
                if step.compiled is None:
                    pytest.skip("AOT compile path unavailable")
                rep = step.sharding_report
                fetch_shardings = step.compiled.output_shardings[0]
                for t, sh in zip(step.device_fetches, fetch_shardings):
                    pred = rep.spec_of(t)
                    if pred is not None and all(e is None
                                                for e in pred):
                        assert sh.is_fully_replicated, (
                            f"{t.name}: analyzer says replicated, XLA "
                            f"committed {sh}")


# ---------------------------------------------------------------------------
# graph_lint CLI (acceptance criterion)
# ---------------------------------------------------------------------------

def _missharded_graphdef(tmp_path):
    """Deliberately mis-sharded example: a large replicated embedding
    (never matched by the rules) + a while body that re-gathers a
    rule-sharded weight every iteration."""
    from simple_tensorflow_tpu.framework import graph_io

    g = stf.Graph()
    with g.as_default():
        stf.get_variable("embeddings", [1024, 512],
                         initializer=stf.zeros_initializer())
        v = stf.get_variable("mlp/kernel", [512, 512],
                             initializer=stf.zeros_initializer())
        x = stf.placeholder(stf.float32, [64, 512], name="x")

        def cond(i, y):
            return stf.less(i, 8)

        def body(i, y):
            return i + 1, stf.matmul(y, v.value())

        _, yn = stf.while_loop(cond, body, [stf.constant(0), x],
                               maximum_iterations=8)
        stf.reduce_sum(yn, name="loss")
    gd = graph_io.graph_to_graphdef(g)
    gpath = tmp_path / "missharded.json"
    gpath.write_text(json.dumps(gd))
    rpath = tmp_path / "rules.json"
    rpath.write_text(json.dumps([["mlp/.*", ["dp", None]]]))
    return gpath, rpath


class TestGraphLintCLI:
    def test_json_mesh_rules_and_exit_code(self, tmp_path):
        from simple_tensorflow_tpu.tools import graph_lint

        gpath, rpath = _missharded_graphdef(tmp_path)
        argv = [str(gpath), "--json", "--mesh", "8",
                "--rules", str(rpath), "--fetch", "loss"]

        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = graph_lint.main(argv)  # default gate: errors only
        lines = [json.loads(line) for line in
                 buf.getvalue().strip().splitlines()]
        codes = {d.get("code") for d in lines if "code" in d}
        assert "lint/replicated-large-tensor" in codes
        assert "lint/resharding-hotspot" in codes
        assert rc == 0  # warnings alone don't fail the default gate

        summary = [d for d in lines if "summary" in d]
        assert summary, "--json must emit a trailing summary record"
        s = summary[0]["summary"]
        assert s["total_collective_bytes"] > 0
        assert "all-gather" in s["bytes_by_kind"]

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = graph_lint.main(argv + ["--max-severity", "warning"])
        assert rc == 1  # sharding hygiene gate trips on warnings

    def test_rules_require_mesh(self, tmp_path):
        from simple_tensorflow_tpu.tools import graph_lint

        gpath, rpath = _missharded_graphdef(tmp_path)
        with pytest.raises(SystemExit):
            graph_lint.main([str(gpath), "--rules", str(rpath)])

    def test_subprocess_entry_point(self, tmp_path):
        """The literal acceptance-criterion invocation: python -m
        simple_tensorflow_tpu.tools.graph_lint --json --mesh 8 <gd>
        exits nonzero under --max-severity warning."""
        gpath, rpath = _missharded_graphdef(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-m",
             "simple_tensorflow_tpu.tools.graph_lint", str(gpath),
             "--json", "--mesh", "8", "--rules", str(rpath),
             "--fetch", "loss", "--max-severity", "warning"],
            capture_output=True, text=True, timeout=300,
            cwd="/root/repo")
        assert proc.returncode == 1, proc.stderr
        codes = set()
        for line in proc.stdout.strip().splitlines():
            try:
                codes.add(json.loads(line).get("code"))
            except json.JSONDecodeError:
                pass
        assert "lint/replicated-large-tensor" in codes
        assert "lint/resharding-hotspot" in codes
