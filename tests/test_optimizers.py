"""Optimizer update math vs hand-computed values
(mirrors ref adam_test.py / momentum_test.py / etc., SURVEY §4)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _one_var_step(opt, n_steps=1, x0=(1.0, 2.0), grad=(0.1, 0.1)):
    """Minimize loss = g·x (constant gradient g) and return x after steps."""
    v = stf.Variable(stf.constant(np.float32(x0)), name="x")
    loss = stf.reduce_sum(stf.constant(np.float32(grad)) * v._ref)
    train = opt.minimize(loss)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        for _ in range(n_steps):
            sess.run(train)
        return sess.run(v.value())


class TestSGDFamily:
    def test_gradient_descent(self):
        x = _one_var_step(stf.train.GradientDescentOptimizer(3.0))
        np.testing.assert_allclose(x, [1.0 - 0.3, 2.0 - 0.3], rtol=1e-6)

    def test_momentum(self):
        lr, m, g = 2.0, 0.9, 0.1
        x = _one_var_step(stf.train.MomentumOptimizer(lr, m), n_steps=2)
        # v1 = g; x1 = x0 - lr*v1 ; v2 = m*v1 + g; x2 = x1 - lr*v2
        v1 = g
        v2 = m * v1 + g
        expect = 1.0 - lr * v1 - lr * v2
        np.testing.assert_allclose(x[0], expect, rtol=1e-5)

    def test_nesterov_momentum_differs(self):
        a = _one_var_step(stf.train.MomentumOptimizer(1.0, 0.9), 2)
        b = _one_var_step(stf.train.MomentumOptimizer(1.0, 0.9,
                                                      use_nesterov=True), 2)
        assert not np.allclose(a, b)

    def test_proximal_gd_matches_gd_without_regularization(self):
        a = _one_var_step(stf.train.GradientDescentOptimizer(1.0))
        b = _one_var_step(stf.train.ProximalGradientDescentOptimizer(1.0))
        np.testing.assert_allclose(a, b, rtol=1e-6)


class TestAdamFamily:
    def test_adam_first_step(self):
        lr, b1, b2, eps = 0.5, 0.9, 0.999, 1e-8
        g = 0.1
        x = _one_var_step(stf.train.AdamOptimizer(lr, b1, b2, eps))
        # step 1: mhat = g, vhat = g^2  => x -= lr * g/(|g| + eps')
        m = (1 - b1) * g
        v = (1 - b2) * g * g
        mhat = m / (1 - b1)
        vhat = v / (1 - b2)
        expect = 1.0 - lr * mhat / (np.sqrt(vhat) + eps)
        np.testing.assert_allclose(x[0], expect, rtol=1e-5)

    def test_adam_slots_created(self):
        v = stf.Variable(stf.zeros([2]), name="w")
        opt = stf.train.AdamOptimizer(0.1)
        opt.minimize(stf.reduce_sum(v._ref * 2.0))
        names = opt.get_slot_names()
        assert "m" in names and "v" in names
        assert opt.get_slot(v, "m") is not None

    def test_adagrad(self):
        lr, g, acc0 = 1.0, 0.1, 0.1
        x = _one_var_step(stf.train.AdagradOptimizer(
            lr, initial_accumulator_value=acc0))
        expect = 1.0 - lr * g / np.sqrt(acc0 + g * g)
        np.testing.assert_allclose(x[0], expect, rtol=1e-5)

    def test_rmsprop(self):
        lr, decay, eps, g = 1.0, 0.9, 1e-10, 0.1
        x = _one_var_step(stf.train.RMSPropOptimizer(lr, decay,
                                                     epsilon=eps))
        # TF semantics: the mean-square accumulator initializes to ONES
        ms = decay * 1.0 + (1 - decay) * g * g
        expect = 1.0 - lr * g / np.sqrt(ms + eps)
        np.testing.assert_allclose(x[0], expect, rtol=1e-4)

    def test_adadelta_moves(self):
        x = _one_var_step(stf.train.AdadeltaOptimizer(1.0, rho=0.95), 3)
        assert x[0] < 1.0

    def test_ftrl_moves(self):
        x = _one_var_step(stf.train.FtrlOptimizer(1.0), 3)
        assert x[0] < 1.0

    def test_adagrad_da_moves(self):
        gs = stf.train.get_or_create_global_step()
        x = _one_var_step(stf.train.AdagradDAOptimizer(
            1.0, global_step=gs), 2)
        assert x[0] < 1.0


class TestOptimizerAPI:
    def test_compute_then_apply(self):
        v = stf.Variable(stf.constant([1.0]), name="cv")
        loss = stf.reduce_sum(stf.square(v._ref))
        opt = stf.train.GradientDescentOptimizer(0.5)
        gvs = opt.compute_gradients(loss)
        gvs = [(stf.clip_by_value(g, -0.1, 0.1), var) for g, var in gvs
               if g is not None]
        train = opt.apply_gradients(gvs)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(train)
            # raw grad 2.0 clipped to 0.1 -> x = 1 - 0.05
            np.testing.assert_allclose(sess.run(v.value()), [0.95],
                                       rtol=1e-6)

    def test_global_step_increment(self):
        v = stf.Variable(stf.constant([1.0]), name="gv")
        gs = stf.train.get_or_create_global_step()
        train = stf.train.GradientDescentOptimizer(0.1).minimize(
            stf.reduce_sum(v._ref), global_step=gs)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(3):
                sess.run(train)
            assert int(np.asarray(sess.run(gs))) == 3

    def test_gradient_clipping_by_global_norm(self):
        t1 = stf.constant([3.0, 4.0])
        t2 = stf.constant([0.0])
        clipped, norm = stf.clip_by_global_norm([t1, t2], 2.5)
        with stf.Session() as sess:
            c1, n = sess.run([clipped[0], norm])
        assert abs(float(n) - 5.0) < 1e-5
        np.testing.assert_allclose(c1, [1.5, 2.0], rtol=1e-5)

    def test_sparse_gradient_updates_only_rows(self):
        table = stf.Variable(stf.ones([4, 2]), name="emb")
        e = stf.nn.embedding_lookup(table, stf.constant([1, 1]))
        loss = stf.reduce_sum(e)
        train = stf.train.GradientDescentOptimizer(0.5).minimize(loss)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(train)
            vals = sess.run(table.value())
        assert vals[0].tolist() == [1.0, 1.0]
        assert vals[1].tolist() == [0.0, 0.0]  # two lookups x lr 0.5


class TestLRDecay:
    def _eval_at_step(self, lr_fn, step):
        gs = stf.train.get_or_create_global_step()
        lr = lr_fn(gs)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(stf.assign(gs, stf.constant(step, stf.int64)))
            return float(sess.run(lr))

    def test_exponential_decay(self):
        v = self._eval_at_step(
            lambda gs: stf.train.exponential_decay(1.0, gs, 10, 0.5,
                                                   staircase=True), 25)
        assert abs(v - 0.25) < 1e-6

    def test_piecewise_constant(self):
        v = self._eval_at_step(
            lambda gs: stf.train.piecewise_constant(
                gs, [10, 20], [1.0, 0.5, 0.1]), 15)
        assert abs(v - 0.5) < 1e-6

    def test_polynomial_decay(self):
        v = self._eval_at_step(
            lambda gs: stf.train.polynomial_decay(1.0, gs, 100,
                                                  end_learning_rate=0.0,
                                                  power=1.0), 50)
        assert abs(v - 0.5) < 1e-6

    def test_cosine_decay(self):
        v = self._eval_at_step(
            lambda gs: stf.train.cosine_decay(1.0, gs, 100), 100)
        assert v < 1e-6

    def test_inverse_time_natural_exp(self):
        v1 = self._eval_at_step(
            lambda gs: stf.train.inverse_time_decay(1.0, gs, 10, 1.0), 10)
        assert abs(v1 - 0.5) < 1e-6
        v2 = self._eval_at_step(
            lambda gs: stf.train.natural_exp_decay(1.0, gs, 10, 1.0), 10)
        assert abs(v2 - np.exp(-1.0)) < 1e-5


class TestEMA:
    def test_moving_average_math(self):
        v = stf.Variable(stf.constant(10.0), name="ema_v")
        ema = stf.train.ExponentialMovingAverage(decay=0.9)
        update = ema.apply([v])
        avg = ema.average(v)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sess.run(update)  # avg = 10 (initialized to var value)
            sess.run(stf.assign(v, stf.constant(20.0)))
            sess.run(update)  # avg = 0.9*10 + 0.1*20 = 11
            np.testing.assert_allclose(float(sess.run(avg)), 11.0,
                                       rtol=1e-5)


class TestMixedPrecisionSlots:
    """bf16 params keep f32 optimizer state and f32 update math (the
    reference trains f32 everywhere; bf16 params are the TPU default
    here, and bf16 Adam moments lose small updates)."""

    def test_bf16_param_gets_f32_slots(self):
        stf.reset_default_graph()
        v = stf.Variable(np.zeros((4,), np.float32).astype(
            stf.bfloat16.np_dtype), name="mp_v")
        f32v = stf.Variable(np.zeros((4,), np.float32), name="mp_f")
        opt = stf.train.AdamOptimizer(0.1)
        g = stf.constant(np.ones((4,), np.float32).astype(
            stf.bfloat16.np_dtype))
        gf = stf.constant(np.ones((4,), np.float32))
        opt.apply_gradients([(g, v), (gf, f32v)])
        assert opt.get_slot(v, "m").dtype.base_dtype == stf.float32
        assert opt.get_slot(v, "v").dtype.base_dtype == stf.float32
        # f32 params keep f32 slots (unchanged behavior)
        assert opt.get_slot(f32v, "m").dtype.base_dtype == stf.float32

    def test_bf16_adam_matches_f32_reference_within_param_rounding(self):
        """Train the same problem with bf16 and f32 params: with f32
        update math the ONLY divergence is the param-dtype rounding, so
        trajectories stay within bf16 epsilon of each other."""
        results = {}
        for dtype in ("float32", "bfloat16"):
            stf.reset_default_graph()
            np_dt = stf.as_dtype(dtype).np_dtype
            w = stf.Variable(np.full((8,), 1.0).astype(np_dt), name="w_" + dtype)
            x = stf.constant(np.linspace(0.5, 1.5, 8).astype(np_dt))
            loss = stf.reduce_sum(stf.square(stf.cast(w, stf.float32) *
                                             stf.cast(x, stf.float32)))
            opt = stf.train.AdamOptimizer(0.01)
            train = opt.minimize(loss, var_list=[w])
            with stf.Session() as sess:
                sess.run(stf.global_variables_initializer())
                for _ in range(50):
                    sess.run(train)
                results[dtype] = np.asarray(
                    sess.run(w), dtype=np.float32)
        np.testing.assert_allclose(results["bfloat16"], results["float32"],
                                   rtol=0.02, atol=0.01)

    def test_bf16_momentum_small_updates_not_lost(self):
        """With f32 momentum accumulation, many small gradients compound;
        bf16 accumulation would round them away relative to the running
        momentum."""
        stf.reset_default_graph()
        w = stf.Variable(np.zeros((1,), np.float32).astype(
            stf.bfloat16.np_dtype), name="w_tiny")
        g = stf.constant(np.full((1,), 1e-3, np.float32).astype(
            stf.bfloat16.np_dtype))
        opt = stf.train.MomentumOptimizer(0.1, 0.9)
        train = opt.apply_gradients([(g, w)])
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            for _ in range(100):
                sess.run(train)
            mom = np.asarray(sess.run(opt.get_slot(w, "momentum")),
                             np.float32)
        # steady-state momentum -> g/(1-mu) = 1e-2
        np.testing.assert_allclose(mom, [1e-2], rtol=0.05)
