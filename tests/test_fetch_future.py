"""FetchFuture laziness + steady-state fast path (ISSUE 4).

ConfigProto(async_fetches=True) makes device-produced fetches come back
as lazy FetchFutures riding jax async dispatch: no device_get until the
caller materializes, device errors surface at materialization, and
concurrent steady-state run() calls stay correct (the device stage is
serialized; futures resolve immutable arrays).
"""

import threading

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.client.session import FetchFuture
from simple_tensorflow_tpu.platform import monitoring


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _cells(name):
    return monitoring.export().get(name, {}).get("cells", {})


def _materializations():
    return _cells("/stf/session/fetch_materializations").get("", 0)


def _fast_path_hits():
    return _cells("/stf/session/fast_path_hits").get("", 0)


class TestLaziness:
    def test_no_device_get_before_materialization(self):
        x = stf.placeholder(stf.float32, [4], name="x")
        y = x * 2.0 + 1.0
        sess = stf.Session(config=stf.ConfigProto(async_fetches=True))
        xv = np.arange(4, dtype=np.float32)
        fut = sess.run(y, {x: xv})
        assert isinstance(fut, FetchFuture)
        before = _materializations()
        assert not fut.materialized
        assert fut.shape == (4,)  # metadata access does NOT materialize
        assert _materializations() == before
        # first host access materializes exactly once
        np.testing.assert_array_equal(np.asarray(fut), xv * 2.0 + 1.0)
        assert fut.materialized
        assert _materializations() == before + 1
        np.testing.assert_array_equal(fut.result(), xv * 2.0 + 1.0)
        assert _materializations() == before + 1  # cached, no second get

    def test_matches_eager_values(self):
        x = stf.placeholder(stf.float32, [3], name="x")
        v = stf.Variable(stf.ones([3]), name="v")
        y = stf.reduce_sum(x * v._ref)
        g = stf.get_default_graph()
        eager = stf.Session(graph=g)
        lazy = stf.Session(graph=g,
                           config=stf.ConfigProto(async_fetches=True))
        eager.run(stf.global_variables_initializer())
        lazy.run(stf.global_variables_initializer())
        xv = np.array([1.0, 2.0, 3.0], np.float32)
        ev = eager.run(y, {x: xv})
        lv = lazy.run(y, {x: xv})
        assert isinstance(ev, np.ndarray) and isinstance(lv, FetchFuture)
        assert float(ev) == float(lv)

    def test_scalar_dunder_conversions(self):
        x = stf.placeholder(stf.float32, [], name="x")
        sess = stf.Session(config=stf.ConfigProto(async_fetches=True))
        fut = sess.run(x * 3.0, {x: np.float32(2.0)})
        assert float(fut) == 6.0
        fut2 = sess.run(stf.cast(x, stf.int32), {x: np.float32(5.0)})
        assert int(fut2) == 5

    def test_fed_and_host_fetches_stay_eager(self):
        """Only device-produced fetches become futures; fed tensors and
        host-stage values keep their eager types."""
        x = stf.placeholder(stf.float32, [2], name="x")
        sess = stf.Session(config=stf.ConfigProto(async_fetches=True))
        xv = np.ones(2, np.float32)
        got_feed, got_dev = sess.run([x, x + 1.0], {x: xv})
        assert isinstance(got_feed, np.ndarray)
        assert isinstance(got_dev, FetchFuture)


class TestErrorPropagation:
    def test_device_error_raises_at_materialization(self):
        """An async device failure must surface when (and only when)
        the future materializes — modeled with a deleted jax buffer,
        the shape any runtime-poisoned value takes."""
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda a: a * 2, donate_argnums=(0,))
        src = jnp.ones(3)
        _ = f(src)  # donation deletes src's buffer
        fut = FetchFuture(src)
        before = _materializations()
        with pytest.raises(Exception, match="deleted|donated"):
            fut.result()
        # a failed materialization is retryable, not silently cached
        assert not fut.materialized
        assert _materializations() == before

    def test_error_repeats_on_retry(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda a: a + 1, donate_argnums=(0,))
        src = jnp.zeros(2)
        _ = f(src)
        fut = FetchFuture(src)
        for _ in range(2):
            with pytest.raises(Exception):
                np.asarray(fut)


class TestConcurrency:
    def test_concurrent_steady_state_runs(self):
        """Threads hammer the same warm plan: per-thread results stay
        correct (futures don't cross wires) and the variable update
        stream loses nothing under the device-stage lock."""
        x = stf.placeholder(stf.float32, [], name="x")
        v = stf.Variable(stf.zeros([]), name="v")
        bump = stf.assign_add(v, 1.0)
        y = x * 2.0
        sess = stf.Session(config=stf.ConfigProto(async_fetches=True))
        sess.run(stf.global_variables_initializer())
        sess.run([y, bump], {x: np.float32(0.0)})  # warm the plan

        n_threads, n_iters = 4, 25
        errs = []

        def worker(tid):
            try:
                for i in range(n_iters):
                    xv = np.float32(tid * 1000 + i)
                    fut, _ = sess.run([y, bump], {x: xv})
                    assert float(fut) == float(xv) * 2.0
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        total = float(np.asarray(sess.run(v._ref)))
        assert total == 1.0 + n_threads * n_iters  # no lost updates


class TestFastPath:
    def test_fast_path_hits_count_warm_pure_device_runs(self):
        x = stf.placeholder(stf.float32, [2], name="x")
        y = x * 3.0
        sess = stf.Session()
        xv = np.ones(2, np.float32)
        sess.run(y, {x: xv})  # plan + compile (miss)
        before = _fast_path_hits()
        for _ in range(3):
            sess.run(y, {x: xv})
        assert _fast_path_hits() == before + 3
