"""Machine-checkable byte budgets for the headline train steps (VERDICT
r4 item 1b): the 77→~55 GB ResNet byte diagnosis and the BERT byte fixes
must be guarded by CI that runs WITHOUT the TPU.

Three layers of guard, each catching what the previous can't:

1. **VJP residual dtypes** — the round-3 ResNet regression was f32
   autodiff residuals (the saved ``(x - mean)`` of the two-pass BN
   variance), invisible in the stf graph and only expressible at the
   jax.vjp level. ``jax.vjp``'s returned closure carries the residuals as
   its pytree leaves, so we inspect them directly: a bf16 input must not
   produce an f32 residual of activation size.

2. **Compiled-step byte ratchet** — XLA cost analysis of the *compiled*
   bench-config train steps on CPU. Absolute numbers are CPU-fusion
   numbers (≈5x the TPU bytes — XLA-CPU barely fuses and upcasts bf16
   math internally), but the ratchet catches any structural regression
   that adds buffer traffic: calibrated 2026-07-30 at ResNet-b256
   367.2 GB / 6.374 TFLOP, BERT-b24-s512 167.6 GB / 8.839 TFLOP.

3. **FLOP pin** — catches accidental double compute (e.g. a broken
   forward-replay CSE) which a byte budget alone might miss.

The slow compiles (several minutes each, then cached by the persistent
jax compilation cache in .jax_cache/) can be skipped with
``STF_BYTE_BUDGET=0``.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import simple_tensorflow_tpu as stf

_RUN_BUDGET = os.environ.get("STF_BYTE_BUDGET", "1") == "1"


# ---------------------------------------------------------------------------
# 1. VJP residual dtype guards
# ---------------------------------------------------------------------------

def _f32_residual_leaks(vjp_fn, activation_elems, allowed_elems=()):
    """f32 leaves of the vjp closure at activation size = saved residuals
    that will be written in forward and re-read in backward at 2x width."""
    leaks = []
    for leaf in jax.tree_util.tree_leaves(vjp_fn):
        if not hasattr(leaf, "dtype"):
            continue
        if leaf.dtype == jnp.float32 and leaf.size >= activation_elems \
                and leaf.size not in allowed_elems:
            leaks.append((leaf.shape, str(leaf.dtype)))
    return leaks


def test_bn_train_vjp_residuals_stay_bf16():
    """Training-mode fused BN on bf16 input: residuals must be the bf16 x
    plus per-channel f32 statistics — never a full-size f32 tensor (the
    round-3 bug: two-pass variance saved f32 ``x - mean``)."""
    from simple_tensorflow_tpu.ops import nn_impl

    n, h, w, c = 8, 16, 16, 32
    x = jnp.asarray(np.random.RandomState(0).randn(n, h, w, c),
                    jnp.bfloat16)
    scale = jnp.ones((c,), jnp.float32)
    offset = jnp.zeros((c,), jnp.float32)

    def f(x, scale, offset):
        return nn_impl._bn_train(x, scale, offset, 1e-3, (0, 1, 2))[0]

    _, vjp_fn = jax.vjp(f, x, scale, offset)
    leaks = _f32_residual_leaks(vjp_fn, activation_elems=x.size)
    assert not leaks, f"f32 activation-size BN residuals: {leaks}"


def test_matmul_vjp_residuals_stay_bf16():
    """bf16 matmul must not save f32 copies of its operands (the round-3
    ``preferred_element_type=f32`` bug doubled every dense layer's
    activation traffic)."""
    a = jnp.asarray(np.random.RandomState(1).randn(256, 512), jnp.bfloat16)
    b = jnp.asarray(np.random.RandomState(2).randn(512, 128), jnp.bfloat16)

    stf.reset_default_graph()
    ta = stf.placeholder(stf.bfloat16, [256, 512], name="a")
    tb = stf.placeholder(stf.bfloat16, [512, 128], name="b")
    out = stf.matmul(ta, tb)
    assert out.dtype.base_dtype == stf.bfloat16, (
        f"bf16 matmul emitted {out.dtype} (TF dtype semantics: output "
        "keeps the input dtype; the MXU accumulates f32 internally)")

    from simple_tensorflow_tpu.framework import lowering as lowering_mod

    pruned = lowering_mod.prune([out.op], fed_tensors={ta, tb})

    def f(av, bv):
        ctx = lowering_mod.LoweringContext({}, rng_root=None)
        ctx.env[ta] = av
        ctx.env[tb] = bv
        lowering_mod.execute_ops(ctx, pruned, fed={ta, tb})
        return ctx.env[out]

    _, vjp_fn = jax.vjp(f, a, b)
    leaks = _f32_residual_leaks(vjp_fn, activation_elems=min(a.size, b.size))
    assert not leaks, f"f32 matmul residuals: {leaks}"


def test_bert_layer_vjp_residuals_stay_bf16():
    """One transformer layer end-to-end at bf16: no f32 residual at
    activation size (embedding pipeline / LayerNorm / attention were the
    round-3 BERT byte sinks)."""
    from simple_tensorflow_tpu.framework import lowering as lowering_mod
    from simple_tensorflow_tpu.models import bert

    cfg = bert.BertConfig(vocab_size=128, hidden_size=64, num_layers=1,
                          num_heads=2, intermediate_size=128,
                          max_position=32, hidden_dropout=0.0,
                          attention_dropout=0.0)
    b_sz, s = 4, 32
    stf.reset_default_graph()
    ids = stf.placeholder(stf.int32, [b_sz, s], name="ids")
    seg = stf.placeholder(stf.int32, [b_sz, s], name="seg")
    out, _pooled, _emb = bert.bert_encoder(
        ids, seg, None, cfg, compute_dtype=stf.bfloat16, training=True)

    sess = stf.Session()
    sess.run(stf.global_variables_initializer())
    state = dict(sess._variable_store.values)
    pruned = lowering_mod.prune([out.op], fed_tensors={ids, seg})

    idv = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (b_sz, s)), jnp.int32)
    sgv = jnp.zeros((b_sz, s), jnp.int32)

    def f(st):
        ctx = lowering_mod.LoweringContext(st,
                                           rng_root=jax.random.key(0))
        ctx.env[ids] = idv
        ctx.env[seg] = sgv
        lowering_mod.execute_ops(ctx, pruned, fed={ids, seg})
        return ctx.env[out]

    _, vjp_fn = jax.vjp(f, state)
    # param-sized f32 is fine (master weights); activation-size is not
    activation_elems = b_sz * s * cfg.hidden_size
    param_sizes = {int(np.prod(v.shape)) for v in state.values()}
    leaks = _f32_residual_leaks(vjp_fn, activation_elems,
                                allowed_elems=param_sizes)
    assert not leaks, f"f32 BERT residuals: {leaks[:8]}"


# ---------------------------------------------------------------------------
# 2+3. Compiled-step byte ratchet + FLOP pin (slow; cached after 1st run)
# ---------------------------------------------------------------------------

# calibrated on CPU 2026-07-30 (see module docstring); ~9% headroom
_RESNET_BYTES_BUDGET = 400e9
_RESNET_FLOPS_RANGE = (5.7e12, 7.1e12)   # 6.374 measured
_BERT_BYTES_BUDGET = 185e9
_BERT_FLOPS_RANGE = (8.0e12, 9.8e12)     # 8.839 measured


def _enable_cache():
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)


# ---------------------------------------------------------------------------
# 4. Function-aware optimizer ratchet (PR 1 tentpole): post-optimization
#    cost-model bytes/FLOPs of a cond+scan model are pinned so in-body
#    CSE/layout wins can't silently regress. Fast (static cost model
#    only, no compile) — always runs.
# ---------------------------------------------------------------------------

# calibrated 2026-08-03: unopt 1.154e7 F / 8.06e6 B -> opt 1.141e7 F /
# 6.88e6 B (NCHW per-op transposes cancelled in the cond branch and the
# scan body, Exp CSE'd in-body); ~8% headroom on the pins
_COND_SCAN_BYTES_BUDGET = 7.4e6
_COND_SCAN_FLOPS_BUDGET = 1.23e7


def _build_cond_scan_model():
    import simple_tensorflow_tpu as stf_mod

    stf_mod.reset_default_graph()
    rng = np.random.RandomState(0)
    n, c, hw, steps = 4, 8, 16, 8
    x = stf_mod.placeholder(stf_mod.float32, [n, c, hw, hw], name="bx")
    w1 = stf_mod.constant(rng.randn(3, 3, c, c).astype(np.float32) * 0.2,
                          name="bw1")
    w2 = stf_mod.constant(rng.randn(3, 3, c, c).astype(np.float32) * 0.2,
                          name="bw2")
    scale = stf_mod.constant(np.ones(c, np.float32))
    offset = stf_mod.constant(np.zeros(c, np.float32))

    def branch_t():
        h = stf_mod.nn.conv2d(x, w1, strides=[1, 1, 1, 1],
                              padding="SAME", data_format="NCHW")
        h, _, _ = stf_mod.nn.fused_batch_norm(h, scale, offset,
                                              data_format="NCHW")
        return stf_mod.nn.relu(h)

    def branch_f():
        h = stf_mod.nn.conv2d(x, w2, strides=[1, 1, 1, 1],
                              padding="SAME", data_format="NCHW")
        return stf_mod.nn.relu(h)

    h0 = stf_mod.cond(stf_mod.reduce_sum(x) > 0.0, branch_t, branch_f)
    dummy = stf_mod.constant(np.zeros((steps, 1), np.float32))

    def body(carry, _):
        h = stf_mod.nn.conv2d(carry, w1, strides=[1, 1, 1, 1],
                              padding="SAME", data_format="NCHW")
        h, _, _ = stf_mod.nn.fused_batch_norm(h, scale, offset,
                                              data_format="NCHW")
        a = stf_mod.exp(carry)
        b = stf_mod.exp(carry)  # in-body CSE target
        return stf_mod.nn.relu(h) + 0.0 * (a + b)

    out = stf_mod.scan(body, dummy, initializer=h0)
    res = stf_mod.reduce_mean(out[-1], name="budget_res")
    return x, res


def test_cond_scan_post_optimization_cost_ratchet():
    import json

    from simple_tensorflow_tpu.framework import (cost_model, graph_io,
                                                 optimizer)

    x, res = _build_cond_scan_model()
    est_unopt = cost_model.estimate(res, feeds=[x])
    gd = graph_io.graph_to_graphdef(stf.get_default_graph())
    opt = optimizer.optimize(gd, keep=[res.name, x.name])

    stf.reset_default_graph()
    graph_io.import_graph_def(json.dumps(opt), name="")
    g = stf.get_default_graph()
    x2 = g.as_graph_element("bx:0", True, False)
    r2 = g.as_graph_element(res.name, True, False)
    est_opt = cost_model.estimate(r2, feeds=[x2])

    # the optimizer must WIN: in-body layout + CSE cut modeled traffic
    assert est_opt.bytes_accessed < est_unopt.bytes_accessed, (
        f"optimization increased modeled bytes: "
        f"{est_opt.bytes_accessed:.3g} >= {est_unopt.bytes_accessed:.3g}")
    # and the post-optimization numbers are pinned (ratchet)
    assert est_opt.bytes_accessed <= _COND_SCAN_BYTES_BUDGET, (
        f"cond/scan post-opt bytes regressed: {est_opt.bytes_accessed:.4g}"
        f" > {_COND_SCAN_BYTES_BUDGET:.4g} (calibrated 6.88e6; in-body "
        "layout/CSE may have stopped firing)")
    assert est_opt.flops <= _COND_SCAN_FLOPS_BUDGET, (
        f"cond/scan post-opt FLOPs regressed: {est_opt.flops:.4g} > "
        f"{_COND_SCAN_FLOPS_BUDGET:.4g} (calibrated 1.141e7)")
    # the numbers stay real: the rewritten graph computes the same value
    xv = np.random.RandomState(1).randn(4, 8, 16, 16).astype(np.float32)
    with stf.Session() as s2:
        got = np.asarray(s2.run(r2, {x2: xv}))
    x, res = _build_cond_scan_model()
    with stf.Session() as s1:
        expected = np.asarray(s1.run(res, {x: xv}))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


@pytest.mark.skipif(not _RUN_BUDGET, reason="STF_BYTE_BUDGET=0")
def test_resnet_train_step_byte_budget():
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from benchmarks import byte_budget

    _enable_cache()
    cost = byte_budget.resnet_cost(batch=256)
    assert cost["bytes_accessed"] <= _RESNET_BYTES_BUDGET, (
        f"ResNet-b256 step bytes regressed: {cost['gbytes']} GB > "
        f"{_RESNET_BYTES_BUDGET / 1e9} GB budget (calibrated 367 GB; a "
        "jump of this size usually means f32 activations crept back in)")
    lo, hi = _RESNET_FLOPS_RANGE
    assert lo <= cost["flops"] <= hi, (
        f"ResNet-b256 step FLOPs {cost['tflops']} TF outside "
        f"[{lo / 1e12}, {hi / 1e12}] — double compute or dropped work?")


@pytest.mark.skipif(not _RUN_BUDGET, reason="STF_BYTE_BUDGET=0")
def test_bert_train_step_byte_budget():
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from benchmarks import byte_budget

    _enable_cache()
    cost = byte_budget.bert_cost(batch=24)
    assert cost["bytes_accessed"] <= _BERT_BYTES_BUDGET, (
        f"BERT-b24-s512 step bytes regressed: {cost['gbytes']} GB > "
        f"{_BERT_BYTES_BUDGET / 1e9} GB budget (calibrated 167.6 GB)")
    lo, hi = _BERT_FLOPS_RANGE
    assert lo <= cost["flops"] <= hi, (
        f"BERT step FLOPs {cost['tflops']} TF outside "
        f"[{lo / 1e12}, {hi / 1e12}]")
