"""Native C++ runtime tests via ctypes round-trips (SURVEY §4)."""

import json
import os
import struct

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.lib import crc32c as pycrc
from simple_tensorflow_tpu.runtime import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native runtime not built")


def test_version():
    assert native.version().startswith("stf-runtime")


def test_crc32c_matches_python():
    for payload in [b"", b"a", b"hello world", os.urandom(1024),
                    os.urandom(7)]:
        # pure-python reference (force the table path with crc=0 short-circuit
        # bypassed by computing manually)
        crc = 0xFFFFFFFF
        for b in payload:
            crc = pycrc._TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
        expect = crc ^ 0xFFFFFFFF
        assert native.crc32c(payload) == expect
        mask = (((expect >> 15) | (expect << 17)) + 0xA282EAD8) & 0xFFFFFFFF
        assert native.masked_crc32c(payload) == mask


def test_tfrecord_native_roundtrip(tmp_path):
    path = str(tmp_path / "native.tfrecord")
    records = [os.urandom(np.random.RandomState(i).randint(0, 2000))
               for i in range(50)] + [b""]
    native.write_tfrecords(path, records)
    got = list(native.read_tfrecords(path, batch=7))
    assert got == records


def test_tfrecord_native_vs_python_format(tmp_path):
    """Native writer output must parse with the pure-python reader and
    vice versa (format parity with ref record_writer.cc)."""
    from simple_tensorflow_tpu.lib.io import tf_record

    path = str(tmp_path / "a.tfrecord")
    records = [b"alpha", b"", b"x" * 1000]
    native.write_tfrecords(path, records)
    assert list(tf_record._read_records_py(path)) == records

    path2 = str(tmp_path / "b.tfrecord")
    with tf_record.TFRecordWriter(path2) as w:
        for r in records:
            w.write(r)
    assert list(native.read_tfrecords(path2)) == records


def test_tfrecord_gzip(tmp_path):
    path = str(tmp_path / "c.tfrecord.gz")
    records = [b"compressed", b"records" * 100]
    native.write_tfrecords(path, records, compression=2)
    # gzip magic
    with open(path, "rb") as f:
        assert f.read(2) == b"\x1f\x8b"
    assert list(native.read_tfrecords(path)) == records


def test_tfrecord_corruption_detected(tmp_path):
    path = str(tmp_path / "d.tfrecord")
    native.write_tfrecords(path, [b"payload-abcdef"])
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a data byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(stf.errors.DataLossError):
        list(native.read_tfrecords(path))


def test_arena():
    a = native.Arena(block_bytes=4096)
    x = a.alloc_ndarray((16, 16), np.float32)
    x[:] = 3.0
    assert a.bytes_in_use >= 16 * 16 * 4
    y = a.alloc_ndarray((100000,), np.uint8)  # forces a new block
    y[:] = 7
    assert (x == 3.0).all()
    assert a.bytes_reserved >= a.bytes_in_use
    # 64-byte alignment
    assert x.ctypes.data % 64 == 0 and y.ctypes.data % 64 == 0
    a.reset()
    assert a.bytes_in_use == 0
    a.close()


def test_prune_toposort_flat():
    # diamond: 0->1, 0->2, 1->3, 2->3 ; extra orphan node 4
    edges = np.array([[0, 1], [0, 2], [1, 3], [2, 3]], np.int32)
    order = native.prune_toposort(5, edges, [3])
    assert order is not None and set(order) == {0, 1, 2, 3}
    pos = {n: i for i, n in enumerate(order)}
    assert pos[0] < pos[1] and pos[0] < pos[2]
    assert pos[1] < pos[3] and pos[2] < pos[3]
    # pruning: only ask for node 1
    order2 = native.prune_toposort(5, edges, [1])
    assert set(order2) == {0, 1}
    # cycle -> None
    cyc = np.array([[0, 1], [1, 0]], np.int32)
    assert native.prune_toposort(2, cyc, [1]) is None


def test_native_prune_matches_python_on_real_graph():
    from simple_tensorflow_tpu.framework import lowering

    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [4], name="x")
    h = x
    for i in range(600):  # push past _NATIVE_PRUNE_MIN_NODES
        h = h + float(i)
    loss = stf.reduce_sum(h)
    dead = stf.square(x)  # not an ancestor of loss
    g = stf.get_default_graph()
    assert len(g.get_operations()) >= lowering._NATIVE_PRUNE_MIN_NODES
    order = lowering.prune([loss.op], fed_tensors={x})
    names = {op.name for op in order}
    assert loss.op.name in names
    assert dead.op.name not in names
    # dependencies before dependents
    pos = {op: i for i, op in enumerate(order)}
    for op in order:
        for t in op.inputs:
            if t.op in pos:
                assert pos[t.op] < pos[op]


def test_cgraph_builds_importable_graphdef():
    g = native.CGraph()
    a = g.add_node("Const", "a")
    g.set_attr(a, "value_f", 2.0)
    g.add_output(a, "float32", [])
    b = g.add_node("Const", "b")
    g.set_attr(b, "value_f", 3.0)
    g.add_output(b, "float32", [])
    add = g.add_node("AddV2", "add")
    g.add_input(add, a, 0)
    g.add_input(add, b, 0)
    g.add_output(add, "float32", [])
    assert g.num_nodes == 3
    gd = json.loads(g.to_json())
    assert [n["name"] for n in gd["node"]] == ["a", "b", "add"]
    assert gd["node"][2]["input"] == ["a:0", "b:0"]
    assert gd["node"][0]["attr"]["value_f"] == 2.0
    assert gd["node"][2]["output_specs"] == [[[], "float32"]]
    g.close()


def test_cgraph_duplicate_name_raises():
    g = native.CGraph()
    g.add_node("NoOp", "n")
    with pytest.raises(stf.errors.OpError):
        g.add_node("NoOp", "n")
    g.close()


def test_session_run_uses_native_prune_smoke():
    """End-to-end: a big graph session step with the native pruner wired."""
    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [8], name="x")
    h = x
    for i in range(600):
        h = h * 1.0001 + 0.001
    y = stf.reduce_sum(h)
    with stf.Session() as sess:
        val = sess.run(y, {x: np.ones(8, np.float32)})
    assert np.isfinite(val)


def test_corruption_past_first_batch_no_duplicates(tmp_path):
    """Regression: a corrupt record past batch 1 must not restart the
    stream (previously the iterator fell back to the Python reader and
    re-delivered records 0..k twice)."""
    from simple_tensorflow_tpu.lib.io import tf_record

    path = str(tmp_path / "e.tfrecord")
    records = [struct.pack("<I", i) * 3 for i in range(300)]
    native.write_tfrecords(path, records)
    raw = bytearray(open(path, "rb").read())
    # corrupt a byte inside record ~290's payload: each record is
    # 12 + 12 + 4 = 28 bytes on disk
    raw[28 * 290 + 14] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    got = []
    with pytest.raises(stf.errors.DataLossError):
        for r in tf_record.tf_record_iterator(path):
            got.append(r)
    # good prefix delivered exactly once, in order
    assert got == records[:290]


def test_run_from_c_savedmodel_roundtrip(tmp_path):
    """StfSessionRun equivalent (ref c/c_api.h TF_SessionRun): export an
    MNIST softmax forward as a SavedModel, load + run it through the C
    entry points via ctypes, and match an in-process Session.run."""
    from simple_tensorflow_tpu.runtime import native

    lib = native.load_session_lib()
    if lib is None:
        pytest.skip("libstf_session.so unavailable (no python3-config?)")

    import simple_tensorflow_tpu as stf
    from simple_tensorflow_tpu import saved_model as sm
    from simple_tensorflow_tpu.models import mnist

    stf.reset_default_graph()
    m = mnist.softmax_model(batch_size=None)
    rng = np.random.RandomState(0)
    X = rng.rand(4, 784).astype(np.float32)
    sess = stf.Session()
    sess.run(stf.global_variables_initializer())
    # non-trivial weights so the comparison means something
    sess.run(stf.assign(
        [v for v in stf.global_variables() if v.var_name == "W"][0],
        rng.randn(784, 10).astype(np.float32) * 0.1))
    expected = sess.run(m["logits"], {m["x"]: X})
    export_dir = str(tmp_path / "export")
    sm.simple_save(sess, export_dir, inputs={"x": m["x"]},
                   outputs={"logits": m["logits"]})

    c = __import__("ctypes")
    with native._Status(native._load()) as st:
        handle = lib.StfSessionLoad(export_dir.encode(), st.handle)
        st.check()
    assert handle

    dims = (c.c_int64 * 2)(4, 784)
    feed = (native.CTensorSpec * 1)()
    feed[0].dtype = b"float32"
    feed[0].rank = 2
    feed[0].dims = dims
    feed[0].data = X.ctypes.data_as(c.c_void_p)
    feed[0].nbytes = X.nbytes
    feed_names = (c.c_char_p * 1)(b"x")
    fetch_names = (c.c_char_p * 1)(b"logits")
    outs = (native.CTensorOut * 1)()
    with native._Status(native._load()) as st:
        lib.StfSessionRun(handle, feed_names, feed, 1,
                          fetch_names, 1, outs, st.handle)
        st.check()
    assert outs[0].dtype == b"float32"
    assert outs[0].rank == 2
    assert (outs[0].dims[0], outs[0].dims[1]) == (4, 10)
    got = np.ctypeslib.as_array(
        c.cast(outs[0].data, c.POINTER(c.c_float)), shape=(4, 10)).copy()
    lib.StfTensorOutRelease(c.byref(outs[0]))
    lib.StfSessionClose(handle)
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_run_from_c_bad_fetch_sets_status(tmp_path):
    from simple_tensorflow_tpu.runtime import native

    lib = native.load_session_lib()
    if lib is None:
        pytest.skip("libstf_session.so unavailable")

    import simple_tensorflow_tpu as stf
    from simple_tensorflow_tpu import saved_model as sm
    from simple_tensorflow_tpu.models import mnist
    from simple_tensorflow_tpu.framework import errors

    stf.reset_default_graph()
    m = mnist.softmax_model(batch_size=None)
    sess = stf.Session()
    sess.run(stf.global_variables_initializer())
    export_dir = str(tmp_path / "export")
    sm.simple_save(sess, export_dir, inputs={"x": m["x"]},
                   outputs={"logits": m["logits"]})

    c = __import__("ctypes")
    with native._Status(native._load()) as st:
        handle = lib.StfSessionLoad(export_dir.encode(), st.handle)
        st.check()
    X = np.zeros((1, 784), np.float32)
    dims = (c.c_int64 * 2)(1, 784)
    feed = (native.CTensorSpec * 1)()
    feed[0].dtype = b"float32"
    feed[0].rank = 2
    feed[0].dims = dims
    feed[0].data = X.ctypes.data_as(c.c_void_p)
    feed[0].nbytes = X.nbytes
    feed_names = (c.c_char_p * 1)(b"x")
    fetch_names = (c.c_char_p * 1)(b"no_such_output")
    outs = (native.CTensorOut * 1)()
    with native._Status(native._load()) as st:
        lib.StfSessionRun(handle, feed_names, feed, 1,
                          fetch_names, 1, outs, st.handle)
        with pytest.raises(errors.InternalError, match="no_such_output"):
            st.check()
    lib.StfSessionClose(handle)


def test_arena_pool_staging_correctness():
    """ArenaPool: values survive the staging copy; buffers recycle after
    slots-1 further stages (the prefetch_to_device contract)."""
    from simple_tensorflow_tpu.runtime import native

    if not native.available():
        pytest.skip("native runtime unavailable")
    pool = native.ArenaPool(slots=3, block_bytes=1 << 16)
    rng = np.random.RandomState(0)
    batches = [rng.rand(8, 16).astype(np.float32) for _ in range(10)]
    staged = []
    for b in batches:
        s = pool.stage((b, {"lbl": b[:, 0].astype(np.int32)}))
        arr, d = s
        np.testing.assert_array_equal(arr, b)
        np.testing.assert_array_equal(d["lbl"], b[:, 0].astype(np.int32))
        # alignment contract for DMA staging
        assert arr.ctypes.data % 64 == 0
        staged.append(s)
    pool.close()


def test_prefetch_to_device_arena_staging():
    from simple_tensorflow_tpu.runtime import native
    from simple_tensorflow_tpu import data as stf_data

    if not native.available():
        pytest.skip("native runtime unavailable")
    rng = np.random.RandomState(1)
    X = rng.rand(32, 4).astype(np.float32)
    ds = stf_data.Dataset.from_tensor_slices(X).batch(8)
    out = list(ds.prefetch_to_device(buffer_size=2, arena_staging=True))
    assert len(out) == 4
    np.testing.assert_allclose(np.concatenate([np.asarray(o) for o in out]),
                               X)


def test_arena_pool_recycle_blocks_on_inflight():
    """A slot recycles only after its recorded in-flight arrays are ready
    (block_until_ready barrier), and staged values survive recycling when
    the transfer COPIES (TPU semantics — simulated with an explicit copy;
    CPU device_put aliases, which is why prefetch_to_device refuses arena
    staging there)."""
    from simple_tensorflow_tpu.runtime import native

    if not native.available():
        pytest.skip("native runtime unavailable")
    import jax
    import jax.numpy as jnp

    pool = native.ArenaPool(slots=2, block_bytes=1 << 16)
    rng = np.random.RandomState(2)
    batches = [rng.rand(4, 8).astype(np.float32) for _ in range(8)]
    devices = []
    for b in batches:
        staged = pool.stage(b)
        d = jnp.array(staged)  # explicit copy = TPU transfer semantics
        pool.mark_in_flight(d)
        devices.append(d)
    # every slot's inflight record was consumed by the recycle barrier
    # except the most recent ones still pending
    assert sum(x is not None for x in pool._inflight) <= 2
    for b, d in zip(batches, devices):
        np.testing.assert_array_equal(np.asarray(d), b)
    pool.close()


def test_prefetch_to_device_refuses_arena_on_cpu():
    """Explicit arena_staging=True on the CPU backend must fall back
    (device_put aliases aligned host buffers there) and stay correct far
    past the recycle window."""
    from simple_tensorflow_tpu.runtime import native
    from simple_tensorflow_tpu import data as stf_data

    if not native.available():
        pytest.skip("native runtime unavailable")
    rng = np.random.RandomState(3)
    X = rng.rand(80, 4).astype(np.float32)
    ds = stf_data.Dataset.from_tensor_slices(X).batch(8)
    out = list(ds.prefetch_to_device(buffer_size=2, arena_staging=True))
    assert len(out) == 10  # 10 batches >> buffer_size+2 slots
    np.testing.assert_allclose(
        np.concatenate([np.asarray(o) for o in out]), X)


def test_c_client_builds_grads_and_trains(tmp_path):
    """C++ client parity (VERDICT r4 item 2; ref cc/framework/scope.h,
    cc/framework/gradients.h:34, cc/framework/gradient_checker.cc):
    compile runtime_cc/client_demo.c — a pure-C program that builds
    y = xW + b, requests dL/dW via StfAddGradients, appends SGD ops,
    runs a train step through StfSessionFromGraphJson, and
    gradient-checks dL/dx against central differences — then match its
    numbers against the same model built natively in Python."""
    import shutil
    import subprocess

    cc_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "runtime_cc")
    if not os.path.exists(os.path.join(cc_dir, "libstf_session.so")):
        if native.load_session_lib() is None:
            pytest.skip("libstf_session.so unavailable")
    gcc = shutil.which("gcc") or shutil.which("cc")
    if gcc is None:
        pytest.skip("no C compiler")

    exe = str(tmp_path / "client_demo")
    subprocess.run(
        [gcc, "-O1", "-o", exe,
         os.path.join(cc_dir, "client_demo.c"),
         "-I", cc_dir, "-L", cc_dir, "-lstf_runtime", "-lstf_session",
         "-lm", f"-Wl,-rpath,{cc_dir}"],
        check=True, capture_output=True, timeout=120)

    # strip the TPU-plugin bootstrap env: with it set, the embedded
    # interpreter's sitecustomize registers the plugin and jax backend
    # init can hang on a wedged relay even under JAX_PLATFORMS=cpu
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["PYTHONPATH"] = os.path.dirname(cc_dir) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([exe], env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    lines = dict(line.split(" ", 1) for line in
                 proc.stdout.strip().splitlines() if " " in line)
    assert "OK" in proc.stdout

    c_l0 = float(lines["l0"])
    c_l1 = float(lines["l1"])
    c_gradcheck = float(lines["gradcheck_max_err"])
    c_w_after = np.array([float(v) for v in lines["W_after"].split()],
                         np.float32).reshape(3, 2)
    assert c_l1 < c_l0
    assert c_gradcheck < 1e-3

    # ---- same model natively in Python: numbers must match -------------
    B, D_IN, D_OUT, LR = 4, 3, 2, 0.1
    xv = np.sin(0.7 * np.arange(B * D_IN, dtype=np.float32) + 0.3) \
        .reshape(B, D_IN).astype(np.float32)
    tv = np.cos(0.3 * np.arange(B * D_OUT, dtype=np.float32) - 0.2) \
        .reshape(B, D_OUT).astype(np.float32)
    w0 = (0.05 * np.arange(1, D_IN * D_OUT + 1, dtype=np.float32)) \
        .reshape(D_IN, D_OUT)

    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [B, D_IN], name="x")
    t = stf.placeholder(stf.float32, [B, D_OUT], name="t")
    W = stf.Variable(w0, name="W")
    b = stf.Variable(np.zeros((D_OUT,), np.float32), name="b")
    y = stf.matmul(x, W._ref) + b._ref
    loss = stf.reduce_mean(stf.square(y - t))
    train = stf.train.GradientDescentOptimizer(LR).minimize(loss)
    sess = stf.Session()
    sess.run(stf.global_variables_initializer())
    feed = {x: xv, t: tv}
    py_l0 = sess.run(loss, feed)
    sess.run(train, feed)
    py_l1 = sess.run(loss, feed)
    py_w = np.asarray(sess.run(W.value()))

    np.testing.assert_allclose(c_l0, py_l0, rtol=1e-5)
    np.testing.assert_allclose(c_l1, py_l1, rtol=1e-5)
    np.testing.assert_allclose(c_w_after, py_w, rtol=1e-5, atol=1e-7)
