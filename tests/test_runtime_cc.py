"""Native C++ runtime tests via ctypes round-trips (SURVEY §4)."""

import json
import os
import struct

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.lib import crc32c as pycrc
from simple_tensorflow_tpu.runtime import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native runtime not built")


def test_version():
    assert native.version().startswith("stf-runtime")


def test_crc32c_matches_python():
    for payload in [b"", b"a", b"hello world", os.urandom(1024),
                    os.urandom(7)]:
        # pure-python reference (force the table path with crc=0 short-circuit
        # bypassed by computing manually)
        crc = 0xFFFFFFFF
        for b in payload:
            crc = pycrc._TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
        expect = crc ^ 0xFFFFFFFF
        assert native.crc32c(payload) == expect
        mask = (((expect >> 15) | (expect << 17)) + 0xA282EAD8) & 0xFFFFFFFF
        assert native.masked_crc32c(payload) == mask


def test_tfrecord_native_roundtrip(tmp_path):
    path = str(tmp_path / "native.tfrecord")
    records = [os.urandom(np.random.RandomState(i).randint(0, 2000))
               for i in range(50)] + [b""]
    native.write_tfrecords(path, records)
    got = list(native.read_tfrecords(path, batch=7))
    assert got == records


def test_tfrecord_native_vs_python_format(tmp_path):
    """Native writer output must parse with the pure-python reader and
    vice versa (format parity with ref record_writer.cc)."""
    from simple_tensorflow_tpu.lib.io import tf_record

    path = str(tmp_path / "a.tfrecord")
    records = [b"alpha", b"", b"x" * 1000]
    native.write_tfrecords(path, records)
    assert list(tf_record._read_records_py(path)) == records

    path2 = str(tmp_path / "b.tfrecord")
    with tf_record.TFRecordWriter(path2) as w:
        for r in records:
            w.write(r)
    assert list(native.read_tfrecords(path2)) == records


def test_tfrecord_gzip(tmp_path):
    path = str(tmp_path / "c.tfrecord.gz")
    records = [b"compressed", b"records" * 100]
    native.write_tfrecords(path, records, compression=2)
    # gzip magic
    with open(path, "rb") as f:
        assert f.read(2) == b"\x1f\x8b"
    assert list(native.read_tfrecords(path)) == records


def test_tfrecord_corruption_detected(tmp_path):
    path = str(tmp_path / "d.tfrecord")
    native.write_tfrecords(path, [b"payload-abcdef"])
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a data byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(stf.errors.DataLossError):
        list(native.read_tfrecords(path))


def test_arena():
    a = native.Arena(block_bytes=4096)
    x = a.alloc_ndarray((16, 16), np.float32)
    x[:] = 3.0
    assert a.bytes_in_use >= 16 * 16 * 4
    y = a.alloc_ndarray((100000,), np.uint8)  # forces a new block
    y[:] = 7
    assert (x == 3.0).all()
    assert a.bytes_reserved >= a.bytes_in_use
    # 64-byte alignment
    assert x.ctypes.data % 64 == 0 and y.ctypes.data % 64 == 0
    a.reset()
    assert a.bytes_in_use == 0
    a.close()


def test_prune_toposort_flat():
    # diamond: 0->1, 0->2, 1->3, 2->3 ; extra orphan node 4
    edges = np.array([[0, 1], [0, 2], [1, 3], [2, 3]], np.int32)
    order = native.prune_toposort(5, edges, [3])
    assert order is not None and set(order) == {0, 1, 2, 3}
    pos = {n: i for i, n in enumerate(order)}
    assert pos[0] < pos[1] and pos[0] < pos[2]
    assert pos[1] < pos[3] and pos[2] < pos[3]
    # pruning: only ask for node 1
    order2 = native.prune_toposort(5, edges, [1])
    assert set(order2) == {0, 1}
    # cycle -> None
    cyc = np.array([[0, 1], [1, 0]], np.int32)
    assert native.prune_toposort(2, cyc, [1]) is None


def test_native_prune_matches_python_on_real_graph():
    from simple_tensorflow_tpu.framework import lowering

    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [4], name="x")
    h = x
    for i in range(600):  # push past _NATIVE_PRUNE_MIN_NODES
        h = h + float(i)
    loss = stf.reduce_sum(h)
    dead = stf.square(x)  # not an ancestor of loss
    g = stf.get_default_graph()
    assert len(g.get_operations()) >= lowering._NATIVE_PRUNE_MIN_NODES
    order = lowering.prune([loss.op], fed_tensors={x})
    names = {op.name for op in order}
    assert loss.op.name in names
    assert dead.op.name not in names
    # dependencies before dependents
    pos = {op: i for i, op in enumerate(order)}
    for op in order:
        for t in op.inputs:
            if t.op in pos:
                assert pos[t.op] < pos[op]


def test_cgraph_builds_importable_graphdef():
    g = native.CGraph()
    a = g.add_node("Const", "a")
    g.set_attr(a, "value_f", 2.0)
    g.add_output(a, "float32", [])
    b = g.add_node("Const", "b")
    g.set_attr(b, "value_f", 3.0)
    g.add_output(b, "float32", [])
    add = g.add_node("AddV2", "add")
    g.add_input(add, a, 0)
    g.add_input(add, b, 0)
    g.add_output(add, "float32", [])
    assert g.num_nodes == 3
    gd = json.loads(g.to_json())
    assert [n["name"] for n in gd["node"]] == ["a", "b", "add"]
    assert gd["node"][2]["input"] == ["a:0", "b:0"]
    assert gd["node"][0]["attr"]["value_f"] == 2.0
    assert gd["node"][2]["output_specs"] == [[[], "float32"]]
    g.close()


def test_cgraph_duplicate_name_raises():
    g = native.CGraph()
    g.add_node("NoOp", "n")
    with pytest.raises(stf.errors.OpError):
        g.add_node("NoOp", "n")
    g.close()


def test_session_run_uses_native_prune_smoke():
    """End-to-end: a big graph session step with the native pruner wired."""
    stf.reset_default_graph()
    x = stf.placeholder(stf.float32, [8], name="x")
    h = x
    for i in range(600):
        h = h * 1.0001 + 0.001
    y = stf.reduce_sum(h)
    with stf.Session() as sess:
        val = sess.run(y, {x: np.ones(8, np.float32)})
    assert np.isfinite(val)


def test_corruption_past_first_batch_no_duplicates(tmp_path):
    """Regression: a corrupt record past batch 1 must not restart the
    stream (previously the iterator fell back to the Python reader and
    re-delivered records 0..k twice)."""
    from simple_tensorflow_tpu.lib.io import tf_record

    path = str(tmp_path / "e.tfrecord")
    records = [struct.pack("<I", i) * 3 for i in range(300)]
    native.write_tfrecords(path, records)
    raw = bytearray(open(path, "rb").read())
    # corrupt a byte inside record ~290's payload: each record is
    # 12 + 12 + 4 = 28 bytes on disk
    raw[28 * 290 + 14] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    got = []
    with pytest.raises(stf.errors.DataLossError):
        for r in tf_record.tf_record_iterator(path):
            got.append(r)
    # good prefix delivered exactly once, in order
    assert got == records[:290]
