"""Session handles (ref: python/ops/session_ops.py:58,155,
core/kernels/session_ops.cc): fetched tensors stay device-resident
across Session.run calls and feed back without a host round trip."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


class TestSessionHandles:
    def test_handle_round_trip(self):
        stf.reset_default_graph()
        a = stf.constant(np.arange(8, dtype=np.float32))
        h_op = stf.get_session_handle(a * 2.0)
        holder, t = stf.get_session_tensor(None, stf.float32)
        out = t + 1.0
        with stf.Session() as sess:
            handle = sess.run(h_op)
            assert isinstance(handle, stf.TensorHandle)
            assert handle.handle.startswith("stf_handle_")
            # feed the TensorHandle object directly (ref allows both)
            r = sess.run(out, {holder: handle})
            np.testing.assert_allclose(r, np.arange(8) * 2.0 + 1.0)
            # feed the raw string too
            r2 = sess.run(out, {holder: np.asarray(handle.handle,
                                                   dtype=object)})
            np.testing.assert_allclose(r2, r)

    def test_value_stays_device_resident(self):
        # handle store holds a jax.Array; pinning + feeding back never
        # converts to numpy. Placeholder input defeats const folding, so
        # the matmul truly executes on device and GetSessionHandle runs
        # post-host on the RAW device array.
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [16, 16])
        h_op = stf.get_session_handle(stf.matmul(x, x))
        with stf.Session() as sess:
            handle = sess.run(h_op, {x: np.ones((16, 16), np.float32)})
            stored = sess._handles[handle.handle]
            assert hasattr(stored, "sharding"), type(stored)
            np.testing.assert_allclose(np.asarray(stored),
                                       np.full((16, 16), 16.0))

    def test_no_host_transfer_under_disallow_guard(self):
        # run→handle→feed round trip with the L0 transfer guard set to
        # "disallow": a host round trip of the 1 MiB payload would raise;
        # the handle path must not.
        stf.reset_default_graph()
        cfg = stf.ConfigProto(transfer_guard="disallow",
                              transfer_guard_threshold_bytes=1 << 16)
        a = stf.constant(np.ones((512, 512), np.float32))  # 1 MiB
        h_op = stf.get_session_handle(a * 3.0)
        holder, t = stf.get_session_tensor(None, stf.float32)
        s = stf.reduce_sum(t)  # scalar fetch: below guard threshold
        sess = stf.Session(config=cfg)
        handle = sess.run(h_op)
        for _ in range(4):  # beyond the 2-call warmup the guard allows
            val = sess.run(s, {holder: handle})
        assert val == 3.0 * 512 * 512

    def test_eval_and_delete(self):
        stf.reset_default_graph()
        h_op = stf.get_session_handle(
            stf.constant(np.array([1.0, 2.0], np.float32)))
        holder, t = stf.get_session_tensor(None, stf.float32)
        with stf.Session() as sess:
            handle = sess.run(h_op)
            np.testing.assert_allclose(handle.eval(), [1.0, 2.0])
            handle.delete()
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="handle"):
                sess.run(t, {holder: handle})

    def test_delete_session_tensor_op(self):
        stf.reset_default_graph()
        h_op = stf.get_session_handle(stf.constant(np.float32(7.0)))
        del_holder, deleter = stf.delete_session_tensor()
        holder, t = stf.get_session_tensor(None, stf.float32)
        with stf.Session() as sess:
            handle = sess.run(h_op)
            sess.run(deleter, {del_holder: handle})
            with pytest.raises(stf.errors.InvalidArgumentError):
                sess.run(t, {holder: handle})

    def test_shared_fetch_returns_numpy(self):
        # fetching a tensor that ALSO feeds GetSessionHandle must still
        # return numpy, not a raw jax.Array
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [4])
        y = x * 2.0
        h_op = stf.get_session_handle(y)
        with stf.Session() as sess:
            hv, yv = sess.run([h_op, y],
                              {x: np.arange(4, dtype=np.float32)})
        assert isinstance(hv, stf.TensorHandle)
        assert isinstance(yv, np.ndarray), type(yv)
        np.testing.assert_allclose(yv, [0., 2., 4., 6.])

    def test_handle_of_host_tensor(self):
        # handles work for host-stage values too (e.g. strings)
        stf.reset_default_graph()
        h_op = stf.get_session_handle(
            stf.constant(np.array(["a", "b"], dtype=object)))
        holder, t = stf.get_session_tensor(None, stf.string)
        with stf.Session() as sess:
            handle = sess.run(h_op)
            out = sess.run(t, {holder: handle})
        assert list(out) == ["a", "b"]
