"""Pallas kernels vs jnp references (interpret mode on the CPU test mesh).

Mirrors the reference's per-kernel numeric tests
(ref: tensorflow/python/kernel_tests/softmax_op_test.py etc.): forward
against a naive implementation, backward against jax.grad of the naive one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_tensorflow_tpu.ops.pallas import (
    flash_attention, layer_norm, quant_matmul, softmax_cross_entropy)
from simple_tensorflow_tpu.ops.pallas.flash_attention import mha_reference
from simple_tensorflow_tpu.ops.pallas.layer_norm import layer_norm_reference
from simple_tensorflow_tpu.ops.pallas.quant_matmul import (
    quant_matmul_reference, quantize_colwise)
from simple_tensorflow_tpu.ops.pallas.softmax_xent import (
    softmax_cross_entropy_reference)


def rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.key(key), shape, dtype=dtype)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_reference(self, causal):
        b, h, s, d = 2, 3, 64, 16
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_unaligned_seq_padding(self):
        b, h, s, d = 1, 2, 50, 16   # 50 not a multiple of block 32
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        ref = mha_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_cross_attention_lengths(self):
        b, h, sq, sk, d = 1, 2, 32, 96, 16
        q = rand(0, (b, h, sq, d))
        k = rand(1, (b, h, sk, d))
        v = rand(2, (b, h, sk, d))
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        ref = mha_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("d", [8, 16])
    def test_gradients_match_reference(self, causal, d):
        b, h, s = 1, 2, 32
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal,
                                block_q=16, block_k=16)
            return jnp.sum(jnp.sin(o))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=causal)))

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4)

    def test_bf16(self):
        b, h, s, d = 1, 2, 64, 32
        q, k, v = (rand(i, (b, h, s, d), jnp.bfloat16) for i in range(3))
        out = flash_attention(q, k, v, causal=True)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out.astype(np.float32),
                                   ref.astype(np.float32), atol=3e-2)

    @pytest.mark.parametrize("bias_shape", [(2, 64), (2, 1, 1, 64)])
    def test_padding_bias_matches_reference(self, bias_shape):
        b, h, s, d = 2, 3, 64, 16
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))
        # mask out the tail 20 key positions of batch 0, 10 of batch 1
        mask = np.zeros((b, s), np.float32)
        mask[0, -20:] = -1e9
        mask[1, -10:] = -1e9
        bias = mask.reshape(bias_shape)
        out = flash_attention(q, k, v, bias=bias, block_q=32, block_k=32)
        ref = mha_reference(q, k, v, bias=mask)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_bias_gradients_match_reference(self):
        b, h, s, d = 1, 2, 32, 16
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))
        mask = np.zeros((b, s), np.float32)
        mask[0, -7:] = -1e9

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, bias=mask, block_q=16, block_k=16)
            return jnp.sum(jnp.sin(o))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(mha_reference(q, k, v, bias=mask)))

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4)

    def test_per_head_bias_rejected(self):
        b, h, s, d = 1, 2, 32, 16
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))
        with pytest.raises(NotImplementedError):
            flash_attention(q, k, v, bias=np.zeros((b, h, s, s), np.float32))

    def test_dropout_deterministic_and_unbiased(self):
        b, h, s, d = 2, 4, 64, 16
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))
        kwargs = dict(dropout_rate=0.4, dropout_seed=123,
                      block_q=32, block_k=32)
        o1 = flash_attention(q, k, v, **kwargs)
        o2 = flash_attention(q, k, v, **kwargs)
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
        o3 = flash_attention(q, k, v, dropout_rate=0.4, dropout_seed=999,
                             block_q=32, block_k=32)
        assert not np.allclose(np.asarray(o1), np.asarray(o3))
        # dropout zeroes ~rate of the prob mass: E[o] ~= no-dropout output.
        # With rate 0.4 and s=64 keys the per-element std is large, so only
        # check the batch-mean is in the right ballpark.
        o_ref = mha_reference(q, k, v)
        np.testing.assert_allclose(float(jnp.mean(o1)),
                                   float(jnp.mean(o_ref)), atol=0.05)

    def test_dropout_rate_zero_equals_no_dropout(self):
        b, h, s, d = 1, 2, 32, 16
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))
        o0 = flash_attention(q, k, v, block_q=16, block_k=16)
        # rate exactly 0 skips the dropout plumbing even with a seed
        o1 = flash_attention(q, k, v, dropout_rate=0.0, dropout_seed=7,
                             block_q=16, block_k=16)
        np.testing.assert_array_equal(np.asarray(o0), np.asarray(o1))

    def test_dropout_gradients_match_finite_differences(self):
        # The dropout mask is a deterministic function of (seed, positions),
        # so flash(..., seed) is a fixed differentiable function and its
        # analytic vjp must match finite differences.
        b, h, s, d = 1, 1, 16, 8
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))

        def loss(q):
            o = flash_attention(q, k, v, dropout_rate=0.3, dropout_seed=42,
                                block_q=8, block_k=8)
            return jnp.sum(o * o)

        g = np.asarray(jax.grad(loss)(q))
        eps = 1e-3
        rng = np.random.RandomState(0)
        for _ in range(5):
            i = tuple(rng.randint(0, n) for n in q.shape)
            dq = np.zeros(q.shape, np.float32)
            dq[i] = eps
            fd = (float(loss(q + dq)) - float(loss(q - dq))) / (2 * eps)
            np.testing.assert_allclose(g[i], fd, atol=1e-2, rtol=1e-2)

    def test_dropout_with_causal_and_bias(self):
        b, h, s, d = 1, 2, 32, 16
        q, k, v = (rand(i, (b, h, s, d)) for i in range(3))
        mask = np.zeros((b, s), np.float32)
        mask[0, -5:] = -1e9
        o = flash_attention(q, k, v, bias=mask, causal=True,
                            dropout_rate=0.2, dropout_seed=5,
                            block_q=16, block_k=16)
        assert np.isfinite(np.asarray(o, np.float32)).all()
        # masked keys stay masked under dropout scaling: rows attending
        # only to live keys -> output finite; compare masked-average vs
        # reference loosely
        o2 = flash_attention(q, k, v, bias=mask, causal=True,
                             dropout_rate=0.2, dropout_seed=5,
                             block_q=16, block_k=16)
        np.testing.assert_array_equal(np.asarray(o), np.asarray(o2))


class TestLayerNorm:
    def test_forward(self):
        x = rand(0, (4, 6, 128))
        gamma = rand(1, (128,)) * 0.1 + 1.0
        beta = rand(2, (128,)) * 0.1
        out = layer_norm(x, gamma, beta, block_rows=8)
        ref = layer_norm_reference(x, gamma, beta)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_backward(self):
        x = rand(0, (16, 64))
        gamma = rand(1, (64,)) * 0.1 + 1.0
        beta = rand(2, (64,)) * 0.1

        def f(impl):
            def loss(x, g, b):
                return jnp.sum(jnp.tanh(impl(x, g, b)))
            return jax.grad(loss, argnums=(0, 1, 2))(x, gamma, beta)

        g1 = f(lambda x, g, b: layer_norm(x, g, b, block_rows=8))
        g2 = f(layer_norm_reference)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(a, b_, atol=1e-5, rtol=1e-4)

    def test_unaligned_rows(self):
        x = rand(0, (13, 32))   # 13 rows not a multiple of block 8
        gamma = jnp.ones((32,))
        beta = jnp.zeros((32,))
        out = layer_norm(x, gamma, beta, block_rows=8)
        ref = layer_norm_reference(x, gamma, beta)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_mixed_param_dtypes_backward(self):
        # cotangent dtypes must match each primal's dtype
        x = rand(0, (16, 64), jnp.bfloat16)
        gamma = jnp.ones((64,), jnp.bfloat16)
        beta = jnp.zeros((64,), jnp.float32)
        dx, dg, db = jax.grad(
            lambda x, g, b: jnp.sum(
                layer_norm(x, g, b, block_rows=8).astype(jnp.float32)),
            argnums=(0, 1, 2))(x, gamma, beta)
        assert dx.dtype == jnp.bfloat16
        assert dg.dtype == jnp.bfloat16
        assert db.dtype == jnp.float32


class TestSoftmaxXent:
    def test_forward(self):
        logits = rand(0, (24, 512)) * 3
        labels = jax.random.randint(jax.random.key(1), (24,), 0, 512)
        out = softmax_cross_entropy(logits, labels, block_rows=8)
        ref = softmax_cross_entropy_reference(logits, labels)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_backward(self):
        logits = rand(0, (8, 128))
        labels = jax.random.randint(jax.random.key(1), (8,), 0, 128)

        g1 = jax.grad(lambda l: jnp.sum(
            softmax_cross_entropy(l, labels, block_rows=8)))(logits)
        g2 = jax.grad(lambda l: jnp.sum(
            softmax_cross_entropy_reference(l, labels)))(logits)
        np.testing.assert_allclose(g1, g2, atol=1e-5, rtol=1e-4)

    def test_batch_dims(self):
        logits = rand(0, (2, 5, 64))
        labels = jax.random.randint(jax.random.key(1), (2, 5), 0, 64)
        out = softmax_cross_entropy(logits, labels)
        assert out.shape == (2, 5)
        ref = softmax_cross_entropy_reference(logits, labels)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_label_smoothing_fused(self):
        # fused smoothing == composed soft-target xent (fwd + grad),
        # across vocab blocks with a ragged edge
        logits = rand(0, (16, 300)) * 3
        labels = jax.random.randint(jax.random.key(1), (16,), 0, 300)
        sm = 0.1

        def composed(l):
            logp = jax.nn.log_softmax(l.astype(jnp.float32), axis=-1)
            conf, low = 1 - sm, sm / 299
            soft = jax.nn.one_hot(labels, 300) * (conf - low) + low
            return -jnp.sum(soft * logp, -1)

        out = softmax_cross_entropy(logits, labels, label_smoothing=sm,
                                    block_rows=8, block_vocab=128)
        np.testing.assert_allclose(out, composed(logits), atol=1e-5,
                                   rtol=1e-5)
        g1 = jax.grad(lambda l: jnp.sum(softmax_cross_entropy(
            l, labels, label_smoothing=sm, block_rows=8,
            block_vocab=128)))(logits)
        g2 = jax.grad(lambda l: jnp.sum(composed(l)))(logits)
        np.testing.assert_allclose(g1, g2, atol=1e-5, rtol=1e-4)

    def test_vocab_blocking_ragged_edge(self):
        # vocab spanning several blocks with a ragged final block (the
        # streamed online-softmax path, unpadded); fwd + bwd vs reference
        logits = rand(0, (16, 700)) * 3
        labels = jax.random.randint(jax.random.key(1), (16,), 0, 700)
        out = softmax_cross_entropy(logits, labels, block_rows=8,
                                    block_vocab=256)
        ref = softmax_cross_entropy_reference(logits, labels)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
        g1 = jax.grad(lambda l: jnp.sum(softmax_cross_entropy(
            l, labels, block_rows=8, block_vocab=256)))(logits)
        g2 = jax.grad(lambda l: jnp.sum(
            softmax_cross_entropy_reference(l, labels)))(logits)
        np.testing.assert_allclose(g1, g2, atol=1e-5, rtol=1e-4)


class TestQuantMatmul:
    def test_matches_reference_quantization(self):
        x = rand(0, (48, 64))
        w = rand(1, (64, 96))
        wq, ws = quantize_colwise(w)
        out = quant_matmul(x, wq, ws)
        ref = quant_matmul_reference(x, wq, ws)
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)

    def test_straight_through_gradient(self):
        from simple_tensorflow_tpu.ops.pallas import quant_matmul_ste

        x = rand(0, (16, 32))
        w = rand(1, (32, 24))
        wq, ws = quantize_colwise(w)
        c = rand(2, (16, 24))   # fixed cotangent weighting (linear loss)
        dx = jax.grad(lambda x: jnp.sum(
            quant_matmul_ste(x, wq, ws) * c))(x)
        # STE: dx must equal the dense-matmul gradient under the same
        # cotangent (quantization rounding contributes no derivative)
        wd = wq.astype(jnp.float32) * ws[None, :]
        dx_ref = jax.grad(lambda x: jnp.sum((x @ wd) * c))(x)
        np.testing.assert_allclose(dx, dx_ref, atol=1e-5, rtol=1e-5)

    def test_scale_gradient(self):
        from simple_tensorflow_tpu.ops.pallas import quant_matmul_ste
        from simple_tensorflow_tpu.ops.pallas.quant_matmul import (
            quantize_rowwise)

        x = rand(0, (16, 32))
        w = rand(1, (32, 24))
        wq, ws = quantize_colwise(w)
        c = rand(2, (16, 24))
        d_ws = jax.grad(lambda s: jnp.sum(
            quant_matmul_ste(x, wq, s) * c))(ws)
        # y = (xq@wq) * x_scale ⊗ w_scale — analytic d/dw_scale
        xq, x_scale = quantize_rowwise(x)
        acc = (xq.astype(jnp.int32) @ wq.astype(jnp.int32)).astype(
            jnp.float32)
        ref = jnp.sum(c * acc * x_scale[:, None], axis=0)
        np.testing.assert_allclose(d_ws, ref, atol=1e-4, rtol=1e-4)

    def test_close_to_float_matmul(self):
        x = rand(0, (32, 128))
        w = rand(1, (128, 64))
        wq, ws = quantize_colwise(w)
        out = quant_matmul(x, wq, ws)
        ref = x @ w
        # int8 dynamic quantization error budget
        err = jnp.abs(out - ref).max() / (jnp.abs(ref).max() + 1e-9)
        assert err < 0.05, float(err)


class TestGraphOps:
    def test_flash_attention_graph_op(self):
        import simple_tensorflow_tpu as stf

        stf.reset_default_graph()
        arrays = [np.asarray(rand(i, (1, 2, 32, 16))) for i in range(3)]
        out_t = stf.nn.fused_attention(*(stf.constant(a) for a in arrays),
                                       causal=True)
        sess = stf.Session()
        out = sess.run(out_t)
        ref = mha_reference(*arrays, causal=True)
        np.testing.assert_allclose(out, np.asarray(ref), atol=2e-5)

    def test_fused_ops_registered_on_package_import(self):
        import simple_tensorflow_tpu  # noqa: F401
        from simple_tensorflow_tpu.framework import op_registry

        for op_type in ("FlashAttention", "FusedLayerNorm",
                        "FusedSoftmaxXent", "QuantMatMul"):
            assert op_registry.is_registered(op_type), op_type


class TestLayerNormWideFeatures:
    def test_block_rows_shrink_for_wide_features(self):
        # (block_rows, n) f32 tiles must stay inside the VMEM budget: at
        # n=8192 the default 256-row block would be an 8 MB tile; the
        # wrapper shrinks rows and the result still matches the reference
        from simple_tensorflow_tpu.ops.pallas.layer_norm import (
            layer_norm, layer_norm_reference)

        # rows must exceed the shrunk block (4MB/8192/4 = 128) so the test
        # actually exercises the clamp: at 512 rows the old code would run
        # a 256-row / 8 MB tile, the clamp runs 128-row / 4 MB tiles
        x = rand(0, (512, 8192)).astype(jnp.bfloat16)
        g = jnp.ones((8192,), jnp.float32)
        b = jnp.zeros((8192,), jnp.float32)
        o1 = layer_norm(x, g, b)
        o2 = layer_norm_reference(x, g, b)
        np.testing.assert_allclose(o1.astype(jnp.float32),
                                   o2.astype(jnp.float32), atol=1e-2)
        gr = jax.grad(lambda x: jnp.sum(layer_norm(x, g, b)
                                        .astype(jnp.float32)))(x)
        assert gr.shape == x.shape


class TestQuantMatmulKBlocking:
    def test_multi_k_block_with_ragged_k(self):
        # contraction longer than TILE_K and NOT a multiple of it: the
        # streamed k-blocks must pad (a ragged final block accumulated
        # out-of-bounds garbage before the fix)
        from simple_tensorflow_tpu.ops.pallas.quant_matmul import TILE_K

        x = rand(0, (32, 2 * TILE_K + 64), jnp.bfloat16)
        w = rand(1, (2 * TILE_K + 64, 96))
        wq, s = quantize_colwise(w)
        o1 = quant_matmul(x, wq, s)
        o2 = quant_matmul_reference(x, wq, s)
        np.testing.assert_allclose(o1.astype(jnp.float32),
                                   o2.astype(jnp.float32), atol=1e-4,
                                   rtol=1e-4)


# ---------------------------------------------------------------------------
# ISSUE 11 satellites: backward-pass parity through the GRAPH path
# (stf.gradients -> SymbolicGradient -> the op's routed lowering ->
# custom VJP) against jax.grad of the XLA reference, plus odd/non-pow2
# shape coverage for all four kernels. Interpret mode on the CPU test
# mesh; shapes kept tiny so tier-1 wall time stays bounded.
# ---------------------------------------------------------------------------


class TestGraphBackwardParity:
    """Gradient parity of every routed kernel vs its XLA reference,
    exercised through stf.gradients on a live graph with the registry
    pinned to `force` (Pallas, interpret mode)."""

    @pytest.fixture(autouse=True)
    def _force_mode(self):
        import simple_tensorflow_tpu as stf

        stf.kernels.set_mode("force")
        stf.reset_default_graph()
        yield
        stf.kernels.set_mode(None)
        stf.kernels.clear_decisions()
        stf.reset_default_graph()

    def _session_grads(self, loss_t, xs):
        import simple_tensorflow_tpu as stf

        grads = stf.gradients(loss_t, xs)
        with stf.Session() as sess:
            return [np.asarray(g) for g in sess.run(grads)]

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_attention_graph_grads(self, causal):
        import simple_tensorflow_tpu as stf

        b, h, s, d = 1, 2, 37, 12    # odd seq, non-pow2 head_dim
        arrays = [np.asarray(rand(i, (b, h, s, d))) for i in range(3)]
        ts = [stf.constant(a) for a in arrays]
        out = stf.nn.fused_attention(*ts, causal=causal)
        loss = stf.reduce_sum(stf.sin(out))
        got = self._session_grads(loss, ts)

        def ref(q, k, v):
            return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=causal)))

        want = jax.grad(ref, argnums=(0, 1, 2))(*arrays)
        for g1, g2 in zip(got, want):
            np.testing.assert_allclose(g1, np.asarray(g2), atol=2e-4,
                                       rtol=2e-4)

    def test_layer_norm_graph_grads(self):
        import simple_tensorflow_tpu as stf

        x = np.asarray(rand(0, (13, 45)))          # both dims odd
        gamma = np.asarray(rand(1, (45,))) * 0.1 + 1.0
        beta = np.asarray(rand(2, (45,))) * 0.1
        ts = [stf.constant(a) for a in (x, gamma, beta)]
        out = stf.nn.fused_layer_norm(*ts)
        loss = stf.reduce_sum(stf.tanh(out))
        got = self._session_grads(loss, ts)

        def ref(x, g, b):
            return jnp.sum(jnp.tanh(layer_norm_reference(x, g, b)))

        want = jax.grad(ref, argnums=(0, 1, 2))(x, gamma, beta)
        for g1, g2 in zip(got, want):
            np.testing.assert_allclose(g1, np.asarray(g2), atol=1e-4,
                                       rtol=1e-3)

    def test_softmax_xent_graph_grads(self):
        import simple_tensorflow_tpu as stf

        logits = np.asarray(rand(0, (9, 301))) * 3  # ragged vocab block
        labels = np.asarray(jax.random.randint(
            jax.random.key(1), (9,), 0, 301), np.int32)
        lt = stf.constant(logits)
        out = stf.nn.fused_softmax_cross_entropy(
            lt, stf.constant(labels), label_smoothing=0.1)
        loss = stf.reduce_sum(out)
        (got,) = self._session_grads(loss, [lt])

        def ref(l):
            return jnp.sum(softmax_cross_entropy_reference(
                l, labels, label_smoothing=0.1))

        want = jax.grad(ref)(logits)
        np.testing.assert_allclose(got, np.asarray(want), atol=1e-4,
                                   rtol=1e-3)

    def test_quant_matmul_graph_grads(self):
        import simple_tensorflow_tpu as stf

        x = np.asarray(rand(0, (17, 33)))           # odd m/k/n
        w = np.asarray(rand(1, (33, 29)))
        wq, ws = quantize_colwise(w)
        xt = stf.constant(x)
        st = stf.constant(np.asarray(ws))
        out = stf.nn.quantized_matmul(xt, stf.constant(np.asarray(wq)), st)
        c = np.asarray(rand(2, (17, 29)))
        loss = stf.reduce_sum(out * stf.constant(c))
        got = self._session_grads(loss, [xt, st])
        from simple_tensorflow_tpu.ops.pallas.quant_matmul import (
            quant_matmul_ste_reference)

        def ref(x, s):
            return jnp.sum(quant_matmul_ste_reference(
                x, np.asarray(wq), s) * c)

        want = jax.grad(ref, argnums=(0, 1))(x, np.asarray(ws))
        for g1, g2 in zip(got, want):
            np.testing.assert_allclose(g1, np.asarray(g2), atol=2e-4,
                                       rtol=2e-4)


class TestOddShapeForward:
    """Non-pow2 / odd shape sweep for all four kernels (jax level,
    interpret mode): the padding/masking paths on ragged edges."""

    @pytest.mark.parametrize("shape", [(1, 1, 7, 4), (2, 3, 33, 24),
                                       (1, 2, 65, 12)])
    def test_flash_attention_odd(self, shape):
        b, h, s, d = shape
        q, k, v = (rand(i, shape) for i in range(3))
        out = flash_attention(q, k, v, block_q=16, block_k=16)
        ref = mha_reference(q, k, v)
        np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)

    @pytest.mark.parametrize("rows,n", [(1, 3), (7, 129), (29, 255)])
    def test_layer_norm_odd(self, rows, n):
        x = rand(0, (rows, n))
        g = rand(1, (n,)) * 0.1 + 1.0
        b = rand(2, (n,)) * 0.1
        out = layer_norm(x, g, b, block_rows=8)
        ref = layer_norm_reference(x, g, b)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-4)

    @pytest.mark.parametrize("rows,vocab", [(1, 5), (11, 257), (5, 1023)])
    def test_softmax_xent_odd(self, rows, vocab):
        logits = rand(0, (rows, vocab)) * 2
        labels = jax.random.randint(jax.random.key(1), (rows,), 0, vocab)
        out = softmax_cross_entropy(logits, labels, block_rows=8,
                                    block_vocab=128)
        ref = softmax_cross_entropy_reference(logits, labels)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-4)

    @pytest.mark.parametrize("m,k,n", [(1, 3, 5), (17, 65, 33),
                                       (31, 129, 7)])
    def test_quant_matmul_odd(self, m, k, n):
        x = rand(0, (m, k))
        w = rand(1, (k, n))
        wq, ws = quantize_colwise(w)
        out = quant_matmul(x, wq, ws)
        ref = quant_matmul_reference(x, wq, ws)
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
