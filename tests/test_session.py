"""Session/graph semantics tests (mirrors ref python/client/session_test.py,
python/framework/ops_test.py)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def test_feed_fetch():
    x = stf.placeholder(stf.float32, [None, 3])
    y = x * 2.0
    with stf.Session() as sess:
        out = sess.run(y, feed_dict={x: np.ones((2, 3), np.float32)})
        np.testing.assert_allclose(out, 2 * np.ones((2, 3)))
        # different batch size -> retrace, same cache entry
        out = sess.run(y, feed_dict={x: np.ones((5, 3), np.float32)})
        assert out.shape == (5, 3)


def test_fetch_structures():
    a = stf.constant(1.0)
    b = stf.constant(2.0)
    with stf.Session() as sess:
        res = sess.run({"x": a, "pair": [a, b], "t": (b,)})
        assert float(res["x"]) == 1.0
        assert [float(v) for v in res["pair"]] == [1.0, 2.0]
        assert isinstance(res["t"], tuple)


def test_variables_and_init():
    v = stf.Variable(3.0, name="v")
    w = stf.Variable(lambda: stf.constant(4.0), name="w")
    total = v + w
    with stf.Session() as sess:
        with pytest.raises(stf.errors.FailedPreconditionError):
            sess.run(total)
        sess.run(stf.global_variables_initializer())
        assert float(sess.run(total)) == 7.0


def test_assign_semantics():
    v = stf.Variable(1.0, name="v")
    assign = v.assign(5.0)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        assert float(sess.run(assign)) == 5.0
        assert float(sess.run(v)) == 5.0
        sess.run(v.assign_add(2.0))
        assert float(sess.run(v)) == 7.0


def test_read_after_write_with_control_deps():
    v = stf.Variable(1.0, name="v")
    assign = v.assign(10.0)
    with stf.control_dependencies([assign]):
        read = v.read_value()
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        assert float(sess.run(read)) == 10.0


def test_name_scoping():
    with stf.name_scope("outer"):
        c = stf.constant(1.0, name="c")
        with stf.name_scope("inner"):
            d = stf.constant(2.0, name="c")
    assert c.op.name == "outer/c"
    assert d.op.name == "outer/inner/c"
    g = stf.get_default_graph()
    assert g.get_tensor_by_name("outer/c:0") is c


def test_gradients_simple():
    x = stf.placeholder(stf.float32, [])
    y = x * x + 3.0 * x
    (dx,) = stf.gradients(y, [x])
    with stf.Session() as sess:
        g = sess.run(dx, feed_dict={x: 2.0})
        assert float(g) == pytest.approx(7.0)


def test_gradients_disconnected():
    x = stf.placeholder(stf.float32, [])
    z = stf.placeholder(stf.float32, [])
    y = x * 2.0
    grads = stf.gradients(y, [x, z])
    assert grads[1] is None


def test_gradients_through_variables():
    v = stf.Variable(np.array([1.0, 2.0], np.float32), name="v")
    loss = stf.reduce_sum(v * v)
    (dv,) = stf.gradients(loss, [v])
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        np.testing.assert_allclose(sess.run(dv), [2.0, 4.0])


def test_gradients_through_variable_reads():
    # TF-1 treats v, v.value(), and v.read_value() as the same variable
    # for tf.gradients; a loss built from any read must produce a real
    # gradient, and mixed reads must SUM their contributions.
    v = stf.Variable(np.array([1.0, 2.0], np.float32), name="vr")
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        for y in (stf.reduce_sum(stf.square(v.value())),
                  stf.reduce_sum(stf.square(v.read_value()))):
            (g,) = stf.gradients(y, [v])
            assert g is not None
            np.testing.assert_allclose(sess.run(g), [2.0, 4.0])
        mixed = (stf.reduce_sum(stf.square(v))
                 + stf.reduce_sum(v.value()))
        (gm,) = stf.gradients(mixed, [v])
        np.testing.assert_allclose(sess.run(gm), [3.0, 5.0])


def test_concurrent_run_serializes_device_stage():
    # TF-1 sessions are thread-safe: N threads x M increments must
    # commit every update (unsynchronized, concurrent steps read the
    # same donated state — deleted-buffer errors and lost updates)
    import threading

    v = stf.Variable(0.0, name="conc_ctr")
    inc = stf.assign_add(v, 1.0)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        errs = []

        def worker():
            try:
                for _ in range(50):
                    sess.run(inc)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
        assert float(np.asarray(sess.run(v))) == 200.0


def test_concurrent_blocked_dequeue_does_not_block_producer():
    # host stages must stay concurrent: a consumer blocked in a host
    # dequeue cannot hold the lock the producer needs
    import threading
    import time

    q = stf.FIFOQueue(capacity=2, dtypes=[stf.int32], shapes=[[]])
    x = stf.placeholder(stf.int32, [])
    enq = q.enqueue([x])
    deq = q.dequeue()
    with stf.Session() as sess:
        out = []

        def consumer():
            for _ in range(6):
                out.append(int(np.asarray(sess.run(deq))))

        c = threading.Thread(target=consumer)
        c.start()
        time.sleep(0.15)  # consumer parks in the blocking host dequeue
        for i in range(6):
            sess.run(enq, feed_dict={x: i})
        c.join(timeout=20)
        assert not c.is_alive()
        assert sorted(out) == list(range(6))


def test_assert_raises_typed_error_and_preserves_state():
    # Assert rides the CheckNumerics flag channel: a failure raises
    # InvalidArgumentError (catchable by type, not an opaque
    # JaxRuntimeError from inside a jax callback) BEFORE the step's
    # variable updates commit; the pass path commits normally.
    with stf.Session() as sess:
        with stf.get_default_graph().control_dependencies(
                [stf.assert_positive(stf.constant([-1.0]),
                                     message="must be positive")]):
            out = stf.identity(stf.constant(1.0))
        # the user's message= must appear in the typed error
        with pytest.raises(stf.errors.InvalidArgumentError,
                           match="must be positive"):
            sess.run(out)

        v = stf.Variable(1.0, name="assert_v")
        sess.run(stf.global_variables_initializer())
        bad = stf.assert_positive(stf.constant([-1.0]))
        with stf.get_default_graph().control_dependencies([bad]):
            upd = stf.assign_add(v, 1.0)
        with pytest.raises(stf.errors.InvalidArgumentError):
            sess.run(upd)
        assert float(np.asarray(sess.run(v))) == 1.0  # no commit
        good = stf.assert_positive(stf.constant([5.0]))
        with stf.get_default_graph().control_dependencies([good]):
            upd2 = stf.assign_add(v, 1.0)
        sess.run(upd2)
        assert float(np.asarray(sess.run(v))) == 2.0


def test_feed_sparse_tensor_value():
    # TF-1 contract: feed_dict={sparse_tensor: SparseTensorValue} expands
    # into the component tensors; fetching the SparseTensor returns a
    # SparseTensorValue (ref python/client/session.py feed/fetch mappers).
    sp = stf.sparse_placeholder(stf.float32, shape=[2, 4], name="spf")
    dense = stf.sparse_tensor_to_dense(sp, default_value=0.0)
    val = stf.SparseTensorValue(
        indices=np.array([[0, 0], [1, 2]], np.int64),
        values=np.array([3.0, 4.0], np.float32),
        dense_shape=np.array([2, 4], np.int64))
    with stf.Session() as sess:
        out = np.asarray(sess.run(dense, feed_dict={sp: val}))
        np.testing.assert_allclose(
            out, [[3, 0, 0, 0], [0, 0, 4, 0]])
        # plain-tuple form works too
        np.asarray(sess.run(
            dense, feed_dict={sp: (val.indices, val.values, [2, 4])}))
        # a static-shape placeholder rejects a mismatched dense_shape
        import pytest as _pytest
        with _pytest.raises(ValueError, match="dense_shape"):
            sess.run(dense,
                     feed_dict={sp: (val.indices, val.values, [3, 4])})
        fetched = sess.run(sp, feed_dict={sp: val})
        assert isinstance(fetched, stf.SparseTensorValue)
        np.testing.assert_allclose(np.asarray(fetched.values), [3.0, 4.0])
        # a dense array is not a sparse feed value: targeted TypeError
        with _pytest.raises(TypeError, match="SparseTensorValue"):
            sess.run(dense, feed_dict={sp: np.zeros((2, 4))})
        # wrong-rank dense_shape must not slip through the ravel
        with _pytest.raises(ValueError, match="rank-1"):
            sess.run(dense, feed_dict={
                sp: (val.indices, val.values, [[2, 4]])})


def test_sgd_training_loop_converges():
    """Linear regression: the MNIST-softmax e2e pattern (BASELINE config 1)."""
    rng = np.random.RandomState(0)
    x_data = rng.randn(64, 3).astype(np.float32)
    true_w = np.array([[1.0], [-2.0], [0.5]], np.float32)
    y_data = x_data @ true_w

    x = stf.placeholder(stf.float32, [None, 3])
    y = stf.placeholder(stf.float32, [None, 1])
    w = stf.Variable(np.zeros((3, 1), np.float32), name="w")
    pred = stf.matmul(x, w)
    loss = stf.reduce_mean(stf.square(pred - y))
    train_op = stf.train.GradientDescentOptimizer(0.1).minimize(loss)

    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        losses = []
        for _ in range(200):
            _, l = sess.run([train_op, loss],
                            feed_dict={x: x_data, y: y_data})
            losses.append(float(l))
        assert losses[-1] < 1e-3
        np.testing.assert_allclose(sess.run(w), true_w, atol=0.05)


def test_cond():
    p = stf.placeholder(stf.bool, [])
    x = stf.constant(2.0)
    out = stf.cond(p, lambda: x * 2.0, lambda: x - 1.0)
    with stf.Session() as sess:
        assert float(sess.run(out, {p: True})) == 4.0
        assert float(sess.run(out, {p: False})) == 1.0


def test_while_loop():
    i0 = stf.constant(0)
    s0 = stf.constant(0)
    i, s = stf.while_loop(lambda i, s: stf.less(i, 10),
                          lambda i, s: (i + 1, s + i), (i0, s0))
    with stf.Session() as sess:
        iv, sv = sess.run([i, s])
        assert int(iv) == 10
        assert int(sv) == 45


def test_random_reproducible_with_seed():
    stf.set_random_seed(42)
    r = stf.random_normal([4], seed=7)
    with stf.Session() as sess:
        a = sess.run(r)
        b = sess.run(r)
    # different step keys -> different draws across runs
    assert not np.allclose(a, b)
    stf.reset_default_graph()
    stf.set_random_seed(42)
    r2 = stf.random_normal([4], seed=7)
    with stf.Session() as sess2:
        a2 = sess2.run(r2)
    np.testing.assert_allclose(a, a2)


def test_control_dependencies_ordering():
    v = stf.Variable(0.0, name="v")
    a1 = v.assign_add(1.0)
    with stf.control_dependencies([a1]):
        a2 = v.assign(v.read_value() * 10.0)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        sess.run(a2)
        assert float(sess.run(v)) == 10.0


def test_dropout_grad_mask_consistency():
    x = stf.placeholder(stf.float32, [100])
    y = stf.nn.dropout(x, keep_prob=0.5)
    (dx,) = stf.gradients(stf.reduce_sum(y), [x])
    with stf.Session() as sess:
        xv = np.ones(100, np.float32)
        yv, dxv = sess.run([y, dx], {x: xv})
        # gradient mask must equal the forward mask
        np.testing.assert_allclose((yv > 0).astype(np.float32) * 2.0, dxv)


class TestVariableValue:
    def test_returns_device_array_with_sharding(self):
        stf.reset_default_graph()
        v = stf.Variable(np.arange(6, dtype=np.float32).reshape(2, 3),
                         name="vv")
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        arr = sess.variable_value("vv")
        assert hasattr(arr, "sharding")
        np.testing.assert_array_equal(np.asarray(arr),
                                      np.arange(6).reshape(2, 3))
        # by Variable object too
        arr2 = sess.variable_value(v)
        assert arr2 is arr
        import pytest as _pytest
        with _pytest.raises(KeyError):
            sess.variable_value("nope")

    def test_resolves_read_tensor_and_suffixed_names(self):
        stf.reset_default_graph()
        with stf.variable_scope("sc"):
            v = stf.get_variable("w", shape=(2,),
                                 initializer=stf.zeros_initializer())
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        ref = sess.variable_value(v)
        # read tensor (op name carries a "/read" suffix) resolves too
        assert sess.variable_value(v.value()) is ref
        assert sess.variable_value("sc/w/read") is ref
        assert sess.variable_value("sc/w:0") is ref
        with _pytest_raises_keyerror_mentioning("Variable"):
            sess.variable_value("sc/nope/read")


import contextlib


@contextlib.contextmanager
def _pytest_raises_keyerror_mentioning(word):
    import pytest as _pytest

    with _pytest.raises(KeyError, match=word):
        yield


# -- ISSUE 2: lifecycle instrumentation (stf.monitoring + StepStats v2) ------

def _cache_counters():
    from simple_tensorflow_tpu.platform import monitoring

    exp = monitoring.export()
    hits = exp["/stf/session/executable_cache/hits"]["cells"].get("", 0)
    misses = exp["/stf/session/executable_cache/misses"]["cells"]
    return hits, dict(misses)


def test_software_trace_phase_spans_and_cache_counters():
    import json

    x = stf.placeholder(stf.float32, [None, 3])
    w = stf.Variable(np.ones((3, 2), np.float32), name="trace_w")
    y = stf.matmul(x, w)
    feed = {x: np.ones((2, 3), np.float32)}
    opts = stf.RunOptions(trace_level=stf.RunOptions.SOFTWARE_TRACE)
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        hits0, misses0 = _cache_counters()

        # compile run: >= 5 distinct lifecycle phase spans
        md = stf.RunMetadata()
        sess.run(y, feed_dict=feed, options=opts, run_metadata=md)
        names = [n["name"] for n in md.step_stats["nodes"]]
        assert {"prune", "optimize", "lower", "jit_compile",
                "device_execute"} <= set(names)
        assert len(set(names)) >= 5
        hits1, misses1 = _cache_counters()
        assert sum(misses1.values()) == sum(misses0.values()) + 1
        assert (misses1.get("new_fetch_feed_signature", 0)
                == misses0.get("new_fetch_feed_signature", 0) + 1)

        # second identical run: a cache hit with ZERO compile spans
        md2 = stf.RunMetadata()
        sess.run(y, feed_dict=feed, options=opts, run_metadata=md2)
        names2 = [n["name"] for n in md2.step_stats["nodes"]]
        assert "jit_compile" not in names2
        assert "prune" not in names2 and "optimize" not in names2
        assert "device_execute" in names2
        hits2, misses2 = _cache_counters()
        assert hits2 == hits1 + 1
        assert sum(misses2.values()) == sum(misses1.values())

        # XLA executable analyses land in cost_graph on traced runs
        assert md.cost_graph.get("flops", 0) > 0
        assert md.cost_graph.get("bytes_accessed", 0) > 0

        # the chrome trace is multi-track Perfetto-readable JSON
        from simple_tensorflow_tpu.client.timeline import Timeline

        trace = json.loads(Timeline(md).generate_chrome_trace_format(
            show_memory=True))
        assert trace["displayTimeUnit"] == "ms"
        evnames = [e["name"] for e in trace["traceEvents"]]
        assert "process_name" in evnames
        assert evnames.count("thread_name") >= 2
        if md.cost_graph.get("memory", {}).get("peak_bytes"):
            assert any(e.get("ph") == "C" for e in trace["traceEvents"])


def test_cache_miss_reason_rewrite_version_bump():
    x = stf.placeholder(stf.float32, [None, 2])
    y = x + 1.0
    feed = {x: np.ones((1, 2), np.float32)}
    with stf.Session() as sess:
        sess.run(y, feed_dict=feed)
        _, misses0 = _cache_counters()
        # an in-place FuncGraph rewrite bumps the graph rewrite version;
        # the same (fetches, feeds) signature must re-plan and label the
        # miss accordingly
        sess.graph._rewrite_version += 1
        sess.run(y, feed_dict=feed)
        _, misses1 = _cache_counters()
        assert (misses1.get("rewrite_version_bump", 0)
                == misses0.get("rewrite_version_bump", 0) + 1)


def test_untraced_run_records_no_spans_but_counts():
    x = stf.placeholder(stf.float32, [None, 2])
    y = x * 2.0
    with stf.Session() as sess:
        md = stf.RunMetadata()
        # run_metadata without trace_level: wall time only, no nodes
        sess.run(y, feed_dict={x: np.ones((1, 2), np.float32)},
                 run_metadata=md)
        assert md.step_stats["wall_time_s"] > 0
        assert md.step_stats["nodes"] == []


def test_run_options_timeout_raises_deadline_exceeded():
    import time as _time

    def _slow(v):
        _time.sleep(0.5)
        return v

    z = stf.py_func(_slow, [stf.constant(np.float32(1.0))], stf.float32)
    z.set_shape([])
    with stf.Session() as sess:
        with pytest.raises(stf.errors.DeadlineExceededError):
            sess.run(z, options=stf.RunOptions(timeout_in_ms=50))
        # a generous deadline passes, and the session stays usable
        out = sess.run(z, options=stf.RunOptions(timeout_in_ms=60000))
        assert float(np.asarray(out)) == 1.0


def test_timeout_preserves_variable_state():
    import time as _time

    v = stf.Variable(1.0, name="deadline_v")
    inc = stf.assign_add(v, 1.0)

    def _slow(u):
        _time.sleep(0.4)
        return u

    slow = stf.py_func(_slow, [stf.constant(np.float32(0.0))], stf.float32)
    slow.set_shape([])
    with stf.Session() as sess:
        sess.run(stf.global_variables_initializer())
        with pytest.raises(stf.errors.DeadlineExceededError):
            sess.run([inc, slow], options=stf.RunOptions(timeout_in_ms=50))
        # the store must not hold donated (deleted) buffers: reads and
        # further updates still work after the aborted run
        val = float(np.asarray(sess.run(v)))
        assert val in (1.0, 2.0)  # commit-then-detect: either is coherent
        sess.run(inc)
        assert float(np.asarray(sess.run(v))) == val + 1.0


def test_traced_then_shape_change_falls_back_and_recomputes():
    # a traced first call pins an AOT executable on the step; feeding a
    # new batch size must transparently fall back to the jit path AND
    # drop the stale cost analysis so later traced runs re-harvest
    x = stf.placeholder(stf.float32, [None, 3])
    y = stf.reduce_sum(x, axis=1)
    opts = stf.RunOptions(trace_level=stf.RunOptions.SOFTWARE_TRACE)
    with stf.Session() as sess:
        md = stf.RunMetadata()
        out = sess.run(y, {x: np.ones((2, 3), np.float32)},
                       options=opts, run_metadata=md)
        assert out.shape == (2,)
        flops_b2 = md.cost_graph.get("flops", 0)
        out = sess.run(y, {x: np.ones((64, 3), np.float32)})
        assert out.shape == (64,)
        md2 = stf.RunMetadata()
        out = sess.run(y, {x: np.ones((64, 3), np.float32)},
                       options=opts, run_metadata=md2)
        assert out.shape == (64,)
        if flops_b2 and md2.cost_graph.get("flops"):
            assert md2.cost_graph["flops"] > flops_b2
        out = sess.run(y, {x: np.ones((2, 3), np.float32)})
        assert out.shape == (2,)


class TestExecutionPlan:
    """Session.plan/ExecutionPlan.execute — the explicit plan/execute
    split of Session.run that stf.serving drives (ISSUE 7 tentpole)."""

    def test_plan_execute_matches_run(self):
        x = stf.placeholder(stf.float32, [None, 3], name="pe_x")
        w = stf.Variable(stf.constant(np.float32([[1.], [2.], [3.]])),
                         name="pe_w")
        y = stf.matmul(x, w)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            feed = np.random.RandomState(0).randn(4, 3).astype(np.float32)
            ref = sess.run(y, {x: feed})
            plan = sess.plan(y, feeds=[x])
            out = plan.execute({x: feed})
            np.testing.assert_array_equal(out, ref)
            # structured fetches rebuild through the plan's mapper
            plan2 = sess.plan({"y": y, "x_thru": x}, feeds=[x])
            out2 = plan2.execute({x: feed})
            assert set(out2) == {"y", "x_thru"}
            np.testing.assert_array_equal(out2["y"], ref)

    def test_plan_shares_executable_cache_with_run(self):
        from simple_tensorflow_tpu.client import session as session_mod

        x = stf.placeholder(stf.float32, [2, 2], name="pc_x")
        y = stf.add(x, x)
        with stf.Session() as sess:
            plan = sess.plan(y, feeds=[x])
            hits = session_mod._metric_cache_hits.get_cell().value()
            # an identical run() signature must HIT the plan's cache
            # entry, not re-plan
            sess.run(y, {x: np.zeros((2, 2), np.float32)})
            assert session_mod._metric_cache_hits.get_cell().value() \
                == hits + 1
            # and the plan executes the same step object
            assert plan.step is sess._cache[plan._key]

    def test_feed_signature_mismatch_raises(self):
        x = stf.placeholder(stf.float32, [2], name="fm_x")
        z = stf.placeholder(stf.float32, [2], name="fm_z")
        y = stf.add(x, x)
        with stf.Session() as sess:
            plan = sess.plan(y, feeds=[x])
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="must match the planned"):
                plan.execute({})
            with pytest.raises(stf.errors.InvalidArgumentError,
                               match="must match the planned"):
                plan.execute({x: np.zeros(2, np.float32),
                              z: np.zeros(2, np.float32)})

    def test_aot_bucket_compile_and_reuse(self):
        from simple_tensorflow_tpu.compiler import aot

        x = stf.placeholder(stf.float32, [None, 4], name="ab_x")
        w = stf.Variable(stf.constant(np.ones((4, 2), np.float32)),
                         name="ab_w")
        y = stf.matmul(x, w)
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            plan = sess.plan(y, feeds=[x])
            exe = plan.compile({x: (8, 4)})
            assert isinstance(exe, aot.AotStepExecutable)
            assert exe.feed_signature in plan.step.aot_cache
            assert "hlo" in exe.hlo_text.lower() or exe.hlo_text
            # matching execution uses the bucket executable; a
            # different batch size still works through the jit path
            out8 = plan.execute({x: np.ones((8, 4), np.float32)})
            out3 = plan.execute({x: np.ones((3, 4), np.float32)})
            assert out8.shape == (8, 2) and out3.shape == (3, 2)
            assert np.all(out8 == 4.0) and np.all(out3 == 4.0)
            # dynamic-dim feed without an override is refused
            with pytest.raises(ValueError, match="dynamic shape"):
                plan.compile()

    def test_plan_on_closed_session_raises(self):
        x = stf.placeholder(stf.float32, [2], name="cl_x")
        y = stf.add(x, x)
        sess = stf.Session()
        plan = sess.plan(y, feeds=[x])
        sess.close()
        with pytest.raises(RuntimeError, match="closed Session"):
            plan.execute({x: np.zeros(2, np.float32)})
        with pytest.raises(RuntimeError, match="closed Session"):
            sess.plan(y, feeds=[x])

    def test_execute_as_futures(self):
        x = stf.placeholder(stf.float32, [2], name="af_x")
        y = stf.multiply(x, stf.constant(np.float32(2.0)))
        with stf.Session() as sess:
            plan = sess.plan(y, feeds=[x])
            fut = plan.execute({x: np.float32([1.0, 2.0])},
                               as_futures=True)
            assert isinstance(fut, stf.FetchFuture)
            np.testing.assert_array_equal(np.asarray(fut),
                                          np.float32([2.0, 4.0]))
