"""Metric-catalog drift gate (ISSUE 8 satellite): the
docs/OBSERVABILITY.md catalog table and the process-global metric
registry can never drift apart again.

Direction 1: every ``/stf/...`` family registered when the library (and
the model-zoo gate's graph builders) are imported must have a catalog
row. Direction 2: every catalog row must name a family that actually
registers. ``docs/observability_allowlist.txt`` exempts names in both
directions — intentionally, loudly, one per line.
"""

import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")
ALLOWLIST = os.path.join(REPO, "docs", "observability_allowlist.txt")


def _registered_names():
    # the root import registers every metric-bearing module (session,
    # optimizer, analysis, data.pipeline, serving, telemetry); the zoo
    # modules ride along for any graph-time registrations
    import simple_tensorflow_tpu  # noqa: F401
    import simple_tensorflow_tpu.models  # noqa: F401
    from simple_tensorflow_tpu.platform import monitoring

    return {n for n in monitoring._registry if n.startswith("/stf/")}


def _documented_names():
    with open(DOC) as f:
        text = f.read()
    # catalog rows are markdown table rows whose first cell is the
    # backticked metric name
    return set(re.findall(r"^\|\s*`(/stf/[^`]+)`", text, re.MULTILINE))


def _allowlisted():
    names = set()
    with open(ALLOWLIST) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                names.add(line)
    return names


def test_catalog_parses_nonempty():
    docs = _documented_names()
    assert len(docs) > 30, (
        "docs/OBSERVABILITY.md catalog table parse came back "
        f"suspiciously small ({len(docs)} rows) — did the table format "
        "change? Update the regex in this test alongside it.")


def test_every_registered_metric_is_documented():
    missing = _registered_names() - _documented_names() - _allowlisted()
    assert not missing, (
        "metric families registered at import but MISSING from the "
        "docs/OBSERVABILITY.md catalog table (add a row, or — only for "
        "intentional omissions — an allowlist line):\n  "
        + "\n  ".join(sorted(missing)))


def test_every_documented_metric_is_registered():
    ghosts = _documented_names() - _registered_names() - _allowlisted()
    assert not ghosts, (
        "docs/OBSERVABILITY.md catalog rows that no longer correspond "
        "to a registered metric family (stale docs rot trust — delete "
        "the row or fix the registration):\n  "
        + "\n  ".join(sorted(ghosts)))


def test_allowlist_entries_are_live():
    # an allowlist line for a name that neither registers nor appears
    # in the docs is dead weight — fail so it gets cleaned up
    dead = [n for n in _allowlisted()
            if n not in _registered_names()
            and n not in _documented_names()]
    assert not dead, (
        "docs/observability_allowlist.txt entries matching nothing: "
        f"{sorted(dead)}")


def test_allowlist_is_not_growing_silently():
    # the steady state is an EMPTY allowlist; this bound forces a
    # deliberate edit (and review) to grow it past a handful
    n = len(_allowlisted())
    assert n <= 5, (
        f"observability allowlist has {n} entries — it is meant for "
        "rare, temporary exemptions, not as a pressure valve. Document "
        "the metrics instead.")


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
