"""SavedModel round-trips, Estimator train/evaluate/predict, debug
wrappers, timeline, device_lib (SURVEY §2.9-§2.11)."""

import json
import os

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


class TestSavedModel:
    def test_simple_save_and_load(self, tmp_path):
        from simple_tensorflow_tpu import saved_model as sm

        x = stf.placeholder(stf.float32, [None, 2], name="x")
        w = stf.Variable(stf.constant([[1.0], [2.0]]), name="w")
        y = stf.matmul(x, w, name="y")
        export_dir = str(tmp_path / "model")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            sm.simple_save(sess, export_dir, inputs={"x": x},
                           outputs={"y": y})
        assert os.path.exists(export_dir)

        stf.reset_default_graph()
        with stf.Session() as sess2:
            meta = sm.loader.load(sess2, [sm.tag_constants.SERVING],
                                  export_dir)
            sig = meta["signature_def"]["serving_default"]
            x_name = sig["inputs"]["x"]["name"]
            y_name = sig["outputs"]["y"]["name"]
            out = sess2.run(y_name, {x_name: np.float32([[3.0, 4.0]])})
        assert out.tolist() == [[11.0]]

    def test_builder_with_signature(self, tmp_path):
        from simple_tensorflow_tpu import saved_model as sm

        x = stf.placeholder(stf.float32, [None], name="inp")
        v = stf.Variable(stf.constant(2.0), name="scale")
        y = stf.multiply(x, v.value(), name="out")
        b = sm.builder.SavedModelBuilder(str(tmp_path / "m"))
        sig = sm.signature_def_utils.predict_signature_def(
            inputs={"x": x}, outputs={"y": y})
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            b.add_meta_graph_and_variables(
                sess, [sm.tag_constants.SERVING],
                signature_def_map={"predict": sig})
        b.save()
        stf.reset_default_graph()
        with stf.Session() as sess:
            meta = sm.loader.load(sess, [sm.tag_constants.SERVING],
                                  str(tmp_path / "m"))
            out = sess.run("out:0", {"inp:0": np.float32([1.0, 3.0])})
        assert out.tolist() == [2.0, 6.0]


class TestMetaGraphVariables:
    def test_import_meta_graph_rebuilds_variables(self, tmp_path):
        """Collections + Variable wrappers must survive export/import so
        Saver.restore finds them (round-2 fix)."""
        v = stf.Variable(stf.constant([1.5, 2.5]), name="mv")
        path = str(tmp_path / "g.meta")
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            saver = stf.train.Saver()
            ckpt = saver.save(sess, str(tmp_path / "ck"),
                              write_meta_graph=False)
            from simple_tensorflow_tpu.framework import graph_io

            graph_io.export_meta_graph(path)

        stf.reset_default_graph()
        from simple_tensorflow_tpu.framework import graph_io

        graph_io.import_meta_graph(path)
        gvars = stf.global_variables()
        assert len(gvars) == 1 and gvars[0].var_name == "mv"
        with stf.Session() as sess2:
            stf.train.Saver().restore(sess2, ckpt)
            out = sess2.run(gvars[0].value())
        assert out.tolist() == [1.5, 2.5]

    def test_scoped_import_does_not_alias_existing_variable(self, tmp_path):
        """An imported 'w' under a scope must get its own store slot, not
        clobber this graph's 'w'."""
        from simple_tensorflow_tpu.framework import graph_io

        stf.Variable(stf.constant([9.0]), name="w")
        path = str(tmp_path / "g.meta")
        graph_io.export_meta_graph(path)

        stf.reset_default_graph()
        mine = stf.Variable(stf.constant([1.0]), name="w")
        graph_io.import_meta_graph(path, import_scope="loaded")
        gvars = stf.global_variables()
        names = sorted(v.var_name for v in gvars)
        assert names == ["loaded/w", "w"], names
        with stf.Session() as sess:
            sess.run(stf.global_variables_initializer())
            assert sess.run(mine.value()).tolist() == [1.0]
            imported = [v for v in gvars if v.var_name == "loaded/w"][0]
            assert sess.run(imported.value()).tolist() == [9.0]


class TestEstimator:
    def _model_fn(self, features, labels, mode, params=None, config=None):
        from simple_tensorflow_tpu import estimator as est

        w = stf.get_variable("w", [2, 1], initializer=stf.zeros_initializer())
        pred = stf.matmul(features["x"], w)
        if mode == est.ModeKeys.PREDICT:
            return est.EstimatorSpec(mode, predictions={"pred": pred})
        loss = stf.reduce_mean(stf.square(pred - labels))
        if mode == est.ModeKeys.EVAL:
            return est.EstimatorSpec(mode, loss=loss)
        gs = stf.train.get_or_create_global_step()
        train_op = stf.train.GradientDescentOptimizer(0.2).minimize(
            loss, global_step=gs)
        return est.EstimatorSpec(mode, loss=loss, train_op=train_op,
                                 predictions={"pred": pred})

    def _input_fn(self):
        rng = np.random.RandomState(0)
        X = rng.rand(32, 2).astype(np.float32)
        Y = (X @ np.float32([[1.0], [2.0]]))
        from simple_tensorflow_tpu import data as stf_data

        ds = stf_data.Dataset.from_tensor_slices(
            {"x": X, "y": Y}).repeat().batch(8)
        f = ds.make_one_shot_iterator().get_next()
        return {"x": f["x"]}, f["y"]

    def test_train_evaluate_predict(self, tmp_path):
        from simple_tensorflow_tpu import estimator as est

        e = est.Estimator(self._model_fn, model_dir=str(tmp_path))
        e.train(self._input_fn, steps=40)
        metrics = e.evaluate(self._input_fn, steps=4)
        assert metrics["loss"] < 0.2
        import itertools

        # input_fn repeats forever; predict streams until input exhaustion,
        # so take a bounded prefix
        preds = list(itertools.islice(e.predict(self._input_fn), 3))
        assert len(preds) == 3 and "pred" in preds[0]


class TestDebug:
    def test_dumping_wrapper_captures_tensors(self, tmp_path):
        from simple_tensorflow_tpu import debug as stf_debug

        x = stf.placeholder(stf.float32, [2], name="x")
        y = stf.square(x, name="sq")
        sess = stf.Session()
        wrapped = stf_debug.DumpingDebugWrapperSession(
            sess, session_root=str(tmp_path))
        out = wrapped.run(y, {x: np.float32([2.0, 3.0])})
        assert out.tolist() == [4.0, 9.0]
        dumps = os.listdir(str(tmp_path))
        assert dumps  # a dump directory per run

    def test_debug_dump_dir_analyzer(self, tmp_path):
        """DebugDumpDir: list/query/filter across runs (the tfdbg
        analyzer layer, ref python/debug/lib/debug_data.py)."""
        from simple_tensorflow_tpu import debug as stf_debug

        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [2], name="ax")
        y = stf.square(x, name="asq")
        z = stf.log(x, name="alog")  # log(-1) = nan for the filter
        sess = stf.Session()
        wrapped = stf_debug.DumpingDebugWrapperSession(
            sess, session_root=str(tmp_path))
        wrapped.run([y, z], {x: np.float32([2.0, 3.0])})
        wrapped.run([y, z], {x: np.float32([-1.0, 3.0])})  # nan run

        dd = stf_debug.DebugDumpDir(str(tmp_path))
        assert dd.runs == [1, 2]
        assert dd.size > 0
        names = dd.dumped_tensor_names()
        assert "asq:0" in names and "alog:0" in names
        # per-tensor history across runs
        data = dd.watch_key_to_data("asq:0")
        assert len(data) == 2
        np.testing.assert_allclose(data[0].get_tensor(), [4.0, 9.0])
        # glob query
        assert dd.query("a*:0") == sorted(
            n for n in names if n.startswith("a"))
        # inf/nan filter finds the second run's log only
        bad = dd.find_inf_or_nan()
        assert any(d.tensor_name == "alog:0" for d in bad)
        assert all("run_2" in d.run_dir for d in bad
                   if d.tensor_name == "alog:0")
        stats = bad[0].stats()
        assert stats["nan"] >= 1

    def test_has_inf_or_nan_filter(self):
        from simple_tensorflow_tpu.debug import has_inf_or_nan

        assert has_inf_or_nan("t", np.array([1.0, np.inf]))
        assert not has_inf_or_nan("t", np.array([1.0, 2.0]))


class TestTimelineAndDevices:
    def test_run_metadata_timeline(self, tmp_path):
        x = stf.placeholder(stf.float32, [4], name="x")
        y = stf.reduce_sum(stf.square(x))
        run_metadata = stf.train.SessionRunValues if False else None
        from simple_tensorflow_tpu.client.session import RunMetadata, RunOptions

        meta = RunMetadata()
        with stf.Session() as sess:
            sess.run(y, {x: np.ones(4, np.float32)},
                     options=RunOptions(trace_level=RunOptions.FULL_TRACE),
                     run_metadata=meta)
        tl = stf.timeline.Timeline(meta.step_stats)
        trace = tl.generate_chrome_trace_format()
        data = json.loads(trace)
        assert "traceEvents" in data and data["traceEvents"]

    def test_list_local_devices(self):
        devs = stf.device_lib.list_local_devices()
        assert devs and devs[0].device_type in ("CPU", "TPU")

    def test_metrics_namespace(self):
        labels = stf.constant([1, 0, 1, 1])
        preds = stf.constant([1, 0, 0, 1])
        acc, update = stf.metrics.accuracy(labels, preds)
        with stf.Session() as sess:
            sess.run(stf.local_variables_initializer())
            sess.run(update)
            assert abs(float(sess.run(acc)) - 0.75) < 1e-6
