"""Ring attention + Ulysses sequence parallelism vs dense reference
(SURVEY §4: 'ring attention equals flash attention' on the 8-dev mesh)."""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import parallel
from simple_tensorflow_tpu.ops.pallas.flash_attention import mha_reference


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


def _qkv(seed=0, b=2, h=4, s=64, d=8):
    rng = np.random.RandomState(seed)
    shape = (b, h, s, d)
    return (rng.randn(*shape).astype(np.float32),
            rng.randn(*shape).astype(np.float32),
            rng.randn(*shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    import jax

    q, k, v = _qkv()
    ref = np.asarray(mha_reference(*map(jax.numpy.asarray, (q, k, v)),
                                   causal=causal))

    mesh = parallel.Mesh({"sp": 8})
    with mesh:
        out = parallel.ring_attention(stf.constant(q), stf.constant(k),
                                      stf.constant(v), causal=causal)
        with stf.Session() as sess:
            val = sess.run(out)
    np.testing.assert_allclose(val, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    import jax

    q, k, v = _qkv(seed=1, h=8)
    ref = np.asarray(mha_reference(*map(jax.numpy.asarray, (q, k, v)),
                                   causal=causal))

    mesh = parallel.Mesh({"sp": 8})
    with mesh:
        out = parallel.sequence_parallel_attention(
            stf.constant(q), stf.constant(k), stf.constant(v), causal=causal)
        with stf.Session() as sess:
            val = sess.run(out)
    np.testing.assert_allclose(val, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_match_dense():
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(seed=2, b=1, h=2, s=32, d=4)

    mesh = parallel.Mesh({"sp": 8})
    with mesh:
        qt, kt, vt = map(stf.constant, (q, k, v))
        out = parallel.ring_attention(qt, kt, vt, causal=True)
        loss = stf.reduce_sum(out * out)
        gq, gk, gv = stf.gradients(loss, [qt, kt, vt])
        with stf.Session() as sess:
            gq_v, gk_v, gv_v = sess.run([gq, gk, gv])

    def dense_loss(q, k, v):
        o = mha_reference(q, k, v, causal=True)
        return jnp.sum(o * o)

    rq, rk, rv = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(gq_v, np.asarray(rq), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(gk_v, np.asarray(rk), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(gv_v, np.asarray(rv), rtol=1e-3, atol=1e-4)


def test_ring_attention_no_mesh_falls_back():
    q, k, v = _qkv(seed=3, s=16)
    ref = np.asarray(mha_reference(*map(np.asarray, (q, k, v)), causal=False))
    out = parallel.ring_attention(stf.constant(q), stf.constant(k),
                                  stf.constant(v))
    with stf.Session() as sess:
        val = sess.run(out)
    np.testing.assert_allclose(val, ref, rtol=2e-2, atol=2e-3)


def test_flash_return_lse_matches_logsumexp():
    import jax
    import jax.numpy as jnp
    from simple_tensorflow_tpu.ops.pallas.flash_attention import (
        flash_attention)

    q, k, v = _qkv(seed=3, b=1, h=2, s=32, d=8)
    o, lse = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             return_lse=True, block_q=16, block_k=16)
    d = q.shape[-1]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    ref_lse = np.log(np.sum(np.exp(s - s.max(-1, keepdims=True)), -1)) \
        + s.max(-1)
    np.testing.assert_allclose(np.asarray(lse), ref_lse, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(mha_reference(q, k, v)),
                               atol=2e-5)


def test_flash_lse_gradient_flows():
    """The lse output is differentiable: d(sum lse)/dq must match the
    dense logsumexp gradient."""
    import jax
    import jax.numpy as jnp
    from simple_tensorflow_tpu.ops.pallas.flash_attention import (
        flash_attention)

    q, k, v = _qkv(seed=4, b=1, h=1, s=16, d=8)

    def loss_flash(q):
        _, lse = flash_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), return_lse=True,
                                 block_q=8, block_k=8)
        return jnp.sum(lse)

    def loss_ref(q):
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, jnp.asarray(k)) / np.sqrt(d)
        return jnp.sum(jax.nn.logsumexp(s, axis=-1))

    g1 = np.asarray(jax.grad(loss_flash)(jnp.asarray(q)))
    g2 = np.asarray(jax.grad(loss_ref)(jnp.asarray(q)))
    np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_path_equals_naive_path(causal):
    """The flash-per-block ring (default) and the naive-score-matrix ring
    must agree — they are the same math, different memory profiles."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from simple_tensorflow_tpu.parallel.mesh import get_shard_map
    from simple_tensorflow_tpu.parallel.ring_attention import (
        ring_attention_p)

    shard_map = get_shard_map()
    q, k, v = _qkv(seed=5, b=1, h=2, s=64, d=8)
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("sp",))
    spec = P(None, None, "sp", None)

    outs = {}
    for use_flash in (True, False):
        fn = shard_map(
            lambda qq, kk, vv, uf=use_flash: ring_attention_p(
                qq, kk, vv, "sp", causal=causal, use_flash=uf),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        outs[use_flash] = np.asarray(jax.jit(fn)(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(outs[True], outs[False], rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(
        outs[True], np.asarray(mha_reference(q, k, v, causal=causal)),
        rtol=1e-4, atol=1e-5)


def test_ring_attention_on_composed_dp_sp_mesh():
    """Ring attention must compose with a data-parallel axis on the same
    mesh (dp=2 x sp=4): equal to dense attention on the full batch."""
    rng = np.random.RandomState(0)
    B, H, S, D = 4, 2, 64, 16
    q, k, v = (rng.randn(B, H, S, D).astype(np.float32) * 0.3
               for _ in range(3))
    mesh = parallel.Mesh({"dp": 2, "sp": 4})
    with mesh:
        qt, kt, vt = (stf.constant(a) for a in (q, k, v))
        out = parallel.ring_attention(qt, kt, vt, causal=True)
        with stf.Session() as sess:
            got = sess.run(out)
    want = np.asarray(mha_reference(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
