"""Tests: TensorArray, Defun, Example/parsing, misc ops, graph optimizer
passes, AOT compile, perf utils (SURVEY §2.1/§2.3/§2.10/§5)."""

import json

import numpy as np
import pytest

import simple_tensorflow_tpu as stf


@pytest.fixture(autouse=True)
def fresh_graph():
    stf.reset_default_graph()
    yield


class TestTensorArray:
    def test_write_read_stack(self):
        ta = stf.TensorArray(stf.float32, size=3, element_shape=[2])
        ta = ta.write(0, [1., 2.]).write(1, [3., 4.]).write(2, [5., 6.])
        with stf.Session() as sess:
            r, s = sess.run([ta.read(1), ta.stack()])
        assert r.tolist() == [3., 4.]
        assert s.tolist() == [[1., 2.], [3., 4.], [5., 6.]]

    def test_unstack_gather_concat(self):
        x = stf.constant(np.arange(12, dtype=np.float32).reshape(3, 2, 2))
        ta = stf.TensorArray(stf.float32, size=3,
                             element_shape=[2, 2]).unstack(x)
        with stf.Session() as sess:
            g = sess.run(ta.gather([2, 0]))
            c = sess.run(ta.concat())
        assert g.shape == (2, 2, 2) and g[0, 0, 0] == 8.0
        assert c.shape == (6, 2)

    def test_scatter_and_size(self):
        ta = stf.TensorArray(stf.int32, size=4, element_shape=[])
        ta = ta.scatter([1, 3], [10, 30])
        with stf.Session() as sess:
            assert sess.run(ta.stack()).tolist() == [0, 10, 0, 30]
            assert int(sess.run(ta.size())) == 4

    def test_gradient_through_tensor_array(self):
        x = stf.constant([1.0, 2.0])
        ta = stf.TensorArray(stf.float32, size=2, element_shape=[2])
        ta = ta.write(0, x * 2.0).write(1, x * 3.0)
        loss = stf.reduce_sum(ta.stack())
        (gx,) = stf.gradients(loss, [x])
        with stf.Session() as sess:
            assert sess.run(gx).tolist() == [5.0, 5.0]

    def test_dynamic_size_rejected(self):
        with pytest.raises(NotImplementedError):
            stf.TensorArray(stf.float32, size=2, element_shape=[1],
                            dynamic_size=True)


class TestDefun:
    def test_call_and_shape_specialization(self):
        calls = []

        @stf.Defun(stf.float32, stf.float32)
        def f(a, b):
            calls.append(1)
            return a * b + 1.0

        y1 = f(stf.constant([1., 2.]), stf.constant([3., 4.]))
        y2 = f(stf.constant([5., 6.]), stf.constant([7., 8.]))  # cache hit
        y3 = f(stf.constant(2.0), stf.constant(3.0))  # new signature
        with stf.Session() as sess:
            assert sess.run(y1).tolist() == [4., 9.]
            assert sess.run(y2).tolist() == [36., 49.]
            assert float(sess.run(y3)) == 7.0
        assert len(calls) == 2  # traced once per shape signature

    def test_capture_and_gradient(self):
        c = stf.constant(3.0)

        @stf.Defun(stf.float32)
        def g(x):
            return x * x * c  # captures c

        x = stf.constant(2.0)
        y = g(x)
        (dx,) = stf.gradients(y, [x])
        with stf.Session() as sess:
            assert float(sess.run(y)) == 12.0
            assert float(sess.run(dx)) == 12.0  # 2*x*c

    def test_multi_output(self):
        @stf.Defun(stf.float32)
        def h(x):
            return x + 1.0, x * 2.0

        a, b = h(stf.constant(4.0))
        with stf.Session() as sess:
            assert sess.run([a, b]) == [5.0, 8.0]


class TestExampleProto:
    def test_roundtrip(self):
        ex = stf.train.Example(features=stf.train.Features(feature={
            "label": stf.train.int64_feature(5),
            "w": stf.train.float_feature([0.5, 2.5]),
            "s": stf.train.bytes_feature([b"ab", b""]),
        }))
        data = ex.SerializeToString()
        back = stf.train.Example.FromString(data)
        assert back.features.feature["label"].int64_list.value == [5]
        assert back.features.feature["w"].float_list.value == [0.5, 2.5]
        assert back.features.feature["s"].bytes_list.value == [b"ab", b""]

    def test_negative_int64(self):
        ex = stf.train.make_example(v=[-3, 7])
        back = stf.train.Example.FromString(ex.SerializeToString())
        assert back.features.feature["v"].int64_list.value == [-3, 7]

    def test_parse_example_graph(self):
        exs = [stf.train.make_example(label=i, w=[float(i), 1.0],
                                      tags=list(range(i)))
               for i in range(3)]
        sers = np.array([e.SerializeToString() for e in exs], dtype=object)
        s = stf.placeholder(stf.string, [3])
        feats = stf.parse_example(s, {
            "label": stf.FixedLenFeature([], stf.int64),
            "w": stf.FixedLenFeature([2], stf.float32),
            "tags": stf.VarLenFeature(stf.int64),
        })
        with stf.Session() as sess:
            out = sess.run(feats, {s: sers})
        assert out["label"].tolist() == [0, 1, 2]
        assert out["w"][2].tolist() == [2.0, 1.0]
        assert out["tags"].values.tolist() == [0, 0, 1]
        assert out["tags"].dense_shape.tolist() == [3, 2]

    def test_parse_single_example(self):
        data = stf.train.make_example(x=[1.5]).SerializeToString()
        feats = stf.parse_single_example(
            stf.constant(np.asarray(data, dtype=object)),
            {"x": stf.FixedLenFeature([1], stf.float32)})
        with stf.Session() as sess:
            assert sess.run(feats["x"]).tolist() == [1.5]

    def test_fixed_len_default(self):
        data = stf.train.make_example(a=1).SerializeToString()
        s = stf.placeholder(stf.string, [1])
        feats = stf.parse_example(s, {
            "missing": stf.FixedLenFeature([], stf.int64, default_value=9)})
        with stf.Session() as sess:
            out = sess.run(feats, {s: np.array([data], dtype=object)})
        assert out["missing"].tolist() == [9]

    def test_decode_raw(self):
        s = stf.placeholder(stf.string, [2])
        d = stf.decode_raw(s, stf.int16)
        with stf.Session() as sess:
            out = sess.run(d, {s: np.array(
                [np.int16([1, 2]).tobytes(), np.int16([3, 4]).tobytes()],
                dtype=object)})
        assert out.tolist() == [[1, 2], [3, 4]]


class TestMiscOps:
    def test_confusion_matrix(self):
        cm = stf.confusion_matrix(stf.constant([1, 2, 4]),
                                  stf.constant([2, 2, 4]), num_classes=5)
        with stf.Session() as sess:
            m = sess.run(cm)
        assert m[1, 2] == 1 and m[2, 2] == 1 and m[4, 4] == 1
        assert m.sum() == 3

    def test_confusion_matrix_weights(self):
        cm = stf.confusion_matrix(stf.constant([0, 1]), stf.constant([0, 1]),
                                  num_classes=2,
                                  weights=stf.constant([0.5, 2.0]))
        with stf.Session() as sess:
            m = sess.run(cm)
        assert m[0, 0] == 0.5 and m[1, 1] == 2.0

    def test_histogram(self):
        h = stf.histogram_fixed_width(
            stf.constant([-1.0, 0.1, 0.49, 0.5, 2.0]), [0.0, 1.0], nbins=2)
        with stf.Session() as sess:
            # out-of-range clamps into edge bins (ref histogram_ops)
            assert sess.run(h).tolist() == [3, 2]

    def test_bitcast(self):
        b = stf.bitcast(stf.constant([1.0], stf.float32), stf.uint32)
        with stf.Session() as sess:
            assert sess.run(b).tolist() == [0x3F800000]

    def test_sets(self):
        pad = np.iinfo(np.int32).min
        a = stf.constant([[1, 2, 3], [4, 5, 6]])
        b = stf.constant([[2, 3, 9], [7, 8, 9]])
        with stf.Session() as sess:
            inter = sess.run(stf.sets.intersection(a, b))
            diff = sess.run(stf.sets.difference(a, b))
            union = sess.run(stf.sets.union(a, b))
            size = sess.run(stf.sets.size(a))
        assert sorted(v for v in inter[0] if v != pad) == [2, 3]
        assert [v for v in inter[1] if v != pad] == []
        assert sorted(v for v in diff[0] if v != pad) == [1]
        assert sorted(v for v in union[1] if v != pad) == [4, 5, 6, 7, 8, 9]
        assert size.tolist() == [3, 3]

    def test_lbeta(self):
        # Beta(1,1) = 1 -> log 0 ; Beta(2,2) = 1/6
        lb = stf.lbeta(stf.constant([[1.0, 1.0], [2.0, 2.0]]))
        with stf.Session() as sess:
            v = sess.run(lb)
        np.testing.assert_allclose(v, [0.0, np.log(1 / 6)], atol=1e-5)

    def test_verify_tensor_all_finite(self):
        x = stf.placeholder(stf.float32, [2])
        y = stf.verify_tensor_all_finite(x, "bad x") * 2.0
        with stf.Session() as sess:
            assert sess.run(y, {x: np.ones(2, np.float32)}).tolist() == [2., 2.]
            with pytest.raises(stf.errors.InvalidArgumentError):
                sess.run(y, {x: np.array([1.0, np.nan], np.float32)})


class TestGraphOptimizer:
    def _graphdef(self):
        a = stf.constant(2.0, name="a")
        b = stf.constant(3.0, name="b")
        c = stf.add(a, b, name="c")  # foldable
        x = stf.placeholder(stf.float32, [], name="x")
        y1 = stf.multiply(x, c, name="y1")
        y2 = stf.multiply(x, c, name="y2")  # CSE twin of y1
        dead = stf.square(x, name="dead")
        out = stf.add(y1, y2, name="out")
        from simple_tensorflow_tpu.framework import graph_io

        return graph_io.graph_to_graphdef(stf.get_default_graph()), out

    def test_constant_folding(self):
        gd, _ = self._graphdef()
        folded = stf.graph_optimizer.constant_folding(gd)
        c = [n for n in folded["node"] if n["name"] == "c"][0]
        assert c["op"] == "Const"

    def test_cse(self):
        gd, _ = self._graphdef()
        opt = stf.graph_optimizer.common_subexpression_elimination(gd)
        names = [n["name"] for n in opt["node"]]
        assert ("y1" in names) != ("y2" in names)  # one of the twins merged
        out = [n for n in opt["node"] if n["name"] == "out"][0]
        assert out["input"][0] == out["input"][1]

    def test_dce(self):
        gd, _ = self._graphdef()
        pruned = stf.graph_optimizer.dead_code_elimination(gd, ["out"])
        names = [n["name"] for n in pruned["node"]]
        assert "dead" not in names and "out" in names

    def test_full_pipeline_preserves_semantics(self):
        gd, out = self._graphdef()
        opt = stf.graph_optimizer.optimize(gd, keep=["out"])
        # import the optimized graph and run both
        with stf.Session() as sess:
            ref = sess.run(out, {"x:0": np.float32(4.0)})
        g2 = stf.Graph()
        with g2.as_default():
            from simple_tensorflow_tpu.framework import graph_io

            graph_io.import_graph_def(opt, name="")
            with stf.Session() as sess:
                got = sess.run("out:0", {"x:0": np.float32(4.0)})
        assert float(ref) == float(got) == 40.0


class TestAot:
    def test_compile_and_run(self):
        from simple_tensorflow_tpu.compiler import aot

        x = stf.placeholder(stf.float32, [4], name="x")
        y = stf.reduce_sum(x * x)
        exe = aot.compile_fetches(y, [x])
        (out,) = exe(np.ones(4, np.float32) * 2.0)
        assert float(out) == 16.0
        assert "HloModule" in exe.hlo_text or "module" in exe.hlo_text
        assert exe.cache_key

    def test_stateful_rejected(self):
        from simple_tensorflow_tpu.compiler import aot

        v = stf.Variable(stf.ones([2]), name="v")
        with pytest.raises(ValueError):
            aot.compile_fetches(v.value() * 2.0, [])

    def test_dynamic_shape_rejected(self):
        from simple_tensorflow_tpu.compiler import aot

        x = stf.placeholder(stf.float32, [None, 2], name="x")
        with pytest.raises(ValueError):
            aot.compile_fetches(stf.reduce_sum(x), [x])


class TestPerf:
    def test_mfu_and_roofline(self):
        from simple_tensorflow_tpu.utils import perf

        assert 0 < perf.mfu(1e12, 1.0) <= 1.0
        r = perf.roofline(step_flops=1e12, step_bytes=1e9)
        assert r["compute_bound"] == (r["intensity_flops_per_byte"]
                                      >= r["ridge_point"])

    def test_step_timer(self):
        from simple_tensorflow_tpu.utils import perf

        t = perf.StepTimer()
        t.start()
        for _ in range(3):
            t.mark()
        s = t.summary()
        assert s["mean_s"] >= 0 and t.steps == 3

    def test_perf_report_with_compiled(self):
        import jax

        from simple_tensorflow_tpu.utils import perf

        f = jax.jit(lambda a, b: a @ b)
        x = np.ones((64, 64), np.float32)
        compiled = f.lower(x, x).compile()
        rep = perf.PerfReport(compiled)
        rep.timer.start()
        f(x, x)
        rep.step_done()
        out = rep.report()
        assert out.get("achieved_tflops", 0) >= 0


class TestConfigProtoTransferGuard:
    """ConfigProto (ref config.proto) + L0 transfer guards (SURVEY §1)."""

    def test_config_proto_fields(self):
        c = stf.ConfigProto(allow_soft_placement=True,
                            log_device_placement=True,
                            gpu_options=stf.GPUOptions(allow_growth=True))
        assert c.allow_soft_placement and c.log_device_placement
        assert c.gpu_options.allow_growth
        with pytest.raises(ValueError):
            stf.ConfigProto(transfer_guard="never")

    def test_disallow_raises_on_hot_path_feed(self):
        stf.reset_default_graph()
        cfg = stf.ConfigProto(transfer_guard="disallow",
                              transfer_guard_threshold_bytes=1024)
        x = stf.placeholder(stf.float32, [64, 64], name="gx")
        y = stf.reduce_sum(x)
        sess = stf.Session(config=cfg)
        feed = {x: np.ones((64, 64), np.float32)}  # 16 KiB > threshold
        # first two runs are warmup/compile: allowed
        sess.run(y, feed)
        sess.run(y, feed)
        with pytest.raises(stf.errors.InvalidArgumentError,
                           match="prefetch_to_device"):
            sess.run(y, feed)

    def test_small_feeds_and_allow_mode_pass(self):
        stf.reset_default_graph()
        cfg = stf.ConfigProto(transfer_guard="disallow",
                              transfer_guard_threshold_bytes=1 << 20)
        x = stf.placeholder(stf.float32, [4], name="sx")
        y = stf.reduce_sum(x)
        sess = stf.Session(config=cfg)
        for _ in range(5):
            sess.run(y, {x: np.ones(4, np.float32)})  # tiny: fine
        stf.reset_default_graph()
        x2 = stf.placeholder(stf.float32, [64, 64], name="ax")
        y2 = stf.reduce_sum(x2)
        s2 = stf.Session()  # no config: guard off
        for _ in range(5):
            s2.run(y2, {x2: np.ones((64, 64), np.float32)})

    def test_disallow_raises_on_big_fetch(self):
        stf.reset_default_graph()
        cfg = stf.ConfigProto(transfer_guard="disallow",
                              transfer_guard_threshold_bytes=1024)
        x = stf.placeholder(stf.float32, [4], name="fx")
        big = stf.tile(stf.reshape(x, [1, 4]), [512, 1])  # 8 KiB out
        sess = stf.Session(config=cfg)
        feed = {x: np.ones(4, np.float32)}
        sess.run(big, feed)
        sess.run(big, feed)
        with pytest.raises(stf.errors.InvalidArgumentError,
                           match="keep large results on device"):
            sess.run(big, feed)


class TestMakeCallable:
    """make_callable fast path (ref session.py make_callable): resolved
    once, per-call dispatch goes straight to the cached XLA step."""

    def test_training_loop_matches_run(self):
        stf.reset_default_graph()
        rng = np.random.RandomState(0)
        X = rng.rand(32, 4).astype(np.float32)
        Y = (X @ np.float32([[1], [2], [-1], [0.5]])).ravel()
        x = stf.placeholder(stf.float32, [32, 4], name="cx")
        y = stf.placeholder(stf.float32, [32], name="cy")
        w = stf.Variable(np.zeros((4,), np.float32), name="cw")
        pred = stf.reduce_sum(x * w, axis=1)
        loss = stf.reduce_mean(stf.square(pred - y))
        opt = stf.train.GradientDescentOptimizer(0.1)
        train = opt.minimize(loss)
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        step_fn = sess.make_callable([train, loss], feed_list=[x, y])
        losses = [step_fn(X, Y)[1] for _ in range(20)]
        assert losses[-1] < losses[0] * 0.5
        # the state the fast path advanced is the state run() sees: the
        # loss run() computes now equals the pre-update loss of the NEXT
        # fast-path step
        final = sess.run(loss, {x: X, y: Y})
        next_loss = step_fn(X, Y)[1]
        np.testing.assert_allclose(final, next_loss, rtol=1e-5)

    def test_fetch_structures_and_arity_check(self):
        stf.reset_default_graph()
        a = stf.placeholder(stf.float32, [2], name="fa")
        b = stf.square(a)
        sess = stf.Session()
        f = sess.make_callable({"sq": b, "in": a}, feed_list=[a])
        out1 = f(np.float32([2, 3]))
        out2 = f(np.float32([4, 5]))  # second call = fast path
        np.testing.assert_allclose(out1["sq"], [4, 9])
        np.testing.assert_allclose(out2["sq"], [16, 25])
        np.testing.assert_allclose(out2["in"], [4, 5])
        with pytest.raises(ValueError, match="Expected 1 feed"):
            f()

    def test_host_stage_fetches_stay_on_general_path(self):
        # string const fetch involves host handling: must still work
        stf.reset_default_graph()
        a = stf.placeholder(stf.float32, [2], name="ha")
        s = stf.constant(np.asarray(["x", "y"], object))
        sess = stf.Session()
        f = sess.make_callable([stf.square(a), s], feed_list=[a])
        for _ in range(3):
            sq, sv = f(np.float32([1, 2]))
            np.testing.assert_allclose(sq, [1, 4])

    def test_fast_path_validates_shape_and_closed_session(self):
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [4], name="vx")
        y = stf.square(x)
        sess = stf.Session()
        f = sess.make_callable(y, feed_list=[x])
        f(np.ones(4, np.float32))
        f(np.ones(4, np.float32))  # adopted
        with pytest.raises(ValueError, match="Cannot feed value of shape"):
            f(np.ones((4, 1), np.float32))
        sess.close()
        with pytest.raises(RuntimeError, match="closed Session"):
            f(np.ones(4, np.float32))

    def test_fast_path_honors_transfer_guard(self):
        stf.reset_default_graph()
        cfg = stf.ConfigProto(transfer_guard="disallow",
                              transfer_guard_threshold_bytes=1024)
        x = stf.placeholder(stf.float32, [64, 64], name="tx")
        y = stf.reduce_sum(x)
        sess = stf.Session(config=cfg)
        f = sess.make_callable(y, feed_list=[x])
        big = np.ones((64, 64), np.float32)
        f(big)  # slow-path warmups (n_calls 1..2 allowed)
        with pytest.raises(stf.errors.InvalidArgumentError,
                           match="prefetch_to_device"):
            for _ in range(3):
                f(big)


class TestRecomputeGrad:
    def test_values_and_grads_match_plain(self):
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [8, 16], name="rgx")
        w = stf.Variable(np.random.RandomState(0).randn(16, 16)
                         .astype(np.float32), name="rgw")

        def block(h):
            return stf.tanh(stf.matmul(h, w)) + h

        y_plain = block(block(x))
        blk = stf.recompute_grad(block)
        y_rc = blk(blk(x))
        (gp,) = stf.gradients(stf.reduce_sum(stf.square(y_plain)), [w])
        (gr,) = stf.gradients(stf.reduce_sum(stf.square(y_rc)), [w])
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        xv = np.random.RandomState(1).randn(8, 16).astype(np.float32)
        out = sess.run({"p": y_plain, "r": y_rc, "gp": gp, "gr": gr},
                       {x: xv})
        np.testing.assert_allclose(out["p"], out["r"], rtol=1e-6)
        np.testing.assert_allclose(out["gp"], out["gr"], rtol=1e-6)

    def test_backward_rematerializes(self):
        # structural: under jax.checkpoint the body's tanh is REPLAYED in
        # the backward, so the lowered program contains more tanh ops for
        # the recompute variant than for the plain one

        from simple_tensorflow_tpu.framework import lowering as lowering_mod

        def count_tanh(use_recompute):
            stf.reset_default_graph()
            x = stf.placeholder(stf.float32, [4, 8], name="ctx")
            w = stf.Variable(np.eye(8, dtype=np.float32), name="ctw")

            def block(h):
                return stf.tanh(stf.matmul(h, w))

            f = stf.recompute_grad(block) if use_recompute else block
            y = f(f(x))
            (g,) = stf.gradients(stf.reduce_sum(y), [w])
            sess = stf.Session()
            sess.run(stf.global_variables_initializer())
            xv = np.zeros((4, 8), np.float32)
            _ = sess.run(g, {x: xv})  # compile
            step = max((v for v in sess._cache.values()
                        if v.has_device_stage),
                       key=lambda s: len(s.device_ops))
            feeds = sess._normalize_feeds({x: xv})
            fa = {t.name: feeds[t] for t in step.feed_tensors}
            state = dict(sess._variable_store.values)
            txt = step.jitted.lower(state, fa, sess._base_key,
                                    np.uint32(1)).as_text()
            return txt.count("stablehlo.tanh")

        assert count_tanh(True) > count_tanh(False)

    def test_per_layer_lambdas_get_distinct_bodies(self):
        # regression: the trace cache was keyed by id(func); a discarded
        # lambda's recycled id aliased another layer's traced body, so
        # layers silently shared (and trained) the wrong weights
        stf.reset_default_graph()
        x = stf.placeholder(stf.float32, [4, 8], name="dlx")
        ws = [stf.Variable(np.random.RandomState(i).randn(8, 8)
                           .astype(np.float32) * 0.3, name=f"dlw{i}")
              for i in range(4)]
        h = x
        for i in range(4):
            h = stf.recompute_grad(
                lambda hh, w=ws[i]: stf.tanh(stf.matmul(hh, w)))(h)
        g = stf.get_default_graph()
        calls = [op for op in g.get_operations()
                 if op.type == "RecomputeGradCall"]
        caps = [sorted(t.name for t in op.inputs[1:]) for op in calls]
        assert caps == [["dlw0:0"], ["dlw1:0"], ["dlw2:0"], ["dlw3:0"]], caps
        grads = stf.gradients(stf.reduce_sum(stf.square(h)), ws)
        sess = stf.Session()
        sess.run(stf.global_variables_initializer())
        gv = sess.run(list(grads),
                      {x: np.random.RandomState(9).randn(4, 8)
                       .astype(np.float32)})
        for a in gv:
            assert float(np.abs(np.asarray(a)).sum()) > 0.0
