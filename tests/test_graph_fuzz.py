"""Randomized graph-equivalence fuzz: build random DAGs simultaneously
in stf and numpy and compare Session.run output against the independent
numpy evaluation.

This is the property the reference's grappler tests state per-pass
(constant_folding_test.cc, optimizer_cse_test.cc: "the optimized graph
computes the same function"); here one generator exercises the whole
plan chain at once — constant folding (constant-only subgraphs), shape
materialization (Shape/Size of static shapes), CSE (deliberately
duplicated ops), DCE (dead branches never fetched), the alias map, and
the lowering itself — against an oracle that shares none of that code.

Each case also does a spot gradient check: d(sum of a random float
node)/d(leaf variable) vs central differences.
"""

import numpy as np
import pytest

import simple_tensorflow_tpu as stf

N_GRAPHS = 40
MAX_OPS = 14


def _mk_leaves(rng):
    """2-4 leaf [a,b] float32 tensors: mix of placeholder/const/Variable."""
    a, b = int(rng.randint(2, 5)), int(rng.randint(2, 5))
    leaves = []
    n = int(rng.randint(2, 5))
    for i in range(n):
        val = rng.randn(a, b).astype(np.float32)
        kind = rng.choice(["ph", "const", "var"])
        if kind == "ph":
            t = stf.placeholder(stf.float32, [a, b], name=f"ph{i}")
            leaves.append((t, val, {"feed": val}))
        elif kind == "const":
            leaves.append((stf.constant(val), val, {}))
        else:
            v = stf.Variable(val, name=f"v{i}")
            leaves.append((v.value(), val, {"var": v}))
    return leaves, (a, b)


def _build_random_graph(rng):
    """Returns (pairs, feed, grad_candidates): pairs is [(tensor, numpy
    value)] for every live node; dead branches are built but not kept."""
    leaves, (a, b) = _mk_leaves(rng)
    feed = {}
    var_leaves = []
    for t, val, extra in leaves:
        if "feed" in extra:
            feed[t] = extra["feed"]
        if "var" in extra:
            var_leaves.append((extra["var"], val))
    pool = [(t, v) for t, v, _ in leaves]

    def pick():
        i = int(rng.randint(len(pool)))
        return pool[i]

    n_ops = int(rng.randint(5, MAX_OPS + 1))
    for k in range(n_ops):
        op = rng.choice(["add", "mul", "sub", "maximum", "minimum",
                         "div", "relu", "tanh", "sigmoid", "exp", "neg",
                         "abs", "transpose", "matmul", "concat",
                         "reduce_sum", "reduce_max", "slice", "where",
                         "cond", "while", "shape_size", "dup", "dead"])
        (x, xv) = pick()
        if op in ("add", "mul", "sub", "maximum", "minimum", "div"):
            (y, yv) = pick()
            if xv.shape != yv.shape:
                # broadcasting case: row vector vs matrix
                if (xv.ndim == 2 and yv.ndim == 2
                        and xv.shape[1] == yv.shape[1]
                        and op in ("add", "mul")):
                    yr, yrv = stf.reduce_sum(y, axis=0, keepdims=True), \
                        yv.sum(axis=0, keepdims=True)
                    f = {"add": (stf.add, np.add),
                         "mul": (stf.multiply, np.multiply)}[op]
                    pool.append((f[0](x, yr), f[1](xv, yrv)))
                continue
            if op == "div":
                den_t = stf.abs(y) + 1.0
                den_v = np.abs(yv) + 1.0
                pool.append((stf.divide(x, den_t), xv / den_v))
                continue
            f = {"add": (stf.add, np.add), "mul": (stf.multiply,
                                                   np.multiply),
                 "sub": (stf.subtract, np.subtract),
                 "maximum": (stf.maximum, np.maximum),
                 "minimum": (stf.minimum, np.minimum)}[op]
            pool.append((f[0](x, y), f[1](xv, yv)))
        elif op == "relu":
            pool.append((stf.nn.relu(x), np.maximum(xv, 0)))
        elif op == "tanh":
            pool.append((stf.tanh(x), np.tanh(xv)))
        elif op == "sigmoid":
            pool.append((stf.sigmoid(x), 1.0 / (1.0 + np.exp(-xv))))
        elif op == "exp":
            # clamp first so chains of exp cannot overflow
            cl_t = stf.clip_by_value(x, -2.0, 2.0)
            cl_v = np.clip(xv, -2.0, 2.0)
            pool.append((stf.exp(cl_t), np.exp(cl_v)))
        elif op == "abs":
            pool.append((stf.abs(x), np.abs(xv)))
        elif op == "neg":
            pool.append((stf.negative(x), -xv))
        elif op == "slice" and xv.ndim == 2 and min(xv.shape) >= 2:
            r = int(rng.randint(1, xv.shape[0]))
            pool.append((x[:r], xv[:r]))
        elif op == "where" and xv.ndim >= 1:
            (y, yv) = pick()
            if yv.shape == xv.shape:
                pool.append((stf.where(stf.greater(x, 0.0), x, y),
                             np.where(xv > 0.0, xv, yv)))
        elif op == "cond":
            # data-dependent branch on a reduced scalar -> lax.cond.
            # Skip near-zero sums: the graph reduces in f32, the mirror
            # in float64 — a tie would flip the branch between them.
            if abs(float(xv.astype(np.float64).sum())) < 1e-3:
                continue
            pred_t = stf.greater(stf.reduce_sum(x), 0.0)
            pred_v = xv.sum() > 0.0
            out_t = stf.cond(pred_t, lambda: stf.tanh(x),
                             lambda: stf.negative(x))
            pool.append((out_t, np.tanh(xv) if pred_v else -xv))
        elif op == "while":
            # bounded while -> lax.while_loop forward, masked-scan
            # gradient replay (the differentiable bounded-loop path)
            k = int(rng.randint(1, 4))
            _, out_t = stf.while_loop(
                lambda i, a: stf.less(i, k),
                lambda i, a: (i + 1, stf.tanh(a) * 1.1),
                [stf.constant(0), x], maximum_iterations=k + 2)
            wv = xv
            for _ in range(k):
                wv = np.tanh(wv) * 1.1
            pool.append((out_t, wv))
        elif op == "transpose" and xv.ndim == 2:
            pool.append((stf.transpose(x), xv.T))
        elif op == "matmul" and xv.ndim == 2:
            (y, yv) = pick()
            if yv.ndim == 2 and xv.shape[1] == yv.shape[0]:
                pool.append((stf.matmul(x, y), xv @ yv))
        elif op == "concat" and xv.ndim == 2:
            (y, yv) = pick()
            if yv.ndim == 2 and yv.shape[1] == xv.shape[1]:
                pool.append((stf.concat([x, y], 0),
                             np.concatenate([xv, yv], 0)))
        elif op == "reduce_sum" and xv.ndim >= 1:
            ax = int(rng.randint(xv.ndim))
            pool.append((stf.reduce_sum(x, axis=ax), xv.sum(axis=ax)))
        elif op == "reduce_max" and xv.ndim >= 1:
            ax = int(rng.randint(xv.ndim))
            pool.append((stf.reduce_max(x, axis=ax, keepdims=True),
                         xv.max(axis=ax, keepdims=True)))
        elif op == "shape_size" and xv.ndim >= 1:
            # exercises shape materialization: Shape/Size of a static
            # shape folds to a constant at plan time
            pool.append((stf.cast(stf.reduce_sum(stf.shape(x)),
                                  stf.float32) * 0.1,
                         np.float32(sum(xv.shape) * 0.1)))
        elif op == "dup":
            # literal duplicate (same inputs, same attrs) — CSE bait;
            # BOTH copies are kept and fetched
            pool.append((stf.tanh(x), np.tanh(xv)))
            pool.append((stf.tanh(x), np.tanh(xv)))
        elif op == "dead":
            # built, never fetched — DCE bait (must not disturb results)
            stf.nn.relu(stf.negative(x))
    return pool, feed, var_leaves


@pytest.mark.parametrize("seed", range(N_GRAPHS))
def test_random_graph_matches_numpy(seed):
    rng = np.random.RandomState(1000 + seed)
    stf.reset_default_graph()
    pool, feed, var_leaves = _build_random_graph(rng)
    # fetch a random live subset (always including the last few nodes,
    # which have the deepest dependency chains)
    idx = sorted(set(range(len(pool) - 3, len(pool))) |
                 set(rng.choice(len(pool),
                                size=min(4, len(pool)), replace=False)))
    idx = [i for i in idx if 0 <= i < len(pool)]
    fetches = [pool[i][0] for i in idx]
    want = [pool[i][1] for i in idx]
    with stf.Session() as sess:
        if var_leaves:
            sess.run(stf.global_variables_initializer())
        got = sess.run(fetches, feed_dict=feed)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), w, rtol=2e-5,
                                       atol=2e-5)
        # spot gradient check THROUGH the fuzzed graph: differentiate
        # the sum of the deepest pool node that depends on the variable
        # and compare against central differences computed by reassigning
        # the variable and re-running the same fetch
        if var_leaves and seed % 3 == 0:
            v, val = var_leaves[0]
            target = None
            for t, _w in reversed(pool):
                if t.dtype.is_floating:
                    yv = stf.reduce_sum(stf.cast(t, stf.float32))
                    (g_t,) = stf.gradients(yv, [v])
                    if g_t is not None:
                        target = (yv, g_t)
                        break
            # the pool always contains v's own read leaf, so a target
            # must exist; a None here means gradients() regressed
            assert target is not None, "no fuzzed node reaches v"
            yv, g_t = target
            g_sym = np.asarray(sess.run(g_t, feed_dict=feed),
                               dtype=np.float64)
            ph = stf.placeholder(stf.float32, list(val.shape))
            asg = stf.assign(v, ph)
            eps = 1e-3
            g_num = np.zeros(val.size, np.float64)

            def eval_at(vv):
                sess.run(asg, feed_dict={ph: vv.reshape(val.shape)})
                return float(np.asarray(
                    sess.run(yv, feed_dict=feed)))

            f0 = eval_at(val.astype(np.float64).ravel()
                         .astype(np.float32))
            comparable = np.ones(val.size, bool)
            for j in range(val.size):
                p = val.astype(np.float64).ravel()
                m = p.copy()
                p[j] += eps
                m[j] -= eps
                fp = eval_at(p.astype(np.float32))
                fm = eval_at(m.astype(np.float32))
                g_num[j] = (fp - fm) / (2 * eps)
                # kink guard: where the graph is non-differentiable
                # (relu/abs/where/max boundaries, cond flips) within
                # +-eps, one-sided slopes disagree — skip that element
                fd_f = (fp - f0) / eps
                fd_b = (f0 - fm) / eps
                if abs(fd_f - fd_b) > 5e-2 * max(1.0, abs(g_num[j])):
                    comparable[j] = False
            sess.run(asg, feed_dict={ph: val})  # restore
            assert comparable.any()  # the check must check something
            np.testing.assert_allclose(g_sym.ravel()[comparable],
                                       g_num[comparable], rtol=5e-3,
                                       atol=5e-3)
            # optimizer wiring through the same random graph: one SGD
            # step must land exactly at val - lr * grad_sym
            lr = 0.1
            train = stf.train.GradientDescentOptimizer(lr).minimize(
                yv, var_list=[v])
            sess.run(train, feed_dict=feed)
            got_after = np.asarray(sess.run(v.value(),
                                            feed_dict=feed),
                                   dtype=np.float64)
            want_after = val.astype(np.float64) - lr * g_sym
            # minimize() recompiles the gradient under its own fetch
            # signature; f32 reduction reordering between the two plans
            # means the file's gradient tolerance applies, not exactness
            np.testing.assert_allclose(got_after, want_after,
                                       rtol=5e-3, atol=5e-3)


@pytest.mark.parametrize("seed", range(1, N_GRAPHS, 4))
def test_random_graph_survives_graphdef_roundtrip(seed):
    """Serialization fuzz: export the random DAG to GraphDef-JSON,
    import into a FRESH graph, and require identical fetch values —
    the path MetaGraph/SavedModel depend on, over the full fuzz
    vocabulary (incl. cond FuncGraphs and shape-materialized consts)."""
    rng = np.random.RandomState(1000 + seed)  # same graphs as the main fuzz
    stf.reset_default_graph()
    pool, feed, var_leaves = _build_random_graph(rng)
    targets = [(t, w) for t, w in pool[-4:]]
    gd = stf.get_default_graph().as_graph_def()
    feed_by_name = {t.name: v for t, v in feed.items()}

    stf.reset_default_graph()
    names = [t.name for t, _w in targets]
    outs = stf.import_graph_def(gd, return_elements=names, name="")
    with stf.Session() as sess:
        # variable leaves re-initialize from their serialized
        # initial-value consts — same values, no checkpoint needed.
        # import_graph_def rebuilds raw ops (not Variable wrappers), so
        # run the initializer Assign ops directly instead of
        # global_variables_initializer (import_meta_graph is the path
        # that restores collections; the saver tests own it).
        init_ops = [op for op in stf.get_default_graph().get_operations()
                    if op.type == "Assign"]
        if var_leaves:
            sess.run(stf.group(*init_ops))
        got = sess.run(outs, feed_dict=feed_by_name)
    for (t, want), g in zip(targets, got):
        np.testing.assert_allclose(np.asarray(g), want, rtol=2e-5,
                                   atol=2e-5)


@pytest.mark.parametrize("seed", range(0, N_GRAPHS, 5))
def test_interleaved_fetch_subsets_share_one_graph(seed):
    """Plan-cache correctness: different (fetches, feeds) signatures on
    ONE session must not contaminate each other — interleave several
    fetch subsets twice and require identical values both rounds."""
    rng = np.random.RandomState(2000 + seed)
    stf.reset_default_graph()
    pool, feed, var_leaves = _build_random_graph(rng)
    subsets = []
    for _ in range(3):
        idx = sorted(rng.choice(len(pool), size=min(3, len(pool)),
                                replace=False))
        subsets.append([pool[i] for i in idx])
    with stf.Session() as sess:
        if var_leaves:
            sess.run(stf.global_variables_initializer())
        rounds = []
        for _round in range(2):
            vals = []
            for sub in subsets:
                got = sess.run([t for t, _w in sub], feed_dict=feed)
                vals.append([np.asarray(g) for g in got])
            rounds.append(vals)
        for sub, got in zip(subsets, rounds[0]):
            for (t, want), g in zip(sub, got):
                np.testing.assert_allclose(g, want, rtol=2e-5, atol=2e-5)
        for a, b in zip(rounds[0], rounds[1]):
            for x, y in zip(a, b):
                np.testing.assert_array_equal(x, y)
